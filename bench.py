"""Benchmark: preds/sec/chip on the BASELINE north-star workload —
streaming MulticlassAccuracy + BinaryAUROC over 10M predictions
(BASELINE.json: "preds/sec/chip on 1B-sample MulticlassAccuracy+AUROC").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup over the reference torcheval implementation
(/root/reference, torch CPU — the only backend it runs on here) on the same
workload sizes.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_CLASSES = 5
TOTAL = 10_000_000
CHUNK = 1_000_000
N_CHUNKS = TOTAL // CHUNK


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy

    key = jax.random.PRNGKey(0)
    kx, ky, kl = jax.random.split(key, 3)
    scores = jax.random.uniform(kx, (CHUNK, NUM_CLASSES), jnp.float32)
    labels = jax.random.randint(ky, (CHUNK,), 0, NUM_CLASSES, jnp.int32)
    logits = jax.random.uniform(kl, (CHUNK,), jnp.float32)
    binary = (labels == 0).astype(jnp.float32)
    jax.block_until_ready((scores, labels, logits, binary))

    def run() -> float:
        acc, auroc = MulticlassAccuracy(num_classes=NUM_CLASSES), BinaryAUROC()
        for _ in range(N_CHUNKS):
            acc.update(scores, labels)
            auroc.update(logits, binary)
        return float(acc.compute()), float(auroc.compute())

    run()  # warmup: compile every kernel
    t0 = time.perf_counter()
    run()
    elapsed = time.perf_counter() - t0
    return TOTAL / elapsed


def bench_reference() -> float:
    sys.path.insert(0, "/root/reference")
    import torch

    from torcheval.metrics import BinaryAUROC, MulticlassAccuracy

    g = torch.Generator().manual_seed(0)
    scores = torch.rand((CHUNK, NUM_CLASSES), generator=g)
    labels = torch.randint(0, NUM_CLASSES, (CHUNK,), generator=g)
    logits = torch.rand((CHUNK,), generator=g)
    binary = (labels == 0).float()

    def run():
        acc, auroc = MulticlassAccuracy(), BinaryAUROC()
        for _ in range(N_CHUNKS):
            acc.update(scores, labels)
            auroc.update(logits, binary)
        return float(acc.compute()), float(auroc.compute())

    run()  # warmup
    t0 = time.perf_counter()
    run()
    elapsed = time.perf_counter() - t0
    return TOTAL / elapsed


def main() -> None:
    tpu_pps = bench_tpu()
    try:
        ref_pps = bench_reference()
        vs_baseline = round(tpu_pps / ref_pps, 3)
    except Exception:
        # never fabricate a parity number: null marks "reference leg not run"
        vs_baseline = None
    print(
        json.dumps(
            {
                "metric": "preds_per_sec_per_chip_acc_plus_auroc_10M",
                "value": round(tpu_pps, 1),
                "unit": "preds/s",
                "vs_baseline": vs_baseline,
            }
        )
    )


if __name__ == "__main__":
    main()

"""Driver benchmark: one JSON line per record, headline (north star) first.

Headline: preds/sec/chip on streaming MulticlassAccuracy + BinaryAUROC
(BASELINE.json: "preds/sec/chip on 1B-sample MulticlassAccuracy+AUROC"),
reported at 10M (with the reference leg for ``vs_baseline``), 100M and the
full 1B — the 1B row runs on bounded memory via exact unique-threshold
summary compaction (``torcheval_tpu/ops/summary.py``).

Then the five BASELINE.md configs (1-5). ``vs_baseline`` is the speedup over
the reference torcheval (/root/reference, torch CPU — the only backend it
runs on here) on the identical workload; ``null`` marks "reference leg not
run" (never fabricated): the 100M/1B rows (CPU-torch would need the full 8+ GB
cache the compaction path exists to avoid) and config 5's on-mesh SPMD row
(the reference cannot run on a TPU mesh). Config 5's cross-process lane DOES
carry a ratio: both frameworks run the same 4-process sync world on this
host's CPU (row ``config5_explicit_sync_accuracy_4proc``).

A persistent XLA compile cache (.jax_cache/) keeps recompiles out of repeat
runs; timed sections always run on pre-warmed shapes either way.

``--obs`` turns on the in-library observability registry
(``torcheval_tpu.obs``) and prints its JSON snapshot after the metric lines
— span timings, jit trace counts, sync-round/byte counters — so a regressed
round can be attributed from library instrumentation, not ad-hoc prints.

``--smoke`` is the CI bit-rot guard (ISSUE 2 satellite): every leg runs at
tiny sizes on CPU, reference legs skip cleanly (no /root/reference in CI),
and main() exits non-zero unless EVERY expected metric row was emitted — so
a bench leg broken by a library change fails the PR's unit-test workflow
instead of surfacing at the next driver round. Smoke numbers are
meaningless as measurements; only completeness is asserted.

``--trace [out.json]`` (ISSUE 7 satellite) records the obs event timeline
for the whole run and writes it as Chrome/Perfetto ``trace_event`` JSON
(load it at chrome://tracing or ui.perfetto.dev): every window-step
dispatch, jit compile, sync round and checkpoint lands as a timeline bar.
``--smoke`` additionally drops the trace plus the obs registry snapshot
into ``$TORCHEVAL_TPU_TEST_ARTIFACT_DIR`` (default ``test-artifacts/``),
which CI uploads on every run — each PR leaves a loadable flight record.
"""

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO)

import numpy as np

_OBS = "--obs" in sys.argv
_SMOKE = "--smoke" in sys.argv


def _trace_arg():
    if "--trace" not in sys.argv:
        return None
    i = sys.argv.index("--trace")
    if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("--"):
        return sys.argv[i + 1]
    return "bench_trace.json"


_TRACE = _trace_arg()

# every emitted metric name, for the --smoke completeness assertion
_EMITTED = []

# rank-tagged timeline events collected from the config5 sync worker
# processes (the only place toolkit sync rounds happen in a bench run);
# merged into the exported Chrome trace so the flight record shows the
# cross-process collective bars, not only the parent's dispatches
_EXTRA_EVENTS = []


def _to_torch(arr):
    """numpy/jax array -> torch tensor via a WRITABLE host copy.
    ``np.asarray`` of a jax array is a read-only view, and
    ``torch.from_numpy`` warns (and would alias UB on write) on non-writable
    buffers — copy first, outside any timed region where it matters."""
    import torch

    return torch.from_numpy(np.array(arr, copy=True))


def _jax():
    import jax

    if _SMOKE:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already initialised (must already be CPU in CI)
    jax.config.update("jax_compilation_cache_dir", os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax


def _time_host(fn, repeats=3):
    """median-of-k wall time for host-side (torch CPU reference) legs: no
    device barrier, no RTT correction — the work is synchronous on this
    host."""
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _time(fn, repeats=3):
    """median-of-k wall time with a host-readback barrier, minus tunnel RTT.

    Two tunneled-chip artifacts to defend against: (a) bursty co-tenant
    stalls (median, not min, so they aren't hidden unfairly vs the single-run
    reference); (b) ``block_until_ready`` has been observed to return BEFORE
    execution completes when the host is loaded (a 10M-sort run reporting
    ~5 ms against a ~180 ms steady state — across every repeat, so the median
    alone doesn't save it). ``_block`` therefore ends every timed run with
    ``jax.device_get``, which cannot return without the bytes. That readback
    pays the tunnel's flat ~0.1 s round trip — pure transport that a real
    host pays microseconds for — so the same barrier is timed empty and its
    median subtracted."""
    import jax
    import jax.numpy as jnp

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    rtts = []
    for i in range(repeats):
        fresh = jnp.float32(i) + 1.0  # fresh value: defeats host-side caching
        jax.block_until_ready(fresh)
        t0 = time.perf_counter()
        jax.device_get(fresh)
        rtts.append(time.perf_counter() - t0)
    times.sort()
    rtts.sort()
    med, rtt = times[len(times) // 2], rtts[len(rtts) // 2]
    if med <= rtt:
        # RTT probes caught a co-tenant burst the timed legs missed; the
        # corrected value would be meaningless (or fabricate preds/1e-9).
        # Fall back to the UNCORRECTED median: conservative (includes the
        # readback transport), never fabricated.
        return med
    return med - rtt


def _slope_pairs(run_chain, short, long, pairs):
    """The shared slope-pair policy: time a short and a long chain back to
    back (``run_chain(k) -> (t_host, elapsed)``), divide the difference by
    the extra run count, DISCARD drift-poisoned pairs on the RAW slope
    (clamping first would turn a poisoned pair into a fake measurement
    that min() then selects), never report below the serial host-enqueue
    slope, and fall back to the conservative uncorrected long-chain figure
    only when every pair was poisoned."""
    per = []
    fallbacks = []
    extra = long - short
    for _ in range(pairs):
        t_host = {}
        elapsed = {}
        for k in (short, long):
            t_host[k], elapsed[k] = run_chain(k)
        slope = (elapsed[long] - elapsed[short]) / extra
        host_slope = max((t_host[long] - t_host[short]) / extra, 0.0)
        if slope > 0:
            per.append(max(slope, host_slope))
        fallbacks.append(elapsed[long] / long)
    return min(per) if per else min(fallbacks)


def _time_chain(fn, n=5, chains=2):
    """Slope timing for dispatch-light legs: queue a SHORT and a LONG chain
    of independent runs (``fn`` returns device values WITHOUT reading back;
    each chain ends in ONE ``device_get`` barrier) back to back, and divide
    the elapsed-time difference by the extra run count.

    Both chains pay exactly one terminal tunnel round trip, so the ~0.1 s
    RTT cancels in the difference with no probe at all. This replaced the
    round-3/4 probe-subtraction design (time one chain, subtract a
    separately-measured RTT), which breaks whenever one RTT — variance
    tens of ms — exceeds the whole chain's signal: observed fabrications
    in both directions ("11.6B preds/s" on config 1; a phantom 2x between
    interleaved config-3 legs when one chain's correction clamped). Only
    RTT *drift between adjacent chains* remains, absorbed by the <=0
    discard, the host-enqueue lower bound, and best-of-``chains`` (a
    single co-tenant stall poisons a whole pair; only an independent pair
    can recover). The final ``device_get`` also guarantees every queued
    run actually finished (``block_until_ready`` alone is not trustworthy
    here; see ``_time``)."""
    import jax

    def run_chain(k):
        t0 = time.perf_counter()
        outs = [fn() for _ in range(k)]
        t_host = time.perf_counter() - t0
        jax.device_get(outs)  # one round trip; see _block
        return t_host, time.perf_counter() - t0

    return _slope_pairs(run_chain, short=2, long=2 + n, pairs=chains)


def _block(*values):
    """End-of-run barrier: host readback of the results (leaf arrays are
    small — scalars and curves). ``device_get`` ALONE: it cannot return
    without the bytes, so it subsumes ``block_until_ready`` (untrustworthy
    here anyway — see ``_time``), and a leading ``block_until_ready`` would
    pay a second flat tunnel round trip per call (~0.1 s, measured: block+get
    183 ms vs get-only 104 ms on a 3 ms kernel) that the RTT correction only
    subtracts once. Multi-leaf gets pipeline into one round trip (measured
    89 ms for 1 leaf vs 91 ms for 3)."""
    import jax

    return jax.device_get(values)


def _ref_time(fn):
    """Same warmup + median-of-k policy as the TPU leg, for a fair ratio
    (host-clocked: the torch leg runs synchronously on this CPU, so it gets
    neither the device barrier nor the RTT correction)."""
    try:
        fn()  # warmup
        return _time_host(fn)
    except Exception:
        return None  # never fabricate a parity number


def _emit(metric, preds, tpu_s, ref_s, unit="preds/s"):
    _EMITTED.append(metric)
    record = {
        "metric": metric,
        "value": round(preds / tpu_s, 1),
        "unit": unit,
        "vs_baseline": round(ref_s / tpu_s, 3) if ref_s else None,
    }
    caveat = _sandbox_caveat(metric)
    if caveat is not None:
        record["sandbox_caveat"] = caveat
    print(json.dumps(record), flush=True)


# THE single registry for sandbox-artifact tagging (ISSUE 18 satellite:
# caveat knowledge lives here and nowhere else). Rows whose VALUE is an
# artifact of the 1-core loopback sandbox (client + server + worker
# timeshare one core, the "wire" is loopback, the 8 mesh "devices" are one
# core): trajectory tooling must not read them as regressions. The caveat
# ships as a FIELD in the row's JSON (machine-readable) — prose in ROADMAP
# items 1a/6 was not enough, every round's record re-litigated the
# readings. Keys are NAME PREFIXES (longest match wins) so rows whose
# names carry run-shape suffixes (config11_sliced_1m_{n}slices) still
# match. Every caveat text MUST name its re-measurement condition — the
# phrase "re-measure" plus where/how — which the bench-hygiene test
# enforces.
_SANDBOX_CAVEAT_ROWS = {
    "config8_cluster_wire_1host_ratio": (
        "loopback-1core: encode/wire/worker share one core; post-"
        "ISSUE-18 smoke runs read 0.31-0.46x with co-tenant noise "
        "dominating any single sample — re-measure on a host whose "
        "device executes off-CPU and whose cores let ingest overlap "
        "compute (docs/performance.md, Ingest pipeline)"
    ),
    "config8_cluster_wire_codec_gain": (
        "loopback-1core: codec encode CPU and the loopback wire share "
        "the core (0.63-0.76x across post-ISSUE-18 smoke runs) — "
        "re-measure on a real NIC where the 3-4x byte shrink buys "
        "wall-clock instead of fighting encode for the core (ROADMAP "
        "item 1a)"
    ),
    "config8_cluster_wire_pipelined_ratio": (
        "loopback-1core: deferred acks overlap submit latency, but with "
        "client+server+worker timesharing ONE core there is no second "
        "core to run the overlapped work — post-ISSUE-18 smoke runs "
        "read 0.43-1.14x against the >=1.5x multi-producer target — "
        "re-measure on a multi-core host where acks ride back while "
        "producers keep encoding (docs/performance.md, Transport)"
    ),
    "config9_elastic_p99": (
        "loopback-1core: every scaled-out 'host' timeshares the SAME "
        "core, so spreading tenants cannot buy submit latency here and "
        "the scaled p99 mostly reads scheduler noise against the flat "
        "(~1x) target; the sandbox-provable claims are the in-leg "
        "observables — zero sheds, drained queues, >=1 live migration, "
        "split merge exact — re-measure the p99 ratio on a fleet whose "
        "hosts own their cores and NICs (docs/robustness.md, Elastic "
        "fleet)"
    ),
    "config6_retrieval_L1M_sharded_ratio": (
        "1core-1dev: at one CPU shard the sharded engine's candidate "
        "exchange + merge is pure overhead (0.71x post-ISSUE-18 smoke) "
        "and a multi-shard mesh would timeshare this same core; the "
        "sandbox-provable claim is the in-leg capacity assert — "
        "per-device label bytes exactly 1/shards of dense — re-measure "
        "the rate ratio on a mesh with one chip per shard "
        "(docs/performance.md, Sharded retrieval)"
    ),
    "config11_sliced_1m": (
        "xla-cpu-scatter: the absolute sliced rates here ride XLA:CPU's "
        "serial per-row scatter loop (~4.2M rows/s post-ISSUE-18 "
        "smoke); on TPU the segment fold vectorizes — re-measure "
        "absolute throughput where the scatter lowers to the vector "
        "unit (docs/performance.md, Sliced metrics)"
    ),
    "config11_sliced_ratio": (
        "xla-cpu-scatter: the per-slice scatter-add lowers to XLA:CPU's "
        "serial per-row scatter loop on this sandbox; on TPU the "
        "segment fold vectorizes and the slice axis costs a vector "
        "lane — re-measure on TPU (docs/performance.md, Sliced metrics)"
    ),
    "config11_sliced_1m_sharded_ratio": (
        "1core-8dev: the 8 mesh devices timeshare ONE core, so every "
        "shard's masked block-range scatter serializes (~8x the scatter "
        "row work back-to-back) and the wall-clock ratio understates a "
        "real mesh; the sandbox-provable claim is the in-leg capacity "
        "assert — state_bytes_per_device{path=sharded} is exactly "
        "1/shards of {path=xla} — re-measure the wall-clock ratio on a "
        "mesh with one chip per shard (docs/performance.md, Sliced "
        "metrics)"
    ),
    "config13_router_restart_blackout_ms": (
        "loopback-1core: the blackout is journal replay + live-fleet "
        "reconciliation, and here every probe RTT is loopback, all "
        "three 'hosts' timeshare the router's core, and the journal's "
        "per-append fsyncs land on the sandbox filesystem — the "
        "sandbox-provable claims are the in-leg observables (every "
        "tenant reconciled, replay bit-identical to the fault-free "
        "oracle) — re-measure the blackout on a real fleet where "
        "probes cross a NIC and hosts own their cores "
        "(docs/robustness.md, Disaster recovery)"
    ),
    "config12_obs_stream_overhead": (
        "loopback-1core: the obs publisher thread timeshares the single "
        "ingest core; the <=2% target applies where telemetry "
        "serialization runs beside ingest, not instead of it — "
        "re-measure on a host with a spare core for the publisher"
    ),
}


def _sandbox_caveat(metric):
    """Longest-prefix registry lookup: ``config11_sliced_1m_4096slices``
    matches the ``config11_sliced_1m`` entry, while
    ``config11_sliced_1m_sharded_ratio`` wins its own longer key."""
    best_key = None
    for key in _SANDBOX_CAVEAT_ROWS:
        if metric.startswith(key) and (
            best_key is None or len(key) > len(best_key)
        ):
            best_key = key
    return _SANDBOX_CAVEAT_ROWS[best_key] if best_key else None


def _emit_row(metric, value, unit):
    """Raw-value row (ms decompositions, dispatch counts) — same record
    format, same emission bookkeeping as _emit. Rows matching a
    _SANDBOX_CAVEAT_ROWS prefix carry their caveat as a machine-readable
    field (both emitters consult the one registry)."""
    _EMITTED.append(metric)
    record = {
        "metric": metric,
        "value": round(value, 3),
        "unit": unit,
        "vs_baseline": None,
    }
    caveat = _sandbox_caveat(metric)
    if caveat is not None:
        record["sandbox_caveat"] = caveat
    print(json.dumps(record), flush=True)


def _floor_rows(prefix, leg_s, nonblocking_fn, emit_host_rows=False):
    """Floor-normalized reconciliation pair for one config leg (VERDICT
    item 5 — previously config1-only): re-run the leg once splitting
    python/host time (dispatch returns, no barrier) from the device+env
    remainder, measure the dispatch floor ADJACENT to the leg (it drifts by
    the minute), and emit the leg's device+env time AS a dispatch count
    against that floor. The count is a property of the code and stays
    stable across rounds; the raw preds/s row swings with the floor — so a
    real regression separates from co-tenant noise in the round record.

    ``nonblocking_fn`` must run the leg's device work WITHOUT a readback
    barrier and return the device values (they are barriered here).
    ``emit_host_rows`` additionally emits the raw ms decomposition pair
    (config1's round-2 rows) ahead of the floor pair."""
    t0 = time.perf_counter()
    out = nonblocking_fn()
    host_s = time.perf_counter() - t0
    _block(out)
    floor_s = _measure_dispatch_floor()
    dev_env_s = max(leg_s - host_s, 0.0)
    if emit_host_rows:
        _emit_row(f"{prefix}_python_host_ms_per_run", host_s * 1e3, "ms")
        _emit_row(
            f"{prefix}_device_plus_env_ms_per_run", dev_env_s * 1e3, "ms"
        )
    _emit_row(f"{prefix}_adjacent_dispatch_floor", floor_s * 1e3, "ms/dispatch")
    _emit_row(
        f"{prefix}_floor_normalized_dispatches",
        dev_env_s / max(floor_s, 1e-9),
        "dispatch-equivalents",
    )


# ----------------------------------------------------------------- headline
NUM_CLASSES = 5
CHUNK = 10_000 if _SMOKE else 1_000_000
BIG_CHUNK = 4_096 if _SMOKE else 16_777_216  # 2^24


def _headline_data(jax, n):
    import jax.numpy as jnp

    kx, ky, kl = jax.random.split(jax.random.PRNGKey(0), 3)
    scores = jax.random.uniform(kx, (n, NUM_CLASSES), jnp.float32)
    labels = jax.random.randint(ky, (n,), 0, NUM_CLASSES, jnp.int32)
    logits = jax.random.uniform(kl, (n,), jnp.float32)
    binary = (labels == 0).astype(jnp.float32)
    jax.block_until_ready((scores, labels, logits, binary))
    return scores, labels, logits, binary


def headline_10m():
    jax = _jax()
    from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy

    n_chunks = 3 if _SMOKE else 10
    total = n_chunks * CHUNK
    scores, labels, logits, binary = _headline_data(jax, CHUNK)

    def run():
        acc, auroc = MulticlassAccuracy(num_classes=NUM_CLASSES), BinaryAUROC()
        for _ in range(n_chunks):
            acc.update(scores, labels)
            auroc.update(logits, binary)
        return _block(acc.compute(), auroc.compute())

    run()  # warmup: compile every kernel
    tpu_s = _time(run)

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import BinaryAUROC as RB
        from torcheval.metrics import MulticlassAccuracy as RA

        g = torch.Generator().manual_seed(0)
        ts = torch.rand((CHUNK, NUM_CLASSES), generator=g)
        tl = torch.randint(0, NUM_CLASSES, (CHUNK,), generator=g)
        tx = torch.rand((CHUNK,), generator=g)
        tb = (tl == 0).float()
        acc, auroc = RA(), RB()
        for _ in range(n_chunks):
            acc.update(ts, tl)
            auroc.update(tx, tb)
        return float(acc.compute()), float(auroc.compute())

    _emit("preds_per_sec_per_chip_acc_plus_auroc_10M", total, tpu_s, _ref_time(ref))


def headline_scaled(total, label, thresh_mult):
    """100M / 1B rows: compaction keeps AUROC state bounded and exact."""
    jax = _jax()
    from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy

    scores, labels, logits, binary = _headline_data(jax, BIG_CHUNK)
    n_chunks = total // BIG_CHUNK
    # per-leg threshold so the compaction path ACTUALLY FIRES on every leg
    # this function claims to measure. Swept on-chip across rounds (identical
    # exact values at every setting). Round-3 sweep after the granule-padding
    # + sync-removal changes, 1B leg (59 chunks): 4x 56.9M, 5x 60.4M,
    # 6x 64.6M, 8x 47.0M preds/s -> 6x stays the sweet spot (worst-case
    # state ~6 chunk-rows + summary of (score, tp, fp) columns ≈ 1.3 GB).
    # 100M leg (5 chunks): 3x so compaction fires; 6x would never compact and
    # silently measure the raw full-cache path instead.
    assert thresh_mult < n_chunks, "compaction must fire within the leg"
    thresh = thresh_mult * BIG_CHUNK

    def run(n):
        acc = MulticlassAccuracy(num_classes=NUM_CLASSES)
        auroc = BinaryAUROC(compaction_threshold=thresh)
        for _ in range(n):
            acc.update(scores, labels)
            auroc.update(logits, binary)
        return _block(acc.compute(), auroc.compute())

    # warmup past the first compaction so _compact_parts and the
    # post-compaction compute shapes compile outside the timed region
    run(thresh_mult + 2)
    tpu_s = _time(lambda: run(n_chunks), repeats=3)
    _emit(f"preds_per_sec_per_chip_acc_plus_auroc_{label}", n_chunks * BIG_CHUNK, tpu_s, None)


# ------------------------------------------------------- BASELINE configs 1-5
def config1_simple_accuracy():
    """MulticlassAccuracy, num_classes=5, simple_example-style streaming."""
    jax = _jax()
    from torcheval_tpu.metrics import MulticlassAccuracy

    rng = np.random.default_rng(0)
    n_batches, batch = (8, 256) if _SMOKE else (200, 8192)
    scores = rng.random((batch, 5)).astype(np.float32)
    labels = rng.integers(0, 5, batch)
    js, jl = jax.device_put(scores), jax.device_put(labels)
    jax.block_until_ready((js, jl))

    def tpu():
        # returns the device scalar WITHOUT reading back: _time_chain queues
        # several runs and pays one barrier (the per-run readback otherwise
        # costs a full tunnel RTT whose variance swamps this leg's signal)
        m = MulticlassAccuracy(num_classes=5)
        for _ in range(n_batches):
            m.update(js, jl)
        return m.compute()

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import MulticlassAccuracy as RefAcc

        ts, tl = _to_torch(scores), _to_torch(labels)
        m = RefAcc()
        for _ in range(n_batches):
            m.update(ts, tl)
        return float(m.compute())

    from torcheval_tpu.metrics import MetricCollection

    col = MetricCollection(MulticlassAccuracy(num_classes=5))

    def tpu_fused():
        col.reset()
        for _ in range(n_batches):
            col.update(js, jl)
        return col.compute()

    _block(tpu())
    _block(tpu_fused())
    ref_s = _ref_time(ref)
    # INTERLEAVED plain/fused chains (VERDICT item 5, same policy as
    # config 3): the two legs do identical device work post-unification, so
    # a sequential measurement turns the environment's ~10 s fast/slow
    # cadence into a phantom lane difference; alternating short slope-pairs
    # keeps each plain+fused comparison inside one environment state.
    plain_times, fused_times = [], []
    for _ in range(3):
        plain_times.append(_time_chain(tpu, n=3, chains=1))
        fused_times.append(_time_chain(tpu_fused, n=3, chains=1))
    plain_s = min(plain_times)
    _emit("config1_multiclass_accuracy_c5", n_batches * batch, plain_s, ref_s)
    # decomposition rows (round-2 verdict #2) + floor-normalized
    # reconciliation (round-4 verdict ask 2), via the shared helper: this
    # leg's device+env time is a handful of dispatches riding the
    # environmental floor, so express it AS a dispatch count against a
    # floor measured in the SAME window. The count is a property of the
    # code (stable across rounds); the raw preds/s row swings with whatever
    # the floor does — r3's 841M vs r4's 282M at 0.556 vs 0.909 ms floors
    # is the same ~3-6 dispatches either way. env_dispatch_floor (last row
    # of the bench) completes the (floor, python, device) triple.
    # ISSUE 6 targets, regression-pinned: python_host < 1 ms (vs r05's
    # 2.635 — concrete-type fast paths cut the per-update python to
    # ~3.4 µs, host-side numpy defaults cut construction from 4 device
    # dispatches per state to 1, and the donated window step pins its
    # input refs until the program retires so the close DISPATCHES instead
    # of blocking on the execution — without that pin, dropping the donated
    # states' wrappers mid-flight stalled the host 40-90 ms per window and
    # this row read ~100 ms) and floor_normalized_dispatches < 20 (vs
    # r05's 119.7 — the run is now ONE window-step program: the vmapped
    # stacked fold replaced the 200-step device-serial lax.scan, and the
    # terminal compute rides inside the same program instead of its own
    # dispatch).
    _floor_rows("config1", plain_s, tpu, emit_host_rows=True)

    # collection path. The row name keeps the r01/r02 "_fused" label for
    # round-over-round comparability, but the mechanism measured here is
    # the whole-window compiled eval step (ISSUE 6, metrics/deferred.py):
    # update() appends each placed batch ONCE to the collection's shared
    # EvalWindow (zero per-batch device dispatch, zero per-member python
    # after the first batch validates the signature), and compute() closes
    # the window as a single donated pjit program carrying the vmapped
    # per-batch update math, the fold AND the terminal compute. The plain
    # leg above rides the same program shape through the solo window step
    # at compute(), so the two rows should MATCH to within environment
    # noise — an inversion here is a regression signal, not a lane
    # difference. Measured from the interleaved alternation above.
    _emit(
        "config1_multiclass_accuracy_c5_fused",
        n_batches * batch,
        min(fused_times),
        ref_s,
    )


def config2_auroc_auprc():
    """BinaryAUROC + BinaryAUPRC, functional API, 10M logits."""
    jax = _jax()
    import torcheval_tpu.metrics.functional as F

    n = 20_000 if _SMOKE else 10_000_000
    x = jax.random.uniform(jax.random.PRNGKey(0), (n,))
    t = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) > 0.5).astype(np.float32)
    jax.block_until_ready((x, t))

    def tpu():
        return _block(F.binary_auroc(x, t), F.binary_auprc(x, t))

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics.functional import binary_auroc as ref_auroc
        from torcheval.metrics.functional import (
            binary_precision_recall_curve as ref_prc,
        )

        tx = _to_torch(x)
        tt = _to_torch(t)
        # the reference snapshot has no binary_auprc metric; build average
        # precision from ITS OWN PRC kernel (precision_recall_curve.py:155-181)
        # + the standard step-sum, so the ratio compares real AP work on both
        # sides (round-2 verdict Weak #5)
        auroc = float(ref_auroc(tx, tt))
        p, r, _ = ref_prc(tx, tt)
        ap = float(torch.sum((r[:-1] - r[1:]) * p[:-1]))
        return auroc, ap

    tpu()
    leg_s = _time(tpu)
    _emit("config2_auroc_auprc_10M", 2 * n, leg_s, _ref_time(ref))
    # floor-normalized pair (VERDICT item 5): same leg without the readback
    # barrier splits host dispatch time from device+environment time
    _floor_rows(
        "config2", leg_s, lambda: (F.binary_auroc(x, t), F.binary_auprc(x, t))
    )


def config3_confusion_f1_imagenet():
    """MulticlassConfusionMatrix + F1, num_classes=1000, ImageNet-eval scale."""
    jax = _jax()
    from torcheval_tpu.metrics import MulticlassConfusionMatrix, MulticlassF1Score

    # 1.3M preds ~ ImageNet val x26 (full size)
    n_batches, batch, c = (3, 2048, 50) if _SMOKE else (13, 100_000, 1000)
    pred = jax.random.randint(jax.random.PRNGKey(0), (batch,), 0, c, np.int32)
    label = jax.random.randint(jax.random.PRNGKey(1), (batch,), 0, c, np.int32)
    jax.block_until_ready((pred, label))

    import jax.numpy as jnp

    def tpu():
        cm = MulticlassConfusionMatrix(c)
        f1 = MulticlassF1Score(num_classes=c, average="macro")
        for _ in range(n_batches):
            cm.update(pred, label)
            f1.update(pred, label)
        # sum the 1000x1000 matrix on device: forces the full compute while
        # keeping the readback barrier payload scalar (the tunnel moves
        # ~8.5 MB/s — pulling 4 MB would time transport, not the metric)
        return jnp.sum(cm.compute()), f1.compute()

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import MulticlassF1Score as RefF1

        tp = _to_torch(pred)
        tl = _to_torch(label)
        # the reference snapshot has no confusion-matrix metric; stream the
        # same counting work in its own idiom (a per-batch torch scatter-add
        # state update — the reference's hot-kernel pattern,
        # f1_score.py:182-190) so both sides do CM + F1 (round-2 verdict
        # named this leg's one-sided work as the gap to close honestly)
        cm_state = torch.zeros(c * c, dtype=torch.int64)
        f1 = RefF1(num_classes=c, average="macro")
        for _ in range(n_batches):
            cm_state += torch.bincount(tl * c + tp, minlength=c * c)
            f1.update(tp, tl)
        return float(cm_state.sum()), float(f1.compute())

    # collection path — like config 1, this measures the deferred-fold
    # lane (appends + one bulk fold) under the legacy "_fused" row name
    from torcheval_tpu.metrics import MetricCollection

    col = MetricCollection(
        {
            "cm": MulticlassConfusionMatrix(c),
            "f1": MulticlassF1Score(num_classes=c, average="macro"),
        }
    )

    def tpu_fused():
        col.reset()
        for _ in range(n_batches):
            col.update(pred, label)
        r = col.compute()
        return jnp.sum(r["cm"]), r["f1"]  # scalar barrier payload, as above

    _block(tpu())
    _block(tpu_fused())
    ref_s = _ref_time(ref)
    # INTERLEAVED chains (round 5): the two legs do identical device work
    # now that standalone metrics group-fold on pending-chunk identity, so
    # any plain-vs-fused gap is environment drift between their timing
    # windows — measuring plain first and fused seconds later showed a
    # consistent phantom 2x that interleaving (parity measured in-process)
    # eliminates. Best-of-2 per leg, alternating, same policy as
    # _time_chain's own chains.
    # 3 alternations of short slope-pairs, not 2 of long ones: the
    # environment toggles between fast/slow states on a ~10 s cadence, and
    # with only 2 samples per leg a full-bench run still produced a phantom
    # 2x (one leg's both chains landing in the slow state). Each
    # _time_chain(n=3, chains=1) call times a 2-run + 5-run pair (~0.3 s
    # per leg including barriers), so a plain+fused alternation completes
    # well inside one environment state.
    plain_times, fused_times = [], []
    for _ in range(3):
        plain_times.append(_time_chain(tpu, n=3, chains=1))
        fused_times.append(_time_chain(tpu_fused, n=3, chains=1))
    _emit(
        "config3_confusion_f1_c1000", n_batches * batch, min(plain_times), ref_s
    )
    _emit(
        "config3_confusion_f1_c1000_fused",
        n_batches * batch,
        min(fused_times),
        ref_s,
    )
    # floor-normalized pair (VERDICT item 5): tpu() already returns device
    # scalars without a barrier, exactly what _floor_rows needs
    _floor_rows("config3", min(plain_times), tpu)


def config4_topk_multilabel():
    """TopKMultilabelAccuracy, k=5, num_labels=10k — interleaved A/B of the
    pre-engine ``lax.top_k`` baseline vs the streaming top-k engine.

    Lane note (ISSUE 2 satellite): this metric rides the DeferredFoldMixin
    append path — updates dispatch NOTHING; the top-k stats core runs in
    one fused fold per budget window. At THIS leg's sizes a single
    (8192, 10000) float32 score batch is ~328 MB, over the 256 MB
    ``_DEFER_BUDGET_BYTES`` valve, so the fold legitimately fires once per
    batch and the leg is bounded by the top-k kernel + one dispatch floor
    per 328 MB batch — NOT by eager host dispatch. Recorded before/after:
    round 1 (pre-deferral, eager per-update kernel) ~0.4M preds/s; BENCH_r05
    (deferred, valve-folding) 970k preds/s at a 0.741 ms floor, 287.9x the
    torch-CPU reference on the identical workload. Deferral's headroom here
    is capped by the batch-size/budget ratio; raising the budget would trade
    HBM headroom for at most ~1 dispatch floor per run.

    Streaming A/B (ISSUE 3 tentpole): with dispatch hygiene settled, the
    leg's remaining cost IS the top-k kernel — a full ~L·log²L sort of the
    10k label axis per fold under ``lax.top_k``. The ``_streaming`` row
    runs the SAME workload with the engine's auto pick (``ops/topk.py``:
    L=10k sits ~10× past the engine's ``_DENSE_L_MAX=1024`` dense
    threshold, so auto selects the Pallas VMEM streaming kernel on TPU /
    threshold-prune elsewhere — one pass over L, k running maxima resident
    in VMEM, no materialised sort). Legs alternate in the same window
    (min-of-3 each, the doc's own interleaving guidance) so the A/B ratio
    is a kernel property, not environment drift.
    """
    jax = _jax()
    from torcheval_tpu.metrics import TopKMultilabelAccuracy

    n_batches, batch, labels = (2, 128, 500) if _SMOKE else (4, 8192, 10_000)
    scores = jax.random.uniform(jax.random.PRNGKey(0), (batch, labels))
    target = (
        jax.random.uniform(jax.random.PRNGKey(1), (batch, labels)) > 0.999
    ).astype(np.int32)
    jax.block_until_ready((scores, target))

    def make_leg(topk_method, block=True):
        def tpu():
            m = TopKMultilabelAccuracy(
                k=5, criteria="contain", topk_method=topk_method
            )
            for _ in range(n_batches):
                m.update(scores, target)
            out = m.compute()
            return _block(out) if block else out

        return tpu

    # "dense" IS the pre-engine code path (lax.top_k full sort): the
    # baseline row keeps its r01-r05 name and meaning
    tpu_dense = make_leg("dense")
    tpu_stream = make_leg("auto")
    tpu_stream_noblock = make_leg("auto", block=False)

    def ref():
        sys.path.insert(0, "/root/reference")
        import torch
        from torcheval.metrics import TopKMultilabelAccuracy as RefTopK

        ts = _to_torch(scores)
        # through _to_torch like every other ref-leg conversion: the r05
        # record still carried the non-writable warning because one
        # conversion bypassed the copying helper — keep ZERO raw
        # torch.from_numpy(np.asarray(...)) call sites in this file
        tt = _to_torch(np.asarray(target).astype(np.float32))
        m = RefTopK(k=5, criteria="contain")
        for _ in range(n_batches):
            m.update(ts, tt)
        return float(m.compute())

    tpu_dense()
    tpu_stream()  # compiles the engine path outside every timed window
    ref_s = _ref_time(ref)
    dense_times, stream_times = [], []
    for _ in range(3):
        dense_times.append(_time(tpu_dense, repeats=1))
        stream_times.append(_time(tpu_stream, repeats=1))
    _emit(
        "config4_topk_multilabel_k5_L10k",
        n_batches * batch,
        min(dense_times),
        ref_s,
    )
    _emit(
        "config4_topk_multilabel_k5_L10k_streaming",
        n_batches * batch,
        min(stream_times),
        ref_s,
    )
    # floor-normalized pair (VERDICT item 5), on the production (auto) path
    _floor_rows("config4", min(stream_times), tpu_stream_noblock)


def config5_sharded_sync():
    """sync_and_compute-equivalent: MulticlassAccuracy over the device mesh
    (implicit-SPMD sync; 32-rank ICI on a pod, every local device here).
    The reference leg needs a multi-GPU NCCL cluster — not runnable here."""
    jax = _jax()
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh

    n_batches, batch = (4, 1024) if _SMOKE else (50, 65536)
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(0)
    from torcheval_tpu.parallel import shard_batch

    # pre-place the sharded global batch: this row measures the SPMD
    # update+sync path, not host→device upload (which here rides a remote
    # tunnel ~3 orders of magnitude slower than a real host's PCIe)
    scores, labels = shard_batch(
        mesh,
        rng.random((batch, 5)).astype(np.float32),
        rng.integers(0, 5, batch),
    )
    jax.block_until_ready((scores, labels))

    ev = ShardedEvaluator(MulticlassAccuracy(num_classes=5), mesh=mesh)

    def tpu():
        ev.reset()
        for _ in range(n_batches):
            ev.update(scores, labels)
        return _block(ev.compute())

    def tpu_noblock():
        ev.reset()
        for _ in range(n_batches):
            ev.update(scores, labels)
        return ev.compute()

    tpu()
    leg_s = _time(tpu)
    _emit(
        f"config5_sharded_sync_accuracy_{mesh.devices.size}dev",
        n_batches * batch,
        leg_s,
        None,
    )
    # floor-normalized pair (VERDICT item 5); the 4-process lane has no such
    # row — its cost is subprocess rendezvous + Gloo rounds, not dispatches
    # against this process's tunnel floor
    _floor_rows("config5", leg_s, tpu_noblock)


def config5_explicit_sync_4proc():
    """config 5's cross-process lane WITH a reference leg: 4 OS processes
    each stream MulticlassAccuracy shards then ``sync_and_compute`` on every
    rank — this framework over ``jax.distributed`` typed collectives vs the
    reference over ``torch.distributed`` Gloo object-pickle gathers
    (``/root/reference/torcheval/metrics/toolkit.py:24-78``). Both worlds are
    CPU processes on this host (the one fabric both sides can run on here:
    the reference leg on a TPU mesh is impossible, and BASELINE's 32-rank
    NCCL cluster is not available), so the ratio isolates the sync machinery
    + update kernels at identical world size. Scored by the SLOWEST rank per
    repeat — the sync is a barrier, so the world moves at the straggler's
    pace — min over repeats (see the scoring comment below for why min, not
    median, on this timeshared single-core host); process startup is
    excluded on both sides (each worker times its own steady-state runs)."""
    import socket
    import subprocess
    import tempfile

    world, n_batches, batch = (4, 3, 512) if _SMOKE else (4, 25, 16384)
    worker = os.path.join(_REPO, "benchmarks", "sync_bench_worker.py")

    import shutil

    def _world_time_once(mode):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        # the port is free NOW but unreserved once the probe socket closes
        # (bind-then-close race); _world_time retries with a fresh port if
        # another process grabs it before rank 0's coordinator binds
        tmpdir = tempfile.mkdtemp(prefix=f"sync_bench_{mode}_")
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # each process models one host
        if _OBS or _TRACE or _SMOKE:
            # workers record their obs timelines (sync rounds live there)
            # and ship the events back for the merged Chrome trace
            env["TORCHEVAL_TPU_BENCH_OBS"] = "1"
        procs = []
        try:
            # per-rank output goes to FILES, not pipes: a rank whose JAX
            # warning spam fills a 64 KB pipe would stall at the collective
            # barrier and deadlock the whole world into the timeout
            logs = [open(os.path.join(tmpdir, f"{mode}_rank{r}.log"), "wb")
                    for r in range(world)]
            for r in range(world):
                procs.append(subprocess.Popen(
                    [
                        sys.executable, worker, mode, str(r), str(world),
                        str(port), tmpdir, str(n_batches), str(batch),
                    ],
                    env=env,
                    stdout=logs[r],
                    stderr=subprocess.STDOUT,
                ))
            try:
                for p in procs:
                    p.wait(timeout=300)
            except subprocess.TimeoutExpired:
                # a hung rank is the likeliest distributed failure; its log
                # is about to be rmtree'd — surface every rank's tail NOW or
                # the diagnosis is lost to the cleanup
                for log in logs:
                    log.flush()
                for r in range(world):
                    path = os.path.join(tmpdir, f"{mode}_rank{r}.log")
                    with open(path, "rb") as f:
                        tail = f.read()[-1000:].decode(errors="replace")
                    print(
                        f"# config5 {mode} rank {r} log tail on hang:\n{tail}",
                        file=sys.stderr,
                    )
                raise
            finally:
                for log in logs:
                    log.close()
            for r, p in enumerate(procs):
                if p.returncode != 0:
                    with open(
                        os.path.join(tmpdir, f"{mode}_rank{r}.log"), "rb"
                    ) as f:
                        out = f.read()
                    raise RuntimeError(
                        f"{mode} rank {r} exited {p.returncode}:\n"
                        f"{out.decode(errors='replace')[-2000:]}"
                    )
            per_rank = []
            for r in range(world):
                with open(os.path.join(tmpdir, f"{mode}_rank{r}.json")) as f:
                    per_rank.append(json.load(f))
                ev_path = os.path.join(tmpdir, f"{mode}_rank{r}_events.json")
                if os.path.exists(ev_path):
                    with open(ev_path) as f:
                        dump = json.load(f)
                    # pid r+1: the parent's own events render as pid 0, so
                    # worker rank 0 must not collide with the parent row
                    _EXTRA_EVENTS.extend(
                        {**e, "rank": dump["rank"] + 1}
                        for e in dump["events"]
                    )
        finally:
            # a rank that died at startup leaves its peers blocked in
            # rendezvous (Gloo waits ~30 min) — never leak them past the leg
            for p in procs:
                if p.poll() is None:
                    p.kill()
            shutil.rmtree(tmpdir, ignore_errors=True)
        # repeat i's world time = slowest rank in repeat i (the sync is a
        # barrier). Across repeats take the MIN, not the median: this host
        # is a single shared core, so 4 timesharing processes × co-tenant
        # bursts poison a high fraction of repeats on WHICHEVER framework is
        # running at that moment (observed swing: 0.5×-1.8× on the same
        # build); min-of-k is the standard burst-robust estimator under
        # timesharing and is applied identically to both worlds.
        repeats = [max(p["times"][i] for p in per_rank)
                   for i in range(len(per_rank[0]["times"]))]
        values = {round(p["value"], 9) for p in per_rank}
        assert len(values) == 1, f"ranks disagree on the synced value: {values}"
        return min(repeats), per_rank[0]["value"]

    def _world_time(mode):
        try:
            return _world_time_once(mode)
        except Exception as exc:
            # one retry with a fresh port, INTENDED for the bind-then-close
            # port race (which is indistinguishable here from other
            # rendezvous failures). A deterministic failure wastes this one
            # re-run; attempt 1's diagnostics are printed first so they are
            # never lost to the retry.
            print(
                f"# config5 {mode} world attempt 1 failed, retrying with a "
                f"fresh port: {exc!r}",
                file=sys.stderr,
            )
            return _world_time_once(mode)

    tpu_s, tpu_val = _world_time("tpu")
    try:
        ref_s, ref_val = _world_time("ref")
    except Exception as exc:  # ref leg failed to RUN: emit null, never a lie
        print(f"# config5 ref leg not run: {exc!r}", file=sys.stderr)
        ref_s = None
    else:
        # a value-parity failure is a correctness bug in the sync machinery,
        # NOT a missing reference leg — it must fail loudly, not emit null
        assert abs(tpu_val - ref_val) < 1e-5, (
            f"sync parity mismatch: tpu={tpu_val} ref={ref_val}"
        )
    _emit(
        f"config5_explicit_sync_accuracy_{world}proc",
        world * n_batches * batch,
        tpu_s,
        ref_s,
    )


def checkpoint_overhead():
    """ISSUE 5 satellite: the robustness tax as a measured number, not a
    guess — save+restore wall time and on-disk bytes for the config1 metric
    set checkpointed MID-STREAM (pending deferred chunks at save time, so
    each timed save pays the fold a periodic checkpoint in a live eval loop
    would). Restore goes into a fresh metric and is parity-checked against
    the source before the rows are emitted."""
    jax = _jax()
    import shutil
    import tempfile

    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.resilience import restore, save

    rng = np.random.default_rng(0)
    n_batches, batch = (4, 256) if _SMOKE else (100, 8192)
    scores = rng.random((batch, 5)).astype(np.float32)
    labels = rng.integers(0, 5, batch)
    js, jl = jax.device_put(scores), jax.device_put(labels)
    jax.block_until_ready((js, jl))
    m = MulticlassAccuracy(num_classes=5)
    for _ in range(n_batches):
        m.update(js, jl)
    ckpt_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        save_times = []
        for _ in range(3):
            m.update(js, jl)  # re-arm the mid-stream pending state
            t0 = time.perf_counter()
            path = save(m, ckpt_dir, keep_last=2)
            save_times.append(time.perf_counter() - t0)
        nbytes = float(
            sum(
                os.path.getsize(os.path.join(path, f))
                for f in os.listdir(path)
            )
        )
        fresh = MulticlassAccuracy(num_classes=5)
        restore_times = []
        for _ in range(3):
            t0 = time.perf_counter()
            restore(fresh, path)
            restore_times.append(time.perf_counter() - t0)
        want, got = float(np.asarray(m.compute())), float(
            np.asarray(fresh.compute())
        )
        assert got == want, f"checkpoint parity mismatch: {got} != {want}"
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    save_times.sort()
    restore_times.sort()
    _emit_row("checkpoint_overhead_save_ms", save_times[1] * 1e3, "ms")
    _emit_row("checkpoint_overhead_restore_ms", restore_times[1] * 1e3, "ms")
    _emit_row("checkpoint_overhead_bytes", nbytes, "bytes")


def config7_serve_tenants():
    """ISSUE 8 / ROADMAP item 3 acceptance: 100+ interleaved tenants
    streamed through ONE daemon process at >= 80% of single-tenant
    throughput.

    Same total work both legs — N batches of the config1 shape — streamed
    either into one tenant or round-robin across the whole fleet, so the
    ratio row isolates the multi-tenancy tax (queue bookkeeping, scheduler
    passes, per-tenant window closes). Program sharing is what makes the
    target reachable: every tenant's collection compiles to the SAME
    window-step program (canonical member keys, deferred.py), so the fleet
    pays one trace, not one per tenant. Submissions use ``block=True`` —
    the bench measures steady-state throughput, not shed throughput."""
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.serve import EvalDaemon

    n_tenants = 100 if _SMOKE else 120
    per_tenant = 2 if _SMOKE else 8
    batch = 256 if _SMOKE else 8192
    total_batches = n_tenants * per_tenant
    rng = np.random.default_rng(7)
    scores = rng.random((batch, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, batch)

    def run_leg(fleet_size: int) -> float:
        with EvalDaemon(
            max_tenants=fleet_size + 1, queue_capacity=64
        ) as daemon:
            # throwaway tenant warms the shared window-step program so
            # neither leg times a compile
            warm = daemon.attach(
                "warm", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
            )
            warm.submit(scores, labels)
            warm.compute(timeout=300)
            warm.detach(timeout=300)
            handles = [
                daemon.attach(
                    f"bench-{i}",
                    {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
                )
                for i in range(fleet_size)
            ]
            t0 = time.perf_counter()
            for _ in range(total_batches // fleet_size):
                for h in handles:
                    h.submit(scores, labels, block=True, timeout=300)
            for h in handles:
                h.compute(timeout=300)
            return time.perf_counter() - t0

    single_s = run_leg(1)
    fleet_s = run_leg(n_tenants)
    preds = total_batches * batch
    single_rate = preds / single_s
    fleet_rate = preds / fleet_s
    _emit_row("config7_serve_tenants_single", single_rate, "preds/s")
    _emit_row(
        f"config7_serve_tenants_interleaved_{n_tenants}",
        fleet_rate,
        "preds/s",
    )
    _emit_row(
        "config7_serve_tenants_throughput_ratio",
        fleet_rate / single_rate,
        "x (target >= 0.8)",
    )


def config8_cluster():
    """ISSUE 10/11: the network front end's price and the migration
    blackout.

    Legs over ONE workload (N distinct batches of the config1 shape into
    a single tenant): (a) ``local_direct`` — the in-process TenantHandle
    path (PR 8's fast path, the baseline); (b) ``wire_1host`` — the same
    stream through EvalServer/EvalClient over loopback TCP with
    idempotent-seq bookkeeping, plus the wire/in-process throughput
    ratio; (b2) ``ingest_overlap`` — concurrent producers at a tight
    window cadence, measuring how much of each window's fill ran while
    the previous window's step executed (the ISSUE 11 double-buffering
    proof); (c) ``wire_2host_migration`` — two hosts sharing a checkpoint
    root, the tenant's host killed mid-stream, measuring the *blackout*:
    wall time from the first failed submit until that batch is durable on
    the survivor (failure detection + checkpoint restore + replay).

    Since ISSUE 11 the timed legs submit DISTINCT batch buffers (a real
    stream never re-submits one array object; identical objects would
    let the coalesced-H2D dedup skip transfers the workload should pay)
    and pin the window cadence with ``window_chunks`` so every window
    program both legs dispatch is warmed ahead of the timers — the ratio
    compares steady-state serving, never a one-off XLA compile."""
    import tempfile

    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.serve import (
        EvalClient,
        EvalDaemon,
        EvalRouter,
        EvalServer,
    )

    n_batches = 8 if _SMOKE else 64
    batch = 256 if _SMOKE else 8192
    window_chunks = 4 if _SMOKE else 8  # n_batches % window_chunks == 0
    rng = np.random.default_rng(8)
    batches = [
        (
            rng.random((batch, NUM_CLASSES)).astype(np.float32),
            rng.integers(0, NUM_CLASSES, batch),
        )
        for _ in range(n_batches)
    ]
    scores, labels = batches[0]
    preds = n_batches * batch

    def metrics():
        return {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}

    # (a) in-process baseline. The warm tenant drives one full window
    # cycle at the leg's exact cadence (window_chunks valve fold + the
    # compute-only close), so the timed stream dispatches only cached
    # programs — same warm-up shape for the wire leg below.
    with EvalDaemon() as daemon:
        handle = daemon.attach(
            "warm", metrics(), window_chunks=window_chunks
        )
        for s, l in batches[:window_chunks]:
            handle.submit(s, l, block=True, timeout=300)
        handle.compute(timeout=300)
        handle.detach(timeout=300)
        handle = daemon.attach(
            "bench", metrics(), window_chunks=window_chunks
        )
        t0 = time.perf_counter()
        for s, l in batches:
            handle.submit(s, l, block=True, timeout=300)
        handle.compute(timeout=300)
        local_s = time.perf_counter() - t0
    _emit_row("config8_cluster_local_direct", preds / local_s, "preds/s")

    # (b) the same stream over loopback TCP. submit_buffer engages the
    # coalesced submit_many frames + scatter-gather packer (ISSUE 11) —
    # per-frame costs amortize over the group the same way the daemon's
    # coalesced H2D amortizes transfers.
    with EvalDaemon(queue_capacity=64) as daemon:
        server = EvalServer(daemon)
        client = EvalClient(
            server.endpoint,
            request_timeout_s=300.0,
            submit_buffer=window_chunks,
            # pinned raw: this is the codec leg's baseline — inheriting a
            # fleet-wide TORCHEVAL_TPU_WIRE_CODEC here would turn the
            # codec_gain row into a codec-vs-codec comparison (~1.0)
            codec="raw",
            # every wire leg forces TCP: with ISSUE 18's same-process
            # local transport auto-selected, this row would silently
            # stop measuring the socket
            local_transport=False,
        )
        spec = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
        client.attach("warm", spec, window_chunks=window_chunks)
        for s, l in batches[:window_chunks]:
            client.submit("warm", s, l)
        client.compute("warm")
        client.detach("warm")
        client.attach("bench", spec, window_chunks=window_chunks)
        t0 = time.perf_counter()
        for s, l in batches:
            client.submit("bench", s, l)
        client.compute("bench")
        wire_s = time.perf_counter() - t0
        client.close()
        server.close()
    wire_rate = preds / wire_s
    _emit_row("config8_cluster_wire_1host", wire_rate, "preds/s")
    # ISSUE 11 before/after on this box: the PRE-pipeline legs recorded
    # 0.95x — an artifact (both legs were dominated by one identical XLA
    # compile; with warmed programs the old wire path measured ~0.15x).
    # The pipeline (zero-copy pooled decode, scatter-gather submit_many
    # coalescing, coalesced H2D, double-buffered windows) brings the
    # honest steady-state ratio to ~0.6x on the 1-core sandbox, where
    # client+server+worker share one core; the >=0.8 target applies to
    # hosts whose device executes off-CPU and whose cores let ingest
    # genuinely overlap compute (docs/performance.md, "Ingest pipeline").
    _emit_row(
        "config8_cluster_wire_1host_ratio",
        wire_rate / (preds / local_s),
        "x of in-process (target >= 0.8 with ingest/compute overlap)",
    )

    # (b-codec) the SAME stream with the negotiated wire codec on
    # (ISSUE 12): delta-narrowed integer leaves + block-quantized f32
    # leaves shrink each submit frame ~3-4x, so the wire leg pays fewer
    # bytes through the loopback kernel. Paired with (b) on the same run,
    # the codec ratio vs the raw ratio is the acceptance observable
    # (codec_gain > 1 = the codec helped; on a 1-core sandbox encode CPU
    # and wire savings fight over the same core, so the honest win here
    # is a lower bound on what a real NIC-bound deployment sees).
    # TORCHEVAL_TPU_BENCH_WIRE_CODEC picks the codec (default qblk, the
    # full compressed wire; "delta" benches the lossless-only variant).
    bench_codec = os.environ.get("TORCHEVAL_TPU_BENCH_WIRE_CODEC", "qblk")
    with EvalDaemon(queue_capacity=64) as daemon:
        server = EvalServer(daemon)
        client = EvalClient(
            server.endpoint,
            request_timeout_s=300.0,
            submit_buffer=window_chunks,
            codec=bench_codec,
            local_transport=False,
        )
        client.attach("warm", spec, window_chunks=window_chunks)
        for s, l in batches[:window_chunks]:
            client.submit("warm", s, l)
        client.compute("warm")
        client.detach("warm")
        client.attach("bench", spec, window_chunks=window_chunks)
        t0 = time.perf_counter()
        for s, l in batches:
            client.submit("bench", s, l)
        client.compute("bench")
        codec_s = time.perf_counter() - t0
        client.close()
        server.close()
    codec_rate = preds / codec_s
    _emit_row(
        f"config8_cluster_wire_codec_1host[{bench_codec}]",
        codec_rate,
        "preds/s",
    )
    _emit_row(
        "config8_cluster_wire_codec_1host_ratio",
        codec_rate / (preds / local_s),
        "x of in-process (paired with config8_cluster_wire_1host_ratio)",
    )
    _emit_row(
        "config8_cluster_wire_codec_gain",
        codec_rate / wire_rate,
        "x of the raw wire on the same run (>1 = codec helped)",
    )

    # (b3) deferred-ack pipelining (ISSUE 18): the same raw-codec wire,
    # but multiple producers each streaming into their own tenant with up
    # to pipeline_depth frames in flight per connection — submits stop
    # paying a full ack RTT each, acks ride back asynchronously. Ratio is
    # vs the lock-step raw wire leg (b) on the same run; the >=1.5x
    # target is a multi-producer claim and needs cores for the
    # overlapped work to actually run on (see the registry caveat).
    import threading

    pipe_depth = 8
    pipe_producers = 4
    pipe_preds = pipe_producers * preds
    with EvalDaemon(queue_capacity=max(64, pipe_producers * n_batches)) as daemon:
        server = EvalServer(daemon, pipeline_depth=pipe_depth)
        client = EvalClient(
            server.endpoint,
            request_timeout_s=300.0,
            submit_buffer=window_chunks,
            codec="raw",
            pipeline_depth=pipe_depth,
            local_transport=False,
        )
        client.attach("warm", spec, window_chunks=window_chunks)
        for s, l in batches[:window_chunks]:
            client.submit("warm", s, l)
        client.compute("warm")
        client.detach("warm")
        for k in range(pipe_producers):
            client.attach(f"pipe-{k}", spec, window_chunks=window_chunks)
        pipe_errors = []

        def _produce(k):
            try:
                for s, l in batches:
                    client.submit(f"pipe-{k}", s, l)
            except Exception as exc:  # noqa: BLE001 - surfaced below
                pipe_errors.append(exc)

        threads = [
            threading.Thread(target=_produce, args=(k,))
            for k in range(pipe_producers)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if pipe_errors:
            raise pipe_errors[0]
        for k in range(pipe_producers):
            client.compute(f"pipe-{k}")
        pipe_s = time.perf_counter() - t0
        client.close()
        server.close()
    pipe_rate = pipe_preds / pipe_s
    _emit_row("config8_cluster_wire_pipelined_1host", pipe_rate, "preds/s")
    _emit_row(
        "config8_cluster_wire_pipelined_ratio",
        pipe_rate / wire_rate,
        "x of the lock-step raw wire (target >= 1.5 with cores for the "
        "overlapped work)",
    )

    # (b4) shared-memory local transport (ISSUE 18): the SAME
    # single-producer workload as (b), but the client and server share
    # this process, so submits hand their payload buffers straight
    # across — the staging-pool slot IS the buffer the daemon decodes,
    # and the loopback socket's write+read copy pair disappears.
    # Auto-selected (local_transport defaults on); >1x of (b) is the
    # skipped copies paying off.
    with EvalDaemon(queue_capacity=64) as daemon:
        server = EvalServer(daemon)
        client = EvalClient(
            server.endpoint,
            request_timeout_s=300.0,
            submit_buffer=window_chunks,
            codec="raw",
        )
        client.attach("warm", spec, window_chunks=window_chunks)
        for s, l in batches[:window_chunks]:
            client.submit("warm", s, l)
        client.compute("warm")
        client.detach("warm")
        client.attach("bench", spec, window_chunks=window_chunks)
        t0 = time.perf_counter()
        for s, l in batches:
            client.submit("bench", s, l)
        client.compute("bench")
        local_tp_s = time.perf_counter() - t0
        client.close()
        server.close()
    local_tp_rate = preds / local_tp_s
    _emit_row(
        "config8_cluster_wire_local_transport", local_tp_rate, "preds/s"
    )
    _emit_row(
        "config8_cluster_wire_local_transport_ratio",
        local_tp_rate / wire_rate,
        "x of the TCP wire on the same workload (>1 = the socket copy "
        "pair was the cost it skipped)",
    )

    # (b2) ingest overlap: concurrent producers keep the daemon queue
    # non-empty, so after a mid-pass valve dispatch the very next append
    # (window N+1's first fill) happens while window N's donated step is
    # still executing; deferred.window.overlap_ms pins the realized
    # overlap (a 0 here would mean fully serial ingest — the exact
    # failure mode ISSUE 11 removes). Untimed, so obs can be on.
    import threading

    from torcheval_tpu import obs as _obs_api
    from torcheval_tpu.obs import registry as _obs_reg

    was_enabled = _obs_reg._enabled
    if not was_enabled:
        _obs_api.enable()

    def _overlap_stats():
        h = _obs_reg.snapshot()["histograms"].get(
            "deferred.window.overlap_ms"
        )
        return (h["count"], h["sum"]) if h else (0, 0.0)

    c0, s0 = _overlap_stats()
    try:
        with EvalDaemon(queue_capacity=max(64, n_batches)) as daemon:
            server = EvalServer(daemon)
            client = EvalClient(
                server.endpoint,
                request_timeout_s=300.0,
                local_transport=False,
            )
            n_producers = 4
            for k in range(n_producers):
                client.attach(
                    f"overlap-{k}", spec, window_chunks=window_chunks
                )
            producer_errors = []

            def produce(k):
                # one tenant per producer: per-tenant client locks don't
                # contend, so frames interleave and every worker pass
                # serves several same-signature tenants (one coalesced
                # transfer)
                try:
                    for s, l in batches:
                        client.submit(f"overlap-{k}", s, l)
                except Exception as exc:  # noqa: BLE001 - surfaced below
                    producer_errors.append(exc)

            threads = [
                threading.Thread(target=produce, args=(k,))
                for k in range(n_producers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if producer_errors:
                raise producer_errors[0]
            for k in range(n_producers):
                client.compute(f"overlap-{k}")
            client.close()
            server.close()
    finally:
        # obs state must not leak into later TIMED legs whatever happens
        c1, s1 = _overlap_stats()
        if not was_enabled:
            _obs_api.disable()
    _emit_row(
        "config8_ingest_overlap_ms",
        ((s1 - s0) / (c1 - c0)) if c1 > c0 else 0.0,
        "ms/window fill overlapped with the previous window's execution",
    )

    # (c) two hosts, victim killed mid-stream: migration blackout
    root = tempfile.mkdtemp(prefix="torcheval_tpu_bench_cluster_")
    daemons = [EvalDaemon(evict_dir=root).start() for _ in range(2)]
    servers = [EvalServer(d) for d in daemons]
    router = EvalRouter(
        [s.endpoint for s in servers],
        request_timeout_s=300.0,
        connect_timeout_s=5.0,
        max_attempts=2,
        backoff_base_s=0.02,
        backoff_cap_s=0.1,
        # the blackout being measured is the WIRE's failure detection:
        # both "hosts" live in this process, so the local transport
        # would short-circuit the very path under test
        local_transport=False,
    )
    spec = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
    router.attach("bench", spec)
    half = n_batches // 2
    for _ in range(half):
        router.submit("bench", scores, labels)
    router.flush("bench")  # durable up to the kill point
    victim = router.placement()["bench"]
    idx = [s.endpoint for s in servers].index(victim)
    servers[idx].close()
    daemons[idx].stop()
    t0 = time.perf_counter()
    # first post-kill submit pays the whole blackout: detection (failed
    # attempts), checkpoint restore on the survivor, replay of the
    # booked batch
    router.submit("bench", scores, labels)
    blackout_s = time.perf_counter() - t0
    for _ in range(n_batches - half - 1):
        router.submit("bench", scores, labels)
    router.compute("bench")
    _emit_row(
        "config8_cluster_wire_2host_migration",
        blackout_s * 1e3,
        "ms blackout",
    )
    router.close()
    for s, d in zip(servers, daemons):
        s.close()
        if d._running:
            d.stop()


def config9_elastic():
    """ISSUE 19: the elastic fleet's headline — offered tenant load beyond
    one host's admission capacity, absorbed by SCALING rather than
    shedding.

    One in-process fleet over loopback TCP (``local_transport=False`` —
    the elastic path under test is the wire one), one shared checkpoint
    root. Phase 1 packs every tenant onto a single host sized exactly at
    its ``max_tenants`` admission limit and measures per-submit wall
    latency. Then the elastic machinery runs END TO END on real folded
    load reports (the obs stream): the host's own report shows it
    saturated, ``HeadroomScalingPolicy`` scales the fleet out through
    ``autoscale_step`` (``provision()`` starts real daemon+server hosts),
    ``rebalance`` live-migrates tenants off the hot host
    (checkpoint + replay, bounded moves per pass), and the first tenant
    is SPLIT across the fleet. Phase 2 replays the same offered stream
    against the scaled fleet and re-measures p99.

    Acceptance observables: zero sheds and drained queues after scale-up
    (capacity absorbed the load), ≥1 live migration, and the split
    tenant's merged ``compute()`` bit-identical to a single-stream
    oracle. The p99 ratio is the caveated row: on the 1-core sandbox
    every "host" timeshares one core, so spreading cannot buy latency
    here — the flat-p99 claim re-measures on a fleet whose hosts own
    their cores."""
    import tempfile

    from torcheval_tpu import obs as _obs_api
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.obs import registry as _obs_reg
    from torcheval_tpu.serve import (
        EvalDaemon,
        EvalRouter,
        EvalServer,
        HeadroomScalingPolicy,
    )

    n_tenants = 4 if _SMOKE else 8
    n_batches = 6 if _SMOKE else 24  # per tenant per phase
    batch = 256 if _SMOKE else 4096
    spec = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
    tenants = [f"bench{i}" for i in range(n_tenants)]

    def make(tenant, idx):
        # distinct, seed-reproducible buffers: a real stream never
        # re-submits one array object, and the split tenant's oracle
        # below replays exactly these
        rng = np.random.default_rng(9000 + 131 * hash(tenant) % 9973 + idx)
        return (
            rng.random((batch, NUM_CLASSES)).astype(np.float32),
            rng.integers(0, NUM_CLASSES, batch),
        )

    def p99(samples):
        ordered = sorted(samples)
        return ordered[min(len(ordered) - 1, int(0.99 * (len(ordered) - 1)))]

    def until(predicate, timeout_s=30.0):
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            if predicate():
                return True
            time.sleep(0.05)
        return predicate()

    def sheds_total():
        counters = _obs_reg.snapshot()["counters"]
        return sum(
            v
            for k, v in counters.items()
            if k.startswith("serve.ingest.sheds{")
        )

    was_enabled = _obs_reg._enabled
    if not was_enabled:
        # the scale-up is driven by the REAL telemetry stream (obs_push
        # load reports), so the whole leg runs with obs on — both timed
        # phases pay the same overhead, the ratio stays fair
        _obs_api.enable()
    sheds_before = sheds_total()
    root = tempfile.mkdtemp(prefix="torcheval_tpu_bench_elastic_")
    daemons, servers = [], []

    def new_host(max_tenants=1024):
        daemon = EvalDaemon(
            evict_dir=root,
            max_tenants=max_tenants,
            queue_capacity=max(64, n_batches),
        ).start()
        server = EvalServer(daemon)
        daemons.append(daemon)
        servers.append(server)
        return server.endpoint

    router = EvalRouter(
        # host 0 admits EXACTLY the offered tenant set: its own load
        # report reads saturated (active == max_tenants), no synthetic
        # load is injected anywhere
        [new_host(max_tenants=n_tenants)],
        request_timeout_s=300.0,
        connect_timeout_s=5.0,
        max_attempts=2,
        backoff_base_s=0.02,
        backoff_cap_s=0.1,
        local_transport=False,
    )
    try:
        router.subscribe_obs(0.2, stale_after_s=10.0)
        for t in tenants:
            router.attach(t, spec)
        # warm every program the timed phases dispatch
        for t in tenants:
            router.submit(t, *make(t, -1))
            router.flush(t)

        # phase 1: the whole offered stream into ONE saturated host
        lat1 = []
        for i in range(n_batches):
            for t in tenants:
                s_, l_ = make(t, i)
                t0 = time.perf_counter()
                router.submit(t, s_, l_)
                lat1.append(time.perf_counter() - t0)
        for t in tenants:
            router.flush(t)
        _emit_row(
            "config9_elastic_p99_submit_1host_ms", p99(lat1) * 1e3, "ms"
        )

        hot_ep = router.endpoints[0]
        until(
            lambda: (
                router.fleet_status()["hosts"][hot_ep].get("load") or 0.0
            )
            > 0.9
        )
        # autoscale: the policy reads the starved fleet headroom and
        # provisions real hosts until the band or max_hosts quiets it
        policy = HeadroomScalingPolicy(
            scale_up_below=0.5, cooldown_s=0.0, max_hosts=4
        )
        for _ in range(3):
            router.autoscale_step(policy, provision=new_host)
        until(
            lambda: all(
                not h["stale"] and h.get("load") is not None
                for h in router.fleet_status()["hosts"].values()
            )
        )
        moved = []
        for _ in range(n_tenants):
            migrated = router.rebalance(
                hot_load=0.5,
                improvement=0.2,
                min_dwell_s=0.0,
                max_moves=2,
            )
            if not migrated:
                break
            moved.extend(migrated)
            time.sleep(0.25)  # let the drained host's next report land
        router.split_tenant(tenants[0], replicas=2)

        # phase 2: the SAME offered stream against the scaled fleet
        lat2 = []
        for i in range(n_batches, 2 * n_batches):
            for t in tenants:
                s_, l_ = make(t, i)
                t0 = time.perf_counter()
                router.submit(t, s_, l_)
                lat2.append(time.perf_counter() - t0)
        for t in tenants:
            router.flush(t)
        _emit_row(
            "config9_elastic_p99_submit_scaled_ms", p99(lat2) * 1e3, "ms"
        )
        _emit_row(
            "config9_elastic_p99_ratio",
            p99(lat2) / p99(lat1),
            "x of 1-host p99 (target ~1: flat as hosts join)",
        )
        _emit_row(
            "config9_elastic_hosts_after_scaleup",
            float(len(router.alive)),
            "hosts (policy grew the fleet from 1)",
        )
        _emit_row(
            "config9_elastic_migrations", float(len(moved)), "tenants moved"
        )
        _emit_row(
            "config9_elastic_queue_depth_after_scaleup",
            float(
                sum(d.load_report()["queue"]["depth"] for d in daemons)
            ),
            "queued batches fleet-wide after flush (must be 0)",
        )
        _emit_row(
            "config9_elastic_sheds_after_scaleup",
            sheds_total() - sheds_before,
            "shed batches (must be 0: scaling absorbed the load)",
        )
        # the split tenant's merged compute vs a single-stream oracle —
        # count-valued states merge exactly, whichever replica saw
        # which batch and through however many live migrations
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for i in range(-1, 2 * n_batches):
            oracle.update(*make(tenants[0], i))
        merged = float(np.asarray(router.compute(tenants[0])["acc"]))
        _emit_row(
            "config9_elastic_split_merge_exact",
            1.0 if merged == float(np.asarray(oracle.compute())) else 0.0,
            "1 = split tenant's merged compute == single-stream oracle",
        )
    finally:
        router.close()
        for s in servers:
            s.close()
        for d in daemons:
            if d._running:
                d.stop()
        if not was_enabled:
            _obs_api.disable()


def config6_retrieval():
    """ISSUE 14: the retrieval family at extreme vocabulary — NDCG@k over
    L=1M labels (4096 at smoke), k ∈ {10, 100}, through the streaming
    top-k engine, plus the label-sharded leg on every local device.

    The dense legs measure the single-device engine (`auto` pick: Pallas
    VMEM streaming on TPU, partial-selection top_k on CPU) ranking +
    relevance gather + ideal ranking per row. The sharded leg runs the SAME
    k=10 workload with the label axis block-distributed across all local
    devices (``sharded_label_topk`` under the fold): per-shard kernels, ONE
    O(k·shards) candidate exchange, exact merge. ``_sharded_ratio`` is the
    sharded/dense rate on the same run (≈1.0 at 1 device; the win is
    *capacity* — per-device label bytes drop to ~1/shards, which the
    ``label_bytes`` gauge row asserts whenever shards > 1: THIS is what
    opens L ~ 10⁶–10⁸ vocabularies that cannot fit one chip)."""
    jax = _jax()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torcheval_tpu.metrics.functional import ndcg_at_k

    rows, labels = (32, 4096) if _SMOKE else (64, 1_000_000)
    scores = jax.random.uniform(jax.random.PRNGKey(0), (rows, labels))
    target = (
        jax.random.uniform(jax.random.PRNGKey(1), (rows, labels)) > 0.999
    ).astype(jnp.float32)
    jax.block_until_ready((scores, target))

    def dense_leg(k):
        def run():
            return _block(ndcg_at_k(scores, target, k=k))

        run()  # compile outside the timed window
        return _time(run)

    rates = {}
    for k in (10, 100):
        leg_s = dense_leg(k)
        rates[k] = rows / leg_s
        _emit(f"config6_retrieval_L1M_k{k}", rows, leg_s, None, unit="rows/s")

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, ("label",))
    shards = devs.size
    sh = NamedSharding(mesh, P(None, "label"))
    s_sh = jax.device_put(scores, sh)
    t_sh = jax.device_put(target, sh)
    jax.block_until_ready((s_sh, t_sh))

    def sharded_run():
        return _block(
            ndcg_at_k(s_sh, t_sh, k=10, label_mesh=(mesh, "label"))
        )

    sharded_run()
    sharded_s = _time(sharded_run)
    sharded_rate = rows / sharded_s
    _emit("config6_retrieval_L1M_sharded", rows, sharded_s, None, unit="rows/s")
    _emit_row(
        "config6_retrieval_L1M_sharded_ratio",
        sharded_rate / rates[10],
        f"x of dense k=10 at {shards} label shard(s)",
    )

    # per-device peak label-axis bytes, via the engine's cost gauges: the
    # sharded leg must sit at ~1/shards of the dense leg's (the capacity
    # acceptance observable). Untimed, so obs can be on.
    from torcheval_tpu import obs as _obs_api
    from torcheval_tpu.obs import registry as _obs_reg
    from torcheval_tpu.ops.topk import _pick_method, sharded_label_topk, topk

    was_enabled = _obs_reg._enabled
    if not was_enabled:
        _obs_api.enable()
    try:
        topk(scores, 10)
        sharded_label_topk(s_sh, 10, mesh=mesh, label_axis="label")
        gauges = _obs_reg.snapshot()["gauges"]
        # read the EXACT keys these two calls just wrote (gauges are
        # last-write-wins, so even a pre-existing entry from an earlier
        # config's topk call now holds THIS call's value); a prefix scan
        # could pick another path's stale gauge when obs was already on
        dense_path = _pick_method(labels, 10, scores.dtype, "auto")
        dense_bytes = gauges[
            f"ops.topk.label_bytes_per_device{{path={dense_path}}}"
        ]
        sharded_bytes = gauges[
            "ops.topk.label_bytes_per_device{path=sharded_label}"
        ]
    finally:
        if not was_enabled:
            _obs_api.disable()
    ratio = sharded_bytes / dense_bytes
    if shards > 1:
        # RELATIVE bound: an absolute tolerance around 1/shards goes
        # vacuous as the shard count grows (0.05 absolute at 64 shards
        # would admit a 4x per-device-bytes regression)
        assert abs(ratio * shards - 1.0) < 0.05, (
            f"sharded per-device label bytes {sharded_bytes} are not "
            f"~1/{shards} of dense {dense_bytes} (ratio {ratio})"
        )
    _emit_row(
        "config6_retrieval_label_bytes_ratio",
        ratio,
        f"x of dense per-device label bytes (expect ~1/{shards})",
    )


def _measure_dispatch_floor():
    """The tunnel's per-dispatch execution cost, in seconds (see
    :func:`env_dispatch_floor` for why and how). Shared by the end-of-bench
    floor row and config 1's floor-normalized reconciliation row (measured
    ADJACENT to the leg it normalizes — the floor drifts by the minute).

    Slope-timed like :func:`_time_chain`: a short and a long dispatch chain
    back to back, divided difference — both chains pay exactly one terminal
    readback RTT, so it cancels with no probe at all (the probe-subtraction
    design fabricated floors near 0 whenever the probe RTT exceeded the
    chain's own terminal RTT)."""
    jax = _jax()
    import jax.numpy as jnp

    @jax.jit
    def step(s):
        return s + 1

    s = jnp.int32(0)
    s = step(s)
    jax.block_until_ready(s)
    def run_chain(k):
        v = jnp.int32(k)
        jax.block_until_ready(v)  # seed transfer outside the window
        t0 = time.perf_counter()
        for _ in range(k):
            v = step(v)
        t_host = time.perf_counter() - t0
        jax.device_get(v)
        return t_host, time.perf_counter() - t0

    return _slope_pairs(run_chain, short=5, long=38, pairs=3)




def config10_sketch():
    """ISSUE 13: approx sketch state. Three rows: (a) measured |approx -
    exact| AUROC error, asserted under the sketch's own a-posteriori bound;
    (b) resident-state bytes ratio, exact sample cache vs sketch; (c) the
    slow streaming leg — 1B rows through BinaryAUROC(approx=True) on
    bounded memory, resident state asserted CONSTANT and RSS growth
    bounded. The exact path cannot run leg (c) at all on this host: 1B
    cached (score, target) rows are 8 GB before the first sort — which is
    precisely the state this mode exists to avoid (the compacted-exact
    1B headline leg bounds memory by score CARDINALITY; the sketch bounds
    it unconditionally)."""
    _jax()
    import resource

    from torcheval_tpu import sketch as _sk
    from torcheval_tpu.metrics import BinaryAUROC

    rng = np.random.default_rng(0)
    n = 50_000 if _SMOKE else 2_000_000
    s = (rng.lognormal(0, 3, n) * np.where(rng.random(n) < 0.5, -1, 1)).astype(
        np.float32
    )
    t = (rng.random(n) < 0.4).astype(np.float32)
    exact = BinaryAUROC()
    exact.update(s, t)
    approx = BinaryAUROC(approx=True)
    approx.update(s, t)
    err = abs(float(exact.compute()) - float(approx.compute()))
    approx._compact()
    bound = _sk.auroc_error_bound(approx.sketch_tp, approx.sketch_fp)
    assert err <= bound + 1e-6, (err, bound)
    # ppm scale: the raw error (~1e-5 at real sizes) would vanish in
    # _emit_row's 3-decimal rounding
    _emit_row("config10_sketch_accuracy_vs_exact", err * 1e6, "abs_error_ppm")
    cache_bytes = sum(
        int(np.asarray(x).nbytes) for x in exact.inputs + exact.targets
    )
    sketch_bytes = int(np.asarray(approx.sketch_tp).nbytes) + int(
        np.asarray(approx.sketch_fp).nbytes
    )
    _emit_row(
        "config10_sketch_bytes_ratio", cache_bytes / sketch_bytes, "x"
    )

    # ---- streaming leg: 1B rows, bounded memory. One pre-generated 4M-row
    # chunk streams repeatedly (the fold cost is identical; generating 1B
    # fresh rows would time the RNG, not the sketch).
    chunk = 65_536 if _SMOKE else 4_194_304
    total = 10 * chunk if _SMOKE else 1_000_000_000
    cs = (rng.lognormal(0, 3, chunk)).astype(np.float32)
    ct = (rng.random(chunk) < 0.4).astype(np.float32)
    m = BinaryAUROC(approx=True, compaction_threshold=chunk)
    m.update(cs, ct)
    m.compute()  # warm the fold + compute programs outside the timed region
    m.reset()
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    resident0 = None
    t0 = time.perf_counter()
    done = 0
    while done < total:
        m.update(cs, ct)
        done += chunk
        if resident0 is None:
            m._compact()
            resident0 = sum(
                int(np.asarray(v).nbytes)
                for v in (m.sketch_tp, m.sketch_fp, m.sketch_nan_dropped)
            )
    value = float(m.compute())
    elapsed = time.perf_counter() - t0
    resident = sum(
        int(np.asarray(v).nbytes)
        for v in (m.sketch_tp, m.sketch_fp, m.sketch_nan_dropped)
    )
    assert resident == resident0, (resident, resident0)
    rss_growth_kb = (
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss - rss0
    )
    # bounded-RSS acceptance: far under the 8 GB the exact cache would
    # need (ru_maxrss is KB on linux; allow jit/runtime slack)
    assert rss_growth_kb < 2 * 1024 * 1024, rss_growth_kb
    assert 0.0 <= value <= 1.0
    _emit("config10_sketch_1b_rows", done, elapsed, None)


def config11_sliced():
    """ISSUE 15: million-cohort sliced eval. Two rows: (a) 1M slices x
    accuracy+AUROC at a power-law cohort distribution — every batch carries
    a slice-id column, per-cohort folds ride ONE segment-scatter inside the
    same donated window-step program the unsliced pair compiles; (b) the
    throughput ratio vs the unsliced collection on IDENTICAL rows
    (acceptance: >= 0.5x — the slice axis must cost a vector lane, not a
    per-slice loop). The one-program property is obs-asserted in-leg: the
    sliced run dispatches exactly as many ``deferred.window_steps`` as the
    unsliced run (slice count never enters the dispatch/collective count;
    the cross-rank two-round bound is pinned by tests/metrics/
    test_sliced_sync.py and the 4-process scenario)."""
    _jax()
    from torcheval_tpu import obs
    from torcheval_tpu.metrics import (
        BinaryAccuracy,
        BinaryAUROC,
        MetricCollection,
        SlicedMetricCollection,
    )

    n_slices = 4_096 if _SMOKE else 1_000_000
    rows = 16_384 if _SMOKE else 1_048_576
    # one compute per 16M rows — an online-eval reporting cadence; the
    # window stays under the 256 MB / 256-chunk valve so the whole epoch
    # is still ONE window-step program
    n_batches = 4 if _SMOKE else 16
    # 2^4 = 16 buckets per slice: the per-slice AUROC sketch state is
    # 2 histograms x 16 x int32 = 128 B/slice (128 MB at 1M slices) — the
    # coarse-width trade the sliced sketch documents (docs/performance.md)
    bits = 4
    rng = np.random.default_rng(0)
    total = rows * (n_batches + 1)
    zipf = (rng.zipf(1.3, total) - 1) % n_slices
    # full coverage + power-law traffic; the affine map makes the cohort
    # ids sparse non-contiguous int64 (the intern table's job is real)
    base = np.concatenate([np.arange(n_slices), zipf])[:total]
    ids = base.astype(np.int64) * 7919 + 13
    scores = rng.random(total).astype(np.float32)
    targets = (rng.random(total) < 0.4).astype(np.float32)

    def batch(i):
        sl = slice(i * rows, (i + 1) * rows)
        return ids[sl], scores[sl], targets[sl]

    def window_steps():
        if not obs.enabled():
            return None
        return sum(
            v
            for k, v in obs.snapshot()["counters"].items()
            if k.startswith("deferred.window_steps")
        )

    sliced = SlicedMetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
        capacity=n_slices,
        curve_bucket_bits=bits,
    )
    sliced.update(*batch(0))  # registers every cohort (table + growth)
    np.asarray(sliced.compute()["acc"]["values"])

    def sliced_epoch():
        for i in range(1, n_batches + 1):
            sliced.update(*batch(i))
        res = sliced.compute()
        np.asarray(res["acc"]["values"])
        np.asarray(res["auroc"]["values"])

    sliced_epoch()  # warm the timed chunk-count's window-step program
    steps0 = window_steps()
    t0 = time.perf_counter()
    sliced_epoch()
    sliced_s = time.perf_counter() - t0
    sliced_steps = (
        window_steps() - steps0 if steps0 is not None else None
    )
    _emit(f"config11_sliced_1m_{n_slices}slices", n_batches * rows, sliced_s, None)

    plain = MetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)}
    )

    def plain_epoch():
        for i in range(1, n_batches + 1):
            _sl, s, t = batch(i)
            plain.update(s, t)
        res = plain.compute()
        # force BOTH members like sliced_epoch does — async dispatch would
        # otherwise leave the AUROC terminal compute outside the timed
        # region and understate plain_s
        np.asarray(res["acc"])
        np.asarray(res["auroc"])

    plain.update(batch(0)[1], batch(0)[2])
    plain.compute()
    plain_epoch()  # same warm treatment as the sliced leg
    steps0 = window_steps()
    t0 = time.perf_counter()
    plain_epoch()
    plain_s = time.perf_counter() - t0
    plain_steps = window_steps() - steps0 if steps0 is not None else None
    if sliced_steps is not None and plain_steps is not None:
        # the one-program contract: the slice axis adds ZERO dispatches
        assert sliced_steps <= plain_steps, (sliced_steps, plain_steps)
    _emit_row(
        "config11_sliced_ratio",
        plain_s / sliced_s,
        "x of unsliced rate on identical rows (target >= 0.5)",
    )


def config11_sliced_sharded():
    """ISSUE 17: the config11 workload with the slice axis SHARDED over
    every local device (``mesh_axis``). Two rows plus one hard in-leg
    assert:

    * ``config11_sliced_1m_sharded`` — sharded-collection throughput on
      the IDENTICAL stream (same seed/affine map as ``config11_sliced``);
    * ``config11_sliced_1m_sharded_ratio`` — vs the unsliced pair on the
      same rows, directly comparable to ``config11_sliced_ratio``. On
      this sandbox the 8 "devices" timeshare ONE core, so each shard's
      masked block-range scatter serializes and the ratio UNDERSTATES a
      real mesh (the caveat field says so); the kernel-path win is the
      TPU claim (docs/performance.md).
    * the sandbox-PROVABLE claim asserts unconditionally when obs is on:
      ``ops.scatter.state_bytes_per_device{path=sharded}`` must be
      exactly ``1/shards`` of the unsharded ``{path=xla}`` gauge for the
      same fold — the capacity math that puts a million-cohort sketch
      back inside per-device memory and the int32 segment-index bound.
    """
    _jax()
    import jax

    from torcheval_tpu import obs
    from torcheval_tpu.metrics import (
        BinaryAccuracy,
        BinaryAUROC,
        MetricCollection,
        SlicedMetricCollection,
    )

    n_slices = 4_096 if _SMOKE else 1_000_000
    rows = 16_384 if _SMOKE else 1_048_576
    n_batches = 4 if _SMOKE else 16
    bits = 4
    shards = len(jax.devices())
    # IDENTICAL stream to config11_sliced: same rng seed, same affine map
    rng = np.random.default_rng(0)
    total = rows * (n_batches + 1)
    zipf = (rng.zipf(1.3, total) - 1) % n_slices
    base = np.concatenate([np.arange(n_slices), zipf])[:total]
    ids = base.astype(np.int64) * 7919 + 13
    scores = rng.random(total).astype(np.float32)
    targets = (rng.random(total) < 0.4).astype(np.float32)

    def batch(i):
        sl = slice(i * rows, (i + 1) * rows)
        return ids[sl], scores[sl], targets[sl]

    def build(sharded):
        kw = {"mesh_axis": "slices"} if sharded else {}
        return SlicedMetricCollection(
            {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
            capacity=n_slices,
            curve_bucket_bits=bits,
            **kw,
        )

    def epoch(col):
        for i in range(1, n_batches + 1):
            col.update(*batch(i))
        res = col.compute()
        np.asarray(res["acc"]["values"])
        np.asarray(res["auroc"]["values"])

    # unsharded twin: builds (or reuses) the xla-path fold so the
    # {path=xla} capacity gauge is populated for the ratio assert below
    plain_sliced = build(sharded=False)
    plain_sliced.update(*batch(0))
    np.asarray(plain_sliced.compute()["acc"]["values"])

    sharded_col = build(sharded=True)
    sharded_col.update(*batch(0))
    np.asarray(sharded_col.compute()["acc"]["values"])
    epoch(sharded_col)  # warm the window-step program
    t0 = time.perf_counter()
    epoch(sharded_col)
    sharded_s = time.perf_counter() - t0
    _emit(
        f"config11_sliced_1m_sharded_{shards}dev",
        n_batches * rows,
        sharded_s,
        None,
    )

    if obs.enabled():
        gauges = obs.snapshot()["gauges"]
        per_dev = gauges["ops.scatter.state_bytes_per_device{path=sharded}"]
        full = gauges["ops.scatter.state_bytes_per_device{path=xla}"]
        # the capacity acceptance: resident scatter state per device is
        # exactly the global extent over the shard count
        assert per_dev * shards == full, (per_dev, shards, full)

    plain = MetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)}
    )

    def plain_epoch():
        for i in range(1, n_batches + 1):
            _sl, s, t = batch(i)
            plain.update(s, t)
        res = plain.compute()
        np.asarray(res["acc"])
        np.asarray(res["auroc"])

    plain.update(batch(0)[1], batch(0)[2])
    plain.compute()
    plain_epoch()
    t0 = time.perf_counter()
    plain_epoch()
    plain_s = time.perf_counter() - t0
    _emit_row(
        "config11_sliced_1m_sharded_ratio",
        plain_s / sharded_s,
        f"x of unsliced rate on identical rows, slice axis {shards}-way "
        "sharded (vs config11_sliced_ratio on the same stream)",
    )


def config12_obs_stream():
    """ISSUE 16 acceptance: streaming telemetry is near-free for ingest.

    Two rows. ``config12_obs_stream_overhead`` submits the SAME workload
    (distinct batches, warmed window programs — the config8 discipline)
    through the wire with the obs push channel OFF and then ON at a
    tight interval, and emits on/off throughput; the target is <= 2%
    cost (ratio >= 0.98) where the publisher thread doesn't timeshare
    the ingest core. ``config12_obs_delta_bytes`` measures what the
    channel SHIPS: compact-JSON bytes of a steady-state delta versus the
    full registry snapshot after the run — the O(changed) claim as a
    number."""
    from torcheval_tpu import obs
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.obs.stream import collect, delta_nbytes
    from torcheval_tpu.serve import EvalClient, EvalDaemon, EvalServer

    n_batches = 8 if _SMOKE else 64
    batch = 256 if _SMOKE else 8192
    window_chunks = 4 if _SMOKE else 8
    rng = np.random.default_rng(12)
    batches = [
        (
            rng.random((batch, NUM_CLASSES)).astype(np.float32),
            rng.integers(0, NUM_CLASSES, batch),
        )
        for _ in range(n_batches)
    ]
    preds = n_batches * batch
    spec = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}

    def run_leg(stream_on: bool) -> float:
        with EvalDaemon(queue_capacity=64) as daemon:
            server = EvalServer(daemon)
            client = EvalClient(
                server.endpoint,
                request_timeout_s=300.0,
                # measure the push channel beside the WIRE ingest path,
                # comparable with prior rounds' rows
                local_transport=False,
            )
            client.attach("warm", spec, window_chunks=window_chunks)
            for s, l in batches[:window_chunks]:
                client.submit("warm", s, l)
            client.compute("warm")
            client.detach("warm")
            client.attach("bench", spec, window_chunks=window_chunks)
            sub = client.subscribe_obs(0.05) if stream_on else None
            t0 = time.perf_counter()
            for s, l in batches:
                client.submit("bench", s, l)
            client.compute("bench")
            leg_s = time.perf_counter() - t0
            if sub is not None:
                # outside the timed region: a smoke leg can finish
                # before the first tick — wait for one push to prove
                # the channel was live during the measurement
                deadline = time.perf_counter() + 5.0
                while sub.received < 1 and time.perf_counter() < deadline:
                    time.sleep(0.01)
                assert sub.received >= 1, "push channel never delivered"
                sub.stop()
            client.close()
            server.close()
            return leg_s

    was_enabled = obs.enabled()
    obs.enable()  # the push channel streams the registry: measure it live
    try:
        off_s = run_leg(False)
        on_s = run_leg(True)
        _emit_row(
            "config12_obs_stream_overhead",
            (preds / on_s) / (preds / off_s),
            "x of push-off ingest rate (target >= 0.98)",
        )
        # steady state: one window's worth of traffic between cursor
        # reads — the delta the publisher would ship on a tick
        with EvalDaemon(queue_capacity=64) as daemon:
            handle = daemon.attach(
                "bytes",
                {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
                window_chunks=window_chunks,
            )
            for s, l in batches[:window_chunks]:
                handle.submit(s, l, block=True, timeout=300)
            handle.compute(timeout=300)
            _d, cursor = collect()  # baseline: everything seen
            for s, l in batches[window_chunks : 2 * window_chunks]:
                handle.submit(s, l, block=True, timeout=300)
            handle.compute(timeout=300)
            delta, _cursor = collect(cursor)
        full, _ = collect()  # a cursor-less collect IS the full snapshot
        _emit_row(
            "config12_obs_delta_bytes",
            delta_nbytes(delta) / max(1, delta_nbytes(full)),
            "x of full-snapshot bytes per tick (smaller is better)",
        )
    finally:
        if not was_enabled:
            obs.disable()


def config13_router_restart():
    """ISSUE 20: the durable control plane's headline — how long the
    fleet is dark when the router process is lost and a new one must be
    stood up from its journal.

    One in-process fleet of three hosts over loopback TCP, one shared
    checkpoint root, one JOURNALED router: a plain tenant and a
    split-by-2 tenant stream phase-1 batches and flush (every update
    durable on the hosts). The first router is then discarded — its
    ``close()`` tears down connections only; tenant state lives on the
    hosts and placement in the journal, so from the journal's point of
    view this is exactly what a crash leaves behind — and the BLACKOUT
    is the wall time for a brand-new ``EvalRouter(journal_dir=...)`` to
    go from constructor to routable: snapshot load, WAL replay, live
    fleet probe, per-tenant reconciliation (adopting the survivors,
    re-deriving the split fan-out ordinal), final compaction.

    Acceptance observables ride along: every tenant reconciled (solo +
    both fan replicas), and after phase 2 streams through the NEW router
    every ``compute()`` is bit-identical to a fault-free single-stream
    oracle — the restart neither lost nor duplicated a batch. The
    blackout row is the caveated one: loopback probes and a 1-core
    sandbox make the absolute number optimistic on the wire side and
    pessimistic on the fsync side."""
    import tempfile

    from torcheval_tpu import obs as _obs_api
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.obs import registry as _obs_reg
    from torcheval_tpu.serve import EvalDaemon, EvalRouter, EvalServer

    n_batches = 6 if _SMOKE else 24  # per tenant per phase
    batch = 256 if _SMOKE else 4096
    spec = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}
    tenants = ("solo", "fan")

    def make(tenant, idx):
        # distinct, seed-reproducible buffers: the oracle below replays
        # exactly these across both router incarnations
        rng = np.random.default_rng(7000 + 131 * hash(tenant) % 9973 + idx)
        return (
            rng.random((batch, NUM_CLASSES)).astype(np.float32),
            rng.integers(0, NUM_CLASSES, batch),
        )

    was_enabled = _obs_reg._enabled
    if not was_enabled:
        # recovery emits its counters (serve.router.recoveries{outcome=},
        # journal_records{kind=}) — run the leg with obs on so the
        # measured blackout includes the real bookkeeping cost
        _obs_api.enable()
    root = tempfile.mkdtemp(prefix="torcheval_tpu_bench_restart_")
    journal_dir = os.path.join(root, "journal")
    daemons, servers, routers = [], [], []

    def new_host():
        daemon = EvalDaemon(
            evict_dir=root, queue_capacity=max(64, n_batches)
        ).start()
        server = EvalServer(daemon)
        daemons.append(daemon)
        servers.append(server)
        return server.endpoint

    endpoints = [new_host() for _ in range(3)]
    router_kwargs = dict(
        journal_dir=journal_dir,
        request_timeout_s=300.0,
        connect_timeout_s=5.0,
        max_attempts=2,
        backoff_base_s=0.02,
        backoff_cap_s=0.1,
        local_transport=False,
    )
    try:
        router = EvalRouter(endpoints, **router_kwargs)
        routers.append(router)
        for t in tenants:
            router.attach(t, spec)
        router.split_tenant("fan", replicas=2)
        for i in range(n_batches):
            for t in tenants:
                router.submit(t, *make(t, i))
        for t in tenants:
            router.flush(t)
        # discard the first router: connections drop, hosts keep every
        # tenant's state, the journal keeps the placement record
        router.close()

        t0 = time.perf_counter()
        router2 = EvalRouter(endpoints, **router_kwargs)
        blackout_s = time.perf_counter() - t0
        routers.append(router2)
        _emit_row(
            "config13_router_restart_blackout_ms",
            blackout_s * 1e3,
            "ms (journal replay + fleet reconcile, constructor to routable)",
        )
        recovery = router2.last_recovery
        _emit_row(
            "config13_router_restart_recovered_tenants",
            float(sum(recovery["outcomes"].values())),
            "tenants reconciled (solo + both fan replicas = 3)",
        )
        _emit_row(
            "config13_router_restart_journal_records",
            float(recovery["journal_records"]),
            "journal records replayed into the recovery pass",
        )

        # phase 2: the streams continue through the NEW router
        for i in range(n_batches, 2 * n_batches):
            for t in tenants:
                router2.submit(t, *make(t, i))
        for t in tenants:
            router2.flush(t)
        exact = 1.0
        for t in tenants:
            oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
            for i in range(2 * n_batches):
                oracle.update(*make(t, i))
            got = float(np.asarray(router2.compute(t)["acc"]))
            if got != float(np.asarray(oracle.compute())):
                exact = 0.0
        _emit_row(
            "config13_router_restart_replay_exact",
            exact,
            "1 = every tenant (incl. the split one) bit-identical to its "
            "fault-free oracle across the restart",
        )
    finally:
        for r in routers:
            try:
                r.close()
            except Exception:
                pass
        for s in servers:
            s.close()
        for d in daemons:
            if d._running:
                d.stop()
        if not was_enabled:
            _obs_api.disable()


def env_dispatch_floor():
    """Record the tunnel's per-dispatch execution cost at bench time.

    Configs that stream many small updates (1 and 3) are bound by this
    environmental floor, which swings 0.2-8 ms with co-tenant load on the
    tunneled chip (a directly-attached TPU dispatches in tens of µs).
    Slope-measured since round 5: a 5-dispatch and a 38-dispatch chain of
    one trivial chained kernel, timed back to back — the divided elapsed
    difference is the marginal per-dispatch cost with the terminal
    readback RTT cancelled exactly (see :func:`_slope_pairs`). Best of 3
    pairs: a single co-tenant stall poisons a whole pair (once recorded a
    "floor" of 1100 ms — a burst reading, not the floor the word claims).
    Emitted so each round's record is interpretable."""
    per_call = _measure_dispatch_floor()
    _emit_row("env_dispatch_floor", per_call * 1e3, "ms/dispatch")


# the rows a complete bench run must emit; --smoke fails unless every one
# appeared (prefix match: the sharded row's name carries the device count)
_EXPECTED_ROW_PREFIXES = (
    "preds_per_sec_per_chip_acc_plus_auroc_10M",
    "preds_per_sec_per_chip_acc_plus_auroc_100M",
    "preds_per_sec_per_chip_acc_plus_auroc_1B",
    "config1_multiclass_accuracy_c5",
    "config1_python_host_ms_per_run",
    "config1_device_plus_env_ms_per_run",
    "config1_adjacent_dispatch_floor",
    "config1_floor_normalized_dispatches",
    "config1_multiclass_accuracy_c5_fused",
    "config2_auroc_auprc_10M",
    "config2_adjacent_dispatch_floor",
    "config2_floor_normalized_dispatches",
    "config3_confusion_f1_c1000",
    "config3_confusion_f1_c1000_fused",
    "config3_adjacent_dispatch_floor",
    "config3_floor_normalized_dispatches",
    "config4_topk_multilabel_k5_L10k",
    "config4_topk_multilabel_k5_L10k_streaming",
    "config4_adjacent_dispatch_floor",
    "config4_floor_normalized_dispatches",
    "config5_sharded_sync_accuracy_",
    "config5_adjacent_dispatch_floor",
    "config5_floor_normalized_dispatches",
    "config5_explicit_sync_accuracy_4proc",
    "config6_retrieval_L1M_k10",
    "config6_retrieval_L1M_k100",
    "config6_retrieval_L1M_sharded",
    "config6_retrieval_L1M_sharded_ratio",
    "config6_retrieval_label_bytes_ratio",
    "checkpoint_overhead_save_ms",
    "checkpoint_overhead_restore_ms",
    "checkpoint_overhead_bytes",
    "config7_serve_tenants_single",
    "config7_serve_tenants_interleaved",
    "config7_serve_tenants_throughput_ratio",
    "config8_cluster_local_direct",
    "config8_cluster_wire_1host",
    "config8_cluster_wire_1host_ratio",
    "config8_cluster_wire_codec_1host",
    "config8_cluster_wire_codec_1host_ratio",
    "config8_cluster_wire_codec_gain",
    "config8_cluster_wire_pipelined_1host",
    "config8_cluster_wire_pipelined_ratio",
    "config8_cluster_wire_local_transport",
    "config8_cluster_wire_local_transport_ratio",
    "config8_cluster_wire_2host_migration",
    "config8_ingest_overlap_ms",
    "config9_elastic_p99_submit_1host_ms",
    "config9_elastic_p99_submit_scaled_ms",
    "config9_elastic_p99_ratio",
    "config9_elastic_hosts_after_scaleup",
    "config9_elastic_migrations",
    "config9_elastic_queue_depth_after_scaleup",
    "config9_elastic_sheds_after_scaleup",
    "config9_elastic_split_merge_exact",
    "config10_sketch_accuracy_vs_exact",
    "config10_sketch_bytes_ratio",
    "config10_sketch_1b_rows",
    "config11_sliced_1m",
    "config11_sliced_ratio",
    "config11_sliced_1m_sharded",
    "config11_sliced_1m_sharded_ratio",
    "config12_obs_stream_overhead",
    "config12_obs_delta_bytes",
    "config13_router_restart_blackout_ms",
    "config13_router_restart_recovered_tenants",
    "config13_router_restart_journal_records",
    "config13_router_restart_replay_exact",
    "env_dispatch_floor",
)


def main() -> None:
    # headline (north star) FIRST: round 1's driver record parsed the first
    # JSON line as the round's number — keep that contract. Legs after the
    # headline are isolated: one leg failing (e.g. a rendezvous flake in the
    # 4-process world) must not erase every later row from the round record.
    if _OBS or _TRACE or _SMOKE:
        # --smoke records too: the CI artifact below carries the trace +
        # snapshot of every run, so a perf regression's flight record is
        # already uploaded when someone goes looking
        from torcheval_tpu import obs

        obs.enable()
        # a full bench run emits far more timeline events than the default
        # ring: size it so early compile bars survive to the export
        obs.set_timeline_capacity(1 << 18)
    headline_10m()
    # smoke: scaled headline legs shrink to n_chunks=10 of the smoke
    # BIG_CHUNK so the compaction path still FIRES at both thresholds
    scaled_totals = (
        (10 * BIG_CHUNK, 10 * BIG_CHUNK)
        if _SMOKE
        else (100_000_000, 1_000_000_000)
    )
    for leg in (
        lambda: headline_scaled(scaled_totals[0], "100M", thresh_mult=3),
        lambda: headline_scaled(scaled_totals[1], "1B", thresh_mult=6),
        config1_simple_accuracy,
        config2_auroc_auprc,
        config3_confusion_f1_imagenet,
        config4_topk_multilabel,
        config5_sharded_sync,
        config5_explicit_sync_4proc,
        config6_retrieval,
        checkpoint_overhead,
        config7_serve_tenants,
        config8_cluster,
        config9_elastic,
        config10_sketch,
        config11_sliced,
        config11_sliced_sharded,
        config12_obs_stream,
        config13_router_restart,
        env_dispatch_floor,
    ):
        try:
            leg()
        except Exception as exc:
            print(f"# bench leg failed (continuing): {exc!r}", file=sys.stderr)
    if _OBS:
        from torcheval_tpu import obs

        # one self-describing JSON line next to the metric rows: registry
        # snapshot (spans / counters / gauges) + the recompile watchdog's
        # per-entry trace counts for the whole bench run
        print(
            json.dumps(
                {
                    "obs_snapshot": obs.snapshot(),
                    "obs_trace_counts": obs.trace_counts(),
                }
            ),
            flush=True,
        )
    if _TRACE or _SMOKE:
        from torcheval_tpu import obs

        trace_json = obs.chrome_trace(extra_events=_EXTRA_EVENTS)
        if _TRACE:
            with open(_TRACE, "w") as f:
                f.write(trace_json)
            print(f"# chrome trace written to {_TRACE}", file=sys.stderr)
        if _SMOKE:
            art = os.environ.get(
                "TORCHEVAL_TPU_TEST_ARTIFACT_DIR", "test-artifacts"
            )
            os.makedirs(art, exist_ok=True)
            with open(os.path.join(art, "bench_trace.json"), "w") as f:
                f.write(trace_json)
            with open(os.path.join(art, "bench_obs_snapshot.json"), "w") as f:
                json.dump(
                    {
                        "obs_snapshot": obs.snapshot(),
                        "obs_trace_counts": obs.trace_counts(),
                    },
                    f,
                    indent=2,
                )
    if _SMOKE:
        missing = [
            p
            for p in _EXPECTED_ROW_PREFIXES
            if not any(name.startswith(p) for name in _EMITTED)
        ]
        if missing:
            print(
                f"# SMOKE FAILURE: missing metric rows: {missing}",
                file=sys.stderr,
            )
            sys.exit(1)
        print(f"# smoke OK: {len(_EMITTED)} rows emitted", flush=True)


if __name__ == "__main__":
    main()

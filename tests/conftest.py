"""Test bootstrap: force an 8-device CPU mesh before JAX backends initialise.

Multi-node TPU semantics are simulated as multi-device single-process SPMD
(SURVEY §4: the reference simulates multi-node as multi-process single-node via
``elastic_launch``; the JAX equivalent is a forced-multi-device host platform —
the same SPMD code path that runs on a real pod).

Note: plain env vars (``JAX_PLATFORMS`` / ``XLA_FLAGS``) are not enough here —
a site plugin may pin ``jax_platforms`` programmatically at interpreter start,
so we override through ``jax.config`` after import, before first backend use.
"""

import os
import sys

os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torcheval_tpu.utils.platform import force_cpu_devices  # noqa: E402

# one shared spelling of the jax-version device-count fallback (config option
# on newer jax, XLA flag on older) — same helper the examples and workers use
force_cpu_devices(8)

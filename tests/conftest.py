"""Test bootstrap: force an 8-device CPU mesh before JAX backends initialise.

Multi-node TPU semantics are simulated as multi-device single-process SPMD
(SURVEY §4: the reference simulates multi-node as multi-process single-node via
``elastic_launch``; the JAX equivalent is a forced-multi-device host platform —
the same SPMD code path that runs on a real pod).

Note: plain env vars (``JAX_PLATFORMS`` / ``XLA_FLAGS``) are not enough here —
a site plugin may pin ``jax_platforms`` programmatically at interpreter start,
so we override through ``jax.config`` after import, before first backend use.
"""

import os

os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

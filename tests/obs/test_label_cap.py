"""Registry label-cardinality guard (ISSUE 15 satellite).

Per-tenant / per-entry label maps previously grew without bound under
churn; the cap drops NEW label sets past the per-name limit — counted into
``obs.labels.dropped{instrument=}`` and warned once per name — so nobody is
tempted to emit per-slice labels (slice results flow through ``compute()``,
never through obs labels).
"""

import unittest

from torcheval_tpu import obs
from torcheval_tpu.obs.registry import (
    Registry,
    set_label_cardinality_cap,
)


class TestLabelCardinalityCap(unittest.TestCase):
    def setUp(self):
        self.prev = set_label_cardinality_cap(4)
        self.addCleanup(set_label_cardinality_cap, self.prev)
        self.registry = Registry()

    def test_counter_series_capped_and_drops_counted(self):
        for i in range(10):
            self.registry.counter("serve.ingest.batches", tenant=f"t{i}")
        snap = self.registry.snapshot()
        kept = [
            k
            for k in snap["counters"]
            if k.startswith("serve.ingest.batches{")
        ]
        self.assertEqual(len(kept), 4)
        self.assertEqual(
            snap["counters"][
                "obs.labels.dropped{instrument=serve.ingest.batches}"
            ],
            6.0,
        )

    def test_existing_series_keep_recording_past_the_cap(self):
        for i in range(6):
            self.registry.counter("c", tenant=f"t{i}")
        self.registry.counter("c", tenant="t0", delta=5.0)
        snap = self.registry.snapshot()
        self.assertEqual(snap["counters"]["c{tenant=t0}"], 6.0)

    def test_unlabeled_series_never_capped(self):
        for i in range(6):
            self.registry.counter("labeled", i=str(i))
        for _ in range(3):
            self.registry.counter("plain")
        self.assertEqual(
            self.registry.snapshot()["counters"]["plain"], 3.0
        )

    def test_cap_spans_instrument_kinds(self):
        # gauges, histograms and spans share the same per-name guard
        for i in range(8):
            self.registry.gauge("g", float(i), k=str(i))
            self.registry.histo("h", float(i), k=str(i))
            with self.registry.span("s", k=str(i)):
                pass
        snap = self.registry.snapshot()
        self.assertEqual(
            len([k for k in snap["gauges"] if k.startswith("g{")]), 4
        )
        self.assertEqual(
            len([k for k in snap["histograms"] if k.startswith("h{")]), 4
        )
        self.assertEqual(
            len([k for k in snap["spans"] if k.startswith("s{")]), 4
        )
        self.assertEqual(
            snap["counters"]["obs.labels.dropped{instrument=g}"], 4.0
        )

    def test_names_capped_independently(self):
        for i in range(5):
            self.registry.counter("a", k=str(i))
        for i in range(3):
            self.registry.counter("b", k=str(i))
        snap = self.registry.snapshot()
        self.assertEqual(
            len([k for k in snap["counters"] if k.startswith("b{")]), 3
        )
        self.assertNotIn("obs.labels.dropped{instrument=b}", snap["counters"])

    def test_reset_clears_the_admission_count(self):
        for i in range(6):
            self.registry.counter("c", k=str(i))
        self.registry.reset()
        for i in range(3):
            self.registry.counter("c", k=str(i))
        snap = self.registry.snapshot()
        self.assertEqual(
            len([k for k in snap["counters"] if k.startswith("c{")]), 3
        )

    def test_cap_validation(self):
        with self.assertRaises(ValueError):
            set_label_cardinality_cap(0)
        with self.assertRaises(ValueError):
            set_label_cardinality_cap("lots")

    def test_default_registry_obs_helpers_ride_the_cap(self):
        obs.enable()
        try:
            obs.reset()
            for i in range(6):
                obs.counter("capped.series", k=str(i))
            snap = obs.snapshot()
            self.assertEqual(
                len(
                    [
                        k
                        for k in snap["counters"]
                        if k.startswith("capped.series{")
                    ]
                ),
                4,
            )
        finally:
            obs.disable()
            obs.reset()


if __name__ == "__main__":
    unittest.main()

"""Dispatch-count regression test (ISSUE 2 satellite; reworked for the
whole-window compiled eval step, ISSUE 6).

The eval hot loop's contract is structural: K ``update()`` calls under one
budget window must cost ZERO device dispatches for deferred members, and the
window must close as exactly ONE compiled program — never O(K) dispatches.
The PR-1 obs registry makes that an observable
(``deferred.window_steps{path=}`` increments once per window-step dispatch;
``deferred.folds{entry=,path=}`` covers the standalone/legacy fold lane), so
a future change that quietly reintroduces per-batch dispatch fails HERE in
CI instead of at the next bench round.

The companion assertion pins the retrace bound of the stacked window step:
a steady constant-batch loop compiles ``deferred.window_step`` for at most
2 distinct signatures per batch shape — the valve-cadence fold program plus
the window-closing program (final flush / terminal compute).
"""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    MeanSquaredError,
    MetricCollection,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    R2Score,
)
from torcheval_tpu.obs import recompile

RNG = np.random.default_rng(7)


def _deferred_dispatches():
    """Every deferred-machinery dispatch counter, window-step and legacy
    fold lanes alike."""
    counters = obs.snapshot()["counters"]
    return {
        k: v
        for k, v in counters.items()
        if k.startswith("deferred.window_steps") or k.startswith("deferred.folds")
    }


def _window_fold_steps():
    """Window-step dispatches that folded chunks (path=stacked|concat);
    path=compute steps fold nothing — they are the chunk-less terminal
    compute of an already-folded window."""
    return {
        k: v
        for k, v in _deferred_dispatches().items()
        if k.startswith("deferred.window_steps") and "path=compute" not in k
    }


class TestFoldDispatchCount(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()

    def tearDown(self):
        obs.disable()
        obs.reset()

    def test_mixed_collection_one_window_is_one_program(self):
        K = 32
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=6),
                "f1": MulticlassF1Score(num_classes=6, average="macro"),
                "cm": MulticlassConfusionMatrix(6),
            }
        )
        # deliberately-odd batch size: this test's trace-count assertions
        # must not be satisfied by jit-cache hits from other tests' shapes
        x = jnp.asarray(RNG.random((37, 6)).astype(np.float32))
        t = jnp.asarray(RNG.integers(0, 6, 37))
        recompile.reset()
        for _ in range(K):
            col.update(x, t)
        # the hot loop itself dispatched NOTHING: zero per-batch device
        # dispatch for deferred members (K << budget window)
        self.assertEqual(_deferred_dispatches(), {})
        col.compute()
        # one window-step program carries all 3 members × K batches' update
        # math, the fold AND every member's terminal compute
        self.assertEqual(sum(_deferred_dispatches().values()), 1)
        self.assertEqual(
            sum(_window_fold_steps().values()), 1
        )  # ...and it was the chunk-folding kind
        batches = obs.snapshot()["counters"].get(
            "deferred.window_step_batches", 0.0
        )
        self.assertEqual(batches, float(K))

    def test_valve_cadence_stays_o1_programs_and_bounded_signatures(self):
        # shrink the window so the valve fires mid-stream: 3 windows of 8
        # chunks + no remainder must be 3 fold-bearing programs (one per
        # window) plus one chunk-less terminal-compute step at compute(),
        # and — constant batch shape — at most 2 distinct
        # deferred.window_step signatures (the valve-cadence fold program
        # and the window-closing program)
        K, window = 24, 8
        col = MetricCollection(
            {"mse": MeanSquaredError(), "r2": R2Score()}
        )
        for m in col.metrics.values():
            m._DEFER_MAX_CHUNKS = window
        x = jnp.asarray(RNG.random(41).astype(np.float32))
        t = jnp.asarray(RNG.random(41).astype(np.float32))
        recompile.reset()
        for _ in range(K):
            col.update(x, t)
        col.compute()
        self.assertEqual(
            sum(_window_fold_steps().values()), K // window
        )  # O(windows), never O(K)
        step_traces = recompile.trace_counts().get(
            "deferred.window_step", {"distinct_signatures": 0}
        )
        self.assertLessEqual(step_traces["distinct_signatures"], 2)
        # and the result is still exact
        expected = float(np.square(np.asarray(t) - np.asarray(x)).mean())
        out = col.compute()
        self.assertAlmostEqual(float(out["mse"]), expected, places=6)

    def test_steady_loop_with_remainder_is_two_signatures(self):
        # K not a multiple of the window: the valve folds at the cadence
        # count, compute() folds the remainder WITH the terminal compute in
        # the same program — exactly the "≤2 signatures per batch shape"
        # bound the stacked window step guarantees
        K, window = 11, 4
        m = MulticlassAccuracy(num_classes=5)
        col = MetricCollection(m)
        m._DEFER_MAX_CHUNKS = window
        x = jnp.asarray(RNG.random((29, 5)).astype(np.float32))
        t = jnp.asarray(RNG.integers(0, 5, 29))
        recompile.reset()
        for _ in range(K):
            col.update(x, t)
        col.compute()
        # 2 valve windows + 1 remainder-fold-plus-compute step
        self.assertEqual(sum(_deferred_dispatches().values()), 3)
        step_traces = recompile.trace_counts().get(
            "deferred.window_step", {"distinct_signatures": 0}
        )
        self.assertLessEqual(step_traces["distinct_signatures"], 2)

    def test_standalone_metric_fold_plus_compute_is_one_program(self):
        # the solo lane rides the same window-step shape: a standalone
        # metric's compute() folds its pending batches AND computes in ONE
        # program (previously a fold dispatch + a compute dispatch)
        m = MulticlassAccuracy(num_classes=6)
        x = jnp.asarray(RNG.random((23, 6)).astype(np.float32))
        t = jnp.asarray(RNG.integers(0, 6, 23))
        for _ in range(5):
            m.update(x, t)
        self.assertEqual(_deferred_dispatches(), {})
        got = float(m.compute())
        self.assertEqual(sum(_deferred_dispatches().values()), 1)
        self.assertAlmostEqual(
            got,
            float(
                (np.asarray(x).argmax(1) == np.asarray(t)).mean()
            ),
            places=6,
        )


if __name__ == "__main__":
    unittest.main()

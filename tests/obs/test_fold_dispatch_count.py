"""Dispatch-count regression test (ISSUE 2 satellite).

The unified eval hot loop's contract is structural: K ``update()`` calls
under one budget window must cost O(1) fold *programs*, never O(K)
dispatches. The PR-1 obs registry makes that an observable
(``deferred.folds{entry=,path=}`` increments once per fold dispatch), so a
future change that quietly reintroduces per-batch dispatch fails HERE in CI
instead of at the next bench round.

The companion assertion pins the retrace bound the stacked/scan fold path
guarantees: a steady constant-batch loop compiles ``deferred.group_fold``
for at most 2 distinct signatures per batch shape (the valve-cadence chunk
count plus the final partial flush).
"""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    MeanSquaredError,
    MetricCollection,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    R2Score,
)
from torcheval_tpu.obs import recompile

RNG = np.random.default_rng(7)


def _fold_dispatches():
    counters = obs.snapshot()["counters"]
    return {
        k: v for k, v in counters.items() if k.startswith("deferred.folds")
    }


class TestFoldDispatchCount(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()

    def tearDown(self):
        obs.disable()
        obs.reset()

    def test_mixed_collection_one_window_is_one_program(self):
        K = 32
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=6),
                "f1": MulticlassF1Score(num_classes=6, average="macro"),
                "cm": MulticlassConfusionMatrix(6),
            }
        )
        # deliberately-odd batch size: this test's trace-count assertions
        # must not be satisfied by jit-cache hits from other tests' shapes
        x = jnp.asarray(RNG.random((37, 6)).astype(np.float32))
        t = jnp.asarray(RNG.integers(0, 6, 37))
        recompile.reset()
        for _ in range(K):
            col.update(x, t)
        # the hot loop itself dispatched NO fold program (K << budget window)
        self.assertEqual(_fold_dispatches(), {})
        col.compute()
        total = sum(_fold_dispatches().values())
        self.assertEqual(total, 1)  # one program for all 3 members × K batches

    def test_valve_cadence_stays_o1_programs_and_bounded_signatures(self):
        # shrink the window so the valve fires mid-stream: 3 windows of 8
        # chunks + no remainder must be 3 programs (one per window), and —
        # constant batch shape — at most 2 distinct deferred.group_fold
        # signatures (the valve-cadence count; no partial flush here)
        K, window = 24, 8
        col = MetricCollection(
            {"mse": MeanSquaredError(), "r2": R2Score()}
        )
        for m in col.metrics.values():
            m._DEFER_MAX_CHUNKS = window
        x = jnp.asarray(RNG.random(41).astype(np.float32))
        t = jnp.asarray(RNG.random(41).astype(np.float32))
        recompile.reset()
        for _ in range(K):
            col.update(x, t)
        col.compute()
        total = sum(_fold_dispatches().values())
        self.assertEqual(total, K // window)  # O(windows), never O(K)
        group_traces = recompile.trace_counts().get(
            "deferred.group_fold", {"distinct_signatures": 0}
        )
        self.assertLessEqual(group_traces["distinct_signatures"], 2)
        # and the result is still exact
        expected = float(np.square(np.asarray(t) - np.asarray(x)).mean())
        out = col.compute()
        self.assertAlmostEqual(float(out["mse"]), expected, places=6)

    def test_steady_loop_with_remainder_is_two_signatures(self):
        # K not a multiple of the window: valve folds at the cadence count,
        # the read folds the remainder — exactly the "≤2 signatures per
        # batch shape" bound the scan path guarantees
        K, window = 11, 4
        m = MulticlassAccuracy(num_classes=5)
        col = MetricCollection(m)
        m._DEFER_MAX_CHUNKS = window
        x = jnp.asarray(RNG.random((29, 5)).astype(np.float32))
        t = jnp.asarray(RNG.integers(0, 5, 29))
        recompile.reset()
        for _ in range(K):
            col.update(x, t)
        col.compute()
        total = sum(_fold_dispatches().values())
        self.assertEqual(total, 3)  # 2 valve windows + 1 remainder fold
        group_traces = recompile.trace_counts().get(
            "deferred.group_fold", {"distinct_signatures": 0}
        )
        self.assertLessEqual(group_traces["distinct_signatures"], 2)


if __name__ == "__main__":
    unittest.main()

"""Recompile watchdog tests: trace counting keyed by abstract signature,
cache hits not counted, the shape-polymorphic storm warning firing exactly
once, and watched_jit's drop-in jit compatibility (static args, donation)."""

import logging
import unittest

import jax
import jax.numpy as jnp

from torcheval_tpu import obs
from torcheval_tpu.obs import recompile


def _capture_telemetry():
    records = []
    logger = logging.getLogger("torcheval_tpu.api_usage")
    handler = logging.Handler()
    handler.emit = records.append
    logger.addHandler(handler)
    return logger, handler, records


class TestWatchedJit(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()
        recompile.reset()
        self._threshold = recompile.retrace_threshold()

    def tearDown(self):
        obs.disable()
        obs.reset()
        recompile.reset()
        recompile.set_retrace_threshold(self._threshold)

    def test_counts_traces_not_calls(self):
        f = obs.watched_jit(lambda x: x * 2, name="double")
        for _ in range(5):
            f(jnp.ones(4))  # one signature -> one trace
        f(jnp.ones(8))  # second signature
        counts = obs.trace_counts()["double"]
        self.assertEqual(counts["traces"], 2)
        self.assertEqual(counts["distinct_signatures"], 2)

    def test_dtype_change_is_a_new_signature(self):
        f = obs.watched_jit(lambda x: x + 1, name="dtypes")
        f(jnp.ones(4, jnp.float32))
        f(jnp.ones(4, jnp.int32))
        self.assertEqual(
            obs.trace_counts()["dtypes"]["distinct_signatures"], 2
        )

    def test_static_argnames_pass_through(self):
        f = obs.watched_jit(
            lambda x, n: x * n, name="static", static_argnames=("n",)
        )
        self.assertEqual(float(f(jnp.ones(()), n=3)), 3.0)
        self.assertEqual(float(f(jnp.ones(()), n=4)), 4.0)
        # distinct static values are distinct signatures (jit cache parity)
        self.assertEqual(
            obs.trace_counts()["static"]["distinct_signatures"], 2
        )

    def test_donate_argnums_pass_through(self):
        f = obs.watched_jit(
            lambda s, a: {k: v + a for k, v in s.items()},
            name="donate",
            donate_argnums=0,
        )
        out = f({"x": jnp.ones(2)}, 1.0)
        self.assertEqual(float(out["x"][0]), 2.0)

    def test_result_parity_with_plain_jit(self):
        def g(x, y):
            return jnp.dot(x, y)

        a = jnp.arange(6.0).reshape(2, 3)
        b = jnp.arange(12.0).reshape(3, 4)
        watched = obs.watched_jit(g, name="parity")
        self.assertTrue(
            bool(jnp.array_equal(watched(a, b), jax.jit(g)(a, b)))
        )

    def test_storm_warns_exactly_once(self):
        recompile.set_retrace_threshold(4)
        f = obs.watched_jit(lambda x: x + 1, name="poly_entry")
        logger, handler, records = _capture_telemetry()
        try:
            # deliberately shape-polymorphic update loop: every call a new
            # shape, running well past the threshold
            for i in range(10):
                f(jnp.ones(i + 1))
        finally:
            logger.removeHandler(handler)
        storms = [
            r for r in records if "Retrace storm" in r.getMessage()
        ]
        self.assertEqual(len(storms), 1)
        self.assertEqual(storms[0].levelno, logging.WARNING)
        self.assertIn("poly_entry", storms[0].getMessage())

    def test_steady_loop_never_warns(self):
        recompile.set_retrace_threshold(4)
        f = obs.watched_jit(lambda x: x + 1, name="steady_entry")
        logger, handler, records = _capture_telemetry()
        try:
            for _ in range(50):
                f(jnp.ones(16))
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            [r for r in records if "Retrace storm" in r.getMessage()], []
        )
        self.assertEqual(obs.trace_counts()["steady_entry"]["traces"], 1)

    def test_reset_rearms_warning(self):
        recompile.set_retrace_threshold(3)
        f = obs.watched_jit(lambda x: x * 1, name="rearm_entry")
        logger, handler, records = _capture_telemetry()
        try:
            for i in range(4):
                f(jnp.ones(i + 1))
            recompile.reset()
            for i in range(4):
                f(jnp.ones(i + 10))
        finally:
            logger.removeHandler(handler)
        storms = [
            r for r in records if "Retrace storm" in r.getMessage()
        ]
        self.assertEqual(len(storms), 2)

    def test_registry_mirrors_while_enabled(self):
        obs.enable()
        f = obs.watched_jit(lambda x: x + 1, name="mirrored")
        f(jnp.ones(3))
        f(jnp.ones(3))
        snap = obs.snapshot()
        self.assertEqual(snap["counters"]["jit.calls{entry=mirrored}"], 2)
        self.assertEqual(
            snap["counters"]["recompile.traces{entry=mirrored}"], 1
        )
        self.assertEqual(snap["spans"]["jit/mirrored"]["count"], 2)

    def test_threshold_validation(self):
        with self.assertRaises(ValueError):
            recompile.set_retrace_threshold(1)

    def test_label_shared_instances_do_not_pool_into_a_storm(self):
        # several jit instances may share a label (the concat and stacked
        # deferred-fold dispatchers both report as "deferred.fold"); each
        # tracing once with its own batch shape is program diversity, NOT a
        # retrace storm
        recompile.set_retrace_threshold(3)
        logger, handler, records = _capture_telemetry()
        try:
            for i in range(8):
                f = obs.watched_jit(lambda x: x + 1, name="shared_label")
                f(jnp.ones(i + 1))  # one trace per fresh instance
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            [r for r in records if "Retrace storm" in r.getMessage()], []
        )
        # the per-label reporting still sees all of them
        self.assertEqual(
            obs.trace_counts()["shared_label"]["traces"], 8
        )

    def test_distinct_static_configs_do_not_pool_into_a_storm(self):
        # one watched entry dispatching many static configurations (the
        # deferred.fold case: one label, a distinct static fold_fn per
        # metric class) — each tracing once is not a storm
        recompile.set_retrace_threshold(3)
        f = obs.watched_jit(
            lambda x, n: x * n, name="static_diverse", static_argnames=("n",)
        )
        logger, handler, records = _capture_telemetry()
        try:
            for i in range(8):
                f(jnp.ones(4), n=i + 1)  # new static => new program
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            [r for r in records if "Retrace storm" in r.getMessage()], []
        )
        # but a drifting SHAPE under one static config still trips
        logger, handler, records = _capture_telemetry()
        try:
            for i in range(6):
                f(jnp.ones(10 + i), n=1)
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            len([r for r in records if "Retrace storm" in r.getMessage()]), 1
        )

    def test_collection_construction_churn_never_warns(self):
        # regression: constructing many MetricCollections and folding many
        # deferred metric instances over a steady batch shape must not trip
        # the watchdog during a fully normal run — all collections share the
        # module-level fold dispatchers, so churn is pure jit-cache reuse.
        # (A genuinely DRIFTING batch shape through the shared fold is a
        # real per-shape recompile and is supposed to warn; the generic
        # drifting-shape case is asserted in
        # test_distinct_static_configs_do_not_pool_into_a_storm above.)
        recompile.set_retrace_threshold(4)
        from torcheval_tpu.metrics import MeanSquaredError, MetricCollection

        logger, handler, records = _capture_telemetry()
        try:
            for _ in range(6):
                col = MetricCollection({"mse": MeanSquaredError()})
                col.update(jnp.ones(8), jnp.ones(8))
                col.compute()
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            [r for r in records if "Retrace storm" in r.getMessage()], []
        )

    def test_weak_type_flip_is_a_new_signature(self):
        # alternating python-scalar (weak) and committed f32 operands
        # retraces jit's cache per flip; the watchdog must see it too
        f = obs.watched_jit(lambda x: x + 1, name="weak_flip")
        f(1.0)  # weak f32 scalar
        f(jnp.float32(1.0))  # strong f32 scalar
        self.assertEqual(
            obs.trace_counts()["weak_flip"]["distinct_signatures"], 2
        )

    def test_abstract_signature_distinguishes_treedef(self):
        sig_list = recompile.abstract_signature(([jnp.ones(2)],), {})
        sig_tuple = recompile.abstract_signature(((jnp.ones(2),),), {})
        self.assertNotEqual(sig_list, sig_tuple)

    def test_library_entry_points_are_watched(self):
        # the ops kernels registered through watched_jit surface in
        # trace_counts under their own entry names after one use
        from torcheval_tpu.ops.confusion import class_counts

        class_counts(jnp.asarray([0, 1, 1]), 3)
        self.assertIn("class_counts", obs.trace_counts())


if __name__ == "__main__":
    unittest.main()

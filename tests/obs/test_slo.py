"""SLO objectives + burn alarms (ISSUE 16 tentpole leg 4): windowed
burn-rate math over the cumulative histograms, edge-triggered breach
semantics (exactly one alarm per transition), and the thread-safe alarm
hook registry."""

import threading
import unittest

from torcheval_tpu.obs import slo as slo_mod
from torcheval_tpu.obs.registry import Registry
from torcheval_tpu.obs.slo import (
    Slo,
    evaluate_slos,
    fire_alarm,
    on_alarm,
    register_slo,
    registered_slos,
    remove_alarm,
    unregister_slo,
)


class TestSloValidation(unittest.TestCase):
    def test_rejects_bad_knobs(self):
        for kw in (
            {"threshold_s": 0.0},
            {"threshold_s": -1.0},
            {"window_s": 0.0},
            {"budget": 0.0},
            {"budget": 1.5},
        ):
            kwargs = {
                "instrument": "x",
                "threshold_s": 0.1,
                "window_s": 10.0,
                "budget": 0.01,
            }
            kwargs.update(kw)
            with self.assertRaises(ValueError):
                Slo("o", **kwargs)


class TestSloEvaluation(unittest.TestCase):
    def setUp(self):
        self.reg = Registry()
        slo_mod._reset_for_tests()
        self.addCleanup(slo_mod._reset_for_tests)

    def _slo(self, **kw):
        kwargs = dict(
            instrument="lat",
            threshold_s=0.1,
            window_s=10.0,
            budget=0.1,
        )
        kwargs.update(kw)
        return Slo("p99", **kwargs)

    def test_no_observations_no_burn(self):
        slo = self._slo()
        res = slo.evaluate(registry=self.reg, now=0.0)
        self.assertEqual(res["burn_rate"], 0.0)
        self.assertEqual(res["breaches"], [])

    def test_good_traffic_stays_under_budget(self):
        slo = self._slo()
        slo.evaluate(registry=self.reg, now=0.0)
        for _ in range(100):
            self.reg.histo("lat", 0.01)  # well under threshold
        res = slo.evaluate(registry=self.reg, now=1.0)
        self.assertEqual(res["burn_rate"], 0.0)
        self.assertEqual(res["breaches"], [])

    def test_bad_traffic_breaches_once_edge_triggered(self):
        fired = []
        on_alarm(fired.append)
        slo = self._slo()
        slo.evaluate(registry=self.reg, now=0.0)
        for _ in range(50):
            self.reg.histo("lat", 5.0)  # way over threshold
        res = slo.evaluate(registry=self.reg, now=1.0)
        self.assertGreaterEqual(res["burn_rate"], 1.0)
        self.assertEqual(len(res["breaches"]), 1)
        self.assertEqual(len(fired), 1)
        self.assertEqual(fired[0]["kind"], "slo.breach")
        self.assertEqual(fired[0]["objective"], "p99")
        # a stuck-bad series alarms ONCE, not once per evaluation
        for t in (2.0, 3.0, 4.0):
            res = slo.evaluate(registry=self.reg, now=t)
            self.assertEqual(res["breaches"], [])
        self.assertEqual(len(fired), 1)
        # breach counter recorded exactly once
        self.assertEqual(
            self.reg.snapshot()["counters"]["slo.breach{objective=p99}"],
            1.0,
        )

    def test_rearms_after_window_slides_clean(self):
        fired = []
        on_alarm(fired.append)
        slo = self._slo()
        slo.evaluate(registry=self.reg, now=0.0)
        for _ in range(10):
            self.reg.histo("lat", 5.0)
        slo.evaluate(registry=self.reg, now=1.0)
        self.assertEqual(len(fired), 1)
        # bad traffic stops; window slides past it -> burn returns to 0
        for _ in range(100):
            self.reg.histo("lat", 0.01)
        slo.evaluate(registry=self.reg, now=12.0)
        res = slo.evaluate(registry=self.reg, now=24.0)
        self.assertEqual(res["burn_rate"], 0.0)
        # a fresh burst alarms AGAIN (the edge re-armed)
        for _ in range(10):
            self.reg.histo("lat", 5.0)
        slo.evaluate(registry=self.reg, now=25.0)
        self.assertEqual(len(fired), 2)

    def test_tenant_label_carried_into_breach_counter(self):
        slo = self._slo()
        slo.evaluate(registry=self.reg, now=0.0)
        for _ in range(10):
            self.reg.histo("lat", 5.0, tenant="t7")
        slo.evaluate(registry=self.reg, now=1.0)
        counters = self.reg.snapshot()["counters"]
        self.assertIn(
            "slo.breach{objective=p99,tenant=t7}", counters
        )

    def test_burn_rate_gauge_always_recorded(self):
        slo = self._slo()
        slo.evaluate(registry=self.reg, now=0.0)
        gauges = self.reg.snapshot()["gauges"]
        self.assertIn("slo.burn_rate{objective=p99}", gauges)

    def test_min_count_suppresses_thin_windows(self):
        slo = self._slo(min_count=5)
        slo.evaluate(registry=self.reg, now=0.0)
        self.reg.histo("lat", 5.0)  # 1 bad observation < min_count
        res = slo.evaluate(registry=self.reg, now=1.0)
        self.assertEqual(res["burn_rate"], 0.0)

    def test_registry_reset_rearms_series(self):
        fired = []
        on_alarm(fired.append)
        slo = self._slo()
        slo.evaluate(registry=self.reg, now=0.0)
        for _ in range(10):
            self.reg.histo("lat", 5.0)
        slo.evaluate(registry=self.reg, now=1.0)
        self.assertEqual(len(fired), 1)
        self.reg.reset()
        slo.evaluate(registry=self.reg, now=2.0)  # forgets dropped series
        for _ in range(10):
            self.reg.histo("lat", 5.0)
        slo.evaluate(registry=self.reg, now=3.0)
        self.assertEqual(len(fired), 2)

    def test_span_instruments_evaluate_too(self):
        fired = []
        on_alarm(fired.append)
        slo = self._slo(instrument="step")
        slo.evaluate(registry=self.reg, now=0.0)
        for _ in range(10):
            self.reg._record_span("step", (), 5.0)
        res = slo.evaluate(registry=self.reg, now=1.0)
        self.assertGreaterEqual(res["burn_rate"], 1.0)
        self.assertEqual(len(fired), 1)


class TestAlarmRegistry(unittest.TestCase):
    def setUp(self):
        slo_mod._reset_for_tests()
        self.addCleanup(slo_mod._reset_for_tests)

    def test_raising_callback_never_blocks_others(self):
        got = []

        def bad(payload):
            raise RuntimeError("boom")

        on_alarm(bad)
        on_alarm(got.append)
        fire_alarm({"kind": "test"})
        self.assertEqual(got, [{"kind": "test"}])

    def test_register_is_idempotent_and_removal_works(self):
        got = []
        on_alarm(got.append)
        on_alarm(got.append)  # no double registration
        fire_alarm({"kind": "a"})
        self.assertEqual(len(got), 1)
        remove_alarm(got.append)
        remove_alarm(got.append)  # no-op when absent
        fire_alarm({"kind": "b"})
        self.assertEqual(len(got), 1)

    def test_concurrent_fire_and_register_is_safe(self):
        got = []
        stop = threading.Event()

        def churner():
            def cb(_p):
                pass

            while not stop.is_set():
                on_alarm(cb)
                remove_alarm(cb)

        t = threading.Thread(target=churner, daemon=True)
        t.start()
        try:
            on_alarm(got.append)
            for _ in range(200):
                fire_alarm({"kind": "x"})
        finally:
            stop.set()
            t.join(5.0)
        self.assertEqual(len(got), 200)


class TestModuleRegistry(unittest.TestCase):
    def setUp(self):
        slo_mod._reset_for_tests()
        self.addCleanup(slo_mod._reset_for_tests)

    def test_register_evaluate_unregister(self):
        reg = Registry()
        slo = Slo(
            "o", instrument="lat", threshold_s=0.1, window_s=10.0
        )
        register_slo(slo)
        register_slo(slo)  # idempotent
        self.assertEqual(registered_slos(), [slo])
        results = evaluate_slos(registry=reg, now=0.0)
        self.assertEqual(len(results), 1)
        self.assertEqual(results[0]["objective"], "o")
        unregister_slo(slo)
        self.assertEqual(registered_slos(), [])
        self.assertEqual(evaluate_slos(registry=reg), [])


if __name__ == "__main__":
    unittest.main()

"""Doc-drift lint (ISSUE 7 satellite): docs/observability.md's metric
inventory table and the library's actual metric-name literals must agree
BOTH ways.

The inventory table is the operator's contract — dashboards and alerts are
built off it — and nothing else stops it rotting: a new
``obs.counter("x.y")`` call site ships silently, a renamed metric leaves a
stale row. This test scans every ``.counter( ".." )`` / ``.gauge( ".." )``
/ ``.histo( ".." )`` string-literal call site under ``torcheval_tpu/``
(whitespace/newline tolerant — several sites are black-wrapped) and parses
the backticked first-cell names out of the doc's ``## Metric inventory``
table, then asserts set equality with a diff naming the drifted side.
"""

import os
import re
import unittest

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_PKG = os.path.join(_REPO, "torcheval_tpu")
_DOC = os.path.join(_REPO, "docs", "observability.md")

# a metric-recording call: any receiver (obs., _obs., reg., registry., ...)
# whose first argument is a string literal. \s* spans the line breaks that
# formatting puts between the paren and the name.
_CALL = re.compile(r'\.(counter|gauge|histo)\(\s*"([^"]+)"')

# an inventory row's first cell: | `name` or | `name{labels}` |
_ROW = re.compile(r"^\|\s*`([^`{]+)(?:\{[^`]*\})?`\s*\|")


def _code_metric_names():
    names = set()
    for dirpath, _dirnames, filenames in os.walk(_PKG):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn)) as f:
                src = f.read()
            for _kind, name in _CALL.findall(src):
                names.add(name)
    return names


def _doc_inventory_names():
    with open(_DOC) as f:
        doc = f.read()
    m = re.search(r"^## Metric inventory$(.*?)^## ", doc, re.M | re.S)
    assert m, "docs/observability.md lost its '## Metric inventory' section"
    names = set()
    for line in m.group(1).splitlines():
        row = _ROW.match(line.strip())
        if row and row.group(1) not in ("metric", "---"):
            names.add(row.group(1))
    return names


class TestDocInventory(unittest.TestCase):
    def test_code_and_doc_inventory_agree(self):
        code = _code_metric_names()
        doc = _doc_inventory_names()
        # sanity: both scans actually found things (a regex rotting to an
        # empty set would otherwise pass vacuously)
        self.assertGreater(len(code), 20)
        undocumented = sorted(code - doc)
        stale = sorted(doc - code)
        self.assertFalse(
            undocumented or stale,
            "metric inventory drift — "
            f"recorded in code but missing from docs/observability.md: "
            f"{undocumented}; documented but no longer recorded: {stale}",
        )


if __name__ == "__main__":
    unittest.main()

"""Cross-module ``obs.reset()`` consistency (ISSUE 7 satellite): ONE reset
drops every registry instrument (cost gauges included), clears the event
timeline ring, clears recompile-watchdog bookkeeping AND re-arms its
once-per-entry storm warnings, and forgets telemetry ``log_once`` keys —
so a "fresh run" is fresh in every leg of the flight recorder at once.
Before this lived in one place, a reset left stale watchdog state
warning-suppressed while the counters it explained were gone.
"""

import logging
import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.obs import recompile, trace
from torcheval_tpu.utils import telemetry


def _capture_telemetry():
    logger = logging.getLogger("torcheval_tpu.api_usage")
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger.addHandler(handler)
    return logger, handler, records


class TestCrossModuleReset(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()
        self._threshold = obs.retrace_threshold()

    def tearDown(self):
        obs.disable()
        obs.reset()
        obs.set_retrace_threshold(self._threshold)

    def test_one_reset_clears_every_leg(self):
        obs.enable()
        # populate all four legs: registry instruments + cost gauges (a
        # compile-bearing watched_jit call), timeline events, watchdog
        # bookkeeping, a consumed log_once key
        f = obs.watched_jit(lambda x: x + 1.0, name="reset.entry")
        f(jnp.ones((4,), jnp.float32))
        obs.histo("reset.h", 0.1)
        telemetry.log_once("reset.test.key", "hello")
        snap = obs.snapshot()
        self.assertIn("obs.cost.flops{entry=reset.entry}", snap["gauges"])
        self.assertGreater(trace.event_count(), 0)
        self.assertIn("reset.entry", obs.trace_counts())

        obs.reset()

        snap = obs.snapshot()
        self.assertEqual(
            snap,
            {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}},
        )
        self.assertEqual(trace.event_count(), 0)
        self.assertEqual(trace.dropped(), 0)
        self.assertEqual(obs.trace_counts(), {})
        # the log_once key re-armed: a fresh run logs again
        logger, handler, records = _capture_telemetry()
        try:
            telemetry.log_once("reset.test.key", "hello again")
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            [r.getMessage() for r in records], ["hello again"]
        )

    def test_reset_rearms_storm_warning(self):
        obs.set_retrace_threshold(3)
        f = obs.watched_jit(lambda x: x * 2.0, name="reset.storm.entry")
        logger, handler, records = _capture_telemetry()
        try:
            for n in range(1, 8):
                f(jnp.asarray(np.ones(n, np.float32)))  # new shape each call
            first = sum(
                "reset.storm.entry" in r.getMessage() for r in records
            )
            obs.reset()
            # the storm condition re-triggers on the next retrace and the
            # re-armed warning fires AGAIN (fresh-run semantics)
            for n in range(8, 15):
                f(jnp.asarray(np.ones(n, np.float32)))
            second = sum(
                "reset.storm.entry" in r.getMessage() for r in records
            )
        finally:
            logger.removeHandler(handler)
        self.assertEqual(first, 1)
        self.assertEqual(second, 2)

    def test_reset_clears_per_instance_signature_stores(self):
        # ISSUE 15 regression: reset() re-arms the storm warning AND must
        # clear every watched_jit instance's per-static-key signature set —
        # otherwise the re-armed warning fires on the very next SINGLE
        # trace over stale counts (one test's legitimate shape diversity
        # leaking a storm into a later churn-free run)
        obs.set_retrace_threshold(3)
        f = obs.watched_jit(lambda x: x + 1.0, name="reset.storm.fresh")
        for n in range(1, 6):
            f(jnp.asarray(np.ones(n, np.float32)))  # a legitimate storm
        obs.reset()
        logger, handler, records = _capture_telemetry()
        try:
            # ONE new shape after reset: a fresh run, no storm
            f(jnp.asarray(np.ones(32, np.float32)))
        finally:
            logger.removeHandler(handler)
        self.assertEqual(
            [
                r.getMessage()
                for r in records
                if "reset.storm.fresh" in r.getMessage()
            ],
            [],
        )

    def test_dropped_wrapper_store_is_collectable(self):
        # review finding: the reset registry must hold instance stores
        # WEAKLY — a dynamically-created wrapper's signature store dies
        # with its closure instead of being pinned forever
        import gc

        from torcheval_tpu.obs import recompile

        before = len(recompile._group_stores)
        f = obs.watched_jit(lambda x: x - 1.0, name="reset.storm.dropme")
        f(jnp.asarray(np.ones(3, np.float32)))
        self.assertEqual(len(recompile._group_stores), before + 1)
        del f
        gc.collect()
        self.assertEqual(len(recompile._group_stores), before)

    def test_reset_while_disabled_is_safe_and_total(self):
        obs.enable()
        obs.counter("reset.c")
        obs.disable()
        obs.reset()  # must not depend on the enable flag
        self.assertEqual(obs.snapshot()["counters"], {})


if __name__ == "__main__":
    unittest.main()

"""Stdlib metrics endpoint (ISSUE 16 satellite): ``GET /metrics``
(Prometheus exposition) + ``GET /health`` (JSON from the wired
provider), ephemeral ports, contained provider failures, idempotent
close."""

import json
import unittest
import urllib.error
import urllib.request

from torcheval_tpu.obs.httpd import MetricsServer
from torcheval_tpu.obs.registry import Registry


class TestMetricsServer(unittest.TestCase):
    def _server(self, **kw):
        srv = MetricsServer(port=0, **kw).start()
        self.addCleanup(srv.close)
        return srv

    def _get(self, srv, path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=10
        )

    def test_metrics_serves_prometheus_text(self):
        reg = Registry()
        reg.counter("requests", 3, lane="a")
        srv = self._server(registry=reg)
        resp = self._get(srv, "/metrics")
        self.assertEqual(resp.status, 200)
        self.assertIn("text/plain", resp.headers["Content-Type"])
        body = resp.read().decode()
        self.assertIn("requests", body)
        self.assertIn("# TYPE", body)

    def test_health_default_is_minimal_ok(self):
        srv = self._server()
        resp = self._get(srv, "/health")
        self.assertEqual(json.loads(resp.read().decode()), {"ok": True})

    def test_health_serves_wired_provider(self):
        srv = self._server(
            health_provider=lambda: {"schema": 1, "queue": {"depth": 2}}
        )
        body = json.loads(self._get(srv, "/health").read().decode())
        self.assertEqual(body["schema"], 1)
        self.assertEqual(body["queue"]["depth"], 2)

    def test_broken_provider_is_a_contained_500(self):
        def broken():
            raise RuntimeError("daemon mid-shutdown")

        srv = self._server(health_provider=broken)
        with self.assertRaises(urllib.error.HTTPError) as ctx:
            self._get(srv, "/health")
        self.assertEqual(ctx.exception.code, 500)
        body = json.loads(ctx.exception.read().decode())
        self.assertFalse(body["ok"])
        self.assertIn("daemon mid-shutdown", body["error"])
        # the server survives the broken provider
        self.assertEqual(self._get(srv, "/metrics").status, 200)

    def test_unknown_path_is_404(self):
        srv = self._server()
        with self.assertRaises(urllib.error.HTTPError) as ctx:
            self._get(srv, "/nope")
        self.assertEqual(ctx.exception.code, 404)

    def test_ephemeral_port_is_readable_and_close_is_idempotent(self):
        srv = MetricsServer(port=0).start()
        self.assertGreater(srv.port, 0)
        self.assertEqual(srv.address, ("127.0.0.1", srv.port))
        srv.close()
        srv.close()  # idempotent

    def test_start_is_idempotent(self):
        srv = self._server()
        port = srv.port
        srv.start()
        self.assertEqual(srv.port, port)


if __name__ == "__main__":
    unittest.main()

"""``obs.sync_snapshot`` on a real 4-process ``jax.distributed`` world
(ISSUE 7 acceptance): per-rank registries merged in exactly ONE collective
round, and the degraded-local path proven with a chaos-delayed straggler —
the PR 5 fault-injection harness reused against the obs wire.

One world, both legs: every rank records distinct instruments, snapshot 1
(collective round 1) is healthy and asserts the merge semantics; snapshot 2
(round 2) runs with rank 2 chaos-delayed past every deadline, so the
survivors must degrade to their local view within ``TIMEOUT_S``.
"""

import json
import os
import socket
import subprocess
import sys
import unittest

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_WORKER = os.path.join(_HERE, "mp_obs_worker.py")

sys.path.insert(0, _HERE)
from mp_obs_worker import (  # noqa: E402
    DEGRADED_ROUND,
    STRAGGLE_S,
    STRAGGLER_RANK,
    TIMEOUT_S,
)

WORLD = 4


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _artifact_dir() -> str:
    """Worker results + obs snapshots; CI points this at the uploaded
    test-artifacts/ directory so a hung run leaves a diagnosable trace."""
    base = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if base:
        d = os.path.join(base, "obs_sync_snapshot")
        os.makedirs(d, exist_ok=True)
        return d
    import tempfile

    return tempfile.mkdtemp(prefix="tpu_obs_snap_")


def _launch_world(tmpdir: str) -> list:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # each worker models one single-device host
    env.update(
        {
            # PR 5 chaos harness: delay the straggler entering the round
            # that carries the second (degraded-leg) sync_snapshot
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_RANK": str(STRAGGLER_RANK),
            "TORCHEVAL_TPU_CHAOS_ROUND": str(DEGRADED_ROUND),
            "TORCHEVAL_TPU_CHAOS_ACTION": "delay",
            "TORCHEVAL_TPU_CHAOS_DELAY_S": str(STRAGGLE_S),
            # leader holds the coordinator alive until the straggler has
            # woken, degraded, and written its results
            "TORCHEVAL_TPU_CHAOS_HOLD_S": str(STRAGGLE_S - TIMEOUT_S + 8.0),
        }
    )
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(r), str(WORLD), str(port), tmpdir],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for r in range(WORLD)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    for r, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise AssertionError(
                f"worker rank {r} exited {p.returncode}:\n{out[-4000:]}"
            )
    results = []
    for r in range(WORLD):
        with open(os.path.join(tmpdir, f"rank{r}.json")) as f:
            results.append(json.load(f))
    return results


class TestSyncSnapshotWorld(unittest.TestCase):
    """One 4-process launch, many assertions (distributed init dominates)."""

    @classmethod
    def setUpClass(cls):
        cls.tmpdir = _artifact_dir()
        cls.results = _launch_world(cls.tmpdir)

    def test_merge_cost_exactly_one_collective_round(self):
        # THE acceptance criterion: the whole-world merge is one round on
        # every rank, observable via toolkit.sync.rounds
        for res in self.results:
            self.assertEqual(res["rounds_delta"], 1.0)

    def test_world_view_identical_shape_on_every_rank(self):
        for res in self.results:
            self.assertEqual(res["view_world_size"], WORLD)
            self.assertEqual(res["view_ranks"], list(range(WORLD)))
            self.assertFalse(res["view_degraded"])

    def test_counters_summed_across_ranks(self):
        # per-rank value is rank+1 -> global 1+2+3+4
        for res in self.results:
            self.assertEqual(res["view_counters"]["mp.obs.batches"], 10.0)
            # labelled series merge per (name, labels): ranks 0,2 -> L0,
            # ranks 1,3 -> L1, two increments each
            self.assertEqual(res["view_counters"]["mp.obs.lane{lane=L0}"], 2.0)
            self.assertEqual(res["view_counters"]["mp.obs.lane{lane=L1}"], 2.0)

    def test_gauges_keep_per_rank_identity(self):
        for res in self.results:
            for r in range(WORLD):
                self.assertEqual(
                    res["view_gauges"][f"mp.obs.rss{{rank={r}}}"],
                    float(100 + r),
                )

    def test_histograms_bucket_summed(self):
        # rank r recorded r+1 samples -> merged count 1+2+3+4
        for res in self.results:
            self.assertEqual(res["view_histo"]["count"], 10)
            self.assertGreater(res["view_histo"]["p95"], 0.0)

    def test_spans_and_timeline_cover_every_rank(self):
        for res in self.results:
            self.assertEqual(res["view_span_count"], WORLD)
            self.assertEqual(res["event_ranks"], list(range(WORLD)))

    def test_degraded_leg_returns_local_within_deadline(self):
        for res in self.results:
            self.assertTrue(res["view2_degraded"])
            self.assertEqual(res["view2_world_size"], 1)
            # the local fallback still answers from this rank's registry
            self.assertEqual(
                res["view2_local_counter"], float(res["rank"] + 1)
            )
            self.assertEqual(res["timeouts_local"], 1.0)

    def test_survivors_did_not_wait_for_the_straggler(self):
        for res in self.results:
            if res["rank"] == STRAGGLER_RANK:
                # the straggler burned its budget sleeping: its own degrade
                # includes the chaos delay
                self.assertGreaterEqual(
                    res["degraded_elapsed_s"], STRAGGLE_S - 1.0
                )
            else:
                self.assertLess(
                    res["degraded_elapsed_s"], STRAGGLE_S - 1.0
                )
                self.assertGreaterEqual(
                    res["degraded_elapsed_s"], TIMEOUT_S - 1.0
                )

    def test_obs_snapshots_written_for_ci_triage(self):
        for r in range(WORLD):
            path = os.path.join(self.tmpdir, f"rank{r}.obs.json")
            self.assertTrue(os.path.exists(path))
            with open(path) as f:
                snap = json.load(f)
            self.assertIn("counters", snap)


if __name__ == "__main__":
    unittest.main()

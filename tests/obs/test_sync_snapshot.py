"""Cross-rank obs aggregation, single-process coverage (ISSUE 7 tentpole
leg 4): the merge semantics, the fixed-size wire encoding with its staged
truncation, and the world-size-1 short circuit. The real 4-process world
(one-collective-round assertion, degraded-local fault leg) lives in
test_sync_snapshot_mp.py.
"""

import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.obs import distributed as dist


def _payload(rank, **over):
    p = {
        "rank": rank,
        "counters": [],
        "gauges": [],
        "histos": [],
        "spans": [],
        "events": [],
        "truncated": False,
    }
    p.update(over)
    return p


class SyncSnapshotTestCase(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()

    def tearDown(self):
        obs.disable()
        obs.reset()


class TestMerge(SyncSnapshotTestCase):
    def test_counters_summed_across_ranks(self):
        view = dist._merge(
            [
                _payload(0, counters=[("c", (), 1.0), ("d", (("k", "v"),), 2.0)]),
                _payload(1, counters=[("c", (), 10.0)]),
            ],
            2,
        )
        self.assertEqual(view["counters"]["c"], 11.0)
        self.assertEqual(view["counters"]["d{k=v}"], 2.0)
        self.assertEqual(view["world_size"], 2)
        self.assertEqual(view["ranks"], [0, 1])
        self.assertFalse(view["degraded"])

    def test_gauges_keep_per_rank_identity(self):
        view = dist._merge(
            [
                _payload(0, gauges=[("g", (), 5.0)]),
                _payload(1, gauges=[("g", (), 7.0)]),
            ],
            2,
        )
        # last-write-wins has no cross-rank meaning: one series per rank
        self.assertEqual(view["gauges"]["g{rank=0}"], 5.0)
        self.assertEqual(view["gauges"]["g{rank=1}"], 7.0)

    def test_histograms_bucket_summed(self):
        from torcheval_tpu.obs.registry import HISTOGRAM_BUCKETS, bucket_index

        b0 = [0] * HISTOGRAM_BUCKETS
        b1 = [0] * HISTOGRAM_BUCKETS
        for v in (0.001, 0.002):
            b0[bucket_index(v)] += 1
        b1[bucket_index(0.004)] += 1
        view = dist._merge(
            [
                _payload(0, histos=[("h", (), (tuple(b0), 2, 0.003))]),
                _payload(1, histos=[("h", (), (tuple(b1), 1, 0.004))]),
            ],
            2,
        )
        h = view["histograms"]["h"]
        self.assertEqual(h["count"], 3)
        self.assertAlmostEqual(h["sum"], 0.007)
        # percentiles re-estimated on the MERGED buckets
        self.assertGreater(h["p99"], h["p50"])

    def test_spans_summed_with_max_of_max(self):
        from torcheval_tpu.obs.registry import HISTOGRAM_BUCKETS, bucket_index

        def span_val(seconds_list):
            b = [0] * HISTOGRAM_BUCKETS
            for s in seconds_list:
                b[bucket_index(s)] += 1
            return (
                len(seconds_list),
                sum(seconds_list),
                max(seconds_list),
                tuple(b),
            )

        view = dist._merge(
            [
                _payload(0, spans=[("s", (), span_val([0.001, 0.002]))]),
                _payload(1, spans=[("s", (), span_val([0.030]))]),
            ],
            2,
        )
        s = view["spans"]["s"]
        self.assertEqual(s["count"], 3)
        self.assertAlmostEqual(s["total_seconds"], 0.033)
        self.assertAlmostEqual(s["max_seconds"], 0.030)
        self.assertGreater(s["p99"], s["p50"])

    def test_events_rank_tagged_and_ordered(self):
        e = {"name": "x", "kind": "t", "ts": 2.0, "dur": 0.0, "labels": {}, "tid": 1}
        view = dist._merge(
            [
                _payload(1, events=[{**e, "ts": 1.0}]),
                _payload(0, events=[{**e, "ts": 3.0}, {**e, "ts": 2.0}]),
            ],
            2,
        )
        got = [(ev["rank"], ev["ts"]) for ev in view["events"]]
        # ordered (rank, ts): per-process clocks are not comparable, so no
        # cross-rank time interleave is attempted
        self.assertEqual(got, [(0, 2.0), (0, 3.0), (1, 1.0)])

    def test_truncated_ranks_surfaced(self):
        view = dist._merge(
            [_payload(0), _payload(2, truncated=True), _payload(1)], 3
        )
        self.assertEqual(view["truncated_ranks"], [2])


class TestWire(SyncSnapshotTestCase):
    def test_encode_decode_round_trip(self):
        p = _payload(3, counters=[("c", (("k", "v"),), 4.0)])
        buf = dist._encode(p, 1 << 16)
        self.assertEqual(buf.dtype, np.uint8)
        self.assertEqual(buf.size, 1 << 16)
        self.assertEqual(dist._decode(buf), p)

    def test_over_budget_drops_events_first(self):
        big_events = [
            {"name": f"e{i}", "kind": "t", "ts": float(i), "dur": 0.0,
             "labels": {"i": i}, "tid": 1}
            for i in range(2000)
        ]
        p = _payload(1, counters=[("c", (), 1.0)], events=big_events)
        buf = dist._encode(p, 1 << 14)  # too small for the events
        got = dist._decode(buf)
        self.assertTrue(got["truncated"])
        self.assertEqual(got["events"], [])
        # instruments survived the first truncation stage
        self.assertEqual(got["counters"], [("c", (), 1.0)])

    def test_tiny_budget_degrades_to_stub_never_raises(self):
        p = _payload(
            2,
            counters=[(f"c{i}", (), float(i)) for i in range(5000)],
        )
        buf = dist._encode(p, 256)
        got = dist._decode(buf)
        self.assertEqual(got["rank"], 2)
        self.assertTrue(got["truncated"])
        self.assertEqual(got["counters"], [])

    def test_absurd_budget_sends_empty_buffer_never_crashes(self):
        # budget too small for even the stage-3 stub pickle: the encoder
        # must not raise mid-collective (numpy broadcast error) — it sends
        # an empty buffer the peers decode as None and drop from the merge
        p = _payload(1, counters=[("c", (), 1.0)])
        buf = dist._encode(p, 16)
        self.assertEqual(buf.size, 16)
        self.assertIsNone(dist._decode(buf))

    def test_decode_garbage_returns_none(self):
        self.assertIsNone(dist._decode(np.zeros(64, dtype=np.uint8)))
        junk = np.full(64, 255, dtype=np.uint8)
        self.assertIsNone(dist._decode(junk))


class TestWorldSizeOne(SyncSnapshotTestCase):
    def test_local_view_same_shape_no_collective(self):
        obs.enable()
        obs.counter("mp.c", 3.0)
        obs.gauge("mp.g", 9.0)
        obs.histo("mp.h", 0.5)
        with obs.span("mp.s"):
            pass
        view = obs.sync_snapshot()
        self.assertEqual(view["world_size"], 1)
        self.assertEqual(view["ranks"], [0])
        self.assertFalse(view["degraded"])
        self.assertEqual(view["counters"]["mp.c"], 3.0)
        self.assertEqual(view["gauges"]["mp.g{rank=0}"], 9.0)
        self.assertEqual(view["histograms"]["mp.h"]["count"], 1)
        self.assertEqual(view["spans"]["mp.s"]["count"], 1)
        # the span mirrored into the timeline and arrives rank-tagged
        self.assertTrue(
            any(e["name"] == "mp.s" and e["rank"] == 0 for e in view["events"])
        )
        # no collective ran at world size 1
        self.assertNotIn(
            "toolkit.sync.rounds", obs.snapshot()["counters"]
        )

    def test_bad_policy_and_budget_rejected(self):
        with self.assertRaises(ValueError):
            obs.sync_snapshot(on_failure="retry")
        with self.assertRaises(ValueError):
            obs.sync_snapshot(max_bytes=4)


if __name__ == "__main__":
    unittest.main()

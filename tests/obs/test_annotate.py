"""Annotation-threading tests: Metric protocol methods, MetricCollection,
ShardedEvaluator and kernel entry points all report spans/scopes; results
are bit-identical with obs on and off; disabled path records nothing."""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    BinaryAUROC,
    MeanSquaredError,
    MetricCollection,
    MulticlassAccuracy,
)
from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh

RNG = np.random.default_rng(7)


def _batch(n=64):
    scores = jnp.asarray(RNG.random((n, 5)).astype(np.float32))
    labels = jnp.asarray(RNG.integers(0, 5, n))
    return scores, labels


class TestAnnotate(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()

    def tearDown(self):
        obs.disable()
        obs.reset()

    def test_disabled_records_no_spans(self):
        m = MulticlassAccuracy(num_classes=5)
        m.update(*_batch())
        m.compute()
        self.assertEqual(obs.snapshot()["spans"], {})

    def test_metric_protocol_spans_named_by_runtime_class(self):
        obs.enable()
        m = BinaryAUROC()  # update/compute live on _BinaryCurveMetric
        s = jnp.asarray(RNG.random(32).astype(np.float32))
        t = jnp.asarray((RNG.random(32) > 0.5).astype(np.float32))
        m.update(s, t)
        m.compute()
        spans = obs.snapshot()["spans"]
        self.assertIn("metric.update/BinaryAUROC", spans)
        self.assertIn("metric.compute/BinaryAUROC", spans)

    def test_merge_state_span(self):
        obs.enable()
        a, b = MulticlassAccuracy(num_classes=5), MulticlassAccuracy(
            num_classes=5
        )
        a.update(*_batch())
        b.update(*_batch())
        a.merge_state([b])
        self.assertIn(
            "metric.merge_state/MulticlassAccuracy",
            obs.snapshot()["spans"],
        )

    def test_values_identical_enabled_vs_disabled(self):
        scores, labels = _batch(128)
        m_off = MulticlassAccuracy(num_classes=5)
        m_off.update(scores, labels)
        off = float(m_off.compute())
        obs.enable()
        m_on = MulticlassAccuracy(num_classes=5)
        m_on.update(scores, labels)
        on = float(m_on.compute())
        self.assertEqual(on, off)

    def test_collection_spans_nest_under_collection(self):
        obs.enable()
        col = MetricCollection(
            {"mse": MeanSquaredError(), "auroc": BinaryAUROC()}
        )
        s = jnp.asarray(RNG.random(32).astype(np.float32))
        t = jnp.asarray((RNG.random(32) > 0.5).astype(np.float32))
        col.update(s, t)
        col.compute()
        spans = obs.snapshot()["spans"]
        self.assertIn("collection.update", spans)
        self.assertIn("collection.compute", spans)
        self.assertIn(
            "collection.compute/metric.compute/BinaryAUROC", spans
        )
        # the whole-window step dispatch is attributed under the collection
        # read that triggered it (update() itself dispatches nothing — that
        # is the point of the window-accumulator lane)
        self.assertIn("collection.compute/jit/deferred.window_step", spans)

    def test_evaluator_spans(self):
        obs.enable()
        ev = ShardedEvaluator(
            MulticlassAccuracy(num_classes=5), mesh=data_parallel_mesh()
        )
        scores = jnp.asarray(RNG.random((64, 5)).astype(np.float32))
        labels = jnp.asarray(RNG.integers(0, 5, 64))
        ev.update(scores, labels)
        ev.compute()
        spans = obs.snapshot()["spans"]
        self.assertIn("evaluator.update", spans)
        self.assertIn("evaluator.compute", spans)
        self.assertIn("evaluator.update/collection.update", spans)

    def test_kernel_entry_point_counted(self):
        obs.enable()
        from torcheval_tpu.ops.curves import binary_auroc_kernel

        s = jnp.asarray(RNG.random(64).astype(np.float32))
        t = jnp.asarray((RNG.random(64) > 0.5).astype(np.float32))
        binary_auroc_kernel(s, t)
        snap = obs.snapshot()
        self.assertEqual(
            snap["counters"]["jit.calls{entry=binary_auroc_kernel}"], 1
        )

    def test_named_scope_lands_in_kernel_hlo(self):
        # the profiler-attribution half: the entry point's name must reach
        # the lowered module text so XLA traces attribute device time per
        # kernel. watched_jit exposes the underlying jit object as .jitted.
        from torcheval_tpu.ops.curves import binary_auroc_kernel

        s = jnp.ones((8,), jnp.float32)
        t = jnp.ones((8,), jnp.float32)
        text = binary_auroc_kernel.jitted.lower(s, t).as_text()
        self.assertIn("binary_auroc_kernel", text)

    def test_user_defined_metric_subclass_is_instrumented(self):
        obs.enable()
        from torcheval_tpu.metrics.metric import Metric

        class MyMetric(Metric):
            def __init__(self):
                super().__init__()
                self._add_state("total", jnp.zeros(()))

            def update(self, x):
                self.total = self.total + jnp.sum(x)
                return self

            def compute(self):
                return self.total

            def merge_state(self, metrics):
                for m in metrics:
                    self.total = self.total + m.total
                return self

        m = MyMetric()
        m.update(jnp.asarray([1.0, 2.0]))
        self.assertEqual(float(m.compute()), 3.0)
        spans = obs.snapshot()["spans"]
        self.assertIn("metric.update/MyMetric", spans)
        self.assertIn("metric.compute/MyMetric", spans)


if __name__ == "__main__":
    unittest.main()

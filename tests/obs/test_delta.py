"""Delta-snapshot algebra (ISSUE 16 tentpole): ``Registry.delta_since``
cursors, ``obs.stream.collect`` / ``DeltaAccumulator``.

The contract under test: applying every delta in order reconstructs the
full snapshot EXACTLY (delta∘delta == snapshot diff), cursors stay
monotonic across ``obs.reset()`` (a reset bumps the generation and the
next delta is full, never a misfolded diff), and sparse histogram bucket
deltas sum exactly to the count delta — the accumulator never drifts.
"""

import threading
import unittest

from torcheval_tpu import obs
from torcheval_tpu.obs import trace as obs_trace
from torcheval_tpu.obs.registry import Registry
from torcheval_tpu.obs.stream import (
    DeltaAccumulator,
    collect,
    delta_nbytes,
)


class TestRegistryDelta(unittest.TestCase):
    def setUp(self):
        self.reg = Registry()

    def test_first_delta_is_full(self):
        self.reg.counter("c", 3)
        delta, cursor = self.reg.delta_since(None)
        self.assertTrue(delta["full"])
        self.assertEqual(delta["counters"]["c"], 3.0)
        self.assertEqual(delta["seq"], 1)
        self.assertIsNotNone(cursor)

    def test_incremental_delta_carries_only_changes(self):
        self.reg.counter("c", 3)
        self.reg.gauge("g", 1.0)
        _, cursor = self.reg.delta_since(None)
        self.reg.counter("c", 2)
        delta, _ = self.reg.delta_since(cursor)
        self.assertFalse(delta["full"])
        self.assertEqual(delta["counters"], {"c": 2.0})
        self.assertEqual(delta["gauges"], {})  # unchanged gauge absent

    def test_quiet_registry_yields_empty_delta(self):
        self.reg.counter("c")
        _, cursor = self.reg.delta_since(None)
        delta, _ = self.reg.delta_since(cursor)
        self.assertEqual(delta["counters"], {})
        self.assertEqual(delta["gauges"], {})
        self.assertEqual(delta["histograms"], {})
        self.assertEqual(delta["spans"], {})

    def test_histogram_bucket_deltas_sum_exactly_to_count_delta(self):
        for v in (0.001, 0.01, 0.01, 1.0, 30.0):
            self.reg.histo("h", v)
        _, cursor = self.reg.delta_since(None)
        for v in (0.01, 0.5, 0.5, 100.0):
            self.reg.histo("h", v)
        delta, _ = self.reg.delta_since(cursor)
        h = delta["histograms"]["h"]
        self.assertEqual(sum(n for _i, n in h["buckets"]), h["count"])
        self.assertEqual(h["count"], 4)
        # every sparse entry is a strictly positive increment
        self.assertTrue(all(n > 0 for _i, n in h["buckets"]))

    def test_span_delta_ships_absolute_max(self):
        self.reg._record_span("s", (), 0.5)
        _, cursor = self.reg.delta_since(None)
        self.reg._record_span("s", (), 0.1)
        delta, _ = self.reg.delta_since(cursor)
        s = delta["spans"]["s"]
        self.assertEqual(s["count"], 1)
        self.assertAlmostEqual(s["total_seconds"], 0.1)
        # max is monotone within a generation: absolute, not a diff
        self.assertAlmostEqual(s["max_seconds"], 0.5)

    def test_cursor_seq_is_monotonic(self):
        seqs = []
        cursor = None
        for _ in range(5):
            self.reg.counter("c")
            delta, cursor = self.reg.delta_since(cursor)
            seqs.append(delta["seq"])
        self.assertEqual(seqs, sorted(seqs))
        self.assertEqual(len(set(seqs)), len(seqs))

    def test_reset_bumps_generation_and_forces_full_delta(self):
        self.reg.counter("c", 10)
        _, cursor = self.reg.delta_since(None)
        gen0 = cursor.gen
        self.reg.reset()
        self.reg.counter("c", 1)
        delta, cursor2 = self.reg.delta_since(cursor)
        self.assertTrue(delta["full"])
        self.assertGreater(delta["gen"], gen0)
        # the counter restarts from 1 — NOT a negative diff vs the old 10
        self.assertEqual(delta["counters"]["c"], 1.0)
        # and the seq still advanced (monotonic across resets)
        self.assertGreater(cursor2.seq, cursor.seq)


class TestDeltaComposition(unittest.TestCase):
    """delta∘delta == snapshot diff, through the accumulator."""

    def _pump(self, reg, seed):
        reg.counter("events", 1 + seed)
        reg.counter("bytes", 10.0 * (seed + 1), lane="SUM")
        reg.gauge("depth", float(seed))
        for v in (0.001 * (seed + 1), 0.1, 2.0**seed):
            reg.histo("lat", v)
        reg._record_span("step", (), 0.01 * (seed + 1))

    def test_accumulated_deltas_reconstruct_snapshot_exactly(self):
        reg = Registry()
        acc = DeltaAccumulator()
        cursor = None
        for seed in range(4):
            self._pump(reg, seed)
            delta, cursor = reg.delta_since(cursor)
            acc.apply(delta)
        want, got = reg.snapshot(), acc.snapshot()
        self.assertEqual(got["counters"], want["counters"])
        self.assertEqual(got["gauges"], want["gauges"])
        for key, h in want["histograms"].items():
            g = got["histograms"][key]
            self.assertEqual(g["count"], h["count"])
            self.assertAlmostEqual(g["sum"], h["sum"])
            for q in ("p50", "p95", "p99"):
                self.assertAlmostEqual(g[q], h[q])
        for key, s in want["spans"].items():
            g = got["spans"][key]
            self.assertEqual(g["count"], s["count"])
            self.assertAlmostEqual(g["total_seconds"], s["total_seconds"])
            self.assertAlmostEqual(g["max_seconds"], s["max_seconds"])

    def test_two_step_composition_equals_one_step(self):
        """Folding deltas A->B and B->C equals the single delta A->C."""
        reg = Registry()
        self._pump(reg, 0)
        _, base = reg.delta_since(None)

        self._pump(reg, 1)
        d1, mid = reg.delta_since(base)
        self._pump(reg, 2)
        d2, _ = reg.delta_since(mid)

        direct, _ = reg.delta_since(base)

        two = DeltaAccumulator()
        two.apply(d1)
        two.apply(d2)
        one = DeltaAccumulator()
        one.apply(direct)
        self.assertEqual(
            two.snapshot()["counters"], one.snapshot()["counters"]
        )
        self.assertEqual(
            two.snapshot()["gauges"], one.snapshot()["gauges"]
        )
        th = two.snapshot()["histograms"]
        oh = one.snapshot()["histograms"]
        self.assertEqual(
            {k: v["count"] for k, v in th.items()},
            {k: v["count"] for k, v in oh.items()},
        )

    def test_full_delta_clears_accumulator_state(self):
        reg = Registry()
        reg.counter("c", 5)
        acc = DeltaAccumulator()
        d, cursor = reg.delta_since(None)
        acc.apply(d)
        reg.reset()
        reg.counter("c", 2)
        d2, _ = reg.delta_since(cursor)
        self.assertTrue(d2["full"])
        acc.apply(d2)
        # post-reset truth, not 5+2
        self.assertEqual(acc.snapshot()["counters"]["c"], 2.0)

    def test_concurrent_writers_never_break_the_algebra(self):
        reg = Registry()
        acc = DeltaAccumulator()
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                reg.counter("spin")

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            cursor = None
            for _ in range(20):
                delta, cursor = reg.delta_since(cursor)
                acc.apply(delta)
        finally:
            stop.set()
            t.join(5.0)
        delta, _ = reg.delta_since(cursor)
        acc.apply(delta)
        self.assertEqual(
            acc.snapshot()["counters"]["spin"],
            reg.snapshot()["counters"]["spin"],
        )


class TestStreamCollect(unittest.TestCase):
    def setUp(self):
        obs.reset()
        obs.enable()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)

    def test_collect_includes_timeline_events_once(self):
        obs_trace.instant("evt.a", kind="test")
        delta, cursor = collect()
        names = [e["name"] for e in delta["events"]]
        self.assertIn("evt.a", names)
        obs_trace.instant("evt.b", kind="test")
        delta2, _ = collect(cursor)
        names2 = [e["name"] for e in delta2["events"]]
        self.assertNotIn("evt.a", names2)  # already streamed
        self.assertIn("evt.b", names2)

    def test_collect_trims_event_floods_and_counts_them(self):
        for i in range(40):
            obs_trace.instant(f"evt.{i}", kind="test")
        delta, _ = collect(max_events=10)
        self.assertEqual(len(delta["events"]), 10)
        self.assertEqual(delta["events_trimmed"], 30)
        # the newest events survive the trim
        self.assertEqual(delta["events"][-1]["name"], "evt.39")

    def test_cursor_survives_obs_reset(self):
        obs_trace.instant("evt.a", kind="test")
        _, cursor = collect()
        obs.reset()  # clears the ring AND bumps the registry generation
        obs.enable()
        obs_trace.instant("evt.c", kind="test")
        # the full delta rewinds the event cursor: post-reset events are
        # delivered even though the all-time index moved backwards
        delta, _ = collect(cursor)
        self.assertTrue(delta["full"])
        self.assertIn(
            "evt.c", [e["name"] for e in delta["events"]]
        )

    def test_delta_nbytes_is_compact_json_length(self):
        delta, _ = collect()
        self.assertGreater(delta_nbytes(delta), 0)
        self.assertIsInstance(delta_nbytes(delta), int)

    def test_obs_reset_forces_full_collect(self):
        obs.counter("c", 2)
        _, cursor = collect()
        obs.reset()
        obs.enable()
        obs.counter("c", 1)
        delta, _ = collect(cursor)
        self.assertTrue(delta["full"])
        self.assertEqual(delta["counters"]["c"], 1.0)


if __name__ == "__main__":
    unittest.main()

"""Worker for the 4-process ``obs.sync_snapshot`` test (ISSUE 7 acceptance).

Each process joins a real ``jax.distributed`` CPU world, records per-rank
obs instruments (counter/labelled counter/gauge/histogram/span — the span
also lands a timeline event), and then:

1. **healthy leg** — ``obs.sync_snapshot(timeout_s=60)`` merges every
   rank's registry; the worker asserts locally that the merge cost exactly
   ONE ``toolkit.sync.rounds`` increment (the one-collective-round
   acceptance criterion), and writes the merged view for the parent's
   cross-rank assertions;
2. **degraded leg** — the chaos hooks (armed by the parent via
   ``TORCHEVAL_TPU_CHAOS_*``, the PR 5 fault-injection harness) delay
   rank ``STRAGGLER_RANK`` past every deadline as it enters the second
   snapshot round; the survivors' ``sync_snapshot(timeout_s=,
   on_failure="local")`` must come back within the deadline with the LOCAL
   single-rank view flagged ``degraded`` and the
   ``toolkit.sync.timeouts{policy=local}`` counter bumped. The straggler
   burns its own budget sleeping and degrades the same way.

Run:  python mp_obs_worker.py <rank> <world> <port> <outdir>
Writes <outdir>/rank<r>.json plus <outdir>/rank<r>.obs.json (CI triage
artifact, same pattern as the fault-injection worlds).
"""

import json
import os
import sys
import time

TIMEOUT_S = 6.0
STRAGGLE_S = 14.0
STRAGGLER_RANK = 2
# healthy snapshot = collective round 1; the degraded leg's snapshot is
# round 2, which is where the parent arms the chaos delay
DEGRADED_ROUND = 2


def main() -> None:
    rank, world, port, outdir = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["MASTER_ADDR"] = "localhost"
    os.environ["MASTER_PORT"] = port
    os.environ["WORLD_SIZE"] = str(world)
    os.environ["RANK"] = str(rank)
    from torcheval_tpu.parallel import init_from_env

    got_rank, got_world = init_from_env()
    assert (got_rank, got_world) == (rank, world)

    from torcheval_tpu import obs

    obs.enable()
    results = {"rank": rank}

    # per-rank instrument values the parent can compute oracles for
    obs.counter("mp.obs.batches", float(rank + 1))
    obs.counter("mp.obs.lane", 1.0, lane=f"L{rank % 2}")
    obs.gauge("mp.obs.rss", float(100 + rank))
    for i in range(rank + 1):
        obs.histo("mp.obs.lat", 0.001 * (i + 1))
    with obs.span("mp.obs.work", rank_tag=str(rank)):
        time.sleep(0.001)

    # --- healthy leg: ONE collective round merges the whole world
    before = obs.snapshot()["counters"].get("toolkit.sync.rounds", 0.0)
    view = obs.sync_snapshot(timeout_s=60.0)
    after = obs.snapshot()["counters"].get("toolkit.sync.rounds", 0.0)
    results["rounds_delta"] = after - before
    results["view_world_size"] = view["world_size"]
    results["view_ranks"] = view["ranks"]
    results["view_degraded"] = view["degraded"]
    results["view_counters"] = {
        k: v for k, v in view["counters"].items() if k.startswith("mp.obs")
    }
    results["view_gauges"] = {
        k: v for k, v in view["gauges"].items() if k.startswith("mp.obs")
    }
    results["view_histo"] = view["histograms"].get("mp.obs.lat")
    results["view_span_count"] = sum(
        v["count"]
        for k, v in view["spans"].items()
        if k.startswith("mp.obs.work")
    )
    results["event_ranks"] = sorted(
        {e["rank"] for e in view["events"] if e["name"] == "mp.obs.work"}
    )

    # --- degraded leg: chaos delays STRAGGLER_RANK entering this round
    t0 = time.monotonic()
    view2 = obs.sync_snapshot(timeout_s=TIMEOUT_S, on_failure="local")
    results["degraded_elapsed_s"] = time.monotonic() - t0
    results["view2_degraded"] = view2["degraded"]
    results["view2_world_size"] = view2["world_size"]
    # degraded-local still answers from THIS rank's registry
    results["view2_local_counter"] = view2["counters"].get("mp.obs.batches")
    snap = obs.snapshot()
    results["timeouts_local"] = snap["counters"].get(
        "toolkit.sync.timeouts{policy=local}", 0.0
    )

    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, f"rank{rank}.obs.json"), "w") as f:
        json.dump(snap, f, indent=2)
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(results, f)
        f.flush()
        os.fsync(f.fileno())
    # rank 0 hosts the coordination service; the coordination client
    # hard-aborts any process outliving the leader, so the leader holds
    # until the delayed straggler has finished its budget-expired degrade
    # and written its results (the PR 5 straggler-world choreography)
    hold_s = float(os.environ.get("TORCHEVAL_TPU_CHAOS_HOLD_S", "0"))
    if rank == 0 and hold_s > 0:
        time.sleep(hold_s)
    # hard exit: after a degraded sync the peers must not risk wedging in
    # interpreter teardown on a world with an abandoned collective
    os._exit(0)


if __name__ == "__main__":
    main()

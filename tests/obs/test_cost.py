"""Device cost attribution coverage (ISSUE 7 tentpole leg 2): per-entry
``obs.cost.*`` gauges off the lowered/compiled objects at watched_jit
compile time, capture only on compile-bearing dispatches, and the
recompile-watchdog suppression of the analysis re-lowering.
"""

import unittest

import jax.numpy as jnp

from torcheval_tpu import obs
from torcheval_tpu.obs import cost


class CostTestCase(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()

    def tearDown(self):
        obs.disable()
        obs.reset()


class TestCapture(CostTestCase):
    def test_compile_emits_per_entry_gauges(self):
        obs.enable()
        f = obs.watched_jit(lambda x: x * 2.0 + 1.0, name="cost.entry.a")
        f(jnp.ones((16,), jnp.float32))
        gauges = obs.snapshot()["gauges"]
        self.assertGreater(gauges["obs.cost.flops{entry=cost.entry.a}"], 0.0)
        self.assertGreater(
            gauges["obs.cost.bytes_accessed{entry=cost.entry.a}"], 0.0
        )
        # CPU exposes memory stats too; where a backend doesn't, the gauge
        # is simply absent (capture stages down, never raises)
        self.assertIn("obs.cost.hbm_bytes{entry=cost.entry.a}", gauges)
        self.assertEqual(
            obs.snapshot()["counters"][
                "obs.cost.captures{entry=cost.entry.a}"
            ],
            1.0,
        )

    def test_cache_hit_does_not_recapture(self):
        obs.enable()
        f = obs.watched_jit(lambda x: x + 1.0, name="cost.entry.b")
        for _ in range(4):
            f(jnp.ones((8,), jnp.float32))
        self.assertEqual(
            obs.snapshot()["counters"][
                "obs.cost.captures{entry=cost.entry.b}"
            ],
            1.0,
        )

    def test_recompile_updates_gauge_to_newest_program(self):
        obs.enable()
        f = obs.watched_jit(lambda x: x * x, name="cost.entry.c")
        f(jnp.ones((8,), jnp.float32))
        small = obs.snapshot()["gauges"][
            "obs.cost.bytes_accessed{entry=cost.entry.c}"
        ]
        f(jnp.ones((4096,), jnp.float32))  # new signature: recompiles
        big = obs.snapshot()["gauges"][
            "obs.cost.bytes_accessed{entry=cost.entry.c}"
        ]
        # last-write-wins: the gauge reports the NEWEST program's cost
        self.assertGreater(big, small)
        self.assertEqual(
            obs.snapshot()["counters"][
                "obs.cost.captures{entry=cost.entry.c}"
            ],
            2.0,
        )

    def test_disabled_captures_nothing(self):
        f = obs.watched_jit(lambda x: x + 1.0, name="cost.entry.d")
        f(jnp.ones((8,), jnp.float32))
        snap = obs.snapshot()
        self.assertEqual(
            [k for k in snap["gauges"] if k.startswith("obs.cost")], []
        )

    def test_compile_span_recorded(self):
        obs.enable()
        f = obs.watched_jit(lambda x: x - 1.0, name="cost.entry.e")
        f(jnp.ones((8,), jnp.float32))
        spans = obs.snapshot()["spans"]
        self.assertEqual(spans["jit.compile/cost.entry.e"]["count"], 1)
        self.assertGreater(
            spans["jit.compile/cost.entry.e"]["total_seconds"], 0.0
        )
        # the capture itself is timed too (its compile() may duplicate work;
        # the span makes that cost visible instead of hidden)
        self.assertEqual(spans["obs.cost.capture{entry=cost.entry.e}"]["count"], 1)

    def test_capture_relowering_invisible_to_watchdog(self):
        obs.enable()
        f = obs.watched_jit(lambda x: x * 3.0, name="cost.entry.f")
        f(jnp.ones((8,), jnp.float32))
        # cost.capture re-lowered the entry (re-running the traced body);
        # the watchdog must have seen exactly ONE trace, not two
        self.assertEqual(
            obs.snapshot()["counters"]["recompile.traces{entry=cost.entry.f}"],
            1.0,
        )
        counts = obs.trace_counts()["cost.entry.f"]
        self.assertEqual(counts["traces"], 1)
        self.assertEqual(counts["distinct_signatures"], 1)

    def test_capture_error_downgrades_to_counter(self):
        obs.enable()

        class Broken:
            def lower(self, *a, **k):
                raise RuntimeError("no lowering")

        cost.capture("cost.entry.broken", Broken(), (), {})
        snap = obs.snapshot()
        self.assertEqual(
            snap["counters"][
                "obs.cost.capture_errors{entry=cost.entry.broken}"
            ],
            1.0,
        )

    def test_sum_property_handles_dict_and_list_forms(self):
        # recent jaxlibs return a dict of properties; older ones a list of
        # per-computation dicts — both forms sum (the tools/flops.py rule)
        self.assertEqual(cost._sum_property({"flops": 5.0}, "flops"), 5.0)
        self.assertEqual(
            cost._sum_property([{"flops": 2.0}, {"flops": 3.0}], "flops"), 5.0
        )
        self.assertEqual(cost._sum_property(None, "flops"), 0.0)
        self.assertEqual(cost._sum_property({}, "flops"), 0.0)


if __name__ == "__main__":
    unittest.main()

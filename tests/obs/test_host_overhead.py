"""Disabled-path host-overhead guard (ISSUE 7 satellite): the flight
recorder must be FREE when off. `MetricCollection.update()` on the armed
fast path — the steady state of every eval loop — performs ZERO obs work
while obs is disabled: no timeline ring appends, no registry records, no
allocation inside the obs modules (the labels dicts for the window hooks
are built behind call-site ``if _obs._enabled`` guards, not inside the
gated helpers). This protects the PR 6 host-diet budget
(<1 ms/run config1; µs-scale per-update cost) from the ISSUE 7 hooks.
"""

import time
import tracemalloc
import unittest
from unittest import mock

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.obs import registry as obs_registry
from torcheval_tpu.obs import slo as obs_slo
from torcheval_tpu.obs import stream as obs_stream
from torcheval_tpu.obs import trace as obs_trace


def _armed_collection():
    from torcheval_tpu.metrics import Mean, MetricCollection, Sum

    col = MetricCollection({"mean": Mean(), "sum": Sum()})
    batch = np.arange(64, dtype=np.float32)
    # first update validates + arms the shared-window fast path; second
    # proves the armed path is taken (same full signature)
    col.update(batch)
    col.update(batch)
    return col, batch


class TestDisabledPathZeroObsWork(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()

    def tearDown(self):
        obs.disable()
        obs.reset()

    def test_zero_ring_appends_and_zero_registry_records(self):
        col, batch = _armed_collection()
        obs_trace.clear()
        reg = obs_registry.default_registry
        with (
            mock.patch.object(
                obs_trace, "_append", side_effect=AssertionError("ring append")
            ),
            mock.patch.object(
                reg, "counter", side_effect=AssertionError("counter")
            ),
            mock.patch.object(reg, "gauge", side_effect=AssertionError("gauge")),
            mock.patch.object(reg, "histo", side_effect=AssertionError("histo")),
            mock.patch.object(
                reg, "_record_span", side_effect=AssertionError("span")
            ),
        ):
            for _ in range(50):
                col.update(batch)
        self.assertEqual(obs_trace.event_count(), 0)

    def test_zero_allocations_inside_obs_modules(self):
        col, batch = _armed_collection()
        # warm any lazy caches on the exact path under measurement
        for _ in range(5):
            col.update(batch)
        # ISSUE 16: the streaming/SLO modules are IMPORTED (top of this
        # file) but idle — merely having them loaded must not add
        # allocations to the armed disabled-path update
        obs_files = (
            obs_trace.__file__,
            obs_registry.__file__,
            obs_stream.__file__,
            obs_slo.__file__,
        )
        tracemalloc.start(25)
        try:
            snap0 = tracemalloc.take_snapshot()
            for _ in range(50):
                col.update(batch)
            snap1 = tracemalloc.take_snapshot()
        finally:
            tracemalloc.stop()
        grew = [
            d
            for d in snap1.compare_to(snap0, "traceback")
            if d.size_diff > 0
            and any(
                f.filename in obs_files for f in d.traceback
            )
        ]
        self.assertEqual(
            grew,
            [],
            "obs modules allocated on the armed disabled-path update: "
            + "; ".join(str(d) for d in grew),
        )

    def test_window_hooks_fire_only_while_enabled(self):
        # sanity inverse: the SAME path does record once enabled — the
        # zero-append assertions above hold because of the enable gate, not
        # because the hooks are disconnected
        col, batch = _armed_collection()
        obs.enable()
        obs_trace.clear()
        col.update(batch)
        names = [e["name"] for e in obs_trace.events()]
        self.assertIn("deferred.window.append", names)

    def test_armed_update_microbenchmark(self):
        # gross-regression tripwire, not a precision benchmark: PR 6
        # measured ~4 µs/update on this path; a generous 1 ms median bound
        # catches an accidental O(ms) obs hook (e.g. an ungated chrome
        # export or lock) while staying robust to CI throttling
        col, batch = _armed_collection()
        for _ in range(10):
            col.update(batch)
        times = []
        for _ in range(7):
            t0 = time.perf_counter()
            for _ in range(20):
                col.update(batch)
            times.append((time.perf_counter() - t0) / 20)
        times.sort()
        self.assertLess(times[len(times) // 2], 1e-3)


if __name__ == "__main__":
    unittest.main()

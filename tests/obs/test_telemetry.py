"""``utils/telemetry.py`` coverage (ISSUE 1 satellite): sink registration /
removal, once-per-key semantics, broken-sink isolation, and the ``log_once``
helper the recompile watchdog warns through."""

import logging
import unittest

from torcheval_tpu.utils import telemetry
from torcheval_tpu.utils.telemetry import (
    log_api_usage_once,
    log_once,
    reset_once_keys,
    set_api_usage_sink,
)

PREFIX = "tests.obs.telemetry/"


class TestTelemetry(unittest.TestCase):
    def setUp(self):
        reset_once_keys(PREFIX)
        set_api_usage_sink(None)

    def tearDown(self):
        reset_once_keys(PREFIX)
        set_api_usage_sink(None)

    def test_once_per_key(self):
        seen = []
        set_api_usage_sink(seen.append)
        key = PREFIX + "once"
        log_api_usage_once(key)
        log_api_usage_once(key)
        log_api_usage_once(key)
        self.assertEqual(seen, [key])

    def test_distinct_keys_each_fire(self):
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(PREFIX + "a")
        log_api_usage_once(PREFIX + "b")
        self.assertEqual(seen, [PREFIX + "a", PREFIX + "b"])

    def test_sink_removal(self):
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(PREFIX + "before")
        set_api_usage_sink(None)
        log_api_usage_once(PREFIX + "after")
        self.assertEqual(seen, [PREFIX + "before"])

    def test_sink_replacement(self):
        first, second = [], []
        set_api_usage_sink(first.append)
        log_api_usage_once(PREFIX + "one")
        set_api_usage_sink(second.append)
        log_api_usage_once(PREFIX + "two")
        self.assertEqual(first, [PREFIX + "one"])
        self.assertEqual(second, [PREFIX + "two"])

    def test_broken_sink_never_raises_and_key_stays_consumed(self):
        def broken(key):
            raise RuntimeError("sink down")

        set_api_usage_sink(broken)
        key = PREFIX + "broken"
        log_api_usage_once(key)  # must not raise
        # the key was consumed by the first (failed) delivery: a healthy
        # sink installed afterwards does NOT get a replay
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(key)
        self.assertEqual(seen, [])

    def test_debug_record_emitted_once(self):
        logger = logging.getLogger("torcheval_tpu.api_usage")
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        handler.setLevel(logging.DEBUG)
        logger.addHandler(handler)
        old_level = logger.level
        logger.setLevel(logging.DEBUG)
        try:
            key = PREFIX + "debugrec"
            log_api_usage_once(key)
            log_api_usage_once(key)
        finally:
            logger.removeHandler(handler)
            logger.setLevel(old_level)
        usage = [r for r in records if key in r.getMessage()]
        self.assertEqual(len(usage), 1)
        self.assertEqual(usage[0].levelno, logging.DEBUG)

    def test_log_once_fires_once_at_level(self):
        logger = logging.getLogger("torcheval_tpu.api_usage")
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logger.addHandler(handler)
        try:
            for _ in range(3):
                log_once(PREFIX + "warnkey", "storm on %s", "entry")
        finally:
            logger.removeHandler(handler)
        hits = [r for r in records if "storm on entry" in r.getMessage()]
        self.assertEqual(len(hits), 1)
        self.assertEqual(hits[0].levelno, logging.WARNING)

    def test_reset_once_keys_prefix_scoped(self):
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(PREFIX + "x")
        log_api_usage_once("tests.obs.other/x")
        reset_once_keys(PREFIX)
        log_api_usage_once(PREFIX + "x")  # re-armed
        log_api_usage_once("tests.obs.other/x")  # still consumed
        self.assertEqual(
            seen, [PREFIX + "x", "tests.obs.other/x", PREFIX + "x"]
        )
        # clean up the unprefixed key for test isolation
        reset_once_keys("tests.obs.other/")

    def test_threaded_once_per_key(self):
        import threading

        seen = []
        set_api_usage_sink(seen.append)
        key = PREFIX + "race"
        threads = [
            threading.Thread(target=log_api_usage_once, args=(key,))
            for _ in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.assertEqual(seen, [key])

    def test_first_time_helper(self):
        key = PREFIX + "first"
        self.assertTrue(telemetry._first_time(key))
        self.assertFalse(telemetry._first_time(key))


if __name__ == "__main__":
    unittest.main()

"""``utils/telemetry.py`` coverage (ISSUE 1 satellite): sink registration /
removal, once-per-key semantics, broken-sink isolation, and the ``log_once``
helper the recompile watchdog warns through. Plus Prometheus export
hardening (ISSUE 7 satellite): label-escaping edge cases and the histogram
text exposition."""

import logging
import unittest

from torcheval_tpu.utils import telemetry
from torcheval_tpu.utils.telemetry import (
    log_api_usage_once,
    log_once,
    reset_once_keys,
    set_api_usage_sink,
)

PREFIX = "tests.obs.telemetry/"


class TestTelemetry(unittest.TestCase):
    def setUp(self):
        reset_once_keys(PREFIX)
        set_api_usage_sink(None)

    def tearDown(self):
        reset_once_keys(PREFIX)
        set_api_usage_sink(None)

    def test_once_per_key(self):
        seen = []
        set_api_usage_sink(seen.append)
        key = PREFIX + "once"
        log_api_usage_once(key)
        log_api_usage_once(key)
        log_api_usage_once(key)
        self.assertEqual(seen, [key])

    def test_distinct_keys_each_fire(self):
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(PREFIX + "a")
        log_api_usage_once(PREFIX + "b")
        self.assertEqual(seen, [PREFIX + "a", PREFIX + "b"])

    def test_sink_removal(self):
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(PREFIX + "before")
        set_api_usage_sink(None)
        log_api_usage_once(PREFIX + "after")
        self.assertEqual(seen, [PREFIX + "before"])

    def test_sink_replacement(self):
        first, second = [], []
        set_api_usage_sink(first.append)
        log_api_usage_once(PREFIX + "one")
        set_api_usage_sink(second.append)
        log_api_usage_once(PREFIX + "two")
        self.assertEqual(first, [PREFIX + "one"])
        self.assertEqual(second, [PREFIX + "two"])

    def test_broken_sink_never_raises_and_key_stays_consumed(self):
        def broken(key):
            raise RuntimeError("sink down")

        set_api_usage_sink(broken)
        key = PREFIX + "broken"
        log_api_usage_once(key)  # must not raise
        # the key was consumed by the first (failed) delivery: a healthy
        # sink installed afterwards does NOT get a replay
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(key)
        self.assertEqual(seen, [])

    def test_debug_record_emitted_once(self):
        logger = logging.getLogger("torcheval_tpu.api_usage")
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        handler.setLevel(logging.DEBUG)
        logger.addHandler(handler)
        old_level = logger.level
        logger.setLevel(logging.DEBUG)
        try:
            key = PREFIX + "debugrec"
            log_api_usage_once(key)
            log_api_usage_once(key)
        finally:
            logger.removeHandler(handler)
            logger.setLevel(old_level)
        usage = [r for r in records if key in r.getMessage()]
        self.assertEqual(len(usage), 1)
        self.assertEqual(usage[0].levelno, logging.DEBUG)

    def test_log_once_fires_once_at_level(self):
        logger = logging.getLogger("torcheval_tpu.api_usage")
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logger.addHandler(handler)
        try:
            for _ in range(3):
                log_once(PREFIX + "warnkey", "storm on %s", "entry")
        finally:
            logger.removeHandler(handler)
        hits = [r for r in records if "storm on entry" in r.getMessage()]
        self.assertEqual(len(hits), 1)
        self.assertEqual(hits[0].levelno, logging.WARNING)

    def test_reset_once_keys_prefix_scoped(self):
        seen = []
        set_api_usage_sink(seen.append)
        log_api_usage_once(PREFIX + "x")
        log_api_usage_once("tests.obs.other/x")
        reset_once_keys(PREFIX)
        log_api_usage_once(PREFIX + "x")  # re-armed
        log_api_usage_once("tests.obs.other/x")  # still consumed
        self.assertEqual(
            seen, [PREFIX + "x", "tests.obs.other/x", PREFIX + "x"]
        )
        # clean up the unprefixed key for test isolation
        reset_once_keys("tests.obs.other/")

    def test_threaded_once_per_key(self):
        import threading

        seen = []
        set_api_usage_sink(seen.append)
        key = PREFIX + "race"
        threads = [
            threading.Thread(target=log_api_usage_once, args=(key,))
            for _ in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.assertEqual(seen, [key])

    def test_first_time_helper(self):
        key = PREFIX + "first"
        self.assertTrue(telemetry._first_time(key))
        self.assertFalse(telemetry._first_time(key))


class TestPrometheusHardening(unittest.TestCase):
    """Export edge cases a fleet scraper would reject or misparse
    (ISSUE 7 satellite): text-format label escaping and the
    ``# TYPE histogram`` exposition contract."""

    def _reg(self):
        from torcheval_tpu.obs.registry import Registry

        return Registry()

    def _text(self, reg):
        from torcheval_tpu.obs.export import prometheus_text

        return prometheus_text(reg)

    def test_label_value_escaping_each_case(self):
        # per the text-format spec, label VALUES escape exactly three
        # characters: backslash, double-quote, newline
        for raw, escaped in (
            ('say "hi"', 'say \\"hi\\"'),
            ("back\\slash", "back\\\\slash"),
            ("line\nbreak", "line\\nbreak"),
        ):
            reg = self._reg()
            reg.counter("c", 1, k=raw)
            self.assertIn(f'k="{escaped}"', self._text(reg))

    def test_label_value_escaping_combined_and_ordered(self):
        # backslash escapes FIRST: escaping the quote before the backslash
        # would double-escape ("\\\"" becoming "\\\\\"")
        reg = self._reg()
        reg.counter("c", 1, k='\\"\n')
        self.assertIn('k="\\\\\\"\\n"', self._text(reg))

    def test_label_name_sanitised_to_charset(self):
        reg = self._reg()
        reg.counter("c", 1, **{"bad-name.x": "v"})
        text = self._text(reg)
        self.assertIn('bad_name_x="v"', text)

    def test_metric_name_sanitised_and_never_digit_led(self):
        reg = self._reg()
        reg.counter("0weird/name", 1)
        text = self._text(reg)
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            name = line.split("{")[0].split(" ")[0]
            self.assertRegex(name, r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

    def test_histogram_exposition_shape(self):
        from torcheval_tpu.obs.registry import bucket_upper_edge, bucket_index

        reg = self._reg()
        for v in (0.001, 0.001, 0.1):
            reg.histo("lat_seconds", v, lane="typed")
        text = self._text(reg)
        self.assertIn("# TYPE lat_seconds histogram", text)
        # cumulative bucket lines over the POPULATED edges only, plus +Inf
        lo = bucket_upper_edge(bucket_index(0.001))
        hi = bucket_upper_edge(bucket_index(0.1))
        self.assertIn(f'lat_seconds_bucket{{lane="typed",le="{lo:g}"}} 2', text)
        self.assertIn(f'lat_seconds_bucket{{lane="typed",le="{hi:g}"}} 3', text)
        self.assertIn('lat_seconds_bucket{lane="typed",le="+Inf"} 3', text)
        self.assertIn('lat_seconds_count{lane="typed"} 3', text)
        self.assertIn('lat_seconds_sum{lane="typed"} 0.102', text)

    def test_histogram_bucket_lines_cumulative_and_monotone(self):
        import re as _re

        reg = self._reg()
        for i in range(40):
            reg.histo("h", 0.0001 * (1 + i % 7))
        text = self._text(reg)
        counts = [
            float(m.group(2))
            for m in _re.finditer(r'h_bucket\{le="([^"]+)"\} (\S+)', text)
        ]
        self.assertGreater(len(counts), 1)
        self.assertEqual(counts, sorted(counts))
        self.assertEqual(counts[-1], 40)

    def test_histogram_family_lines_contiguous_under_one_header(self):
        # _bucket/_sum/_count must form ONE group under ONE # TYPE header —
        # scrapers treat a family split across headers as a parse error
        reg = self._reg()
        reg.histo("h", 0.5, lane="a")
        reg.histo("h", 0.5, lane="b")
        reg.counter("other", 1)
        text = self._text(reg)
        lines = text.splitlines()
        h_header = [i for i, l in enumerate(lines) if l == "# TYPE h histogram"]
        self.assertEqual(len(h_header), 1)
        i = h_header[0] + 1
        family = set()
        while i < len(lines) and not lines[i].startswith("#"):
            family.add(lines[i].split("{")[0].split(" ")[0])
            i += 1
        self.assertEqual(family, {"h_bucket", "h_sum", "h_count"})
        # nothing h-flavored appears outside the family block
        for j, line in enumerate(lines):
            if line.startswith("h_"):
                self.assertTrue(h_header[0] < j < i)

    def test_span_histogram_family_exposed(self):
        reg = self._reg()
        with reg.span("outer"):
            pass
        text = self._text(reg)
        self.assertIn("# TYPE torcheval_tpu_span_seconds histogram", text)
        self.assertIn(
            'torcheval_tpu_span_seconds_bucket{path="outer",le="+Inf"} 1',
            text,
        )
        self.assertIn('torcheval_tpu_span_seconds_count{path="outer"} 1', text)


if __name__ == "__main__":
    unittest.main()

"""Event-timeline coverage (ISSUE 7 tentpole leg 1): the bounded ring, the
registry span sink, the dispatch-site hooks, and the Chrome/Perfetto
``trace_event`` export.

The flight-recorder contract under test:

* disabled path records NOTHING (one global read per hook — the host-diet
  guard in test_host_overhead.py pins the per-update cost; here we pin the
  semantics);
* every default-registry span close mirrors into the ring as a complete
  event with a real start time and duration;
* the ring is bounded: overflow evicts oldest and counts ``dropped()``;
* ``chrome_trace()`` emits loadable ``trace_event`` JSON — phase ``X`` for
  durations, ``i`` for instants, microsecond timestamps, args carrying the
  labels.
"""

import json
import time
import unittest

from torcheval_tpu import obs
from torcheval_tpu.obs import trace


class TraceTestCase(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()
        self._cap = trace.capacity()

    def tearDown(self):
        obs.disable()
        obs.reset()
        trace.set_capacity(self._cap)


class TestRing(TraceTestCase):
    def test_disabled_records_nothing(self):
        trace.instant("x", kind="test")
        trace.complete("y", time.perf_counter(), 0.001, kind="test")
        self.assertEqual(trace.events(), [])
        self.assertEqual(trace.event_count(), 0)

    def test_instant_and_complete_recorded_when_enabled(self):
        obs.enable()
        trace.instant("i.name", kind="window", chunks=3)
        t0 = time.perf_counter()
        trace.complete("c.name", t0, 0.25, kind="sync", lane="typed")
        events = trace.events()
        self.assertEqual([e["name"] for e in events], ["i.name", "c.name"])
        inst, comp = events
        self.assertEqual(inst["dur"], 0.0)
        self.assertEqual(inst["kind"], "window")
        self.assertEqual(inst["labels"], {"chunks": 3})
        self.assertEqual(comp["dur"], 0.25)
        self.assertEqual(comp["labels"], {"lane": "typed"})
        # ts is seconds since module epoch: positive, ordered
        self.assertGreaterEqual(comp["ts"], 0.0)

    def test_registry_spans_mirror_into_ring(self):
        obs.enable()
        with obs.span("outer", tag="t"):
            time.sleep(0.001)
        events = trace.events()
        self.assertEqual(len(events), 1)
        (ev,) = events
        self.assertEqual(ev["name"], "outer")
        self.assertEqual(ev["kind"], "span")
        self.assertEqual(ev["labels"], {"tag": "t"})
        self.assertGreater(ev["dur"], 0.0)

    def test_non_default_registry_spans_do_not_mirror(self):
        obs.enable()
        reg = obs.Registry()
        with reg.span("private"):
            pass
        self.assertEqual(trace.events(), [])

    def test_ring_bounded_evicts_oldest_and_counts_dropped(self):
        obs.enable()
        trace.set_capacity(4)
        for i in range(7):
            trace.instant(f"e{i}", kind="test")
        names = [e["name"] for e in trace.events()]
        self.assertEqual(names, ["e3", "e4", "e5", "e6"])
        self.assertEqual(trace.dropped(), 3)

    def test_clear_resets_ring_and_dropped(self):
        obs.enable()
        trace.set_capacity(2)
        for i in range(5):
            trace.instant(f"e{i}", kind="test")
        trace.clear()
        self.assertEqual(trace.events(), [])
        self.assertEqual(trace.dropped(), 0)

    def test_set_capacity_keeps_newest(self):
        obs.enable()
        for i in range(6):
            trace.instant(f"e{i}", kind="test")
        trace.set_capacity(3)
        self.assertEqual(
            [e["name"] for e in trace.events()], ["e3", "e4", "e5"]
        )
        # the shrink evicted 3 events — dropped() must own up to them
        self.assertEqual(trace.dropped(), 3)
        with self.assertRaises(ValueError):
            trace.set_capacity(0)


class TestChromeTrace(TraceTestCase):
    def test_chrome_trace_schema(self):
        obs.enable()
        trace.instant("moment", kind="window", chunks=2)
        with obs.span("work"):
            time.sleep(0.001)
        doc = json.loads(obs.chrome_trace())
        self.assertIn("traceEvents", doc)
        self.assertEqual(doc["displayTimeUnit"], "ms")
        by_name = {e["name"]: e for e in doc["traceEvents"]}
        inst = by_name["moment"]
        self.assertEqual(inst["ph"], "i")
        self.assertEqual(inst["s"], "t")
        self.assertEqual(inst["args"], {"chunks": 2})
        comp = by_name["work"]
        self.assertEqual(comp["ph"], "X")
        self.assertGreater(comp["dur"], 0.0)  # microseconds
        for e in doc["traceEvents"]:
            # the trace_event required fields, all JSON-native types
            self.assertIsInstance(e["name"], str)
            self.assertIsInstance(e["cat"], str)
            self.assertIsInstance(e["pid"], int)
            self.assertIsInstance(e["tid"], int)
            self.assertIsInstance(e["ts"], (int, float))

    def test_chrome_trace_merges_extra_rank_tagged_events(self):
        obs.enable()
        trace.instant("local", kind="test")
        extra = [
            {
                "name": "remote",
                "kind": "test",
                "ts": 1.0,
                "dur": 0.5,
                "labels": {"k": "v"},
                "tid": 7,
                "rank": 3,
            }
        ]
        doc = json.loads(obs.chrome_trace(extra))
        by_name = {e["name"]: e for e in doc["traceEvents"]}
        self.assertEqual(by_name["remote"]["pid"], 3)  # rank becomes pid
        self.assertEqual(by_name["remote"]["ph"], "X")
        self.assertEqual(by_name["local"]["pid"], 0)

    def test_dropped_count_exported(self):
        obs.enable()
        trace.set_capacity(1)
        trace.instant("a", kind="test")
        trace.instant("b", kind="test")
        doc = json.loads(obs.chrome_trace())
        self.assertEqual(doc["otherData"]["dropped_events"], 1)


class TestDispatchSiteHooks(TraceTestCase):
    """The flight recorder sees the real eval machinery: window lifecycle
    events from the collection fast path, window-step dispatch bars, jit
    trace/cache-hit instants."""

    def test_window_lifecycle_and_step_events(self):
        import numpy as np

        from torcheval_tpu.metrics import Mean, MetricCollection

        obs.enable()
        col = MetricCollection({"m": Mean()})
        rng = np.random.default_rng(0)
        for _ in range(3):
            col.update(rng.random(32, dtype=np.float32))
        col.compute()
        names = [e["name"] for e in trace.events()]
        self.assertIn("deferred.window.open", names)
        self.assertIn("deferred.window.append", names)
        self.assertIn("deferred.window.close", names)
        self.assertIn("deferred.window_step.dispatch", names)
        # the dispatch bar carries the window occupancy
        (step,) = [
            e
            for e in trace.events()
            if e["name"] == "deferred.window_step.dispatch"
        ]
        self.assertEqual(step["labels"]["batches"], 3)
        self.assertGreater(step["dur"], 0.0)

    def test_watched_jit_trace_vs_cache_hit(self):
        import jax.numpy as jnp

        obs.enable()
        f = obs.watched_jit(lambda x: x + 1, name="trace.test.entry")
        f(jnp.ones((3,)))
        f(jnp.ones((3,)))
        names = [e["name"] for e in trace.events()]
        self.assertIn("watched_jit.trace", names)
        self.assertIn("watched_jit.cache_hit", names)
        # the compile-bearing dispatch also records a jit.compile span bar
        self.assertIn("jit.compile/trace.test.entry", names)


if __name__ == "__main__":
    unittest.main()

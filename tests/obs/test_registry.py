"""Registry + export unit tests: counters/gauges/spans, nesting, thread
safety, enable/disable gating, JSON snapshot and Prometheus exposition."""

import json
import threading
import time
import unittest

from torcheval_tpu import obs
from torcheval_tpu.obs.export import prometheus_text, to_json
from torcheval_tpu.obs.registry import Registry


class TestRegistry(unittest.TestCase):
    def setUp(self):
        self.reg = Registry()

    def test_counter_accumulates(self):
        self.reg.counter("c")
        self.reg.counter("c", 2.5)
        self.assertEqual(self.reg.snapshot()["counters"]["c"], 3.5)

    def test_counter_rejects_negative(self):
        with self.assertRaises(ValueError):
            self.reg.counter("c", -1)

    def test_counter_labels_are_distinct_series(self):
        self.reg.counter("bytes", 10, lane="SUM")
        self.reg.counter("bytes", 5, lane="CAT")
        self.reg.counter("bytes", 1, lane="SUM")
        snap = self.reg.snapshot()["counters"]
        self.assertEqual(snap["bytes{lane=SUM}"], 11)
        self.assertEqual(snap["bytes{lane=CAT}"], 5)

    def test_gauge_last_write_wins(self):
        self.reg.gauge("world", 4)
        self.reg.gauge("world", 8)
        self.assertEqual(self.reg.snapshot()["gauges"]["world"], 8.0)

    def test_span_records_count_total_max(self):
        for _ in range(3):
            with self.reg.span("s"):
                time.sleep(0.002)
        s = self.reg.snapshot()["spans"]["s"]
        self.assertEqual(s["count"], 3)
        self.assertGreaterEqual(s["total_seconds"], 0.006 * 0.5)
        self.assertGreaterEqual(s["total_seconds"], s["max_seconds"])

    def test_nested_spans_record_joined_paths(self):
        with self.reg.span("outer"):
            with self.reg.span("inner"):
                pass
            with self.reg.span("inner"):
                pass
        spans = self.reg.snapshot()["spans"]
        self.assertEqual(spans["outer"]["count"], 1)
        self.assertEqual(spans["outer/inner"]["count"], 2)
        self.assertNotIn("inner", spans)
        # nesting state fully unwound: a fresh span is top-level again
        with self.reg.span("later"):
            pass
        self.assertIn("later", self.reg.snapshot()["spans"])

    def test_span_exception_safe(self):
        with self.assertRaises(RuntimeError):
            with self.reg.span("boom"):
                raise RuntimeError("x")
        self.assertEqual(self.reg.snapshot()["spans"]["boom"]["count"], 1)
        with self.reg.span("after"):
            pass
        self.assertIn("after", self.reg.snapshot()["spans"])

    def test_thread_safety_and_thread_local_nesting(self):
        def work(tid):
            for _ in range(200):
                self.reg.counter("n")
                with self.reg.span(f"t{tid}"):
                    with self.reg.span("leaf"):
                        pass

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = self.reg.snapshot()
        self.assertEqual(snap["counters"]["n"], 800)
        # each thread's nesting stayed thread-local: leaves always joined
        # under their own thread's parent, never a sibling's
        for i in range(4):
            self.assertEqual(snap["spans"][f"t{i}"]["count"], 200)
            self.assertEqual(snap["spans"][f"t{i}/leaf"]["count"], 200)

    def test_reset(self):
        self.reg.counter("c")
        self.reg.gauge("g", 1)
        self.reg.histo("h", 0.5)
        with self.reg.span("s"):
            pass
        self.reg.reset()
        snap = self.reg.snapshot()
        self.assertEqual(
            snap,
            {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}},
        )

    def test_histogram_counts_sum_and_percentiles(self):
        for v in (0.001, 0.001, 0.001, 0.1):
            self.reg.histo("lat", v)
        h = self.reg.snapshot()["histograms"]["lat"]
        self.assertEqual(h["count"], 4)
        self.assertAlmostEqual(h["sum"], 0.103, places=9)
        # p50 falls in the 0.001 bucket, p99 in the 0.1 bucket (log2 edges)
        self.assertLess(h["p50"], 0.01)
        self.assertGreater(h["p99"], 0.05)

    def test_histogram_labels_are_distinct_series(self):
        self.reg.histo("rt", 1.0, lane="typed")
        self.reg.histo("rt", 1.0, lane="object")
        self.reg.histo("rt", 1.0, lane="typed")
        snap = self.reg.snapshot()["histograms"]
        self.assertEqual(snap["rt{lane=typed}"]["count"], 2)
        self.assertEqual(snap["rt{lane=object}"]["count"], 1)

    def test_span_percentiles_in_snapshot(self):
        for _ in range(4):
            with self.reg.span("p"):
                time.sleep(0.001)
        s = self.reg.snapshot()["spans"]["p"]
        for q in ("p50", "p95", "p99"):
            self.assertGreater(s[q], 0.0)
            self.assertLessEqual(s["p50"], s["p99"])

    def test_histogram_bucket_edges_are_static_and_mergeable(self):
        from torcheval_tpu.obs.registry import (
            HISTOGRAM_BUCKETS,
            bucket_index,
            bucket_upper_edge,
        )

        # a value never lands above its bucket's inclusive upper edge, and
        # always above the previous edge — the invariant bucket-summed
        # cross-rank merges (and the Prometheus cumulative-le lines) rely on
        for v in (1e-9, 3e-7, 0.001, 0.25, 0.5, 1.0, 7.0, 1e6):
            i = bucket_index(v)
            self.assertLessEqual(v, bucket_upper_edge(i))
            if 0 < i < HISTOGRAM_BUCKETS - 1:
                self.assertGreater(v, bucket_upper_edge(i - 1))

    def test_histogram_non_finite_values_clamped_not_poisoning(self):
        import math

        from torcheval_tpu.obs.registry import (
            HISTOGRAM_BUCKETS,
            bucket_index,
        )

        # frexp reports exponent 0 for non-finite input — without the clamp
        # inf/NaN would land mid-range and poison _sum forever
        self.assertEqual(bucket_index(math.inf), HISTOGRAM_BUCKETS - 1)
        self.assertEqual(bucket_index(math.nan), 0)
        self.assertEqual(bucket_index(-math.inf), 0)
        self.reg.histo("h", 1.0)
        self.reg.histo("h", math.inf)
        self.reg.histo("h", math.nan)
        h = self.reg.snapshot()["histograms"]["h"]
        self.assertEqual(h["count"], 3)
        self.assertEqual(h["sum"], 1.0)  # non-finite excluded from _sum
        self.assertTrue(math.isfinite(h["p50"]))


class TestModuleLevelGating(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs.reset()

    def tearDown(self):
        obs.disable()
        obs.reset()

    def test_disabled_records_nothing(self):
        obs.counter("c")
        obs.gauge("g", 1)
        with obs.span("s"):
            pass
        snap = obs.snapshot()
        self.assertEqual(
            snap,
            {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}},
        )

    def test_enabled_records(self):
        obs.enable()
        obs.counter("c", 2)
        obs.gauge("g", 7)
        with obs.span("s"):
            pass
        snap = obs.snapshot()
        self.assertEqual(snap["counters"]["c"], 2)
        self.assertEqual(snap["gauges"]["g"], 7)
        self.assertEqual(snap["spans"]["s"]["count"], 1)

    def test_disable_keeps_recorded_values(self):
        obs.enable()
        obs.counter("c")
        obs.disable()
        obs.counter("c")  # ignored
        self.assertEqual(obs.snapshot()["counters"]["c"], 1)


class TestExport(unittest.TestCase):
    def setUp(self):
        self.reg = Registry()
        self.reg.counter("sync.rounds", 2)
        self.reg.counter("lane_bytes", 128, lane="SUM")
        self.reg.gauge("world_size", 4)
        with self.reg.span("outer"):
            with self.reg.span("inner"):
                pass

    def test_json_round_trips(self):
        doc = json.loads(to_json(self.reg))
        self.assertEqual(doc["counters"]["sync.rounds"], 2)
        self.assertEqual(doc["counters"]["lane_bytes{lane=SUM}"], 128)
        self.assertEqual(doc["gauges"]["world_size"], 4)
        self.assertEqual(doc["spans"]["outer/inner"]["count"], 1)

    def test_prometheus_text_shape(self):
        text = prometheus_text(self.reg)
        self.assertIn("# TYPE sync_rounds counter", text)
        self.assertIn("sync_rounds 2", text)
        self.assertIn('lane_bytes{lane="SUM"} 128', text)
        self.assertIn("# TYPE world_size gauge", text)
        self.assertIn("world_size 4", text)
        # spans flatten to summary-style series with the path as a label
        self.assertIn(
            'torcheval_tpu_span_count{path="outer/inner"} 1', text
        )
        self.assertIn("torcheval_tpu_span_seconds_total", text)
        self.assertTrue(text.endswith("\n"))
        # every sample line's metric name is Prometheus-legal
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            name = line.split("{")[0].split(" ")[0]
            self.assertRegex(name, r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

    def test_items_does_not_hold_lock_for_consumer(self):
        # _items must materialise under the lock and release it before the
        # consumer formats — an abandoned/slow consumer must not block
        # instrumented threads
        items = self.reg._items()
        self.assertIsInstance(items, list)
        # lock is free again: an instrumented call completes immediately
        self.reg.counter("after_items")
        self.assertEqual(
            self.reg.snapshot()["counters"]["after_items"], 1
        )

    def test_span_families_are_contiguous_with_multiple_paths(self):
        reg = Registry()
        with reg.span("a"):
            pass
        with reg.span("b"):
            pass
        text = prometheus_text(reg)
        current = None
        seen = set()
        for line in text.splitlines():
            if line.startswith("# TYPE"):
                current = line.split()[2]
                self.assertNotIn(current, seen, "family split into groups")
                seen.add(current)
            else:
                name = line.split("{")[0].split(" ")[0]
                # histogram families: _bucket/_sum/_count samples live
                # under the family's single # TYPE header
                self.assertTrue(
                    name == current
                    or (
                        current is not None
                        and name
                        in (
                            current + "_bucket",
                            current + "_sum",
                            current + "_count",
                        )
                    ),
                    f"sample {name} outside family {current}",
                )

    def test_label_value_escaping(self):
        reg = Registry()
        reg.counter("c", 1, k='a"b\\c\nd')
        text = prometheus_text(reg)
        self.assertIn('k="a\\"b\\\\c\\nd"', text)

    def test_empty_registry_exports_empty(self):
        reg = Registry()
        self.assertEqual(prometheus_text(reg), "")
        self.assertEqual(
            json.loads(to_json(reg)),
            {"counters": {}, "gauges": {}, "histograms": {}, "spans": {}},
        )


if __name__ == "__main__":
    unittest.main()

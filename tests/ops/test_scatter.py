"""Segment scatter (``ops/scatter.py``, ISSUE 17): the VMEM-tiled Pallas
kernel (interpret-mode parity off-TPU), the block-range sharded route on
the forced-8-CPU mesh, the GSPMD ``custom_partitioning`` wrapper, the
auto-pick envelope, the obs path/capacity accounting, and the fail-closed
validation errors."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torcheval_tpu import obs
from torcheval_tpu.ops.scatter import (
    _PALLAS_MAX_SEGMENTS,
    _resolve_method,
    pallas_segment_sum,
    segment_scatter,
    sharded_pallas_segment_sum,
)

RNG = np.random.default_rng(17)


def _mesh():
    return Mesh(np.asarray(jax.devices()), ("slices",))


def _ref_sum(vals, rows, num_segments):
    out = np.zeros((num_segments,) + vals.shape[1:], np.float64)
    for r, v in zip(rows, vals):
        if 0 <= r < num_segments:
            out[r] += v
    return out


class TestPallasSegmentSum(unittest.TestCase):
    """Interpret-mode kernel parity with ``jax.ops.segment_sum`` — the
    same numbers the Mosaic lowering must produce on TPU."""

    def _check(self, n, d, num_segments, msg=""):
        vals = RNG.integers(0, 5, (n, d)).astype(np.float32)
        rows = RNG.integers(-2, num_segments + 3, n)  # OOB both sides
        got = pallas_segment_sum(
            jnp.asarray(vals), jnp.asarray(rows), num_segments,
            interpret=True,
        )
        want = jax.ops.segment_sum(
            jnp.asarray(vals), jnp.asarray(rows), num_segments=num_segments
        )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=msg
        )
        np.testing.assert_array_equal(
            np.asarray(got), _ref_sum(vals, rows, num_segments), err_msg=msg
        )

    def test_parity_across_shapes(self):
        for n, d, s in (
            (211, 3, 7),     # everything ragged vs the tile plan
            (1024, 128, 64),  # exact lane tiles
            (37, 1, 513),     # segment extent crosses a seg_tile boundary
            (8, 130, 9),      # d past one lane tile
        ):
            self._check(n, d, s, msg=f"n={n} d={d} segs={s}")

    def test_empty_sample_stream(self):
        got = pallas_segment_sum(
            jnp.zeros((0, 4), jnp.float32),
            jnp.zeros((0,), jnp.int32),
            6,
            interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), np.zeros((6, 4)))

    def test_shape_validation(self):
        with self.assertRaisesRegex(ValueError, "vals \\(N, D\\)"):
            pallas_segment_sum(
                jnp.zeros((4, 2, 2)), jnp.zeros((4,), jnp.int32), 3
            )
        with self.assertRaisesRegex(ValueError, "vals \\(N, D\\)"):
            pallas_segment_sum(
                jnp.zeros((4, 2)), jnp.zeros((5,), jnp.int32), 3
            )


class TestSegmentScatterLocal(unittest.TestCase):
    def test_xla_reduces(self):
        vals = RNG.random((64, 2)).astype(np.float32)
        rows = RNG.integers(0, 5, 64)
        for reduce, op in (
            ("sum", jax.ops.segment_sum),
            ("max", jax.ops.segment_max),
            ("min", jax.ops.segment_min),
        ):
            got = segment_scatter(
                jnp.asarray(vals), jnp.asarray(rows), 5, reduce=reduce
            )
            want = op(jnp.asarray(vals), jnp.asarray(rows), num_segments=5)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_forced_pallas_matches_xla_with_nd_tail(self):
        # the sliced fold scatters (N, k, d)-stacked deltas: the kernel
        # path flattens the tail and must restore it bit-identically
        vals = RNG.integers(0, 9, (128, 3, 4)).astype(np.int32)
        rows = RNG.integers(0, 11, 128)
        got = segment_scatter(
            jnp.asarray(vals), jnp.asarray(rows), 11, method="pallas"
        )
        want = jax.ops.segment_sum(
            jnp.asarray(vals), jnp.asarray(rows), num_segments=11
        )
        self.assertEqual(got.dtype, want.dtype)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_validation_errors(self):
        v = jnp.zeros((4, 2), jnp.float32)
        r = jnp.zeros((4,), jnp.int32)
        with self.assertRaisesRegex(ValueError, "reduce must be"):
            segment_scatter(v, r, 3, reduce="mean")
        with self.assertRaisesRegex(ValueError, "method must be"):
            segment_scatter(v, r, 3, method="mosaic")
        with self.assertRaisesRegex(ValueError, "sum.*only"):
            segment_scatter(v, r, 3, reduce="max", method="pallas")
        with self.assertRaisesRegex(ValueError, "together"):
            segment_scatter(v, r, 3, mesh=_mesh())
        with self.assertRaisesRegex(ValueError, "together"):
            segment_scatter(v, r, 3, axis="slices")

    def test_auto_pick_envelope(self):
        v32 = jnp.zeros((8, 4), jnp.float32)
        # CPU never auto-picks the kernel; TPU does inside the envelope
        self.assertEqual(_resolve_method("auto", "sum", 64, v32, "cpu"), "xla")
        self.assertEqual(
            _resolve_method("auto", "sum", 64, v32, "tpu"), "pallas"
        )
        self.assertEqual(
            _resolve_method(
                "auto", "sum", _PALLAS_MAX_SEGMENTS + 1, v32, "tpu"
            ),
            "xla",
        )
        self.assertEqual(_resolve_method("auto", "max", 64, v32, "tpu"), "xla")
        self.assertEqual(
            _resolve_method(
                "auto", "sum", 64, jnp.zeros((8, 1024), jnp.float32), "tpu"
            ),
            "xla",
        )
        # explicit method always wins
        self.assertEqual(
            _resolve_method("pallas", "sum", 10**9, v32, "cpu"), "pallas"
        )


class TestSegmentScatterSharded(unittest.TestCase):
    """The block-range route on the forced 8-device CPU mesh: output born
    ``P(axis)``-sharded, bit-identical to the unsharded reduction, no
    state-sized gather in the lowering."""

    def _parity(self, reduce):
        mesh = _mesh()
        vals = RNG.integers(0, 7, (512, 4)).astype(np.int32)
        rows = RNG.integers(-3, 44, 512)  # OOB rows drop on both routes
        got = segment_scatter(
            jnp.asarray(vals),
            jnp.asarray(rows),
            40,
            reduce=reduce,
            mesh=mesh,
            axis="slices",
        )
        self.assertEqual(got.sharding.spec, P("slices"))
        want = segment_scatter(jnp.asarray(vals), jnp.asarray(rows), 40,
                               reduce=reduce)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # every addressable shard holds exactly 1/8 of the segment axis
        for s in got.addressable_shards:
            self.assertEqual(s.data.shape, (5, 4))

    def test_sharded_parity_all_reduces(self):
        for reduce in ("sum", "max", "min"):
            self._parity(reduce)

    def test_uneven_extent_fails_closed(self):
        with self.assertRaisesRegex(ValueError, "not a multiple"):
            segment_scatter(
                jnp.zeros((4, 2), jnp.float32),
                jnp.zeros((4,), jnp.int32),
                42,  # 42 % 8 != 0
                mesh=_mesh(),
                axis="slices",
            )

    def test_no_state_sized_all_gather_in_hlo(self):
        mesh = _mesh()
        num_segments, d = 4096, 8

        def fold(vals, rows):
            return segment_scatter(
                vals, rows, num_segments, mesh=mesh, axis="slices"
            )

        hlo = (
            jax.jit(fold)
            .lower(
                jax.ShapeDtypeStruct((256, d), jnp.float32),
                jax.ShapeDtypeStruct((256,), jnp.int32),
            )
            .compile()
            .as_text()
        )
        self.assertNotIn("all-gather", hlo)
        self.assertNotIn(f"f32[{num_segments},{d}]", hlo)  # no full-extent buf
        self.assertIn(f"f32[{num_segments // 8},{d}]", hlo)  # per-shard tile


class TestCustomPartitioning(unittest.TestCase):
    """``sharded_pallas_segment_sum``: sample-sharded operands reduce
    locally per shard + one psum instead of an operand all-gather."""

    def test_single_device_identity(self):
        vals = RNG.random((64, 4)).astype(np.float32)
        rows = RNG.integers(0, 6, 64)
        got = sharded_pallas_segment_sum(
            jnp.asarray(vals), jnp.asarray(rows), 6, True
        )
        want = pallas_segment_sum(
            jnp.asarray(vals), jnp.asarray(rows), 6, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_sample_sharded_operand_folds_with_psum(self):
        mesh = _mesh()
        n, d, segs = 1024, 4, 16
        vals = RNG.integers(0, 5, (n, d)).astype(np.float32)
        rows = RNG.integers(0, segs, n)
        vs = jax.device_put(
            jnp.asarray(vals), NamedSharding(mesh, P("slices", None))
        )
        rs = jax.device_put(
            jnp.asarray(rows), NamedSharding(mesh, P("slices"))
        )
        fn = jax.jit(
            lambda v, r: sharded_pallas_segment_sum(v, r, segs, True)
        )
        got = fn(vs, rs)
        np.testing.assert_array_equal(
            np.asarray(got), _ref_sum(vals, rows, segs)
        )


class TestScatterObs(unittest.TestCase):
    def test_path_counter_and_capacity_gauge(self):
        mesh = _mesh()
        vals = jnp.ones((256, 4), jnp.float32)
        rows = jnp.zeros((256,), jnp.int32)
        obs.enable()
        try:
            obs.reset()
            segment_scatter(vals, rows, 64)
            segment_scatter(vals, rows, 64, method="pallas", interpret=True)
            segment_scatter(vals, rows, 64, mesh=mesh, axis="slices")
            snap = obs.snapshot()
            c = snap["counters"]
            self.assertEqual(c["ops.scatter.calls{path=xla}"], 1)
            self.assertEqual(c["ops.scatter.calls{path=pallas}"], 1)
            self.assertEqual(c["ops.scatter.calls{path=sharded}"], 1)
            g = snap["gauges"]
            full = 64 * 4 * 4  # segments * lanes * f32
            self.assertEqual(
                g["ops.scatter.state_bytes_per_device{path=xla}"], full
            )
            # the capacity observable: per-device bytes shrink by the
            # shard count on the sharded path
            self.assertEqual(
                g["ops.scatter.state_bytes_per_device{path=sharded}"],
                full / 8,
            )
        finally:
            obs.disable()
            obs.reset()


if __name__ == "__main__":
    unittest.main()

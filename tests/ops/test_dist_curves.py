"""Distributed bucket-sort curve reduction on the forced 8-device CPU mesh
(round-4 verdict ask 4: per-shard sort + all_to_all replaces XLA's
gather-based sort partitioning for sharded curve caches; round-5 verdict
missing #1/#2: sub-axis engagement on multi-axis meshes + the one-vs-all
multiclass family)."""

import re
import unittest

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, roc_auc_score

from torcheval_tpu.metrics import (
    BinaryAUPRC,
    BinaryAUROC,
    MulticlassAUPRC,
    MulticlassAUROC,
)
from torcheval_tpu.ops.dist_curves import (
    _program,
    sharded_binary_auprc,
    sharded_binary_auroc,
    sharded_multiclass_auprc,
    sharded_multiclass_auroc,
)
from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh, shard_batch

RNG = np.random.default_rng(17)


def _tied_data(n):
    s = ((RNG.random(n) * 300).astype(np.int32) / 300.0).astype(np.float32)
    t = (RNG.random(n) < 0.4).astype(np.float32)
    return s, t


def _mc_tied_data(n, num_classes):
    # quantized scores: heavy cross-shard ties AND exactly-representable
    # trapezoid partial sums, so the dist path's per-shard integration must
    # agree with the fused single-sort kernel BIT-FOR-BIT (AUROC)
    s = ((RNG.random((n, num_classes)) * 300).astype(np.int32) / 300.0).astype(
        np.float32
    )
    t = RNG.integers(0, num_classes, size=n).astype(np.int32)
    return s, t


def _hlo_all_to_all_defs(hlo: str):
    return re.findall(r"%all-to-all[\w.]*? = ", hlo)


class TestDistCurveKernels(unittest.TestCase):
    def setUp(self):
        self.mesh = data_parallel_mesh()

    def _sharded_lists(self, batches):
        s_list = [shard_batch(self.mesh, jnp.asarray(s)) for s, _ in batches]
        t_list = [shard_batch(self.mesh, jnp.asarray(t)) for _, t in batches]
        return s_list, t_list

    def test_auroc_parity_multi_batch_with_ties(self):
        batches = [_tied_data(8 * (200 + 100 * i)) for i in range(3)]
        s_list, t_list = self._sharded_lists(batches)
        all_s = np.concatenate([s for s, _ in batches])
        all_t = np.concatenate([t for _, t in batches])
        v, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        self.assertAlmostEqual(float(v), roc_auc_score(all_t, all_s), places=6)

    def test_auprc_parity(self):
        batches = [_tied_data(8 * 250) for _ in range(2)]
        s_list, t_list = self._sharded_lists(batches)
        all_s = np.concatenate([s for s, _ in batches])
        all_t = np.concatenate([t for _, t in batches])
        v, ov = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        self.assertAlmostEqual(
            float(v), average_precision_score(all_t, all_s), places=5
        )

    def test_neg_inf_scores(self):
        s = np.array([0.9, -np.inf, 0.4, -np.inf, 0.1, 0.7, 0.2, 0.3] * 32,
                     np.float32)
        t = (RNG.random(s.size) < 0.5).astype(np.float32)
        s_list, t_list = self._sharded_lists([(s, t)])
        v, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        fin = np.where(np.isinf(s), -1e30, s)  # rank-equivalent for sklearn
        self.assertAlmostEqual(float(v), roc_auc_score(t, fin), places=6)

    def test_signed_zeros_share_a_tie_group(self):
        # -0.0 == +0.0 in float compares: the fused path merges them into
        # one tie group, so the key transform must too (review finding:
        # distinct bitcast keys silently changed the result by ~2e-3)
        n = 3200
        s, t = _tied_data(n)
        s[:100], t[:100] = 0.0, 1.0
        s[100:200], t[100:200] = -0.0, 0.0
        perm = np.random.default_rng(0).permutation(n)  # spread across shards
        s, t = s[perm], t[perm]
        s_list, t_list = self._sharded_lists([(s, t)])
        v, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        self.assertAlmostEqual(float(v), roc_auc_score(t, s), places=6)

    def test_degenerate_targets_guard(self):
        s, _ = _tied_data(800)
        s_list, t_list = self._sharded_lists([(s, np.ones(800, np.float32))])
        v, _ = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(float(v), 0.5)
        v2, _ = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        self.assertAlmostEqual(float(v2), 1.0, places=6)

    def test_capacity_overflow_detected_exactly(self):
        # every row the same score: one bucket receives everything — the
        # kernel must COUNT the clipped rows, never silently drop them
        n = 8 * 128
        s = np.full(n, 0.5, np.float32)
        t = (RNG.random(n) < 0.5).astype(np.float32)
        s_list, t_list = self._sharded_lists([(s, t)])
        _, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertGreater(int(ov), 0)

    def test_nan_scores_trip_error_channel(self):
        # NaN-scored REAL rows would take the padding's sort position in the
        # bucket sort (diverging from the fused kernels' NaN-first order) —
        # they must be counted into the error channel, never silently folded
        n = 8 * 200
        s, t = _tied_data(n)
        s[3] = np.nan
        s[n // 2] = np.nan
        s_list, t_list = self._sharded_lists([(s, t)])
        _, err = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertGreaterEqual(int(err), 2)
        _, err = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        self.assertGreaterEqual(int(err), 2)

    def test_nan_free_data_keeps_zero_error_channel(self):
        s_list, t_list = self._sharded_lists([_tied_data(8 * 200)])
        _, err = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(err), 0)

    def test_no_sample_all_gather_in_hlo(self):
        # the acceptance criterion (round-4 verdict ask 4): the compiled
        # program for a sharded curve compute contains NO all-gather at all —
        # the only sample-sized collective is the all-to-all bucket exchange;
        # per-shard totals ride K-element all-reduces
        batches = [_tied_data(8 * 256)]
        s_list, t_list = self._sharded_lists(batches)
        fn = _program(self.mesh, "data", "auroc")
        hlo = fn.lower(s_list, t_list).compile().as_text()
        self.assertNotIn("all-gather", hlo)
        self.assertIn("all-to-all", hlo)

    # ------------------------------------------------- multiclass family
    def _sharded_mc(self, s, t):
        return (
            [shard_batch(self.mesh, jnp.asarray(s))],
            [shard_batch(self.mesh, jnp.asarray(t))],
        )

    def test_multiclass_auroc_parity_bitexact_vs_fused(self):
        from torcheval_tpu.ops.curves import multiclass_auroc_kernel

        C = 6
        s, t = _mc_tied_data(8 * 250, C)
        s_list, t_list = self._sharded_mc(s, t)
        vals, err = sharded_multiclass_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(err), 0)
        ref = [
            roc_auc_score((t == c).astype(int), s[:, c]) for c in range(C)
        ]
        np.testing.assert_allclose(np.asarray(vals), ref, atol=1e-6)
        # the acceptance bar: quantized scores make every trapezoid partial
        # sum exactly representable in f32, so the per-shard decomposition
        # must agree with the fused one-vs-all kernel bit-for-bit
        fused = np.asarray(
            multiclass_auroc_kernel(jnp.asarray(s), jnp.asarray(t))
        )
        self.assertTrue(np.array_equal(np.asarray(vals), fused))

    def test_multiclass_auprc_parity_vs_fused(self):
        from torcheval_tpu.ops.curves import multiclass_auprc_kernel

        C = 4
        s, t = _mc_tied_data(8 * 200, C)
        s_list, t_list = self._sharded_mc(s, t)
        vals, err = sharded_multiclass_auprc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(err), 0)
        ref = [
            average_precision_score((t == c).astype(int), s[:, c])
            for c in range(C)
        ]
        np.testing.assert_allclose(np.asarray(vals), ref, atol=1e-5)
        # AP's precision terms (tp/(tp+fp)) are not exactly representable,
        # so per-shard summation order costs a few ulps vs the fused single
        # sum — near-equality, unlike AUROC's exact trapezoid sums
        fused = np.asarray(
            multiclass_auprc_kernel(jnp.asarray(s), jnp.asarray(t))
        )
        np.testing.assert_allclose(np.asarray(vals), fused, atol=1e-6)

    def test_multiclass_shared_exchange_no_all_gather_in_hlo(self):
        # one-vs-all over C classes still exchanges through ONE batched
        # all_to_all per column (key/tp/fp — vmap's collective batching
        # rule), and the compiled program has no all-gather at all
        C = 5
        s, t = _mc_tied_data(8 * 200, C)
        s_list, t_list = self._sharded_mc(s, t)
        fn = _program(self.mesh, "data", "mc_auroc")
        hlo = fn.lower(s_list, t_list).compile().as_text()
        self.assertNotIn("all-gather", hlo)
        defs = _hlo_all_to_all_defs(hlo)
        self.assertGreaterEqual(len(defs), 1)
        self.assertLessEqual(len(defs), 3)  # shared exchange: O(1) in C
        # the batched operands carry the class axis through the collective
        self.assertIn(f"[{C},", hlo[hlo.index("all-to-all"):][:4000])

    def test_multiclass_nan_scores_trip_error_channel(self):
        C = 3
        s, t = _mc_tied_data(8 * 150, C)
        s[5, 1] = np.nan
        s[77, 0] = np.nan
        s_list, t_list = self._sharded_mc(s, t)
        _, err = sharded_multiclass_auroc(s_list, t_list, mesh=self.mesh)
        self.assertGreaterEqual(int(err), 2)
        _, err = sharded_multiclass_auprc(s_list, t_list, mesh=self.mesh)
        self.assertGreaterEqual(int(err), 2)

    def test_multiclass_capacity_overflow_detected(self):
        # one massively-tied class is enough to poison the value: the error
        # channel must report it even when other classes are clean
        C = 3
        s, t = _mc_tied_data(8 * 128, C)
        s[:, 1] = 0.5
        s_list, t_list = self._sharded_mc(s, t)
        _, err = sharded_multiclass_auroc(s_list, t_list, mesh=self.mesh)
        self.assertGreater(int(err), 0)


class TestQuantizedExchange(unittest.TestCase):
    """ISSUE 12: the int8/bf16 quantized exchange — bit-identical values
    (unit counts are exact in int8 and the merge widens before any
    cumulative sum; bf16 splitters only move load, never results), the
    same 3-collective structure, and the halved obs-accounted payload."""

    def setUp(self):
        self.mesh = data_parallel_mesh()

    def _sharded(self, s, t):
        return (
            [shard_batch(self.mesh, jnp.asarray(s))],
            [shard_batch(self.mesh, jnp.asarray(t))],
        )

    def test_binary_values_bit_identical_to_unquantized(self):
        for maker in (sharded_binary_auroc, sharded_binary_auprc):
            s, t = _tied_data(8 * 300)
            s_list, t_list = self._sharded(s, t)
            v_raw, e_raw = maker(s_list, t_list, mesh=self.mesh)
            v_q, e_q = maker(
                s_list, t_list, mesh=self.mesh, quantize=True
            )
            self.assertEqual(int(e_raw), 0)
            self.assertEqual(int(e_q), 0)
            self.assertEqual(float(v_raw), float(v_q))

    def test_multiclass_values_bit_identical_to_unquantized(self):
        C = 5
        s, t = _mc_tied_data(8 * 200, C)
        s_list, t_list = self._sharded(s, t)
        for maker in (sharded_multiclass_auroc, sharded_multiclass_auprc):
            v_raw, _ = maker(s_list, t_list, mesh=self.mesh)
            v_q, _ = maker(s_list, t_list, mesh=self.mesh, quantize=True)
            np.testing.assert_array_equal(np.asarray(v_raw), np.asarray(v_q))

    def test_quantized_multiclass_hlo_still_three_collectives(self):
        # the acceptance HLO assertion: the quantized shared exchange
        # keeps the vmapped-batched structure — <= 3 all_to_all defs
        # independent of C, no all-gather anywhere — with int8 count
        # operands and a bf16 splitter-histogram all-reduce visible
        C = 5
        s, t = _mc_tied_data(8 * 200, C)
        s_list, t_list = self._sharded(s, t)
        fn = _program(self.mesh, "data", "mc_auroc", True)
        hlo = fn.lower(s_list, t_list).compile().as_text()
        self.assertNotIn("all-gather", hlo)
        defs = _hlo_all_to_all_defs(hlo)
        self.assertGreaterEqual(len(defs), 1)
        self.assertLessEqual(len(defs), 3)
        self.assertIn(f"[{C},", hlo[hlo.index("all-to-all"):][:4000])
        self.assertIn("s8[", hlo)  # int8 count columns in the exchange
        self.assertIn("bf16[", hlo)  # bf16 splitter histogram all-reduce

    def test_quantized_error_channels_still_trip(self):
        n = 8 * 200
        s, t = _tied_data(n)
        s[3] = np.nan
        s_list, t_list = self._sharded(s, t)
        _, err = sharded_binary_auroc(
            s_list, t_list, mesh=self.mesh, quantize=True
        )
        self.assertGreaterEqual(int(err), 1)
        s2 = np.full(n, 0.5, np.float32)
        s_list, t_list = self._sharded(s2, t)
        _, ov = sharded_binary_auroc(
            s_list, t_list, mesh=self.mesh, quantize=True
        )
        self.assertGreater(int(ov), 0)

    def test_exchange_bytes_accounted_per_codec(self):
        from torcheval_tpu import obs
        from torcheval_tpu.ops.dist_curves import _bucket_capacity

        s, t = _tied_data(8 * 256)
        s_list, t_list = self._sharded(s, t)
        obs.enable()
        try:
            obs.reset()
            sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
            sharded_binary_auroc(
                s_list, t_list, mesh=self.mesh, quantize=True
            )
            counters = obs.snapshot()["counters"]
            cap = _bucket_capacity(256, 8)
            self.assertEqual(
                counters[
                    "dist_curves.exchange_send_bytes"
                    "{codec=raw,kernel=auroc}"
                ],
                12 * 8 * cap,
            )
            self.assertEqual(
                counters[
                    "dist_curves.exchange_send_bytes"
                    "{codec=q8,kernel=auroc}"
                ],
                6 * 8 * cap,
            )
            self.assertEqual(
                counters["dist_curves.exchanges{codec=q8,kernel=auroc}"], 1
            )
        finally:
            obs.disable()
            obs.reset()

    def test_env_knob_engages_quantized_program(self):
        import os
        from unittest import mock

        from torcheval_tpu.ops import dist_curves as dc

        s, t = _tied_data(8 * 200)
        s_list, t_list = self._sharded(s, t)
        with mock.patch.object(
            dc, "_program", wraps=dc._program
        ) as spy, mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_SYNC_QUANTIZE": "1"}
        ):
            v_env, _ = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        # env "1" resolves to the bf16 splitter mode (ISSUE 13 widened the
        # knob: "int8" engages the chunked qpsum instead)
        self.assertEqual(spy.call_args[0][3], "bf16")
        v_raw, _ = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(float(v_env), float(v_raw))


class TestDistCurveMetricIntegration(unittest.TestCase):
    """BinaryAUROC/AUPRC automatically take the distributed path when their
    cache is uniformly data-sharded (the ShardedEvaluator regime)."""

    def setUp(self):
        self.mesh = data_parallel_mesh()

    def test_evaluator_auroc_uses_dist_path(self):
        import torcheval_tpu.metrics.classification.auroc as auroc_mod

        ev = ShardedEvaluator(
            {"auroc": BinaryAUROC(), "auprc": BinaryAUPRC()}, mesh=self.mesh
        )
        parts = [_tied_data(8 * 200) for _ in range(3)]
        for s, t in parts:
            ev.update(jnp.asarray(s), jnp.asarray(t))
        m = ev.metrics["auroc"]
        self.assertIsNotNone(m._sharded_raw_mesh())  # dist path is active
        calls = []
        orig = auroc_mod._auroc_from_parts

        def _spy(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        auroc_mod._auroc_from_parts = _spy
        try:
            out = ev.compute()
        finally:
            auroc_mod._auroc_from_parts = orig
        self.assertEqual(calls, [])  # the gather-based program never ran
        all_s = np.concatenate([s for s, _ in parts])
        all_t = np.concatenate([t for _, t in parts])
        self.assertAlmostEqual(
            float(out["auroc"]), roc_auc_score(all_t, all_s), places=6
        )
        self.assertAlmostEqual(
            float(out["auprc"]),
            average_precision_score(all_t, all_s),
            places=5,
        )

    def test_overflow_falls_back_to_gather_path(self):
        # massively tied scores overload one bucket; the metric must detect
        # the overflow and fall back to the fused sort program — correct
        # result, never dropped rows
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        n = 8 * 128
        s = np.full(n, 0.25, np.float32)
        s[: n // 2] = 0.75
        t = (RNG.random(n) < 0.5).astype(np.float32)
        ev.update(jnp.asarray(s), jnp.asarray(t))
        self.assertAlmostEqual(
            float(ev.compute()), roc_auc_score(t, s), places=6
        )

    def test_nan_scores_fall_back_to_fused_path_and_match_unsharded(self):
        # a NaN-scored sample in a sharded cache must compute the SAME value
        # the unsharded cache computes (the fused kernels' NaN semantics),
        # via the error-channel fallback — not a silently different curve
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        n = 8 * 150
        s, t = _tied_data(n)
        s[7] = np.nan
        ev.update(jnp.asarray(s), jnp.asarray(t))
        self.assertIsNotNone(ev.metrics["metric"]._sharded_raw_mesh())
        sharded_value = float(ev.compute())
        plain = BinaryAUROC()
        plain.update(jnp.asarray(s), jnp.asarray(t))
        self.assertAlmostEqual(sharded_value, float(plain.compute()), places=6)

    def test_multi_axis_mesh_uses_dist_path(self):
        # round-5 verdict missing #1 INVERTED: a single named axis that is a
        # SUBSET of a (data, model) mesh now engages the bucket sort — the
        # kernel sizes itself from mesh.shape[axis], its collectives bind to
        # that axis only, and the compiled program still contains no sample
        # all-gather (the acceptance criterion on the realistic topology)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.asarray(jax.devices()).reshape(4, 2)
        mesh2d = Mesh(devs, ("data", "model"))
        s, t = _tied_data(8 * 100)
        for axis in ("data", "model"):  # both sub-axes: sizes 4 and 2
            sh = NamedSharding(mesh2d, P(axis))
            m = BinaryAUROC()
            m.update(
                jax.device_put(jnp.asarray(s), sh),
                jax.device_put(jnp.asarray(t), sh),
            )
            dist = m._sharded_raw_mesh()
            self.assertIsNotNone(dist)
            self.assertEqual(str(dist[1]), axis)
            self.assertAlmostEqual(
                float(m.compute()), roc_auc_score(t, s), places=6
            )
        # the compiled (4,2)-mesh program: no all-gather anywhere; the only
        # sample-sized collective is the all-to-all bucket exchange
        sh = NamedSharding(mesh2d, P("data"))
        s_list = [jax.device_put(jnp.asarray(s), sh)]
        t_list = [jax.device_put(jnp.asarray(t), sh)]
        hlo = (
            _program(mesh2d, "data", "auroc")
            .lower(s_list, t_list)
            .compile()
            .as_text()
        )
        self.assertNotIn("all-gather", hlo)
        self.assertIn("all-to-all", hlo)

    def test_multi_axis_mesh_multiclass_dist_path(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.asarray(jax.devices()).reshape(4, 2)
        mesh2d = Mesh(devs, ("data", "model"))
        C = 4
        s, t = _mc_tied_data(4 * 120, C)
        sh = NamedSharding(mesh2d, P("data"))
        m = MulticlassAUROC(num_classes=C, average=None)
        m.update(
            jax.device_put(jnp.asarray(s), sh),
            jax.device_put(jnp.asarray(t), sh),
        )
        self.assertIsNotNone(m._sharded_raw_mesh())
        ref = [roc_auc_score((t == c).astype(int), s[:, c]) for c in range(C)]
        np.testing.assert_allclose(np.asarray(m.compute()), ref, atol=1e-6)

    def test_tuple_spec_or_sharded_classes_fall_back_to_fused_path(self):
        # still outside the kernel's contract: rows sharded over SEVERAL
        # axes at once (tuple spec entry) or a sharded trailing class dim —
        # compute falls back to the fused program instead of raising
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.asarray(jax.devices()).reshape(4, 2)
        mesh2d = Mesh(devs, ("data", "model"))
        s, t = _tied_data(8 * 100)
        m = BinaryAUROC()
        spec = P(("data", "model"))
        m.update(
            jax.device_put(jnp.asarray(s), NamedSharding(mesh2d, spec)),
            jax.device_put(jnp.asarray(t), NamedSharding(mesh2d, spec)),
        )
        self.assertIsNone(m._sharded_raw_mesh())
        self.assertAlmostEqual(
            float(m.compute()), roc_auc_score(t, s), places=6
        )
        C = 4
        sc, tc = _mc_tied_data(4 * 60, C)
        mc = MulticlassAUROC(num_classes=C, average=None)
        mc.update(
            jax.device_put(
                jnp.asarray(sc), NamedSharding(mesh2d, P("data", "model"))
            ),
            jax.device_put(jnp.asarray(tc), NamedSharding(mesh2d, P("data"))),
        )
        self.assertIsNone(mc._sharded_raw_mesh())
        ref = [
            roc_auc_score((tc == c).astype(int), sc[:, c]) for c in range(C)
        ]
        np.testing.assert_allclose(np.asarray(mc.compute()), ref, atol=1e-6)

    def test_unsharded_cache_keeps_plain_path(self):
        m = BinaryAUROC()
        s, t = _tied_data(1000)
        m.update(jnp.asarray(s), jnp.asarray(t))
        self.assertIsNone(m._sharded_raw_mesh())
        self.assertAlmostEqual(float(m.compute()), roc_auc_score(t, s), places=6)

    def test_evaluator_multiclass_uses_dist_path(self):
        # sharded MulticlassAUROC/AUPRC caches compute WITHOUT the fused
        # one-vs-all program (spy) and match the sklearn oracle
        import torcheval_tpu.metrics.classification.auroc as auroc_mod

        C = 5
        ev = ShardedEvaluator(
            {
                "auroc": MulticlassAUROC(num_classes=C, average=None),
                "auprc": MulticlassAUPRC(num_classes=C, average=None),
            },
            mesh=self.mesh,
        )
        parts = [_mc_tied_data(8 * 150, C) for _ in range(2)]
        for s, t in parts:
            ev.update(jnp.asarray(s), jnp.asarray(t))
        self.assertIsNotNone(ev.metrics["auroc"]._sharded_raw_mesh())
        spied = []
        orig_roc = auroc_mod._mc_auroc_from_parts
        orig_ap = auroc_mod._mc_auprc_from_parts

        def _spy_roc(*a, **k):
            spied.append("roc")
            return orig_roc(*a, **k)

        def _spy_ap(*a, **k):
            spied.append("ap")
            return orig_ap(*a, **k)

        auroc_mod._mc_auroc_from_parts = _spy_roc
        auroc_mod._mc_auprc_from_parts = _spy_ap
        try:
            out = ev.compute()
        finally:
            auroc_mod._mc_auroc_from_parts = orig_roc
            auroc_mod._mc_auprc_from_parts = orig_ap
        self.assertEqual(spied, [])  # the gather-based programs never ran
        all_s = np.concatenate([s for s, _ in parts])
        all_t = np.concatenate([t for _, t in parts])
        np.testing.assert_allclose(
            np.asarray(out["auroc"]),
            [
                roc_auc_score((all_t == c).astype(int), all_s[:, c])
                for c in range(C)
            ],
            atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(out["auprc"]),
            [
                average_precision_score((all_t == c).astype(int), all_s[:, c])
                for c in range(C)
            ],
            atol=1e-5,
        )

    def test_merged_then_computed_after_sync_still_correct(self):
        # merging pulls state through _set_states — mixed provenance caches
        # must still compute correctly (dist path simply disables itself
        # when entries are not uniformly sharded)
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        s1, t1 = _tied_data(8 * 100)
        ev.update(jnp.asarray(s1), jnp.asarray(t1))
        other = BinaryAUROC()
        s2, t2 = _tied_data(999)  # not divisible by 8, unsharded
        other.update(jnp.asarray(s2), jnp.asarray(t2))
        merged = ev.metrics["m"] if "m" in ev.metrics else list(ev.metrics.values())[0]
        merged.merge_state([other])
        want = roc_auc_score(np.concatenate([t1, t2]), np.concatenate([s1, s2]))
        self.assertAlmostEqual(float(merged.compute()), want, places=6)


class TestDistPathCounter(unittest.TestCase):
    """``ops.dist_curves.calls{path=,family=}`` makes the dist-vs-fused
    selection observable (mirrors ``ops.topk.calls{path=}``): artifacts like
    the multichip dryrun assert the dist path actually engaged instead of
    silently validating the fallback."""

    def setUp(self):
        from torcheval_tpu import obs

        obs.enable()
        obs.reset()
        self.mesh = data_parallel_mesh()

    def tearDown(self):
        from torcheval_tpu import obs

        obs.disable()
        obs.reset()

    def _counters(self):
        from torcheval_tpu import obs

        return obs.snapshot()["counters"]

    def test_binary_dist_and_fused_paths_counted(self):
        s, t = _tied_data(8 * 100)
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        ev.update(jnp.asarray(s), jnp.asarray(t))
        ev.compute()
        c = self._counters()
        self.assertEqual(
            c.get("ops.dist_curves.calls{family=binary,path=dist}"), 1.0
        )
        self.assertNotIn("ops.dist_curves.calls{family=binary,path=fused}", c)
        plain = BinaryAUPRC()
        plain.update(jnp.asarray(s), jnp.asarray(t))
        plain.compute()
        c = self._counters()
        self.assertEqual(
            c.get("ops.dist_curves.calls{family=binary,path=fused}"), 1.0
        )

    def test_multiclass_paths_counted(self):
        C = 3
        s, t = _mc_tied_data(8 * 100, C)
        ev = ShardedEvaluator(
            MulticlassAUROC(num_classes=C), mesh=self.mesh
        )
        ev.update(jnp.asarray(s), jnp.asarray(t))
        ev.compute()
        c = self._counters()
        self.assertEqual(
            c.get("ops.dist_curves.calls{family=multiclass,path=dist}"), 1.0
        )
        plain = MulticlassAUPRC(num_classes=C)
        plain.update(jnp.asarray(s), jnp.asarray(t))
        plain.compute()
        c = self._counters()
        self.assertEqual(
            c.get("ops.dist_curves.calls{family=multiclass,path=fused}"), 1.0
        )

    def test_overflow_fallback_counts_as_fused(self):
        # a sharded cache whose skew trips the capacity valve lands on the
        # fused program — the counter must say so (the observable behind
        # docs/performance.md's detect-and-fallback cost)
        n = 8 * 128
        s = np.full(n, 0.5, np.float32)
        t = (RNG.random(n) < 0.5).astype(np.float32)
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        ev.update(jnp.asarray(s), jnp.asarray(t))
        ev.compute()
        c = self._counters()
        self.assertEqual(
            c.get("ops.dist_curves.calls{family=binary,path=fused}"), 1.0
        )
        self.assertNotIn("ops.dist_curves.calls{family=binary,path=dist}", c)


@pytest.mark.slow
class TestAdversarialSkewFallback(unittest.TestCase):
    """Adversarial-skew coverage at a size where the detect-and-fallback
    cost is measurable (tier-1 runs exclude ``slow``): a massive-tie stream
    must trip the ``DIST_CAPACITY_FACTOR`` overflow valve, fall back to the
    fused program, and still be exactly correct. The measured cost of the
    failed dist attempt is recorded in docs/performance.md §Distributed
    curve reduction."""

    def test_massive_ties_trip_overflow_and_fall_back_correctly(self):
        import time

        mesh = data_parallel_mesh()
        n = 8 * 200_000
        # 80% of the stream ties on ONE score: that tie group is a single
        # bucket holding 0.8·n_local rows per source against a per-bucket
        # send capacity of 4·n_local/8 = 0.5·n_local — guaranteed overflow
        s = np.where(
            RNG.random(n) < 0.8, np.float32(0.5), np.float32(0.25)
        ).astype(np.float32)
        t = (RNG.random(n) < 0.4).astype(np.float32)
        s_g = shard_batch(mesh, jnp.asarray(s))
        t_g = shard_batch(mesh, jnp.asarray(t))
        # the kernel detects the overflow exactly (never silently drops)
        _, ov = sharded_binary_auroc([s_g], [t_g], mesh=mesh)
        self.assertGreater(int(ov), 0)
        # the metric detects and falls back; time the full compute (failed
        # dist attempt + fused fallback) vs the fused program alone
        ev = ShardedEvaluator(BinaryAUROC(), mesh=mesh)
        ev.update(jnp.asarray(s), jnp.asarray(t))
        t0 = time.perf_counter()
        v = float(ev.compute())
        t_fallback = time.perf_counter() - t0
        self.assertAlmostEqual(v, roc_auc_score(t, s), places=6)
        # baseline: the SAME sharded cache forced straight to the fused
        # program (what every compute would pay if the dist path did not
        # exist) — the delta is the pure detect-and-fallback overhead
        ev2 = ShardedEvaluator(BinaryAUROC(), mesh=mesh)
        ev2.update(jnp.asarray(s), jnp.asarray(t))
        m2 = list(ev2.metrics.values())[0]
        m2._sharded_raw_mesh = lambda: None
        t0 = time.perf_counter()
        v2 = float(ev2.compute())
        t_fused = time.perf_counter() - t0
        self.assertAlmostEqual(v, v2, places=6)
        # the valve is detect-and-fallback, not detect-and-die: the whole
        # thing stays within a small multiple of the fused program
        print(
            f"\nskew fallback: dist-attempt+fused={t_fallback * 1e3:.1f} ms, "
            f"fused-only={t_fused * 1e3:.1f} ms, n={n}"
        )




class TestInt8QPsum(unittest.TestCase):
    """ISSUE 13 satellite (ROADMAP 1(b)): the int8-chunked reduce-scatter/
    all-gather qpsum for the splitter histogram — bit-identical values
    (splitters only balance load), int8 collectives visible in the HLO,
    and a clean bf16 fallback when the bin count does not chunk evenly."""

    def setUp(self):
        self.mesh = data_parallel_mesh()

    def _sharded(self, s, t):
        return (
            [shard_batch(self.mesh, jnp.asarray(s))],
            [shard_batch(self.mesh, jnp.asarray(t))],
        )

    def test_values_bit_identical_across_all_three_modes(self):
        # AUROC is asserted BIT-identical: quantized scores make every
        # trapezoid partial sum exactly representable in f32, so the value
        # is independent of where the (possibly shifted) splitters put the
        # rows. AUPRC's step integral is not order-free in f32 — a shifted
        # splitter regroups the psum'd precision terms — so the int8 mode
        # (whose histogram is lossy even on small counts, unlike bf16's
        # exact <=256 integers) is asserted to a few ulps; both sides are
        # exact decompositions of the same integral.
        s, t = _tied_data(8 * 300)
        s_list, t_list = self._sharded(s, t)
        v_raw, e_raw = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        v_bf, _ = sharded_binary_auroc(
            s_list, t_list, mesh=self.mesh, quantize=True
        )
        v_i8, e_i8 = sharded_binary_auroc(
            s_list, t_list, mesh=self.mesh, quantize="int8"
        )
        self.assertEqual(int(e_raw), 0)
        self.assertEqual(int(e_i8), 0)
        self.assertEqual(float(v_raw), float(v_bf))
        self.assertEqual(float(v_raw), float(v_i8))
        p_raw, pe = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        p_i8, pi = sharded_binary_auprc(
            s_list, t_list, mesh=self.mesh, quantize="int8"
        )
        self.assertEqual(int(pe), 0)
        self.assertEqual(int(pi), 0)
        self.assertAlmostEqual(float(p_raw), float(p_i8), places=6)

    def test_multiclass_bit_identical_and_hlo_int8_collectives(self):
        C = 5
        s, t = _mc_tied_data(8 * 200, C)
        s_list, t_list = self._sharded(s, t)
        v_raw, _ = sharded_multiclass_auroc(s_list, t_list, mesh=self.mesh)
        v_i8, _ = sharded_multiclass_auroc(
            s_list, t_list, mesh=self.mesh, quantize="int8"
        )
        # AUROC: bit-identical (exact trapezoid sums, see the binary test)
        np.testing.assert_array_equal(np.asarray(v_raw), np.asarray(v_i8))
        p_raw, _ = sharded_multiclass_auprc(s_list, t_list, mesh=self.mesh)
        p_i8, _ = sharded_multiclass_auprc(
            s_list, t_list, mesh=self.mesh, quantize="int8"
        )
        # AUPRC: few-ulp summation-order drift when a splitter shifts
        np.testing.assert_allclose(
            np.asarray(p_raw), np.asarray(p_i8), atol=1e-6
        )
        fn = _program(self.mesh, "data", "mc_auroc", "int8")
        hlo = fn.lower(s_list, t_list).compile().as_text()
        self.assertIn("s8[", hlo)
        # the histogram qpsum's int8 legs: at least one s8 all-to-all
        # (reduce-scatter leg) and one s8 all-gather (re-broadcast leg)
        a2a_s8 = [
            line
            for line in hlo.splitlines()
            if "all-to-all" in line and "s8[" in line
        ]
        ag_s8 = [
            line
            for line in hlo.splitlines()
            if "all-gather" in line and "s8[" in line
        ]
        self.assertTrue(a2a_s8)
        self.assertTrue(ag_s8)
        # and NO bf16 splitter all-reduce left in the int8 program
        self.assertNotIn("bf16[", hlo)

    def test_env_int8_engages_and_error_channels_survive(self):
        import os
        from unittest import mock

        from torcheval_tpu.ops import dist_curves as dc

        n = 8 * 200
        s, t = _tied_data(n)
        s_list, t_list = self._sharded(s, t)
        with mock.patch.object(
            dc, "_program", wraps=dc._program
        ) as spy, mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_SYNC_QUANTIZE": "int8"}
        ):
            v_env, _ = sharded_binary_auroc(
                s_list, t_list, mesh=self.mesh
            )
        self.assertEqual(spy.call_args[0][3], "int8")
        v_raw, _ = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(float(v_env), float(v_raw))
        # NaN + overflow error channels intact under int8
        s_nan = s.copy()
        s_nan[1] = np.nan
        s_list, t_list = self._sharded(s_nan, t)
        _, err = sharded_binary_auroc(
            s_list, t_list, mesh=self.mesh, quantize="int8"
        )
        self.assertGreaterEqual(int(err), 1)
        s_const = np.full(n, 0.5, np.float32)
        s_list, t_list = self._sharded(s_const, t)
        _, ov = sharded_binary_auroc(
            s_list, t_list, mesh=self.mesh, quantize="int8"
        )
        self.assertGreater(int(ov), 0)


if __name__ == "__main__":
    unittest.main()

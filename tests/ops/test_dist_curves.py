"""Distributed bucket-sort curve reduction on the forced 8-device CPU mesh
(round-4 verdict ask 4: per-shard sort + all_to_all replaces XLA's
gather-based sort partitioning for sharded curve caches)."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.metrics import average_precision_score, roc_auc_score

from torcheval_tpu.metrics import BinaryAUPRC, BinaryAUROC
from torcheval_tpu.ops.dist_curves import (
    _program,
    sharded_binary_auprc,
    sharded_binary_auroc,
)
from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh, shard_batch

RNG = np.random.default_rng(17)


def _tied_data(n):
    s = ((RNG.random(n) * 300).astype(np.int32) / 300.0).astype(np.float32)
    t = (RNG.random(n) < 0.4).astype(np.float32)
    return s, t


class TestDistCurveKernels(unittest.TestCase):
    def setUp(self):
        self.mesh = data_parallel_mesh()

    def _sharded_lists(self, batches):
        s_list = [shard_batch(self.mesh, jnp.asarray(s)) for s, _ in batches]
        t_list = [shard_batch(self.mesh, jnp.asarray(t)) for _, t in batches]
        return s_list, t_list

    def test_auroc_parity_multi_batch_with_ties(self):
        batches = [_tied_data(8 * (200 + 100 * i)) for i in range(3)]
        s_list, t_list = self._sharded_lists(batches)
        all_s = np.concatenate([s for s, _ in batches])
        all_t = np.concatenate([t for _, t in batches])
        v, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        self.assertAlmostEqual(float(v), roc_auc_score(all_t, all_s), places=6)

    def test_auprc_parity(self):
        batches = [_tied_data(8 * 250) for _ in range(2)]
        s_list, t_list = self._sharded_lists(batches)
        all_s = np.concatenate([s for s, _ in batches])
        all_t = np.concatenate([t for _, t in batches])
        v, ov = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        self.assertAlmostEqual(
            float(v), average_precision_score(all_t, all_s), places=5
        )

    def test_neg_inf_scores(self):
        s = np.array([0.9, -np.inf, 0.4, -np.inf, 0.1, 0.7, 0.2, 0.3] * 32,
                     np.float32)
        t = (RNG.random(s.size) < 0.5).astype(np.float32)
        s_list, t_list = self._sharded_lists([(s, t)])
        v, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        fin = np.where(np.isinf(s), -1e30, s)  # rank-equivalent for sklearn
        self.assertAlmostEqual(float(v), roc_auc_score(t, fin), places=6)

    def test_signed_zeros_share_a_tie_group(self):
        # -0.0 == +0.0 in float compares: the fused path merges them into
        # one tie group, so the key transform must too (review finding:
        # distinct bitcast keys silently changed the result by ~2e-3)
        n = 3200
        s, t = _tied_data(n)
        s[:100], t[:100] = 0.0, 1.0
        s[100:200], t[100:200] = -0.0, 0.0
        perm = np.random.default_rng(0).permutation(n)  # spread across shards
        s, t = s[perm], t[perm]
        s_list, t_list = self._sharded_lists([(s, t)])
        v, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(ov), 0)
        self.assertAlmostEqual(float(v), roc_auc_score(t, s), places=6)

    def test_degenerate_targets_guard(self):
        s, _ = _tied_data(800)
        s_list, t_list = self._sharded_lists([(s, np.ones(800, np.float32))])
        v, _ = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(float(v), 0.5)
        v2, _ = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        self.assertAlmostEqual(float(v2), 1.0, places=6)

    def test_capacity_overflow_detected_exactly(self):
        # every row the same score: one bucket receives everything — the
        # kernel must COUNT the clipped rows, never silently drop them
        n = 8 * 128
        s = np.full(n, 0.5, np.float32)
        t = (RNG.random(n) < 0.5).astype(np.float32)
        s_list, t_list = self._sharded_lists([(s, t)])
        _, ov = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertGreater(int(ov), 0)

    def test_nan_scores_trip_error_channel(self):
        # NaN-scored REAL rows would take the padding's sort position in the
        # bucket sort (diverging from the fused kernels' NaN-first order) —
        # they must be counted into the error channel, never silently folded
        n = 8 * 200
        s, t = _tied_data(n)
        s[3] = np.nan
        s[n // 2] = np.nan
        s_list, t_list = self._sharded_lists([(s, t)])
        _, err = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertGreaterEqual(int(err), 2)
        _, err = sharded_binary_auprc(s_list, t_list, mesh=self.mesh)
        self.assertGreaterEqual(int(err), 2)

    def test_nan_free_data_keeps_zero_error_channel(self):
        s_list, t_list = self._sharded_lists([_tied_data(8 * 200)])
        _, err = sharded_binary_auroc(s_list, t_list, mesh=self.mesh)
        self.assertEqual(int(err), 0)

    def test_no_sample_all_gather_in_hlo(self):
        # the acceptance criterion (round-4 verdict ask 4): the compiled
        # program for a sharded curve compute contains NO all-gather at all —
        # the only sample-sized collective is the all-to-all bucket exchange;
        # per-shard totals ride K-element all-reduces
        batches = [_tied_data(8 * 256)]
        s_list, t_list = self._sharded_lists(batches)
        fn = _program(self.mesh, "data", "auroc")
        hlo = fn.lower(s_list, t_list).compile().as_text()
        self.assertNotIn("all-gather", hlo)
        self.assertIn("all-to-all", hlo)


class TestDistCurveMetricIntegration(unittest.TestCase):
    """BinaryAUROC/AUPRC automatically take the distributed path when their
    cache is uniformly data-sharded (the ShardedEvaluator regime)."""

    def setUp(self):
        self.mesh = data_parallel_mesh()

    def test_evaluator_auroc_uses_dist_path(self):
        import torcheval_tpu.metrics.classification.auroc as auroc_mod

        ev = ShardedEvaluator(
            {"auroc": BinaryAUROC(), "auprc": BinaryAUPRC()}, mesh=self.mesh
        )
        parts = [_tied_data(8 * 200) for _ in range(3)]
        for s, t in parts:
            ev.update(jnp.asarray(s), jnp.asarray(t))
        m = ev.metrics["auroc"]
        self.assertIsNotNone(m._sharded_raw_mesh())  # dist path is active
        calls = []
        orig = auroc_mod._auroc_from_parts

        def _spy(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        auroc_mod._auroc_from_parts = _spy
        try:
            out = ev.compute()
        finally:
            auroc_mod._auroc_from_parts = orig
        self.assertEqual(calls, [])  # the gather-based program never ran
        all_s = np.concatenate([s for s, _ in parts])
        all_t = np.concatenate([t for _, t in parts])
        self.assertAlmostEqual(
            float(out["auroc"]), roc_auc_score(all_t, all_s), places=6
        )
        self.assertAlmostEqual(
            float(out["auprc"]),
            average_precision_score(all_t, all_s),
            places=5,
        )

    def test_overflow_falls_back_to_gather_path(self):
        # massively tied scores overload one bucket; the metric must detect
        # the overflow and fall back to the fused sort program — correct
        # result, never dropped rows
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        n = 8 * 128
        s = np.full(n, 0.25, np.float32)
        s[: n // 2] = 0.75
        t = (RNG.random(n) < 0.5).astype(np.float32)
        ev.update(jnp.asarray(s), jnp.asarray(t))
        self.assertAlmostEqual(
            float(ev.compute()), roc_auc_score(t, s), places=6
        )

    def test_nan_scores_fall_back_to_fused_path_and_match_unsharded(self):
        # a NaN-scored sample in a sharded cache must compute the SAME value
        # the unsharded cache computes (the fused kernels' NaN semantics),
        # via the error-channel fallback — not a silently different curve
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        n = 8 * 150
        s, t = _tied_data(n)
        s[7] = np.nan
        ev.update(jnp.asarray(s), jnp.asarray(t))
        self.assertIsNotNone(ev.metrics["metric"]._sharded_raw_mesh())
        sharded_value = float(ev.compute())
        plain = BinaryAUROC()
        plain.update(jnp.asarray(s), jnp.asarray(t))
        self.assertAlmostEqual(sharded_value, float(plain.compute()), places=6)

    def test_multi_axis_mesh_falls_back_to_fused_path(self):
        # a 2-D mesh (or a tuple spec entry) must NOT enter the bucket-sort
        # kernel, whose k_devices/capacity assume the spec axis covers the
        # whole mesh — compute falls back to the fused program instead of
        # raising (review finding)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.asarray(jax.devices()).reshape(4, 2)
        mesh2d = Mesh(devs, ("data", "model"))
        s, t = _tied_data(8 * 100)
        for spec in (P(("data", "model")), P("data")):
            m = BinaryAUROC()
            m.update(
                jax.device_put(jnp.asarray(s), NamedSharding(mesh2d, spec)),
                jax.device_put(jnp.asarray(t), NamedSharding(mesh2d, spec)),
            )
            self.assertIsNone(m._sharded_raw_mesh())
            self.assertAlmostEqual(
                float(m.compute()), roc_auc_score(t, s), places=6
            )

    def test_unsharded_cache_keeps_plain_path(self):
        m = BinaryAUROC()
        s, t = _tied_data(1000)
        m.update(jnp.asarray(s), jnp.asarray(t))
        self.assertIsNone(m._sharded_raw_mesh())
        self.assertAlmostEqual(float(m.compute()), roc_auc_score(t, s), places=6)

    def test_merged_then_computed_after_sync_still_correct(self):
        # merging pulls state through _set_states — mixed provenance caches
        # must still compute correctly (dist path simply disables itself
        # when entries are not uniformly sharded)
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        s1, t1 = _tied_data(8 * 100)
        ev.update(jnp.asarray(s1), jnp.asarray(t1))
        other = BinaryAUROC()
        s2, t2 = _tied_data(999)  # not divisible by 8, unsharded
        other.update(jnp.asarray(s2), jnp.asarray(t2))
        merged = ev.metrics["m"] if "m" in ev.metrics else list(ev.metrics.values())[0]
        merged.merge_state([other])
        want = roc_auc_score(np.concatenate([t1, t2]), np.concatenate([s1, s2]))
        self.assertAlmostEqual(float(merged.compute()), want, places=6)


if __name__ == "__main__":
    unittest.main()

"""Direct unit tests for the shared XLA kernels in ``torcheval_tpu/ops``."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.ops.confusion import (
    class_counts,
    confusion_matrix_counts,
    normalize_confusion_matrix,
    topk_onehot,
)

RNG = np.random.default_rng(0)


class TestClassCounts(unittest.TestCase):
    def test_unweighted_matches_bincount(self):
        labels = RNG.integers(0, 17, 500)
        want = np.bincount(labels, minlength=17)
        for method in ("matmul", "scatter", "sort", "auto"):
            got = np.asarray(class_counts(jnp.asarray(labels), 17, method=method))
            np.testing.assert_array_equal(got, want, err_msg=method)

    def test_sort_path_drops_out_of_range(self):
        labels = jnp.asarray([0, 1, 7, -1, 1, 2])
        got = np.asarray(class_counts(labels, 3, method="sort"))
        np.testing.assert_array_equal(got, [1, 2, 1])

    def test_sort_path_rejects_weights(self):
        with self.assertRaisesRegex(ValueError, "unweighted"):
            class_counts(
                jnp.asarray([0, 1]), 2, jnp.asarray([1.0, 2.0]), method="sort"
            )

    def test_pallas_kernel_matches_bincount(self):
        # interpret mode on the CPU suite; the same kernel compiles for real
        # on a TPU backend (class_counts flips interpret off there)
        for n, c in ((0, 3), (5, 1), (500, 17), (300, 129), (1000, 1000)):
            labels = RNG.integers(-1, c + 2, n)  # includes out-of-range
            want = np.bincount(labels[(labels >= 0) & (labels < c)], minlength=c)
            got = np.asarray(
                class_counts(jnp.asarray(labels, jnp.int32), c, method="pallas")
            )
            np.testing.assert_array_equal(got, want, err_msg=f"n={n} c={c}")

    def test_pallas_tile_plan_is_mosaic_legal(self):
        # block second-to-last dim must be a multiple of 8 (f32 sublanes) and
        # the (rows, 128, c_tile) one-hot must fit the VMEM budget; a C large
        # enough to shrink the block exposed a non-divisible 207-row block on
        # real TPU lowering, and an unshrunk one-hot OOM'd VMEM at C=10k
        from torcheval_tpu.ops.pallas_hist import (
            _VMEM_BUDGET_BYTES,
            _round_up,
            _tile_plan,
        )

        for c in (1, 100, 1000, 1290, 10_000, 65_536, 500_000):
            c_pad = _round_up(c, 128)
            rows, c_tile = _tile_plan(c_pad)
            self.assertEqual(rows % 8, 0, f"c={c} -> rows={rows}")
            self.assertGreaterEqual(rows, 8)
            self.assertEqual(c_tile % 128, 0)
            self.assertLessEqual(
                rows * 128 * c_tile * 4, 2 * _VMEM_BUDGET_BYTES, f"c={c}"
            )

    def test_pallas_rejects_weights(self):
        with self.assertRaisesRegex(ValueError, "unweighted"):
            class_counts(
                jnp.asarray([0, 1]), 2, jnp.asarray([1.0, 2.0]), method="pallas"
            )

    def test_auto_pick_respects_exactness_and_size(self):
        from torcheval_tpu.ops.confusion import _pick_method

        self.assertEqual(_pick_method(100_000, 1000, "auto", False), "matmul")
        # huge virtual one-hot: unweighted goes to sort, weighted to scatter
        self.assertEqual(_pick_method(1_000_000, 10_000, "auto", False), "sort")
        self.assertEqual(_pick_method(1_000_000, 10_000, "auto", True), "scatter")
        # counts up to 2**24 INCLUSIVE are f32-exact (ADVICE r02 off-by-one):
        # the boundary batch keeps the fast lowering, one past it does not
        self.assertEqual(_pick_method(1 << 24, 2, "auto", False), "matmul")
        self.assertEqual(_pick_method((1 << 24) + 1, 2, "auto", False), "sort")

    def test_unknown_method_rejected(self):
        with self.assertRaisesRegex(ValueError, "method must be one of"):
            class_counts(jnp.asarray([0, 1]), 2, method="Sort")

    def test_auto_picks_pallas_only_on_tpu_and_large(self):
        from unittest import mock

        from torcheval_tpu.ops import confusion

        big_n = 16_777_215  # < 2**24, n*C over the Pallas threshold
        # this suite runs on the CPU backend: auto must never route to the
        # interpret-mode Pallas kernel
        self.assertNotEqual(
            confusion._pick_method(big_n, 1000, "auto", False), "pallas"
        )
        with mock.patch.object(
            confusion.jax, "default_backend", return_value="tpu"
        ):
            # since round 3 the kernel carries a custom_partitioning GSPMD
            # rule (per-shard VMEM histograms + psum), so the auto-pick fires
            # on ANY world size of a tpu backend
            self.assertEqual(
                confusion._pick_method(big_n, 1000, "auto", False), "pallas"
            )
            # small workloads and weighted counts keep the XLA lowerings
            self.assertEqual(
                confusion._pick_method(1_000_000, 1000, "auto", False),
                "matmul",
            )
            self.assertEqual(
                confusion._pick_method(big_n, 1000, "auto", True), "scatter"
            )

    def test_weighted(self):
        labels = RNG.integers(0, 5, 100)
        w = RNG.random(100).astype(np.float32)
        want = np.bincount(labels, weights=w, minlength=5)
        for method in ("matmul", "scatter"):
            got = np.asarray(
                class_counts(jnp.asarray(labels), 5, jnp.asarray(w), method=method)
            )
            np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=method)

    def test_out_of_range_dropped(self):
        labels = jnp.asarray([0, 1, 7, -1, 1])
        got = np.asarray(class_counts(labels, 3, method="scatter"))
        np.testing.assert_array_equal(got, [1, 2, 0])

    def test_rejects_2d(self):
        with self.assertRaisesRegex(ValueError, "1-D"):
            class_counts(jnp.zeros((2, 2), jnp.int32), 3)


class TestConfusionMatrixCounts(unittest.TestCase):
    def test_matches_sklearn(self):
        from sklearn.metrics import confusion_matrix as sk_cm

        p = RNG.integers(0, 9, 400)
        t = RNG.integers(0, 9, 400)
        got = np.asarray(confusion_matrix_counts(jnp.asarray(p), jnp.asarray(t), 9))
        np.testing.assert_array_equal(got, sk_cm(t, p, labels=np.arange(9)))

    def test_one_bad_coordinate_drops_whole_sample(self):
        p = jnp.asarray([0, 5, 1])   # 5 out of range for C=3
        t = jnp.asarray([0, 1, -2])  # -2 out of range
        got = np.asarray(confusion_matrix_counts(p, t, 3))
        self.assertEqual(int(got.sum()), 1)
        self.assertEqual(int(got[0, 0]), 1)

    def test_matmul_and_scatter_lowerings_agree(self):
        # both sides of the N·C² auto-pick produce identical counts,
        # including dropped out-of-range coordinates
        from torcheval_tpu.ops import confusion

        p = np.concatenate([RNG.integers(0, 9, 400), [-1, 9, 3]])
        t = np.concatenate([RNG.integers(0, 9, 400), [2, 2, 12]])
        jp, jt = jnp.asarray(p), jnp.asarray(t)
        via_matmul = np.asarray(confusion_matrix_counts(jp, jt, 9))
        orig = confusion._CONFUSION_MATMUL_BUDGET
        try:
            confusion._CONFUSION_MATMUL_BUDGET = 0  # force the scatter path
            via_scatter = np.asarray(
                jax.jit(
                    confusion.confusion_matrix_counts.__wrapped__,
                    static_argnames=("num_classes", "normalize"),
                )(jp, jt, 9)
            )
        finally:
            confusion._CONFUSION_MATMUL_BUDGET = orig
        np.testing.assert_array_equal(via_matmul, via_scatter)

    def test_normalize_modes(self):
        mat = jnp.asarray([[2, 0], [1, 1]], jnp.int32)
        np.testing.assert_allclose(
            np.asarray(normalize_confusion_matrix(mat, "all")).sum(), 1.0
        )
        np.testing.assert_allclose(
            np.asarray(normalize_confusion_matrix(mat, "true")).sum(axis=1), [1, 1]
        )
        pred_norm = np.asarray(normalize_confusion_matrix(mat, "pred"))
        np.testing.assert_allclose(pred_norm.sum(axis=0), [1, 1])
        with self.assertRaisesRegex(ValueError, "normalize"):
            normalize_confusion_matrix(mat, "bogus")


class TestTopkOnehot(unittest.TestCase):
    def test_exactly_k_per_row(self):
        scores = jnp.asarray(RNG.random((32, 10)).astype(np.float32))
        out = np.asarray(topk_onehot(scores, 3))
        np.testing.assert_array_equal(out.sum(axis=1), np.full(32, 3))

    def test_selects_top_scores(self):
        scores = jnp.asarray([[0.1, 0.9, 0.5, 0.7]])
        out = np.asarray(topk_onehot(scores, 2))
        np.testing.assert_array_equal(out[0], [0, 1, 0, 1])

    def test_ties_broken_by_index(self):
        scores = jnp.asarray([[1.0, 1.0, 1.0]])
        out = np.asarray(topk_onehot(scores, 2))
        np.testing.assert_array_equal(out[0], [1, 1, 0])


class TestCurveKernelEdges(unittest.TestCase):
    def test_empty_inputs(self):
        from torcheval_tpu.ops.curves import (
            binary_auprc_kernel,
            binary_auroc_kernel,
        )

        e = jnp.zeros((0,))
        self.assertEqual(float(binary_auroc_kernel(e, e)), 0.5)
        self.assertEqual(float(binary_auprc_kernel(e, e)), 0.0)

    def test_single_sample(self):
        from torcheval_tpu.ops.curves import binary_auroc_kernel

        # degenerate single-class input -> 0.5 guard
        self.assertEqual(
            float(binary_auroc_kernel(jnp.asarray([0.7]), jnp.asarray([1.0]))),
            0.5,
        )

    def test_counts_kernels_match_unit_expansion(self):
        from sklearn.metrics import roc_auc_score

        from torcheval_tpu.ops.curves import binary_auroc_counts_kernel

        # aggregated rows == expanded per-sample rows
        scores = jnp.asarray([0.9, 0.5, 0.1])
        tp = jnp.asarray([3, 0, 2], jnp.int32)
        fp = jnp.asarray([1, 4, 0], jnp.int32)
        got = float(binary_auroc_counts_kernel(scores, tp, fp))
        exp_scores = np.repeat([0.9, 0.5, 0.1], [4, 4, 2])
        exp_target = np.concatenate([[1] * 3 + [0], [0] * 4, [1] * 2])
        self.assertAlmostEqual(got, roc_auc_score(exp_target, exp_scores), places=6)


class TestParallelHelpers(unittest.TestCase):
    def test_replicate_and_eval_shardings(self):
        from torcheval_tpu.parallel import data_parallel_mesh, replicate
        from torcheval_tpu.parallel.evaluator import eval_shardings

        mesh = data_parallel_mesh()
        x = replicate(mesh, jnp.arange(4.0))
        self.assertEqual(len(x.sharding.device_set), len(jax.devices()))
        repl, sharded = eval_shardings(mesh)
        self.assertTrue(repl.is_fully_replicated)
        self.assertFalse(sharded.is_fully_replicated)

    def test_shard_batch_uneven_warns_once(self):
        import logging

        from torcheval_tpu.parallel import data_parallel_mesh, shard_batch
        from torcheval_tpu.parallel import mesh as mesh_mod

        mesh = data_parallel_mesh()
        mesh_mod._warned_uneven_batch = False
        with self.assertLogs(level=logging.WARNING):
            shard_batch(mesh, np.zeros((9, 2), np.float32))  # 9 % 8 != 0
        # second time: no warning (warned-once flag)
        with self.assertNoLogs(level=logging.WARNING):
            shard_batch(mesh, np.zeros((9, 2), np.float32))


if __name__ == "__main__":
    unittest.main()


class TestShardedPallasHistogram(unittest.TestCase):
    """The custom_partitioning GSPMD rule: per-shard VMEM histograms + one
    psum, with the sharded operand never re-gathered (round-2 verdict #5)."""

    def _mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.devices()), ("data",))

    def test_sharded_counts_match_bincount(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torcheval_tpu.ops.pallas_hist import sharded_pallas_class_counts

        mesh = self._mesh()
        n, c = 8 * 1000, 37
        labels = np.random.default_rng(0).integers(0, c, n).astype(np.int32)
        sharded = jax.device_put(
            jnp.asarray(labels), NamedSharding(mesh, P("data"))
        )
        fn = jax.jit(
            lambda ls: sharded_pallas_class_counts(ls, c, True),
            in_shardings=NamedSharding(mesh, P("data")),
        )
        out = fn(sharded)
        np.testing.assert_array_equal(
            np.asarray(out), np.bincount(labels, minlength=c)
        )

    def test_sharded_operand_not_gathered(self):
        # the compiled program must reduce per-shard results (all-reduce),
        # never all-gather the sample operand onto one device
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torcheval_tpu.ops.pallas_hist import sharded_pallas_class_counts

        mesh = self._mesh()
        n, c = 8 * 1024, 16
        fn = jax.jit(
            lambda ls: sharded_pallas_class_counts(ls, c, True),
            in_shardings=NamedSharding(mesh, P("data")),
        )
        hlo = fn.lower(
            jax.ShapeDtypeStruct((n,), jnp.int32)
        ).compile().as_text()
        self.assertNotIn("all-gather", hlo)
        self.assertIn("all-reduce", hlo)

    def test_replicated_operand_single_count(self):
        # replicated input: no psum (counts would multiply by world size)
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torcheval_tpu.ops.pallas_hist import sharded_pallas_class_counts

        mesh = self._mesh()
        n, c = 2048, 9
        labels = np.random.default_rng(1).integers(0, c, n).astype(np.int32)
        repl = jax.device_put(jnp.asarray(labels), NamedSharding(mesh, P()))
        fn = jax.jit(
            lambda ls: sharded_pallas_class_counts(ls, c, True),
            in_shardings=NamedSharding(mesh, P()),
        )
        np.testing.assert_array_equal(
            np.asarray(fn(repl)), np.bincount(labels, minlength=c)
        )


class TestMatchTripleCounts(unittest.TestCase):
    """Both lanes of the F1/precision/recall sufficient-statistic kernel."""

    def _oracle(self, pred, target, c):
        tp = np.bincount(target[pred == target], minlength=c)
        label = np.bincount(target, minlength=c)
        prd = np.bincount(pred, minlength=c)
        return tp, label, prd

    def test_matmul_lane(self):
        from torcheval_tpu.ops.confusion import match_triple_counts

        c = 11
        pred = RNG.integers(0, c, 500).astype(np.int32)
        target = RNG.integers(0, c, 500).astype(np.int32)
        got = match_triple_counts(jnp.asarray(pred), jnp.asarray(target), c)
        for g, w in zip(got, self._oracle(pred, target, c)):
            np.testing.assert_array_equal(np.asarray(g), w)

    def test_joint_sort_lane(self):
        # force the over-budget branch by shrinking the matmul budget
        from unittest import mock

        from torcheval_tpu.ops import confusion

        c = 11
        pred = RNG.integers(0, c, 500).astype(np.int32)
        target = RNG.integers(0, c, 500).astype(np.int32)
        with mock.patch.object(confusion, "_MATMUL_ELEMENT_BUDGET", 1):
            got = confusion.match_triple_counts.__wrapped__(
                jnp.asarray(pred), jnp.asarray(target), c
            )
        for g, w in zip(got, self._oracle(pred, target, c)):
            np.testing.assert_array_equal(np.asarray(g), w)

    def test_joint_sort_lane_drops_out_of_range(self):
        from unittest import mock

        from torcheval_tpu.ops import confusion

        c = 5
        pred = np.asarray([0, 1, 2, 9, -1], np.int32)
        target = np.asarray([0, 1, 3, -2, 7], np.int32)
        with mock.patch.object(confusion, "_MATMUL_ELEMENT_BUDGET", 1):
            got = confusion.match_triple_counts.__wrapped__(
                jnp.asarray(pred), jnp.asarray(target), c
            )
        valid_t = (target >= 0) & (target < c)
        valid_p = (pred >= 0) & (pred < c)
        tp = np.bincount(target[(pred == target) & valid_t], minlength=c)
        label = np.bincount(target[valid_t], minlength=c)
        prd = np.bincount(pred[valid_p], minlength=c)
        for g, w in zip(got, (tp, label, prd)):
            np.testing.assert_array_equal(np.asarray(g), w)

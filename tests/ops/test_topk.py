"""Streaming top-k engine (``torcheval_tpu/ops/topk.py``): interpret-mode
Pallas kernel and threshold-prune equivalence against ``lax.top_k`` (values
AND tie-broken indices), valve correctness on adversarial inputs, and the
``ops.topk.calls{path=}`` obs dispatch accounting per backend."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.ops.topk import (
    _DENSE_L_MAX,
    _PALLAS_MAX_K,
    _pick_method,
    pallas_topk,
    prune_topk,
    topk,
    topk_indices,
    topk_values,
)

RNG = np.random.default_rng(7)


def _ref(x, k):
    v, i = jax.lax.top_k(jnp.asarray(x, jnp.float32), k)
    return np.asarray(v), np.asarray(i)


def _assert_matches(test, x, k, method, **kw):
    v, i = topk(jnp.asarray(x), k, method=method, **kw)
    rv, ri = _ref(x, k)
    msg = f"method={method} shape={x.shape} k={k}"
    np.testing.assert_array_equal(np.asarray(v), rv, err_msg=msg)
    np.testing.assert_array_equal(np.asarray(i), ri, err_msg=msg)


class TestPallasInterpret(unittest.TestCase):
    """The REAL kernel in interpret mode (forced method="pallas" off-TPU
    auto-interprets, mirroring class_counts); the same kernel compiles for
    real on a TPU backend."""

    def test_random_matches_lax_top_k(self):
        for shape, k in (((37, 3000), 5), ((8, 1537), 3), ((128, 2048), 1)):
            x = RNG.random(shape, dtype=np.float32)
            _assert_matches(self, x, k, "pallas")

    def test_tie_rows_match_tie_break(self):
        # heavy ties: quantized values force the lowest-index-first order
        x = RNG.integers(0, 5, (64, 2048)).astype(np.float32)
        _assert_matches(self, x, 7, "pallas")

    def test_all_equal_rows(self):
        _assert_matches(self, np.ones((16, 1536), np.float32), 5, "pallas")

    def test_k_equals_l_edge(self):
        # k == L (full descending sort) stays exact, incl. tie order
        x = RNG.integers(0, 3, (9, 100)).astype(np.float32)
        _assert_matches(self, x, 100, "pallas")

    def test_k_beyond_l_raises(self):
        with self.assertRaises(ValueError):
            topk(jnp.zeros((4, 16)), 17, method="pallas")

    def test_neg_inf_rows(self):
        # real -inf scores must win over label padding and carry
        # placeholders: indices come back in ascending order
        x = np.full((8, 1111), -np.inf, np.float32)
        x[:, 700] = 1.0
        _assert_matches(self, x, 4, "pallas")

    def test_ragged_tile_and_row_shapes(self):
        # L not a multiple of the 512 tile, N not a multiple of the block
        x = RNG.random((13, 10000), dtype=np.float32)
        _assert_matches(self, x, 5, "pallas")

    def test_k_larger_than_pallas_carry_rejected(self):
        with self.assertRaises(ValueError):
            pallas_topk(jnp.zeros((4, 4096)), _PALLAS_MAX_K + 1)


class TestPrune(unittest.TestCase):
    def test_random_matches_lax_top_k(self):
        for shape, k in (((37, 3000), 5), ((16, 4096), 20), ((64, 2048), 1)):
            x = RNG.random(shape, dtype=np.float32)
            _assert_matches(self, x, k, "prune")

    def test_tie_rows_match_tie_break(self):
        x = RNG.integers(0, 5, (64, 2048)).astype(np.float32)
        _assert_matches(self, x, 7, "prune")

    def test_valve_on_all_equal_rows(self):
        # every element ties the kth-value threshold -> every group's
        # survivor count exceeds the budget -> the lax.cond valve must
        # re-run exact dense top_k (indices 0..k-1 per row)
        x = np.ones((16, 4096), np.float32)
        v, i = prune_topk(jnp.asarray(x), 5)
        np.testing.assert_array_equal(np.asarray(v), np.ones((16, 5), np.float32))
        np.testing.assert_array_equal(
            np.asarray(i), np.tile(np.arange(5), (16, 1))
        )

    def test_valve_on_heavy_tail_row(self):
        # one row floods a single group with > budget survivors while the
        # others stay easy: the batch-level valve must keep EVERY row exact
        x = RNG.random((8, 4096), dtype=np.float32)
        x[3, :128] = 2.0  # 128 survivors in group 0 > budget (8)
        _assert_matches(self, x, 5, "prune")

    def test_rows_with_neg_inf_take_valve(self):
        # fewer than k finite values -> theta degenerates to -inf -> every
        # lane survives -> valve -> dense; result must still be exact
        x = np.full((4, 2048), -np.inf, np.float32)
        x[:, 5] = 1.0
        _assert_matches(self, x, 3, "prune")

    def test_small_l_falls_back_to_dense(self):
        # below the group plan's feasibility the forced path is still exact
        x = RNG.random((6, 256), dtype=np.float32)
        _assert_matches(self, x, 4, "prune")

    def test_k_equals_l_edge(self):
        x = RNG.integers(0, 3, (9, 100)).astype(np.float32)
        _assert_matches(self, x, 100, "prune")


class TestEngineDispatch(unittest.TestCase):
    """Path selection + the ops.topk.calls{path=} obs counter per backend.

    This suite runs on the CPU backend: auto must resolve dense everywhere
    (the prune auto-pick is a measured CPU dead end — docs/performance.md)
    and never pallas. The pallas label is pinned via the forced method,
    which is exactly what a TPU auto pick resolves to at these sizes."""

    def test_pick_method_cpu(self):
        self.assertEqual(_pick_method(10_000, 5, jnp.float32, "auto"), "dense")
        self.assertEqual(_pick_method(512, 5, jnp.float32, "auto"), "dense")
        self.assertEqual(_pick_method(10_000, 5, jnp.int32, "auto"), "dense")
        # forced methods pass through untouched
        for m in ("dense", "prune", "pallas"):
            self.assertEqual(_pick_method(10_000, 5, jnp.float32, m), m)

    def test_pick_method_tpu_monkeypatched(self):
        # the TPU branch of the picker, without a TPU: backend query patched
        # (sys.modules lookup: the ops package re-exports the topk FUNCTION
        # under the module's name, so attribute-style import finds that)
        import sys

        topk_mod = sys.modules["torcheval_tpu.ops.topk"]
        orig = topk_mod.jax.default_backend
        topk_mod.jax.default_backend = lambda: "tpu"
        try:
            self.assertEqual(
                _pick_method(10_000, 5, jnp.float32, "auto"), "pallas"
            )
            # over the carry width -> not pallas even on TPU
            self.assertEqual(
                _pick_method(10_000, _PALLAS_MAX_K + 1, jnp.float32, "auto"),
                "dense",
            )
            # small L stays dense on every backend
            self.assertEqual(
                _pick_method(_DENSE_L_MAX, 5, jnp.float32, "auto"), "dense"
            )
        finally:
            topk_mod.jax.default_backend = orig

    def test_obs_counter_per_path(self):
        from torcheval_tpu import obs

        obs.enable()
        obs.reset()
        try:
            x_big = jnp.asarray(RNG.random((4, 4096), dtype=np.float32))
            x_small = jnp.asarray(RNG.random((4, 64), dtype=np.float32))
            topk(x_big, 5)  # auto on CPU -> dense
            topk(x_small, 5)  # auto, small L -> dense
            topk(x_big, 5, method="prune")
            topk(x_big, 5, method="pallas")
            counters = obs.snapshot()["counters"]
            self.assertEqual(counters.get("ops.topk.calls{path=dense}"), 2.0)
            self.assertEqual(counters.get("ops.topk.calls{path=prune}"), 1.0)
            self.assertEqual(counters.get("ops.topk.calls{path=pallas}"), 1.0)
        finally:
            obs.disable()
            obs.reset()

    def test_values_indices_helpers(self):
        x = RNG.random((5, 2048), dtype=np.float32)
        rv, ri = _ref(x, 3)
        np.testing.assert_array_equal(np.asarray(topk_values(x, 3)), rv)
        np.testing.assert_array_equal(np.asarray(topk_indices(x, 3)), ri)

    def test_validation(self):
        with self.assertRaises(ValueError):
            topk(jnp.zeros((4, 8)), 0)
        with self.assertRaises(ValueError):
            topk(jnp.zeros((8,)), 2)
        with self.assertRaises(TypeError):
            topk(jnp.zeros((4, 8)), np.int64(2))
        with self.assertRaises(ValueError):
            topk(jnp.zeros((4, 8)), 2, method="radix")


class TestMetricWiring(unittest.TestCase):
    """The engine behind _topk_multilabel_stats / TopKMultilabelAccuracy's
    deferred fold / reciprocal_rank's k cutoff stays result-identical to
    the dense baseline on every forced path."""

    def test_functional_topk_multilabel_accuracy_paths_agree(self):
        from torcheval_tpu.metrics.functional import topk_multilabel_accuracy

        s = RNG.random((64, 2048), dtype=np.float32)
        t = (RNG.random((64, 2048)) > 0.99).astype(np.int32)
        for criteria in ("exact_match", "hamming", "overlap", "contain", "belong"):
            vals = {
                m: float(
                    topk_multilabel_accuracy(
                        s, t, criteria=criteria, k=5, topk_method=m
                    )
                )
                for m in ("dense", "prune", "pallas", "auto")
            }
            self.assertEqual(
                len(set(vals.values())), 1, f"{criteria}: {vals}"
            )

    def test_functional_all_equal_scores_paths_agree(self):
        # adversarial ties end-to-end: prune valves, pallas min-index
        # tie-breaks — all must match the dense top-k set {0..k-1}
        s = np.ones((8, 2048), np.float32)
        t = np.zeros((8, 2048), np.int32)
        t[:, :5] = 1
        from torcheval_tpu.metrics.functional import topk_multilabel_accuracy

        for m in ("dense", "prune", "pallas"):
            self.assertEqual(
                float(
                    topk_multilabel_accuracy(
                        s, t, criteria="contain", k=5, topk_method=m
                    )
                ),
                1.0,
                m,
            )

    def test_metric_rejects_bad_topk_method_eagerly(self):
        # updates defer, so this must raise at CONSTRUCTION, not compute()
        from torcheval_tpu.metrics import TopKMultilabelAccuracy

        with self.assertRaisesRegex(ValueError, "topk_method"):
            TopKMultilabelAccuracy(k=2, topk_method="pallass")

    def test_metric_deferred_fold_paths_agree(self):
        from torcheval_tpu.metrics import TopKMultilabelAccuracy

        s = jnp.asarray(RNG.random((32, 2048), dtype=np.float32))
        t = jnp.asarray((RNG.random((32, 2048)) > 0.995).astype(np.int32))
        results = {}
        for m in ("dense", "pallas", "auto"):
            metric = TopKMultilabelAccuracy(
                k=5, criteria="overlap", topk_method=m
            )
            for _ in range(3):
                metric.update(s, t)
            results[m] = float(metric.compute())
        self.assertEqual(len(set(results.values())), 1, results)

    def test_reciprocal_rank_k_path_matches_full_comparison(self):
        from torcheval_tpu.metrics.functional import reciprocal_rank

        # on CPU auto resolves dense, so this exercises the guard + the
        # unchanged full-width branch
        for x in (
            RNG.random((128, 2048), dtype=np.float32),
            RNG.integers(0, 9, (64, 2048)).astype(np.float32),
        ):
            tgt = RNG.integers(0, x.shape[1], x.shape[0])
            got = np.asarray(reciprocal_rank(x, tgt, k=5))
            y = np.take_along_axis(x, tgt[:, None], axis=-1)
            rank = (x > y).sum(-1)
            want = np.where(rank >= 5, 0.0, 1.0 / (rank + 1)).astype(np.float32)
            np.testing.assert_array_equal(got, want)

    def test_reciprocal_rank_engine_branch_matches_full_comparison(self):
        # the TRUNCATED-rank branch itself (rank from the k engine VALUES,
        # saturating at k), which auto only reaches on a TPU backend: force
        # the picker to the prune engine so the branch runs — against the
        # REAL engine — on CPU. Fresh shapes per assert: the kernel's jit
        # cache is keyed on shapes and the pick happens at trace time.
        import sys

        from torcheval_tpu.metrics.functional import reciprocal_rank

        topk_mod = sys.modules["torcheval_tpu.ops.topk"]
        orig = topk_mod._pick_method

        def forced(l, k, dtype, method):
            return "prune" if method == "auto" else orig(l, k, dtype, method)

        topk_mod._pick_method = forced
        try:
            for x in (
                RNG.random((96, 2050), dtype=np.float32),
                RNG.integers(0, 9, (40, 2051)).astype(np.float32),  # ties
            ):
                tgt = RNG.integers(0, x.shape[1], x.shape[0])
                got = np.asarray(reciprocal_rank(x, tgt, k=5))
                y = np.take_along_axis(x, tgt[:, None], axis=-1)
                rank = (x > y).sum(-1)
                want = np.where(rank >= 5, 0.0, 1.0 / (rank + 1)).astype(
                    np.float32
                )
                np.testing.assert_array_equal(got, want)
        finally:
            topk_mod._pick_method = orig


class TestShardedPallasTopk(unittest.TestCase):
    """The custom_partitioning GSPMD rule: top-k is row-independent, so a
    batch-sharded operand runs the VMEM kernel per shard with NO collective
    and the outputs inherit the row sharding (mirrors
    TestShardedPallasHistogram in test_kernels.py)."""

    def _mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.devices()), ("data",))

    def test_sharded_rows_match_lax_top_k(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torcheval_tpu.ops.topk import sharded_pallas_topk

        mesh = self._mesh()
        n = 8 * len(jax.devices())
        x = RNG.random((n, 2048), dtype=np.float32)
        sharded = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P("data", None))
        )
        fn = jax.jit(
            lambda a: sharded_pallas_topk(a, 5, True),
            in_shardings=NamedSharding(mesh, P("data", None)),
        )
        v, i = fn(sharded)
        rv, ri = _ref(x, 5)
        np.testing.assert_array_equal(np.asarray(v), rv)
        np.testing.assert_array_equal(np.asarray(i), ri)

    def test_sharded_operand_not_gathered(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torcheval_tpu.ops.topk import sharded_pallas_topk

        mesh = self._mesh()
        n = 8 * len(jax.devices())
        fn = jax.jit(
            lambda a: sharded_pallas_topk(a, 5, True),
            in_shardings=NamedSharding(mesh, P("data", None)),
        )
        hlo = (
            fn.lower(jax.ShapeDtypeStruct((n, 2048), jnp.float32))
            .compile()
            .as_text()
        )
        # row-local selection: no operand gather AND no reduction at all
        self.assertNotIn("all-gather", hlo)
        self.assertNotIn("all-reduce", hlo)

    def test_replicated_operand(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torcheval_tpu.ops.topk import sharded_pallas_topk

        mesh = self._mesh()
        x = RNG.random((16, 1536), dtype=np.float32)
        repl = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P()))
        fn = jax.jit(
            lambda a: sharded_pallas_topk(a, 3, True),
            in_shardings=NamedSharding(mesh, P()),
        )
        v, i = fn(repl)
        rv, ri = _ref(x, 3)
        np.testing.assert_array_equal(np.asarray(v), rv)
        np.testing.assert_array_equal(np.asarray(i), ri)


if __name__ == "__main__":
    unittest.main()

"""Label-sharded streaming top-k (``ops/topk.py::sharded_label_topk``,
ISSUE 14 tentpole): per-shard kernel + one O(k·shards) candidate all-gather
+ exact 2-key merge must be bit-identical to dense ``lax.top_k`` (values AND
tie-ordered indices) on the forced-8-CPU mesh, with an HLO assertion that
the label axis is never replicated, plus the engine auto-pick and the obs
candidate-exchange accounting."""

import re
import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torcheval_tpu.ops.topk import (
    _IDX_SENTINEL,
    label_sharding_of,
    sharded_label_topk,
    topk,
)

RNG = np.random.default_rng(14)


def _mesh():
    return Mesh(np.asarray(jax.devices()), ("label",))


def _ref(x, k):
    v, i = jax.lax.top_k(jnp.asarray(x, jnp.float32), k)
    return np.asarray(v), np.asarray(i)


def _assert_matches(test, got, x, k, msg=""):
    rv, ri = _ref(x, k)
    np.testing.assert_array_equal(np.asarray(got[0]), rv, err_msg=msg)
    np.testing.assert_array_equal(np.asarray(got[1]), ri, err_msg=msg)


class TestShardedLabelTopk(unittest.TestCase):
    """Numeric parity on an 8-shard label mesh (conftest forces 8 CPU
    devices — the 'forced-8-CPU mesh' of the acceptance criteria)."""

    def test_presharded_operand_matches_lax_top_k(self):
        mesh = _mesh()
        sh = NamedSharding(mesh, P(None, "label"))
        for shape, k in (((13, 4096), 5), ((4, 1024), 7), ((64, 2048), 1)):
            x = RNG.random(shape, dtype=np.float32)
            xs = jax.device_put(jnp.asarray(x), sh)
            _assert_matches(
                self, sharded_label_topk(xs, k), x, k, f"{shape} k={k}"
            )

    def test_tie_rows_match_tie_break(self):
        # heavy ties: quantized values force the min-GLOBAL-index order,
        # including ties that straddle shard boundaries
        mesh = _mesh()
        x = RNG.integers(0, 4, (32, 2048)).astype(np.float32)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(None, "label"))
        )
        _assert_matches(self, sharded_label_topk(xs, 9), x, 9)

    def test_all_equal_rows(self):
        mesh = _mesh()
        x = np.ones((8, 1024), np.float32)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(None, "label"))
        )
        _assert_matches(self, sharded_label_topk(xs, 5), x, 5)

    def test_neg_inf_rows_beat_padding(self):
        # real -inf scores must win over ragged padding and sentinels: the
        # 2-key merge ties them at -inf and the min-global-index key must
        # pick the REAL entries in ascending-index order
        mesh = _mesh()
        x = np.full((6, 1000), -np.inf, np.float32)  # ragged: 1000 % 8 != 0
        x[:, 700] = 1.0
        got = sharded_label_topk(
            jnp.asarray(x), 4, mesh=mesh, label_axis="label"
        )
        _assert_matches(self, got, x, 4)
        self.assertTrue(np.all(np.asarray(got[1]) < _IDX_SENTINEL))

    def test_pos_inf_ties(self):
        mesh = _mesh()
        x = RNG.random((5, 2048)).astype(np.float32)
        x[:, [3, 900, 1999]] = np.inf  # +inf ties across three shards
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(None, "label"))
        )
        _assert_matches(self, sharded_label_topk(xs, 5), x, 5)

    def test_k_times_shards_exceeds_l_edge(self):
        # k=50 over L=100 on 8 shards: per-shard k_local saturates at the
        # 13-wide local tile, so every shard contributes its WHOLE tile
        mesh = _mesh()
        x = RNG.integers(0, 3, (8, 100)).astype(np.float32)
        got = sharded_label_topk(
            jnp.asarray(x), 50, mesh=mesh, label_axis="label"
        )
        _assert_matches(self, got, x, 50)

    def test_ragged_label_tiles(self):
        # L with no relation to the shard count (incl. prime): the in-shard
        # validity mask + sentinel discipline keeps parity exact
        mesh = _mesh()
        for l in (10007, 1000, 130):
            x = RNG.integers(0, 5, (7, l)).astype(np.float32)
            got = sharded_label_topk(
                jnp.asarray(x), 6, mesh=mesh, label_axis="label"
            )
            _assert_matches(self, got, x, 6, f"L={l}")

    def test_forced_pallas_local_kernel(self):
        # the REAL per-shard streaming kernel in interpret mode off-TPU
        mesh = _mesh()
        x = RNG.integers(0, 5, (16, 2048)).astype(np.float32)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(None, "label"))
        )
        _assert_matches(
            self, sharded_label_topk(xs, 7, method="pallas"), x, 7
        )

    def test_multi_axis_batch_by_label_mesh(self):
        # batch sharding composes with label sharding: rows stay sharded
        # over "data", the candidate exchange runs over "label" only
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs.reshape(2, 4), ("data", "label"))
        x = RNG.random((16, 1536), dtype=np.float32)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P("data", "label"))
        )
        got = sharded_label_topk(xs, 3)
        _assert_matches(self, got, x, 3)
        # outputs keep the row sharding (the label axis is gone)
        self.assertEqual(
            got[0].sharding.spec[0], "data", got[0].sharding
        )

    def test_explicit_mesh_keeps_batch_sharding(self):
        # regression (review finding): batch_axes must derive from the
        # committed operand even when mesh/label_axis are passed
        # explicitly — otherwise the shard_map in_spec replicates the rows
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs.reshape(2, 4), ("data", "label"))
        x = RNG.random((16, 1536), dtype=np.float32)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P("data", "label"))
        )
        got = sharded_label_topk(xs, 3, mesh=mesh, label_axis="label")
        _assert_matches(self, got, x, 3)
        self.assertEqual(got[0].sharding.spec[0], "data", got[0].sharding)
        # and the fully-explicit 3-way spelling (the tracer/metric path)
        got = sharded_label_topk(
            xs, 3, mesh=mesh, label_axis="label", batch_axes="data"
        )
        _assert_matches(self, got, x, 3)
        self.assertEqual(got[0].sharding.spec[0], "data", got[0].sharding)

    def test_auto_pick_stays_dense_for_non_f32(self):
        # regression (review finding): the sharded engine selects in f32,
        # so wide integers (distinct ints that collapse in f32) must keep
        # the exact dense path under auto
        mesh = _mesh()
        x = (np.arange(8 * 2048, dtype=np.int32) + (1 << 25)).reshape(8, 2048)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(None, "label"))
        )
        v, i = topk(xs, 3)  # auto: non-f32 → dense, never the f32 merge
        rv, ri = jax.lax.top_k(jnp.asarray(x), 3)
        self.assertEqual(v.dtype, jnp.int32)
        np.testing.assert_array_equal(np.asarray(v), np.asarray(rv))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ri))

    def test_unknown_label_axis_raises(self):
        with self.assertRaisesRegex(ValueError, "not an axis"):
            sharded_label_topk(
                jnp.zeros((4, 64)), 2, mesh=_mesh(), label_axis="lable"
            )

    def test_gather_companion(self):
        # the retrieval-metric path: relevance gathered at the selected
        # indices INSIDE each shard, returned in merge order
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs.reshape(2, 4), ("data", "label"))
        sh = NamedSharding(mesh, P("data", "label"))
        x = RNG.random((16, 1536), dtype=np.float32)
        t = (RNG.random((16, 1536)) > 0.9).astype(np.float32)
        _v, i, g = sharded_label_topk(
            jax.device_put(jnp.asarray(x), sh),
            3,
            gather=jax.device_put(jnp.asarray(t), sh),
        )
        _rv, ri = _ref(x, 3)
        np.testing.assert_array_equal(
            np.asarray(g), np.take_along_axis(t, ri, axis=1)
        )


class TestNoReplicationHLO(unittest.TestCase):
    """The acceptance observable: the compiled program may exchange ONLY the
    O(k·shards) candidate columns — no all-gather whose result approaches
    the full label width, and no other full-width collective."""

    def test_all_gathers_are_candidate_sized(self):
        mesh = _mesh()
        n, l, k = 13, 4096, 5
        shards = len(jax.devices())
        fn = jax.jit(
            lambda a: sharded_label_topk(
                a, k, mesh=mesh, label_axis="label"
            )
        )
        hlo = (
            fn.lower(jax.ShapeDtypeStruct((n, l), jnp.float32))
            .compile()
            .as_text()
        )
        gathers = re.findall(r"\[([0-9,]+)\][^\n]*? all-gather", hlo)
        self.assertTrue(gathers, "expected the candidate all-gather in HLO")
        budget = n * shards * k  # elements per candidate column
        for dims in gathers:
            elems = int(np.prod([int(d) for d in dims.split(",")]))
            self.assertLessEqual(
                elems,
                budget,
                f"an all-gather result of shape [{dims}] exceeds the "
                f"candidate exchange budget ({budget} elements) — the "
                "label axis is being replicated",
            )
        # and nothing else moves the full operand either
        self.assertNotIn("all-to-all", hlo)

    def test_engine_auto_pick_engages_on_label_sharded_operand(self):
        from torcheval_tpu import obs

        mesh = _mesh()
        x = RNG.random((8, 2048), dtype=np.float32)
        xs = jax.device_put(
            jnp.asarray(x), NamedSharding(mesh, P(None, "label"))
        )
        self.assertIsNotNone(label_sharding_of(xs))
        obs.enable()
        obs.reset()
        try:
            got = topk(xs, 4)  # auto → sharded_label
            _assert_matches(self, got, x, 4)
            counters = obs.snapshot()["counters"]
            self.assertEqual(
                counters.get("ops.topk.calls{path=sharded_label}"), 1.0
            )
            shards = len(jax.devices())
            # (f32 value + i32 index) per candidate, k·shards per row
            self.assertEqual(
                counters.get("ops.topk.merge_bytes"),
                float(8 * shards * 4 * 8),
            )
            gauges = obs.snapshot()["gauges"]
            per_dev = gauges.get(
                "ops.topk.label_bytes_per_device{path=sharded_label}"
            )
            self.assertEqual(per_dev, float(8 * (2048 // shards) * 4))
            # the dense pick on the same UNSHARDED operand records the full
            # label width — the ~1/shards ratio the bench leg asserts
            topk(jnp.asarray(x), 4)
            gauges = obs.snapshot()["gauges"]
            dense = gauges.get(
                "ops.topk.label_bytes_per_device{path=dense}"
            )
            self.assertEqual(dense, float(8 * 2048 * 4))
            self.assertAlmostEqual(per_dev / dense, 1.0 / shards)
        finally:
            obs.disable()
            obs.reset()

    def test_validation(self):
        with self.assertRaisesRegex(ValueError, "label-sharded"):
            sharded_label_topk(jnp.zeros((4, 64)), 2)  # no mesh, unsharded
        mesh = _mesh()
        with self.assertRaises(ValueError):
            sharded_label_topk(
                jnp.zeros((4, 64)), 0, mesh=mesh, label_axis="label"
            )
        with self.assertRaises(ValueError):
            sharded_label_topk(
                jnp.zeros((4, 64)), 2, mesh=mesh, label_axis="label",
                method="radix",
            )
        with self.assertRaisesRegex(ValueError, "gather"):
            sharded_label_topk(
                jnp.zeros((4, 64)), 2, mesh=mesh, label_axis="label",
                gather=jnp.zeros((4, 32)),
            )


if __name__ == "__main__":
    unittest.main()

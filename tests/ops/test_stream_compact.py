"""Stream-compaction kernel tests (interpret mode — runs the exact kernel
algorithm on CPU; the compiled Mosaic path differs only in lowering).

Round-4 verdict weak #1/#2: the kernel shipped with zero coverage and a
0*NaN lane-poisoning bug in the boundary tile. These tests pin the fix:
every case asserts bit-equality with the two-sort ``compact_counts``
formulation, which is itself pinned against the reference in the curve
parity suites. Reference behavior being replaced: boolean-mask compaction,
``torcheval/metrics/functional/classification/auroc.py:50-67``.
"""

import unittest

import jax.numpy as jnp
import numpy as np
from sklearn.metrics import roc_auc_score

import torcheval_tpu.metrics.classification.auroc as auroc_mod
from torcheval_tpu.metrics import BinaryAUPRC, BinaryAUROC
from torcheval_tpu.ops.stream_compact import (
    combine_f32_bits,
    combine_i32,
    compact_summary_rows,
    split_f32_bits,
    split_i32,
    stream_compact,
)
from torcheval_tpu.ops.summary import compact_counts, compact_counts_fast


def _assert_fast_matches_reference(tc, s, tp, fp):
    """compact_counts_fast(interpret) must match compact_counts bit-for-bit:
    same live rows, same counts, NaN padding, same n_unique/nan_dropped."""
    s, tp, fp = jnp.asarray(s), jnp.asarray(tp), jnp.asarray(fp)
    rs, rtp, rfp, rn, rnan = (np.asarray(a) for a in compact_counts(s, tp, fp))
    fs, ftp, ffp, fn, fnan = (
        np.asarray(a) for a in compact_counts_fast(s, tp, fp, interpret=True)
    )
    tc.assertEqual(int(rn), int(fn))
    tc.assertEqual(int(rnan), int(fnan))
    nl = int(fn)
    tc.assertEqual(int(np.isnan(fs[:nl]).sum()), 0, "NaN leaked into live rows")
    np.testing.assert_array_equal(rs[:nl], fs[:nl])
    tc.assertTrue(np.all(np.isnan(fs[nl:])), "padding rows must be NaN")
    np.testing.assert_array_equal(rtp, ftp)
    np.testing.assert_array_equal(rfp, ffp)


class TestStreamCompactPrimitive(unittest.TestCase):
    """The generic compress-to-front primitive."""

    def test_basic_stable_order(self):
        mask = np.array([0, 1, 1, 0, 1] + [0] * 251, np.float32)
        col = np.arange(256, dtype=np.float32)
        (out,), n_live = stream_compact(
            jnp.asarray(mask), [jnp.asarray(col)], interpret=True
        )
        self.assertEqual(int(n_live), 3)
        np.testing.assert_array_equal(np.asarray(out)[:3], [1.0, 2.0, 4.0])

    def test_dead_lane_nan_inf_ignored(self):
        # NaN/±inf in DEAD lanes must not poison the live lanes sharing
        # their 128-lane tile (the round-4 bug: 0 * NaN = NaN in the
        # permutation matmul)
        n = 256
        mask = np.zeros(n, np.float32)
        mask[:100] = 1.0  # boundary tile: lanes 0-99 live, 100-127 dead
        col = np.full(n, np.nan, np.float32)
        col[:100] = np.arange(100, dtype=np.float32)
        col[100:128] = np.inf  # adjacency: dead lanes IN the live tile
        (out,), n_live = stream_compact(
            jnp.asarray(mask), [jnp.asarray(col)], interpret=True
        )
        self.assertEqual(int(n_live), 100)
        got = np.asarray(out)[:100]
        self.assertEqual(int(np.isnan(got).sum()), 0)
        np.testing.assert_array_equal(got, np.arange(100, dtype=np.float32))

    def test_multi_column_multi_tile(self):
        rng = np.random.default_rng(7)
        n = 1000
        mask = (rng.random(n) < 0.6).astype(np.float32)
        cols = [rng.random(n).astype(np.float32) for _ in range(3)]
        outs, n_live = stream_compact(
            jnp.asarray(mask), [jnp.asarray(c) for c in cols], interpret=True
        )
        nl = int(n_live)
        self.assertEqual(nl, int(mask.sum()))
        for c, out in zip(cols, outs):
            np.testing.assert_array_equal(np.asarray(out)[:nl], c[mask > 0])

    def test_multi_chunk_dma_flushes(self):
        # > 2 staging chunks (_CHUNK = 2048) and > 1 grid block (_BLOCK =
        # 8192): exercises the flush path, the slack-row carry-down, and the
        # double-buffered DMA waits
        rng = np.random.default_rng(8)
        n = 16384
        mask = (rng.random(n) < 0.8).astype(np.float32)
        col = rng.random(n).astype(np.float32)
        (out,), n_live = stream_compact(
            jnp.asarray(mask), [jnp.asarray(col)], interpret=True
        )
        nl = int(n_live)
        self.assertEqual(nl, int(mask.sum()))
        self.assertGreater(nl, 3 * 2048)  # really crossed several chunks
        np.testing.assert_array_equal(np.asarray(out)[:nl], col[mask > 0])

    def test_too_many_columns_raises(self):
        mask = jnp.ones((128,), jnp.float32)
        cols = [jnp.zeros((128,), jnp.float32)] * 8
        with self.assertRaisesRegex(ValueError, "at most"):
            stream_compact(mask, cols, interpret=True)

    def test_all_dead_and_all_live(self):
        col = jnp.arange(256, dtype=jnp.float32)
        _, n0 = stream_compact(
            jnp.zeros((256,), jnp.float32), [col], interpret=True
        )
        self.assertEqual(int(n0), 0)
        (out,), n1 = stream_compact(
            jnp.ones((256,), jnp.float32), [col], interpret=True
        )
        self.assertEqual(int(n1), 256)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(col))


class TestBitTransport(unittest.TestCase):
    """Exact 16-bit-halves transport for i32 counts and f32 raw bits."""

    def test_split_combine_i32(self):
        x = jnp.asarray([0, 1, 65535, 65536, 2**24 + 3, 2**31 - 1], jnp.int32)
        hi, lo = split_i32(x)
        self.assertTrue(bool(jnp.all(hi < 65536)) and bool(jnp.all(lo < 65536)))
        np.testing.assert_array_equal(
            np.asarray(combine_i32(hi, lo)), np.asarray(x)
        )

    def test_split_combine_f32_bits_total(self):
        # total over f32: NaN, ±inf, -0.0, denormals all round-trip
        vals = np.array(
            [0.0, -0.0, 1.5, -1e38, 1e-40, np.inf, -np.inf, np.nan],
            np.float32,
        )
        hi, lo = split_f32_bits(jnp.asarray(vals))
        self.assertTrue(bool(jnp.all(hi < 65536)) and bool(jnp.all(lo < 65536)))
        self.assertTrue(bool(jnp.all(jnp.isfinite(hi))))
        back = np.asarray(combine_f32_bits(hi, lo))
        np.testing.assert_array_equal(
            back.view(np.uint32), vals.view(np.uint32)
        )


class TestCompactCountsFastParity(unittest.TestCase):
    """compact_counts_fast == compact_counts, bit-for-bit, on every shape of
    input the fold pipeline produces."""

    def test_boundary_tile_with_nan_padding(self):
        # the confirmed round-4 repro: 200 live rows (200 % 128 = 72 in the
        # straddling tile) followed by NaN padding
        rng = np.random.default_rng(0)
        n_live, n = 200, 1024
        s = np.full(n, np.nan, np.float32)
        s[:n_live] = np.sort(rng.random(n_live).astype(np.float32))[::-1]
        tp = np.zeros(n, np.int32)
        fp = np.zeros(n, np.int32)
        tp[:n_live] = rng.integers(0, 5, n_live)
        fp[:n_live] = rng.integers(0, 5, n_live)
        _assert_fast_matches_reference(self, s, tp, fp)

    def test_every_boundary_phase(self):
        # live counts hitting several phases of the 128-lane tile, incl. the
        # exact-multiple case
        rng = np.random.default_rng(1)
        for n_live in (1, 127, 128, 129, 255, 256, 300):
            n = 512
            s = np.full(n, np.nan, np.float32)
            s[:n_live] = -np.sort(-rng.random(n_live).astype(np.float32))
            tp = np.zeros(n, np.int32)
            tp[:n_live] = 1
            fp = np.zeros(n, np.int32)
            _assert_fast_matches_reference(self, s, tp, fp)

    def test_pos_and_neg_inf_scores(self):
        # ±inf are legal scores (log-probs); they must survive the MXU via
        # the raw-bits transport and order correctly
        s = np.array([np.inf, 3.0, 0.5, -np.inf] * 16, np.float32)
        tp = np.ones(64, np.int32)
        fp = np.ones(64, np.int32)
        _assert_fast_matches_reference(self, s, tp, fp)

    def test_counts_above_2_16(self):
        # per-row aggregated counts past 65536: exactness of the u16-halves
        # transport under the bf16x3 matmul
        s = np.repeat(np.linspace(1, 0, 8).astype(np.float32), 32)
        tp = np.full(256, 3_000_000 // 32, np.int32)
        fp = np.full(256, 123_456 // 32, np.int32)
        _assert_fast_matches_reference(self, s, tp, fp)

    def test_nan_scored_samples_counted(self):
        # NaN SAMPLES (not padding) are dropped and counted identically
        s = np.array([0.9, np.nan, 0.4, np.nan, 0.1] * 8, np.float32)
        tp = np.ones(40, np.int32)
        fp = np.zeros(40, np.int32)
        _assert_fast_matches_reference(self, s, tp, fp)

    def test_random_streams_with_ties(self):
        rng = np.random.default_rng(2)
        for seed in range(3):
            n = 4096
            s = (rng.random(n) * 50).astype(np.int32) / 50.0  # heavy ties
            tp = rng.integers(0, 3, n).astype(np.int32)
            fp = rng.integers(0, 3, n).astype(np.int32)
            _assert_fast_matches_reference(self, s.astype(np.float32), tp, fp)

    def test_multi_chunk_fold(self):
        # a fold big enough for many staging flushes and 2+ grid blocks
        rng = np.random.default_rng(3)
        n = 16384
        s = rng.random(n).astype(np.float32)
        tp = rng.integers(0, 2, n).astype(np.int32)
        fp = 1 - tp
        _assert_fast_matches_reference(self, s, tp, fp)


class _InterpretModeMixin:
    """Force the integrated fold pipeline onto the Pallas kernel (interpret
    mode) for the duration of a test — the exact code path the 1B TPU bench
    takes, algorithmically, on CPU."""

    def setUp(self):
        self._saved = auroc_mod.STREAM_COMPACTION
        auroc_mod.STREAM_COMPACTION = "interpret"

    def tearDown(self):
        auroc_mod.STREAM_COMPACTION = self._saved


class TestIntegratedFastPath(_InterpretModeMixin, unittest.TestCase):
    """BinaryAUROC/AUPRC with compaction_threshold riding the streaming
    kernel AND the presorted compute kernels end to end."""

    def _data(self, n=4000):
        rng = np.random.default_rng(11)
        x = (rng.random(n) * 200).astype(np.int32) / 200.0  # forced ties
        t = (rng.random(n) < 0.35).astype(np.float32)
        return x.astype(np.float32), t

    def test_auroc_stream_compaction_parity(self):
        x, t = self._data()
        m = BinaryAUROC(compaction_threshold=500)
        for i in range(0, len(x), 250):
            m.update(x[i : i + 250], t[i : i + 250])
        self.assertTrue(m.summary_scores)
        # the presorted (sort-free) compute path must actually be taken
        self.assertIsNotNone(m._presorted_summary())
        self.assertAlmostEqual(float(m.compute()), roc_auc_score(t, x), places=6)

    def test_auprc_stream_compaction_parity(self):
        from sklearn.metrics import average_precision_score

        x, t = self._data()
        m = BinaryAUPRC(compaction_threshold=700)
        for i in range(0, len(x), 350):
            m.update(x[i : i + 350], t[i : i + 350])
        # raw leftovers (4000 % 700 != 0) keep the fused-sort path — a
        # compute-time forced compaction measured SLOWER than the sort
        self.assertTrue(m.inputs)
        self.assertIsNone(m._presorted_summary())
        self.assertAlmostEqual(
            float(m.compute()), average_precision_score(t, x), places=5
        )
        # once the state IS a lone compacted summary, compute rides the
        # sort-free kernel
        m._prepare_for_merge_state()
        self.assertIsNotNone(m._presorted_summary())
        self.assertAlmostEqual(
            float(m.compute()), average_precision_score(t, x), places=5
        )

    def test_neg_inf_scores_survive_fast_compaction(self):
        # the TPU-path twin of test_curve_classes.py::
        # test_neg_inf_scores_survive_compaction — would have caught the
        # round-4 bug before it shipped
        x = np.array([0.9, -np.inf, 0.4, -np.inf, 0.1, 0.7] * 4, np.float32)
        t = np.array([1, 1, 0, 0, 0, 1] * 4, np.float32)
        raw, comp = BinaryAUROC(), BinaryAUROC(compaction_threshold=6)
        raw.update(x, t)
        for i in range(0, len(x), 6):
            comp.update(x[i : i + 6], t[i : i + 6])
        self.assertAlmostEqual(
            float(comp.compute()), float(raw.compute()), places=6
        )

    def test_refold_over_stored_summary(self):
        # repeated compactions re-fold the NaN-padded summary buffer — the
        # exact boundary-tile adjacency that corrupted round 4's 1B run
        x, t = self._data(2000)
        m = BinaryAUROC(compaction_threshold=300)
        for i in range(0, len(x), 100):
            m.update(x[i : i + 100], t[i : i + 100])
        for _ in range(3):
            m._compact()  # refold: summary + NaN padding through the kernel
        self.assertAlmostEqual(float(m.compute()), roc_auc_score(t, x), places=6)

    def test_nan_samples_still_raise(self):
        m = BinaryAUROC(compaction_threshold=10)
        x = np.linspace(0, 1, 20).astype(np.float32)
        x[3] = np.nan
        m.update(jnp.asarray(x), jnp.asarray((x > 0.5).astype(np.float32)))
        with self.assertRaisesRegex(ValueError, "NaN scores reached"):
            m.compute()

    def test_merge_then_compute(self):
        x, t = self._data(2000)
        a = BinaryAUROC(compaction_threshold=300)
        a.update(x[:1000], t[:1000])
        b = BinaryAUROC(compaction_threshold=300)
        b.update(x[1000:], t[1000:])
        a.merge_state([b])
        self.assertAlmostEqual(float(a.compute()), roc_auc_score(t, x), places=6)


class TestPresortedKernels(unittest.TestCase):
    """Direct coverage for the sort-free compute kernels (round-4 weak #3)."""

    def _summary(self):
        rng = np.random.default_rng(5)
        s = rng.random(500).astype(np.float32)
        tp = rng.integers(0, 4, 500).astype(np.int32)
        fp = rng.integers(0, 4, 500).astype(np.int32)
        return compact_counts(jnp.asarray(s), jnp.asarray(tp), jnp.asarray(fp))

    def test_presorted_auroc_matches_sorting_kernel(self):
        from torcheval_tpu.ops.curves import (
            binary_auroc_counts_kernel,
            binary_auroc_counts_presorted_kernel,
        )

        s, tp, fp, _, _ = self._summary()
        self.assertAlmostEqual(
            float(binary_auroc_counts_presorted_kernel(s, tp, fp)),
            float(binary_auroc_counts_kernel(s, tp, fp)),
            places=6,
        )

    def test_presorted_auprc_matches_sorting_kernel(self):
        from torcheval_tpu.ops.curves import (
            binary_auprc_counts_kernel,
            binary_auprc_counts_presorted_kernel,
        )

        s, tp, fp, _, _ = self._summary()
        self.assertAlmostEqual(
            float(binary_auprc_counts_presorted_kernel(s, tp, fp)),
            float(binary_auprc_counts_kernel(s, tp, fp)),
            places=6,
        )

    def test_presorted_empty_inputs(self):
        from torcheval_tpu.ops.curves import (
            binary_auprc_counts_presorted_kernel,
            binary_auroc_counts_presorted_kernel,
        )

        e = jnp.zeros((0,), jnp.float32)
        z = jnp.zeros((0,), jnp.int32)
        self.assertEqual(float(binary_auroc_counts_presorted_kernel(e, z, z)), 0.5)
        self.assertEqual(float(binary_auprc_counts_presorted_kernel(e, z, z)), 0.0)


if __name__ == "__main__":
    unittest.main()

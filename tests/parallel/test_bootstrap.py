"""Unit tests for the multi-host bootstrap helper.

The real multi-process join (4 OS processes over jax.distributed) is covered
by ``tests/metrics/test_multiprocess_sync.py``, whose worker now boots through
``init_from_env`` with torch-elastic env vars. Here: the env-resolution logic
and the single-process fallbacks, which need no cluster.
"""

import os
import sys
import unittest
from unittest import mock

import jax

from torcheval_tpu.parallel import init_from_env, is_initialized
from torcheval_tpu.parallel.bootstrap import _resolve_env


class TestResolveEnv(unittest.TestCase):
    def test_jax_style(self):
        env = {
            "COORDINATOR_ADDRESS": "10.0.0.1:1234",
            "NUM_PROCESSES": "8",
            "PROCESS_ID": "3",
        }
        self.assertEqual(_resolve_env(env), ("10.0.0.1:1234", 8, 3))

    def test_torch_elastic_style(self):
        env = {
            "MASTER_ADDR": "head-node",
            "MASTER_PORT": "29500",
            "WORLD_SIZE": "4",
            "RANK": "1",
        }
        self.assertEqual(_resolve_env(env), ("head-node:29500", 4, 1))

    def test_jax_style_wins_over_elastic(self):
        env = {
            "COORDINATOR_ADDRESS": "jax-coord:1",
            "MASTER_ADDR": "torch-coord",
            "MASTER_PORT": "2",
            "NUM_PROCESSES": "16",
            "WORLD_SIZE": "4",
            "PROCESS_ID": "5",
            "RANK": "1",
        }
        self.assertEqual(_resolve_env(env), ("jax-coord:1", 16, 5))

    def test_master_addr_without_port_raises(self):
        with self.assertRaisesRegex(ValueError, "MASTER_ADDR and MASTER_PORT"):
            _resolve_env({"MASTER_ADDR": "head-node"})
        with self.assertRaisesRegex(ValueError, "MASTER_ADDR and MASTER_PORT"):
            _resolve_env({"MASTER_PORT": "29500"})

    def test_empty_env(self):
        self.assertEqual(_resolve_env({}), (None, None, None))

    def test_non_integer_raises(self):
        with self.assertRaisesRegex(ValueError, "WORLD_SIZE='four'"):
            _resolve_env({"WORLD_SIZE": "four"})


class TestAutoDetectable(unittest.TestCase):
    """_auto_detectable delegates to jax's own cluster probes (which read the
    real ``os.environ``), so these tests patch the process environment."""

    def test_this_single_host_environment_is_not_a_cluster(self):
        from torcheval_tpu.parallel.bootstrap import _auto_detectable

        # the regression this guards: single-host TPU VMs export
        # TPU_WORKER_HOSTNAMES=localhost, which must not look like a pod
        self.assertFalse(_auto_detectable())

    @mock.patch.dict(
        os.environ,
        {
            "SLURM_JOB_ID": "1234",
            "SLURM_STEP_NODELIST": "node[0-3]",
            "SLURM_NTASKS": "4",
            "SLURM_PROCID": "0",
            "SLURM_LOCALID": "0",
        },
        clear=True,
    )
    def test_multiprocess_slurm_allocation_is_detected(self):
        from torcheval_tpu.parallel.bootstrap import _auto_detectable

        self.assertTrue(_auto_detectable())

    @mock.patch.dict(
        os.environ,
        {
            "SLURM_JOB_ID": "1234",
            "SLURM_STEP_NODELIST": "node0",
            "SLURM_NTASKS": "1",
            "SLURM_PROCID": "0",
            "SLURM_LOCALID": "0",
        },
        clear=True,
    )
    def test_single_process_slurm_allocation_is_not_a_cluster(self):
        # a probe that is "present" but resolves world size 1 has nothing to
        # join (same filter keeps mere-package-presence probes like mpi4py out)
        from torcheval_tpu.parallel.bootstrap import _auto_detectable

        self.assertFalse(_auto_detectable())

    def test_fallback_heuristic_when_probes_unavailable(self):
        from torcheval_tpu.parallel import bootstrap

        fb = bootstrap._fallback_auto_detect
        self.assertFalse(fb({"TPU_WORKER_HOSTNAMES": "localhost"}))
        self.assertTrue(fb({"TPU_WORKER_HOSTNAMES": "host0,host1"}))
        self.assertFalse(fb({"SLURM_NTASKS": "1"}))
        self.assertTrue(fb({"SLURM_NTASKS": "8"}))
        self.assertFalse(fb({}))

        # the probe-API-moved path routes to the fallback
        with mock.patch.dict(sys.modules, {"jax._src.clusters": None}):
            self.assertFalse(bootstrap._auto_detectable())


class TestInitFromEnvSingleProcess(unittest.TestCase):
    # the ambient environment must not leak in: a stale torchrun shell's
    # WORLD_SIZE/RANK (or a SLURM allocation) would otherwise send these
    # tests down the real-initialize path
    @mock.patch.dict(os.environ, {}, clear=True)
    def test_no_coordinator_stays_single_process(self):
        # conftest never initializes jax.distributed, and this test must not
        # either: with nothing configured the helper is a pure no-op
        self.assertFalse(is_initialized())
        self.assertEqual(init_from_env(), (0, 1))
        self.assertFalse(is_initialized())

    @mock.patch.dict(os.environ, {}, clear=True)
    def test_world_size_without_coordinator_raises(self):
        with self.assertRaisesRegex(ValueError, "no coordinator"):
            init_from_env(num_processes=4)

    @mock.patch.dict(
        os.environ, {"WORLD_SIZE": "4", "RANK": "3"}, clear=True
    )
    def test_rank_without_coordinator_raises(self):
        # half-configured launcher: every worker silently becoming rank 0 of 1
        # is the failure mode this guard exists for
        with self.assertRaisesRegex(ValueError, "no coordinator"):
            init_from_env()

    @mock.patch.dict(
        os.environ, {"WORLD_SIZE": "1", "RANK": "0"}, clear=True
    )
    def test_consistent_single_process_env_stays_single_process(self):
        # RANK=0/WORLD_SIZE=1 is a common container default, not a
        # misconfiguration — must not raise
        self.assertEqual(init_from_env(), (0, 1))
        self.assertFalse(is_initialized())


_RETRY_ENV = {
    "MASTER_ADDR": "localhost",
    "MASTER_PORT": "29999",
    "WORLD_SIZE": "4",
    "RANK": "1",
}


@mock.patch.dict(os.environ, _RETRY_ENV, clear=True)
class TestConnectRetry(unittest.TestCase):
    """ISSUE 5: coordinator connection retries with bounded exponential
    backoff + jitter. ``jax.distributed.initialize`` is mocked — the real
    multi-process join is covered by the mp test workers — so these pin the
    retry policy itself: which errors retry, how many times, how long the
    sleeps are, and the ``bootstrap.retries`` obs counter.

    ``_enable_cpu_collectives`` is stubbed: with initialize mocked there is
    no distributed client, and selecting gloo without one poisons CPU
    backend creation for the rest of the process."""

    def setUp(self):
        from torcheval_tpu.parallel import bootstrap

        p = mock.patch.object(
            bootstrap, "_enable_cpu_collectives", lambda: None
        )
        p.start()
        self.addCleanup(p.stop)

    def test_connection_failure_retries_then_succeeds(self):
        from torcheval_tpu import obs
        from torcheval_tpu.parallel import bootstrap

        sleeps = []
        calls = {"n": 0}

        def flaky(**kwargs):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("failed to connect to coordinator")

        obs.enable()
        try:
            obs.reset()
            with mock.patch.object(jax.distributed, "initialize", flaky), \
                    mock.patch.object(bootstrap.time, "sleep", sleeps.append):
                got = init_from_env(connect_backoff_s=1.0)
            snap = obs.snapshot()["counters"]
        finally:
            obs.disable()
            obs.reset()
        self.assertEqual(calls["n"], 3)
        self.assertEqual(got, (jax.process_index(), jax.process_count()))
        self.assertEqual(snap["bootstrap.retries"], 2.0)
        # exponential base with 0.5-1.5x jitter: 1s then 2s nominal
        self.assertEqual(len(sleeps), 2)
        self.assertTrue(0.5 <= sleeps[0] <= 1.5, sleeps)
        self.assertTrue(1.0 <= sleeps[1] <= 3.0, sleeps)

    def test_failed_attempt_resets_partial_init_before_retry(self):
        # the runtime assigns its client object BEFORE the connection
        # attempt, so a connect failure leaves is_initialized() true and a
        # naive retry raises "should only be called once" forever — each
        # failed attempt must clear that state before the next initialize
        from jax._src.distributed import global_state

        self.addCleanup(setattr, global_state, "client", None)
        self.addCleanup(setattr, global_state, "service", None)
        from torcheval_tpu.parallel import bootstrap

        calls = {"n": 0}
        sentinel = object()

        def flaky(**kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                global_state.client = sentinel  # half-initialized, then fail
                raise RuntimeError("failed to connect")
            # the retry must arrive with the partial state cleared, exactly
            # as the real initialize requires (it raises "called once"
            # whenever client is already set)
            assert global_state.client is None, "partial init not reset"

        with mock.patch.object(jax.distributed, "initialize", flaky), \
                mock.patch.object(bootstrap.time, "sleep", lambda s: None):
            init_from_env()
        self.assertEqual(calls["n"], 2)
        self.assertFalse(is_initialized())

    def test_gives_up_after_bounded_attempts_with_original_error(self):
        from torcheval_tpu.parallel import bootstrap

        sleeps = []
        with mock.patch.object(
            jax.distributed,
            "initialize",
            side_effect=RuntimeError("coordinator unreachable"),
        ), mock.patch.object(bootstrap.time, "sleep", sleeps.append):
            with self.assertRaisesRegex(RuntimeError, "coordinator unreachable"):
                init_from_env(connect_attempts=3)
        self.assertEqual(len(sleeps), 2)  # attempts - 1 backoffs

    def test_configuration_errors_never_retry(self):
        from torcheval_tpu.parallel import bootstrap

        sleeps = []
        with mock.patch.object(
            jax.distributed,
            "initialize",
            side_effect=ValueError("bad coordinator_address"),
        ), mock.patch.object(bootstrap.time, "sleep", sleeps.append):
            with self.assertRaises(ValueError):
                init_from_env()
        self.assertEqual(sleeps, [])

    def test_attempts_env_override(self):
        from torcheval_tpu.parallel import bootstrap

        sleeps = []
        with mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_CONNECT_ATTEMPTS": "1"}
        ), mock.patch.object(
            jax.distributed,
            "initialize",
            side_effect=RuntimeError("nope"),
        ), mock.patch.object(bootstrap.time, "sleep", sleeps.append):
            with self.assertRaises(RuntimeError):
                init_from_env()
        self.assertEqual(sleeps, [])  # one attempt, no backoff

    def test_backoff_cap(self):
        from torcheval_tpu.parallel import bootstrap

        sleeps = []
        with mock.patch.object(
            jax.distributed,
            "initialize",
            side_effect=RuntimeError("down"),
        ), mock.patch.object(bootstrap.time, "sleep", sleeps.append):
            with self.assertRaises(RuntimeError):
                init_from_env(connect_attempts=4, connect_backoff_s=100.0)
        # every nominal delay (100, 200, 400) is capped at 30s pre-jitter
        for s in sleeps:
            self.assertLessEqual(s, 30.0 * 1.5)

    def test_invalid_attempts_rejected(self):
        with self.assertRaisesRegex(ValueError, "connect_attempts"):
            init_from_env(connect_attempts=0)


if __name__ == "__main__":
    unittest.main()

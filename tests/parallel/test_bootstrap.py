"""Unit tests for the multi-host bootstrap helper.

The real multi-process join (4 OS processes over jax.distributed) is covered
by ``tests/metrics/test_multiprocess_sync.py``, whose worker now boots through
``init_from_env`` with torch-elastic env vars. Here: the env-resolution logic
and the single-process fallbacks, which need no cluster.
"""

import os
import sys
import unittest
from unittest import mock

from torcheval_tpu.parallel import init_from_env, is_initialized
from torcheval_tpu.parallel.bootstrap import _resolve_env


class TestResolveEnv(unittest.TestCase):
    def test_jax_style(self):
        env = {
            "COORDINATOR_ADDRESS": "10.0.0.1:1234",
            "NUM_PROCESSES": "8",
            "PROCESS_ID": "3",
        }
        self.assertEqual(_resolve_env(env), ("10.0.0.1:1234", 8, 3))

    def test_torch_elastic_style(self):
        env = {
            "MASTER_ADDR": "head-node",
            "MASTER_PORT": "29500",
            "WORLD_SIZE": "4",
            "RANK": "1",
        }
        self.assertEqual(_resolve_env(env), ("head-node:29500", 4, 1))

    def test_jax_style_wins_over_elastic(self):
        env = {
            "COORDINATOR_ADDRESS": "jax-coord:1",
            "MASTER_ADDR": "torch-coord",
            "MASTER_PORT": "2",
            "NUM_PROCESSES": "16",
            "WORLD_SIZE": "4",
            "PROCESS_ID": "5",
            "RANK": "1",
        }
        self.assertEqual(_resolve_env(env), ("jax-coord:1", 16, 5))

    def test_master_addr_without_port_raises(self):
        with self.assertRaisesRegex(ValueError, "MASTER_ADDR and MASTER_PORT"):
            _resolve_env({"MASTER_ADDR": "head-node"})
        with self.assertRaisesRegex(ValueError, "MASTER_ADDR and MASTER_PORT"):
            _resolve_env({"MASTER_PORT": "29500"})

    def test_empty_env(self):
        self.assertEqual(_resolve_env({}), (None, None, None))

    def test_non_integer_raises(self):
        with self.assertRaisesRegex(ValueError, "WORLD_SIZE='four'"):
            _resolve_env({"WORLD_SIZE": "four"})


class TestAutoDetectable(unittest.TestCase):
    """_auto_detectable delegates to jax's own cluster probes (which read the
    real ``os.environ``), so these tests patch the process environment."""

    def test_this_single_host_environment_is_not_a_cluster(self):
        from torcheval_tpu.parallel.bootstrap import _auto_detectable

        # the regression this guards: single-host TPU VMs export
        # TPU_WORKER_HOSTNAMES=localhost, which must not look like a pod
        self.assertFalse(_auto_detectable())

    @mock.patch.dict(
        os.environ,
        {
            "SLURM_JOB_ID": "1234",
            "SLURM_STEP_NODELIST": "node[0-3]",
            "SLURM_NTASKS": "4",
            "SLURM_PROCID": "0",
            "SLURM_LOCALID": "0",
        },
        clear=True,
    )
    def test_multiprocess_slurm_allocation_is_detected(self):
        from torcheval_tpu.parallel.bootstrap import _auto_detectable

        self.assertTrue(_auto_detectable())

    @mock.patch.dict(
        os.environ,
        {
            "SLURM_JOB_ID": "1234",
            "SLURM_STEP_NODELIST": "node0",
            "SLURM_NTASKS": "1",
            "SLURM_PROCID": "0",
            "SLURM_LOCALID": "0",
        },
        clear=True,
    )
    def test_single_process_slurm_allocation_is_not_a_cluster(self):
        # a probe that is "present" but resolves world size 1 has nothing to
        # join (same filter keeps mere-package-presence probes like mpi4py out)
        from torcheval_tpu.parallel.bootstrap import _auto_detectable

        self.assertFalse(_auto_detectable())

    def test_fallback_heuristic_when_probes_unavailable(self):
        from torcheval_tpu.parallel import bootstrap

        fb = bootstrap._fallback_auto_detect
        self.assertFalse(fb({"TPU_WORKER_HOSTNAMES": "localhost"}))
        self.assertTrue(fb({"TPU_WORKER_HOSTNAMES": "host0,host1"}))
        self.assertFalse(fb({"SLURM_NTASKS": "1"}))
        self.assertTrue(fb({"SLURM_NTASKS": "8"}))
        self.assertFalse(fb({}))

        # the probe-API-moved path routes to the fallback
        with mock.patch.dict(sys.modules, {"jax._src.clusters": None}):
            self.assertFalse(bootstrap._auto_detectable())


class TestInitFromEnvSingleProcess(unittest.TestCase):
    # the ambient environment must not leak in: a stale torchrun shell's
    # WORLD_SIZE/RANK (or a SLURM allocation) would otherwise send these
    # tests down the real-initialize path
    @mock.patch.dict(os.environ, {}, clear=True)
    def test_no_coordinator_stays_single_process(self):
        # conftest never initializes jax.distributed, and this test must not
        # either: with nothing configured the helper is a pure no-op
        self.assertFalse(is_initialized())
        self.assertEqual(init_from_env(), (0, 1))
        self.assertFalse(is_initialized())

    @mock.patch.dict(os.environ, {}, clear=True)
    def test_world_size_without_coordinator_raises(self):
        with self.assertRaisesRegex(ValueError, "no coordinator"):
            init_from_env(num_processes=4)

    @mock.patch.dict(
        os.environ, {"WORLD_SIZE": "4", "RANK": "3"}, clear=True
    )
    def test_rank_without_coordinator_raises(self):
        # half-configured launcher: every worker silently becoming rank 0 of 1
        # is the failure mode this guard exists for
        with self.assertRaisesRegex(ValueError, "no coordinator"):
            init_from_env()

    @mock.patch.dict(
        os.environ, {"WORLD_SIZE": "1", "RANK": "0"}, clear=True
    )
    def test_consistent_single_process_env_stays_single_process(self):
        # RANK=0/WORLD_SIZE=1 is a common container default, not a
        # misconfiguration — must not raise
        self.assertEqual(init_from_env(), (0, 1))
        self.assertFalse(is_initialized())


if __name__ == "__main__":
    unittest.main()

"""Sync deadline + degraded-mode unit tests (simulated world).

The real dead-rank hang is exercised in 4 OS processes by
``test_fault_injection.py``; here the collective layer is stubbed so every
policy/timeout edge runs in milliseconds: the watchdog fires and names the
round and lane, ``on_failure="local"`` degrades to local results with the
obs counter bumped, transport errors under a deadline wrap as
:class:`SyncRoundError`, and invalid arguments are rejected eagerly.
"""

import threading
import time
import unittest
from unittest import mock

import jax.numpy as jnp
import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy, Sum
from torcheval_tpu.metrics import toolkit
from torcheval_tpu.metrics.toolkit import (
    SyncError,
    SyncRoundError,
    SyncTimeoutError,
    get_synced_metric,
    get_synced_state_dict,
    sync_and_compute,
    sync_and_compute_collection,
)
from torcheval_tpu.utils.telemetry import reset_once_keys


def _hang(seconds):
    def impl(x, group):
        time.sleep(seconds)
        raise AssertionError("hung collective unexpectedly completed")

    return impl


class _SimulatedWorld(unittest.TestCase):
    """Patch the world to size 2 and stub the collective impl — the layers
    above (_allgather_stacked and the public APIs) run for real."""

    def setUp(self):
        patches = [
            mock.patch.object(toolkit, "_world_size", lambda: 2),
            mock.patch.object(toolkit, "_process_index", lambda: 0),
        ]
        for p in patches:
            p.start()
            self.addCleanup(p.stop)
        reset_once_keys("toolkit.sync.degraded")

    def _metric(self):
        m = Sum()
        m.update(jnp.asarray([4.0, 1.0]))
        return m


class TestTimeoutRaises(_SimulatedWorld):
    def test_timeout_names_round_and_lane(self):
        with mock.patch.object(toolkit, "_allgather_stacked_impl", _hang(3)):
            t0 = time.monotonic()
            with self.assertRaises(SyncTimeoutError) as ctx:
                sync_and_compute(self._metric(), recipient_rank="all", timeout_s=0.2)
            elapsed = time.monotonic() - t0
        self.assertLess(elapsed, 2.0)  # returned at the deadline, not the hang
        self.assertEqual(ctx.exception.round, "descriptor")
        self.assertEqual(ctx.exception.lane, "typed")
        self.assertEqual(ctx.exception.timeout_s, 0.2)
        self.assertIn("descriptor", str(ctx.exception))

    def test_object_lane_timeout_names_object_round(self):
        from torcheval_tpu.utils.test_utils import DummySumDictStateMetric

        d = DummySumDictStateMetric()
        d.update("k", 1.0)
        with mock.patch.object(toolkit, "_allgather_stacked_impl", _hang(3)):
            with self.assertRaises(SyncTimeoutError) as ctx:
                sync_and_compute(d, recipient_rank="all", timeout_s=0.2)
        self.assertEqual(ctx.exception.round, "object-length")
        self.assertEqual(ctx.exception.lane, "object")

    def test_budget_is_shared_across_rounds(self):
        # first round eats most of the budget; the second must not get a
        # fresh timeout_s (a per-round budget would wait ~2x the deadline)
        calls = {"n": 0}

        def slow_first(x, group):
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.35)
                # simulate a completed round so the sync proceeds to round 2
                return np.stack([x, x])
            time.sleep(10)
            raise AssertionError("unreachable")

        m = self._metric()
        with mock.patch.object(toolkit, "_allgather_stacked_impl", slow_first):
            t0 = time.monotonic()
            with self.assertRaises(SyncTimeoutError) as ctx:
                sync_and_compute(m, recipient_rank="all", timeout_s=0.5)
            elapsed = time.monotonic() - t0
        self.assertEqual(ctx.exception.round, "payload")
        self.assertLess(elapsed, 2.0)

    def test_transport_error_under_deadline_wraps_as_round_error(self):
        def boom(x, group):
            raise RuntimeError("connection reset by peer")

        with mock.patch.object(toolkit, "_allgather_stacked_impl", boom):
            with self.assertRaises(SyncRoundError) as ctx:
                sync_and_compute(self._metric(), recipient_rank=0, timeout_s=1.0)
        self.assertEqual(ctx.exception.round, "descriptor")
        self.assertIsInstance(ctx.exception.__cause__, RuntimeError)

    def test_no_deadline_keeps_original_error_type(self):
        # without timeout_s the pre-ISSUE-5 contract holds: errors pass
        # through unwrapped (and hangs hang — not testable here)
        def boom(x, group):
            raise RuntimeError("schema-adjacent failure")

        with mock.patch.object(toolkit, "_allgather_stacked_impl", boom):
            with self.assertRaises(RuntimeError) as ctx:
                sync_and_compute(self._metric(), recipient_rank=0)
        self.assertNotIsInstance(ctx.exception, SyncError)


class TestDegradedMode(_SimulatedWorld):
    def test_local_policy_returns_local_compute_and_counts(self):
        m = self._metric()
        obs.enable()
        try:
            obs.reset()
            with mock.patch.object(
                toolkit, "_allgather_stacked_impl", _hang(3)
            ):
                out = sync_and_compute(
                    m, recipient_rank="all", timeout_s=0.2, on_failure="local"
                )
            snap = obs.snapshot()["counters"]
        finally:
            obs.disable()
            obs.reset()
        self.assertEqual(float(out), 5.0)  # the LOCAL (unsynced) value
        self.assertEqual(snap["toolkit.sync.timeouts{policy=local}"], 1.0)

    def test_local_policy_returns_on_every_rank_even_non_recipient(self):
        # the recipient contract is unsatisfiable once the exchange failed;
        # each survivor's local state is the only data it still has
        m = self._metric()
        with mock.patch.object(toolkit, "_allgather_stacked_impl", _hang(3)):
            out = sync_and_compute(
                m, recipient_rank=1, timeout_s=0.2, on_failure="local"
            )
        self.assertEqual(float(out), 5.0)

    def test_get_synced_metric_local_returns_clone(self):
        m = self._metric()
        with mock.patch.object(toolkit, "_allgather_stacked_impl", _hang(3)):
            got = get_synced_metric(
                m, recipient_rank="all", timeout_s=0.2, on_failure="local"
            )
        self.assertIsNot(got, m)  # source never mutated / aliased
        self.assertEqual(float(got.compute()), 5.0)

    def test_get_synced_state_dict_local(self):
        m = self._metric()
        with mock.patch.object(toolkit, "_allgather_stacked_impl", _hang(3)):
            sd = get_synced_state_dict(
                m, recipient_rank="all", timeout_s=0.2, on_failure="local"
            )
        self.assertEqual(float(sd["weighted_sum"]), 5.0)

    def test_collection_local_degrades_every_member_uniformly(self):
        from torcheval_tpu.utils.test_utils import DummySumDictStateMetric

        acc = MulticlassAccuracy(num_classes=3)
        rng = np.random.default_rng(0)
        x = rng.random((16, 3)).astype(np.float32)
        t = rng.integers(0, 3, 16)
        acc.update(jnp.asarray(x), jnp.asarray(t))
        d = DummySumDictStateMetric()
        d.update("k", 2.0)
        s = self._metric()
        obs.enable()
        try:
            obs.reset()
            with mock.patch.object(
                toolkit, "_allgather_stacked_impl", _hang(3)
            ):
                out = sync_and_compute_collection(
                    {"acc": acc, "d": d, "s": s},
                    recipient_rank="all",
                    timeout_s=0.2,
                    on_failure="local",
                )
            snap = obs.snapshot()["counters"]
        finally:
            obs.disable()
            obs.reset()
        self.assertEqual(sorted(out), ["acc", "d", "s"])
        self.assertEqual(float(out["s"]), 5.0)
        self.assertEqual(float(out["d"]), 2.0)
        self.assertAlmostEqual(
            float(out["acc"]), float((x.argmax(1) == t).mean()), places=6
        )
        self.assertEqual(snap["toolkit.sync.timeouts{policy=local}"], 1.0)

    def test_raise_policy_still_counts(self):
        obs.enable()
        try:
            obs.reset()
            with mock.patch.object(
                toolkit, "_allgather_stacked_impl", _hang(3)
            ):
                with self.assertRaises(SyncTimeoutError):
                    sync_and_compute(
                        self._metric(), recipient_rank="all", timeout_s=0.2
                    )
            snap = obs.snapshot()["counters"]
        finally:
            obs.disable()
            obs.reset()
        self.assertEqual(snap["toolkit.sync.timeouts{policy=raise}"], 1.0)


class TestArgumentValidation(_SimulatedWorld):
    def test_bad_policy_rejected_eagerly(self):
        for api in (
            lambda: sync_and_compute(self._metric(), on_failure="retry"),
            lambda: get_synced_metric(self._metric(), on_failure="retry"),
            lambda: sync_and_compute_collection(
                {"s": self._metric()}, on_failure="retry"
            ),
        ):
            with self.assertRaisesRegex(ValueError, "on_failure"):
                api()

    def test_nonpositive_timeout_rejected(self):
        with mock.patch.object(
            toolkit, "_allgather_stacked_impl", _hang(0.01)
        ):
            with self.assertRaisesRegex(ValueError, "timeout_s"):
                sync_and_compute(self._metric(), timeout_s=0.0)

    def test_degenerate_timeouts_rejected_at_every_entry_point(self):
        """ISSUE 8 satellite: non-positive AND non-finite timeouts raise
        ``ValueError`` at the API boundary of all four sync entry points —
        BEFORE any collective or state mutation. ``nan`` is the sneaky
        one: it slips past a plain ``<= 0`` comparison and arms a watchdog
        whose every remaining-time computation is ``nan`` (neither fires
        nor guards); ``inf`` arms one that can never fire."""
        calls = {"n": 0}

        def counting_impl(x, group):
            calls["n"] += 1
            raise AssertionError("collective must not run")

        entry_points = (
            lambda t: sync_and_compute(self._metric(), timeout_s=t),
            lambda t: get_synced_metric(self._metric(), timeout_s=t),
            lambda t: get_synced_state_dict(self._metric(), timeout_s=t),
            lambda t: sync_and_compute_collection(
                {"s": self._metric()}, timeout_s=t
            ),
        )
        with mock.patch.object(
            toolkit, "_allgather_stacked_impl", counting_impl
        ):
            for api in entry_points:
                for bad in (0, -1.0, float("nan"), float("inf"), "5"):
                    with self.assertRaisesRegex(ValueError, "timeout_s"):
                        api(bad)
        self.assertEqual(calls["n"], 0)

    def test_valid_timeouts_still_accepted(self):
        # the boundary check must not over-reject: positive finite floats
        # and ints pass through, None means no deadline
        with mock.patch.object(
            toolkit,
            "_allgather_stacked_impl",
            lambda x, group: np.stack([np.asarray(x)] * 2),
        ):
            for ok in (5, 0.5, None):
                self.assertIsNotNone(
                    sync_and_compute(
                        self._metric(), recipient_rank="all", timeout_s=ok
                    )
                )

    def test_watchdog_thread_is_daemonic(self):
        # a timed-out collective leaves its watchdog thread blocked inside
        # the native call; it must be daemonic so process exit never hangs
        seen = {}
        orig = threading.Thread

        class SpyThread(orig):
            def start(self):
                if self.name.startswith("toolkit-sync-"):
                    seen["daemon"] = self.daemon
                super().start()

        with mock.patch.object(threading, "Thread", SpyThread):
            with mock.patch.object(
                toolkit, "_allgather_stacked_impl", _hang(0.6)
            ):
                with self.assertRaises(SyncTimeoutError):
                    sync_and_compute(self._metric(), timeout_s=0.1)
        self.assertTrue(seen.get("daemon"))


if __name__ == "__main__":
    unittest.main()

"""End-to-end preemption recovery in 4 REAL processes (ISSUE 5 acceptance).

Two worlds, one worker script, three claims:

* **kill world** — chaos hard-kills rank 2 (``os._exit``, no goodbye to
  the coordinator) as it enters the second sync's descriptor round. The
  transport surfaces the dead peer however it likes (observed: a fast
  connection error, wrapped as ``SyncRoundError``); degraded mode returns
  every survivor's LOCAL value with
  ``toolkit.sync.timeouts{policy=local}`` incremented, and the pre-fault
  checkpoints restore in THIS (fresh) process to bit-identical
  ``compute()`` — including the dead rank's.
* **straggler world** — chaos makes rank 2 sleep past its whole sync
  budget instead of dying. Its peers' collective then genuinely HANGS
  (connections stay open; nothing errors), so the survivors' return is the
  watchdog timeout itself: elapsed ≈ ``timeout_s``, proving the deadline
  fires on a real blocked Gloo collective, not only on stubs.

Workers write their obs registry snapshots next to their results; CI
uploads the directory as an artifact when the job fails, turning a hung run
into a diagnosable trace (which sync round each rank reached).
"""

import json
import os
import socket
import subprocess
import sys
import unittest

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_WORKER = os.path.join(_HERE, "mp_chaos_worker.py")
WORLD = 4

sys.path.insert(0, _HERE)
from mp_chaos_worker import (  # noqa: E402
    CHAOS_EXIT_CODE,
    KILLED_RANK,
    NUM_CLASSES,
    TIMEOUT_S,
    make_shard,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


# the straggler world's sleep: longer than the whole sync2 budget, so the
# delayed rank's own deadline expires before it ever enters the round —
# its peers' collective is left one participant short and simply blocks
STRAGGLE_S = 20.0


def _artifact_dir(scenario: str) -> str:
    """Working directory for worker results + obs snapshots. CI points this
    at a workspace path (TORCHEVAL_TPU_TEST_ARTIFACT_DIR) and uploads it on
    failure; locally it is a tempdir."""
    configured = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if configured:
        out = os.path.join(configured, f"fault_injection_{scenario}")
        os.makedirs(out, exist_ok=True)
        return out
    import tempfile

    return tempfile.mkdtemp(prefix=f"tpu_chaos_{scenario}_")


def _launch_world(tmpdir: str, action: str):
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # each worker models one single-device host
    # arm chaos for every worker; only KILLED_RANK acts, at its 3rd
    # collective round (= entering the second sync's descriptor exchange)
    env.update(
        {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_RANK": str(KILLED_RANK),
            "TORCHEVAL_TPU_CHAOS_ROUND": "3",
            "TORCHEVAL_TPU_CHAOS_ACTION": action,
            "TORCHEVAL_TPU_CHAOS_DELAY_S": str(STRAGGLE_S),
            "TORCHEVAL_TPU_CHAOS_EXIT_CODE": str(CHAOS_EXIT_CODE),
        }
    )
    if action == "delay":
        # rank 0 (the coordination-service leader) must outlive the
        # straggler's sleep, or the runtime SIGABRTs the straggler the
        # moment the leader exits (observed: coordination_service_agent
        # "Polled an error ... Terminating process")
        env["TORCHEVAL_TPU_CHAOS_HOLD_S"] = str(STRAGGLE_S - TIMEOUT_S + 8.0)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(r), str(WORLD), str(port), tmpdir],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for r in range(WORLD)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    return procs, outs


class TestFaultInjection(unittest.TestCase):
    """The kill world: one 4-process launch, many assertions (distributed
    init dominates the cost)."""

    SCENARIO = "kill"

    @classmethod
    def setUpClass(cls):
        cls.tmpdir = _artifact_dir(cls.SCENARIO)
        procs, outs = _launch_world(cls.tmpdir, cls.SCENARIO)
        cls.returncodes = [p.returncode for p in procs]
        cls.outs = outs
        cls.results = {}
        for r in range(WORLD):
            path = os.path.join(cls.tmpdir, f"rank{r}.json")
            if os.path.exists(path):
                with open(path) as f:
                    cls.results[r] = json.load(f)

    def _survivors(self):
        return [r for r in range(WORLD) if r != KILLED_RANK]

    def test_killed_rank_died_with_injected_exit_code(self):
        self.assertEqual(
            self.returncodes[KILLED_RANK],
            CHAOS_EXIT_CODE,
            f"rank {KILLED_RANK} output:\n{self.outs[KILLED_RANK][-2000:]}",
        )
        # the injected death happens before the rank writes any results
        self.assertNotIn(KILLED_RANK, self.results)

    def test_survivors_exited_cleanly_with_results(self):
        for r in self._survivors():
            self.assertEqual(
                self.returncodes[r],
                0,
                f"rank {r} exited {self.returncodes[r]}:\n{self.outs[r][-4000:]}",
            )
            self.assertIn(r, self.results)

    def test_healthy_sync_matches_global_oracle(self):
        all_s, all_l = zip(*(make_shard(r, phase=0) for r in range(WORLD)))
        scores, labels = np.concatenate(all_s), np.concatenate(all_l)
        want = float((scores.argmax(1) == labels).mean())
        for r in self._survivors():
            self.assertAlmostEqual(self.results[r]["sync1"], want, places=6)

    def test_degraded_sync_returns_local_within_deadline(self):
        for r in self._survivors():
            res = self.results[r]
            # local oracle: BOTH phases of this rank's own stream, nothing
            # from any other rank
            s = np.concatenate(
                [make_shard(r, phase=0)[0], make_shard(r, phase=1)[0]]
            )
            l = np.concatenate(
                [make_shard(r, phase=0)[1], make_shard(r, phase=1)[1]]
            )
            want_local = float((s.argmax(1) == l).mean())
            self.assertAlmostEqual(res["sync2"], want_local, places=6)
            self.assertEqual(res["sync2"], res["local_compute_post"])
            # came back at the deadline, not after a transport-level hang
            # (generous slack: the watchdog joins at timeout, then local
            # compute runs; anything near the 240 s launch timeout means
            # the deadline never fired)
            self.assertLess(res["sync2_elapsed_s"], TIMEOUT_S + 30.0)

    def test_timeout_counter_incremented_once(self):
        for r in self._survivors():
            self.assertEqual(self.results[r]["timeouts_local"], 1.0)

    def test_obs_snapshots_written_for_ci_triage(self):
        for r in self._survivors():
            path = os.path.join(self.tmpdir, f"rank{r}.obs.json")
            self.assertTrue(os.path.exists(path))
            with open(path) as f:
                snap = json.load(f)
            self.assertIn("toolkit.sync.rounds", snap["counters"])

    def test_prefault_checkpoint_restores_bit_identical(self):
        # THIS process is the "fresh process" of the acceptance criterion:
        # it never saw the workers' state except through the checkpoint
        import jax.numpy as jnp  # noqa: F401  (ensures jax is up)

        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.resilience import restore

        for r in self._survivors():
            fresh = MulticlassAccuracy(num_classes=NUM_CLASSES)
            restore(fresh, os.path.join(self.tmpdir, f"ckpt_rank{r}"))
            got = float(np.asarray(fresh.compute()))
            self.assertEqual(
                got,
                self.results[r]["local_compute_at_ckpt"],
                f"rank {r}: restored compute drifted from the pre-fault value",
            )

    def test_dead_ranks_checkpoint_also_restores(self):
        # rank 2 checkpointed BEFORE it was killed: its accumulated state
        # survives its death — the whole point of the checkpoint leg
        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.resilience import restore

        fresh = MulticlassAccuracy(num_classes=NUM_CLASSES)
        restore(fresh, os.path.join(self.tmpdir, f"ckpt_rank{KILLED_RANK}"))
        s, l = make_shard(KILLED_RANK, phase=0)
        want = float((s.argmax(1) == l).mean())
        self.assertAlmostEqual(
            float(np.asarray(fresh.compute())), want, places=6
        )


class TestStragglerTimeout(unittest.TestCase):
    """The straggler world: rank 2 sleeps ``STRAGGLE_S`` (> the whole sync2
    budget) entering round 3, so its own deadline expires before it joins
    and its peers' collective is a genuine HANG — connections open, no
    transport error possible. The survivors' return time therefore IS the
    watchdog: elapsed ≈ TIMEOUT_S, the real proof that ``timeout_s`` fires
    on a blocked Gloo collective."""

    @classmethod
    def setUpClass(cls):
        cls.tmpdir = _artifact_dir("delay")
        procs, outs = _launch_world(cls.tmpdir, "delay")
        cls.returncodes = [p.returncode for p in procs]
        cls.outs = outs
        cls.results = {}
        for r in range(WORLD):
            path = os.path.join(cls.tmpdir, f"rank{r}.json")
            if os.path.exists(path):
                with open(path) as f:
                    cls.results[r] = json.load(f)

    def test_every_rank_survives_and_degrades_to_local(self):
        # nobody dies in this world — including the straggler, whose spent
        # budget short-circuits to SyncTimeoutError before it enters the
        # collective
        for r in range(WORLD):
            self.assertEqual(
                self.returncodes[r],
                0,
                f"rank {r} exited {self.returncodes[r]}:\n{self.outs[r][-4000:]}",
            )
            res = self.results[r]
            self.assertEqual(res["sync2"], res["local_compute_post"])
            self.assertEqual(res["timeouts_local"], 1.0)

    def test_survivors_waited_out_the_full_deadline(self):
        for r in range(WORLD):
            if r == KILLED_RANK:
                continue
            elapsed = self.results[r]["sync2_elapsed_s"]
            # the watchdog, not a fast transport error, produced the return:
            # the collective blocked for the whole budget
            self.assertGreaterEqual(elapsed, TIMEOUT_S - 0.5)
            self.assertLess(elapsed, TIMEOUT_S + 30.0)

    def test_straggler_burned_its_budget_sleeping(self):
        elapsed = self.results[KILLED_RANK]["sync2_elapsed_s"]
        self.assertGreaterEqual(elapsed, STRAGGLE_S - 0.5)


if __name__ == "__main__":
    unittest.main()

"""Unit tests for the env-gated fault-injection hooks (resilience/chaos.py).

The kill action (``os._exit`` mid-collective) can only run in a disposable
process — that is ``test_fault_injection.py``'s 4-process world. Here: the
round/rank targeting, the straggler delay, and the must-not-break-production
edges (disarmed fast path, malformed env disarms instead of raising).
"""

import os
import shutil
import tempfile
import time
import unittest
from unittest import mock

from torcheval_tpu.resilience import chaos


class TestChaosHooks(unittest.TestCase):
    def tearDown(self):
        chaos.reset_for_tests()

    def _arm(self, **extra):
        env = {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_RANK": "0",  # this process in a 1-proc world
            "TORCHEVAL_TPU_CHAOS_ROUND": "2",
            "TORCHEVAL_TPU_CHAOS_ACTION": "delay",
            "TORCHEVAL_TPU_CHAOS_DELAY_S": "0.3",
        }
        env.update(extra)
        return mock.patch.dict(os.environ, env)

    def test_disarmed_is_a_noop(self):
        with mock.patch.dict(os.environ):
            os.environ.pop("TORCHEVAL_TPU_CHAOS", None)
            chaos.reset_for_tests()
            t0 = time.monotonic()
            for _ in range(1000):
                chaos.on_sync_round()
            self.assertLess(time.monotonic() - t0, 0.5)

    def test_delay_fires_only_at_configured_round(self):
        with self._arm():
            chaos.reset_for_tests()
            t0 = time.monotonic()
            chaos.on_sync_round()  # round 1: no action
            first = time.monotonic() - t0
            t0 = time.monotonic()
            chaos.on_sync_round()  # round 2: the straggler delay
            second = time.monotonic() - t0
            t0 = time.monotonic()
            chaos.on_sync_round()  # round 3: past the target, no action
            third = time.monotonic() - t0
        self.assertLess(first, 0.2)
        self.assertGreaterEqual(second, 0.3)
        self.assertLess(third, 0.2)

    def test_other_rank_never_acts(self):
        with self._arm(TORCHEVAL_TPU_CHAOS_RANK="7"):
            chaos.reset_for_tests()
            t0 = time.monotonic()
            for _ in range(3):
                chaos.on_sync_round()
            self.assertLess(time.monotonic() - t0, 0.2)

    def test_malformed_config_disarms_instead_of_raising(self):
        # a stale TORCHEVAL_TPU_CHAOS=1 without the targeting vars must
        # never be able to break a production sync
        with mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_CHAOS": "1"}, clear=False
        ):
            for var in (
                "TORCHEVAL_TPU_CHAOS_RANK",
                "TORCHEVAL_TPU_CHAOS_ROUND",
            ):
                os.environ.pop(var, None)
            chaos.reset_for_tests()
            chaos.on_sync_round()  # no raise, no action

    def test_unknown_action_disarms(self):
        with self._arm(TORCHEVAL_TPU_CHAOS_ACTION="explode"):
            chaos.reset_for_tests()
            chaos.on_sync_round()
            chaos.on_sync_round()  # the configured round: still no action


class TestIngestHooks(unittest.TestCase):
    """The queue-boundary actions (ISSUE 8 satellite): poison and
    ingestion delay. End-to-end through a daemon in
    tests/serve/test_fault_containment.py; here the targeting and
    corruption semantics in isolation."""

    def tearDown(self):
        chaos.reset_for_tests()

    def _arm(self, **extra):
        env = {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_ACTION": "poison",
            "TORCHEVAL_TPU_CHAOS_TENANT": "t",
            "TORCHEVAL_TPU_CHAOS_STEP": "2",
            "TORCHEVAL_TPU_CHAOS_POISON": "nan",
        }
        env.update(extra)
        return mock.patch.dict(os.environ, env)

    def _batch(self):
        import numpy as np

        return (
            np.ones((4, 3), dtype=np.float32),
            np.zeros(4, dtype=np.int64),
        )

    def test_nan_poison_targets_tenant_and_step_only(self):
        import numpy as np

        with self._arm():
            chaos.reset_for_tests()
            clean = chaos.on_ingest("t", 1, self._batch())
            self.assertFalse(np.isnan(clean[0]).any())
            other = chaos.on_ingest("someone-else", 2, self._batch())
            self.assertFalse(np.isnan(other[0]).any())
            hit = chaos.on_ingest("t", 2, self._batch())
            # the first FLOAT argument is all-NaN; the int labels untouched
            self.assertTrue(np.isnan(hit[0]).all())
            self.assertEqual(hit[1].dtype.kind, "i")

    def test_shape_poison_drops_a_leading_row(self):
        with self._arm(TORCHEVAL_TPU_CHAOS_POISON="shape"):
            chaos.reset_for_tests()
            s, l = self._batch()
            hit = chaos.on_ingest("t", 2, (s, l))
            self.assertEqual(hit[0].shape, (3, 3))
            self.assertEqual(hit[1].shape, (4,))

    def test_ingest_delay_sleeps_at_the_boundary(self):
        with self._arm(
            TORCHEVAL_TPU_CHAOS_ACTION="ingest_delay",
            TORCHEVAL_TPU_CHAOS_DELAY_S="0.3",
        ):
            chaos.reset_for_tests()
            t0 = time.monotonic()
            chaos.on_ingest("t", 1, self._batch())
            self.assertLess(time.monotonic() - t0, 0.2)
            t0 = time.monotonic()
            out = chaos.on_ingest("t", 2, self._batch())
            self.assertGreaterEqual(time.monotonic() - t0, 0.3)
            # a delay never corrupts
            self.assertEqual(out[0].shape, (4, 3))

    def test_load_spike_fires_on_every_batch_from_step(self):
        # NOT one-shot: a load spike models sustained pressure (ISSUE
        # 19's rebalance/split driver), so every admitted batch at/after
        # the armed step pays the delay
        with self._arm(
            TORCHEVAL_TPU_CHAOS_ACTION="load_spike",
            TORCHEVAL_TPU_CHAOS_DELAY_S="0.15",
        ):
            chaos.reset_for_tests()
            t0 = time.monotonic()
            chaos.on_ingest("t", 1, self._batch())
            self.assertLess(time.monotonic() - t0, 0.1)
            for step in (2, 3):
                t0 = time.monotonic()
                out = chaos.on_ingest("t", step, self._batch())
                self.assertGreaterEqual(
                    time.monotonic() - t0, 0.15, f"step {step}"
                )
            # a load spike never corrupts the batch
            self.assertEqual(out[0].shape, (4, 3))

    def test_hot_tenant_alias_targets_tenant_and_arms_ingest(self):
        with self._arm(
            TORCHEVAL_TPU_CHAOS_ACTION="hot_tenant",
            TORCHEVAL_TPU_CHAOS_DELAY_S="0.15",
        ):
            chaos.reset_for_tests()
            self.assertTrue(chaos.ingest_armed())
            t0 = time.monotonic()
            chaos.on_ingest("someone-else", 2, self._batch())
            self.assertLess(time.monotonic() - t0, 0.1)
            t0 = time.monotonic()
            chaos.on_ingest("t", 2, self._batch())
            self.assertGreaterEqual(time.monotonic() - t0, 0.15)

    def test_wildcard_tenant_and_fires_once(self):
        import numpy as np

        with self._arm(TORCHEVAL_TPU_CHAOS_TENANT="*"):
            chaos.reset_for_tests()
            hit = chaos.on_ingest("anybody", 2, self._batch())
            self.assertTrue(np.isnan(hit[0]).all())
            again = chaos.on_ingest("anybody", 2, self._batch())
            self.assertFalse(np.isnan(again[0]).any())

    def test_sync_armed_process_passes_ingest_untouched_and_vice_versa(self):
        import numpy as np

        with mock.patch.dict(
            os.environ,
            {
                "TORCHEVAL_TPU_CHAOS": "1",
                "TORCHEVAL_TPU_CHAOS_ACTION": "delay",
                "TORCHEVAL_TPU_CHAOS_RANK": "0",
                "TORCHEVAL_TPU_CHAOS_ROUND": "1",
                "TORCHEVAL_TPU_CHAOS_DELAY_S": "0.0",
            },
        ):
            chaos.reset_for_tests()
            out = chaos.on_ingest("t", 1, self._batch())
            self.assertFalse(np.isnan(out[0]).any())
        with self._arm():
            chaos.reset_for_tests()
            t0 = time.monotonic()
            chaos.on_sync_round()
            self.assertLess(time.monotonic() - t0, 0.2)

    def test_missing_ingest_vars_disarm(self):
        with self._arm():
            os.environ.pop("TORCHEVAL_TPU_CHAOS_TENANT")
            chaos.reset_for_tests()
            out = chaos.on_ingest("t", 2, self._batch())
            import numpy as np

            self.assertFalse(np.isnan(out[0]).any())


class TestHostActions(unittest.TestCase):
    """Host-level chaos (ISSUE 10): targeting and one-shot semantics of
    the eval-wire server hooks. The dying actions (``host_kill`` /
    ``ack_drop``'s ``os._exit``) can only run in a disposable process —
    that is ``tests/serve/test_cluster_mp.py``'s drill; here the
    partition directive, the per-tenant submit counting, and the
    disarmed edges."""

    def tearDown(self):
        chaos.reset_for_tests()

    def _arm(self, action="host_partition", tenant="bob", step="2"):
        return mock.patch.dict(
            os.environ,
            {
                "TORCHEVAL_TPU_CHAOS": "1",
                "TORCHEVAL_TPU_CHAOS_ACTION": action,
                "TORCHEVAL_TPU_CHAOS_TENANT": tenant,
                "TORCHEVAL_TPU_CHAOS_STEP": step,
            },
        )

    def test_disarmed_gate_is_false(self):
        with mock.patch.dict(os.environ):
            os.environ.pop("TORCHEVAL_TPU_CHAOS", None)
            chaos.reset_for_tests()
            self.assertFalse(chaos.host_armed())

    def test_partition_fires_at_tenant_step_only_once(self):
        with self._arm():
            chaos.reset_for_tests()
            self.assertTrue(chaos.host_armed())
            # other tenants and other ops never trip it
            self.assertIsNone(chaos.on_host_request("submit", "alice"))
            self.assertIsNone(chaos.on_host_request("compute", "bob"))
            self.assertIsNone(chaos.on_host_request("submit", "bob"))  # 1
            self.assertEqual(
                chaos.on_host_request("submit", "bob"), "partition"  # 2
            )
            # one-shot: the counter never matches again
            self.assertIsNone(chaos.on_host_request("submit", "bob"))

    def test_ack_drop_directive_returned_for_server_to_honor(self):
        with self._arm(action="ack_drop", step="1"):
            chaos.reset_for_tests()
            self.assertEqual(
                chaos.on_host_request("submit", "bob"), "ack_drop"
            )

    def test_wildcard_tenant_counts_per_tenant(self):
        with self._arm(tenant="*", step="2"):
            chaos.reset_for_tests()
            self.assertIsNone(chaos.on_host_request("submit", "a"))  # a:1
            self.assertIsNone(chaos.on_host_request("submit", "b"))  # b:1
            self.assertEqual(
                chaos.on_host_request("submit", "a"), "partition"  # a:2
            )

    def test_host_actions_do_not_arm_other_hooks(self):
        import numpy as np

        with self._arm():
            chaos.reset_for_tests()
            self.assertFalse(chaos.ingest_armed())
            rng = np.random.default_rng(0)
            batch = (rng.random((4, 2)).astype(np.float32),)
            out = chaos.on_ingest("bob", 2, batch)
            self.assertFalse(np.isnan(out[0]).any())
            t0 = time.monotonic()
            chaos.on_sync_round()
            self.assertLess(time.monotonic() - t0, 0.2)

    def test_ingest_actions_do_not_arm_host_hooks(self):
        with mock.patch.dict(
            os.environ,
            {
                "TORCHEVAL_TPU_CHAOS": "1",
                "TORCHEVAL_TPU_CHAOS_ACTION": "poison",
                "TORCHEVAL_TPU_CHAOS_TENANT": "bob",
                "TORCHEVAL_TPU_CHAOS_STEP": "1",
            },
        ):
            chaos.reset_for_tests()
            self.assertFalse(chaos.host_armed())
            self.assertIsNone(chaos.on_host_request("submit", "bob"))

    def test_missing_step_disarms_with_warning_not_raise(self):
        with self._arm():
            os.environ.pop("TORCHEVAL_TPU_CHAOS_STEP")
            chaos.reset_for_tests()
            self.assertFalse(chaos.host_armed())


class TestRouterKillHooks(unittest.TestCase):
    """The control-plane kill (ISSUE 20 tentpole): targeting by point,
    tenant and 1-based matching-op count. The real ``os._exit`` runs only
    in the disposable driver of ``tests/serve/test_router_restart_mp.py``;
    here it is mocked so the selection logic is testable in-process."""

    def tearDown(self):
        chaos.reset_for_tests()

    def _arm(self, **extra):
        env = {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_ACTION": "router_kill",
            "TORCHEVAL_TPU_CHAOS_TENANT": "*",
            "TORCHEVAL_TPU_CHAOS_STEP": "2",
            "TORCHEVAL_TPU_CHAOS_EXIT_CODE": "47",
        }
        env.update(extra)
        return mock.patch.dict(os.environ, env)

    def test_router_armed_gate(self):
        with self._arm():
            chaos.reset_for_tests()
            self.assertTrue(chaos.router_armed())
        with mock.patch.dict(os.environ):
            os.environ.pop("TORCHEVAL_TPU_CHAOS", None)
            chaos.reset_for_tests()
            self.assertFalse(chaos.router_armed())

    def test_fires_at_the_armed_op_count_with_exit_code(self):
        with self._arm(), mock.patch.object(os, "_exit") as ex:
            chaos.reset_for_tests()
            chaos.on_router_op("submit", "a")  # op 1: no action
            ex.assert_not_called()
            chaos.on_router_op("submit", "b")  # op 2: the kill
            ex.assert_called_once_with(47)

    def test_point_filter_counts_only_matching_ops(self):
        with self._arm(
            TORCHEVAL_TPU_CHAOS_POINT="migrate_exported",
            TORCHEVAL_TPU_CHAOS_STEP="1",
        ), mock.patch.object(os, "_exit") as ex:
            chaos.reset_for_tests()
            for _ in range(5):
                chaos.on_router_op("submit", "a")  # wrong point: uncounted
            ex.assert_not_called()
            chaos.on_router_op("migrate_exported", "a")
            ex.assert_called_once_with(47)

    def test_tenant_filter_counts_only_matching_ops(self):
        with self._arm(
            TORCHEVAL_TPU_CHAOS_TENANT="vic", TORCHEVAL_TPU_CHAOS_STEP="1"
        ), mock.patch.object(os, "_exit") as ex:
            chaos.reset_for_tests()
            chaos.on_router_op("submit", "other")  # uncounted
            ex.assert_not_called()
            chaos.on_router_op("submit", "vic")
            ex.assert_called_once_with(47)

    def test_fires_once_per_process(self):
        with self._arm(TORCHEVAL_TPU_CHAOS_STEP="1"), mock.patch.object(
            os, "_exit"
        ) as ex:
            chaos.reset_for_tests()
            chaos.on_router_op("submit", "a")
            chaos.on_router_op("submit", "a")
            self.assertEqual(ex.call_count, 1)

    def test_router_action_does_not_arm_other_hooks(self):
        with self._arm():
            chaos.reset_for_tests()
            self.assertFalse(chaos.host_armed())
            self.assertFalse(chaos.ingest_armed())
            self.assertFalse(chaos.ckpt_armed())

    def test_malformed_config_disarms(self):
        with self._arm():
            os.environ.pop("TORCHEVAL_TPU_CHAOS_STEP")
            chaos.reset_for_tests()
            self.assertFalse(chaos.router_armed())


class TestCkptCorruptHooks(unittest.TestCase):
    """The silent-bit-rot injector (ISSUE 20): flips one payload byte of
    the selected save in place. End-to-end (quarantine + lineage
    fallback) in tests/serve/test_router_recovery.py; here the substring
    targeting, the matching-save count, and the flip itself."""

    def tearDown(self):
        chaos.reset_for_tests()

    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="tpu_chaos_ckpt_")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)

    def _ckpt(self, name):
        path = os.path.join(self.dir, name)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "state.npz"), "wb") as f:
            f.write(bytes(range(64)))
        return path

    def _payload(self, path):
        with open(os.path.join(path, "state.npz"), "rb") as f:
            return f.read()

    def _arm(self, **extra):
        env = {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_ACTION": "ckpt_corrupt",
            "TORCHEVAL_TPU_CHAOS_TENANT": "/vic/",
            "TORCHEVAL_TPU_CHAOS_STEP": "1",
        }
        env.update(extra)
        return mock.patch.dict(os.environ, env)

    def test_flips_exactly_one_byte_of_the_matching_save(self):
        path = self._ckpt("vic/ckpt-1")
        before = self._payload(path)
        with self._arm():
            chaos.reset_for_tests()
            self.assertTrue(chaos.ckpt_armed())
            chaos.on_ckpt_saved(path)
        after = self._payload(path)
        diff = [i for i in range(len(before)) if before[i] != after[i]]
        self.assertEqual(diff, [12])
        self.assertEqual(after[12], before[12] ^ 0xFF)

    def test_substring_filter_skips_other_tenants_saves(self):
        other = self._ckpt("bob/ckpt-1")
        vic = self._ckpt("vic/ckpt-1")
        before = self._payload(other)
        with self._arm():
            chaos.reset_for_tests()
            chaos.on_ckpt_saved(other)  # not /vic/: uncounted, untouched
            self.assertEqual(self._payload(other), before)
            vic_before = self._payload(vic)
            chaos.on_ckpt_saved(vic)
            self.assertNotEqual(self._payload(vic), vic_before)

    def test_step_counts_matching_saves_and_fires_once(self):
        g1 = self._ckpt("vic/ckpt-1")
        g2 = self._ckpt("vic/ckpt-2")
        g3 = self._ckpt("vic/ckpt-3")
        with self._arm(TORCHEVAL_TPU_CHAOS_STEP="2"):
            chaos.reset_for_tests()
            before = {p: self._payload(p) for p in (g1, g2, g3)}
            chaos.on_ckpt_saved(g1)  # save 1: intact
            chaos.on_ckpt_saved(g2)  # save 2: flipped
            chaos.on_ckpt_saved(g3)  # one-shot spent: intact
        self.assertEqual(self._payload(g1), before[g1])
        self.assertNotEqual(self._payload(g2), before[g2])
        self.assertEqual(self._payload(g3), before[g3])

    def test_missing_payload_warns_instead_of_raising(self):
        path = os.path.join(self.dir, "vic", "ckpt-9")
        os.makedirs(path)  # no state.npz inside
        with self._arm():
            chaos.reset_for_tests()
            chaos.on_ckpt_saved(path)  # must not raise

    def test_ckpt_action_does_not_arm_router_hooks(self):
        with self._arm():
            chaos.reset_for_tests()
            self.assertFalse(chaos.router_armed())
            self.assertFalse(chaos.host_armed())


if __name__ == "__main__":
    unittest.main()

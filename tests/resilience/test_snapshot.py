"""Checkpoint/restore engine tests (torcheval_tpu/resilience/snapshot.py).

ISSUE 5 acceptance: round trips are bit-identical (including the tricky
state containers — WINDOW deques with order+maxlen, SampleCacheMetric
empty-cache dtypes, Throughput's max-elapsed merge), ``restore`` rejects
corrupted payloads and schema-mismatched manifests with structured errors,
and writes are atomic (a simulated crash between the temp write and the
rename publishes nothing).
"""

import json
import os
import shutil
import tempfile
import time
import unittest
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import (
    BinaryAUROC,
    MetricCollection,
    MulticlassAccuracy,
    Sum,
    Throughput,
    WindowedClickThroughRate,
)
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric
from torcheval_tpu.resilience import (
    CheckpointError,
    latest_checkpoint,
    list_checkpoints,
    restore,
    save,
)
from torcheval_tpu.resilience import snapshot as snapshot_mod
from torcheval_tpu.utils.test_utils import DummySumDictStateMetric

RNG = np.random.default_rng(7)


def _acc_batch(n=64, c=5, seed=None):
    rng = np.random.default_rng(seed) if seed is not None else RNG
    return (
        rng.random((n, c)).astype(np.float32),
        rng.integers(0, c, n),
    )


class _IntCache(SampleCacheMetric[jax.Array]):
    """Integer-cache fixture (mirrors tests/metrics/test_sample_cache.py)."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_cache_state("ids", dtype=jnp.int32)

    def update(self, ids):
        self.ids.append(self._input(ids))
        return self

    def compute(self) -> jax.Array:
        return self._concat_cache("ids")


class _TmpDirTest(unittest.TestCase):
    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="tpu_ckpt_")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)


class TestRoundTrip(_TmpDirTest):
    def test_bare_metric_mid_stream_bit_identical(self):
        m = MulticlassAccuracy(num_classes=5)
        x, t = _acc_batch()
        m.update(jnp.asarray(x), jnp.asarray(t))
        self.assertTrue(m._pending)  # mid-window: save must fold first
        path = save(m, self.dir)
        self.assertEqual(m._pending, [])  # folded, not dropped
        want = np.asarray(m.compute())
        fresh = MulticlassAccuracy(num_classes=5)
        restore(fresh, path)
        self.assertTrue((np.asarray(fresh.compute()) == want).all())
        # the restored metric keeps streaming
        x2, t2 = _acc_batch(16)
        fresh.update(jnp.asarray(x2), jnp.asarray(t2))
        ref = MulticlassAccuracy(num_classes=5)
        ref.update(
            jnp.asarray(np.concatenate([x, x2])),
            jnp.asarray(np.concatenate([t, t2])),
        )
        self.assertAlmostEqual(
            float(fresh.compute()), float(ref.compute()), places=6
        )

    def test_restore_from_parent_dir_takes_latest(self):
        m = Sum()
        m.update(jnp.asarray([1.0]))
        save(m, self.dir)
        m.update(jnp.asarray([2.0]))
        save(m, self.dir)
        fresh = Sum()
        restore(fresh, self.dir)  # parent dir -> newest ckpt
        self.assertEqual(float(fresh.compute()), 3.0)

    def test_mixed_metric_dict_round_trip_including_cache_and_dict(self):
        acc = MulticlassAccuracy(num_classes=5)
        auroc = BinaryAUROC()
        x, t = _acc_batch()
        scores = RNG.random(33).astype(np.float32)
        targets = (RNG.random(33) > 0.4).astype(np.float32)
        acc.update(jnp.asarray(x), jnp.asarray(t))
        auroc.update(jnp.asarray(scores), jnp.asarray(targets))
        d = DummySumDictStateMetric()
        d.update("a", 2.0)
        d.update("b", 3.0)
        want_acc = np.asarray(acc.compute())
        want_auroc = np.asarray(auroc.compute())
        save({"acc": acc, "auroc": auroc, "d": d}, self.dir)
        fresh_acc = MulticlassAccuracy(num_classes=5)
        fresh_auroc = BinaryAUROC()
        fresh_d = DummySumDictStateMetric()
        restore({"acc": fresh_acc, "auroc": fresh_auroc, "d": fresh_d}, self.dir)
        self.assertTrue((np.asarray(fresh_acc.compute()) == want_acc).all())
        self.assertTrue((np.asarray(fresh_auroc.compute()) == want_auroc).all())
        self.assertEqual(float(fresh_d.compute()), 5.0)
        # dict state keeps missing-key-is-zero semantics after restore
        fresh_d.update("c", 1.0)
        self.assertEqual(float(fresh_d.compute()), 6.0)

    def test_metric_collection_object_round_trip(self):
        col = MetricCollection({"acc": MulticlassAccuracy(num_classes=5)})
        x, t = _acc_batch()
        col.update(jnp.asarray(x), jnp.asarray(t))
        want = float(col.compute()["acc"])
        save(col, self.dir)
        fresh = MetricCollection({"acc": MulticlassAccuracy(num_classes=5)})
        restore(fresh, self.dir)
        self.assertEqual(float(fresh.compute()["acc"]), want)

    def test_sharded_evaluator_round_trip(self):
        from torcheval_tpu.parallel import ShardedEvaluator

        ev = ShardedEvaluator({"acc": MulticlassAccuracy(num_classes=5)})
        x, t = _acc_batch(64)
        ev.update(jnp.asarray(x), jnp.asarray(t))
        want = float(ev.compute()["acc"])
        save(ev, self.dir)
        fresh = ShardedEvaluator({"acc": MulticlassAccuracy(num_classes=5)})
        restore(fresh, self.dir)
        self.assertEqual(float(fresh.compute()["acc"]), want)
        # restored state is back on the mesh: further sharded updates work
        fresh.update(jnp.asarray(x), jnp.asarray(t))
        self.assertAlmostEqual(float(fresh.compute()["acc"]), want, places=6)


class TestTrickyContainers(_TmpDirTest):
    def test_window_deque_order_and_maxlen_preserved(self):
        m = WindowedClickThroughRate(window_size=3)
        for i in range(5):  # 5 updates > window 3: only the newest 3 survive
            m.update(jnp.asarray([float(i % 2)] * 4))
        want_rows = [np.asarray(r) for r in m.window]
        lifetime, windowed = (np.asarray(v) for v in m.compute())
        save(m, self.dir)
        fresh = WindowedClickThroughRate(window_size=3)
        restore(fresh, self.dir)
        self.assertEqual(fresh.window.maxlen, 3)
        self.assertEqual(len(fresh.window), 3)
        for got, want in zip(fresh.window, want_rows):
            self.assertTrue((np.asarray(got) == want).all())
        got_lifetime, got_windowed = (np.asarray(v) for v in fresh.compute())
        self.assertTrue((got_lifetime == lifetime).all())
        self.assertTrue((got_windowed == windowed).all())
        # the bound still enforces after restore: one more update evicts
        # the oldest restored row, exactly as it would have pre-save
        fresh.update(jnp.asarray([1.0] * 4))
        self.assertEqual(len(fresh.window), 3)
        self.assertTrue(
            (np.asarray(fresh.window[0]) == want_rows[1]).all()
        )

    def test_sample_cache_empty_dtype_honored_on_restore(self):
        m = _IntCache()
        save(m, self.dir)  # empty cache checkpoint
        fresh = _IntCache()
        restore(fresh, self.dir)
        out = fresh.compute()
        self.assertEqual(out.shape, (0,))
        self.assertEqual(out.dtype, jnp.int32)  # not silently float32

    def test_sample_cache_chunks_round_trip(self):
        m = _IntCache()
        m.update(jnp.asarray([3, 1, 2], dtype=jnp.int32))
        m.update(jnp.asarray([9, 8], dtype=jnp.int32))
        save(m, self.dir)
        fresh = _IntCache()
        restore(fresh, self.dir)
        self.assertTrue(
            (np.asarray(fresh.compute()) == np.asarray([3, 1, 2, 9, 8])).all()
        )
        self.assertEqual(fresh.compute().dtype, jnp.int32)

    def test_throughput_max_elapsed_merge_unaffected_by_restore(self):
        m = Throughput()
        m.update(num_processed=100, elapsed_time_sec=4.0)
        save(m, self.dir)
        fresh = Throughput()
        restore(fresh, self.dir)
        peer = Throughput()
        peer.update(num_processed=200, elapsed_time_sec=2.0)
        fresh.merge_state([peer])
        # counts sum (300), elapsed is the MAX (4.0), not the sum (6.0):
        # the restore must not have perturbed the merge semantics
        self.assertEqual(float(fresh.num_total), 300.0)
        self.assertEqual(float(fresh.elapsed_time_sec), 4.0)
        self.assertEqual(float(fresh.compute()), 75.0)


class TestValidation(_TmpDirTest):
    def _saved_sum(self):
        m = Sum()
        m.update(jnp.asarray([5.0]))
        return save(m, self.dir)

    def test_missing_checkpoint_not_found(self):
        with self.assertRaises(CheckpointError) as ctx:
            restore(Sum(), os.path.join(self.dir, "nope"))
        self.assertEqual(ctx.exception.reason, "not_found")

    def test_corrupted_payload_rejected(self):
        path = self._saved_sum()
        with open(os.path.join(path, "state.npz"), "r+b") as f:
            f.seek(12)
            f.write(b"\xde\xad\xbe\xef")
        with self.assertRaises(CheckpointError) as ctx:
            restore(Sum(), path)
        self.assertEqual(ctx.exception.reason, "checksum_mismatch")

    def test_corrupted_manifest_rejected(self):
        path = self._saved_sum()
        with open(os.path.join(path, "manifest.json"), "w") as f:
            f.write("{not json")
        with self.assertRaises(CheckpointError) as ctx:
            restore(Sum(), path)
        self.assertEqual(ctx.exception.reason, "corrupt_manifest")

    def test_manifest_missing_field_rejected(self):
        path = self._saved_sum()
        mpath = os.path.join(path, "manifest.json")
        with open(mpath) as f:
            manifest = json.load(f)
        del manifest["payload_sha256"]
        with open(mpath, "w") as f:
            json.dump(manifest, f)
        with self.assertRaises(CheckpointError) as ctx:
            restore(Sum(), path)
        self.assertEqual(ctx.exception.reason, "corrupt_manifest")

    def test_schema_mismatch_different_metric_set(self):
        self._saved_sum()
        with self.assertRaises(CheckpointError) as ctx:
            restore(MulticlassAccuracy(num_classes=5), self.dir)
        self.assertEqual(ctx.exception.reason, "schema_mismatch")

    def test_schema_mismatch_window_config_drift(self):
        # window_size is fold-relevant configuration (_sync_schema_extra):
        # the digest must reject a drifted replica, exactly as the sync
        # wire's schema digest does
        m = WindowedClickThroughRate(window_size=4)
        m.update(jnp.asarray([1.0]))
        save(m, self.dir)
        with self.assertRaises(CheckpointError) as ctx:
            restore(WindowedClickThroughRate(window_size=5), self.dir)
        self.assertEqual(ctx.exception.reason, "schema_mismatch")

    def test_shape_drift_within_same_schema_rejected(self):
        # macro accuracy's per-class counters: num_classes is not in the
        # digest (same class/state/reduction schema) but sizes the state —
        # the per-leaf shape check must catch it before any state install
        m = MulticlassAccuracy(num_classes=5, average="macro")
        x, t = _acc_batch()
        m.update(jnp.asarray(x), jnp.asarray(t))
        save(m, self.dir)
        target = MulticlassAccuracy(num_classes=4, average="macro")
        before = {k: np.asarray(v) for k, v in target.state_dict().items()}
        with self.assertRaises(CheckpointError) as ctx:
            restore(target, self.dir)
        self.assertEqual(ctx.exception.reason, "schema_mismatch")
        # failed restore left the target untouched
        after = {k: np.asarray(v) for k, v in target.state_dict().items()}
        for k in before:
            self.assertTrue((before[k] == after[k]).all(), k)

    def test_failed_validation_precedes_any_state_write(self):
        path = self._saved_sum()
        with open(os.path.join(path, "state.npz"), "r+b") as f:
            f.seek(12)
            f.write(b"\xde\xad\xbe\xef")
        target = Sum()
        target.update(jnp.asarray([42.0]))
        with self.assertRaises(CheckpointError):
            restore(target, path)
        self.assertEqual(float(target.compute()), 42.0)  # unperturbed


class TestAtomicityAndRotation(_TmpDirTest):
    def test_crash_between_temp_write_and_rename_publishes_nothing(self):
        m = Sum()
        m.update(jnp.asarray([1.0]))
        real_replace = os.replace

        def crash(src, dst):
            raise OSError("simulated crash before rename")

        with mock.patch.object(snapshot_mod.os, "replace", crash):
            with self.assertRaises(OSError):
                save(m, self.dir)
        # no partial checkpoint is visible: a reader scanning the directory
        # finds nothing to restore from
        self.assertEqual(list_checkpoints(self.dir), [])
        self.assertIsNone(latest_checkpoint(self.dir))
        with self.assertRaises(CheckpointError) as ctx:
            restore(Sum(), self.dir)
        self.assertEqual(ctx.exception.reason, "not_found")
        # and a later save on the same directory succeeds cleanly
        with mock.patch.object(snapshot_mod.os, "replace", real_replace):
            path = save(m, self.dir)
        fresh = Sum()
        restore(fresh, path)
        self.assertEqual(float(fresh.compute()), 1.0)

    def test_stray_tmp_dirs_are_invisible_to_readers(self):
        os.makedirs(os.path.join(self.dir, ".tmp-ckpt-00000007-123"))
        self.assertEqual(list_checkpoints(self.dir), [])
        m = Sum()
        m.update(jnp.asarray([2.0]))
        save(m, self.dir)
        self.assertEqual(len(list_checkpoints(self.dir)), 1)

    def test_keep_last_rotation(self):
        m = Sum()
        for i in range(4):
            m.update(jnp.asarray([1.0]))
            save(m, self.dir, keep_last=2)
        ckpts = list_checkpoints(self.dir)
        self.assertEqual(len(ckpts), 2)
        self.assertTrue(ckpts[-1].endswith("ckpt-00000003"))
        fresh = Sum()
        restore(fresh, self.dir)
        self.assertEqual(float(fresh.compute()), 4.0)

    def test_step_numbering_monotonic_after_rotation(self):
        m = Sum()
        m.update(jnp.asarray([1.0]))
        for _ in range(3):
            save(m, self.dir, keep_last=1)
        # rotation removed older steps but numbering keeps advancing
        self.assertTrue(
            latest_checkpoint(self.dir).endswith("ckpt-00000002")
        )

    def test_invalid_keep_last_rejected_before_any_write(self):
        m = Sum()
        with self.assertRaisesRegex(ValueError, "keep_last"):
            save(m, self.dir, keep_last=0)
        # the argument error must precede the save side effect: no
        # checkpoint published, no counters bumped
        self.assertEqual(list_checkpoints(self.dir), [])

    def test_explicit_step_collision_rejected(self):
        m = Sum()
        save(m, self.dir, step=3)
        with self.assertRaises(CheckpointError):
            save(m, self.dir, step=3)


class TestStaleTmpGC(_TmpDirTest):
    """ISSUE 8 satellite: a crash mid-``save()`` leaves a ``.tmp-*``
    directory behind (the cleanup handler cannot run through a hard
    death); the NEXT successful save in the same directory reclaims it —
    while tmp dirs belonging to a live concurrent writer are left alone."""

    def _crashed_writer_tmp(self) -> str:
        """Run a LITERAL crash between temp write and rename in a child
        process: ``os.replace`` is swapped for ``os._exit``, so no python
        cleanup (not even ``save``'s own except-handler) runs. Returns the
        orphaned tmp path; the child's pid is provably dead."""
        import subprocess
        import sys as _sys

        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        script = (
            "import os, sys\n"
            "sys.path.insert(0, %r)\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import jax.numpy as jnp\n"
            "from torcheval_tpu.metrics import Sum\n"
            "from torcheval_tpu.resilience import snapshot as snap\n"
            "m = Sum(); m.update(jnp.asarray([1.0]))\n"
            "snap.os.replace = lambda s, d: os._exit(7)\n"
            "snap.save(m, sys.argv[1])\n"
        ) % repo
        proc = subprocess.run(
            [_sys.executable, "-c", script, self.dir],
            capture_output=True,
            timeout=120,
        )
        self.assertEqual(proc.returncode, 7, proc.stderr.decode()[-2000:])
        tmps = [n for n in os.listdir(self.dir) if n.startswith(".tmp-")]
        self.assertEqual(len(tmps), 1, "the crash should orphan ONE tmp dir")
        # the tmp name embeds the writer's pid, and that writer is dead
        pid = snapshot_mod._tmp_writer_pid(tmps[0])
        self.assertIsNotNone(pid)
        with self.assertRaises(ProcessLookupError):
            os.kill(pid, 0)
        return os.path.join(self.dir, tmps[0])

    def test_next_save_reclaims_crash_orphaned_tmp(self):
        orphan = self._crashed_writer_tmp()
        m = Sum()
        m.update(jnp.asarray([2.0]))
        save(m, self.dir)
        self.assertFalse(
            os.path.exists(orphan),
            "the next successful save must GC the dead writer's tmp dir",
        )
        # the published checkpoint is untouched by the GC
        fresh = Sum()
        restore(fresh, self.dir)
        self.assertEqual(float(fresh.compute()), 2.0)

    def test_live_writers_tmp_dirs_left_alone(self):
        import subprocess
        import sys as _sys

        # a concurrent writer that is still alive (fresh mtime, live pid):
        # its in-progress tmp must never be reclaimed out from under it
        sleeper = subprocess.Popen(
            [_sys.executable, "-c", "import time; time.sleep(60)"]
        )
        try:
            live_tmp = os.path.join(
                self.dir, f".tmp-ckpt-00000009-{sleeper.pid}"
            )
            os.makedirs(live_tmp)
            # our own pid is skipped outright (save() is re-entrant-safe)
            own_tmp = os.path.join(
                self.dir, f".tmp-ckpt-00000010-{os.getpid()}"
            )
            os.makedirs(own_tmp)
            m = Sum()
            m.update(jnp.asarray([1.0]))
            save(m, self.dir)
            self.assertTrue(os.path.exists(live_tmp))
            self.assertTrue(os.path.exists(own_tmp))
        finally:
            sleeper.kill()
            sleeper.wait()

    def test_unparseable_pid_falls_back_to_mtime_age(self):
        # names that do not match the FULL .tmp-ckpt-<step>-<pid> shape
        # must use the age fallback — including a foreign tool's numeric
        # suffix (.tmp-upload-123: pid 123 being dead must NOT delete a
        # concurrent tool's fresh data) and a truncated checkpoint name
        fresh_tmp = os.path.join(self.dir, ".tmp-upload-123")
        old_tmp = os.path.join(self.dir, ".tmp-ckpt-older-garbage")
        self.assertIsNone(snapshot_mod._tmp_writer_pid(".tmp-upload-123"))
        self.assertIsNone(snapshot_mod._tmp_writer_pid(".tmp-ckpt-00000010"))
        os.makedirs(fresh_tmp)
        os.makedirs(old_tmp)
        stale = time.time() - 2 * snapshot_mod._TMP_GC_MIN_AGE_S
        os.utime(old_tmp, (stale, stale))
        m = Sum()
        m.update(jnp.asarray([1.0]))
        save(m, self.dir)
        # fresh, concurrent-looking dir survives; the hour-old one goes
        self.assertTrue(os.path.exists(fresh_tmp))
        self.assertFalse(os.path.exists(old_tmp))


class TestRotationUnderChurn(_TmpDirTest):
    """ISSUE 8 satellite: ``keep_last=N`` under rapid save/evict cycles
    never deletes the newest checkpoint, and ``latest_checkpoint`` stays
    consistent for a reader listing mid-rotation."""

    def test_concurrent_reader_never_observes_zero_checkpoints(self):
        import threading

        errors = []
        stop = threading.Event()
        first_saved = threading.Event()

        def reader():
            while not stop.is_set():
                path = latest_checkpoint(self.dir)
                if path is None:
                    if first_saved.is_set():
                        errors.append("latest_checkpoint returned None")
                    continue
                try:
                    with open(os.path.join(path, "manifest.json")) as f:
                        json.load(f)
                except (FileNotFoundError, json.JSONDecodeError):
                    # the picked dir was rotated away between list and
                    # open: consistency demands a NEWER latest now exists
                    newer = latest_checkpoint(self.dir)
                    if newer is None or newer <= path:
                        errors.append(
                            f"latest regressed: {path} -> {newer}"
                        )

        t = threading.Thread(target=reader)
        t.start()
        try:
            m = Sum()
            for i in range(30):
                m.update(jnp.asarray([1.0]))
                path = save(m, self.dir, keep_last=2)
                first_saved.set()
                # the just-published newest is never rotation's victim
                self.assertTrue(os.path.isdir(path))
                ckpts = list_checkpoints(self.dir)
                self.assertLessEqual(len(ckpts), 2)
                self.assertEqual(ckpts[-1], path)
        finally:
            stop.set()
            t.join(30)
        self.assertEqual(errors, [])
        fresh = Sum()
        restore(fresh, self.dir)
        self.assertEqual(float(fresh.compute()), 30.0)

    def test_serve_eviction_churn_rotates_and_resumes(self):
        """The caller this satellite exists for: the serve daemon's
        evict→reattach cycle, rapidly, against one tenant directory with
        ``keep_last=2`` — the newest eviction checkpoint must always
        restore, and the rotation bound must hold."""
        from torcheval_tpu.serve import EvalDaemon

        rng = np.random.default_rng(21)
        batches = [
            (
                rng.random((16, 5)).astype(np.float32),
                rng.integers(0, 5, 16),
            )
            for _ in range(6)
        ]
        oracle = MulticlassAccuracy(num_classes=5)
        with EvalDaemon(evict_dir=self.dir, evict_keep_last=2) as daemon:
            h = daemon.attach("churn", MulticlassAccuracy(num_classes=5))
            for s, l in batches:
                h.submit(s, l)
                oracle.update(s, l)
                oracle.compute()  # mirror the per-cycle fold grouping
                daemon.evict("churn", timeout=60)
                h = daemon.attach(
                    "churn",
                    MulticlassAccuracy(num_classes=5),
                    resume="require",
                )
            got = float(np.asarray(h.compute(timeout=60)))
        self.assertEqual(got, float(np.asarray(oracle.compute())))
        tenant_dir = os.path.join(self.dir, "churn")
        self.assertLessEqual(len(list_checkpoints(tenant_dir)), 2)


class TestObsCounters(_TmpDirTest):
    def test_save_restore_counters(self):
        from torcheval_tpu import obs

        m = Sum()
        m.update(jnp.asarray([1.0]))
        obs.enable()
        try:
            obs.reset()
            path = save(m, self.dir)
            restore(Sum(), path)
            snap = obs.snapshot()["counters"]
        finally:
            obs.disable()
            obs.reset()
        self.assertEqual(snap["resilience.checkpoint.saves"], 1.0)
        self.assertEqual(snap["resilience.checkpoint.restores"], 1.0)
        self.assertGreater(snap["resilience.checkpoint.bytes"], 0.0)

def _corrupt_manifest(ckpt):
    with open(os.path.join(ckpt, "manifest.json"), "w") as f:
        f.write('{"truncated mid-wr')


def _flip_payload_byte(ckpt):
    path = os.path.join(ckpt, "state.npz")
    with open(path, "r+b") as f:
        f.seek(12)
        byte = f.read(1)
        f.seek(12)
        f.write(bytes([byte[0] ^ 0xFF]))


class TestLineageFallback(_TmpDirTest):
    """ISSUE 20 tentpole: restore_latest_valid walks newest->oldest past
    corrupt generations, quarantining (renaming, never deleting) each."""

    def _saved_sum(self, *values):
        m = Sum()
        for v in values:
            m.update(jnp.asarray([float(v)]))
            save(m, self.dir)
        return m

    def test_falls_back_past_corrupt_newest_and_quarantines(self):
        from torcheval_tpu import obs

        self._saved_sum(1.0, 2.0)  # gen1 holds 1.0, gen2 holds 3.0
        ckpts = list_checkpoints(self.dir)
        _flip_payload_byte(ckpts[-1])
        obs.enable()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)
        target = Sum()
        restored_path = snapshot_mod.restore_latest_valid(target, self.dir)
        self.assertEqual(restored_path, ckpts[0])
        self.assertEqual(float(np.asarray(target.compute())), 1.0)
        # quarantined: renamed corrupt-*, bytes preserved, no longer listed
        self.assertEqual(list_checkpoints(self.dir), [ckpts[0]])
        corrupt = [
            n for n in os.listdir(self.dir) if n.startswith("corrupt-")
        ]
        self.assertEqual(len(corrupt), 1)
        self.assertTrue(
            os.path.exists(
                os.path.join(self.dir, corrupt[0], "state.npz")
            )
        )
        counters = obs.snapshot()["counters"]
        self.assertEqual(
            counters.get("resilience.checkpoint.corrupt_quarantined"), 1.0
        )
        self.assertEqual(
            counters.get("resilience.checkpoint.fallback_restores"), 1.0
        )

    def test_every_generation_corrupt_raises_not_found(self):
        self._saved_sum(1.0, 2.0)
        for ckpt in list_checkpoints(self.dir):
            _corrupt_manifest(ckpt)
        with self.assertRaises(CheckpointError) as ctx:
            snapshot_mod.restore_latest_valid(Sum(), self.dir)
        self.assertEqual(ctx.exception.reason, "not_found")
        # quarantined, not deleted: both generations' bytes survive
        corrupt = [
            n for n in os.listdir(self.dir) if n.startswith("corrupt-")
        ]
        self.assertEqual(len(corrupt), 2)

    def test_schema_mismatch_raises_without_quarantining(self):
        # A wrong restore TARGET indicts the caller's configuration, not
        # the checkpoint's bytes — quarantining would destroy lineage a
        # correctly-configured caller could still use.
        self._saved_sum(1.0)
        with self.assertRaises(CheckpointError) as ctx:
            snapshot_mod.restore_latest_valid(
                MulticlassAccuracy(num_classes=5), self.dir
            )
        self.assertEqual(ctx.exception.reason, "schema_mismatch")
        self.assertEqual(len(list_checkpoints(self.dir)), 1)
        self.assertEqual(
            [n for n in os.listdir(self.dir) if n.startswith("corrupt-")],
            [],
        )


class TestDiscoveryHardening(_TmpDirTest):
    """ISSUE 20 satellite: one tenant's torn manifest must never raise
    mid-discovery or hide other tenants' recoverable checkpoints."""

    def _tenant(self, name, gens=1):
        sub = os.path.join(self.dir, name)
        m = Sum()
        for i in range(gens):
            m.update(jnp.asarray([1.0]))
            save(m, sub)
        return sub

    def test_corrupt_manifest_skipped_and_counted(self):
        from torcheval_tpu import obs

        good = self._tenant("good")
        bad = self._tenant("bad", gens=2)
        ckpts = list_checkpoints(bad)
        _corrupt_manifest(ckpts[-1])
        obs.enable()
        self.addCleanup(obs.disable)
        self.addCleanup(obs.reset)
        found = snapshot_mod.discover_checkpoints(self.dir)
        # "bad" offers its previous (valid) generation; "good" unaffected
        self.assertEqual(found["bad"], ckpts[0])
        self.assertEqual(found["good"], list_checkpoints(good)[-1])
        self.assertEqual(
            obs.snapshot()["counters"].get(
                "resilience.checkpoint.corrupt_skipped"
                "{reason=corrupt_manifest}"
            ),
            1.0,
        )

    def test_tenant_with_no_readable_generation_is_omitted(self):
        self._tenant("good")
        bad = self._tenant("bad")
        _corrupt_manifest(list_checkpoints(bad)[-1])
        found = snapshot_mod.discover_checkpoints(self.dir)
        self.assertEqual(sorted(found), ["good"])


class TestQuarantineRotationInterplay(_TmpDirTest):
    """ISSUE 20 satellite: the .tmp-* GC and keep_last rotation must
    never collect a corrupt-* quarantine dir or the last valid
    generation — including under the 30-rapid-saves churn pattern."""

    def test_rotation_spares_the_last_valid_generation(self):
        m = Sum()
        for _ in range(3):
            m.update(jnp.asarray([1.0]))
            save(m, self.dir)
        gen1, gen2, gen3 = list_checkpoints(self.dir)
        _corrupt_manifest(gen2)
        _corrupt_manifest(gen3)
        snapshot_mod.rotate_checkpoints(self.dir, keep_last=2)
        # the naive cut would delete gen1 — the only restorable bytes
        self.assertTrue(os.path.exists(gen1))
        restored = Sum()
        self.assertEqual(
            snapshot_mod.restore_latest_valid(restored, self.dir), gen1
        )

    def test_quarantine_survives_rapid_save_churn(self):
        m = Sum()
        m.update(jnp.asarray([1.0]))
        save(m, self.dir)
        quarantined = snapshot_mod.quarantine_checkpoint(
            list_checkpoints(self.dir)[-1]
        )
        # a dead-writer tmp alongside it: the GC must reclaim THIS and
        # only this
        dead_tmp = os.path.join(self.dir, ".tmp-ckpt-00000099-999999999")
        os.makedirs(dead_tmp)
        for _ in range(30):
            m.update(jnp.asarray([1.0]))
            save(m, self.dir, keep_last=2)
        self.assertTrue(os.path.exists(quarantined))
        self.assertFalse(os.path.exists(dead_tmp))
        self.assertLessEqual(len(list_checkpoints(self.dir)), 2)
        # the newest generation is restorable after all that churn
        snapshot_mod.restore_latest_valid(Sum(), self.dir)

    def test_quarantine_collision_names_are_unique(self):
        m = Sum()
        for _ in range(2):
            m.update(jnp.asarray([1.0]))
            save(m, self.dir)
        first, second = list_checkpoints(self.dir)
        q1 = snapshot_mod.quarantine_checkpoint(first)
        # recreate the same step name and quarantine again: the second
        # quarantine must not clobber the first's forensic bytes
        os.rename(second, first)
        q2 = snapshot_mod.quarantine_checkpoint(first)
        self.assertNotEqual(q1, q2)
        self.assertTrue(os.path.exists(q1))
        self.assertTrue(os.path.exists(q2))


if __name__ == "__main__":
    unittest.main()

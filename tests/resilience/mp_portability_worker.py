"""Worker for the cross-host checkpoint portability tests (ISSUE 10
satellite): each invocation is a FRESH process with its own forced CPU
device count, so a save and its restore genuinely cross an ``XLA_FLAGS``
/ mesh boundary the way a migration between differently-sized hosts does.

Modes (argv: <mode> <dir> <device_count> <out_json>):

* ``save_serve``   — run a serve eviction checkpoint (daemon evict) for a
  replicated-state tenant.
* ``resume_serve`` — re-attach with ``resume="require"`` on a different
  device count, stream phase 2, compute.
* ``save_sharded`` / ``restore_sharded`` — a metric whose vector state is
  explicitly SHARDED over a mesh axis sized to the device count; restore
  must succeed on an equal mesh and raise the structured
  ``CheckpointError("unsupported")`` on an unequal one.
* ``save_sliced_sharded`` / ``restore_sliced_plain`` /
  ``restore_sliced_sharded`` — ISSUE 17: a slice-axis-sharded
  ``SlicedMetricCollection`` checkpoint restores REPLICATED on an
  unsharded (1-device) target and re-shards onto an equal mesh; an
  unequal mesh still raises the structured
  ``CheckpointError("unsupported")`` before any state write.
"""

import json
import sys

import numpy as np

NUM_CLASSES = 5
BATCH = 32
PHASE1, PHASE2 = 3, 2
VEC = 8  # divisible by every device count the tests use


def make_batch(i: int):
    rng = np.random.default_rng(1234 + i)
    return (
        rng.random((BATCH, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, BATCH),
    )


def _sharded_metric(n_devices: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torcheval_tpu.metrics.metric import Metric
    from torcheval_tpu.metrics.state import zeros_state

    class VecState(Metric):
        """Minimal metric with one VECTOR state, shardable over 'x'."""

        def __init__(self, **kw):
            super().__init__(**kw)
            self._add_state("v", zeros_state((VEC,), jnp.float32))

        def update(self, x):
            self.v = self.v + self._input(x)
            return self

        def compute(self):
            return jnp.sum(self.v)

        def merge_state(self, metrics):
            for other in metrics:
                self.v = self.v + other.v
            return self

    mesh = Mesh(np.array(jax.devices()), ("x",))
    return VecState().to(NamedSharding(mesh, P("x")))


SLICED_N = 181
SLICED_BATCHES = 4


def make_sliced_batch(i: int):
    rng = np.random.default_rng(4321 + i)
    ids = (rng.zipf(1.4, SLICED_N) * 7919 + 13).astype(np.int64)
    scores = rng.random(SLICED_N).astype(np.float32)
    targets = (rng.random(SLICED_N) < 0.5).astype(np.int32)
    return ids, scores, targets


def _sliced_collection(sharded: bool):
    from torcheval_tpu.metrics import (
        BinaryAccuracy,
        BinaryAUROC,
        SlicedMetricCollection,
    )

    kw = {"mesh_axis": "slices"} if sharded else {}
    return SlicedMetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
        capacity=4,
        **kw,
    )


def _sliced_values(col) -> dict:
    res = col.compute()
    return {
        "ids": np.asarray(res["acc"].slice_ids).tolist(),
        "acc": np.asarray(res["acc"]["values"]).tolist(),
        "auroc": np.asarray(res["auroc"]["values"]).tolist(),
    }


def main() -> None:
    mode, directory, n_devices, out_json = (
        sys.argv[1],
        sys.argv[2],
        int(sys.argv[3]),
        sys.argv[4],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    from torcheval_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(n_devices)
    result = {"devices": len(jax.devices())}

    if mode in ("save_serve", "resume_serve"):
        from torcheval_tpu.metrics import MulticlassAccuracy
        from torcheval_tpu.serve import EvalDaemon

        daemon = EvalDaemon(evict_dir=directory).start()
        if mode == "save_serve":
            handle = daemon.attach(
                "porty", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
            )
            for i in range(PHASE1):
                handle.submit(*make_batch(i))
            result["checkpoint"] = daemon.evict("porty", timeout=120)
        else:
            handle = daemon.attach(
                "porty",
                {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)},
                resume="require",
            )
            for i in range(PHASE1, PHASE1 + PHASE2):
                handle.submit(*make_batch(i))
            result["value"] = float(
                np.asarray(handle.compute(timeout=120)["acc"])
            )
        daemon.stop()
    elif mode == "save_sharded":
        import jax.numpy as jnp

        from torcheval_tpu.resilience import save

        m = _sharded_metric(n_devices)
        m.update(jnp.arange(float(VEC)))
        result["sharding_replicated"] = bool(
            m.v.sharding.is_fully_replicated
        )
        result["checkpoint"] = save(m, directory)
        result["value"] = float(np.asarray(m.compute()))
    elif mode == "restore_sharded":
        from torcheval_tpu.resilience import CheckpointError, restore

        m = _sharded_metric(n_devices)
        try:
            restore(m, directory)
            result["value"] = float(np.asarray(m.compute()))
        except CheckpointError as e:
            result["error_reason"] = e.reason
            result["error_message"] = str(e)
    elif mode == "save_sliced_sharded":
        from torcheval_tpu.resilience import save

        col = _sliced_collection(sharded=True)
        for i in range(SLICED_BATCHES):
            col.update(*make_sliced_batch(i))
        m = col.metrics["auroc"]
        result["sharding_replicated"] = bool(
            m.sketch_tp.sharding.is_fully_replicated
        )
        result["checkpoint"] = save(col, directory)
        result["values"] = _sliced_values(col)
    elif mode in ("restore_sliced_plain", "restore_sliced_sharded"):
        from torcheval_tpu.resilience import CheckpointError, restore

        col = _sliced_collection(sharded=mode.endswith("sharded"))
        try:
            restore(col, directory)
        except CheckpointError as e:
            result["error_reason"] = e.reason
            result["error_message"] = str(e)
        else:
            m = col.metrics["auroc"]
            result["sharding_replicated"] = bool(
                m.sketch_tp.sharding.is_fully_replicated
            )
            # still live post-restore: stream one more batch, then compute
            col.update(*make_sliced_batch(SLICED_BATCHES))
            result["values"] = _sliced_values(col)
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    with open(out_json, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()

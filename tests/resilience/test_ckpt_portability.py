"""Cross-host checkpoint portability (ISSUE 10 satellite).

The cluster router's migration contract rests on ``resilience.restore``
accepting a checkpoint written by a DIFFERENT host — in general a host
with a different ``XLA_FLAGS`` forced device count or mesh. The supported
contract (documented in docs/robustness.md, "Checkpoint portability"):

* **replicated state restores anywhere** — the payload stores global host
  values, so a serve eviction checkpoint from an 8-device host resumes
  bit-identically on a 2-device host (proven here with real fresh
  processes on each side);
* **sharded state requires an equal mesh** — state split across a mesh
  axis restores onto an equal mesh (axis names and sizes) and raises the
  structured ``CheckpointError(reason="unsupported")`` on any other,
  instead of silently re-laying the state out across a topology the
  saver never validated.
"""

import json
import os
import subprocess
import sys
import tempfile
import unittest

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_WORKER = os.path.join(_HERE, "mp_portability_worker.py")

sys.path.insert(0, _HERE)
from mp_portability_worker import (  # noqa: E402
    NUM_CLASSES,
    PHASE1,
    PHASE2,
    make_batch,
)


def _run(mode: str, directory: str, devices: int) -> dict:
    out_json = os.path.join(
        directory, f"{mode}_{devices}.json"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # each worker forces its OWN device count
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, _WORKER, mode, directory, str(devices), out_json],
        env=env,
        capture_output=True,
        timeout=240,
    )
    if proc.returncode != 0:
        raise AssertionError(
            f"{mode} (devices={devices}) failed:\n"
            f"{proc.stdout.decode(errors='replace')[-4000:]}\n"
            f"{proc.stderr.decode(errors='replace')[-4000:]}"
        )
    with open(out_json) as f:
        return json.load(f)


class TestServeCheckpointPortability(unittest.TestCase):
    """A serve eviction checkpoint (replicated state) crosses device
    counts: save on 8 devices, resume on 2, bit-identical to the
    fault-free oracle."""

    def test_evict_on_8_devices_resume_on_2(self):
        root = tempfile.mkdtemp(prefix="tpu_port_serve_")
        saved = _run("save_serve", root, 8)
        self.assertEqual(saved["devices"], 8)
        self.assertTrue(os.path.isdir(saved["checkpoint"]))
        resumed = _run("resume_serve", root, 2)
        self.assertEqual(resumed["devices"], 2)
        from torcheval_tpu.metrics import MulticlassAccuracy

        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for i in range(PHASE1 + PHASE2):
            oracle.update(*make_batch(i))
        self.assertEqual(
            resumed["value"], float(np.asarray(oracle.compute()))
        )

    def test_resume_on_1_device_also_exact(self):
        root = tempfile.mkdtemp(prefix="tpu_port_serve1_")
        _run("save_serve", root, 4)
        resumed = _run("resume_serve", root, 1)
        self.assertEqual(resumed["devices"], 1)
        from torcheval_tpu.metrics import MulticlassAccuracy

        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for i in range(PHASE1 + PHASE2):
            oracle.update(*make_batch(i))
        self.assertEqual(
            resumed["value"], float(np.asarray(oracle.compute()))
        )


class TestShardedStatePortability(unittest.TestCase):
    """Sharded state: equal mesh restores; unequal mesh raises the
    structured ``unsupported`` reason BEFORE any state write."""

    def test_equal_mesh_restores(self):
        root = tempfile.mkdtemp(prefix="tpu_port_shard_eq_")
        saved = _run("save_sharded", root, 8)
        self.assertFalse(saved["sharding_replicated"])  # genuinely sharded
        restored = _run("restore_sharded", root, 8)
        self.assertNotIn("error_reason", restored)
        self.assertEqual(restored["value"], saved["value"])

    def test_unequal_mesh_axis_raises_structured_unsupported(self):
        root = tempfile.mkdtemp(prefix="tpu_port_shard_ne_")
        _run("save_sharded", root, 8)
        restored = _run("restore_sharded", root, 4)
        self.assertEqual(restored.get("error_reason"), "unsupported")
        self.assertIn("mesh", restored["error_message"])


class TestSlicedShardedPortability(unittest.TestCase):
    """ISSUE 17: slice-axis-sharded checkpoints. The payload carries the
    GLOBAL slice-axis value and the block-range layout is a function of
    capacity alone, so a sharded save restores REPLICATED on a 1-device
    host and re-shards bit-identically onto an equal mesh; an unequal
    mesh stays the structured ``unsupported`` failure."""

    @classmethod
    def setUpClass(cls):
        # ONE sharded save feeds all three restore legs (each restore is
        # read-only on the checkpoint; fresh processes per leg regardless)
        cls.root = tempfile.mkdtemp(prefix="tpu_port_sliced_")
        cls.saved = _run("save_sliced_sharded", cls.root, 8)

    def _oracle_values(self):
        from mp_portability_worker import (
            SLICED_BATCHES,
            _sliced_collection,
            _sliced_values,
            make_sliced_batch,
        )

        col = _sliced_collection(sharded=False)
        for i in range(SLICED_BATCHES + 1):  # restore modes add one batch
            col.update(*make_sliced_batch(i))
        return _sliced_values(col)

    def test_sharded_save_restores_replicated_on_1_device(self):
        self.assertFalse(
            self.saved["sharding_replicated"]
        )  # genuinely sharded at save time
        restored = _run("restore_sliced_plain", self.root, 1)
        self.assertNotIn("error_reason", restored)
        self.assertTrue(restored["sharding_replicated"])
        self.assertEqual(restored["values"], self._oracle_values())

    def test_sharded_save_reshards_on_equal_mesh(self):
        restored = _run("restore_sliced_sharded", self.root, 8)
        self.assertNotIn("error_reason", restored)
        self.assertFalse(restored["sharding_replicated"])  # re-sharded
        self.assertEqual(restored["values"], self._oracle_values())

    def test_unequal_mesh_raises_structured_unsupported(self):
        restored = _run("restore_sliced_sharded", self.root, 4)
        self.assertEqual(restored.get("error_reason"), "unsupported")
        self.assertIn("mesh", restored["error_message"])


if __name__ == "__main__":
    unittest.main()

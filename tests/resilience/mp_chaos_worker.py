"""Worker for the 4-process fault-injection test (ISSUE 5 acceptance).

Each process joins a real ``jax.distributed`` CPU world, streams its shard
into a local ``MulticlassAccuracy``, completes one HEALTHY sync, checkpoints
its local replica, streams more, and enters a second sync — at which point
the chaos hooks (armed by the parent via ``TORCHEVAL_TPU_CHAOS_*``) kill
rank 2 with a hard ``os._exit`` as it enters the descriptor round. The
survivors' ``sync_and_compute(..., timeout_s=, on_failure="local")`` must
come back within the deadline with their LOCAL values and the
``toolkit.sync.timeouts{policy=local}`` counter bumped.

Run:  python mp_chaos_worker.py <rank> <world> <port> <outdir>
Writes <outdir>/rank<r>.json and <outdir>/rank<r>.obs.json (the obs registry
snapshot — uploaded as a CI artifact on failure, so a hung CI run leaves a
diagnosable trace of which sync round each rank reached). Rank 2 writes
nothing: it is dead.
"""

import json
import os
import sys
import time

import numpy as np

NUM_CLASSES = 5
BATCH = 48
# the survivors' degraded-mode deadline; the parent asserts the wall time
# of the failed sync stays within a small multiple of this
TIMEOUT_S = 8.0
CHAOS_EXIT_CODE = 43
KILLED_RANK = 2


def make_shard(rank: int, phase: int):
    rng = np.random.default_rng(1000 + 10 * phase + rank)
    scores = rng.random((BATCH, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, BATCH)
    return scores, labels


def main() -> None:
    rank, world, port, outdir = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["MASTER_ADDR"] = "localhost"
    os.environ["MASTER_PORT"] = port
    os.environ["WORLD_SIZE"] = str(world)
    os.environ["RANK"] = str(rank)
    from torcheval_tpu.parallel import init_from_env

    got_rank, got_world = init_from_env()
    assert (got_rank, got_world) == (rank, world)
    import jax.numpy as jnp

    from torcheval_tpu import obs
    from torcheval_tpu.metrics import MulticlassAccuracy
    from torcheval_tpu.metrics.toolkit import sync_and_compute
    from torcheval_tpu.resilience import save

    obs.enable()
    results = {"rank": rank}

    acc = MulticlassAccuracy(num_classes=NUM_CLASSES)
    s0, l0 = make_shard(rank, phase=0)
    acc.update(jnp.asarray(s0), jnp.asarray(l0))

    # --- sync 1 (rounds 1-2): every rank alive, full global value
    r = sync_and_compute(
        acc, recipient_rank="all", timeout_s=60.0, on_failure="local"
    )
    results["sync1"] = float(np.asarray(r))

    # --- pre-fault checkpoint of the LOCAL replica (per-rank directory:
    # state is process-local in the explicit sync model)
    ckpt_dir = os.path.join(outdir, f"ckpt_rank{rank}")
    save(acc, ckpt_dir)
    # repr round-trips the float64 exactly through JSON: the parent asserts
    # the restored compute() is BIT-identical to this
    results["local_compute_at_ckpt"] = float(np.asarray(acc.compute()))

    # --- post-checkpoint stream (these batches are NOT in the checkpoint)
    s1, l1 = make_shard(rank, phase=1)
    acc.update(jnp.asarray(s1), jnp.asarray(l1))
    results["local_compute_post"] = float(np.asarray(acc.compute()))

    # --- sync 2 (rounds 3-4): chaos kills rank 2 entering round 3. The
    # survivors' collective has a dead member and can only hang or error;
    # degraded mode must return the LOCAL value within the deadline.
    t0 = time.monotonic()
    r = sync_and_compute(
        acc, recipient_rank="all", timeout_s=TIMEOUT_S, on_failure="local"
    )
    results["sync2"] = float(np.asarray(r))
    results["sync2_elapsed_s"] = time.monotonic() - t0

    snap = obs.snapshot()
    results["timeouts_local"] = snap["counters"].get(
        "toolkit.sync.timeouts{policy=local}", 0.0
    )
    results["sync_rounds"] = snap["counters"].get("toolkit.sync.rounds", 0.0)

    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, f"rank{rank}.obs.json"), "w") as f:
        json.dump(snap, f, indent=2)
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(results, f)
        f.flush()
        os.fsync(f.fileno())
    # straggler world only: rank 0 hosts the coordination service, and the
    # coordination client hard-aborts (SIGABRT) any process that outlives
    # the leader — so the leader holds until the delayed rank has finished
    # its own (budget-expired) degrade and written its results
    hold_s = float(os.environ.get("TORCHEVAL_TPU_CHAOS_HOLD_S", "0"))
    if rank == 0 and hold_s > 0:
        time.sleep(hold_s)
    # hard exit: after a degraded sync the dead rank's peers must not risk
    # wedging in interpreter teardown (atexit distributed shutdown would
    # wait on a world that no longer exists)
    os._exit(0)


if __name__ == "__main__":
    main()

"""SampleCacheMetric base-class edges (metrics/sample_cache.py).

ISSUE 2 satellite: the empty-cache ``_concat_cache`` fallback used to return
``jnp.empty(shape)`` — silently float32 whatever the cache's element dtype.
The dtype now threads from ``_add_cache_state`` (or an explicit
``empty_dtype``), so an empty ``compute()`` honours the metric's declared
dtype.
"""

import unittest

import jax
import jax.numpy as jnp

from torcheval_tpu.metrics.ranking import HitRate, ReciprocalRank
from torcheval_tpu.metrics.sample_cache import SampleCacheMetric


class _IntCache(SampleCacheMetric[jax.Array]):
    """Minimal integer-cache metric: ids concatenated on read."""

    def __init__(self, *, device=None) -> None:
        super().__init__(device=device)
        self._add_cache_state("ids", dtype=jnp.int32)

    def update(self, ids):
        self.ids.append(self._input(ids))
        return self

    def compute(self) -> jax.Array:
        return self._concat_cache("ids")


class TestEmptyCacheDtype(unittest.TestCase):
    def test_declared_int_dtype_survives_empty_compute(self):
        out = _IntCache().compute()
        self.assertEqual(out.shape, (0,))
        self.assertEqual(out.dtype, jnp.int32)

    def test_empty_then_stream_round_trip(self):
        m = _IntCache()
        self.assertEqual(m.compute().dtype, jnp.int32)
        m.update(jnp.asarray([3, 1, 2], dtype=jnp.int32))
        self.assertEqual(m.compute().dtype, jnp.int32)
        self.assertEqual(m.compute().shape, (3,))

    def test_default_float_caches_unchanged(self):
        # shipped score-cache metrics keep their float32 empty compute
        self.assertEqual(HitRate().compute().dtype, jnp.float32)
        self.assertEqual(ReciprocalRank().compute().dtype, jnp.float32)

    def test_explicit_empty_dtype_overrides(self):
        m = _IntCache()
        out = m._concat_cache("ids", empty_dtype=jnp.float32)
        self.assertEqual(out.dtype, jnp.float32)


if __name__ == "__main__":
    unittest.main()

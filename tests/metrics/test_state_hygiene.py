"""Dispatch-hygiene contracts for the state machinery (metrics/state.py).

On a tunneled chip every device dispatch costs a 0.2-8 ms floor, so metric
construction/reset/clone must not dispatch at all when the backend never
donates buffers. These tests pin the aliasing rules on both sides of the
donation gate — the CPU test backend donates, so the no-donation side is
exercised under a mock, exactly like the collection tests do.
"""

import unittest
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import MulticlassAccuracy, MulticlassF1Score
from torcheval_tpu.metrics.state import (
    _copy_leaf,
    _zeros_template,
    copy_state,
    put_state,
    zeros_state,
)


def _no_donation():
    return mock.patch(
        "torcheval_tpu.utils.platform.donation_pipelines", return_value=False
    )


class TestZerosState(unittest.TestCase):
    def test_cached_template_when_not_donating(self):
        with _no_donation():
            a = zeros_state((7,), jnp.int32)
            b = zeros_state((7,), jnp.int32)
            self.assertIs(a, b)  # shared template: zero dispatches after first
            self.assertIsNot(a, zeros_state((7,), jnp.float32))  # dtype keyed

    def test_fresh_arrays_when_donating(self):
        # donation invalidates buffers: a shared template would die with the
        # first donated fold, so each call must mint a fresh array
        a = zeros_state((7,), jnp.int32)
        b = zeros_state((7,), jnp.int32)
        self.assertIsNot(a, b)

    def test_values_are_zero_either_way(self):
        with _no_donation():
            np.testing.assert_array_equal(np.asarray(zeros_state((3,))), 0.0)
        np.testing.assert_array_equal(np.asarray(zeros_state((3,))), 0.0)


class TestCopyLeaf(unittest.TestCase):
    def test_alias_when_not_donating(self):
        x = jnp.arange(4.0)
        with _no_donation():
            self.assertIs(_copy_leaf(x), x)

    def test_copy_when_donating(self):
        x = jnp.arange(4.0)
        y = _copy_leaf(x)
        self.assertIsNot(y, x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


class TestPutLeafFastPath(unittest.TestCase):
    def test_already_resident_is_identity(self):
        dev = jax.devices()[0]
        x = jax.device_put(jnp.arange(4.0), dev)
        self.assertIs(put_state(x, dev), x)

    def test_cross_device_still_moves(self):
        devs = jax.devices()
        if len(devs) < 2:
            self.skipTest("needs 2 devices")
        x = jax.device_put(jnp.arange(4.0), devs[0])
        y = put_state(x, devs[1])
        self.assertEqual(y.devices(), {devs[1]})


class TestMetricLifecycleUnderAliasing(unittest.TestCase):
    """The correctness story the aliasing must not break, exercised with the
    no-donation gate active end to end."""

    def test_instances_independent_and_reset_true_zero(self):
        with _no_donation():
            a = MulticlassF1Score(num_classes=4, average="macro")
            b = MulticlassF1Score(num_classes=4, average="macro")
            rng = np.random.default_rng(0)
            s = rng.random((64, 4)).astype(np.float32)
            t = rng.integers(0, 4, 64)
            a.update(jnp.asarray(s), jnp.asarray(t))
            va = float(a.compute())
            # b shares zero templates with a but must stay untouched
            self.assertEqual(float(jnp.sum(b.state_dict()["num_tp"])), 0.0)
            a.reset()
            self.assertEqual(float(jnp.sum(a.state_dict()["num_tp"])), 0.0)
            a.update(jnp.asarray(s), jnp.asarray(t))
            self.assertAlmostEqual(float(a.compute()), va, places=6)

    def test_snapshot_survives_later_updates(self):
        with _no_donation():
            m = MulticlassAccuracy(num_classes=4)
            m.update(jnp.eye(4), jnp.arange(4))
            snap = m.state_dict()
            before = float(snap["num_total"])
            m.update(jnp.eye(4), jnp.arange(4))
            self.assertEqual(float(snap["num_total"]), before)

    def test_copy_state_still_copies_containers(self):
        # container copies are structural even when leaves alias: appending
        # to the copy must not grow the original
        with _no_donation():
            cache = [jnp.arange(3.0)]
            c = copy_state(cache)
            self.assertIsNot(c, cache)
            c.append(jnp.arange(2.0))
            self.assertEqual(len(cache), 1)


if __name__ == "__main__":
    unittest.main()

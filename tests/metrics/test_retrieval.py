"""Retrieval metric family (ISSUE 14): NDCG@k / MAP@k / Recall@k (+ the
k-parametrized retrieval HitRate alignment) against a pure-numpy oracle,
the deferred one-program window-step contract, merge_state, checkpoint
round-trip, toolkit sync, and the label-sharded fold path."""

import shutil
import tempfile
import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torcheval_tpu.metrics import MAP, NDCG, MetricCollection, RecallAtK
from torcheval_tpu.metrics.functional import (
    hit_rate,
    map_at_k,
    ndcg_at_k,
    recall_at_k,
    retrieval_hit_rate,
)

RNG = np.random.default_rng(41)
N, L, K = 64, 2048, 10


def _data(graded=False, with_empty_row=True):
    s = RNG.random((N, L)).astype(np.float32)
    t = (RNG.random((N, L)) > 0.995).astype(np.float32)
    if graded:
        t = (t * RNG.integers(1, 4, (N, L))).astype(np.float32)
    if with_empty_row:
        t[0] = 0.0  # a row with no relevant label → NaN / excluded
    return s, t


def oracle(s, t, k):
    """Per-sample numpy oracle: stable argsort = lax.top_k's tie order."""
    order = np.argsort(-s, axis=1, kind="stable")[:, :k]
    rel = np.take_along_axis(t, order, axis=1)
    m = (t > 0).sum(1)
    relb = (rel > 0).astype(np.float64)
    rec = np.where(m > 0, relb.sum(1) / np.maximum(m, 1), np.nan)
    prec = np.cumsum(relb, 1) / np.arange(1, k + 1)
    ap = np.where(
        m > 0, (relb * prec).sum(1) / np.maximum(np.minimum(m, k), 1), np.nan
    )
    disc = 1.0 / np.log2(np.arange(k) + 2)
    dcg = (rel * disc).sum(1)
    ideal = -np.sort(-t, axis=1)[:, :k]
    idcg = (np.maximum(ideal, 0) * disc).sum(1)
    ndcg = np.where(idcg > 0, dcg / np.where(idcg > 0, idcg, 1), np.nan)
    hr = np.where(m > 0, relb.max(1), np.nan)
    return rec, ap, ndcg, hr


class TestFunctionalOracleParity(unittest.TestCase):
    def test_binary_relevance_kernels(self):
        s, t = _data()
        rec_o, ap_o, _, hr_o = oracle(s, t, K)
        np.testing.assert_allclose(
            np.asarray(recall_at_k(s, t, k=K)), rec_o, rtol=1e-6,
            equal_nan=True,
        )
        np.testing.assert_allclose(
            np.asarray(map_at_k(s, t, k=K)), ap_o, rtol=1e-6, equal_nan=True
        )
        np.testing.assert_allclose(
            np.asarray(retrieval_hit_rate(s, t, k=K)), hr_o, rtol=1e-6,
            equal_nan=True,
        )

    def test_graded_ndcg(self):
        s, t = _data(graded=True)
        _, _, ndcg_o, _ = oracle(s, t, K)
        np.testing.assert_allclose(
            np.asarray(ndcg_at_k(s, t, k=K)), ndcg_o, rtol=1e-5,
            equal_nan=True,
        )

    def test_k_none_ranks_every_label(self):
        s, t = _data()
        want = oracle(s, t, L)[0]
        np.testing.assert_allclose(
            np.asarray(recall_at_k(s, t)), want, rtol=1e-6, equal_nan=True
        )

    def test_k_beyond_l_clamps(self):
        s = RNG.random((4, 8)).astype(np.float32)
        t = np.eye(4, 8, dtype=np.float32)
        np.testing.assert_allclose(
            np.asarray(recall_at_k(s, t, k=100)),
            np.asarray(recall_at_k(s, t, k=8)),
        )

    def test_hit_rate_alignment(self):
        # the k-parametrized alignment pass: one-hot targets + tie-free
        # scores ⇒ retrieval_hit_rate == the single-label hit_rate
        s = RNG.random((N, L)).astype(np.float32)
        tgt = RNG.integers(0, L, N)
        onehot = np.zeros((N, L), np.float32)
        onehot[np.arange(N), tgt] = 1.0
        for k in (1, K, None):
            np.testing.assert_array_equal(
                np.asarray(retrieval_hit_rate(s, onehot, k=k)),
                np.asarray(hit_rate(s, tgt, k=k)),
                err_msg=f"k={k}",
            )

    def test_topk_method_paths_agree(self):
        s, t = _data()
        base = np.asarray(ndcg_at_k(s, t, k=K, topk_method="dense"))
        for method in ("pallas", "prune", "auto"):
            np.testing.assert_array_equal(
                np.asarray(ndcg_at_k(s, t, k=K, topk_method=method)),
                base,
                err_msg=method,
            )

    def test_validation(self):
        with self.assertRaises(ValueError):
            recall_at_k(np.zeros((4,)), np.zeros((4,)))
        with self.assertRaises(ValueError):
            recall_at_k(np.zeros((4, 8)), np.zeros((4, 9)))
        with self.assertRaises(ValueError):
            recall_at_k(np.zeros((4, 8)), np.zeros((4, 8)), k=0)
        with self.assertRaises(ValueError):
            NDCG(k=-1)
        with self.assertRaises(ValueError):
            MAP(topk_method="radix")
        with self.assertRaises(ValueError):
            RecallAtK(label_mesh="label")
        # a typo'd axis name must reject at CONSTRUCTION, not as a
        # KeyError at window close after the stream was accepted
        mesh = Mesh(np.asarray(jax.devices()), ("label",))
        with self.assertRaisesRegex(ValueError, "not an.*axis"):
            NDCG(k=3, label_mesh=(mesh, "lable"))
        with self.assertRaisesRegex(ValueError, "batch axes"):
            NDCG(k=3, label_mesh=(mesh, "label", "data"))


class TestClassMetrics(unittest.TestCase):
    def test_mean_over_valid_rows_matches_oracle(self):
        s, t = _data()
        sg = (t * RNG.integers(1, 4, (N, L))).astype(np.float32)
        rec_o, ap_o, _, _ = oracle(s, t, K)
        ndcg_o = oracle(s, sg, K)[2]
        for cls, target, want in (
            (RecallAtK, t, np.nanmean(rec_o)),
            (MAP, t, np.nanmean(ap_o)),
            (NDCG, sg, np.nanmean(ndcg_o)),
        ):
            m = cls(k=K)
            for i in range(0, N, 16):
                m.update(s[i : i + 16], target[i : i + 16])
            self.assertAlmostEqual(
                float(m.compute()), float(want), places=5, msg=cls.__name__
            )

    def test_empty_compute_is_nan(self):
        self.assertTrue(np.isnan(float(NDCG(k=3).compute())))

    def test_merge_state_matches_single_stream(self):
        s, t = _data()
        a, b = MAP(k=5), MAP(k=5)
        a.update(s[:32], t[:32])
        b.update(s[32:], t[32:])
        mono = MAP(k=5)
        mono.update(s, t)
        self.assertEqual(
            float(a.merge_state([b]).compute()), float(mono.compute())
        )

    def test_checkpoint_round_trip(self):
        from torcheval_tpu.resilience import restore, save

        s, t = _data()
        m = RecallAtK(k=K)
        m.update(s[:32], t[:32])
        d = tempfile.mkdtemp(prefix="retrieval_ckpt_")
        try:
            path = save(m, d)
            fresh = RecallAtK(k=K)
            restore(fresh, path)
            # mid-stream restore: the remaining half streams on
            fresh.update(s[32:], t[32:])
            mono = RecallAtK(k=K)
            mono.update(s, t)
            self.assertEqual(float(fresh.compute()), float(mono.compute()))
        finally:
            shutil.rmtree(d, ignore_errors=True)

    def test_toolkit_sync_scalar_sum_states(self):
        # single-process world: sync_and_compute takes the ws-1 no-op lane;
        # what this pins is that the retrieval states RIDE the toolkit
        # surface (SUM scalar lanes, no bespoke machinery) — the real
        # 4-process bit-identity lives in test_multiprocess_sync.py
        import logging

        from torcheval_tpu.metrics.state import Reduction
        from torcheval_tpu.metrics.toolkit import sync_and_compute

        s, t = _data()
        m = NDCG(k=K)
        m.update(s, t)
        self.assertEqual(
            m._state_name_to_reduction,
            {"score_sum": Reduction.SUM, "num_valid": Reduction.SUM},
        )
        logger = logging.getLogger("torcheval_tpu.metrics.toolkit")
        prev_level = logger.level
        logger.setLevel(logging.ERROR)  # silence the expected ws-1 warning
        self.addCleanup(logger.setLevel, prev_level)
        self.assertEqual(
            float(sync_and_compute(m, recipient_rank="all")),
            float(m.compute()),
        )

    def test_window_step_single_program_steady_loop(self):
        # the one-program contract: a steady constant-batch loop through a
        # collection of all three metrics folds + computes in at most TWO
        # deferred.window_step signatures (valve cadence + terminal close),
        # counted RELATIVE to the process's prior jit-cache state
        from torcheval_tpu.obs import recompile

        def sigs():
            return (
                recompile.trace_counts()
                .get("deferred.window_step", {})
                .get("distinct_signatures", 0)
            )

        s, t = _data(with_empty_row=False)
        col = MetricCollection(
            {
                "ndcg": NDCG(k=K),
                "map": MAP(k=K),
                "recall": RecallAtK(k=K),
            }
        )
        # warm one full window cycle at the loop's signature
        for i in range(0, 32, 16):
            col.update(s[i : i + 16], t[i : i + 16])
        col.compute()
        col.reset()
        before = sigs()
        for _ in range(3):
            for i in range(0, N, 16):
                col.update(s[i : i + 16], t[i : i + 16])
            col.compute()
        self.assertLessEqual(sigs() - before, 2)

    def test_update_inside_user_jit(self):
        # tracer transparency (the test_deferred idiom): a user jitting the
        # whole eval step around the metric — tracer args take the eager
        # fold path, no tracer outlives its trace
        s, t = _data(with_empty_row=False)

        def step(si, ti):
            m = RecallAtK(k=5)
            m.update(si, ti)
            self.assertEqual(m._pending, [])  # folded eagerly, not queued
            return m.compute()

        got = jax.jit(step)(jnp.asarray(s[:16]), jnp.asarray(t[:16]))
        mono = RecallAtK(k=5)
        mono.update(s[:16], t[:16])
        self.assertEqual(float(got), float(mono.compute()))


class TestLabelShardedFold(unittest.TestCase):
    """The extreme-vocabulary path: label_mesh threads the sharded engine
    through _fold_params; values must match the dense oracle exactly."""

    def _mesh(self):
        return Mesh(np.asarray(jax.devices()), ("label",))

    def test_label_mesh_matches_dense(self):
        mesh = self._mesh()
        s, t = _data(graded=True)
        want = float(np.nanmean(oracle(s, t, K)[2]))
        m = NDCG(k=K, label_mesh=(mesh, "label"))
        sh = NamedSharding(mesh, P(None, "label"))
        for i in range(0, N, 16):
            m.update(
                jax.device_put(jnp.asarray(s[i : i + 16]), sh),
                jax.device_put(jnp.asarray(t[i : i + 16]), sh),
            )
        self.assertAlmostEqual(float(m.compute()), want, places=5)

    def test_batch_by_label_mesh_three_tuple(self):
        # rows stay data-sharded through the fold: the 3-tuple label_mesh
        # threads batch_axes to the shard_map (inside jit the operand is a
        # tracer, so the engine cannot derive the row sharding itself)
        devs = np.asarray(jax.devices())
        mesh = Mesh(devs.reshape(2, 4), ("data", "label"))
        s, t = _data()
        sh = NamedSharding(mesh, P("data", "label"))
        m = RecallAtK(k=K, label_mesh=(mesh, "label", "data"))
        for i in range(0, N, 16):
            m.update(
                jax.device_put(jnp.asarray(s[i : i + 16]), sh),
                jax.device_put(jnp.asarray(t[i : i + 16]), sh),
            )
        want = float(np.nanmean(oracle(s, t, K)[0]))
        self.assertAlmostEqual(float(m.compute()), want, places=5)

    def test_functional_label_mesh_matches_dense(self):
        mesh = self._mesh()
        s, t = _data()
        sh = NamedSharding(mesh, P(None, "label"))
        got = recall_at_k(
            jax.device_put(jnp.asarray(s), sh),
            jax.device_put(jnp.asarray(t), sh),
            k=K,
            label_mesh=(mesh, "label"),
        )
        np.testing.assert_allclose(
            np.asarray(got), oracle(s, t, K)[0], rtol=1e-6, equal_nan=True
        )


if __name__ == "__main__":
    unittest.main()

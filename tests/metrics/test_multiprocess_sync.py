"""Tier-3 distributed tests: the explicit sync path in 4 REAL processes.

Mirrors the reference's strategy (``torcheval/utils/test_utils/
metric_class_tester.py:272-311``, ``tests/metrics/test_toolkit.py:160-174``):
multi-node is simulated as multi-process single-node. Here each process is a
separate ``jax.distributed`` participant on the CPU backend (Gloo), so the
batched typed wire (``_gather_collection_states`` — descriptor exchange,
empty-rank CAT entries, the uint8 object-gather lane) executes for real, not
via hand-built rank dicts.
"""

import json
import os
import socket
import subprocess
import sys
import unittest

import numpy as np
from sklearn.metrics import roc_auc_score

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_WORKER = os.path.join(_HERE, "mp_sync_worker.py")
WORLD = 4

sys.path.insert(0, _HERE)
from mp_sync_worker import (  # noqa: E402
    AUROC_SIZES,
    NUM_CLASSES,
    RETRIEVAL_K,
    make_acc_shard,
    make_auroc_shard,
    make_dict_updates,
    make_quant_counts,
    make_retrieval_shard,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_world(tmpdir: str) -> list:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # workers pick their own platform (cpu) AND force their own local
    # device count (2, for the sharded sliced scenario) before backend
    # init; scrub any inherited forcing so the worker's choice wins
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(r), str(WORLD), str(port), tmpdir],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for r in range(WORLD)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    for r, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise AssertionError(
                f"worker rank {r} exited {p.returncode}:\n{out[-4000:]}"
            )
    results = []
    for r in range(WORLD):
        with open(os.path.join(tmpdir, f"rank{r}.json")) as f:
            results.append(json.load(f))
    return results


class TestMultiprocessSync(unittest.TestCase):
    """One 4-process launch, many assertions (distributed init dominates the
    cost, so every scenario rides the same world)."""

    @classmethod
    def setUpClass(cls):
        import tempfile

        cls.tmpdir = tempfile.mkdtemp(prefix="tpu_mp_sync_")
        cls.results = _launch_world(cls.tmpdir)

    def test_sum_recipient_permutations(self):
        # per-rank local sums are 3*(rank+1); global = 3*(1+2+3+4) = 30
        for r, res in enumerate(self.results):
            self.assertEqual(res["sum_r0"], 30.0 if r == 0 else None)
            self.assertEqual(res["sum_r1"], 30.0 if r == 1 else None)
            self.assertEqual(res["sum_rall"], 30.0)

    def test_multiclass_accuracy_matches_single_stream(self):
        all_s, all_l = [], []
        for r in range(WORLD):
            s, l = make_acc_shard(r)
            all_s.append(s)
            all_l.append(l)
        scores = np.concatenate(all_s)
        labels = np.concatenate(all_l)
        want = float((scores.argmax(1) == labels).mean())
        for res in self.results:
            self.assertAlmostEqual(res["acc_all"], want, places=6)

    def test_retrieval_family_syncs_bit_identical_across_ranks(self):
        # ISSUE 14: NDCG/MAP/Recall are two scalar SUM lanes — every rank's
        # synced mean must be BIT-identical to every other rank's (same
        # typed-wire reduction on every rank), and match the single-stream
        # oracle that folds all four shards into one replica
        from torcheval_tpu.metrics import MAP, NDCG, RecallAtK

        for key, cls in (("ndcg", NDCG), ("map", MAP), ("recall", RecallAtK)):
            values = {res[f"retrieval_{key}_all"] for res in self.results}
            self.assertEqual(
                len(values), 1, f"{key}: ranks disagree: {values}"
            )
            oracle = cls(k=RETRIEVAL_K)
            for r in range(WORLD):
                s, t = make_retrieval_shard(r)
                oracle.update(s, t)
            self.assertAlmostEqual(
                values.pop(), float(oracle.compute()), places=5, msg=key
            )

    def test_throughput_sum_counts_max_elapsed(self):
        # counts 100+200+300+400 = 1000; elapsed max = 4.0 -> 250
        for res in self.results:
            self.assertAlmostEqual(res["throughput_all"], 250.0, places=5)

    def test_auroc_uneven_cat_with_empty_rank(self):
        self.assertEqual(AUROC_SIZES[2], 0)  # the scenario premise
        all_s, all_t = [], []
        for r in range(WORLD):
            s, t = make_auroc_shard(r)
            all_s.append(s)
            all_t.append(t)
        scores = np.concatenate(all_s)
        targets = np.concatenate(all_t)
        want = roc_auc_score(targets, scores)
        for r, res in enumerate(self.results):
            self.assertAlmostEqual(res["auroc_all"], want, places=5)
            if r == 0:
                self.assertAlmostEqual(res["auroc_r0"], want, places=5)
            else:
                self.assertIsNone(res["auroc_r0"])

    def test_sketch_states_sync_bit_identical_to_oracle(self):
        # ISSUE 13: approx (resident-sketch) metrics over the real wire —
        # exact bucket-add fold, so equality is BIT-level on every rank,
        # incl. under the CI re-run with the quantized codecs forced on
        from torcheval_tpu.metrics import BinaryAUROC, Quantile

        oracle = BinaryAUROC(approx=4096, compaction_threshold=512)
        qoracle = Quantile((0.25, 0.75), bucket_count=4096)
        for r in range(WORLD):
            s, t = make_auroc_shard(r)
            if s.size:
                oracle.update(s, t)
            qoracle.update(make_quant_counts(r).astype(np.float32))
        want = float(oracle.compute())
        qwant = [float(v) for v in np.asarray(qoracle.compute())]
        for res in self.results:
            self.assertEqual(res["sketch_auroc_all"], want)
            self.assertEqual(res["sketch_quantile_all"], qwant)

    def test_sliced_ragged_cohorts_bit_identical_to_oracle(self):
        # ISSUE 15: per-cohort states with RAGGED per-rank populations
        # (overlapping pools, rank 2 empty) over the real transport. The
        # union table is id-sorted and identical on every rank; counter and
        # sketch lanes are integer SUM, so equality is BIT-level — incl.
        # under the CI re-run with TORCHEVAL_TPU_SYNC_QUANTIZE=1.
        from mp_sync_worker import make_sliced_collection, make_sliced_shard

        oracle = make_sliced_collection()
        for r in range(WORLD):
            for b in make_sliced_shard(r):
                oracle.update(*b)
        want = oracle.compute()
        order = np.argsort(want["acc"].slice_ids)
        want_ids = [int(i) for i in want["acc"].slice_ids[order]]
        want_acc = np.asarray(want["acc"]["values"])[order].tolist()
        want_auroc = np.asarray(want["auroc"]["values"])[order].tolist()
        for res in self.results:
            self.assertEqual(res["sliced_ids"], want_ids)
            self.assertEqual(res["sliced_acc"], want_acc)
            self.assertEqual(res["sliced_auroc"], want_auroc)

    def test_sliced_sharded_leg_bit_identical_raw_and_quantized(self):
        # ISSUE 17: the same scenario with the slice axis sharded over
        # each process's LOCAL 2-device mesh. The per-rank states were
        # genuinely split (not replicated) and both the transport-default
        # sync and the explicit quantize=True sync deliver per-slice
        # values bit-identical to the UNSHARDED single-stream oracle.
        from mp_sync_worker import make_sliced_collection, make_sliced_shard

        oracle = make_sliced_collection()
        for r in range(WORLD):
            for b in make_sliced_shard(r):
                oracle.update(*b)
        want = oracle.compute()
        order = np.argsort(want["acc"].slice_ids)
        want_ids = [int(i) for i in want["acc"].slice_ids[order]]
        want_acc = np.asarray(want["acc"]["values"])[order].tolist()
        want_auroc = np.asarray(want["auroc"]["values"])[order].tolist()
        for res in self.results:
            self.assertFalse(res["sliced_sharded_replicated"])
            for prefix in ("sliced_sharded", "sliced_sharded_q"):
                self.assertEqual(res[f"{prefix}_ids"], want_ids)
                self.assertEqual(res[f"{prefix}_acc"], want_acc)
                self.assertEqual(res[f"{prefix}_auroc"], want_auroc)

    def test_sliced_sync_is_two_collective_rounds(self):
        # every slice's state moves in the SAME two rounds — the slice
        # axis widens lanes, never adds collectives
        for res in self.results:
            self.assertEqual(res["rounds_sliced"], 2)

    def test_synced_metric_and_state_dict_on_rank_1(self):
        total = WORLD * 64
        for r, res in enumerate(self.results):
            if r == 1:
                self.assertIsNotNone(res["synced_metric_r1"])
                self.assertEqual(
                    res["synced_sd_r1_keys"], ["num_correct", "num_total"]
                )
                self.assertEqual(res["synced_sd_r1_num_total"], float(total))
            else:
                self.assertIsNone(res["synced_metric_r1"])
                self.assertEqual(res["synced_sd_r1_keys"], [])

    def test_collection_single_gather_pass(self):
        # values must equal the per-metric syncs computed in the same world
        all_s, all_t = [], []
        for r in range(WORLD):
            s, t = make_auroc_shard(r)
            all_s.append(s)
            all_t.append(t)
        want_auroc = roc_auc_score(np.concatenate(all_t), np.concatenate(all_s))
        want_dict = sum(v for r in range(WORLD) for _, v in make_dict_updates(r))
        for r, res in enumerate(self.results):
            col = res["collection_all"]
            self.assertEqual(
                sorted(col), ["acc", "auroc", "dict", "sum", "tp"]
            )
            self.assertAlmostEqual(col["acc"], res["acc_all"], places=6)
            self.assertAlmostEqual(col["sum"], 30.0, places=5)
            self.assertAlmostEqual(col["auroc"], want_auroc, places=5)
            self.assertAlmostEqual(col["dict"], want_dict, places=5)
            self.assertAlmostEqual(col["tp"], 250.0, places=5)
            if r == 1:
                self.assertEqual(
                    res["collection_r1"], ["acc", "auroc", "dict", "sum", "tp"]
                )
            else:
                self.assertIsNone(res["collection_r1"])

    def test_windowed_deque_sync_preserves_entry_boundaries(self):
        # 8 updates worldwide into a window of 6: the object-lane sync must
        # keep per-update rows (a CAT concat would collapse each rank's
        # window into one row and the bound would miscount)
        for res in self.results:
            self.assertAlmostEqual(res["windowed_ctr_lifetime"], 0.5, places=6)
            self.assertAlmostEqual(
                res["windowed_ctr_windowed"], 16.0 / 24.0, places=6
            )

    def test_sync_is_two_collective_rounds(self):
        # the wire-cost contract (counted inside the real 4-process world):
        # descriptor matrix + byte payload, independent of state count —
        # for a 2-SUM-state metric, a 2-CAT-cache metric, and a whole
        # 3-metric array-lane collection alike
        for res in self.results:
            self.assertEqual(res["rounds_acc"], 2)
            self.assertEqual(res["rounds_auroc"], 2)
            self.assertEqual(res["rounds_collection"], 2)

    def test_obs_collective_accounting(self):
        # ISSUE 1 acceptance: the same two-round invariant read from the obs
        # registry on every rank of the real 4-process world, with nonzero
        # payload bytes per populated Reduction lane and the true world size
        for res in self.results:
            self.assertEqual(res["obs_acc_rounds"], 2)
            self.assertEqual(res["obs_auroc_rounds"], 2)
            self.assertGreater(res["obs_acc_sum_lane_bytes"], 0)
            self.assertGreater(res["obs_acc_payload_bytes"], 0)
            self.assertEqual(res["obs_world_size"], 4)
            # CAT lane bytes are local: nonzero exactly where the rank's
            # cache holds samples (rank 2's shard is deliberately empty)
            if AUROC_SIZES[res["rank"]]:
                self.assertGreater(res["obs_auroc_cat_lane_bytes"], 0)
            else:
                self.assertEqual(res["obs_auroc_cat_lane_bytes"], 0)

    def test_lane_bytes_raw_and_encoded_agree_on_raw_codec(self):
        # ISSUE 12 satellite: when the codec is raw the lane_bytes /
        # lane_bytes_encoded pair must agree EXACTLY — the guard against
        # silent double-count regressions in either counter. (Holds under
        # the TORCHEVAL_TPU_SYNC_QUANTIZE=1 CI rerun too: accuracy's
        # states sit below the quantization floor and stay raw.)
        for res in self.results:
            self.assertEqual(
                res["obs_acc_sum_lane_bytes"],
                res["obs_acc_sum_lane_bytes_encoded_raw"],
            )

    def test_quantized_sync_over_real_transport(self):
        # ISSUE 12 acceptance, on the real 4-process Gloo wire: integer
        # lanes bit-exact, f32 drift within the documented bound, still
        # two collective rounds, and the encoded payload >= 4x below raw
        # on the integer-lane-dominant state
        for res in self.results:
            self.assertTrue(res["quant_int_exact"])
            self.assertTrue(res["quant_f32_within_bound"])
            self.assertEqual(res["quant_rounds"], 2)
            self.assertGreater(res["quant_lane_bytes_raw"], 0)
            self.assertLessEqual(
                res["quant_lane_bytes_encoded"] * 4,
                res["quant_lane_bytes_raw"],
            )

    def test_window_config_drift_raises_uniformly(self):
        # window_size drift across ranks: the schema digest (which folds in
        # _sync_schema_extra) mismatches and EVERY rank raises — the typed
        # fold never reaches merge_state's local validation
        for res in self.results:
            self.assertTrue(res["wctr_config_drift_error"])

    def test_windowed_sync_rides_typed_wire(self):
        # round-4 verdict ask 5: WINDOW deque states travel on the typed
        # two-round wire (stacked per-update rows), not the pickled object
        # lane — same window result, two collective rounds; the object lane
        # stays reserved for dict-keyed states (2 typed + 2 object rounds
        # for a mixed collection)
        for res in self.results:
            self.assertEqual(res["rounds_wctr"], 2)
            self.assertEqual(res["rounds_wctr_plus_dict"], 4)
            self.assertAlmostEqual(
                res["wctr_typed_value"], 16.0 / 24.0, places=6
            )

    def test_subgroup_sync(self):
        # processes=[1, 3]: members fold only each other's state; ranks 0/2
        # never enter the collective and get an eager non-member ValueError
        sub_scores = np.concatenate([make_auroc_shard(1)[0], make_auroc_shard(3)[0]])
        sub_targets = np.concatenate([make_auroc_shard(1)[1], make_auroc_shard(3)[1]])
        want_auroc = roc_auc_score(sub_targets, sub_scores)
        want_dict = sum(
            v for r in (1, 3) for _, v in make_dict_updates(r)
        )
        for r, res in enumerate(self.results):
            if r in (1, 3):
                # Sum over member ranks only: 10*(1+1) + 10*(3+1) = 60
                self.assertEqual(res["subgroup_sum_all"], 60.0)
                self.assertEqual(
                    res["subgroup_sum_r3"], 60.0 if r == 3 else None
                )
                self.assertTrue(res["subgroup_bad_recipient"])
                col = res["subgroup_collection"]
                self.assertEqual(col["s"], 60.0)
                self.assertAlmostEqual(col["auroc"], want_auroc, places=5)
                self.assertAlmostEqual(col["d"], want_dict, places=5)
                self.assertEqual(
                    res["subgroup_sd_r1"], 60.0 if r == 1 else None
                )
            else:
                self.assertTrue(res["subgroup_nonmember_error"])

    def test_dict_state_object_gather(self):
        want = sum(v for r in range(WORLD) for _, v in make_dict_updates(r))
        keys = sorted(
            {k for r in range(WORLD) for k, _ in make_dict_updates(r)}
        )
        for r, res in enumerate(self.results):
            self.assertAlmostEqual(res["dict_all"], want, places=5)
            self.assertEqual(res["dict_keys_r0"], keys if r == 0 else None)


if __name__ == "__main__":
    unittest.main()

"""Direct value parity against the reference implementation itself.

The oracle sweep (`tests/metrics/test_oracle_sweep.py`) checks our kernels
against sklearn and hand-written oracles; this module closes the remaining
gap by running the SAME random inputs through the actual reference
(`/root/reference` torcheval, torch CPU) and through this framework, and
asserting the outputs match for every functional export and its option
grid. Where the two frameworks deliberately diverge (reference bugs fixed,
not reproduced — README "Porting from torcheval" §4), the divergence itself
is asserted, so every documented deviation is pinned by a test rather than
prose.

Parity-grid inputs are constructed so every class appears in both `target`
and `pred`: undefined per-class values are exactly where the frameworks'
conventions differ (ours NaN-marks, the reference warns and zeros), and
those conventions are covered by the oracle sweep, not here.
"""

import sys
import unittest

import jax.numpy as jnp
import numpy as np

import pytest

sys.path.insert(0, "/root/reference")
torch = pytest.importorskip(
    "torch", reason="reference parity needs torch"
)
# skip (not error) where the reference checkout is absent: these tests pin
# parity against /root/reference and are meaningless without it
pytest.importorskip(
    "torcheval.metrics.functional",
    reason="reference torcheval checkout not present at /root/reference",
)
import torcheval.metrics.functional as RF  # noqa: E402

import torcheval_tpu.metrics.functional as F  # noqa: E402

SEEDS = (0, 1, 2)


def _close(ours, ref, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(ref), rtol=rtol, atol=atol, equal_nan=True
    )


def _cls_batch(rng, n, c):
    """Scores and labels where every class appears in target AND argmax-pred."""
    scores = rng.random((n, c)).astype(np.float32)
    labels = rng.integers(0, c, n)
    labels[:c] = np.arange(c)  # every class in target
    scores[np.arange(c), np.arange(c)] += 2.0  # every class in pred
    return scores, labels


class TestClassificationParity(unittest.TestCase):
    def test_multiclass_accuracy_grid(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s, l = _cls_batch(rng, 200, 7)
            ts, tl = torch.from_numpy(s), torch.from_numpy(l)
            js, jl = jnp.asarray(s), jnp.asarray(l)
            for average in ("micro", "macro", None):
                _close(
                    F.multiclass_accuracy(js, jl, average=average, num_classes=7),
                    RF.multiclass_accuracy(ts, tl, average=average, num_classes=7),
                )
            for k in (1, 2, 3):
                _close(
                    F.multiclass_accuracy(js, jl, k=k, num_classes=7),
                    RF.multiclass_accuracy(ts, tl, k=k, num_classes=7),
                )

    def test_binary_threshold_family_grid(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            x = rng.random(300).astype(np.float32)
            # int targets: the reference's precision/recall kernels use
            # bitwise ops that reject float targets
            t = (rng.random(300) < 0.4).astype(np.int64)
            tx, tt = torch.from_numpy(x), torch.from_numpy(t)
            jx, jt = jnp.asarray(x), jnp.asarray(t)
            for threshold in (0.25, 0.5, 0.75):
                for ours, ref in (
                    (F.binary_accuracy, RF.binary_accuracy),
                    (F.binary_f1_score, RF.binary_f1_score),
                    (F.binary_precision, RF.binary_precision),
                    (F.binary_recall, RF.binary_recall),
                ):
                    _close(
                        ours(jx, jt, threshold=threshold),
                        ref(tx, tt, threshold=threshold),
                    )

    def test_multiclass_prf_grid(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s, l = _cls_batch(rng, 250, 6)
            ts, tl = torch.from_numpy(s), torch.from_numpy(l)
            js, jl = jnp.asarray(s), jnp.asarray(l)
            for average in ("micro", "macro", "weighted", None):
                for ours, ref in (
                    (F.multiclass_f1_score, RF.multiclass_f1_score),
                    (F.multiclass_precision, RF.multiclass_precision),
                    (F.multiclass_recall, RF.multiclass_recall),
                ):
                    _close(
                        ours(js, jl, average=average, num_classes=6),
                        ref(ts, tl, average=average, num_classes=6),
                    )

    def test_multilabel_accuracy_grid(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s = rng.random((100, 5)).astype(np.float32)
            t = (rng.random((100, 5)) < 0.5).astype(np.float32)
            ts, tt = torch.from_numpy(s), torch.from_numpy(t)
            js, jt = jnp.asarray(s), jnp.asarray(t)
            for criteria in ("exact_match", "hamming", "overlap", "contain", "belong"):
                _close(
                    F.multilabel_accuracy(js, jt, criteria=criteria),
                    RF.multilabel_accuracy(ts, tt, criteria=criteria),
                )

    def test_topk_multilabel_parity_at_k2(self):
        # the reference hardcodes k=2 regardless of the k argument (its
        # documented bug, fixed on our side) — parity holds exactly at k=2
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s = rng.random((80, 6)).astype(np.float32)
            t = (rng.random((80, 6)) < 0.3).astype(np.float32)
            ts, tt = torch.from_numpy(s), torch.from_numpy(t)
            js, jt = jnp.asarray(s), jnp.asarray(t)
            for criteria in ("exact_match", "hamming", "overlap", "contain", "belong"):
                _close(
                    F.topk_multilabel_accuracy(js, jt, criteria=criteria, k=2),
                    RF.topk_multilabel_accuracy(ts, tt, criteria=criteria, k=2),
                )

    def test_auroc_and_curves(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            x = rng.random(500).astype(np.float32)
            t = (rng.random(500) < 0.5).astype(np.float32)
            tx, tt = torch.from_numpy(x), torch.from_numpy(t)
            jx, jt = jnp.asarray(x), jnp.asarray(t)
            _close(F.binary_auroc(jx, jt), RF.binary_auroc(tx, tt), rtol=1e-4)
            ours = F.binary_precision_recall_curve(jx, jt)
            ref = RF.binary_precision_recall_curve(tx, tt)
            for o, r in zip(ours, ref):
                _close(o, r, rtol=1e-4)

    def test_multiclass_prc(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s, l = _cls_batch(rng, 120, 4)
            ts, tl = torch.from_numpy(s), torch.from_numpy(l)
            js, jl = jnp.asarray(s), jnp.asarray(l)
            ours = F.multiclass_precision_recall_curve(js, jl, num_classes=4)
            ref = RF.multiclass_precision_recall_curve(ts, tl, num_classes=4)
            for o_list, r_list in zip(ours, ref):
                self.assertEqual(len(o_list), len(r_list))
                for o, r in zip(o_list, r_list):
                    _close(o, r, rtol=1e-4)

    def test_binned_prc_grid(self):
        explicit = [0.0, 0.2, 0.5, 0.8, 1.0]
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            x = rng.random(400).astype(np.float32)
            # int targets: the reference's binned update uses bitwise ops
            t = (rng.random(400) < 0.4).astype(np.int64)
            tx, tt = torch.from_numpy(x), torch.from_numpy(t)
            jx, jt = jnp.asarray(x), jnp.asarray(t)
            for threshold in (10, 100, explicit):
                ours = F.binary_binned_precision_recall_curve(
                    jx, jt, threshold=threshold
                )
                ref = RF.binary_binned_precision_recall_curve(
                    tx, tt, threshold=threshold
                )
                for o, r in zip(ours, ref):
                    _close(o, r, rtol=1e-4)

    def test_multiclass_binned_prc(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s, l = _cls_batch(rng, 150, 4)
            ts, tl = torch.from_numpy(s), torch.from_numpy(l)
            js, jl = jnp.asarray(s), jnp.asarray(l)
            ours = F.multiclass_binned_precision_recall_curve(
                js, jl, num_classes=4, threshold=20
            )
            ref = RF.multiclass_binned_precision_recall_curve(
                ts, tl, num_classes=4, threshold=20
            )
            for o_part, r_part in zip(ours[:2], ref[:2]):  # per-class lists
                for o, r in zip(o_part, r_part):
                    _close(o, r, rtol=1e-4)
            _close(ours[2], ref[2], rtol=1e-6)  # shared threshold grid

    def test_normalized_entropy_grid(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            p = rng.uniform(0.05, 0.95, 300).astype(np.float32)
            t = (rng.random(300) < 0.35).astype(np.float32)
            w = rng.uniform(0.5, 2.0, 300).astype(np.float32)
            logits = np.log(p / (1 - p)).astype(np.float32)
            tp, tt, tw = map(torch.from_numpy, (p, t, w))
            jp, jt, jw = map(jnp.asarray, (p, t, w))
            _close(
                F.binary_normalized_entropy(jp, jt),
                RF.binary_normalized_entropy(tp, tt),
                rtol=1e-4,
            )
            _close(
                F.binary_normalized_entropy(jp, jt, weight=jw),
                RF.binary_normalized_entropy(tp, tt, weight=tw),
                rtol=1e-4,
            )
            _close(
                F.binary_normalized_entropy(
                    jnp.asarray(logits), jt, from_logits=True
                ),
                RF.binary_normalized_entropy(
                    torch.from_numpy(logits), tt, from_logits=True
                ),
                rtol=1e-4,
            )
            # multi-task lane
            p2 = rng.uniform(0.05, 0.95, (2, 150)).astype(np.float32)
            t2 = (rng.random((2, 150)) < 0.4).astype(np.float32)
            _close(
                F.binary_normalized_entropy(jnp.asarray(p2), jnp.asarray(t2), num_tasks=2),
                RF.binary_normalized_entropy(
                    torch.from_numpy(p2), torch.from_numpy(t2), num_tasks=2
                ),
                rtol=1e-4,
            )


class TestRankingRegressionAggregationParity(unittest.TestCase):
    def test_hit_rate_and_reciprocal_rank(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            s = rng.random((60, 9)).astype(np.float32)
            t = rng.integers(0, 9, 60)
            ts, tl = torch.from_numpy(s), torch.from_numpy(t)
            js, jl = jnp.asarray(s), jnp.asarray(t)
            for k in (None, 1, 3, 9):
                _close(
                    F.hit_rate(js, jl, k=k), RF.hit_rate(ts, tl, k=k)
                )
                _close(
                    F.reciprocal_rank(js, jl, k=k),
                    RF.reciprocal_rank(ts, tl, k=k),
                )

    def test_frequency_and_collisions(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            x = rng.integers(0, 20, 100)
            xf = x.astype(np.float32)
            for k in (0.0, 3.0, 10.5):
                _close(
                    F.frequency_at_k(jnp.asarray(xf), k),
                    RF.frequency_at_k(torch.from_numpy(xf), k),
                )
            _close(
                F.num_collisions(jnp.asarray(x.astype(np.int64))),
                RF.num_collisions(torch.from_numpy(x.astype(np.int64))),
            )

    def test_mse_and_r2_grid(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            i1 = rng.random(120).astype(np.float32)
            t1 = rng.random(120).astype(np.float32)
            i2 = rng.random((120, 3)).astype(np.float32)
            t2 = rng.random((120, 3)).astype(np.float32)
            w = rng.uniform(0.1, 2.0, 120).astype(np.float32)
            for (oi, ot), (ti, tt) in (
                ((jnp.asarray(i1), jnp.asarray(t1)),
                 (torch.from_numpy(i1), torch.from_numpy(t1))),
                ((jnp.asarray(i2), jnp.asarray(t2)),
                 (torch.from_numpy(i2), torch.from_numpy(t2))),
            ):
                for multioutput in ("uniform_average", "raw_values"):
                    _close(
                        F.mean_squared_error(oi, ot, multioutput=multioutput),
                        RF.mean_squared_error(ti, tt, multioutput=multioutput),
                        rtol=1e-4,
                    )
                    _close(
                        F.mean_squared_error(
                            oi, ot, sample_weight=jnp.asarray(w),
                            multioutput=multioutput,
                        ),
                        RF.mean_squared_error(
                            ti, tt, sample_weight=torch.from_numpy(w),
                            multioutput=multioutput,
                        ),
                        rtol=1e-4,
                    )
                for multioutput in (
                    "uniform_average", "raw_values", "variance_weighted"
                ):
                    _close(
                        F.r2_score(oi, ot, multioutput=multioutput),
                        RF.r2_score(ti, tt, multioutput=multioutput),
                        rtol=1e-4,
                    )
                _close(
                    F.r2_score(oi, ot, num_regressors=2),
                    RF.r2_score(ti, tt, num_regressors=2),
                    rtol=1e-4,
                )

    def test_sum_weights(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            x = rng.random(64).astype(np.float32)
            w = rng.random(64).astype(np.float32)
            _close(F.sum(jnp.asarray(x)), RF.sum(torch.from_numpy(x)), rtol=1e-5)
            _close(
                F.sum(jnp.asarray(x), 2.5),
                RF.sum(torch.from_numpy(x), 2.5),
                rtol=1e-5,
            )
            _close(
                F.sum(jnp.asarray(x), jnp.asarray(w)),
                RF.sum(torch.from_numpy(x), torch.from_numpy(w)),
                rtol=1e-5,
            )


class TestDocumentedDeviations(unittest.TestCase):
    """README Porting §4: reference bugs are FIXED, not reproduced. Each
    deviation is pinned here: the reference exhibits the bug, we don't."""

    def test_topk_multilabel_reference_ignores_k(self):
        rng = np.random.default_rng(0)
        s = rng.random((50, 8)).astype(np.float32)
        t = (rng.random((50, 8)) < 0.3).astype(np.float32)
        ts, tt = torch.from_numpy(s), torch.from_numpy(t)
        # the reference returns the SAME value for k=3 as for k=2
        # (torcheval topk_multilabel_accuracy hardcodes k=2 internally)
        ref_k2 = float(RF.topk_multilabel_accuracy(ts, tt, criteria="contain", k=2))
        ref_k3 = float(RF.topk_multilabel_accuracy(ts, tt, criteria="contain", k=3))
        self.assertEqual(ref_k2, ref_k3)  # the bug, demonstrated
        # ours honors k: k=3 "contain" can only match MORE rows than k=2
        js, jt = jnp.asarray(s), jnp.asarray(t)
        ours_k2 = float(F.topk_multilabel_accuracy(js, jt, criteria="contain", k=2))
        ours_k3 = float(F.topk_multilabel_accuracy(js, jt, criteria="contain", k=3))
        self.assertEqual(ours_k2, ref_k2)  # parity where the reference is right
        self.assertGreater(ours_k3, ours_k2)  # and k actually does something

    def test_functional_mean_export(self):
        # reference lists "mean" in functional.__all__ but never imports it
        # (the documented export bug); ours exports a working mean
        self.assertIn("mean", RF.__all__)
        self.assertFalse(hasattr(RF, "mean"))
        x = jnp.asarray(np.asarray([1.0, 2.0, 3.0], np.float32))
        self.assertAlmostEqual(float(F.mean(x)), 2.0, places=6)


if __name__ == "__main__":
    unittest.main()

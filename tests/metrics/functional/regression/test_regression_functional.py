"""Functional regression metrics vs sklearn oracles (SURVEY §4 tier 1)."""

import unittest

import numpy as np
from sklearn.metrics import mean_squared_error as sk_mse
from sklearn.metrics import r2_score as sk_r2

from torcheval_tpu.metrics.functional import mean_squared_error, r2_score


class TestMeanSquaredError(unittest.TestCase):
    def _check(self, input, target, sample_weight=None, multioutput="uniform_average"):
        got = mean_squared_error(
            input, target, sample_weight=sample_weight, multioutput=multioutput
        )
        sk_multi = "raw_values" if multioutput == "raw_values" else "uniform_average"
        want = sk_mse(
            target, input, sample_weight=sample_weight, multioutput=sk_multi
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)

    def test_1d(self):
        rng = np.random.default_rng(0)
        self._check(rng.random(100).astype(np.float32), rng.random(100).astype(np.float32))

    def test_2d(self):
        rng = np.random.default_rng(1)
        x = rng.random((50, 3)).astype(np.float32)
        y = rng.random((50, 3)).astype(np.float32)
        self._check(x, y)
        self._check(x, y, multioutput="raw_values")

    def test_weighted(self):
        rng = np.random.default_rng(2)
        x = rng.random((40, 2)).astype(np.float32)
        y = rng.random((40, 2)).astype(np.float32)
        w = rng.random(40).astype(np.float32)
        self._check(x, y, sample_weight=w)
        self._check(x, y, sample_weight=w, multioutput="raw_values")

    def test_docstring_values(self):
        got = mean_squared_error(
            np.array([0.9, 0.5, 0.3, 0.5]), np.array([0.5, 0.8, 0.2, 0.8])
        )
        np.testing.assert_allclose(float(got), 0.0875, rtol=1e-5)

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "multioutput"):
            mean_squared_error(np.zeros(4), np.zeros(4), multioutput="bogus")
        with self.assertRaisesRegex(ValueError, "same size"):
            mean_squared_error(np.zeros(4), np.zeros(5))
        with self.assertRaisesRegex(ValueError, "1D or 2D"):
            mean_squared_error(np.zeros((2, 2, 2)), np.zeros((2, 2, 2)))
        with self.assertRaisesRegex(ValueError, "sample_weight"):
            mean_squared_error(
                np.zeros(4), np.zeros(4), sample_weight=np.ones(3)
            )

    def test_2d_sample_weight_rejected(self):
        # a (n, d) weight would mis-broadcast in the weighted fold; the
        # documented shape is (n_sample,) only
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            mean_squared_error(
                np.zeros((4, 2)), np.zeros((4, 2)), sample_weight=np.ones((4, 2))
            )


class TestR2Score(unittest.TestCase):
    def _check(self, input, target, multioutput="uniform_average"):
        got = r2_score(input, target, multioutput=multioutput)
        want = sk_r2(target, input, multioutput=multioutput)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-6)

    def test_1d(self):
        rng = np.random.default_rng(3)
        y = rng.random(100).astype(np.float32)
        x = y + 0.1 * rng.random(100).astype(np.float32)
        self._check(x, y)

    def test_2d_all_multioutput(self):
        rng = np.random.default_rng(4)
        y = rng.random((60, 3)).astype(np.float32)
        x = y + 0.05 * rng.standard_normal((60, 3)).astype(np.float32)
        for mo in ("uniform_average", "raw_values", "variance_weighted"):
            self._check(x, y, multioutput=mo)

    def test_adjusted(self):
        got = r2_score(
            np.array([1.2, 2.5, 3.6, 4.5, 6.0]),
            np.array([1.0, 2.0, 3.0, 4.0, 5.0]),
            multioutput="raw_values",
            num_regressors=2,
        )
        np.testing.assert_allclose(float(got), 0.62, rtol=1e-4)

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "multioutput"):
            r2_score(np.zeros(4), np.zeros(4), multioutput="bogus")
        with self.assertRaisesRegex(ValueError, "num_regressors"):
            r2_score(np.zeros(4), np.zeros(4), num_regressors=-1)
        with self.assertRaisesRegex(ValueError, "num_regressors"):
            r2_score(np.arange(4.0), np.arange(4.0), num_regressors=3)
        with self.assertRaisesRegex(ValueError, "at least two samples"):
            r2_score(np.zeros(1), np.zeros(1))


if __name__ == "__main__":
    unittest.main()

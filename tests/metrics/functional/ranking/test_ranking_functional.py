"""Functional ranking metrics (SURVEY §4 tier 1): reference docstring values
plus a brute-force numpy oracle for random inputs."""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.functional import (
    frequency_at_k,
    hit_rate,
    num_collisions,
    reciprocal_rank,
)

INPUT = np.array(
    [[0.3, 0.1, 0.6], [0.5, 0.2, 0.3], [0.2, 0.1, 0.7], [0.3, 0.3, 0.4]],
    dtype=np.float32,
)
TARGET = np.array([2, 1, 1, 0])


def _ranks(scores: np.ndarray, target: np.ndarray) -> np.ndarray:
    y = scores[np.arange(len(target)), target]
    return (scores > y[:, None]).sum(axis=1)


class TestHitRate(unittest.TestCase):
    def test_docstring(self):
        np.testing.assert_allclose(
            np.asarray(hit_rate(INPUT, TARGET, k=2)), [1.0, 0.0, 0.0, 1.0]
        )

    def test_k_none_all_hit(self):
        np.testing.assert_allclose(
            np.asarray(hit_rate(INPUT, TARGET)), np.ones(4)
        )

    def test_random_vs_oracle(self):
        rng = np.random.default_rng(0)
        scores = rng.random((200, 17)).astype(np.float32)
        target = rng.integers(0, 17, 200)
        for k in (1, 3, 16):
            want = (_ranks(scores, target) < k).astype(np.float32)
            np.testing.assert_allclose(
                np.asarray(hit_rate(scores, target, k=k)), want
            )

    def test_k_none_nan_poisons_invalid_targets_under_jit(self):
        # the k=None fast path must apply the same NaN validity mask as the
        # k-set kernel when tracing suppresses the eager range check
        import jax

        out = jax.jit(lambda i, t: hit_rate(i, t))(
            jnp.ones((3, 4)), jnp.asarray([0, 5, -1])
        )
        got = np.asarray(out)
        self.assertEqual(got[0], 1.0)
        self.assertTrue(np.isnan(got[1]) and np.isnan(got[2]))

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "two-dimensional"):
            hit_rate(np.zeros(4), TARGET)
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            hit_rate(INPUT, INPUT)
        with self.assertRaisesRegex(ValueError, "minibatch"):
            hit_rate(INPUT, np.array([0, 1]))
        with self.assertRaisesRegex(ValueError, "positive"):
            hit_rate(INPUT, TARGET, k=0)


class TestReciprocalRank(unittest.TestCase):
    def test_docstring(self):
        np.testing.assert_allclose(
            np.asarray(reciprocal_rank(INPUT, TARGET)),
            [1.0, 1 / 3, 1 / 3, 0.5],
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(reciprocal_rank(INPUT, TARGET, k=2)),
            [1.0, 0.0, 0.0, 0.5],
            rtol=1e-6,
        )

    def test_random_vs_oracle(self):
        rng = np.random.default_rng(1)
        scores = rng.random((100, 9)).astype(np.float32)
        target = rng.integers(0, 9, 100)
        rank = _ranks(scores, target)
        want = 1.0 / (rank + 1.0)
        np.testing.assert_allclose(
            np.asarray(reciprocal_rank(scores, target)), want, rtol=1e-6
        )
        want_k = np.where(rank >= 3, 0.0, want)
        np.testing.assert_allclose(
            np.asarray(reciprocal_rank(scores, target, k=3)), want_k, rtol=1e-6
        )


class TestNumCollisions(unittest.TestCase):
    def test_docstring(self):
        np.testing.assert_array_equal(
            np.asarray(num_collisions(np.array([3, 4, 2, 3]))), [1, 0, 0, 1]
        )
        np.testing.assert_array_equal(
            np.asarray(num_collisions(np.array([3, 4, 1, 3, 1, 1, 5]))),
            [1, 0, 2, 1, 2, 2, 0],
        )

    def test_random_vs_oracle(self):
        rng = np.random.default_rng(2)
        ids = rng.integers(0, 50, 500)
        counts = np.bincount(ids, minlength=50)
        want = counts[ids] - 1
        np.testing.assert_array_equal(np.asarray(num_collisions(ids)), want)

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "integer"):
            num_collisions(np.array([0.5, 1.0]))
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            num_collisions(np.zeros((2, 2), dtype=np.int32))


class TestFrequencyAtK(unittest.TestCase):
    def test_docstring(self):
        np.testing.assert_allclose(
            np.asarray(frequency_at_k(np.array([0.3, 0.1, 0.6]), k=0.5)),
            [1.0, 1.0, 0.0],
        )

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "negative"):
            frequency_at_k(np.array([0.3]), k=-1.0)
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            frequency_at_k(np.zeros((2, 2)), k=0.5)


if __name__ == "__main__":
    unittest.main()

"""Error-path parity matrices: the rejection surface of every metric family.

The reference pairs every functional metric with an exhaustive invalid-input
``assertRaisesRegex`` block (pattern at
``/root/reference/tests/metrics/functional/classification/test_accuracy.py:55-61``,
replicated per family). This module is the parametrized equivalent: one table
row per (callable, bad input, expected error), grouped by family, asserting
the documented error strings — shapes, dtypes, ranges, and option combos.
"""

import re
import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu import metrics as M
from torcheval_tpu.metrics import functional as F

A = jnp.asarray


def rows_1d(n):
    return A(np.zeros(n, np.float32))


def scores_2d(n, c):
    return A(np.zeros((n, c), np.float32))


def labels_1d(n, hi=2):
    return A(np.zeros(n, np.int32))


class _MatrixTester(unittest.TestCase):
    """Each CASES row: (label, callable, ValueError/TypeError, regex)."""

    CASES = ()

    def test_matrix(self):
        for label, fn, exc, pattern in self.CASES:
            with self.subTest(label):
                with self.assertRaisesRegex(exc, pattern):
                    fn()


class TestAccuracyFamilyErrors(_MatrixTester):
    CASES = (
        # ---- param checks (reference accuracy.py:290-310)
        ("bad average", lambda: F.multiclass_accuracy(rows_1d(4), labels_1d(4), average="bogus"),
         ValueError, r"`average` was not in the allowed value"),
        ("macro needs num_classes", lambda: F.multiclass_accuracy(rows_1d(4), labels_1d(4), average="macro"),
         ValueError, r"num_classes should be a positive number"),
        ("macro bad num_classes", lambda: F.multiclass_accuracy(rows_1d(4), labels_1d(4), average="macro", num_classes=0),
         ValueError, r"num_classes should be a positive number"),
        ("k not int", lambda: F.multiclass_accuracy(scores_2d(4, 3), labels_1d(4), num_classes=3, k=2.5),
         TypeError, r"Expected `k` to be an integer"),
        ("k < 1", lambda: F.multiclass_accuracy(scores_2d(4, 3), labels_1d(4), num_classes=3, k=0),
         ValueError, r"greater than 0"),
        # ---- update input checks (reference accuracy.py:313-342)
        ("first-dim mismatch", lambda: F.multiclass_accuracy(rows_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        ("target 2-D", lambda: F.multiclass_accuracy(scores_2d(4, 3), scores_2d(4, 3)),
         ValueError, r"target should be a one-dimensional tensor"),
        ("input 3-D", lambda: F.multiclass_accuracy(A(np.zeros((4, 3, 2), np.float32)), labels_1d(4)),
         ValueError, re.escape("input should have shape of (num_sample,) or (num_sample, num_classes)")),
        ("k>1 needs 2-D input", lambda: F.multiclass_accuracy(rows_1d(4), labels_1d(4), k=2),
         ValueError, re.escape("input should have shape (num_sample, num_classes) for k > 1")),
        ("class-width mismatch", lambda: F.multiclass_accuracy(scores_2d(4, 5), labels_1d(4), average="macro", num_classes=3),
         ValueError, r"input should have shape"),
        # ---- binary
        ("binary shape mismatch", lambda: F.binary_accuracy(scores_2d(4, 2), rows_1d(3)),
         ValueError, r"same dimensions"),
        ("binary target 2-D", lambda: F.binary_accuracy(scores_2d(4, 2), scores_2d(4, 2)),
         ValueError, r"one-dimensional tensor"),
        # ---- multilabel
        ("multilabel bad criteria", lambda: F.multilabel_accuracy(scores_2d(4, 3), scores_2d(4, 3), criteria="sometimes"),
         ValueError, r"`criteria` was not in the allowed value"),
        ("multilabel shape mismatch", lambda: F.multilabel_accuracy(scores_2d(4, 3), scores_2d(3, 3)),
         ValueError, r"same dimensions"),
        # ---- top-k multilabel (k=2 bug fixed: k honoured, k<=1 rejected)
        ("topk k=1 rejected", lambda: F.topk_multilabel_accuracy(scores_2d(4, 3), scores_2d(4, 3), k=1),
         ValueError, r"greater than 1"),
        ("topk k not int", lambda: F.topk_multilabel_accuracy(scores_2d(4, 3), scores_2d(4, 3), k="2"),
         TypeError, r"Expected `k` to be an integer"),
        ("topk bad criteria", lambda: F.topk_multilabel_accuracy(scores_2d(4, 3), scores_2d(4, 3), criteria="x", k=2),
         ValueError, r"`criteria` was not in the allowed value"),
        ("topk 1-D input", lambda: F.topk_multilabel_accuracy(rows_1d(4), rows_1d(4), k=2),
         ValueError, re.escape("input should have shape (num_sample, num_classes)")),
        # ---- class-metric constructors and updates reject identically
        ("class bad average", lambda: M.MulticlassAccuracy(average="bogus"),
         ValueError, r"`average` was not in the allowed value"),
        ("class update mismatch", lambda: M.MulticlassAccuracy().update(rows_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        ("class binary update mismatch", lambda: M.BinaryAccuracy().update(rows_1d(4), rows_1d(3)),
         ValueError, r"same dimensions"),
        ("class topk k=1", lambda: M.TopKMultilabelAccuracy(k=1),
         ValueError, r"greater than 1"),
    )


class TestF1PrecisionRecallErrors(_MatrixTester):
    CASES = (
        ("f1 bad average", lambda: F.multiclass_f1_score(rows_1d(4), labels_1d(4), average="median"),
         ValueError, r"`average` was not in the allowed"),
        ("f1 macro needs classes", lambda: F.multiclass_f1_score(rows_1d(4), labels_1d(4), average="macro"),
         ValueError, r"num_classes should be a positive number"),
        ("f1 shape mismatch", lambda: F.multiclass_f1_score(rows_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        ("f1 target 2-D", lambda: F.multiclass_f1_score(scores_2d(4, 3), scores_2d(4, 3), num_classes=3),
         ValueError, r"one-dimensional tensor"),
        ("f1 class-width mismatch", lambda: F.multiclass_f1_score(scores_2d(4, 5), labels_1d(4), average="macro", num_classes=3),
         ValueError, r"input should have shape"),
        ("binary f1 shape", lambda: F.binary_f1_score(rows_1d(4), rows_1d(3)),
         ValueError, r"same dimensions"),
        ("precision bad average", lambda: F.multiclass_precision(rows_1d(4), labels_1d(4), average="harmonic"),
         ValueError, r"`average` was not in the allowed"),
        ("precision macro needs classes", lambda: F.multiclass_precision(rows_1d(4), labels_1d(4), average=None),
         ValueError, r"num_classes"),
        ("precision shape mismatch", lambda: F.multiclass_precision(rows_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        ("recall bad average", lambda: F.multiclass_recall(rows_1d(4), labels_1d(4), average="harmonic"),
         ValueError, r"`average` was not in the allowed"),
        ("recall macro needs classes", lambda: F.multiclass_recall(rows_1d(4), labels_1d(4), average="macro"),
         ValueError, r"`num_classes` should be a positive number"),
        ("recall shape mismatch", lambda: F.multiclass_recall(rows_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        ("binary recall shape", lambda: F.binary_recall(rows_1d(4), rows_1d(3)),
         ValueError, r"same dimensions"),
        ("binary recall 2-D", lambda: F.binary_recall(scores_2d(4, 2), scores_2d(4, 2)),
         ValueError, r"one-dimensional tensor"),
        # class metrics
        ("class f1 bad average", lambda: M.MulticlassF1Score(average="median"),
         ValueError, r"`average` was not in the allowed"),
        ("class f1 update mismatch", lambda: M.MulticlassF1Score().update(rows_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        ("class binary precision mismatch", lambda: M.BinaryPrecision().update(rows_1d(4), rows_1d(3)),
         ValueError, r"same dimensions"),
        ("class binary recall 2-D", lambda: M.BinaryRecall().update(scores_2d(4, 2), scores_2d(4, 2)),
         ValueError, r"one-dimensional tensor"),
    )


class TestConfusionCurveErrors(_MatrixTester):
    CASES = (
        ("cm num_classes < 2", lambda: F.multiclass_confusion_matrix(labels_1d(4), labels_1d(4), num_classes=1),
         ValueError, r"num_classes must be at least 2"),
        ("cm bad normalize", lambda: F.multiclass_confusion_matrix(labels_1d(4), labels_1d(4), num_classes=3, normalize="rows"),
         ValueError, r"normalize must be one of"),
        ("cm shape mismatch", lambda: F.multiclass_confusion_matrix(labels_1d(4), labels_1d(3), num_classes=3),
         ValueError, r"same first dimension"),
        ("binary cm bad normalize", lambda: F.binary_confusion_matrix(rows_1d(4), labels_1d(4), normalize="rows"),
         ValueError, r"normalize must be one of"),
        ("class cm num_classes", lambda: M.MulticlassConfusionMatrix(1),
         ValueError, r"num_classes must be at least 2"),
        ("class cm update mismatch", lambda: M.MulticlassConfusionMatrix(3).update(labels_1d(4), labels_1d(3)),
         ValueError, r"same first dimension"),
        # auroc / auprc
        ("auroc shape mismatch", lambda: F.binary_auroc(rows_1d(4), rows_1d(3)),
         ValueError, r"same shape"),
        ("class auroc shape mismatch", lambda: M.BinaryAUROC().update(rows_1d(4), rows_1d(3)),
         ValueError, r"same shape"),
        ("auroc compaction threshold", lambda: M.BinaryAUROC(compaction_threshold=0),
         ValueError, r"compaction_threshold must be positive"),
        # binned PRC threshold specs
        ("binned unsorted thresholds", lambda: F.binary_binned_precision_recall_curve(rows_1d(4), labels_1d(4), threshold=A(np.asarray([0.5, 0.2], np.float32))),
         ValueError, r"should be a sorted array"),
        ("binned out-of-range thresholds", lambda: F.binary_binned_precision_recall_curve(rows_1d(4), labels_1d(4), threshold=A(np.asarray([0.0, 1.5], np.float32))),
         ValueError, re.escape("should be in the range of [0, 1]")),
        # normalized entropy
        ("ne shape mismatch", lambda: F.binary_normalized_entropy(rows_1d(4), rows_1d(3)),
         ValueError, r"is different from `target` shape"),
        ("ne weight mismatch", lambda: F.binary_normalized_entropy(rows_1d(4), rows_1d(4), weight=rows_1d(3)),
         ValueError, r"weight"),
        ("ne prob out of range", lambda: F.binary_normalized_entropy(A(np.asarray([0.2, 1.5], np.float32)), rows_1d(2)),
         ValueError, r"should be probability"),
        ("ne num_tasks mismatch", lambda: F.binary_normalized_entropy(scores_2d(3, 4), scores_2d(3, 4), num_tasks=2),
         ValueError, r"num_tasks"),
    )


class TestCTRCalibrationErrors(_MatrixTester):
    CASES = (
        ("ctr 2-D at num_tasks=1", lambda: F.click_through_rate(scores_2d(3, 4)),
         ValueError, r"one-dimensional"),
        ("ctr tasks mismatch", lambda: F.click_through_rate(rows_1d(4), num_tasks=3),
         ValueError, r"`num_tasks = 3`"),
        ("ctr weights shape", lambda: F.click_through_rate(rows_1d(4), rows_1d(3)),
         ValueError, r"`weights` shape"),
        ("ctr list weights shape", lambda: F.click_through_rate(rows_1d(4), [1.0, 2.0]),
         ValueError, r"`weights` shape"),
        ("calibration target shape", lambda: F.weighted_calibration(rows_1d(4), rows_1d(3)),
         ValueError, r"`target` shape"),
        ("calibration weight shape", lambda: F.weighted_calibration(rows_1d(4), rows_1d(4), rows_1d(3)),
         ValueError, r"`weight` shape"),
        ("class ctr num_tasks", lambda: M.ClickThroughRate(num_tasks=0),
         ValueError, r"num_tasks"),
        ("class windowed window_size", lambda: M.WindowedClickThroughRate(window_size=0),
         ValueError, r"window_size"),
        ("class windowed calibration tasks", lambda: M.WindowedWeightedCalibration(num_tasks=-1),
         ValueError, r"num_tasks"),
        ("class calibration update shape", lambda: M.WeightedCalibration().update(rows_1d(4), rows_1d(3)),
         ValueError, r"`target` shape"),
    )


class TestRankingRegressionAggregationErrors(_MatrixTester):
    CASES = (
        ("hit_rate target 2-D", lambda: F.hit_rate(scores_2d(3, 4), scores_2d(3, 4)),
         ValueError, r"one-dimensional"),
        ("hit_rate input 1-D", lambda: F.hit_rate(rows_1d(3), labels_1d(3)),
         ValueError, r"two-dimensional"),
        ("hit_rate size mismatch", lambda: F.hit_rate(scores_2d(3, 4), labels_1d(2)),
         ValueError, r"same minibatch dimension"),
        ("hit_rate k <= 0", lambda: F.hit_rate(scores_2d(3, 4), labels_1d(3), k=0),
         ValueError, r"k should be None or positive"),
        ("hit_rate target out of range", lambda: F.hit_rate(scores_2d(3, 4), A(np.asarray([0, 1, 9], np.int32))),
         ValueError, re.escape("target indices must be in [0, 4)")),
        ("reciprocal_rank input 1-D", lambda: F.reciprocal_rank(rows_1d(3), labels_1d(3)),
         ValueError, r"two-dimensional"),
        ("frequency input 2-D", lambda: F.frequency_at_k(scores_2d(3, 4), 1.0),
         ValueError, r"one-dimensional"),
        ("frequency negative k", lambda: F.frequency_at_k(rows_1d(3), -1.0),
         ValueError, r"k should not be negative"),
        ("collisions 2-D", lambda: F.num_collisions(scores_2d(3, 4).astype(jnp.int32)),
         ValueError, r"one-dimensional"),
        ("collisions float dtype", lambda: F.num_collisions(rows_1d(3)),
         ValueError, r"integer tensor"),
        # regression
        ("mse bad multioutput", lambda: F.mean_squared_error(rows_1d(4), rows_1d(4), multioutput="mean"),
         ValueError, r"multioutput"),
        ("mse 3-D", lambda: F.mean_squared_error(A(np.zeros((2, 2, 2), np.float32)), A(np.zeros((2, 2, 2), np.float32))),
         ValueError, r"should be 1D or 2D"),
        ("mse shape mismatch", lambda: F.mean_squared_error(rows_1d(4), rows_1d(3)),
         ValueError, r"should have the same size"),
        ("mse weight 2-D", lambda: F.mean_squared_error(rows_1d(4), rows_1d(4), sample_weight=scores_2d(2, 2)),
         ValueError, r"`sample_weight` should be a one-dimensional tensor"),
        ("r2 bad multioutput", lambda: F.r2_score(rows_1d(4), rows_1d(4), multioutput="mean"),
         ValueError, r"multioutput"),
        ("r2 bad num_regressors", lambda: F.r2_score(rows_1d(4), rows_1d(4), num_regressors=-1),
         ValueError, r"num_regressors"),
        ("r2 too few samples", lambda: F.r2_score(rows_1d(1), rows_1d(1)),
         ValueError, r"at least two samples"),
        ("r2 regressors vs samples", lambda: F.r2_score(rows_1d(4), rows_1d(4), num_regressors=3),
         ValueError, r"`num_regressors` must be smaller than"),
        # aggregation
        ("sum weight shape", lambda: F.sum(rows_1d(4), A(np.zeros(3, np.float32))),
         ValueError, r"weight must be a scalar or an array whose shape matches"),
        ("throughput negative elapsed", lambda: M.Throughput().update(num_processed=1, elapsed_time_sec=-1.0),
         ValueError, r"elapsed_time_sec"),
        ("mean weight shape", lambda: M.Mean().update(rows_1d(4), weight=A(np.zeros(3, np.float32))),
         ValueError, r"weight must be a scalar or an array whose shape matches"),
    )


if __name__ == "__main__":
    unittest.main()

"""Tier-1 oracle tests: counter-family functionals vs scikit-learn.

Mirrors the reference strategy (SURVEY §4: sklearn as independent oracle,
e.g. ``tests/metrics/functional/classification/test_accuracy.py:12,28-30``)
plus invalid-input assertRaises coverage.
"""

import unittest

import jax.numpy as jnp
import numpy as np
from sklearn.metrics import (
    accuracy_score,
    confusion_matrix as sk_confusion_matrix,
    f1_score as sk_f1,
    precision_score as sk_precision,
    recall_score as sk_recall,
)

from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils import assert_result_close

RNG = np.random.default_rng(42)
C = 7
N = 500
TARGET = RNG.integers(0, C, size=N)
PRED_LABELS = RNG.integers(0, C, size=N)
PRED_SCORES = RNG.normal(size=(N, C)).astype(np.float32)
BIN_TARGET = RNG.integers(0, 2, size=N)
BIN_SCORES = RNG.random(N).astype(np.float32)
BIN_PRED = (BIN_SCORES >= 0.5).astype(np.int64)


class TestMulticlassAccuracy(unittest.TestCase):
    def test_micro_labels(self):
        assert_result_close(
            F.multiclass_accuracy(jnp.asarray(PRED_LABELS), jnp.asarray(TARGET)),
            accuracy_score(TARGET, PRED_LABELS),
        )

    def test_micro_scores(self):
        pred = PRED_SCORES.argmax(1)
        assert_result_close(
            F.multiclass_accuracy(jnp.asarray(PRED_SCORES), jnp.asarray(TARGET)),
            accuracy_score(TARGET, pred),
        )

    def test_macro_and_none(self):
        pred = PRED_SCORES.argmax(1)
        # sklearn macro recall == torcheval macro accuracy (per-class acc is recall)
        expected = sk_recall(TARGET, pred, average="macro")
        assert_result_close(
            F.multiclass_accuracy(
                jnp.asarray(PRED_SCORES), jnp.asarray(TARGET),
                average="macro", num_classes=C,
            ),
            expected,
        )
        per_class = F.multiclass_accuracy(
            jnp.asarray(PRED_SCORES), jnp.asarray(TARGET), average=None, num_classes=C
        )
        expected_pc = sk_recall(TARGET, pred, average=None)
        assert_result_close(per_class, expected_pc)

    def test_topk(self):
        k = 3
        topk_hits = np.array(
            [
                (PRED_SCORES[i] > PRED_SCORES[i, TARGET[i]]).sum() < k
                for i in range(N)
            ]
        )
        assert_result_close(
            F.multiclass_accuracy(
                jnp.asarray(PRED_SCORES), jnp.asarray(TARGET), k=k
            ),
            topk_hits.mean(),
        )

    def test_invalid_inputs(self):
        with self.assertRaisesRegex(ValueError, "`average` was not"):
            F.multiclass_accuracy(jnp.zeros(3), jnp.zeros(3), average="bogus")
        with self.assertRaisesRegex(ValueError, "num_classes should be a positive"):
            F.multiclass_accuracy(jnp.zeros(3), jnp.zeros(3), average="macro")
        with self.assertRaisesRegex(ValueError, "same first dimension"):
            F.multiclass_accuracy(jnp.zeros(3), jnp.zeros(4))
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            F.multiclass_accuracy(jnp.zeros((3, 2)), jnp.zeros((3, 2)))
        with self.assertRaisesRegex(ValueError, "for k > 1"):
            F.multiclass_accuracy(jnp.zeros(3), jnp.zeros(3), k=2)
        with self.assertRaisesRegex(TypeError, "`k` to be an integer"):
            F.multiclass_accuracy(jnp.zeros(3), jnp.zeros(3), k=1.5)


class TestBinaryAccuracy(unittest.TestCase):
    def test_binary(self):
        assert_result_close(
            F.binary_accuracy(jnp.asarray(BIN_SCORES), jnp.asarray(BIN_TARGET)),
            accuracy_score(BIN_TARGET, BIN_PRED),
        )

    def test_threshold(self):
        pred = (BIN_SCORES >= 0.8).astype(np.int64)
        assert_result_close(
            F.binary_accuracy(
                jnp.asarray(BIN_SCORES), jnp.asarray(BIN_TARGET), threshold=0.8
            ),
            accuracy_score(BIN_TARGET, pred),
        )


class TestMultilabelAccuracy(unittest.TestCase):
    def setUp(self):
        self.target = RNG.integers(0, 2, size=(64, 5))
        self.scores = RNG.random((64, 5)).astype(np.float32)
        self.pred = (self.scores >= 0.5).astype(np.int64)

    def test_exact_match(self):
        expected = (self.pred == self.target).all(axis=1).mean()
        assert_result_close(
            F.multilabel_accuracy(jnp.asarray(self.scores), jnp.asarray(self.target)),
            expected,
        )

    def test_hamming(self):
        expected = (self.pred == self.target).mean()
        assert_result_close(
            F.multilabel_accuracy(
                jnp.asarray(self.scores), jnp.asarray(self.target), criteria="hamming"
            ),
            expected,
        )

    def test_overlap_contain_belong(self):
        overlap = (
            ((self.pred == self.target) & (self.pred == 1)).max(axis=1)
            | ((self.pred == 0) & (self.target == 0)).all(axis=1)
        ).mean()
        contain = ((self.pred - self.target) >= 0).all(axis=1).mean()
        belong = ((self.pred - self.target) <= 0).all(axis=1).mean()
        for criteria, expected in [
            ("overlap", overlap),
            ("contain", contain),
            ("belong", belong),
        ]:
            assert_result_close(
                F.multilabel_accuracy(
                    jnp.asarray(self.scores), jnp.asarray(self.target), criteria=criteria
                ),
                expected,
            )

    def test_topk_respects_k(self):
        # fixed reference bug: topk(k) was hardcoded to 2 (accuracy.py:394)
        k = 3
        idx = np.argsort(-self.scores, axis=1, kind="stable")[:, :k]
        pred = np.zeros_like(self.target)
        np.put_along_axis(pred, idx, 1, axis=1)
        expected = (pred == self.target).all(axis=1).mean()
        assert_result_close(
            F.topk_multilabel_accuracy(
                jnp.asarray(self.scores), jnp.asarray(self.target), k=k
            ),
            expected,
        )

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "`criteria` was not"):
            F.multilabel_accuracy(jnp.zeros((2, 2)), jnp.zeros((2, 2)), criteria="x")
        with self.assertRaisesRegex(ValueError, "greater than 1"):
            F.topk_multilabel_accuracy(jnp.zeros((2, 2)), jnp.zeros((2, 2)), k=1)


class TestF1(unittest.TestCase):
    def test_micro_macro_weighted_none(self):
        pred = PRED_SCORES.argmax(1)
        for average in ["micro", "macro", "weighted", None]:
            expected = sk_f1(TARGET, pred, average=average, zero_division=0)
            assert_result_close(
                F.multiclass_f1_score(
                    jnp.asarray(PRED_SCORES),
                    jnp.asarray(TARGET),
                    num_classes=C,
                    average=average,
                ),
                expected,
                atol=1e-5,
            )

    def test_binary_f1(self):
        expected = sk_f1(BIN_TARGET, BIN_PRED, zero_division=0)
        assert_result_close(
            F.binary_f1_score(jnp.asarray(BIN_SCORES), jnp.asarray(BIN_TARGET)),
            expected,
        )

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "num_classes should be"):
            F.multiclass_f1_score(jnp.zeros(3), jnp.zeros(3), average="macro")


class TestPrecisionRecall(unittest.TestCase):
    def test_precision_all_averages(self):
        pred = PRED_SCORES.argmax(1)
        for average in ["micro", "macro", "weighted", None]:
            expected = sk_precision(TARGET, pred, average=average, zero_division=0)
            assert_result_close(
                F.multiclass_precision(
                    jnp.asarray(PRED_SCORES),
                    jnp.asarray(TARGET),
                    num_classes=C,
                    average=average,
                ),
                expected,
            )

    def test_recall_all_averages(self):
        pred = PRED_SCORES.argmax(1)
        for average in ["micro", "macro", "weighted", None]:
            expected = sk_recall(TARGET, pred, average=average, zero_division=0)
            assert_result_close(
                F.multiclass_recall(
                    jnp.asarray(PRED_SCORES),
                    jnp.asarray(TARGET),
                    num_classes=C,
                    average=average,
                ),
                expected,
            )

    def test_binary(self):
        assert_result_close(
            F.binary_precision(jnp.asarray(BIN_SCORES), jnp.asarray(BIN_TARGET)),
            sk_precision(BIN_TARGET, BIN_PRED, zero_division=0),
        )
        assert_result_close(
            F.binary_recall(jnp.asarray(BIN_SCORES), jnp.asarray(BIN_TARGET)),
            sk_recall(BIN_TARGET, BIN_PRED, zero_division=0),
        )


class TestConfusionMatrix(unittest.TestCase):
    def test_multiclass(self):
        pred = PRED_SCORES.argmax(1)
        expected = sk_confusion_matrix(TARGET, pred, labels=np.arange(C))
        np.testing.assert_array_equal(
            np.asarray(
                F.multiclass_confusion_matrix(
                    jnp.asarray(PRED_SCORES), jnp.asarray(TARGET), C
                )
            ),
            expected,
        )

    def test_normalized(self):
        expected = sk_confusion_matrix(
            BIN_TARGET, BIN_PRED, labels=[0, 1], normalize="true"
        )
        assert_result_close(
            F.binary_confusion_matrix(
                jnp.asarray(BIN_SCORES), jnp.asarray(BIN_TARGET), normalize="true"
            ),
            expected,
        )

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "num_classes must be"):
            F.multiclass_confusion_matrix(jnp.zeros(3), jnp.zeros(3), 1)
        with self.assertRaisesRegex(ValueError, "normalize"):
            F.multiclass_confusion_matrix(jnp.zeros(3), jnp.zeros(3), 3, normalize="x")


class TestClassCountsMethods(unittest.TestCase):
    def test_matmul_vs_scatter_agree(self):
        from torcheval_tpu.ops import class_counts

        labels = jnp.asarray(RNG.integers(0, 100, size=10_000))
        weights = jnp.asarray(RNG.random(10_000).astype(np.float32))
        a = class_counts(labels, 100, method="matmul")
        b = class_counts(labels, 100, method="scatter")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        wa = class_counts(labels, 100, weights, method="matmul")
        wb = class_counts(labels, 100, weights, method="scatter")
        np.testing.assert_allclose(np.asarray(wa), np.asarray(wb), rtol=1e-5)


class TestConfusionOutOfRange(unittest.TestCase):
    def test_partial_out_of_range_sample_is_dropped(self):
        # a sample with one bad coordinate must not fold into a valid cell
        mat = F.multiclass_confusion_matrix(
            jnp.asarray([0, 5]), jnp.asarray([0, 1]), 3
        )
        expected = np.zeros((3, 3), dtype=np.int32)
        expected[0, 0] = 1
        np.testing.assert_array_equal(np.asarray(mat), expected)


if __name__ == "__main__":
    unittest.main()


class TestThresholdVariants(unittest.TestCase):
    """Threshold permutations for binary counter functionals."""

    def test_binary_precision_threshold(self):
        rng = np.random.default_rng(70)
        x = rng.random(300).astype(np.float32)
        t = rng.integers(0, 2, 300)
        for thr in (0.2, 0.5, 0.8):
            pred = (x >= thr).astype(int)
            got = F.binary_precision(jnp.asarray(pred), jnp.asarray(t))
            want = sk_precision(t, pred, zero_division=0)
            np.testing.assert_allclose(float(got), want, rtol=1e-5)

    def test_binary_recall_int_inputs(self):
        rng = np.random.default_rng(71)
        pred = rng.integers(0, 2, 200)
        t = rng.integers(0, 2, 200)
        got = F.binary_recall(jnp.asarray(pred), jnp.asarray(t))
        np.testing.assert_allclose(
            float(got), sk_recall(t, pred, zero_division=0), rtol=1e-5
        )

    def test_binary_f1_threshold_sweep(self):
        rng = np.random.default_rng(72)
        x = rng.random(300).astype(np.float32)
        t = rng.integers(0, 2, 300)
        for thr in (0.3, 0.6):
            got = F.binary_f1_score(jnp.asarray(x), jnp.asarray(t), threshold=thr)
            want = sk_f1(t, (x >= thr).astype(int), zero_division=0)
            np.testing.assert_allclose(float(got), want, rtol=1e-5)

    def test_binary_confusion_matrix_threshold_normalize(self):
        rng = np.random.default_rng(73)
        x = rng.random(200).astype(np.float32)
        t = rng.integers(0, 2, 200)
        got = F.binary_confusion_matrix(
            jnp.asarray(x), jnp.asarray(t), threshold=0.4, normalize="all"
        )
        want = sk_confusion_matrix(t, (x >= 0.4).astype(int), labels=[0, 1], normalize="all")
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)

    def test_multiclass_accuracy_label_input_form(self):
        # 1-D integer predictions (already-argmaxed) are a documented input
        # form alongside (N, C) scores
        rng = np.random.default_rng(74)
        pred = rng.integers(0, 4, 150)
        t = rng.integers(0, 4, 150)
        got = F.multiclass_accuracy(jnp.asarray(pred), jnp.asarray(t))
        np.testing.assert_allclose(float(got), (pred == t).mean(), rtol=1e-6)

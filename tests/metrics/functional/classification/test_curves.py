"""Curve-based functional metrics vs sklearn oracles and reference docstring
values (SURVEY §4 tier 1)."""

import unittest

import jax.numpy as jnp
import numpy as np
from sklearn.metrics import (
    average_precision_score,
    precision_recall_curve as sk_prc,
    roc_auc_score,
)

import torcheval_tpu.metrics.functional as F

RNG = np.random.default_rng(0)


class TestBinaryAUROC(unittest.TestCase):
    def test_docstring(self):
        self.assertAlmostEqual(
            float(F.binary_auroc(np.array([0.1, 0.5, 0.7, 0.8]), np.array([1, 0, 1, 1]))),
            2 / 3,
            places=5,
        )
        # tied scores integrate along the tie diagonal
        self.assertAlmostEqual(
            float(F.binary_auroc(np.array([1.0, 1, 1, 0]), np.array([1, 0, 1, 0]))),
            0.75,
            places=6,
        )

    def test_random_vs_sklearn(self):
        for n in (10, 1000, 4097):
            x = RNG.random(n).astype(np.float32)
            t = RNG.integers(0, 2, n)
            if t.min() == t.max():
                t[0] = 1 - t[0]
            self.assertAlmostEqual(
                float(F.binary_auroc(x, t)), roc_auc_score(t, x), places=5
            )

    def test_heavy_ties_vs_sklearn(self):
        x = RNG.integers(0, 5, 500).astype(np.float32) / 4.0
        t = RNG.integers(0, 2, 500)
        self.assertAlmostEqual(
            float(F.binary_auroc(x, t)), roc_auc_score(t, x), places=5
        )

    def test_degenerate_returns_half(self):
        self.assertEqual(float(F.binary_auroc(np.array([0.3, 0.7]), np.array([1, 1]))), 0.5)
        self.assertEqual(float(F.binary_auroc(np.array([0.3, 0.7]), np.array([0, 0]))), 0.5)

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            F.binary_auroc(np.zeros((2, 2)), np.zeros(2))
        with self.assertRaisesRegex(ValueError, "same shape"):
            F.binary_auroc(np.zeros(3), np.zeros(4))


class TestBinaryAUPRC(unittest.TestCase):
    def test_random_vs_sklearn(self):
        for n in (10, 1000):
            x = RNG.random(n).astype(np.float32)
            t = RNG.integers(0, 2, n)
            if t.max() == 0:
                t[0] = 1
            self.assertAlmostEqual(
                float(F.binary_auprc(x, t)),
                average_precision_score(t, x),
                places=5,
            )

    def test_ties_vs_sklearn(self):
        x = RNG.integers(0, 4, 300).astype(np.float32)
        t = RNG.integers(0, 2, 300)
        self.assertAlmostEqual(
            float(F.binary_auprc(x, t)), average_precision_score(t, x), places=5
        )


class TestBinaryPRC(unittest.TestCase):
    def test_docstring(self):
        p, r, t = F.binary_precision_recall_curve(
            np.array([0.1, 0.5, 0.7, 0.8]), np.array([0, 0, 1, 1])
        )
        np.testing.assert_allclose(
            np.asarray(p), [0.5, 2 / 3, 1.0, 1.0, 1.0], rtol=1e-5
        )
        np.testing.assert_allclose(np.asarray(r), [1.0, 1.0, 1.0, 0.5, 0.0])
        np.testing.assert_allclose(np.asarray(t), [0.1, 0.5, 0.7, 0.8], rtol=1e-6)

    def test_random_vs_sklearn(self):
        x = RNG.random(500).astype(np.float32)
        t = RNG.integers(0, 2, 500)
        p, r, th = F.binary_precision_recall_curve(x, t)
        skp, skr, skt = sk_prc(t, x)
        np.testing.assert_allclose(np.asarray(p), skp, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(r), skr, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(th), skt, rtol=1e-5)

    def test_no_positives_recall_one(self):
        p, r, t = F.binary_precision_recall_curve(
            np.array([0.2, 0.8]), np.array([0, 0])
        )
        np.testing.assert_allclose(np.asarray(r)[:-1], [1.0, 1.0])


class TestMulticlassPRC(unittest.TestCase):
    def test_docstring(self):
        inp = np.tile(np.array([[0.1], [0.5], [0.7], [0.8]], dtype=np.float32), (1, 4))
        tg = np.array([0, 1, 2, 3])
        ps, rs, ts = F.multiclass_precision_recall_curve(inp, tg, num_classes=4)
        np.testing.assert_allclose(
            np.asarray(ps[0]), [0.25, 0.0, 0.0, 0.0, 1.0], rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(ps[3]), [0.25, 1 / 3, 0.5, 1.0, 1.0], rtol=1e-5
        )
        np.testing.assert_allclose(np.asarray(rs[1]), [1.0, 1.0, 0.0, 0.0, 0.0])
        np.testing.assert_allclose(np.asarray(ts[0]), [0.1, 0.5, 0.7, 0.8], rtol=1e-6)

    def test_random_vs_sklearn_per_class(self):
        n, c = 300, 5
        inp = RNG.random((n, c)).astype(np.float32)
        tg = RNG.integers(0, c, n)
        ps, rs, ts = F.multiclass_precision_recall_curve(inp, tg)
        for k in range(c):
            skp, skr, skt = sk_prc((tg == k).astype(int), inp[:, k])
            np.testing.assert_allclose(np.asarray(ps[k]), skp, rtol=1e-5)
            np.testing.assert_allclose(np.asarray(rs[k]), skr, rtol=1e-5)
            np.testing.assert_allclose(np.asarray(ts[k]), skt, rtol=1e-5)


class TestBinnedPRC(unittest.TestCase):
    def test_docstring_binary(self):
        p, r, t = F.binary_binned_precision_recall_curve(
            np.array([0.2, 0.8, 0.5, 0.9]), np.array([0, 1, 0, 1]), threshold=5
        )
        np.testing.assert_allclose(
            np.asarray(p), [0.5, 2 / 3, 2 / 3, 1.0, 1.0, 1.0], rtol=1e-5
        )
        np.testing.assert_allclose(np.asarray(r), [1, 1, 1, 1, 0, 0])
        np.testing.assert_allclose(np.asarray(t), [0, 0.25, 0.5, 0.75, 1.0])

    def test_docstring_binary_tensor_threshold(self):
        p, r, t = F.binary_binned_precision_recall_curve(
            np.array([0.2, 0.3, 0.4, 0.5]),
            np.array([0, 0, 1, 1]),
            threshold=np.array([0.0, 0.25, 0.75, 1.0]),
        )
        np.testing.assert_allclose(np.asarray(p), [0.5, 2 / 3, 1.0, 1.0, 1.0], rtol=1e-5)
        np.testing.assert_allclose(np.asarray(r), [1.0, 1.0, 0.0, 0.0, 0.0])

    def test_docstring_multiclass(self):
        inp = np.tile(np.array([[0.1], [0.5], [0.7], [0.8]], dtype=np.float32), (1, 4))
        tg = np.array([0, 1, 2, 3])
        thr = [0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9]
        ps, rs, t = F.multiclass_binned_precision_recall_curve(
            inp, tg, num_classes=4, threshold=thr
        )
        np.testing.assert_allclose(
            np.asarray(ps[0]),
            [0.25, 0, 0, 0, 0, 0, 0, 0, 1.0, 1.0],
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(ps[3]),
            [0.25, 1 / 3, 1 / 3, 1 / 3, 1 / 3, 0.5, 0.5, 1.0, 1.0, 1.0],
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(rs[1]), [1, 1, 1, 1, 1, 0, 0, 0, 0, 0]
        )

    def test_invalid_threshold(self):
        with self.assertRaisesRegex(ValueError, "sorted"):
            F.binary_binned_precision_recall_curve(
                np.zeros(2), np.zeros(2), threshold=np.array([0.5, 0.2])
            )
        with self.assertRaisesRegex(ValueError, "range"):
            F.binary_binned_precision_recall_curve(
                np.zeros(2), np.zeros(2), threshold=np.array([0.5, 1.2])
            )


class TestBinaryNormalizedEntropy(unittest.TestCase):
    def test_docstring(self):
        self.assertAlmostEqual(
            float(F.binary_normalized_entropy(np.array([0.2, 0.3]), np.array([1.0, 0.0]))),
            1.4183,
            places=3,
        )
        self.assertAlmostEqual(
            float(
                F.binary_normalized_entropy(
                    np.array([0.2, 0.3]),
                    np.array([1.0, 0.0]),
                    weight=np.array([5.0, 1.0]),
                )
            ),
            3.1087,
            places=3,
        )
        self.assertAlmostEqual(
            float(
                F.binary_normalized_entropy(
                    np.array([-1.3863, -0.8473]),
                    np.array([1.0, 0.0]),
                    from_logits=True,
                )
            ),
            1.4183,
            places=3,
        )
        np.testing.assert_allclose(
            np.asarray(
                F.binary_normalized_entropy(
                    np.array([[0.2, 0.3], [0.5, 0.1]]),
                    np.array([[1.0, 0.0], [0.0, 1.0]]),
                    num_tasks=2,
                )
            ),
            [1.4183, 2.1610],
            rtol=1e-4,
        )

    def test_invalid(self):
        with self.assertRaisesRegex(ValueError, "different from"):
            F.binary_normalized_entropy(np.zeros(3), np.zeros(4))
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            F.binary_normalized_entropy(np.zeros((2, 3)), np.zeros((2, 3)))
        with self.assertRaisesRegex(ValueError, "num_tasks = 2"):
            F.binary_normalized_entropy(np.zeros(3), np.zeros(3), num_tasks=2)
        with self.assertRaisesRegex(ValueError, "probability"):
            F.binary_normalized_entropy(
                np.array([1.5, 0.2]), np.array([1.0, 0.0])
            )


if __name__ == "__main__":
    unittest.main()


class TestCompactCounts(unittest.TestCase):
    """Unit tests for the threshold-summary compaction kernel
    (``ops/summary.py``): static shapes, tie merging, padding discipline."""

    def _run(self, scores, tp, fp):
        import jax.numpy as jnp

        from torcheval_tpu.ops.summary import compact_counts

        return compact_counts(
            jnp.asarray(scores, jnp.float32),
            jnp.asarray(tp, jnp.int32),
            jnp.asarray(fp, jnp.int32),
        )

    def test_merges_ties_and_pads(self):
        s, tp, fp, n, _ = self._run(
            [0.5, 0.2, 0.5, 0.9, 0.2, 0.2],
            [1, 0, 0, 1, 1, 0],
            [0, 1, 1, 0, 0, 1],
        )
        self.assertEqual(int(n), 3)
        np.testing.assert_allclose(np.asarray(s[:3]), [0.9, 0.5, 0.2])
        np.testing.assert_array_equal(np.asarray(tp[:3]), [1, 1, 1])
        np.testing.assert_array_equal(np.asarray(fp[:3]), [0, 1, 2])
        # padding: NaN scores, zero counts, static length preserved
        self.assertEqual(s.shape, (6,))
        self.assertTrue(np.all(np.isnan(np.asarray(s[3:]))))
        self.assertEqual(int(np.asarray(tp[3:]).sum()), 0)

    def test_existing_padding_recompacts_to_padding(self):
        s, tp, fp, n, _ = self._run(
            [0.3, np.nan, 0.3, np.nan], [1, 0, 0, 0], [0, 0, 1, 0]
        )
        self.assertEqual(int(n), 1)
        np.testing.assert_allclose(np.asarray(s[:1]), [0.3])
        self.assertTrue(np.all(np.isnan(np.asarray(s[1:]))))

    def test_neg_inf_is_a_legal_score_not_padding(self):
        # -inf scores (log(0) log-probs) must survive compaction: they sort
        # after every finite score but BEFORE the NaN padding block
        s, tp, fp, n, _ = self._run(
            [0.5, -np.inf, -np.inf, np.nan], [1, 1, 0, 0], [0, 0, 1, 0]
        )
        self.assertEqual(int(n), 2)
        np.testing.assert_allclose(np.asarray(s[:2]), [0.5, -np.inf])
        np.testing.assert_array_equal(np.asarray(tp[:2]), [1, 1])
        np.testing.assert_array_equal(np.asarray(fp[:2]), [0, 1])
        self.assertTrue(np.all(np.isnan(np.asarray(s[2:]))))

    def test_summary_feeds_curve_kernels_exactly(self):
        from sklearn.metrics import average_precision_score, roc_auc_score

        from torcheval_tpu.ops.curves import (
            binary_auprc_counts_kernel,
            binary_auroc_counts_kernel,
        )

        rng = np.random.default_rng(7)
        scores = (rng.random(5000) * 50).astype(np.int32) / 50.0  # heavy ties
        target = (rng.random(5000) < 0.4).astype(np.int32)
        s, tp, fp, _, _ = self._run(scores, target, 1 - target)
        auc = float(binary_auroc_counts_kernel(s, tp, fp))
        ap = float(binary_auprc_counts_kernel(s, tp, fp))
        self.assertAlmostEqual(auc, roc_auc_score(target, scores), places=6)
        self.assertAlmostEqual(
            ap, average_precision_score(target, scores), places=5
        )

    def test_empty(self):
        s, tp, fp, n, _ = self._run([], [], [])
        self.assertEqual((s.shape, int(n)), ((0,), 0))


class TestCompactNanHandling(unittest.TestCase):
    def test_nan_sample_rows_are_counted_not_silently_dropped(self):
        import jax.numpy as jnp

        from torcheval_tpu.ops.summary import compact_counts

        s, tp, fp, n, nan_dropped = compact_counts(
            jnp.asarray([0.5, np.nan, 0.2], jnp.float32),
            jnp.asarray([1, 1, 0], jnp.int32),
            jnp.asarray([0, 0, 1], jnp.int32),
        )
        self.assertEqual(int(n), 2)
        self.assertEqual(int(nan_dropped), 1)

    def test_compacting_metric_raises_on_nan_scores(self):
        from torcheval_tpu.metrics import BinaryAUROC

        # round 3: the NaN check is a device-side flag raised at compute()
        # (the per-compaction host read serialized the pipeline); update()
        # itself stays non-blocking
        m = BinaryAUROC(compaction_threshold=4)
        m.update(
            np.array([0.1, np.nan, 0.3, 0.4], np.float32),
            np.array([0, 1, 0, 1], np.float32),
        )
        with self.assertRaisesRegex(ValueError, "NaN"):
            m.compute()


class TestMulticlassAUROCandAUPRC(unittest.TestCase):
    """One-vs-all extensions vs the sklearn oracle."""

    def setUp(self):
        rng = np.random.default_rng(11)
        self.C, N = 6, 3000
        self.scores = rng.random((N, self.C)).astype(np.float32)
        self.target = rng.integers(0, self.C, N)
        self.onehot = np.eye(self.C)[self.target]

    def test_macro_auroc(self):
        want = roc_auc_score(self.onehot, self.scores, average="macro")
        got = float(
            F.multiclass_auroc(
                jnp.asarray(self.scores), jnp.asarray(self.target),
                num_classes=self.C,
            )
        )
        self.assertAlmostEqual(got, want, places=5)

    def test_per_class_auprc(self):
        got = np.asarray(
            F.multiclass_auprc(
                jnp.asarray(self.scores), jnp.asarray(self.target),
                num_classes=self.C, average=None,
            )
        )
        want = [
            average_precision_score(self.onehot[:, c], self.scores[:, c])
            for c in range(self.C)
        ]
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_absent_class_degenerates(self):
        # class C-1 never appears: AUROC 0.5, AUPRC 0.0 for it
        target = np.clip(self.target, 0, self.C - 2)
        auroc = np.asarray(
            F.multiclass_auroc(
                jnp.asarray(self.scores), jnp.asarray(target),
                num_classes=self.C, average=None,
            )
        )
        auprc = np.asarray(
            F.multiclass_auprc(
                jnp.asarray(self.scores), jnp.asarray(target),
                num_classes=self.C, average=None,
            )
        )
        self.assertAlmostEqual(float(auroc[-1]), 0.5, places=6)
        self.assertAlmostEqual(float(auprc[-1]), 0.0, places=6)

    def test_param_errors(self):
        with self.assertRaisesRegex(ValueError, "num_classes must be at least 2"):
            F.multiclass_auroc(jnp.zeros((4, 3)), jnp.zeros(4, jnp.int32))
        with self.assertRaisesRegex(ValueError, "`average` was not in the allowed"):
            F.multiclass_auprc(
                jnp.zeros((4, 3)), jnp.zeros(4, jnp.int32),
                num_classes=3, average="weighted",
            )

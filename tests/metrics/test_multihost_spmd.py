"""REAL multi-host SPMD evaluation test: 4 processes × 2 local CPU devices
form one 8-device global mesh; ``ShardedEvaluator`` + a non-addressable
sharded curve cache run in lockstep (see ``mp_spmd_worker.py``).

This goes beyond the reference's tier-3 strategy (multi-process sync of
LOCAL metrics): here the metric state itself is global — the implicit-SPMD
lane the TPU design makes primary (docs/distributed.md "Lane 1") — and the
assertion is that every process computes the same globally-correct value,
equal to the single-stream sklearn/numpy oracle.
"""

import json
import os
import socket
import subprocess
import sys
import unittest

import numpy as np
from sklearn.metrics import roc_auc_score

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_WORKER = os.path.join(_HERE, "mp_spmd_worker.py")
WORLD = 4

sys.path.insert(0, _HERE)
from mp_spmd_worker import N_BATCHES, make_global_batch  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


class TestMultihostSPMD(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        import tempfile

        cls.tmpdir = tempfile.mkdtemp()
        port = _free_port()
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("XLA_FLAGS", None)
        # worker output goes to files, not pipes: draining pipes one rank at
        # a time can deadlock the whole group if another rank fills its 64KB
        # pipe buffer while rank 0 blocks inside a collective
        logs = [os.path.join(cls.tmpdir, f"rank{r}.log") for r in range(WORLD)]
        handles = [open(path, "wb") for path in logs]
        procs = [
            subprocess.Popen(
                [sys.executable, _WORKER, str(r), str(WORLD), str(port), cls.tmpdir],
                env=env,
                stdout=handles[r],
                stderr=subprocess.STDOUT,
            )
            for r in range(WORLD)
        ]
        cls.outputs = []
        try:
            for r, p in enumerate(procs):
                try:
                    p.wait(timeout=300)
                except subprocess.TimeoutExpired:
                    pass
                with open(logs[r], "rb") as f:
                    out = f.read().decode(errors="replace")
                cls.outputs.append((p.returncode, out))
        finally:
            # a hung rank (e.g. a peer crashed before joining the collective)
            # must not leave orphans holding the port for 4x the timeout
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for h in handles:
                h.close()

    def _results(self):
        for rc, out in self.outputs:
            self.assertEqual(rc, 0, f"worker failed:\n{out[-3000:]}")
        res = []
        for r in range(WORLD):
            with open(os.path.join(self.tmpdir, f"rank{r}.json")) as f:
                res.append(json.load(f))
        return res

    def test_every_process_gets_the_global_oracle_value(self):
        res = self._results()
        # single-stream oracle over the full global stream
        all_scores, all_labels, all_logits, all_binary = [], [], [], []
        for b in range(N_BATCHES):
            s, l, x, t = make_global_batch(b)
            all_scores.append(s); all_labels.append(l)
            all_logits.append(x); all_binary.append(t)
        scores = np.concatenate(all_scores)
        labels = np.concatenate(all_labels)
        logits = np.concatenate(all_logits)
        binary = np.concatenate(all_binary)
        want_acc = float(np.mean(scores.argmax(1) == labels))
        want_auroc = roc_auc_score(binary, logits)
        for r, got in enumerate(res):
            self.assertAlmostEqual(got["acc"], want_acc, places=6, msg=f"rank {r}")
            self.assertAlmostEqual(
                got["auroc"], want_auroc, places=5, msg=f"rank {r}"
            )

    def test_all_ranks_agree(self):
        res = self._results()
        for key in ("acc", "auroc"):
            vals = {round(r[key], 9) for r in res}
            self.assertEqual(len(vals), 1, f"{key} differs across ranks: {vals}")

    def test_host_data_rejected_with_guidance(self):
        for r in self._results():
            self.assertEqual(r["host_data_guard"], "ok")


if __name__ == "__main__":
    unittest.main()

"""SlicedMetricCollection core contracts (ISSUE 15).

The acceptance bars pinned here:

* per-slice values BIT-identical to a looped per-slice oracle — the
  standalone metric fed only that slice's samples — for exact counter
  members AND sketch members (same integer counts, same kernels);
* the slice axis adds ZERO device dispatches: K batches x S slices still
  close as ONE ``deferred.window_step`` program, obs-asserted at two very
  different slice counts;
* the sparse id table: first-seen interning, geometric growth (a pure pad
  — rows never move), int64 ids incl. negatives, checkpoint round-trip of
  the table bit-identically onto a FRESH smaller-capacity collection;
* ``merge_collections`` merges replicas by ORIGINAL id;
* sliceability rejections are loud and name the reason.
"""

import tempfile
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    ClickThroughRate,
    Max,
    MeanSquaredError,
    MulticlassAccuracy,
    SlicedMetricCollection,
    Sum,
)
from torcheval_tpu.metrics.sliced import SliceTable, check_sliceable


def tearDownModule():
    # the looped per-slice oracles legitimately trace solo window steps at
    # one shape PER SLICE — leave the process-wide recompile-watchdog
    # bookkeeping (and any storm-warning once-keys) clean for later obs
    # tests that assert a churn-free run stays silent
    obs.reset()


def _batches(seed=0, n_batches=3, n=257, pool=13, id_scale=101):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(0, pool, n).astype(np.int64) * id_scale - 7
        s = rng.random(n).astype(np.float32)
        t = (rng.random(n) < 0.4).astype(np.float32)
        out.append((ids, s, t))
    return out


class TestSliceTable(unittest.TestCase):
    def test_first_seen_order_and_growth(self):
        t = SliceTable(2)
        rows, grew = t.intern(np.asarray([5, 9, 5, 7], np.int64))
        self.assertTrue(grew)  # 3 distinct ids > capacity 2
        np.testing.assert_array_equal(rows, [0, 1, 0, 2])
        self.assertEqual(t.capacity, 4)
        np.testing.assert_array_equal(t.registered_ids(), [5, 9, 7])
        rows2, grew2 = t.intern(np.asarray([7, 9], np.int64))
        self.assertFalse(grew2)
        np.testing.assert_array_equal(rows2, [2, 1])

    def test_negative_and_64bit_ids(self):
        t = SliceTable(4)
        ids = np.asarray([-(1 << 40), (1 << 41) + 3, -1, 0], np.int64)
        rows, _ = t.intern(ids)
        np.testing.assert_array_equal(rows, [0, 1, 2, 3])
        np.testing.assert_array_equal(t.registered_ids(), ids)

    def test_rejects_non_integer_columns(self):
        t = SliceTable(4)
        with self.assertRaises(ValueError):
            t.intern(np.asarray([1.5, 2.5]))
        with self.assertRaises(ValueError):
            t.intern(np.zeros((2, 2), np.int64))

    def test_replace_round_trip_and_duplicate_rejection(self):
        t = SliceTable(4)
        t.intern(np.asarray([3, 1, 2], np.int64))
        ids = t.registered_ids()
        t2 = SliceTable(2)
        t2.replace(ids, 8)
        np.testing.assert_array_equal(t2.registered_ids(), ids)
        self.assertEqual(t2.capacity, 8)
        with self.assertRaises(ValueError):
            t2.replace(np.asarray([1, 1], np.int64), 4)


class TestSlicedOracleParity(unittest.TestCase):
    """Per-slice bit-identity against the looped standalone oracle."""

    def _assert_member_matches_oracle(self, result, batches, make_metric):
        all_ids = np.concatenate([b[0] for b in batches])
        cols = [np.concatenate([b[i] for b in batches]) for i in (1, 2)]
        vals = np.asarray(result["values"])
        self.assertEqual(len(result.slice_ids), len(np.unique(all_ids)))
        for n, sid in enumerate(result.slice_ids):
            mask = all_ids == sid
            oracle = make_metric()
            oracle.update(cols[0][mask], cols[1][mask])
            self.assertEqual(
                float(oracle.compute()), float(vals[n]), msg=f"slice {sid}"
            )

    def test_exact_and_sketch_members_bit_identical(self):
        batches = _batches()
        col = SlicedMetricCollection(
            {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
            capacity=2,  # forces several geometric growth events
        )
        for b in batches:
            col.update(*b)
        res = col.compute()
        self._assert_member_matches_oracle(
            res["acc"], batches, BinaryAccuracy
        )
        self._assert_member_matches_oracle(
            res["auroc"], batches, lambda: BinaryAUROC(approx=1024)
        )

    def test_sketch_member_within_documented_bound_of_exact(self):
        # the approx acceptance bar: per-slice sketch AUROC sits within the
        # sketch's own a-posteriori bound of the EXACT per-slice AUROC,
        # computed from that slice's resident histogram
        from torcheval_tpu import sketch as sk

        batches = _batches(seed=21, pool=7)
        col = SlicedMetricCollection(
            {"auroc": BinaryAUROC(approx=1024)}, capacity=4
        )
        for b in batches:
            col.update(*b)
        res = col.compute()["auroc"]
        member = col.metrics["auroc"]
        member._fold_now()
        tp = np.asarray(member.sketch_tp)
        fp = np.asarray(member.sketch_fp)
        all_ids = np.concatenate([b[0] for b in batches])
        all_s = np.concatenate([b[1] for b in batches])
        all_t = np.concatenate([b[2] for b in batches])
        for n, sid in enumerate(res.slice_ids):
            m = all_ids == sid
            exact = BinaryAUROC()
            exact.update(all_s[m], all_t[m])
            bound = sk.auroc_error_bound(tp[n], fp[n])
            self.assertLessEqual(
                abs(
                    float(np.asarray(res["values"])[n])
                    - float(exact.compute())
                ),
                bound + 1e-6,
                msg=f"slice {sid}",
            )

    def test_repeated_compute_is_idempotent(self):
        batches = _batches(seed=5)
        col = SlicedMetricCollection({"acc": BinaryAccuracy()}, capacity=4)
        for b in batches:
            col.update(*b)
        first = np.asarray(col.compute()["acc"]["values"])
        second = np.asarray(col.compute()["acc"]["values"])
        np.testing.assert_array_equal(first, second)

    def test_multiclass_and_regression_members(self):
        rng = np.random.default_rng(7)
        col = SlicedMetricCollection(
            {"acc": MulticlassAccuracy(num_classes=5)}, capacity=4
        )
        mse_col = SlicedMetricCollection({"mse": MeanSquaredError()}, capacity=4)
        batches = []
        for _ in range(3):
            ids = rng.integers(0, 9, 181).astype(np.int64) * 11
            scores = rng.random((181, 5)).astype(np.float32)
            labels = rng.integers(0, 5, 181).astype(np.int32)
            batches.append((ids, scores, labels))
            col.update(ids, scores, labels)
            mse_col.update(ids, scores[:, 0], labels.astype(np.float32))
        res = col.compute()
        mres = mse_col.compute()["mse"]
        all_ids = np.concatenate([b[0] for b in batches])
        all_s = np.concatenate([b[1] for b in batches])
        all_l = np.concatenate([b[2] for b in batches])
        for n, sid in enumerate(res["acc"].slice_ids):
            m = all_ids == sid
            oracle = MulticlassAccuracy(num_classes=5)
            oracle.update(all_s[m], all_l[m])
            self.assertEqual(
                float(oracle.compute()),
                float(np.asarray(res["acc"]["values"])[n]),
            )
            omse = MeanSquaredError()
            omse.update(all_s[m, 0], all_l[m].astype(np.float32))
            # float-sum states: per-slice segment accumulation orders the
            # adds differently than the oracle's batched tree reduction —
            # equal within f32 associativity (integer-count members above
            # are the bit-identical ones)
            np.testing.assert_allclose(
                float(omse.compute()),
                float(np.asarray(mres["values"])[
                    int(np.nonzero(mres.slice_ids == sid)[0][0])
                ]),
                rtol=1e-5,
            )

    def test_max_member_extrema_reduce(self):
        rng = np.random.default_rng(9)
        col = SlicedMetricCollection({"mx": Max()}, capacity=2)
        ids = rng.integers(0, 6, 300).astype(np.int64)
        v = rng.standard_normal(300).astype(np.float32)
        col.update(ids, v)
        res = col.compute()["mx"]
        for n, sid in enumerate(res.slice_ids):
            self.assertEqual(
                float(v[ids == sid].max()),
                float(np.asarray(res["values"])[n]),
            )


class TestOneProgramProperty(unittest.TestCase):
    def _window_steps(self):
        return sum(
            v
            for k, v in obs.snapshot()["counters"].items()
            if k.startswith("deferred.window_steps")
        )

    def test_dispatch_count_independent_of_slice_count(self):
        obs.enable()
        try:
            counts = {}
            for n_slices, pool in ((8, 8), (2048, 2048)):
                col = SlicedMetricCollection(
                    {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
                    capacity=n_slices,
                )
                batches = _batches(seed=3, n_batches=4, pool=pool)
                col.update(*batches[0])
                np.asarray(col.compute()["acc"]["values"])  # warm + register
                obs.reset()
                before = self._window_steps()
                for b in batches:
                    col.update(*b)
                col.compute()
                counts[n_slices] = self._window_steps() - before
            # K batches x S slices close as ONE program; S never enters
            self.assertEqual(counts[8], 1)
            self.assertEqual(counts[8], counts[2048])
        finally:
            obs.disable()
            obs.reset()


class TestLifecycle(unittest.TestCase):
    def test_checkpoint_round_trip_onto_fresh_collection(self):
        from torcheval_tpu.resilience.snapshot import save, restore

        batches = _batches(seed=11, pool=29)
        col = SlicedMetricCollection(
            {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
            capacity=2,
        )
        for b in batches:
            col.update(*b)
        res = col.compute()
        with tempfile.TemporaryDirectory() as d:
            ckpt = save(col, d)
            fresh = SlicedMetricCollection(
                {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
                capacity=2,  # smaller than the grown checkpoint capacity
            )
            restore(fresh, ckpt)
            # the sparse id table round-trips bit-identically
            np.testing.assert_array_equal(
                fresh.slice_table.registered_ids(),
                col.slice_table.registered_ids(),
            )
            self.assertEqual(
                fresh.slice_table.capacity, col.slice_table.capacity
            )
            r2 = fresh.compute()
            for key in ("acc", "auroc"):
                np.testing.assert_array_equal(
                    np.asarray(r2[key]["values"]),
                    np.asarray(res[key]["values"]),
                )
            # the restored collection keeps streaming (new ids included)
            ids, s, t = batches[0]
            fresh.update(ids * 7 + 1, s, t)
            fresh.compute()

    def test_checkpoint_rejects_trailing_shape_drift(self):
        from torcheval_tpu.resilience.snapshot import (
            CheckpointError,
            save,
            restore,
        )

        col = SlicedMetricCollection(
            {"auroc": BinaryAUROC(approx=1024)},
            capacity=4,
            curve_bucket_bits=10,
        )
        col.update(*_batches(n_batches=1)[0])
        with tempfile.TemporaryDirectory() as d:
            ckpt = save(col, d)
            drifted = SlicedMetricCollection(
                {"auroc": BinaryAUROC(approx=1024)},
                capacity=4,
                curve_bucket_bits=11,  # different per-slice bucket width
            )
            with self.assertRaises(CheckpointError):
                restore(drifted, ckpt)

    def test_merge_collections_by_original_id(self):
        batches = _batches(seed=13, n_batches=4, pool=17)
        make = lambda: SlicedMetricCollection(  # noqa: E731
            {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
            capacity=2,
        )
        whole = make()
        for b in batches:
            whole.update(*b)
        want = whole.compute()
        a, b_col = make(), make()
        for b in batches[:2]:
            a.update(*b)
        for b in batches[2:]:
            b_col.update(*b)
        a.merge_collections([b_col])
        got = a.compute()
        for key in ("acc", "auroc"):
            # align by id: merge appends b's unseen ids after a's
            order_w = np.argsort(want[key].slice_ids)
            order_g = np.argsort(got[key].slice_ids)
            np.testing.assert_array_equal(
                got[key].slice_ids[order_g], want[key].slice_ids[order_w]
            )
            np.testing.assert_array_equal(
                np.asarray(got[key]["values"])[order_g],
                np.asarray(want[key]["values"])[order_w],
            )

    def test_rejected_growth_rolls_back_the_id_table(self):
        # review finding: a growth the members REJECT must roll the table
        # back too — a table grown past the member states would make every
        # later batch's new cohorts scatter silently out of segment range
        col = SlicedMetricCollection({"acc": BinaryAccuracy()}, capacity=4)
        first = (
            np.asarray([1, 2], np.int64),
            np.asarray([0.9, 0.1], np.float32),
            np.asarray([1.0, 0.0], np.float32),
        )
        col.update(*first)
        mark = (col.slice_table.count, col.slice_table.capacity)

        def boom(capacity):
            raise ValueError("int32 segment-index (simulated)")

        col.metrics["acc"]._check_capacity = boom
        with self.assertRaisesRegex(ValueError, "segment-index"):
            col.update(
                np.arange(10, dtype=np.int64),
                np.zeros(10, np.float32),
                np.zeros(10, np.float32),
            )
        self.assertEqual(
            (col.slice_table.count, col.slice_table.capacity), mark
        )
        del col.metrics["acc"].__dict__["_check_capacity"]
        # the collection is fully live: the SAME cohorts register cleanly
        col.update(
            np.arange(10, dtype=np.int64),
            np.zeros(10, np.float32),
            np.zeros(10, np.float32),
        )
        res = col.compute()["acc"]
        self.assertEqual(res.num_slices, 10)  # {1,2} ∪ {0..9}
        # and the pre-failure cohorts kept their data (cohort 1: 0.9/1.0
        # from batch one + 0.0/0.0 from the retry — both correct)
        self.assertEqual(float(res.value_of(1)), 1.0)

    def test_rejected_merge_fails_closed_before_any_member_mutates(self):
        # review finding: member merges grow the SHARED table, so a later
        # member's capacity rejection (the sliced sketch's int32 extent
        # bound) must fire BEFORE any member merges — a half-merged
        # collection has no rollback
        make = lambda: SlicedMetricCollection(  # noqa: E731
            {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
            capacity=2,
        )
        batches = _batches(seed=23, n_batches=4, pool=17)
        a, b = make(), make()
        for bt in batches[:2]:
            a.update(*bt)
        for bt in batches[2:]:
            b.update(*bt)
        acc = a.metrics["acc"]
        acc._fold_now()
        table_before = (a.slice_table.count, a.slice_table.capacity)
        states_before = {
            name: np.asarray(getattr(acc, name)).copy()
            for name in acc._sliced_state_names
        }

        def boom(capacity):
            raise ValueError("int32 segment-index (simulated)")

        a.metrics["auroc"]._check_capacity = boom
        with self.assertRaisesRegex(ValueError, "segment-index"):
            a.merge_collections([b])
        # 'acc' merges before 'auroc' in member order — the rejection must
        # have fired before it touched anything
        self.assertEqual(
            (a.slice_table.count, a.slice_table.capacity), table_before
        )
        for name, before in states_before.items():
            np.testing.assert_array_equal(np.asarray(getattr(acc, name)), before)
        del a.metrics["auroc"].__dict__["_check_capacity"]
        # fully live: the SAME merge now lands and matches the whole stream
        a.merge_collections([b])
        whole = make()
        for bt in batches:
            whole.update(*bt)
        want = whole.compute()
        got = a.compute()
        for key in ("acc", "auroc"):
            order_w = np.argsort(want[key].slice_ids)
            order_g = np.argsort(got[key].slice_ids)
            np.testing.assert_array_equal(
                got[key].slice_ids[order_g], want[key].slice_ids[order_w]
            )
            np.testing.assert_array_equal(
                np.asarray(got[key]["values"])[order_g],
                np.asarray(want[key]["values"])[order_w],
            )

    def test_merge_respects_per_state_reductions(self):
        # review finding: a sum-fold member can carry MAX states (config
        # grids like BinnedPRC's threshold); merging those additively
        # would double the grid on rows both replicas hold
        from torcheval_tpu.metrics import BinaryBinnedPrecisionRecallCurve

        def make():
            return SlicedMetricCollection(
                {"prc": BinaryBinnedPrecisionRecallCurve(threshold=5)},
                capacity=4,
            )

        batches = _batches(seed=17, pool=6)
        a, b = make(), make()
        for bt in batches[:2]:
            a.update(*bt)
        for bt in batches[2:]:
            b.update(*bt)
        a.merge_collections([b])
        member = a.metrics["prc"]
        member._fold_now()
        grid = np.asarray(
            BinaryBinnedPrecisionRecallCurve(threshold=5)
            ._state_name_to_default["threshold"]
        )
        # every slice's threshold row is exactly ONE grid, not 2x
        np.testing.assert_array_equal(
            np.asarray(member.threshold),
            np.broadcast_to(grid, (member._table.capacity,) + grid.shape),
        )
        # and the counters merged by original id, matching the whole stream
        whole = make()
        for bt in batches:
            whole.update(*bt)
        want = whole.compute()["prc"]
        got = a.compute()["prc"]
        order_g = np.argsort(got.slice_ids)
        order_w = np.argsort(want.slice_ids)
        for leaf_g, leaf_w in zip(got["values"], want["values"]):
            np.testing.assert_array_equal(
                np.asarray(leaf_g)[order_g], np.asarray(leaf_w)[order_w]
            )

    def test_reset_forgets_cohorts(self):
        col = SlicedMetricCollection({"acc": BinaryAccuracy()}, capacity=4)
        col.update(*_batches(n_batches=1)[0])
        col.reset()
        self.assertEqual(col.slice_table.count, 0)
        ids = np.asarray([77, 78], np.int64)
        col.update(ids, np.asarray([0.9, 0.1], np.float32), np.asarray([1.0, 0.0], np.float32))
        res = col.compute()["acc"]
        np.testing.assert_array_equal(res.slice_ids, ids)
        np.testing.assert_array_equal(np.asarray(res["values"]), [1.0, 1.0])


class TestValidation(unittest.TestCase):
    def test_unsliceable_members_reject_with_reason(self):
        # exact curve metric: per-slice sample caches cannot survive
        with self.assertRaisesRegex(ValueError, "approx"):
            SlicedMetricCollection({"auroc": BinaryAUROC()})
        # host/cache state metric (Cat-like) rejects
        from torcheval_tpu.metrics import Cat

        with self.assertRaisesRegex(ValueError, "cannot be sliced"):
            SlicedMetricCollection({"cat": Cat()})
        # streamed template rejects (schema is part of checkpoints)
        used = BinaryAccuracy()
        used.update(
            np.asarray([0.9], np.float32), np.asarray([1.0], np.float32)
        )
        with self.assertRaisesRegex(ValueError, "fresh"):
            SlicedMetricCollection({"acc": used})

    def test_check_sliceable_approx_forwarding(self):
        # an exact curve template is sliceable iff the serve approx knob
        # WILL switch it (the validate-then-commit composition)
        check_sliceable(BinaryAUROC(), approx=1024)
        with self.assertRaises(ValueError):
            check_sliceable(BinaryAUROC(), approx=None)

    def test_update_rejects_kwargs_and_bad_columns(self):
        col = SlicedMetricCollection({"acc": BinaryAccuracy()}, capacity=4)
        with self.assertRaises(ValueError):
            col.update(np.asarray([1]), np.asarray([0.5]), weight=1.0)
        with self.assertRaises(ValueError):
            col.update(np.asarray([1.5]), np.asarray([0.5], np.float32))
        with self.assertRaises(ValueError):
            col.update(np.asarray([1, 2], np.int64))

    def test_mismatched_column_lengths_reject(self):
        col = SlicedMetricCollection({"acc": BinaryAccuracy()}, capacity=4)
        with self.assertRaises(ValueError):
            col.update(
                np.asarray([1, 2, 3], np.int64),
                np.asarray([0.5, 0.5], np.float32),
                np.asarray([1.0, 0.0], np.float32),
            )

    def test_sketch_extent_fails_closed_before_int32_index_wrap(self):
        # review finding: the combined segment index is int32 — past
        # num_slices * (2B+1) > 2^31-1 it would WRAP and silently corrupt
        # per-slice counts. Construction, and growth that would cross the
        # bound, must raise with the remedies named instead.
        from torcheval_tpu.sketch.cache import check_sliced_sketch_extent

        planes = 2 * 1024 + 1  # bits=10
        at_bound = (2**31 - 1) // planes
        check_sliced_sketch_extent(10, at_bound)  # inside: fine
        with self.assertRaisesRegex(ValueError, "int32 segment-index"):
            check_sliced_sketch_extent(10, at_bound + 1)
        # the remedy names the EXACT serve knob (ISSUE 17): slice-axis
        # sharding relaxes the bound to per-shard
        with self.assertRaisesRegex(
            ValueError, r'slices=\{"mesh_axis": \.\.\.\}'
        ):
            check_sliced_sketch_extent(10, at_bound + 1)
        # ... and the bound IS per shard: the same extent passes when
        # split over enough shards, and fails closed past the
        # per-shard edge
        check_sliced_sketch_extent(10, 2 * at_bound, shards=2)
        with self.assertRaisesRegex(ValueError, "int32 segment-index"):
            check_sliced_sketch_extent(10, 2 * (at_bound + 1), shards=2)
        # construction rejects INSTANTLY (before materializing multi-GB
        # default histograms): default 16-bit buckets cap at ~16k slices
        with self.assertRaisesRegex(ValueError, "int32 segment-index"):
            SlicedMetricCollection(
                {"auroc": BinaryAUROC(approx=True)}, capacity=20_000
            )
        # growth path: the pre-pad validation rejects a capacity past the
        # bound with the member left consistent at its old capacity
        col = SlicedMetricCollection(
            {"auroc": BinaryAUROC(approx=1024)},
            capacity=4,
            curve_bucket_bits=10,
        )
        col.update(
            np.asarray([1, 2], np.int64),
            np.asarray([0.5, 0.5], np.float32),
            np.asarray([1.0, 0.0], np.float32),
        )
        col.slice_table.replace(
            col.slice_table.registered_ids(), at_bound + 1
        )
        with self.assertRaisesRegex(ValueError, "int32 segment-index"):
            col._grow_members()
        self.assertEqual(int(col.metrics["auroc"].sketch_tp.shape[0]), 4)

    def test_sliceable_family_coverage(self):
        for metric in (
            BinaryAccuracy(),
            MulticlassAccuracy(num_classes=3),
            MeanSquaredError(),
            Sum(),
            Max(),
            ClickThroughRate(),
        ):
            check_sliceable(metric)


class TestSlicedResult(unittest.TestCase):
    def test_accessors_and_dict_protocol(self):
        col = SlicedMetricCollection({"acc": BinaryAccuracy()}, capacity=4)
        ids = np.asarray([9, 4], np.int64)
        col.update(
            np.asarray([9, 9, 4], np.int64),
            np.asarray([0.9, 0.1, 0.8], np.float32),
            np.asarray([1.0, 1.0, 1.0], np.float32),
        )
        res = col.compute()["acc"]
        np.testing.assert_array_equal(res.slice_ids, ids)
        self.assertEqual(res.num_slices, 2)
        self.assertEqual(float(res.value_of(4)), 1.0)
        self.assertEqual(res.as_dict()[9], 0.5)
        with self.assertRaises(KeyError):
            res.value_of(123)
        # dict protocol intact (the wire marshals it as a plain dict)
        self.assertEqual(sorted(res.keys()), ["slice_ids", "values"])
        self.assertEqual(len(list(res.values())), 2)

    def test_tuple_valued_results_are_tree_aware(self):
        # review finding: members whose compute returns a TUPLE per slice
        # (curve points) must index each leaf's slice axis in as_dict /
        # value_of, not the stack axis np.asarray would invent
        from torcheval_tpu.metrics.sliced import SlicedResult

        ids = np.asarray([7, 8, 9], np.int64)
        precision = np.arange(6, dtype=np.float32).reshape(3, 2)
        recall = precision + 100.0
        res = SlicedResult(ids, (precision, recall))
        d = res.as_dict()
        self.assertEqual(sorted(d), [7, 8, 9])
        np.testing.assert_array_equal(d[9][0], precision[2])
        np.testing.assert_array_equal(d[9][1], recall[2])
        v = res.value_of(8)
        np.testing.assert_array_equal(np.asarray(v[0]), precision[1])


if __name__ == "__main__":
    unittest.main()

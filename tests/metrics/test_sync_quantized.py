"""Quantized metric-state sync lanes (ISSUE 12).

The wire codecs run inside ``_gather_collection_states``, so these tests
drive the REAL two-round exchange — encode, descriptor matrix, payload
concatenation, per-rank decode, fold — over a **simulated wire**: W
threads, one per rank, rendezvous at a barrier inside a stubbed
``_allgather_stacked_impl`` and exchange genuinely different per-rank
buffers. That exercises everything but the transport (which the real
4-process world in ``test_multiprocess_sync.py`` covers, re-run by CI
with ``TORCHEVAL_TPU_SYNC_QUANTIZE=1``).

Contracts pinned here, per the ISSUE 12 acceptance:

* integer SUM/MAX/MIN lanes are BIT-EXACT at world sizes 2/4/8
  (lossless narrowing + widened accumulation);
* f32 SUM lanes drift within the documented bound — per element, at most
  ``sum over ranks of max|rank block| / 254`` (each rank contributes at
  most half a quantization step; docs/distributed.md "Quantized sync");
* ``quantize=False`` opts out per call and restores exact raw bytes even
  with the env knob forced on;
* non-finite f32 entries fall back to the raw lane (error-channel shape)
  and the synced result is bit-identical to an unquantized sync;
* the ``lane_bytes`` / ``lane_bytes_encoded`` pair shows >= 4x shrink on
  an integer-lane-dominant state at world size 8, and agrees exactly
  when the codec is raw;
* ranks that DISAGREE on the knob (env drift) still interoperate — the
  codec travels per entry in the descriptor.
"""

import os
import threading
import unittest
from unittest import mock

import jax.numpy as jnp
import numpy as np

import torcheval_tpu.metrics.toolkit as tk
from torcheval_tpu import obs
from torcheval_tpu.metrics.metric import Metric
from torcheval_tpu.metrics.state import Reduction


class BigState(Metric):
    """Integer-lane-dominant metric: two int64 SUM count lanes (the
    dominant payload — held as host numpy so the 64-bit width survives
    jax's 32-bit default, exactly like the toolkit's own faithful-numpy
    decode path), an int32 MAX watermark, and an f32 SUM tail."""

    N = 4096
    RAW_BYTES = N * (8 + 8 + 4 + 4)  # the four states' raw wire bytes

    def __init__(self, **kw):
        super().__init__(**kw)
        self._add_state(
            "counts", np.zeros(self.N, np.int64), reduction=Reduction.SUM
        )
        self._add_state(
            "hits", np.zeros(self.N, np.int64), reduction=Reduction.SUM
        )
        self._add_state(
            "peak", jnp.zeros(self.N, jnp.int32), reduction=Reduction.MAX
        )
        self._add_state(
            "fsum", jnp.zeros(self.N, jnp.float32), reduction=Reduction.SUM
        )

    def update(self, c, h, p, f):
        self.counts = np.asarray(self.counts, np.int64) + np.asarray(
            c, np.int64
        )
        self.hits = np.asarray(self.hits, np.int64) + np.asarray(
            h, np.int64
        )
        self.peak = jnp.maximum(self.peak, jnp.asarray(p, jnp.int32))
        self.fsum = self.fsum + jnp.asarray(f)
        return self

    def compute(self):
        return (
            int(self.counts.sum()),
            int(self.hits.sum()),
            int(jnp.max(self.peak)),
            float(jnp.sum(self.fsum)),
        )

    def merge_state(self, metrics):
        for o in metrics:
            self.counts = self.counts + np.asarray(o.counts, np.int64)
            self.hits = self.hits + np.asarray(o.hits, np.int64)
            self.peak = jnp.maximum(self.peak, o.peak)
            self.fsum = self.fsum + o.fsum
        return self


def make_replica(rank: int, fscale: float = 10.0) -> BigState:
    rng = np.random.default_rng(100 + rank)
    return BigState().update(
        rng.integers(0, 200, BigState.N),
        rng.integers(0, 50, BigState.N),
        rng.integers(0, 1000, BigState.N),
        (rng.random(BigState.N) * fscale).astype(np.float32),
    )


class _SimWire:
    """Barrier-coordinated allgather stub: each rank thread contributes
    its own buffer and receives the genuine per-rank stack — the real
    collective's semantics, minus the network."""

    def __init__(self, world: int):
        self.world = world
        self.barrier = threading.Barrier(world)
        self.slots = [None] * world
        self.tls = threading.local()
        self.round_bytes = []
        self._lock = threading.Lock()

    def allgather(self, x, group):
        assert group is None
        rank = self.tls.rank
        self.slots[rank] = np.array(x, copy=True)
        self.barrier.wait()
        out = np.stack(self.slots)
        with self._lock:
            self.round_bytes.append(int(np.asarray(x).nbytes))
        self.barrier.wait()  # all read before the next round overwrites
        return out


def run_world(world, fn):
    """Run ``fn(rank)`` on W rank threads under the simulated wire;
    returns per-rank results (exceptions re-raised). The module patches
    are entered ONCE on the main thread — entering mock.patch per rank
    thread would race the save/restore and could leak a patched
    ``_world_size`` into later tests; the per-thread rank rides the sim's
    thread-local instead."""
    sim = _SimWire(world)
    results = [None] * world
    errors = []

    def runner(rank):
        sim.tls.rank = rank
        try:
            results[rank] = fn(rank)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((rank, e))

    with mock.patch.object(
        tk, "_allgather_stacked_impl", sim.allgather
    ), mock.patch.object(
        tk, "_world_size", lambda: world
    ), mock.patch.object(
        tk, "_process_index", lambda: sim.tls.rank
    ):
        threads = [
            threading.Thread(target=runner, args=(r,)) for r in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0][1]
    return results, sim


def exact_merge(world, fscale: float = 10.0) -> BigState:
    base = make_replica(0, fscale)
    return base.merge_state(
        [make_replica(r, fscale) for r in range(1, world)]
    )


class TestQuantizedSync(unittest.TestCase):
    def _sync_world(self, world, quantize, fscale=10.0):
        def fn(rank):
            return tk.get_synced_metric(
                make_replica(rank, fscale),
                recipient_rank="all",
                quantize=quantize,
            )

        return run_world(world, fn)

    def test_integer_lanes_bit_exact_across_world_sizes(self):
        for world in (2, 4, 8):
            results, _ = self._sync_world(world, quantize=True)
            want = exact_merge(world)
            for synced in results:
                np.testing.assert_array_equal(
                    np.asarray(synced.counts), np.asarray(want.counts)
                )
                np.testing.assert_array_equal(
                    np.asarray(synced.peak), np.asarray(want.peak)
                )

    def test_f32_sum_drift_within_documented_bound(self):
        # the documented tolerance: each rank's entry dequantizes within
        # max|block|/254 per element, and the fold adds one such error
        # per contributing rank — so the synced sum sits within
        # sum_r(max|rank_r|)/254 of the exact rank-ordered fold (plus a
        # whisker for f32 accumulation order)
        for world in (2, 4, 8):
            results, _ = self._sync_world(world, quantize=True)
            want = np.asarray(exact_merge(world).fsum)
            bound = sum(
                float(np.abs(np.asarray(make_replica(r).fsum)).max())
                for r in range(world)
            ) / 254.0
            for synced in results:
                drift = np.abs(np.asarray(synced.fsum) - want)
                self.assertGreater(drift.max(), 0)  # actually quantized
                self.assertLessEqual(drift.max(), bound * 1.0001)

    def test_quantize_false_opt_out_restores_exact_bytes(self):
        env = {"TORCHEVAL_TPU_SYNC_QUANTIZE": "1"}
        with mock.patch.dict(os.environ, env):
            results, sim = self._sync_world(4, quantize=False)
        want = exact_merge(4)
        for synced in results:
            np.testing.assert_array_equal(
                np.asarray(synced.fsum), np.asarray(want.fsum)
            )
        # payload round carries the full raw bytes of all four states
        self.assertEqual(sim.round_bytes[-1], BigState.RAW_BYTES)

    def test_env_default_engages_quantization(self):
        env = {"TORCHEVAL_TPU_SYNC_QUANTIZE": "1"}
        with mock.patch.dict(os.environ, env):
            _, sim = self._sync_world(4, quantize=None)
        self.assertLess(sim.round_bytes[-1], BigState.RAW_BYTES // 3)

    def test_payload_round_shrinks_at_least_4x_at_world_8(self):
        _, sim_raw = self._sync_world(8, quantize=False)
        _, sim_q = self._sync_world(8, quantize=True)
        self.assertLessEqual(
            sim_q.round_bytes[-1] * 4, sim_raw.round_bytes[-1]
        )

    def test_lane_bytes_encoded_counters_and_ratio(self):
        obs.enable()
        try:
            obs.reset()
            self._sync_world(8, quantize=True)
            counters = obs.snapshot()["counters"]
            raw = counters["toolkit.sync.lane_bytes{lane=SUM}"]
            raw += counters["toolkit.sync.lane_bytes{lane=MAX}"]
            enc = sum(
                v
                for k, v in counters.items()
                if k.startswith("toolkit.sync.lane_bytes_encoded{")
            )
            self.assertGreater(raw, 0)
            # >= 4x on the integer-dominant state (acceptance criterion)
            self.assertLessEqual(enc * 4, raw)
            # raw codec label absent: every lane actually encoded
            self.assertNotIn(
                "toolkit.sync.lane_bytes_encoded{codec=raw,lane=SUM}",
                counters,
            )

            # and with the codec RAW, the two counters agree exactly
            # (the lane_bytes accounting-drift guard)
            obs.reset()
            self._sync_world(4, quantize=False)
            counters = obs.snapshot()["counters"]
            for lane in ("SUM", "MAX"):
                self.assertEqual(
                    counters[f"toolkit.sync.lane_bytes{{lane={lane}}}"],
                    counters[
                        "toolkit.sync.lane_bytes_encoded"
                        f"{{codec=raw,lane={lane}}}"
                    ],
                )
        finally:
            obs.disable()
            obs.reset()

    def test_nonfinite_f32_falls_back_to_raw_lane(self):
        def fn(rank):
            m = make_replica(rank)
            bad = np.zeros(BigState.N, np.float32)
            bad[rank] = np.inf if rank % 2 else np.nan
            m.update(
                np.zeros(BigState.N, np.int64),
                np.zeros(BigState.N, np.int64),
                np.zeros(BigState.N, np.int64),
                bad,
            )
            return tk.get_synced_metric(
                m, recipient_rank="all", quantize=True
            )

        obs.enable()
        try:
            obs.reset()
            results, _ = run_world(4, fn)
            counters = obs.snapshot()["counters"]
            self.assertGreaterEqual(
                counters["toolkit.sync.quantize_fallbacks{reason=nonfinite}"],
                4,
            )
        finally:
            obs.disable()
            obs.reset()
        # the f32 lane shipped raw: results bit-identical to an exact
        # merge (NaN/inf propagate exactly as an unquantized sync would)
        fsum = np.asarray(results[0].fsum)
        self.assertTrue(np.isnan(fsum[0]))
        self.assertTrue(np.isinf(fsum[1]))
        # integer lanes still narrowed and exact
        want = exact_merge(4)
        np.testing.assert_array_equal(
            np.asarray(results[0].counts), np.asarray(want.counts)
        )

    def test_mixed_knob_ranks_interoperate(self):
        # env drift: rank 0 quantizes, the others do not — the per-entry
        # codec column makes decode per-rank, so the sync still lands,
        # ints exact, floats within the single quantizing rank's bound
        def fn(rank):
            return tk.get_synced_metric(
                make_replica(rank),
                recipient_rank="all",
                quantize=(rank == 0),
            )

        results, _ = run_world(4, fn)
        want = exact_merge(4)
        bound = float(np.abs(np.asarray(make_replica(0).fsum)).max()) / 254.0
        for synced in results:
            np.testing.assert_array_equal(
                np.asarray(synced.counts), np.asarray(want.counts)
            )
            drift = np.abs(np.asarray(synced.fsum) - np.asarray(want.fsum))
            self.assertLessEqual(drift.max(), bound * 1.0001)

    def test_small_f32_states_stay_bit_exact_under_quantize(self):
        # scalar/small states never quantize (Q8_MIN_ELEMENTS floor):
        # a Sum metric synced with quantization forced on is bit-exact
        from torcheval_tpu.metrics import Sum

        def fn(rank):
            s = Sum()
            s.update(jnp.asarray([float(rank + 1), 2.0 * (rank + 1)]))
            return tk.sync_and_compute(
                s, recipient_rank="all", quantize=True
            )

        results, _ = run_world(4, fn)
        for value in results:
            self.assertEqual(float(np.asarray(value)), 30.0)

    def test_sync_is_still_two_rounds(self):
        _, sim = self._sync_world(4, quantize=True)
        self.assertEqual(len(sim.round_bytes), 2 * 4)  # 2 rounds x 4 ranks


if __name__ == "__main__":
    unittest.main()

"""Slice-axis-sharded collections (ISSUE 17 tentpole) on the forced
8-device CPU mesh (``tests/conftest.py``): numeric BIT-parity with the
unsharded twin (integer counters exact, sketch curves exact — the fold
order per slice is identical), real ``P(axis)`` state placement, the
no-state-replication HLO bound on the fold lowering, growth / merge /
reset / clone / cross-load round trips, and the per-shard sketch-extent
envelope."""

import copy
import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    SlicedMetricCollection,
)
from torcheval_tpu.metrics.sliced import _ID_STATE_NAMES, _sliced_fold

SHARDS = 8


def _make(sharded: bool, capacity: int = 8, **kw):
    mesh_kw = {"mesh_axis": "slices"} if sharded else {}
    return SlicedMetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
        capacity=capacity,
        **mesh_kw,
        **kw,
    )


def _batches(n_unique: int, n_batches: int = 3, n: int = 4096, seed: int = 7):
    rng = np.random.default_rng(seed)
    pool = np.arange(n_unique, dtype=np.int64) * 991 + 7
    out = []
    for _ in range(n_batches):
        ids = rng.choice(pool, n)
        scores = rng.random(n).astype(np.float32)
        targets = (rng.random(n) < 0.5).astype(np.float32)
        out.append((ids, scores, targets))
    return out


def _feed(col, batches):
    for b in batches:
        col.update(*b)
    return col


def _values(col):
    res = col.compute()
    return {
        "ids": np.asarray(res["acc"].slice_ids),
        "acc": np.asarray(res["acc"]["values"]),
        "auroc": np.asarray(res["auroc"]["values"]),
    }


def _assert_same(test, got, want):
    np.testing.assert_array_equal(got["ids"], want["ids"])
    np.testing.assert_array_equal(got["acc"], want["acc"])
    np.testing.assert_array_equal(got["auroc"], want["auroc"])


class TestShardedParity(unittest.TestCase):
    def _parity(self, n_unique, capacity):
        batches = _batches(n_unique)
        want = _values(_feed(_make(False, capacity), batches))
        col = _feed(_make(True, capacity), batches)
        got = _values(col)
        _assert_same(self, got, want)
        return col

    def test_parity_small_capacity(self):
        # S=8: one slice row per shard
        col = self._parity(n_unique=8, capacity=8)
        self.assertEqual(col.slice_table.capacity, 8)

    def test_parity_wide_with_growth_past_2048(self):
        # S >= 2048 with table growth crossing the sharded capacity
        # (growth stays a multiple of the shard count; the sketch curves
        # stay bit-identical because each slice's histogram sees the same
        # adds in the same order, just on its owning shard)
        col = self._parity(n_unique=2500, capacity=2048)
        self.assertGreaterEqual(col.slice_table.capacity, 2560)
        self.assertEqual(col.slice_table.capacity % SHARDS, 0)

    def test_states_genuinely_sharded_ids_replicated(self):
        col = _feed(_make(True, capacity=64), _batches(48))
        for m in col.metrics.values():
            m._fold_now()
            for name in m._sliced_state_names:
                st = getattr(m, name)
                self.assertEqual(st.sharding.spec, P("slices"), name)
                self.assertFalse(st.sharding.is_fully_replicated, name)
                # each device holds exactly capacity/8 slice rows
                shard_rows = {
                    s.data.shape[0] for s in st.addressable_shards
                }
                self.assertEqual(shard_rows, {64 // SHARDS}, name)
            for name in _ID_STATE_NAMES:
                st = getattr(m, name)
                self.assertTrue(
                    st.sharding.is_fully_replicated, name
                )

    def test_capacity_rounds_up_to_shard_multiple(self):
        col = _make(True, capacity=3)
        self.assertEqual(col.slice_table.capacity, SHARDS)

    def test_explicit_mesh_and_validation(self):
        mesh = Mesh(np.asarray(jax.devices()), ("cohorts",))
        col = SlicedMetricCollection(
            {"acc": BinaryAccuracy()},
            capacity=8,
            mesh=mesh,
            mesh_axis="cohorts",
        )
        _feed(col, _batches(8, n_batches=1))
        col.metrics["acc"]._fold_now()
        st = col.metrics["acc"].num_correct
        self.assertEqual(st.sharding.spec, P("cohorts"))
        with self.assertRaisesRegex(ValueError, "mesh_axis"):
            SlicedMetricCollection(
                {"acc": BinaryAccuracy()}, capacity=8, mesh=mesh
            )
        with self.assertRaisesRegex(ValueError, "nope"):
            SlicedMetricCollection(
                {"acc": BinaryAccuracy()},
                capacity=8,
                mesh=mesh,
                mesh_axis="nope",
            )

    def test_merge_collections_parity(self):
        batches = _batches(40, n_batches=4)
        want = _values(_feed(_make(False), batches))
        a = _feed(_make(True), batches[:2])
        b = _feed(_make(True), batches[2:])
        _assert_same(self, _values(a.merge_collections([b])), want)

    def test_reset_then_reuse_parity(self):
        batches = _batches(24)
        col = _feed(_make(True), batches)
        col.compute()
        col.reset()
        _assert_same(
            self,
            _values(_feed(col, batches)),
            _values(_feed(_make(False), batches)),
        )

    def test_deepcopy_keeps_sharding_and_parity(self):
        col = _feed(_make(True), _batches(24))
        want = _values(col)
        clone = copy.deepcopy(col)
        _assert_same(self, _values(clone), want)
        m = clone.metrics["auroc"]
        m._fold_now()
        self.assertEqual(m.sketch_tp.sharding.spec, P("slices"))
        # the clone shares the SAME mesh object (meshes carry live device
        # handles — they are session singletons, not state)
        self.assertIs(
            clone._slice_shard[0], col._slice_shard[0]
        )

    def test_state_dicts_cross_load_both_directions(self):
        batches = _batches(24)
        want = _values(_feed(_make(False), batches))
        sharded = _feed(_make(True), batches)
        plain = _make(False)
        plain.load_state_dicts(sharded.state_dicts())
        _assert_same(self, _values(plain), want)
        back = _make(True)
        back.load_state_dicts(plain.state_dicts())
        _assert_same(self, _values(back), want)
        m = back.metrics["auroc"]
        self.assertEqual(m.sketch_tp.sharding.spec, P("slices"))


class TestShardedFoldHLO(unittest.TestCase):
    """The no-state-replication bound: the compiled window fold holds no
    per-device full-extent ``[S, ...]`` buffer and runs no all-gather —
    every state-sized array in the program is the ``S/8`` shard tile."""

    def _compiled_fold_text(self, member, arg_shapes):
        fold = jax.jit(
            lambda *a: _sliced_fold(*a, *member._fold_params)
        )
        return fold.lower(*arg_shapes).compile().as_text()

    def test_counter_member_fold(self):
        col = _make(True, capacity=4096)
        n = 2048
        hlo = self._compiled_fold_text(
            col.metrics["acc"],
            (
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.float32),
                jax.ShapeDtypeStruct((n,), jnp.float32),
            ),
        )
        self.assertNotIn("all-gather", hlo)
        for full in ("f32[4096,", "s32[4096,", "f32[4096]", "s32[4096]"):
            self.assertNotIn(full, hlo)
        self.assertIn("[512", hlo)  # the per-shard block tile

    def test_sketch_member_fold(self):
        col = _make(True, capacity=4096)
        m = col.metrics["auroc"]
        n = 2048
        fold = jax.jit(lambda *a: m._fold_fn(*a, *m._fold_params))
        hlo = (
            fold.lower(
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.float32),
                jax.ShapeDtypeStruct((n,), jnp.float32),
            )
            .compile()
            .as_text()
        )
        self.assertNotIn("all-gather", hlo)
        # the global histogram would be s32[4096,1024]; only the
        # per-shard s32[512,1024] tile may exist per device
        self.assertNotIn("[4096,1024]", hlo)
        self.assertIn("[512,1024]", hlo)


class TestShardedSketchExtent(unittest.TestCase):
    """The int32 segment-index bound is PER SHARD: capacities the
    unsharded member must reject fit once split over the mesh (the
    acceptance criterion's capacity math — materializing a real ~2^31
    histogram is a TPU-pod exercise, so the envelope is proven at the
    member validation hook the construction/growth paths call)."""

    def test_member_bound_is_per_shard(self):
        plain = _make(False).metrics["auroc"]
        sharded = _make(True).metrics["auroc"]
        planes = 2 * 1024 + 1  # approx=1024 with matching bucket bits
        bound = (2**31 - 1) // planes
        # past the unsharded bound: plain member fails closed...
        with self.assertRaisesRegex(ValueError, "int32 segment-index"):
            plain._check_capacity(SHARDS * bound)
        # ...the 8-shard member accepts the same capacity...
        sharded._check_capacity(SHARDS * bound)
        # ...and fails closed again past ITS per-shard edge, still naming
        # the serve knob
        with self.assertRaisesRegex(
            ValueError, r'slices=\{"mesh_axis": \.\.\.\}'
        ):
            sharded._check_capacity(SHARDS * (bound + 1))


if __name__ == "__main__":
    unittest.main()

"""Worker for ``test_multihost_spmd.py``: REAL multi-host SPMD evaluation.

4 OS processes × 2 local CPU devices each = one 8-device global mesh. Every
process runs the SAME program in lockstep (standard multi-controller JAX):
``ShardedEvaluator`` folds globally-sharded batches into replicated metric
state, and the curve metric's compute runs as one partitioned program over a
cache whose shards are mostly NON-addressable from any single process — the
exact situation the docs' multi-host story (docs/distributed.md "Lane 1")
claims to handle with no host-side shard touching.

Batch construction uses ``jax.make_array_from_process_local_data`` fed only
this host's shard (the per-host data-loader idiom — the only legal one
multi-host). The worker also asserts ``shard_batch`` REJECTS host-local data
in this world with guidance pointing at that idiom: scattering host values
across hosts would need cross-host transfers the backend doesn't provide.

Run:  python mp_spmd_worker.py <rank> <world> <port> <outdir>
"""

import json
import os
import sys

import numpy as np

NUM_CLASSES = 5
GLOBAL_BATCH = 64  # divisible by the 8-device mesh
N_BATCHES = 3
LOCAL_DEVICES = 2


def make_global_batch(b: int):
    rng = np.random.default_rng(500 + b)
    scores = rng.random((GLOBAL_BATCH, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, GLOBAL_BATCH)
    logits = rng.random(GLOBAL_BATCH).astype(np.float32)
    binary = (rng.random(GLOBAL_BATCH) < 0.4).astype(np.float32)
    return scores, labels, logits, binary


def _jsonable(x):
    arr = np.asarray(x)
    return arr.tolist() if arr.ndim else float(arr)


def main() -> None:
    rank, world, port, outdir = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    import jax

    from torcheval_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(LOCAL_DEVICES)
    from torcheval_tpu.parallel import init_from_env

    os.environ["MASTER_ADDR"] = "localhost"
    os.environ["MASTER_PORT"] = port
    os.environ["WORLD_SIZE"] = str(world)
    os.environ["RANK"] = str(rank)
    got_rank, got_world = init_from_env()
    assert (got_rank, got_world) == (rank, world)
    assert len(jax.devices()) == world * LOCAL_DEVICES, jax.devices()
    assert len(jax.local_devices()) == LOCAL_DEVICES

    from torcheval_tpu.metrics import BinaryAUROC, MulticlassAccuracy
    from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh

    mesh = data_parallel_mesh()  # all 8 global devices
    assert mesh.devices.size == world * LOCAL_DEVICES

    results = {"rank": rank}

    # host-local data through shard_batch must fail loudly on a multi-process
    # world (device_put cannot scatter host values across hosts)
    from torcheval_tpu.parallel import shard_batch

    try:
        shard_batch(mesh, np.zeros((GLOBAL_BATCH, 2), np.float32))
        results["host_data_guard"] = "MISSING"
    except ValueError as e:
        results["host_data_guard"] = (
            "ok" if "make_array_from_process_local_data" in str(e) else str(e)
        )

    # global batches built from each host's LOCAL shard (the per-host
    # data-loader idiom); ShardedEvaluator accepts them as-is. acc and auroc
    # take different inputs, so each gets its own evaluator (a collection
    # broadcasts one update signature to all members).
    ev = ShardedEvaluator(MulticlassAccuracy(num_classes=NUM_CLASSES), mesh=mesh)
    ev_auroc = ShardedEvaluator(BinaryAUROC(), mesh=mesh)
    for b in range(N_BATCHES):
        scores, labels, logits, binary = make_global_batch(b)
        ev.update(*_global_from_local(mesh, rank, scores, labels))
        ev_auroc.update(*_global_from_local(mesh, rank, logits, binary))
    results["acc"] = _jsonable(ev.compute())
    results["auroc"] = _jsonable(ev_auroc.compute())

    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(results, f)


def _global_from_local(mesh, rank, *full_arrays):
    """Lane 2: build the global array from THIS process's local shard only
    (``make_array_from_process_local_data``) — the per-host data-loader idiom.
    The full array is deterministic in every process; each host slices its
    own quarter, and the resulting global jax.Array has non-addressable
    shards everywhere else."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    world = jax.process_count()
    out = []
    for full in full_arrays:
        per = full.shape[0] // world
        local = full[rank * per : (rank + 1) * per]
        sharding = NamedSharding(mesh, P("data"))
        out.append(jax.make_array_from_process_local_data(sharding, local))
    return tuple(out)


if __name__ == "__main__":
    main()

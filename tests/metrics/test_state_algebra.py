"""Algebraic invariants of the streaming-state protocol.

The reference's harness checks one algebraic fact (N-way merge == single
stream); these tests pin the rest of the algebra every distributed eval
loop implicitly relies on — if any fails, some ordering of workers, shards,
or merge trees silently changes results:

* update-order invariance: counters don't care which batch came first, and
  curve metrics don't care which rank's cache lands first;
* merge associativity: ``(a+b)+c == a+(b+c)`` — a pod folding replicas in a
  tree must agree with a ring;
* merge identity: merging a fresh (never-updated) replica is a no-op;
* reset returns to the true initial state (compute-after-reset behaves like
  a fresh instance, including for deferred and cache metrics).
"""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import (
    BinaryAUROC,
    BinaryPrecisionRecallCurve,
    Max,
    Mean,
    MeanSquaredError,
    MulticlassAccuracy,
    MulticlassF1Score,
    Sum,
)

RNG = np.random.default_rng(7)


def _cls_batches(k, n=64, c=4):
    out = []
    for _ in range(k):
        s = RNG.random((n, c)).astype(np.float32)
        t = RNG.integers(0, c, n)
        out.append((jnp.asarray(s), jnp.asarray(t)))
    return out


def _bin_batches(k, n=64):
    out = []
    for _ in range(k):
        x = RNG.random(n).astype(np.float32)
        t = (RNG.random(n) < 0.5).astype(np.float32)
        out.append((jnp.asarray(x), jnp.asarray(t)))
    return out


def _reg_batches(k, n=64):
    return [
        (
            jnp.asarray(RNG.random(n).astype(np.float32)),
            jnp.asarray(RNG.random(n).astype(np.float32)),
        )
        for _ in range(k)
    ]


MAKERS = (
    ("acc", lambda: MulticlassAccuracy(num_classes=4), _cls_batches),
    ("f1", lambda: MulticlassF1Score(num_classes=4, average="macro"), _cls_batches),
    ("auroc", BinaryAUROC, _bin_batches),
    ("mse", MeanSquaredError, _reg_batches),
    ("sum", Sum, lambda k: [(b[0],) for b in _reg_batches(k)]),
    ("mean", Mean, lambda k: [(b[0],) for b in _reg_batches(k)]),
    ("max", Max, lambda k: [(b[0],) for b in _reg_batches(k)]),
)


def _fed(make, batches):
    m = make()
    for b in batches:
        m.update(*b)
    return m


def _assert_same(a, b, msg):
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7, err_msg=msg
    )


class TestStateAlgebra(unittest.TestCase):
    def test_update_order_invariance(self):
        for name, make, gen in MAKERS:
            batches = gen(4)
            fwd = _fed(make, batches).compute()
            rev = _fed(make, list(reversed(batches))).compute()
            _assert_same(fwd, rev, f"{name}: update order changed the result")

    def test_merge_associativity(self):
        for name, make, gen in MAKERS:
            batches = gen(3)
            # (a + b) + c
            a, b, c = (_fed(make, [bt]) for bt in batches)
            left = a.merge_state([b]).merge_state([c]).compute()
            # a + (b + c)
            a2, b2, c2 = (_fed(make, [bt]) for bt in batches)
            right = a2.merge_state([b2.merge_state([c2])]).compute()
            _assert_same(left, right, f"{name}: merge is not associative")
            # and both equal the single stream
            single = _fed(make, batches).compute()
            _assert_same(left, single, f"{name}: merge tree != single stream")

    def test_merge_identity(self):
        for name, make, gen in MAKERS:
            batches = gen(2)
            fed = _fed(make, batches)
            want = np.asarray(fed.compute())
            fed2 = _fed(make, batches)
            fed2.merge_state([make()])  # fresh replica: identity element
            _assert_same(
                fed2.compute(), want, f"{name}: merging a fresh replica changed state"
            )

    def test_reset_equals_fresh(self):
        for name, make, gen in MAKERS:
            batches = gen(2)
            m = _fed(make, batches)
            m.reset()
            probe = gen(2)
            m2 = make()
            for b in probe:
                m.update(*b)
                m2.update(*b)
            _assert_same(
                m.compute(), m2.compute(), f"{name}: reset metric != fresh metric"
            )

    def test_curve_metric_rank_order_invariance(self):
        # CAT caches from different "ranks" in any order: the sort inside
        # compute makes cache order irrelevant
        batches = _bin_batches(3)
        a = _fed(BinaryPrecisionRecallCurve, batches)
        b = _fed(BinaryPrecisionRecallCurve, list(reversed(batches)))
        for o, r in zip(a.compute(), b.compute()):
            _assert_same(o, r, "PRC: cache order changed the curve")


if __name__ == "__main__":
    unittest.main()

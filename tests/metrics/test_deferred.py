"""DeferredFoldMixin edge cases (metrics/deferred.py).

The hot-loop machinery behind every counter metric since round 3: update()
is an O(1) append, the math folds lazily. These tests pin the lifecycle
edges the collection tests don't reach: merges with pending batches on both
sides, signature-change flushes, the tracer fallback inside an enclosing
jit, pickling mid-stream, the byte-budget valve, and load_state_dict's
drop-pending contract.
"""

import pickle
import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MulticlassF1Score,
)

RNG = np.random.default_rng(42)


def _batch(n=32, c=4):
    return (
        RNG.random((n, c)).astype(np.float32),
        RNG.integers(0, c, n),
    )


class TestDeferredEdges(unittest.TestCase):
    def test_merge_with_pending_on_both_sides(self):
        a, b = MulticlassAccuracy(num_classes=4), MulticlassAccuracy(num_classes=4)
        xa, ta = _batch()
        xb, tb = _batch()
        a.update(jnp.asarray(xa), jnp.asarray(ta))  # pending, unfolded
        b.update(jnp.asarray(xb), jnp.asarray(tb))  # pending, unfolded
        self.assertTrue(a._pending and b._pending)  # the scenario premise
        a.merge_state([b])
        X, T = np.concatenate([xa, xb]), np.concatenate([ta, tb])
        self.assertAlmostEqual(
            float(a.compute()), float((X.argmax(1) == T).mean()), places=6
        )
        # merge must not have mutated the source
        self.assertAlmostEqual(
            float(b.compute()), float((xb.argmax(1) == tb).mean()), places=6
        )

    def test_signature_change_flushes_pending(self):
        # (N,) 1-D input batches then an (N, C) 2-D batch: ranks differ, so
        # the mixin must flush the old signature before queueing the new one
        # (one concatenation never mixes ranks) and still count everything
        m = MulticlassAccuracy(num_classes=4)
        t1 = RNG.integers(0, 4, 16)
        m.update(jnp.asarray(t1.astype(np.float32)), jnp.asarray(t1))  # 1-D
        x2, t2 = _batch(24)
        m.update(jnp.asarray(x2), jnp.asarray(t2))  # 2-D: flush + append
        correct = 16 + int((x2.argmax(1) == t2).sum())
        self.assertAlmostEqual(float(m.compute()), correct / 40.0, places=6)

    def test_dtype_change_flushes_pending(self):
        m = BinaryAccuracy()
        x1 = RNG.random(16).astype(np.float32)
        t1 = RNG.integers(0, 2, 16).astype(np.float32)
        m.update(jnp.asarray(x1), jnp.asarray(t1))
        x2 = RNG.random(16).astype(np.float32)
        t2 = RNG.integers(0, 2, 16).astype(np.int32)  # target dtype changes
        m.update(jnp.asarray(x2), jnp.asarray(t2))
        X = np.concatenate([x1, x2])
        T = np.concatenate([t1, t2.astype(np.float32)])
        self.assertAlmostEqual(
            float(m.compute()), float(((X >= 0.5) == T).mean()), places=6
        )

    def test_update_inside_enclosing_jit(self):
        # a user jitting their whole eval step around the metric: tracer
        # args take the eager fold path so no tracer outlives its trace
        x, t = _batch(64)

        def step(xs, ts):
            m = MulticlassAccuracy(num_classes=4)
            m.update(xs, ts)
            self.assertEqual(m._pending, [])  # folded eagerly, not queued
            return m.compute()

        got = jax.jit(step)(jnp.asarray(x), jnp.asarray(t))
        self.assertAlmostEqual(
            float(got), float((x.argmax(1) == t).mean()), places=6
        )

    def test_pickle_mid_stream(self):
        m = MulticlassF1Score(num_classes=4, average="macro")
        x, t = _batch(48)
        m.update(jnp.asarray(x), jnp.asarray(t))
        self.assertTrue(m._pending)
        clone = pickle.loads(pickle.dumps(m))
        self.assertEqual(clone._pending, [])
        np.testing.assert_allclose(
            np.asarray(clone.compute()), np.asarray(m.compute()), rtol=1e-6
        )
        # the restored metric keeps streaming correctly
        x2, t2 = _batch(16)
        clone.update(jnp.asarray(x2), jnp.asarray(t2))
        ref = MulticlassF1Score(num_classes=4, average="macro")
        X, T = np.concatenate([x, x2]), np.concatenate([t, t2])
        ref.update(jnp.asarray(X), jnp.asarray(T))
        np.testing.assert_allclose(
            np.asarray(clone.compute()), np.asarray(ref.compute()), rtol=1e-6
        )

    def test_byte_budget_valve(self):
        m = MulticlassAccuracy(num_classes=4)
        x, t = _batch(256)
        per_update = x.nbytes + np.asarray(t).nbytes
        m._DEFER_BUDGET_BYTES = 3 * per_update  # force periodic folds
        for _ in range(10):
            m.update(jnp.asarray(x), jnp.asarray(t))
        self.assertLess(len(m._pending), 4)  # valve fired along the way
        self.assertAlmostEqual(
            float(m.compute()), float((x.argmax(1) == t).mean()), places=6
        )
        self.assertEqual(float(m.num_total), 2560.0)

    def test_load_state_dict_drops_pending(self):
        donor = MulticlassAccuracy(num_classes=4)
        x, t = _batch()
        donor.update(jnp.asarray(x), jnp.asarray(t))
        sd = donor.state_dict()
        m = MulticlassAccuracy(num_classes=4)
        m.update(jnp.asarray(x[:8]), jnp.asarray(t[:8]))  # pending to drop
        m.load_state_dict(sd)
        # loading replaces the logical state wholesale: the pre-load pending
        # batches belong to the replaced stream and must not leak in
        self.assertEqual(float(m.num_total), float(x.shape[0]))
        self.assertAlmostEqual(
            float(m.compute()), float((x.argmax(1) == t).mean()), places=6
        )

    def test_reset_discards_pending(self):
        m = MulticlassAccuracy(num_classes=4)
        x, t = _batch()
        m.update(jnp.asarray(x), jnp.asarray(t))
        m.reset()
        self.assertEqual(m._pending, [])
        x2, t2 = _batch(16)
        m.update(jnp.asarray(x2), jnp.asarray(t2))
        # read through state_dict: direct attribute reads see only the
        # folded-so-far value (documented deferral semantics)
        self.assertEqual(float(m.state_dict()["num_total"]), 16.0)


if __name__ == "__main__":
    unittest.main()

"""DeferredFoldMixin edge cases (metrics/deferred.py).

The hot-loop machinery behind every counter metric since round 3: update()
is an O(1) append, the math folds lazily. These tests pin the lifecycle
edges the collection tests don't reach: merges with pending batches on both
sides, signature-change flushes, the tracer fallback inside an enclosing
jit, pickling mid-stream, the byte-budget valve, and load_state_dict's
fold-before-overwrite contract (ISSUE 5: a mid-window restore must be
exact — stale pending chunks never fold into restored state, and partial
loads keep their contribution in untouched states).
"""

import pickle
import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    MulticlassAccuracy,
    MulticlassF1Score,
)

RNG = np.random.default_rng(42)


def _batch(n=32, c=4):
    return (
        RNG.random((n, c)).astype(np.float32),
        RNG.integers(0, c, n),
    )


class TestStandaloneGroupFold(unittest.TestCase):
    """Round-4 verdict ask 8: standalone deferred metrics fed the same
    placed batches (outside any collection) fold in ONE program, keyed on
    pending-chunk identity."""

    def _spy(self):
        # wraps every fold dispatcher — the scan-vs-concat choice is a
        # static argument inside these entry points, so the assertions pin
        # dispatch counts whatever physical fold shape the pending
        # signatures select
        import torcheval_tpu.metrics.deferred as dmod

        calls = {"single": 0, "group": 0}
        names = {
            "_fold_dispatch": "single",
            "_fold_dispatch_donated": "single",
            "_group_fold_dispatch": "group",
            "_group_fold_dispatch_donated": "group",
        }
        orig = {name: getattr(dmod, name) for name in names}

        def wrap(name, kind):
            real = orig[name]

            def f(*a, **k):
                calls[kind] += 1
                return real(*a, **k)

            return f

        for name, kind in names.items():
            setattr(dmod, name, wrap(name, kind))

        def restore():
            for k, v in orig.items():
                setattr(dmod, k, v)

        return calls, restore

    def test_same_batches_fold_in_one_program(self):
        x, t = _batch(64, 4)
        jx, jt = jnp.asarray(x), jnp.asarray(t)
        acc = MulticlassAccuracy(num_classes=4)
        f1 = MulticlassF1Score(num_classes=4, average="macro")
        for _ in range(3):
            acc.update(jx, jt)
            f1.update(jx, jt)
        self.assertTrue(acc._pending and f1._pending)
        calls, restore = self._spy()
        try:
            got_acc = float(acc.compute())  # folds BOTH metrics
            self.assertEqual(f1._pending, [])
            got_f1 = float(f1.compute())
        finally:
            restore()
        self.assertEqual(calls, {"single": 0, "group": 1})
        self.assertAlmostEqual(got_acc, float((x.argmax(1) == t).mean()), places=6)
        import sklearn.metrics as sk

        X3 = np.concatenate([x] * 3)
        T3 = np.concatenate([t] * 3)
        self.assertAlmostEqual(
            got_f1,
            float(sk.f1_score(T3, X3.argmax(1), average="macro")),
            places=5,
        )

    def test_valve_triggered_fold_groups_common_prefix(self):
        # mid-stream the triggering metric is one chunk ahead of its peer;
        # the valve must fold the shared prefix in one program and leave the
        # straggler chunk pending — never degrade to per-metric folds
        a = MulticlassAccuracy(num_classes=4)
        b = MulticlassF1Score(num_classes=4, average="macro")
        a._DEFER_MAX_CHUNKS = 4  # shrink the valve for the test
        b._DEFER_MAX_CHUNKS = 4
        batches = [_batch(16, 4) for _ in range(6)]
        calls, restore = self._spy()
        try:
            for x, t in batches:
                jx, jt = jnp.asarray(x), jnp.asarray(t)
                a.update(jx, jt)  # valve fires here at chunk 4, b holds 3
                b.update(jx, jt)
            got_a = float(a.compute())
            got_b = float(b.compute())
        finally:
            restore()
        self.assertEqual(calls["single"], 0)  # every fold was grouped
        self.assertGreaterEqual(calls["group"], 2)
        X = np.concatenate([x for x, _ in batches])
        T = np.concatenate([t for _, t in batches])
        self.assertAlmostEqual(got_a, float((X.argmax(1) == T).mean()), places=6)
        import sklearn.metrics as sk

        self.assertAlmostEqual(
            got_b,
            float(sk.f1_score(T, X.argmax(1), average="macro")),
            places=5,
        )

    def test_pickle_restored_metric_rejoins_grouping(self):
        m1 = MulticlassAccuracy(num_classes=4)
        m2 = pickle.loads(pickle.dumps(m1))
        m3 = MulticlassAccuracy(num_classes=4)
        x, t = _batch(32, 4)
        jx, jt = jnp.asarray(x), jnp.asarray(t)
        m2.update(jx, jt)
        m3.update(jx, jt)
        calls, restore = self._spy()
        try:
            m3.compute()
        finally:
            restore()
        self.assertEqual(calls, {"single": 0, "group": 1})
        self.assertEqual(m2._pending, [])  # restored metric was grouped

    def test_different_batches_do_not_group(self):
        xa, ta = _batch()
        xb, tb = _batch()
        a = MulticlassAccuracy(num_classes=4)
        b = MulticlassAccuracy(num_classes=4)
        a.update(jnp.asarray(xa), jnp.asarray(ta))
        b.update(jnp.asarray(xb), jnp.asarray(tb))
        calls, restore = self._spy()
        try:
            a.compute()
        finally:
            restore()
        self.assertEqual(calls["group"], 0)
        self.assertTrue(b._pending)  # untouched
        self.assertAlmostEqual(
            float(b.compute()), float((xb.argmax(1) == tb).mean()), places=6
        )


class TestDeferredEdges(unittest.TestCase):
    def test_merge_with_pending_on_both_sides(self):
        a, b = MulticlassAccuracy(num_classes=4), MulticlassAccuracy(num_classes=4)
        xa, ta = _batch()
        xb, tb = _batch()
        a.update(jnp.asarray(xa), jnp.asarray(ta))  # pending, unfolded
        b.update(jnp.asarray(xb), jnp.asarray(tb))  # pending, unfolded
        self.assertTrue(a._pending and b._pending)  # the scenario premise
        a.merge_state([b])
        X, T = np.concatenate([xa, xb]), np.concatenate([ta, tb])
        self.assertAlmostEqual(
            float(a.compute()), float((X.argmax(1) == T).mean()), places=6
        )
        # merge must not have mutated the source
        self.assertAlmostEqual(
            float(b.compute()), float((xb.argmax(1) == tb).mean()), places=6
        )

    def test_signature_change_flushes_pending(self):
        # (N,) 1-D input batches then an (N, C) 2-D batch: ranks differ, so
        # the mixin must flush the old signature before queueing the new one
        # (one concatenation never mixes ranks) and still count everything
        m = MulticlassAccuracy(num_classes=4)
        t1 = RNG.integers(0, 4, 16)
        m.update(jnp.asarray(t1.astype(np.float32)), jnp.asarray(t1))  # 1-D
        x2, t2 = _batch(24)
        m.update(jnp.asarray(x2), jnp.asarray(t2))  # 2-D: flush + append
        correct = 16 + int((x2.argmax(1) == t2).sum())
        self.assertAlmostEqual(float(m.compute()), correct / 40.0, places=6)

    def test_dtype_change_flushes_pending(self):
        m = BinaryAccuracy()
        x1 = RNG.random(16).astype(np.float32)
        t1 = RNG.integers(0, 2, 16).astype(np.float32)
        m.update(jnp.asarray(x1), jnp.asarray(t1))
        x2 = RNG.random(16).astype(np.float32)
        t2 = RNG.integers(0, 2, 16).astype(np.int32)  # target dtype changes
        m.update(jnp.asarray(x2), jnp.asarray(t2))
        X = np.concatenate([x1, x2])
        T = np.concatenate([t1, t2.astype(np.float32)])
        self.assertAlmostEqual(
            float(m.compute()), float(((X >= 0.5) == T).mean()), places=6
        )

    def test_update_inside_enclosing_jit(self):
        # a user jitting their whole eval step around the metric: tracer
        # args take the eager fold path so no tracer outlives its trace
        x, t = _batch(64)

        def step(xs, ts):
            m = MulticlassAccuracy(num_classes=4)
            m.update(xs, ts)
            self.assertEqual(m._pending, [])  # folded eagerly, not queued
            return m.compute()

        got = jax.jit(step)(jnp.asarray(x), jnp.asarray(t))
        self.assertAlmostEqual(
            float(got), float((x.argmax(1) == t).mean()), places=6
        )

    def test_pickle_mid_stream(self):
        m = MulticlassF1Score(num_classes=4, average="macro")
        x, t = _batch(48)
        m.update(jnp.asarray(x), jnp.asarray(t))
        self.assertTrue(m._pending)
        clone = pickle.loads(pickle.dumps(m))
        self.assertEqual(clone._pending, [])
        np.testing.assert_allclose(
            np.asarray(clone.compute()), np.asarray(m.compute()), rtol=1e-6
        )
        # the restored metric keeps streaming correctly
        x2, t2 = _batch(16)
        clone.update(jnp.asarray(x2), jnp.asarray(t2))
        ref = MulticlassF1Score(num_classes=4, average="macro")
        X, T = np.concatenate([x, x2]), np.concatenate([t, t2])
        ref.update(jnp.asarray(X), jnp.asarray(T))
        np.testing.assert_allclose(
            np.asarray(clone.compute()), np.asarray(ref.compute()), rtol=1e-6
        )

    def test_byte_budget_valve(self):
        m = MulticlassAccuracy(num_classes=4)
        x, t = _batch(256)
        per_update = x.nbytes + np.asarray(t).nbytes
        m._DEFER_BUDGET_BYTES = 3 * per_update  # force periodic folds
        for _ in range(10):
            m.update(jnp.asarray(x), jnp.asarray(t))
        self.assertLess(len(m._pending), 4)  # valve fired along the way
        self.assertAlmostEqual(
            float(m.compute()), float((x.argmax(1) == t).mean()), places=6
        )
        self.assertEqual(float(m.num_total), 2560.0)

    def test_load_state_dict_mid_window_restore_is_exact(self):
        # ISSUE 5 satellite: pending chunks queued against the OLD state
        # fold into it BEFORE the overwrite — they must never fold into the
        # restored state on the next read (the checkpoint-restore shape:
        # post-checkpoint batches are discarded with the stream they
        # belong to)
        donor = MulticlassAccuracy(num_classes=4)
        x, t = _batch()
        donor.update(jnp.asarray(x), jnp.asarray(t))
        sd = donor.state_dict()
        m = MulticlassAccuracy(num_classes=4)
        m.update(jnp.asarray(x[:8]), jnp.asarray(t[:8]))  # mid-window
        self.assertTrue(m._pending)
        m.load_state_dict(sd)
        self.assertEqual(m._pending, [])
        self.assertEqual(float(m.num_total), float(x.shape[0]))
        self.assertAlmostEqual(
            float(m.compute()), float((x.argmax(1) == t).mean()), places=6
        )

    def test_partial_load_keeps_pending_contribution_in_untouched_states(self):
        # strict=False naming only num_correct: the pending batch's
        # contribution to num_total must survive (the old drop-pending
        # behavior silently lost it)
        m = MulticlassAccuracy(num_classes=4)
        x, t = _batch(24)
        m.update(jnp.asarray(x), jnp.asarray(t))  # pending, unfolded
        m.load_state_dict({"num_correct": jnp.zeros(())}, strict=False)
        self.assertEqual(float(m.state_dict()["num_total"]), 24.0)
        self.assertEqual(float(m.state_dict()["num_correct"]), 0.0)

    def test_reset_discards_pending(self):
        # ISSUE 5 satellite audit: a reset mid-window must drop the whole
        # pending machinery (_pending / _pending_bytes / _pending_sig) so
        # no pre-reset chunk can leak into the next fold
        m = MulticlassAccuracy(num_classes=4)
        x, t = _batch()
        m.update(jnp.asarray(x), jnp.asarray(t))
        self.assertTrue(m._pending)
        self.assertGreater(m._pending_bytes, 0)
        self.assertIsNotNone(m._pending_sig)
        m.reset()
        self.assertEqual(m._pending, [])
        self.assertEqual(m._pending_bytes, 0)
        self.assertIsNone(m._pending_sig)
        x2, t2 = _batch(16)
        m.update(jnp.asarray(x2), jnp.asarray(t2))
        # read through state_dict: direct attribute reads see only the
        # folded-so-far value (documented deferral semantics)
        self.assertEqual(float(m.state_dict()["num_total"]), 16.0)
        self.assertAlmostEqual(
            float(m.compute()), float((x2.argmax(1) == t2).mean()), places=6
        )


class TestDeferValves(unittest.TestCase):
    """ISSUE 2 satellite: the two deferral valves' exact mechanics — the
    2x-scale hard valve on a collection-managed member streamed into
    directly, and the flush-before-append ordering on a signature change."""

    def test_managed_member_direct_stream_valve_fires_at_exactly_2x(self):
        from torcheval_tpu.metrics import MetricCollection

        m = MulticlassAccuracy(num_classes=4)
        MetricCollection(m)  # marks managed: collection owns the trigger
        m._DEFER_MAX_CHUNKS = 3
        x, t = _batch(8, 4)
        jx, jt = jnp.asarray(x), jnp.asarray(t)
        # updates 1..5 stay pending: the managed scale doubles the chunk cap
        # (2 * 3 = 6), so the 1x cap passing at chunk 3 must NOT fold
        for i in range(1, 6):
            m.update(jx, jt)
            self.assertEqual(len(m._pending), i)
        # chunk 6 reaches the 2x hard valve: everything folds
        m.update(jx, jt)
        self.assertEqual(m._pending, [])
        self.assertEqual(float(m.num_total), 48.0)  # folded, not dropped
        self.assertAlmostEqual(
            float(m.compute()), float((x.argmax(1) == t).mean()), places=6
        )

    def test_mixed_signature_flush_folds_old_before_append(self):
        # an (N, C) chunk arriving after (N,) chunks must fold the old
        # signature FIRST, then append — the pending list never holds two
        # signatures (one fold never mixes ranks)
        m = MulticlassAccuracy(num_classes=4)
        t1 = RNG.integers(0, 4, 16)
        j1 = jnp.asarray(t1.astype(np.float32))
        m.update(j1, jnp.asarray(t1))  # 1-D input chunks
        m.update(j1, jnp.asarray(t1))
        self.assertEqual(len(m._pending), 2)
        x2, t2 = _batch(24)
        m.update(jnp.asarray(x2), jnp.asarray(t2))  # 2-D: flush + append
        # pending holds ONLY the new-signature chunk...
        self.assertEqual(len(m._pending), 1)
        self.assertEqual(m._pending[0][0].ndim, 2)
        # ...and the old chunks are already in the folded state (direct
        # attribute read = folded-so-far value)
        self.assertEqual(float(m.num_total), 32.0)
        correct = 32 + int((x2.argmax(1) == t2).sum())
        self.assertAlmostEqual(float(m.compute()), correct / 56.0, places=6)


class TestStackedScanFold(unittest.TestCase):
    """The stacked/scan fold path (uniform pending signatures) must agree
    with the concat/per-chunk fallback (ragged signatures) bit-for-bit."""

    def test_uniform_vs_ragged_chunks_agree(self):
        uniform = MulticlassAccuracy(num_classes=4)
        ragged = MulticlassAccuracy(num_classes=4)
        x, t = _batch(60, 4)
        jx, jt = jnp.asarray(x), jnp.asarray(t)
        for i in range(4):  # four (15, 4) chunks: stacked scan path
            uniform.update(jx[i * 15 : (i + 1) * 15], jt[i * 15 : (i + 1) * 15])
        ragged.update(jx[:20], jt[:20])  # (20,) then (40,): concat fallback
        ragged.update(jx[20:], jt[20:])
        self.assertAlmostEqual(
            float(uniform.compute()), float(ragged.compute()), places=7
        )
        self.assertAlmostEqual(
            float(uniform.compute()), float((x.argmax(1) == t).mean()), places=6
        )

    def test_extrema_state_threads_through_scan(self):
        from torcheval_tpu.metrics import Max, Min

        rows = RNG.random((5, 32)).astype(np.float32)
        mx, mn = Max(), Min()
        for row in rows:  # five same-shape chunks: scan carry threads state
            mx.update(jnp.asarray(row))
            mn.update(jnp.asarray(row))
        self.assertEqual(float(mx.compute()), float(rows.max()))
        self.assertEqual(float(mn.compute()), float(rows.min()))
        # keep streaming after the fold: the reduce keeps threading
        mx.update(jnp.asarray(rows[0] + 10.0))
        self.assertEqual(float(mx.compute()), float((rows[0] + 10.0).max()))

    def test_int_counter_meets_float_delta_in_scan(self):
        # MSE's sum_weight starts int32 and promotes to float32 on the first
        # weighted fold; the scan carry must stay dtype-stable (first chunk
        # folds outside the scan to settle promotion)
        from torcheval_tpu.metrics import MeanSquaredError

        m = MeanSquaredError()
        x = RNG.random(16).astype(np.float32)
        t = RNG.random(16).astype(np.float32)
        w = RNG.random(16).astype(np.float32)
        for _ in range(3):
            m.update(jnp.asarray(x), jnp.asarray(t), sample_weight=jnp.asarray(w))
        expected = (np.square(t - x) * w).sum() * 3 / (w.sum() * 3)
        self.assertAlmostEqual(float(m.compute()), float(expected), places=5)


if __name__ == "__main__":
    unittest.main()

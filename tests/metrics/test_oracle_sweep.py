"""Randomized oracle sweep: many (seed, shape, option) permutations per metric
family checked against scikit-learn / numpy oracles in one parametrized pass.

The reference reaches its test breadth through many hand-written spec cases
per metric (e.g. ``tests/metrics/classification/test_accuracy.py:25-61`` and
siblings, ~7k test LoC). This sweep gets equivalent input-space coverage by
drawing structured random cases — including degenerate ones (single class
present, empty positives, constant scores) — and asserting exact agreement
with the independent oracle on every draw.

Each case also checks the streaming invariant the class API is built on:
feeding the same samples in two chunks and merging must equal one shot.
"""

import unittest

import jax.numpy as jnp
import numpy as np
import sklearn.metrics as sk

from torcheval_tpu.metrics import (
    BinaryAUROC,
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)
from torcheval_tpu.metrics import functional as F
from torcheval_tpu.utils.test_utils import assert_result_close

SEEDS = range(6)


def _case(seed, n_min=8, n_max=400, c_min=2, c_max=11):
    """A structured random multiclass case; some draws are degenerate."""
    rng = np.random.default_rng(seed * 7919 + 13)
    n = int(rng.integers(n_min, n_max))
    c = int(rng.integers(c_min, c_max))
    target = rng.integers(0, c, n)
    if seed % 3 == 2:  # degenerate: only one true class present
        target[:] = target[0]
    scores = rng.normal(size=(n, c)).astype(np.float32)
    if seed % 3 == 1:  # ties everywhere: constant scores
        scores[:] = 0.25
    return n, c, scores, target


class TestCounterFamilySweep(unittest.TestCase):
    def test_precision_recall_f1_all_averages(self):
        for seed in SEEDS:
            n, c, scores, target = _case(seed)
            pred = scores.argmax(1)
            js, jt = jnp.asarray(scores), jnp.asarray(target)
            for average in ("micro", "macro", "weighted", None):
                kw = dict(average=average, num_classes=c)
                sk_kw = dict(
                    average=average, labels=np.arange(c), zero_division=0
                )
                for ours, oracle in (
                    (F.multiclass_precision, sk.precision_score),
                    (F.multiclass_recall, sk.recall_score),
                    (F.multiclass_f1_score, sk.f1_score),
                ):
                    got = np.asarray(ours(js, jt, **kw))
                    want = oracle(target, pred, **sk_kw)
                    # our kernels emit NaN for undefined per-class values
                    # where sklearn's zero_division=0 emits 0
                    got = np.nan_to_num(got, nan=0.0)
                    np.testing.assert_allclose(
                        got, want, rtol=1e-5, atol=1e-6,
                        err_msg=f"seed={seed} avg={average} fn={ours.__name__}",
                    )

    def test_accuracy_micro_matches_sklearn(self):
        for seed in SEEDS:
            n, c, scores, target = _case(seed)
            got = F.multiclass_accuracy(jnp.asarray(scores), jnp.asarray(target))
            assert_result_close(got, sk.accuracy_score(target, scores.argmax(1)))

    def test_confusion_matrix_matches_sklearn(self):
        for seed in SEEDS:
            n, c, scores, target = _case(seed)
            pred = scores.argmax(1)
            got = np.asarray(
                F.multiclass_confusion_matrix(
                    jnp.asarray(pred), jnp.asarray(target), num_classes=c
                )
            )
            want = sk.confusion_matrix(target, pred, labels=np.arange(c))
            np.testing.assert_array_equal(got, want, err_msg=f"seed={seed}")


class TestCurveFamilySweep(unittest.TestCase):
    def test_auroc_matches_sklearn(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed * 104729 + 7)
            n = int(rng.integers(16, 600))
            scores = rng.random(n).astype(np.float32)
            if seed % 3 == 1:
                scores = np.round(scores, 1)  # heavy ties
            target = (rng.random(n) < 0.4).astype(np.float32)
            if target.sum() in (0, n):
                target[0] = 1.0 - target[0]  # keep both classes present
            got = F.binary_auroc(jnp.asarray(scores), jnp.asarray(target))
            want = sk.roc_auc_score(target, scores)
            assert_result_close(got, want)

    def test_binned_prc_matches_direct_counts(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed + 31)
            n, t_count = int(rng.integers(20, 300)), int(rng.integers(3, 40))
            scores = rng.random(n).astype(np.float32)
            target = (rng.random(n) < 0.5).astype(np.int32)
            thresholds = np.sort(rng.random(t_count)).astype(np.float32)
            prec, rec, thr = F.binary_binned_precision_recall_curve(
                jnp.asarray(scores), jnp.asarray(target),
                threshold=jnp.asarray(thresholds),
            )
            # direct numpy oracle
            want_p, want_r = [], []
            for th in thresholds:
                pred = scores >= th
                tp = int((pred & (target == 1)).sum())
                fp = int((pred & (target == 0)).sum())
                fn = int(((~pred) & (target == 1)).sum())
                want_p.append(tp / (tp + fp) if tp + fp else 1.0)
                want_r.append(tp / (tp + fn) if tp + fn else np.nan)
            np.testing.assert_allclose(
                np.asarray(prec)[:-1], want_p, rtol=1e-6, err_msg=f"seed={seed}"
            )
            np.testing.assert_allclose(
                np.asarray(rec)[:-1], want_r, rtol=1e-6, err_msg=f"seed={seed}"
            )


class TestRegressionSweep(unittest.TestCase):
    def test_mse_multioutput_and_weights(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed + 47)
            n, d = int(rng.integers(8, 200)), int(rng.integers(1, 5))
            inp = rng.normal(size=(n, d)).astype(np.float32)
            tgt = rng.normal(size=(n, d)).astype(np.float32)
            w = rng.random(n).astype(np.float32) + 0.1
            for multioutput in ("uniform_average", "raw_values"):
                got = F.mean_squared_error(
                    jnp.asarray(inp), jnp.asarray(tgt),
                    sample_weight=jnp.asarray(w), multioutput=multioutput,
                )
                want = sk.mean_squared_error(
                    tgt, inp, sample_weight=w, multioutput=multioutput
                )
                assert_result_close(got, want)

    def test_r2_variants(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed + 91)
            n, d = int(rng.integers(12, 200)), int(rng.integers(1, 4))
            tgt = rng.normal(size=(n, d)).astype(np.float32)
            inp = (tgt + 0.3 * rng.normal(size=(n, d))).astype(np.float32)
            for multioutput in ("uniform_average", "raw_values", "variance_weighted"):
                got = F.r2_score(
                    jnp.asarray(inp), jnp.asarray(tgt), multioutput=multioutput
                )
                want = sk.r2_score(tgt, inp, multioutput=multioutput)
                assert_result_close(got, want)


class TestRankingSweep(unittest.TestCase):
    def test_hit_rate_vs_loop_oracle(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed + 3)
            n, c = int(rng.integers(4, 60)), int(rng.integers(3, 12))
            scores = rng.normal(size=(n, c)).astype(np.float32)
            target = rng.integers(0, c, n)
            for k in (1, 2, c // 2 + 1, None):
                got = np.asarray(
                    F.hit_rate(
                        jnp.asarray(scores), jnp.asarray(target), k=k
                    )
                )
                want = []
                for i in range(n):
                    # rank = #scores strictly above the target's (reference
                    # semantics; ties all share the best rank of the group)
                    rank = int((scores[i] > scores[i, target[i]]).sum())
                    kk = c if k is None else k
                    want.append(1.0 if rank < kk else 0.0)
                np.testing.assert_allclose(
                    got, want, err_msg=f"seed={seed} k={k}"
                )

    def test_reciprocal_rank_vs_loop_oracle(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed + 5)
            n, c = int(rng.integers(4, 60)), int(rng.integers(3, 12))
            scores = rng.normal(size=(n, c)).astype(np.float32)
            target = rng.integers(0, c, n)
            got = np.asarray(
                F.reciprocal_rank(jnp.asarray(scores), jnp.asarray(target))
            )
            want = []
            for i in range(n):
                rank = int((scores[i] > scores[i, target[i]]).sum())
                want.append(1.0 / (rank + 1))
            np.testing.assert_allclose(
                got, want, rtol=1e-6, err_msg=f"seed={seed}"
            )


class TestNormalizedEntropySweep(unittest.TestCase):
    def test_from_logits_and_probabilities_agree_with_hand_oracle(self):
        def _oracle(probs, target, weight):
            eps = 1e-12
            ce = -(
                weight * (target * np.log(np.clip(probs, eps, None))
                          + (1 - target) * np.log(np.clip(1 - probs, eps, None)))
            ).sum() / weight.sum()
            base_rate = (weight * target).sum() / weight.sum()
            baseline = -(
                base_rate * np.log(base_rate)
                + (1 - base_rate) * np.log(1 - base_rate)
            )
            return ce / baseline

        for seed in SEEDS:
            rng = np.random.default_rng(seed + 17)
            n = int(rng.integers(16, 200))
            logits = rng.normal(size=n).astype(np.float64)
            probs = 1.0 / (1.0 + np.exp(-logits))
            target = (rng.random(n) < 0.35).astype(np.float64)
            if target.sum() in (0, n):
                target[0] = 1.0 - target[0]
            weight = (rng.random(n) + 0.1).astype(np.float64)
            want = _oracle(probs, target, weight)
            got_p = F.binary_normalized_entropy(
                jnp.asarray(probs), jnp.asarray(target),
                weight=jnp.asarray(weight),
            )
            got_l = F.binary_normalized_entropy(
                jnp.asarray(logits), jnp.asarray(target),
                weight=jnp.asarray(weight), from_logits=True,
            )
            assert_result_close(got_p, want)
            assert_result_close(got_l, want)


class TestStreamingEquivalenceSweep(unittest.TestCase):
    """chunked update + merge == one-shot, across random splits and options."""

    def test_counter_metrics(self):
        for seed in SEEDS:
            n, c, scores, target = _case(seed, n_min=24)
            split = int(np.random.default_rng(seed).integers(4, n - 4))
            for make in (
                lambda: MulticlassAccuracy(num_classes=c, average="macro"),
                lambda: MulticlassF1Score(num_classes=c, average="macro"),
                lambda: MulticlassPrecision(num_classes=c, average=None),
                lambda: MulticlassRecall(num_classes=c, average="weighted"),
            ):
                one = make()
                one.update(jnp.asarray(scores), jnp.asarray(target))
                a, b = make(), make()
                a.update(jnp.asarray(scores[:split]), jnp.asarray(target[:split]))
                b.update(jnp.asarray(scores[split:]), jnp.asarray(target[split:]))
                a.merge_state([b])
                assert_result_close(a.compute(), one.compute())

    def test_auroc_with_empty_chunk(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            n = int(rng.integers(16, 300))
            scores = rng.random(n).astype(np.float32)
            target = (rng.random(n) < 0.5).astype(np.float32)
            if target.sum() in (0, n):
                target[0] = 1.0 - target[0]
            one = BinaryAUROC()
            one.update(jnp.asarray(scores), jnp.asarray(target))
            a, b = BinaryAUROC(), BinaryAUROC()
            a.update(jnp.asarray(scores), jnp.asarray(target))
            a.merge_state([b])  # b never updated: empty CAT state merges clean
            assert_result_close(a.compute(), one.compute())


if __name__ == "__main__":
    unittest.main()

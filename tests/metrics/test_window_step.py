"""Whole-window compiled eval step (ISSUE 6): donation end-to-end, the
collection's host-accumulator update lanes, and mid-window read paths.

The tentpole contract: ``MetricCollection.update()`` appends each placed
batch ONCE to a shared :class:`~torcheval_tpu.metrics.deferred.EvalWindow`
(zero per-batch device dispatch for deferred members) and the window closes
as ONE donated pjit program containing the per-batch update math, the fold,
and — at ``compute()`` time — the terminal computes. These tests pin

* donation end-to-end on a ``donation_pipelines()`` backend (CPU in this
  suite): the window step really invalidates the donated state buffers, the
  chunk stack is donated exactly when every chunk is library-owned,
  ``state.py``'s copy-on-read template guard still holds, and every donated
  dispatch pins its input refs until the program retires (dropping a donated
  input's wrapper mid-flight blocks the host on the execution);
* mid-window ``resilience.snapshot.save`` round-trips bit-identical
  (pending window chunks fold before serialization);
* every slow-path lane (kwargs, scalar args, signature changes, direct
  member streaming, member-level reads/resets) agrees with standalone
  metrics.
"""

import shutil
import tempfile
import unittest
import warnings

import jax
import jax.numpy as jnp
import numpy as np

import torcheval_tpu.metrics.deferred as dmod
from torcheval_tpu.metrics import (
    BinaryAUROC,
    Mean,
    MeanSquaredError,
    MetricCollection,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.metrics.state import zeros_state
from torcheval_tpu.utils.platform import donation_pipelines

RNG = np.random.default_rng(11)


def _batch(n=32, c=4):
    return (
        RNG.random((n, c)).astype(np.float32),
        RNG.integers(0, c, n),
    )


def _spy_window_dispatchers():
    """Wrap the three window-step dispatchers, recording which fired."""
    calls = {"plain": 0, "donated": 0, "donated_all": 0}
    names = {
        "_window_step_dispatch": "plain",
        "_window_step_dispatch_donated": "donated",
        "_window_step_dispatch_donated_all": "donated_all",
    }
    orig = {name: getattr(dmod, name) for name in names}

    def wrap(name, kind):
        real = orig[name]

        def f(*a, **k):
            calls[kind] += 1
            return real(*a, **k)

        return f

    for name, kind in names.items():
        setattr(dmod, name, wrap(name, kind))

    def restore():
        for k, v in orig.items():
            setattr(dmod, k, v)

    return calls, restore


@unittest.skipUnless(
    donation_pipelines(), "donation is gated off on this backend"
)
class TestWindowDonation(unittest.TestCase):
    def test_donated_state_buffers_are_invalidated(self):
        # the window step donates the full state tree: a raw reference
        # captured from a state attribute before the fold must be DEAD
        # afterwards (the documented donation caveat, now at window
        # granularity), while reads through the metric stay exact
        m = MulticlassAccuracy(num_classes=4)
        col = MetricCollection(m)
        x, t = _batch()
        col.update(x, t)
        stale = m.num_total  # pre-fold buffer (int32 -> aliasable in place)
        out = float(col.compute())
        self.assertAlmostEqual(out, float((x.argmax(1) == t).mean()), places=6)
        with self.assertRaises(RuntimeError):
            _ = stale + 1  # donated buffer: deleted by the window step

    def test_chunk_stack_donated_only_when_library_owned(self):
        # numpy batches: the collection's placement creates the device
        # buffers, so the window owns them and the donate-everything
        # dispatcher runs. jax.Array batches: the caller still holds the
        # buffers — state-only donation.
        x, t = _batch()
        calls, restore = _spy_window_dispatchers()
        try:
            col = MetricCollection(MulticlassAccuracy(num_classes=4))
            self.assertTrue(col._window.owned)
            for _ in range(3):
                col.update(x, t)  # numpy in: placement copies
            self.assertTrue(col._window.owned)
            with warnings.catch_warnings():
                # the suppression contract: unusable chunk donations must
                # not leak a UserWarning per window to the caller
                warnings.simplefilter("error")
                got = float(col.compute())
            self.assertEqual(calls["donated_all"], 1)
            self.assertEqual(calls["plain"], 0)

            jx, jt = jnp.asarray(x), jnp.asarray(t)
            col2 = MetricCollection(MulticlassAccuracy(num_classes=4))
            for _ in range(3):
                col2.update(jx, jt)  # caller-held jax buffers
            self.assertFalse(col2._window.owned)
            got2 = float(col2.compute())
            self.assertEqual(calls["donated_all"], 1)  # unchanged
            self.assertGreaterEqual(calls["donated"], 1)
        finally:
            restore()
        self.assertAlmostEqual(got, float((x.argmax(1) == t).mean()), places=6)
        self.assertAlmostEqual(got2, got, places=7)
        # ...and the caller's arrays are still alive after the fold
        self.assertEqual(int(jt.sum()), int(t.sum()))

    def test_mixed_eager_member_blocks_chunk_donation(self):
        # an eager member (sample cache) may retain the placed chunk
        # buffers — the window must never claim ownership
        from torcheval_tpu.metrics import BinaryAccuracy

        col = MetricCollection(
            {"bacc": BinaryAccuracy(), "auroc": BinaryAUROC()}
        )
        x = RNG.random(64).astype(np.float32)
        t = RNG.integers(0, 2, 64).astype(np.float32)
        col.update(x, t)
        self.assertFalse(col._window.owned)
        out = col.compute()
        self.assertAlmostEqual(
            float(out["bacc"]), float(((x >= 0.5) == t).mean()), places=6
        )

    def test_copy_on_read_template_guard_still_holds(self):
        # state.py: with donation on, zeros_state must hand out FRESH
        # buffers (a shared template would be invalidated by a donated
        # window step) and state_dict snapshots must be real copies
        self.assertIsNot(zeros_state((), dtype=jnp.int32), zeros_state((), dtype=jnp.int32))
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        x, t = _batch()
        col.update(x, t)
        sd = col.state_dicts()["metric"]  # folds, then copies
        col.update(x, t)
        col.compute()  # donated window step invalidates the live buffers
        self.assertEqual(float(sd["num_total"]), float(x.shape[0]))
        # a sibling fresh metric's default states were never aliased to the
        # donated ones
        fresh = MulticlassAccuracy(num_classes=4)
        self.assertEqual(float(fresh.num_total), 0.0)

    def test_donated_inputs_pinned_until_program_retires(self):
        # deleting a donated input's python wrapper while its program is
        # still executing blocks the host on the execution (measured
        # 40-90 ms per window on XLA:CPU — the async-dispatch win of the
        # one-program window gone), so every donated dispatch must park
        # its input refs in the in-flight registry until the program's
        # outputs are ready, and the next dispatch must sweep retired holds
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        m = col.metrics["metric"]
        x, t = _batch()
        for _ in range(3):
            col.update(x, t)
        donated = [getattr(m, n) for n in m._state_name_to_default]
        col.compute()
        held_ids = {
            id(leaf)
            for _, refs in dmod._inflight_donated
            for leaf in jax.tree_util.tree_leaves(refs)
        }
        for arr in donated:
            self.assertIn(id(arr), held_ids)  # pinned while in flight
        # the program's outputs ARE the metric's new states: once they are
        # ready the program has retired, and the next donated dispatch
        # sweeps the hold
        jax.block_until_ready([getattr(m, n) for n in m._state_name_to_default])
        col2 = MetricCollection(MulticlassAccuracy(num_classes=4))
        col2.update(x, t)
        col2.compute()
        held_ids = {
            id(leaf)
            for _, refs in dmod._inflight_donated
            for leaf in jax.tree_util.tree_leaves(refs)
        }
        for arr in donated:
            self.assertNotIn(id(arr), held_ids)  # retired hold swept

    def test_orphaned_holds_reanchor_instead_of_dropping(self):
        # an in-flight hold whose anchor probe raises was donated to a
        # LATER dispatch — the program may still be executing, so the hold
        # must re-anchor on the new dispatch's output (same-device programs
        # retire in submission order), never drop mid-flight
        class DeletedAnchor:
            def is_ready(self):
                raise RuntimeError("Array has been deleted")

        sentinel = object()
        saved = list(dmod._inflight_donated)
        try:
            dmod._inflight_donated[:] = [(DeletedAnchor(), (sentinel,))]
            dmod._hold_donated_inputs(jnp.zeros(1), {"s": jnp.ones(1)})
            held = [
                leaf
                for _, refs in dmod._inflight_donated
                for leaf in jax.tree_util.tree_leaves(
                    refs, is_leaf=lambda x: x is sentinel
                )
            ]
            self.assertTrue(any(leaf is sentinel for leaf in held))
            # the pre-dispatch sweep KEEPS raised-probe holds (it cannot
            # prove retirement) and drops ready ones
            dmod._inflight_donated[:] = [(DeletedAnchor(), (sentinel,))]
            dmod._sweep_retired_holds()
            self.assertEqual(len(dmod._inflight_donated), 1)
            ready = jax.block_until_ready(jnp.zeros(1))
            dmod._inflight_donated[:] = [(ready, (sentinel,))]
            dmod._sweep_retired_holds()
            self.assertEqual(dmod._inflight_donated, [])
        finally:
            dmod._inflight_donated[:] = saved

    def test_mid_window_snapshot_save_roundtrips_bit_identical(self):
        from torcheval_tpu.resilience import restore as ckpt_restore
        from torcheval_tpu.resilience import save as ckpt_save

        m = MulticlassAccuracy(num_classes=4)
        col = MetricCollection(m)
        x, t = _batch(48)
        col.update(x, t)
        col.update(x, t)
        self.assertTrue(col._window.chunks)  # mid-window: open chunks
        ckpt_dir = tempfile.mkdtemp(prefix="window_ckpt_")
        try:
            path = ckpt_save(m, ckpt_dir)
            self.assertEqual(col._window.chunks, [])  # folded before serialize
            fresh = MulticlassAccuracy(num_classes=4)
            ckpt_restore(fresh, path)
            for name in ("num_correct", "num_total"):
                self.assertTrue(
                    (
                        np.asarray(getattr(fresh, name))
                        == np.asarray(m.state_dict()[name])
                    ).all()
                )
            self.assertEqual(float(fresh.compute()), float(m.compute()))
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)


class TestWindowLanes(unittest.TestCase):
    """The host-accumulator update lanes must all agree with standalone
    metrics bit-for-bit."""

    def test_member_state_dict_mid_window_drains_the_window(self):
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=4),
                "f1": MulticlassF1Score(num_classes=4, average="macro"),
            }
        )
        x, t = _batch()
        col.update(x, t)
        self.assertTrue(col._window.chunks)
        sd = col["acc"].state_dict()  # single-member read, shared window
        self.assertEqual(float(sd["num_total"]), float(x.shape[0]))
        self.assertEqual(col._window.chunks, [])
        # the sibling's contribution survived the drain
        out = col.compute()
        import sklearn.metrics as sk

        self.assertAlmostEqual(
            float(out["f1"]),
            float(sk.f1_score(t, x.argmax(1), average="macro")),
            places=5,
        )

    def test_member_compute_mid_window_rides_the_window_close(self):
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=4),
                "cm": MulticlassConfusionMatrix(4),
            }
        )
        x, t = _batch()
        col.update(x, t)
        got = float(col["acc"].compute())  # direct member read mid-window
        self.assertAlmostEqual(got, float((x.argmax(1) == t).mean()), places=6)
        self.assertEqual(int(np.asarray(col["cm"].compute()).sum()), x.shape[0])

    def test_kwargs_lane_matches_standalone(self):
        col = MetricCollection(MeanSquaredError())
        ref = MeanSquaredError()
        for _ in range(3):
            x = RNG.random(32).astype(np.float32)
            t = RNG.random(32).astype(np.float32)
            w = RNG.random(32).astype(np.float32)
            col.update(x, t, sample_weight=w)
            ref.update(x, t, sample_weight=w)
        self.assertAlmostEqual(
            float(col.compute()), float(ref.compute()), places=6
        )

    def test_signature_change_mid_stream_through_collection(self):
        # 1-D label-style batches then a 2-D score batch: the window must
        # flush the old signature before accepting the new one
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        t1 = RNG.integers(0, 4, 16)
        col.update(t1.astype(np.float32), t1)
        col.update(t1.astype(np.float32), t1)
        x2, t2 = _batch(24)
        col.update(x2, t2)
        correct = 32 + int((x2.argmax(1) == t2).sum())
        self.assertAlmostEqual(float(col.compute()), correct / 56.0, places=6)

    def test_ragged_batch_sizes_share_one_window(self):
        # a batch-size change is NOT a signature flush (ragged leading dims
        # coexist; the in-trace uniformity gate picks the per-chunk path)
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        x, t = _batch(60)
        col.update(x[:20], t[:20])
        col.update(x[20:], t[20:])
        self.assertAlmostEqual(
            float(col.compute()), float((x.argmax(1) == t).mean()), places=6
        )

    def test_direct_member_stream_interleaved_with_window(self):
        # a member updated OUTSIDE the collection mid-window: its own
        # pending folds solo at close, the shared window folds for everyone
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=4),
                "cm": MulticlassConfusionMatrix(4),
            }
        )
        x, t = _batch(32)
        ex, et = _batch(16)
        col.update(x, t)
        col["acc"].update(ex, et)  # direct, acc only
        col.update(x, t)
        out = col.compute()
        X, T = np.concatenate([x, ex, x]), np.concatenate([t, et, t])
        self.assertAlmostEqual(
            float(out["acc"]), float((X.argmax(1) == T).mean()), places=6
        )
        self.assertEqual(int(np.asarray(out["cm"]).sum()), 64)

    def test_member_reset_mid_window_keeps_sibling_contributions(self):
        col = MetricCollection(
            {
                "a": MulticlassAccuracy(num_classes=4),
                "b": MulticlassAccuracy(num_classes=4),
            }
        )
        x, t = _batch(20)
        col.update(x, t)
        col["a"].reset()  # folds the shared window for b, then wipes a
        x2, t2 = _batch(12)
        col.update(x2, t2)
        out = col.compute()
        self.assertAlmostEqual(
            float(out["a"]), float((x2.argmax(1) == t2).mean()), places=6
        )
        X, T = np.concatenate([x, x2]), np.concatenate([t, t2])
        self.assertAlmostEqual(
            float(out["b"]), float((X.argmax(1) == T).mean()), places=6
        )

    def test_metric_in_two_collections_drains_both_windows(self):
        # a metric wrapped by several collections belongs to EVERY window:
        # direct reads must drain them all (a single-slot back-reference
        # would silently orphan the first collection's open chunks)
        m = MulticlassAccuracy(num_classes=4)
        col1 = MetricCollection({"acc": m})
        col2 = MetricCollection({"acc": m})
        x, t = _batch(32)
        col1.update(x, t)  # sits in col1's window
        self.assertEqual(float(m.state_dict()["num_total"]), 32.0)
        x2, t2 = _batch(16)
        col2.update(x2, t2)
        got = float(m.compute())  # closes col2's window with the compute
        X, T = np.concatenate([x, x2]), np.concatenate([t, t2])
        self.assertAlmostEqual(got, float((X.argmax(1) == T).mean()), places=6)
        # a COLLECTION-level compute must also see the other collection's
        # open chunks: the terminal compute drains them before running
        x3, t3 = _batch(8)
        col1.update(x3, t3)
        out = col2.compute()
        X, T = np.concatenate([X, x3]), np.concatenate([T, t3])
        self.assertAlmostEqual(
            float(out["acc"]), float((X.argmax(1) == T).mean()), places=6
        )

    def test_dead_collection_windows_are_pruned_not_leaked(self):
        import gc

        m = MulticlassAccuracy(num_classes=4)
        x, t = _batch(16)
        for _ in range(5):  # re-wrap per "epoch", leave a window open
            col = MetricCollection({"acc": m})
            col.update(x, t)
            del col
        gc.collect()
        # the dead collections' orphaned chunks still count (they were fed
        # by the user), and the dead windows are pruned at the next read
        self.assertEqual(float(m.state_dict()["num_total"]), 80.0)
        self.assertEqual(len(m._defer_windows), 0)

    def test_collection_reset_drops_the_window(self):
        col = MetricCollection(Mean())
        col.update(np.arange(8.0, dtype=np.float32))
        col.reset()
        self.assertEqual(col._window.chunks, [])
        col.update(np.full(4, 2.0, dtype=np.float32))
        self.assertEqual(float(col.compute()), 2.0)

    def test_repeated_compute_is_stable(self):
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=4),
                "cm": MulticlassConfusionMatrix(4),
            }
        )
        x, t = _batch()
        col.update(x, t)
        first = col.compute()
        second = col.compute()  # chunk-less terminal-compute step
        self.assertEqual(float(first["acc"]), float(second["acc"]))
        self.assertTrue(
            (np.asarray(first["cm"]) == np.asarray(second["cm"])).all()
        )

    def test_tracer_updates_fall_back_to_member_lane(self):
        # a user jitting their eval step around the collection: tracer args
        # must never sit in the window past their trace
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        x, t = _batch(16)

        @jax.jit
        def step(xs, ts):
            col.update(xs, ts)
            self.assertEqual(col._window.chunks, [])
            return col.compute()

        got = step(jnp.asarray(x), jnp.asarray(t))
        self.assertAlmostEqual(
            float(got), float((x.argmax(1) == t).mean()), places=6
        )

    def test_mixed_vmap_and_scan_members_share_one_window(self):
        # TopKMultilabelAccuracy's fold has no batching rule
        # (_fold_vmap=False) so it rides _stacked_fold's scan fallback while
        # the sibling folds vmapped — both lanes slice ONE in-program chunk
        # stack and must match standalone streams
        col = MetricCollection(
            {
                "topk": TopKMultilabelAccuracy(criteria="hamming", k=2),
                "ml": MultilabelAccuracy(criteria="hamming"),
            }
        )
        ref_topk = TopKMultilabelAccuracy(criteria="hamming", k=2)
        ref_ml = MultilabelAccuracy(criteria="hamming")
        for _ in range(4):
            x = RNG.random((16, 8)).astype(np.float32)
            t = (RNG.random((16, 8)) > 0.5).astype(np.float32)
            col.update(x, t)
            ref_topk.update(x, t)
            ref_ml.update(x, t)
        got = col.compute()
        self.assertAlmostEqual(
            float(got["topk"]), float(ref_topk.compute()), places=6
        )
        self.assertAlmostEqual(
            float(got["ml"]), float(ref_ml.compute()), places=6
        )

    def test_subclassed_update_override_runs_every_batch(self):
        # the window fast path replays only the library's own _defer append,
        # so a member whose update() is overridden outside the library must
        # keep the per-member lane — its per-batch side effects (counters,
        # logging, extra validation) run for EVERY batch, not just the first
        class CountingAccuracy(MulticlassAccuracy):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.calls = 0

            def update(self, input, target):
                self.calls += 1
                return super().update(input, target)

        m = CountingAccuracy(num_classes=4)
        col = MetricCollection(m)
        self.assertFalse(col._window_armable)
        x, t = _batch(48)
        for i in range(3):
            col.update(x[i * 16 : (i + 1) * 16], t[i * 16 : (i + 1) * 16])
        self.assertEqual(m.calls, 3)
        self.assertAlmostEqual(
            float(col.compute()), float((x.argmax(1) == t).mean()), places=6
        )
        # shipped metrics keep the fast path armed
        self.assertTrue(
            MetricCollection(MulticlassAccuracy(num_classes=4))._window_armable
        )

    def test_subclassed_compute_override_is_honored(self):
        # the window close runs the class-level _compute_fn INSTEAD of
        # member compute(), so a compute() overridden outside the library
        # must fall back to the member's own compute() (its state still
        # folds with the window; only the terminal stays member-own)
        class PercentAccuracy(MulticlassAccuracy):
            def compute(self):
                return super().compute() * 100.0

        m = PercentAccuracy(num_classes=4)
        col = MetricCollection({"acc": m})
        self.assertEqual(col._window_compute_keys, ())
        x, t = _batch(32)
        col.update(x, t)
        self.assertAlmostEqual(
            float(col.compute()["acc"]),
            float((x.argmax(1) == t).mean()) * 100.0,
            places=4,
        )
        # shipped computes keep riding the in-program terminal
        self.assertEqual(
            MetricCollection(
                {"acc": MulticlassAccuracy(num_classes=4)}
            )._window_compute_keys,
            ("acc",),
        )

    def test_rewrapping_per_epoch_does_not_accumulate_dead_windows(self):
        # a long-lived metric re-wrapped by a fresh collection per epoch,
        # with all reads going through the collection: the close() drain
        # must prune windows whose owning collection died, or
        # _defer_windows (each pinning its collection's member dict) grows
        # O(epochs)
        m = Mean()
        total, count = 0.0, 0
        for _ in range(6):
            col = MetricCollection(m)
            xs = RNG.random(16).astype(np.float32)
            col.update(xs)
            col.compute()
            total += float(xs.sum())
            count += 16
            del col
        self.assertLessEqual(len(m._defer_windows), 1)
        self.assertAlmostEqual(float(m.compute()), total / count, places=5)


if __name__ == "__main__":
    unittest.main()

"""Class ranking metrics through the protocol harness (SURVEY §4 tier 2).

Per-sample-vector metrics: the N-way merge reorders samples (rank-major), so
``merge_and_compute_result`` differs from the streaming result exactly like
the reference's list-state tests.
"""

import numpy as np

from torcheval_tpu.metrics import HitRate, ReciprocalRank
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    BATCH_SIZE,
    NUM_PROCESSES,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
)

NUM_CLASSES = 7


def _ranks(scores: np.ndarray, target: np.ndarray) -> np.ndarray:
    y = np.take_along_axis(scores, target[..., None], axis=-1)[..., 0]
    return (scores > y[..., None]).sum(axis=-1)


def _rank_major(per_update: np.ndarray) -> np.ndarray:
    """Reorder a (num_updates, batch) result the way a NUM_PROCESSES-way merge
    concatenates it: each rank's contiguous slice of updates, in rank order."""
    per_rank = NUM_TOTAL_UPDATES // NUM_PROCESSES
    chunks = [
        per_update[r * per_rank : (r + 1) * per_rank].reshape(-1)
        for r in range(NUM_PROCESSES)
    ]
    return np.concatenate(chunks)


class TestHitRateClass(MetricClassTester):
    def test_hit_rate(self):
        rng = np.random.default_rng(20)
        scores = rng.random(
            (NUM_TOTAL_UPDATES, BATCH_SIZE, NUM_CLASSES)
        ).astype(np.float32)
        target = rng.integers(0, NUM_CLASSES, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        hits = (_ranks(scores, target) < 3).astype(np.float32)
        self.run_class_implementation_tests(
            metric=HitRate(k=3),
            state_names={"scores"},
            update_kwargs={"input": scores, "target": target},
            compute_result=hits.reshape(-1),
            merge_and_compute_result=_rank_major(hits),
        )


class TestReciprocalRankClass(MetricClassTester):
    def test_reciprocal_rank(self):
        rng = np.random.default_rng(21)
        scores = rng.random(
            (NUM_TOTAL_UPDATES, BATCH_SIZE, NUM_CLASSES)
        ).astype(np.float32)
        target = rng.integers(0, NUM_CLASSES, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        rr = 1.0 / (_ranks(scores, target) + 1.0)
        self.run_class_implementation_tests(
            metric=ReciprocalRank(),
            state_names={"scores"},
            update_kwargs={"input": scores, "target": target},
            compute_result=rr.reshape(-1).astype(np.float32),
            merge_and_compute_result=_rank_major(rr).astype(np.float32),
        )

    def test_empty_compute(self):
        self.assertEqual(ReciprocalRank().compute().shape, (0,))
        self.assertEqual(HitRate().compute().shape, (0,))


class TestRankingKVariants(MetricClassTester):
    def test_hit_rate_k1(self):
        rng = np.random.default_rng(41)
        scores = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 6)).astype(np.float32)
        target = rng.integers(0, 6, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        hits = (_ranks(scores, target) < 1).astype(np.float32)
        self.run_class_implementation_tests(
            metric=HitRate(k=1),
            state_names={"scores"},
            update_kwargs={"input": scores, "target": target},
            compute_result=hits.reshape(-1),
            merge_and_compute_result=_rank_major(hits),
        )

    def test_reciprocal_rank_k2(self):
        rng = np.random.default_rng(42)
        scores = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 6)).astype(np.float32)
        target = rng.integers(0, 6, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        ranks = _ranks(scores, target)
        rr = np.where(ranks < 2, 1.0 / (ranks + 1), 0.0).astype(np.float32)
        self.run_class_implementation_tests(
            metric=ReciprocalRank(k=2),
            state_names={"scores"},
            update_kwargs={"input": scores, "target": target},
            compute_result=rr.reshape(-1),
            merge_and_compute_result=_rank_major(rr),
        )

    def test_invalid_update_shapes(self):
        with self.assertRaisesRegex(ValueError, "two-dimensional"):
            HitRate().update(np.zeros(3), np.zeros(3, dtype=np.int64))
        with self.assertRaisesRegex(ValueError, "minibatch"):
            ReciprocalRank().update(np.zeros((3, 2)), np.zeros(4, dtype=np.int64))

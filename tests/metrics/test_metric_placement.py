"""Metric state placement: devices, shardings, committed-input routing."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from torcheval_tpu.metrics import MulticlassAccuracy, Sum
from torcheval_tpu.parallel import data_parallel_mesh


class TestDevicePlacement(unittest.TestCase):
    def test_constructor_device_string(self):
        m = Sum(device="cpu")
        self.assertEqual(m.device.platform, "cpu")

    def test_to_explicit_device(self):
        dev = jax.devices()[-1]
        m = Sum().to(dev)
        m.update(jnp.asarray([1.0]))
        self.assertIn(dev, m.weighted_sum.devices())

    def test_update_moves_committed_inputs(self):
        # a batch committed to device 0 must fold into state on device 1
        d0, d1 = jax.devices()[0], jax.devices()[1]
        m = Sum().to(d1)
        x = jax.device_put(jnp.asarray([2.0, 3.0]), d0)
        m.update(x)
        self.assertEqual(float(m.compute()), 5.0)
        self.assertIn(d1, m.weighted_sum.devices())

    def test_invalid_device_spec(self):
        with self.assertRaises((ValueError, TypeError)):
            Sum(device=123)

    def test_reset_keeps_device(self):
        dev = jax.devices()[-1]
        m = Sum().to(dev)
        m.update(jnp.asarray([1.0]))
        m.reset()
        self.assertIn(dev, m.weighted_sum.devices())


class TestShardingPlacement(unittest.TestCase):
    def test_to_sharding_replicates_state(self):
        mesh = data_parallel_mesh()
        repl = NamedSharding(mesh, P())
        m = MulticlassAccuracy(num_classes=4).to(repl)
        self.assertEqual(
            len(m.num_total.sharding.device_set), len(jax.devices())
        )
        m.update(jnp.eye(4), jnp.arange(4))
        self.assertEqual(float(m.compute()), 1.0)

    def test_sharded_batch_kept_sharded_by_input(self):
        from torcheval_tpu.parallel import shard_batch

        mesh = data_parallel_mesh()
        repl = NamedSharding(mesh, P())
        m = MulticlassAccuracy(num_classes=4).to(repl)
        x = shard_batch(mesh, np.eye(4, dtype=np.float32).repeat(2, axis=0))
        routed = m._input(x)
        # the data-sharded batch must NOT be re-placed (that would all-gather)
        self.assertEqual(routed.sharding, x.sharding)

    def test_pickle_restores_to_local_device(self):
        import pickle

        m = Sum().to(jax.devices()[0])
        m.update(jnp.asarray([7.0]))
        m2 = pickle.loads(pickle.dumps(m))
        self.assertEqual(float(m2.compute()), 7.0)
        self.assertIsInstance(m2.device, jax.Device)


if __name__ == "__main__":
    unittest.main()

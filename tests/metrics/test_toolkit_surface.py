"""Toolkit helper surface: world-size-1 semantics, batch helpers, guards.

Complements test_toolkit.py (fold core / mesh) and
test_multiprocess_sync.py (real 4-process world).
"""

import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import (
    BinaryAUROC,
    Mean,
    MetricCollection,
    MulticlassAccuracy,
    Sum,
    Throughput,
)
from torcheval_tpu.metrics.toolkit import (
    clone_metric,
    clone_metrics,
    get_synced_metric,
    get_synced_state_dict,
    merge_metrics,
    reset_metrics,
    sync_and_compute,
    sync_and_compute_collection,
    to_device,
)


class TestWorldSizeOne(unittest.TestCase):
    """Single-process semantics (reference toolkit.py:199-215: warn + return
    the input)."""

    def test_sync_and_compute_returns_local(self):
        m = Sum()
        m.update(jnp.asarray([1.0, 2.0]))
        with self.assertLogs(level="WARNING"):
            self.assertEqual(float(sync_and_compute(m)), 3.0)

    def test_get_synced_metric_identity(self):
        m = Sum()
        with self.assertLogs(level="WARNING"):
            self.assertIs(get_synced_metric(m), m)

    def test_get_synced_state_dict(self):
        m = Sum()
        m.update(jnp.asarray([4.0]))
        with self.assertLogs(level="WARNING"):
            sd = get_synced_state_dict(m)
        self.assertEqual(float(sd["weighted_sum"]), 4.0)

    def test_sync_collection(self):
        ms = {"a": Sum(), "b": Mean()}
        ms["a"].update(jnp.asarray([2.0]))
        ms["b"].update(jnp.asarray([3.0]))
        with self.assertLogs(level="WARNING"):
            out = sync_and_compute_collection(ms)
        self.assertEqual(float(out["a"]), 2.0)
        self.assertEqual(float(out["b"]), 3.0)

    def test_invalid_recipient_rank(self):
        with self.assertRaisesRegex(ValueError, "recipient_rank"):
            get_synced_metric(Sum(), recipient_rank="some")


class TestBatchHelpers(unittest.TestCase):
    def test_clone_metrics_independent(self):
        m = Sum()
        m.update(jnp.asarray([1.0]))
        clones = clone_metrics([m, m])
        clones[0].update(jnp.asarray([9.0]))
        self.assertEqual(float(m.compute()), 1.0)
        self.assertEqual(float(clones[1].compute()), 1.0)
        self.assertEqual(float(clones[0].compute()), 10.0)

    def test_reset_metrics(self):
        ms = [Sum(), Mean()]
        ms[0].update(jnp.asarray([5.0]))
        ms[1].update(jnp.asarray([5.0]))
        reset_metrics(ms)
        self.assertEqual(float(ms[0].compute()), 0.0)
        self.assertEqual(float(ms[1].compute()), 0.0)

    def test_to_device_moves_state(self):
        devices = jax.devices()
        ms = to_device([Sum(), Throughput()], devices[-1])
        for m in ms:
            self.assertEqual(m.device, devices[-1])
            for v in m._states().values():
                self.assertIn(devices[-1], v.devices())

    def test_merge_metrics_empty_and_single(self):
        self.assertIsNone(merge_metrics([]))
        m = Sum()
        m.update(jnp.asarray([2.0]))
        merged = merge_metrics([m])
        self.assertEqual(float(merged.compute()), 2.0)
        merged.update(jnp.asarray([1.0]))
        self.assertEqual(float(m.compute()), 2.0)  # source untouched

    def test_merge_metrics_does_not_mutate_sources(self):
        a, b = Sum(), Sum()
        a.update(jnp.asarray([1.0]))
        b.update(jnp.asarray([2.0]))
        merged = merge_metrics([a, b])
        self.assertEqual(float(merged.compute()), 3.0)
        self.assertEqual(float(a.compute()), 1.0)
        self.assertEqual(float(b.compute()), 2.0)


class TestSampleCacheToolkitInteraction(unittest.TestCase):
    def test_prepare_for_merge_state_compacts_cat_cache(self):
        m = BinaryAUROC()
        for _ in range(3):
            m.update(jnp.asarray([0.1, 0.9]), jnp.asarray([0.0, 1.0]))
        self.assertEqual(len(m.inputs), 3)
        m._prepare_for_merge_state()
        self.assertEqual(len(m.inputs), 1)
        self.assertEqual(m.inputs[0].shape, (6,))

    def test_clone_of_cache_metric_is_independent(self):
        m = BinaryAUROC()
        m.update(jnp.asarray([0.2, 0.8]), jnp.asarray([0.0, 1.0]))
        c = clone_metric(m)
        c.update(jnp.asarray([0.5]), jnp.asarray([1.0]))
        self.assertEqual(sum(a.shape[0] for a in m.inputs), 2)
        self.assertEqual(sum(a.shape[0] for a in c.inputs), 3)


class TestCollectionWithToolkit(unittest.TestCase):
    def test_sync_collection_of_fused_metrics_world1(self):
        col = MetricCollection(
            {"acc": MulticlassAccuracy(num_classes=3), "sum": Sum()}
        )
        col["acc"].update(jnp.eye(3), jnp.arange(3))
        with self.assertLogs(level="WARNING"):
            out = sync_and_compute_collection(col.metrics)
        self.assertEqual(float(out["acc"]), 1.0)


if __name__ == "__main__":
    unittest.main()

"""Worker executed by ``test_multiprocess_sync.py`` in 4 real OS processes.

Each process joins a ``jax.distributed`` CPU world (Gloo collectives), streams
its rank's shard into local metric replicas, and drives the *explicit* sync
path — ``sync_and_compute`` / ``get_synced_metric`` / ``get_synced_state_dict``
— exactly as a multi-host TPU pod would. This is the JAX equivalent of the
reference's tier-3 strategy (``torcheval/utils/test_utils/
metric_class_tester.py:272-311``: ``elastic_launch`` of 4 local processes).

Run:  python mp_sync_worker.py <rank> <world> <port> <outdir>
Writes <outdir>/rank<r>.json with every scenario's results.

The data-generation helpers live here (imported by the parent test for its
single-stream oracles) and must stay deterministic in (rank, world).
"""

import json
import os
import sys

import numpy as np

NUM_CLASSES = 5
ACC_BATCH = 64
# deliberately uneven AUROC shard sizes, including one empty rank (rank 2):
# exercises the CAT descriptor exchange's empty-rank adoption path
AUROC_SIZES = [37, 11, 0, 52]


def make_acc_shard(rank: int):
    rng = np.random.default_rng(100 + rank)
    scores = rng.random((ACC_BATCH, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, ACC_BATCH)
    return scores, labels


def make_auroc_shard(rank: int):
    n = AUROC_SIZES[rank]
    rng = np.random.default_rng(200 + rank)
    scores = rng.random(n).astype(np.float32)
    targets = (rng.random(n) < 0.4).astype(np.float32)
    return scores, targets


RETRIEVAL_K = 7
RETRIEVAL_L = 512


def make_retrieval_shard(rank: int):
    rng = np.random.default_rng(500 + rank)
    scores = rng.random((24, RETRIEVAL_L)).astype(np.float32)
    targets = (rng.random((24, RETRIEVAL_L)) > 0.98).astype(np.float32)
    if rank == 1:
        targets[:4] = 0.0  # some invalid rows on one rank
    return scores, targets


def make_dict_updates(rank: int):
    # overlapping and rank-unique keys
    return [("shared", float(rank + 1)), (f"rank{rank}", 10.0 * (rank + 1))]


# quantized-wire scenario (ISSUE 12): an integer-lane-dominant state big
# enough for the codecs to engage (int64 counts held as host numpy so the
# 64-bit width survives jax's 32-bit default)
QUANT_N = 4096


def make_quant_counts(rank: int):
    rng = np.random.default_rng(300 + rank)
    return rng.integers(0, 200, QUANT_N).astype(np.int64)


def make_quant_fsum(rank: int):
    rng = np.random.default_rng(400 + rank)
    return (rng.random(QUANT_N) * 10.0).astype(np.float32)


def make_quant_metric(rank: int):
    from torcheval_tpu.metrics.metric import Metric
    from torcheval_tpu.metrics.state import Reduction

    class QuantSumMetric(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self._add_state(
                "counts",
                np.zeros(QUANT_N, np.int64),
                reduction=Reduction.SUM,
            )
            self._add_state(
                "fsum",
                np.zeros(QUANT_N, np.float32),
                reduction=Reduction.SUM,
            )

        def update(self, c, f):
            self.counts = np.asarray(self.counts, np.int64) + c
            self.fsum = np.asarray(self.fsum, np.float32) + f
            return self

        def compute(self):
            return float(self.counts.sum()) + float(self.fsum.sum())

        def merge_state(self, metrics):
            for o in metrics:
                self.counts = self.counts + np.asarray(o.counts)
                self.fsum = self.fsum + np.asarray(o.fsum)
            return self

    return QuantSumMetric().update(
        make_quant_counts(rank), make_quant_fsum(rank)
    )


# sliced-collection scenario (ISSUE 15): ragged per-rank cohort
# populations — overlapping pools, one EMPTY rank — synced over the real
# wire; the parent asserts per-slice bit-identity to its single-stream
# oracle. All count lanes are int32 SUM, so the CI quantized re-run
# (TORCHEVAL_TPU_SYNC_QUANTIZE=1) must stay bit-identical too.
SLICED_POOL = 9
SLICED_N = 181


def make_sliced_shard(rank: int):
    if rank == 2:
        return []  # empty rank: contributes only reduce identities
    rng = np.random.default_rng(600 + rank)
    pool_ids = (np.arange(SLICED_POOL) + rank * 4) * 97 - 13
    out = []
    for _ in range(2):
        ids = rng.choice(pool_ids, SLICED_N)
        scores = rng.random(SLICED_N).astype(np.float32)
        targets = (rng.random(SLICED_N) < 0.5).astype(np.float32)
        out.append((ids, scores, targets))
    return out


def make_sliced_collection(mesh=None, mesh_axis=None):
    from torcheval_tpu.metrics import (
        BinaryAccuracy,
        BinaryAUROC,
        SlicedMetricCollection,
    )

    kw = {} if mesh_axis is None else {"mesh": mesh, "mesh_axis": mesh_axis}
    return SlicedMetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
        capacity=4,
        **kw,
    )


def _jsonable(x):
    arr = np.asarray(x)
    return arr.tolist() if arr.ndim else float(arr)


def main() -> None:
    rank, world, port, outdir = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    # ISSUE 17: give every process TWO local CPU devices so the sharded
    # sliced scenario can split the slice axis over a per-process mesh.
    # Every other scenario is device-count-agnostic (the wire moves host
    # bytes via process_allgather; state stays replicated locally).
    from torcheval_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(2)
    # join the world through the public bootstrap helper, fed torch-elastic
    # style env vars — exactly how a launch script written for the reference
    # (torchrun setting MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE) would drive it
    os.environ["MASTER_ADDR"] = "localhost"
    os.environ["MASTER_PORT"] = port
    os.environ["WORLD_SIZE"] = str(world)
    os.environ["RANK"] = str(rank)
    from torcheval_tpu.parallel import init_from_env

    got_rank, got_world = init_from_env()
    assert (got_rank, got_world) == (rank, world)
    import jax.numpy as jnp

    from torcheval_tpu.metrics import (
        BinaryAUROC,
        MulticlassAccuracy,
        Quantile,
        Sum,
        Throughput,
    )
    from torcheval_tpu.metrics.toolkit import (
        get_synced_metric,
        get_synced_state_dict,
        sync_and_compute,
        sync_and_compute_collection,
    )
    from torcheval_tpu.utils.test_utils import DummySumDictStateMetric

    assert jax.process_count() == world

    results = {"rank": rank}

    # --- SUM-state: Sum, recipient permutations 0 / 1 / "all"
    s = Sum()
    s.update(jnp.asarray([float(rank + 1), 2.0 * (rank + 1)]))
    for recipient in (0, 1, "all"):
        r = sync_and_compute(s, recipient_rank=recipient)
        results[f"sum_r{recipient}"] = None if r is None else _jsonable(r)

    # --- SUM-state with per-class counters: MulticlassAccuracy
    acc = MulticlassAccuracy(num_classes=NUM_CLASSES)
    scores, labels = make_acc_shard(rank)
    acc.update(jnp.asarray(scores), jnp.asarray(labels))
    r = sync_and_compute(acc, recipient_rank="all")
    results["acc_all"] = _jsonable(r)

    # --- MAX-state: Throughput (sum counts, max elapsed)
    t = Throughput()
    t.update(num_processed=100 * (rank + 1), elapsed_time_sec=float(rank + 1))
    r = sync_and_compute(t, recipient_rank="all")
    results["throughput_all"] = _jsonable(r)

    # --- CAT-state, uneven lengths incl. an empty rank: BinaryAUROC
    auroc = BinaryAUROC()
    a_scores, a_targets = make_auroc_shard(rank)
    if a_scores.size:  # rank 2 never updates — its cache stays empty
        auroc.update(jnp.asarray(a_scores), jnp.asarray(a_targets))
    r = sync_and_compute(auroc, recipient_rank="all")
    results["auroc_all"] = _jsonable(r)
    r0 = sync_and_compute(auroc, recipient_rank=0)
    results["auroc_r0"] = None if r0 is None else _jsonable(r0)

    # --- ISSUE 13: resident-sketch states over the REAL wire. The sketch
    # lanes are int32 SUM histograms — the fold is exact bucket-add on any
    # transport (and LOSSLESS under the quantized codecs CI forces on in
    # its re-run), so the parent asserts bit-identity against its own
    # single-stream oracle, not a tolerance.
    sk = BinaryAUROC(approx=4096, compaction_threshold=512)
    if a_scores.size:  # rank 2 stays empty (zero sketch merges as zeros)
        sk.update(jnp.asarray(a_scores), jnp.asarray(a_targets))
    r = sync_and_compute(sk, recipient_rank="all")
    results["sketch_auroc_all"] = _jsonable(r)
    q = Quantile((0.25, 0.75), bucket_count=4096)
    q.update(jnp.asarray(make_quant_counts(rank).astype(np.float32)))
    r = sync_and_compute(q, recipient_rank="all")
    results["sketch_quantile_all"] = [_jsonable(v) for v in np.asarray(r)]

    # --- ISSUE 15: sliced collection with ragged per-rank cohort
    # populations (rank 2 empty). The sliced lanes are plain int32 SUM with
    # a leading slice axis; the toolkit's post-gather union alignment must
    # deliver per-slice values bit-identical to the parent's single-stream
    # oracle on every rank, quantized or not.
    scol = make_sliced_collection()
    for b in make_sliced_shard(rank):
        scol.update(*b)
    r = sync_and_compute_collection(dict(scol.metrics), recipient_rank="all")
    results["sliced_ids"] = [int(i) for i in r["acc"]["slice_ids"]]
    results["sliced_acc"] = _jsonable(r["acc"]["values"])
    results["sliced_auroc"] = _jsonable(r["auroc"]["values"])

    # --- ISSUE 17: the SAME sliced scenario with the slice axis SHARDED
    # over this process's LOCAL 2-device mesh. The wire is process-level
    # (host bytes via process_allgather — the local np.asarray gather
    # assembles the global slice axis from addressable shards without a
    # cross-process collective), so per-process device sharding composes
    # with it; the install path re-shards the union-aligned state. Synced
    # both ways: transport default (raw, or quantized under the CI
    # re-run's env knob) AND explicit quantize=True — per-slice values
    # must be bit-identical to the parent's unsharded oracle either way.
    from jax.sharding import Mesh as _Mesh

    local_mesh = _Mesh(np.asarray(jax.local_devices()), ("slices",))
    scol_sh = make_sliced_collection(mesh=local_mesh, mesh_axis="slices")
    for b in make_sliced_shard(rank):
        scol_sh.update(*b)
    results["sliced_sharded_replicated"] = bool(
        scol_sh.metrics["auroc"].sketch_tp.sharding.is_fully_replicated
    )
    r = sync_and_compute_collection(
        dict(scol_sh.metrics), recipient_rank="all"
    )
    results["sliced_sharded_ids"] = [int(i) for i in r["acc"]["slice_ids"]]
    results["sliced_sharded_acc"] = _jsonable(r["acc"]["values"])
    results["sliced_sharded_auroc"] = _jsonable(r["auroc"]["values"])
    rq = sync_and_compute_collection(
        dict(scol_sh.metrics), recipient_rank="all", quantize=True
    )
    results["sliced_sharded_q_ids"] = [int(i) for i in rq["acc"]["slice_ids"]]
    results["sliced_sharded_q_acc"] = _jsonable(rq["acc"]["values"])
    results["sliced_sharded_q_auroc"] = _jsonable(rq["auroc"]["values"])

    # --- synced metric object + synced state dict on recipient 1
    synced = get_synced_metric(acc, recipient_rank=1)
    results["synced_metric_r1"] = (
        None if synced is None else _jsonable(synced.compute())
    )
    sd = get_synced_state_dict(acc, recipient_rank=1)
    results["synced_sd_r1_keys"] = sorted(sd)
    if sd:
        results["synced_sd_r1_num_total"] = _jsonable(sd["num_total"])

    # --- dict-state metric through the object-gather fallback
    d = DummySumDictStateMetric()
    for key, val in make_dict_updates(rank):
        d.update(key, val)
    r = sync_and_compute(d, recipient_rank="all")
    results["dict_all"] = _jsonable(r)
    synced_d = get_synced_metric(d, recipient_rank=0)
    results["dict_keys_r0"] = (
        None if synced_d is None else sorted(synced_d.x)
    )

    # --- whole-collection sync: one typed two-round exchange for acc+sum+
    # auroc (uneven CAT incl. the empty rank) plus one object gather for the
    # dict metric — exercises the batched wire end to end
    col = {"acc": acc, "sum": s, "auroc": auroc, "dict": d, "tp": t}
    r = sync_and_compute_collection(col, recipient_rank="all")
    results["collection_all"] = {k: _jsonable(v) for k, v in r.items()}
    r1 = sync_and_compute_collection(col, recipient_rank=1)
    results["collection_r1"] = None if r1 is None else sorted(r1)

    # --- ISSUE 14: retrieval family — two scalar SUM lanes per metric, so
    # the synced mean must be BIT-identical to folding all shards into one
    # replica (integer valid counts; float sums add in rank order on the
    # typed wire exactly as the parent's oracle adds them)
    from torcheval_tpu.metrics import MAP, NDCG, RecallAtK

    r_scores, r_targets = make_retrieval_shard(rank)
    for key, cls in (("ndcg", NDCG), ("map", MAP), ("recall", RecallAtK)):
        rm = cls(k=RETRIEVAL_K)
        rm.update(jnp.asarray(r_scores), jnp.asarray(r_targets))
        r = sync_and_compute(rm, recipient_rank="all")
        results[f"retrieval_{key}_all"] = _jsonable(r)

    # --- windowed deque-state metric through the object lane: per-update
    # window-entry boundaries must survive the sync (each rank contributes
    # its own update rows, bounded by the shared window size)
    from torcheval_tpu.metrics import WindowedClickThroughRate

    wctr = WindowedClickThroughRate(window_size=6)
    for _ in range(2):  # 8 updates worldwide > window 6
        wctr.update(jnp.asarray([1.0 if rank >= 2 else 0.0] * 4))
    # rank-ordered merge: window keeps the LAST 6 of
    # [r0,r0, r1,r1, r2,r2, r3,r3] = [0,0, 4,4, 4,4] clicks / 24 weight
    wr = sync_and_compute(wctr, recipient_rank="all")
    results["windowed_ctr_lifetime"] = float(np.asarray(wr[0])[0])
    results["windowed_ctr_windowed"] = float(np.asarray(wr[1])[0])

    # --- window-config drift: replicas disagreeing on window_size must fail
    # loudly and UNIFORMLY at the typed exchange (the schema digest carries
    # _sync_schema_extra; the typed fold never reaches merge_state's eager
    # ValueError)
    bad = WindowedClickThroughRate(window_size=6 if rank == 2 else 5)
    bad.update(jnp.asarray([1.0]))
    try:
        sync_and_compute(bad, recipient_rank="all")
        results["wctr_config_drift_error"] = False
    except RuntimeError as e:
        results["wctr_config_drift_error"] = "schema mismatch" in str(e)

    # --- sub-process-group sync (reference process_group semantics,
    # toolkit.py:24-78): ranks 1 and 3 sync within processes=[1, 3] while
    # ranks 0 and 2 are genuinely uninvolved — they never enter the
    # collective (their only interaction is the eager non-member ValueError)
    SUBGROUP = [1, 3]
    if rank in SUBGROUP:
        sub = Sum()
        sub.update(jnp.asarray([10.0 * (rank + 1)]))  # 20 + 40 -> 60
        r = sync_and_compute(sub, recipient_rank="all", processes=SUBGROUP)
        results["subgroup_sum_all"] = _jsonable(r)
        r3 = sync_and_compute(sub, recipient_rank=3, processes=SUBGROUP)
        results["subgroup_sum_r3"] = None if r3 is None else _jsonable(r3)
        # recipient outside the subgroup: eager raise, no collective entered
        try:
            sync_and_compute(sub, recipient_rank=0, processes=SUBGROUP)
            results["subgroup_bad_recipient"] = None
        except ValueError:
            results["subgroup_bad_recipient"] = True
        # whole-collection subgroup sync: typed lanes (SUM + uneven CAT)
        # plus the object lane (dict state) — all scoped to the subgroup
        sub_auroc = BinaryAUROC()
        ss, st = make_auroc_shard(rank)
        if ss.size:
            sub_auroc.update(jnp.asarray(ss), jnp.asarray(st))
        sub_d = DummySumDictStateMetric()
        for key, val in make_dict_updates(rank):
            sub_d.update(key, val)
        rc = sync_and_compute_collection(
            {"s": sub, "auroc": sub_auroc, "d": sub_d},
            recipient_rank="all",
            processes=SUBGROUP,
        )
        results["subgroup_collection"] = {k: _jsonable(v) for k, v in rc.items()}
        sd = get_synced_state_dict(sub, recipient_rank=1, processes=SUBGROUP)
        results["subgroup_sd_r1"] = (
            _jsonable(sd["weighted_sum"]) if sd else None
        )
    else:
        # non-members must be rejected eagerly (entering the collective
        # would hang the members) — reference: invalid process_group use
        try:
            sync_and_compute(s, processes=SUBGROUP)
            results["subgroup_nonmember_error"] = None
        except ValueError as e:
            results["subgroup_nonmember_error"] = "not a member" in str(e)

    # --- wire-cost contract: count the actual collective rounds. A sync is
    # exactly TWO process_allgather calls (descriptor matrix + byte payload)
    # no matter how many states the metric (or whole array-lane collection)
    # has; the dict metric's object lane costs two more (its own length +
    # payload exchange). Every process must patch and sync in lockstep — the
    # patched wrapper still calls the real collective underneath.
    from jax.experimental import multihost_utils as _mhu

    real_allgather = _mhu.process_allgather
    counts = {}

    def _counting(*a, **k):
        counts["n"] = counts.get("n", 0) + 1
        return real_allgather(*a, **k)

    _mhu.process_allgather = _counting
    try:
        counts["n"] = 0
        sync_and_compute(acc, recipient_rank="all")  # 2 SUM states
        results["rounds_acc"] = counts["n"]
        counts["n"] = 0
        sync_and_compute(auroc, recipient_rank="all")  # 2 CAT caches
        results["rounds_auroc"] = counts["n"]
        counts["n"] = 0
        sync_and_compute_collection(
            {"acc": acc, "auroc": auroc, "tp": t}, recipient_rank="all"
        )  # whole array-lane collection: still one two-round exchange
        results["rounds_collection"] = counts["n"]
        counts["n"] = 0
        # sliced collection (ISSUE 15): every slice's state moves in the
        # SAME two typed rounds — slice count never adds a collective
        sync_and_compute_collection(
            dict(scol.metrics), recipient_rank="all"
        )
        results["rounds_sliced"] = counts["n"]
        counts["n"] = 0
        # windowed deque state rides the TYPED wire (round-5: stacked rows
        # with per-update boundaries), not the pickled object lane — so a
        # windowed CTR sync is the same two rounds as any typed metric
        results["wctr_typed_value"] = float(
            np.asarray(sync_and_compute(wctr, recipient_rank="all")[1])[0]
        )
        results["rounds_wctr"] = counts["n"]
        counts["n"] = 0
        # and a collection mixing windowed + dict metrics pays exactly
        # 2 typed + 2 object rounds
        sync_and_compute_collection(
            {"wctr": wctr, "dict": d}, recipient_rank="all"
        )
        results["rounds_wctr_plus_dict"] = counts["n"]
    finally:
        _mhu.process_allgather = real_allgather

    # --- obs collective accounting (ISSUE 1 acceptance): with obs enabled,
    # a sync_and_compute in this 4-process world reports exactly 2 accounted
    # collective rounds, nonzero payload bytes per POPULATED Reduction lane,
    # and the true participating world size — the wire-cost contract above,
    # re-read from the in-library registry instead of a monkeypatch
    from torcheval_tpu import obs

    obs.enable()
    try:
        obs.reset()
        sync_and_compute(acc, recipient_rank="all")
        snap = obs.snapshot()
        results["obs_acc_rounds"] = snap["counters"]["toolkit.sync.rounds"]
        results["obs_acc_sum_lane_bytes"] = snap["counters"][
            "toolkit.sync.lane_bytes{lane=SUM}"
        ]
        results["obs_acc_payload_bytes"] = snap["counters"][
            "toolkit.sync.payload_bytes"
        ]
        results["obs_world_size"] = snap["gauges"]["toolkit.sync.world_size"]

        # lane_bytes accounting-drift guard (ISSUE 12 satellite): when the
        # codec is raw, the raw and encoded counters must agree EXACTLY —
        # a silent double-count in either immediately breaks this pair.
        # (Accuracy's states sit below the quantization floor, so this
        # holds even when TORCHEVAL_TPU_SYNC_QUANTIZE=1 forces the codec
        # on for the CI rerun.)
        results["obs_acc_sum_lane_bytes_encoded_raw"] = snap["counters"][
            "toolkit.sync.lane_bytes_encoded{codec=raw,lane=SUM}"
        ]

        obs.reset()
        sync_and_compute(auroc, recipient_rank="all")
        snap = obs.snapshot()
        results["obs_auroc_rounds"] = snap["counters"]["toolkit.sync.rounds"]
        # the CAT lane records LOCAL bytes: nonzero exactly on the ranks
        # whose cache holds samples (rank 2's shard is deliberately empty)
        results["obs_auroc_cat_lane_bytes"] = snap["counters"][
            "toolkit.sync.lane_bytes{lane=CAT}"
        ]

        # --- quantized wire over the REAL 4-process transport (ISSUE 12):
        # an integer-lane-dominant metric syncs with quantize=True — int64
        # count lanes must come back bit-exact (narrow + widened
        # accumulation), the f32 sum lane within its documented bound, the
        # wire still two rounds, and the encoded bytes >= 4x below raw
        obs.reset()
        qm = make_quant_metric(rank)
        q_synced = get_synced_metric(qm, recipient_rank="all", quantize=True)
        qsnap = obs.snapshot()
        results["quant_rounds"] = qsnap["counters"]["toolkit.sync.rounds"]
        want_counts = np.sum(
            [make_quant_counts(r) for r in range(world)], axis=0
        )
        results["quant_int_exact"] = bool(
            np.array_equal(np.asarray(q_synced.counts), want_counts)
        )
        want_fsum = np.sum(
            [make_quant_fsum(r) for r in range(world)], axis=0,
            dtype=np.float64,
        )
        bound = (
            sum(
                float(np.abs(make_quant_fsum(r)).max())
                for r in range(world)
            )
            / 254.0
            + 1e-3
        )
        results["quant_f32_within_bound"] = bool(
            np.abs(np.asarray(q_synced.fsum) - want_fsum).max() <= bound
        )
        raw_b = sum(
            v
            for k, v in qsnap["counters"].items()
            if k.startswith("toolkit.sync.lane_bytes{")
        )
        enc_b = sum(
            v
            for k, v in qsnap["counters"].items()
            if k.startswith("toolkit.sync.lane_bytes_encoded{")
        )
        results["quant_lane_bytes_raw"] = raw_b
        results["quant_lane_bytes_encoded"] = enc_b
    finally:
        obs.disable()
        obs.reset()

    os.makedirs(outdir, exist_ok=True)
    with open(os.path.join(outdir, f"rank{rank}.json"), "w") as f:
        json.dump(results, f)


if __name__ == "__main__":
    main()

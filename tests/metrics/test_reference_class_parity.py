"""Streaming + merge parity against the reference's class-level state machines.

`tests/metrics/functional/test_reference_parity.py` pins single-shot value
parity per functional kernel; this module pins the CLASS protocol against
the reference itself: chunked `update` streams accumulate to the same
result, and `merge_state` over differently-fed replicas agrees — i.e. a
user porting a streaming eval loop (README "Porting from torcheval") gets
bit-compatible numbers, not just compatible APIs.
"""

import sys
import unittest

import jax.numpy as jnp
import numpy as np

import pytest

sys.path.insert(0, "/root/reference")
torch = pytest.importorskip(
    "torch", reason="reference parity needs torch"
)
# skip (not error) where the reference checkout is absent: these tests pin
# parity against /root/reference and are meaningless without it
pytest.importorskip(
    "torcheval.metrics",
    reason="reference torcheval checkout not present at /root/reference",
)
import torcheval.metrics as RM  # noqa: E402

import torcheval_tpu.metrics as M  # noqa: E402

SEEDS = (0, 3)
CHUNKS = 3


def _close(ours, ref, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(ref), rtol=rtol, atol=atol, equal_nan=True
    )


def _stream_and_merge(make_ours, make_ref, batches, rtol=1e-5):
    """Stream all batches into one pair; also split across two replicas and
    merge. Assert ours == reference for both protocols."""
    ours, ref = make_ours(), make_ref()
    for args in batches:
        ours.update(*(jnp.asarray(a) for a in args))
        ref.update(*(torch.from_numpy(np.asarray(a)) for a in args))
    _close(ours.compute(), ref.compute(), rtol=rtol)

    ours_a, ours_b = make_ours(), make_ours()
    ref_a, ref_b = make_ref(), make_ref()
    for i, args in enumerate(batches):
        (ours_a if i % 2 == 0 else ours_b).update(
            *(jnp.asarray(a) for a in args)
        )
        (ref_a if i % 2 == 0 else ref_b).update(
            *(torch.from_numpy(np.asarray(a)) for a in args)
        )
    ours_a.merge_state([ours_b])
    ref_a.merge_state([ref_b])
    _close(ours_a.compute(), ref_a.compute(), rtol=rtol)


def _cls_chunks(rng, n, c):
    out = []
    for _ in range(CHUNKS):
        scores = rng.random((n, c)).astype(np.float32)
        labels = rng.integers(0, c, n)
        labels[:c] = np.arange(c)
        scores[np.arange(c), np.arange(c)] += 2.0
        out.append((scores, labels))
    return out


class TestClassificationClassParity(unittest.TestCase):
    def test_multiclass_accuracy(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = _cls_chunks(rng, 100, 5)
            for average in ("micro", "macro"):
                _stream_and_merge(
                    lambda: M.MulticlassAccuracy(average=average, num_classes=5),
                    lambda: RM.MulticlassAccuracy(average=average, num_classes=5),
                    batches,
                )

    def test_multiclass_f1_precision_recall(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = _cls_chunks(rng, 120, 4)
            for ours_cls, ref_cls in (
                (M.MulticlassF1Score, RM.MulticlassF1Score),
                (M.MulticlassPrecision, RM.MulticlassPrecision),
                (M.MulticlassRecall, RM.MulticlassRecall),
            ):
                for average in ("micro", "macro", "weighted"):
                    _stream_and_merge(
                        lambda: ours_cls(average=average, num_classes=4),
                        lambda: ref_cls(average=average, num_classes=4),
                        batches,
                    )

    def test_binary_threshold_classes(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = [
                (
                    rng.random(80).astype(np.float32),
                    (rng.random(80) < 0.4).astype(np.int64),
                )
                for _ in range(CHUNKS)
            ]
            for ours_cls, ref_cls in (
                (M.BinaryAccuracy, RM.BinaryAccuracy),
                (M.BinaryF1Score, RM.BinaryF1Score),
                (M.BinaryPrecision, RM.BinaryPrecision),
                (M.BinaryRecall, RM.BinaryRecall),
            ):
                _stream_and_merge(ours_cls, ref_cls, batches)

    def test_binary_auroc_and_curves(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = [
                (
                    rng.random(150).astype(np.float32),
                    (rng.random(150) < 0.5).astype(np.float32),
                )
                for _ in range(CHUNKS)
            ]
            _stream_and_merge(M.BinaryAUROC, RM.BinaryAUROC, batches, rtol=1e-4)
            # curve tuple: compare leaf-wise through compute()
            ours, ref = M.BinaryPrecisionRecallCurve(), RM.BinaryPrecisionRecallCurve()
            for x, t in batches:
                ours.update(jnp.asarray(x), jnp.asarray(t))
                ref.update(torch.from_numpy(x), torch.from_numpy(t))
            for o, r in zip(ours.compute(), ref.compute()):
                _close(o, r, rtol=1e-4)

    def test_binned_prc_class(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            ours = M.BinaryBinnedPrecisionRecallCurve(threshold=20)
            ref = RM.BinaryBinnedPrecisionRecallCurve(threshold=20)
            for _ in range(CHUNKS):
                x = rng.random(120).astype(np.float32)
                t = (rng.random(120) < 0.4).astype(np.int64)
                ours.update(jnp.asarray(x), jnp.asarray(t))
                ref.update(torch.from_numpy(x), torch.from_numpy(t))
            for o, r in zip(ours.compute(), ref.compute()):
                _close(o, r, rtol=1e-4)

    def test_normalized_entropy_class(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = [
                (
                    rng.uniform(0.05, 0.95, 100).astype(np.float32),
                    (rng.random(100) < 0.3).astype(np.float32),
                )
                for _ in range(CHUNKS)
            ]
            _stream_and_merge(
                M.BinaryNormalizedEntropy, RM.BinaryNormalizedEntropy,
                batches, rtol=1e-4,
            )


class TestRankingRegressionAggregationClassParity(unittest.TestCase):
    def test_regression_classes(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = [
                (
                    rng.random(90).astype(np.float32),
                    rng.random(90).astype(np.float32),
                )
                for _ in range(CHUNKS)
            ]
            _stream_and_merge(M.MeanSquaredError, RM.MeanSquaredError, batches, rtol=1e-4)
            _stream_and_merge(M.R2Score, RM.R2Score, batches, rtol=1e-4)

    def test_ranking_classes(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = [
                (
                    rng.random((50, 6)).astype(np.float32),
                    rng.integers(0, 6, 50),
                )
                for _ in range(CHUNKS)
            ]
            _stream_and_merge(M.HitRate, RM.HitRate, batches)
            _stream_and_merge(M.ReciprocalRank, RM.ReciprocalRank, batches)

    def test_aggregation_classes(self):
        for seed in SEEDS:
            rng = np.random.default_rng(seed)
            batches = [
                (rng.random(64).astype(np.float32),) for _ in range(CHUNKS)
            ]
            for ours_cls, ref_cls in (
                (M.Sum, RM.Sum),
                (M.Mean, RM.Mean),
                (M.Max, RM.Max),
                (M.Min, RM.Min),
            ):
                _stream_and_merge(ours_cls, ref_cls, batches)
            # Cat: compare concatenated payloads
            ours, ref = M.Cat(), RM.Cat()
            for (x,) in batches:
                ours.update(jnp.asarray(x))
                ref.update(torch.from_numpy(x))
            _close(ours.compute(), ref.compute())

    def test_throughput_class(self):
        ours, ref = M.Throughput(), RM.Throughput()
        for n, s in ((100, 1.0), (250, 2.5), (75, 0.5)):
            ours.update(num_processed=n, elapsed_time_sec=s)
            ref.update(num_processed=n, elapsed_time_sec=s)
        _close(ours.compute(), ref.compute(), rtol=1e-5)
        # merge: counts sum, elapsed takes the max
        oa, ob, ra, rb = M.Throughput(), M.Throughput(), RM.Throughput(), RM.Throughput()
        oa.update(num_processed=100, elapsed_time_sec=2.0)
        ra.update(num_processed=100, elapsed_time_sec=2.0)
        ob.update(num_processed=300, elapsed_time_sec=3.0)
        rb.update(num_processed=300, elapsed_time_sec=3.0)
        oa.merge_state([ob])
        ra.merge_state([rb])
        _close(oa.compute(), ra.compute(), rtol=1e-5)


if __name__ == "__main__":
    unittest.main()

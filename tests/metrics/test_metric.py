"""Base ``Metric`` state-machine tests, one block per state container type.

Mirrors the coverage of ``/root/reference/tests/metrics/test_metric.py:22-473``:
state registration, reset, state_dict round-trip and strictness, device move,
merge semantics of the dummy fixtures.
"""

import copy
import pickle
import unittest
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.utils.test_utils import (
    DummySumDequeStateMetric,
    DummySumDictStateMetric,
    DummySumListStateMetric,
    DummySumMetric,
)


class TestMetricBase(unittest.TestCase):
    def test_add_state_and_defaults(self):
        m = DummySumMetric()
        self.assertEqual(m.state_names, ("sum",))
        np.testing.assert_allclose(np.asarray(m.sum), 0.0)
        self.assertEqual(m._state_name_to_reduction["sum"], Reduction.SUM)

    def test_update_compute_reset_tensor_state(self):
        m = DummySumMetric()
        m.update(jnp.asarray([1.0, 2.0])).update(jnp.asarray(3.0))
        np.testing.assert_allclose(np.asarray(m.compute()), 6.0)
        m.reset()
        np.testing.assert_allclose(np.asarray(m.compute()), 0.0)

    def test_list_state(self):
        m = DummySumListStateMetric()
        m.update(jnp.asarray([1.0, 2.0]))
        m.update(jnp.asarray([3.0, 4.0]))
        self.assertEqual(len(m.x), 2)
        np.testing.assert_allclose(np.asarray(m.compute()), 10.0)
        m.reset()
        self.assertEqual(m.x, [])

    def test_dict_state(self):
        m = DummySumDictStateMetric()
        m.update("a", jnp.asarray(1.0))
        m.update("b", jnp.asarray(2.0))
        m.update("a", jnp.asarray(3.0))
        np.testing.assert_allclose(np.asarray(m.x["a"]), 4.0)
        np.testing.assert_allclose(np.asarray(m.compute()), 6.0)
        m.reset()
        self.assertEqual(dict(m.x), {})

    def test_deque_state_maxlen(self):
        m = DummySumDequeStateMetric(maxlen=2)
        for v in [1.0, 2.0, 3.0]:
            m.update(jnp.asarray(v))
        self.assertEqual(len(m.x), 2)
        np.testing.assert_allclose(np.asarray(m.compute()), 5.0)
        m.reset()
        self.assertEqual(len(m.x), 0)
        self.assertEqual(m.x.maxlen, 2)

    def test_state_dict_roundtrip(self):
        m = DummySumMetric()
        m.update(jnp.asarray(5.0))
        sd = m.state_dict()
        m2 = DummySumMetric()
        m2.load_state_dict(sd)
        np.testing.assert_allclose(np.asarray(m2.compute()), 5.0)

    def test_load_state_dict_strict(self):
        m = DummySumMetric()
        with self.assertRaisesRegex(RuntimeError, "missing keys"):
            m.load_state_dict({}, strict=True)
        with self.assertRaisesRegex(RuntimeError, "unexpected"):
            m.load_state_dict({"sum": jnp.zeros(()), "bogus": jnp.zeros(())})
        # non-strict ignores extras
        m.load_state_dict({"sum": jnp.asarray(7.0), "bogus": jnp.zeros(())}, strict=False)
        np.testing.assert_allclose(np.asarray(m.compute()), 7.0)

    def test_merge_state(self):
        a, b, c = DummySumMetric(), DummySumMetric(), DummySumMetric()
        a.update(jnp.asarray(1.0))
        b.update(jnp.asarray(2.0))
        c.update(jnp.asarray(4.0))
        a.merge_state([b, c])
        np.testing.assert_allclose(np.asarray(a.compute()), 7.0)
        # sources untouched
        np.testing.assert_allclose(np.asarray(b.compute()), 2.0)

    def test_to_device(self):
        m = DummySumMetric()
        m.update(jnp.asarray(3.0))
        m.to("cpu")
        self.assertEqual(m.device.platform, "cpu")
        np.testing.assert_allclose(np.asarray(m.compute()), 3.0)
        # deque maxlen preserved through to()
        d = DummySumDequeStateMetric(maxlen=3)
        d.update(jnp.asarray(1.0))
        d.to("cpu")
        self.assertEqual(d.x.maxlen, 3)

    def test_pickle_and_deepcopy(self):
        m = DummySumListStateMetric()
        m.update(jnp.asarray([1.0, 2.0]))
        for clone in (copy.deepcopy(m), pickle.loads(pickle.dumps(m))):
            np.testing.assert_allclose(np.asarray(clone.compute()), 3.0)
            # clone is independent
            clone.update(jnp.asarray(10.0))
            np.testing.assert_allclose(np.asarray(m.compute()), 3.0)

    def test_multiple_devices_available(self):
        # conftest forces 8 CPU devices; the sync layer depends on this
        self.assertGreaterEqual(len(jax.devices()), 8)

    def test_api_usage_telemetry_once_per_class(self):
        # mirrors reference metric.py:44 (_log_api_usage_once): each metric
        # class fires the usage hook exactly once per process
        from torcheval_tpu.metrics.aggregation import Sum
        from torcheval_tpu.utils import telemetry

        seen = []
        telemetry.set_api_usage_sink(seen.append)
        try:
            telemetry._seen.discard("torcheval_tpu.metrics.Sum")
            Sum()
            Sum()  # second construction: no duplicate record
            self.assertEqual(
                seen.count("torcheval_tpu.metrics.Sum"), 1
            )
        finally:
            telemetry.set_api_usage_sink(None)

    def test_api_usage_sink_errors_do_not_break_construction(self):
        from torcheval_tpu.metrics.aggregation import Mean
        from torcheval_tpu.utils import telemetry

        def bad_sink(key):
            raise RuntimeError("boom")

        telemetry.set_api_usage_sink(bad_sink)
        try:
            telemetry._seen.discard("torcheval_tpu.metrics.Mean")
            m = Mean()  # must not raise
            self.assertIsNotNone(m)
        finally:
            telemetry.set_api_usage_sink(None)

    def test_deepcopy_preserves_shared_array_identity(self):
        # advisor r3 (low): two attributes referencing the same array object
        # must stay shared in the clone, matching copy.deepcopy semantics
        import copy

        from torcheval_tpu.metrics.aggregation import Sum

        m = Sum()
        shared = jnp.ones((3,))
        m.a_ref = shared
        m.b_ref = shared
        c = copy.deepcopy(m)
        self.assertIs(c.a_ref, c.b_ref)
        # and tuples referenced twice stay one object too
        t = (shared, 2)
        m.t1 = t
        m.t2 = t
        c2 = copy.deepcopy(m)
        self.assertIs(c2.t1, c2.t2)
        # a cycle through a tuple stays a single object, like copy.deepcopy
        lst = []
        cyc = (lst,)
        lst.append(cyc)
        m.cyc = cyc
        c3 = copy.deepcopy(m)
        self.assertIs(c3.cyc, c3.cyc[0][0])


if __name__ == "__main__":
    unittest.main()

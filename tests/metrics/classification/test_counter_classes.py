"""Tier-2 protocol tests for the counter-family class metrics.

Mirrors ``/root/reference/tests/metrics/classification/test_accuracy.py`` etc.:
one run_class_implementation_tests spec per class, expected values computed by
sklearn / numpy on the concatenated stream.
"""

import numpy as np
import jax.numpy as jnp
from sklearn.metrics import (
    accuracy_score,
    confusion_matrix as sk_confusion_matrix,
    f1_score as sk_f1,
    precision_score as sk_precision,
    recall_score as sk_recall,
)

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryF1Score,
    BinaryPrecision,
    BinaryRecall,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.utils.test_utils import (
    BATCH_SIZE,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
)

RNG = np.random.default_rng(7)
C = 5
SCORES = RNG.normal(size=(NUM_TOTAL_UPDATES, BATCH_SIZE, C)).astype(np.float32)
TARGET = RNG.integers(0, C, size=(NUM_TOTAL_UPDATES, BATCH_SIZE))
FLAT_PRED = SCORES.reshape(-1, C).argmax(1)
FLAT_TARGET = TARGET.reshape(-1)
BIN_SCORES = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
BIN_TARGET = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE))
FLAT_BIN_PRED = (BIN_SCORES.reshape(-1) >= 0.5).astype(np.int64)
FLAT_BIN_TARGET = BIN_TARGET.reshape(-1)


class TestAccuracyClasses(MetricClassTester):
    def test_multiclass_accuracy_micro(self):
        self.run_class_implementation_tests(
            metric=MulticlassAccuracy(),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=accuracy_score(FLAT_TARGET, FLAT_PRED),
        )

    def test_multiclass_accuracy_macro(self):
        self.run_class_implementation_tests(
            metric=MulticlassAccuracy(average="macro", num_classes=C),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_recall(FLAT_TARGET, FLAT_PRED, average="macro"),
        )

    def test_binary_accuracy(self):
        self.run_class_implementation_tests(
            metric=BinaryAccuracy(),
            state_names={"num_correct", "num_total"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=accuracy_score(FLAT_BIN_TARGET, FLAT_BIN_PRED),
        )

    def test_multilabel_accuracy(self):
        target = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE, 4))
        scores = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 4)).astype(np.float32)
        pred = (scores.reshape(-1, 4) >= 0.5).astype(np.int64)
        expected = (pred == target.reshape(-1, 4)).all(axis=1).mean()
        self.run_class_implementation_tests(
            metric=MultilabelAccuracy(),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(scores), "target": jnp.asarray(target)},
            compute_result=expected,
        )

    def test_topk_multilabel_accuracy(self):
        k = 3
        target = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE, C))
        flat = SCORES.reshape(-1, C)
        idx = np.argsort(-flat, axis=1, kind="stable")[:, :k]
        pred = np.zeros_like(target.reshape(-1, C))
        np.put_along_axis(pred, idx, 1, axis=1)
        expected = (pred == target.reshape(-1, C)).all(axis=1).mean()
        self.run_class_implementation_tests(
            metric=TopKMultilabelAccuracy(k=k),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(target)},
            compute_result=expected,
        )

    def test_binary_accuracy_nondefault_threshold(self):
        thr = 0.7
        pred = (BIN_SCORES.reshape(-1) >= thr).astype(np.int64)
        self.run_class_implementation_tests(
            metric=BinaryAccuracy(threshold=thr),
            state_names={"num_correct", "num_total"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=accuracy_score(FLAT_BIN_TARGET, pred),
        )

    # (MultilabelAccuracy's criteria matrix lives in TestMultilabelSpecMatrix
    # below — including the overlap empty-sets-match clause)

    def test_topk_multilabel_criteria_matrix(self):
        k = 2
        target = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE, C))
        flat = SCORES.reshape(-1, C)
        idx = np.argsort(-flat, axis=1, kind="stable")[:, :k]
        pred = np.zeros_like(target.reshape(-1, C))
        np.put_along_axis(pred, idx, 1, axis=1)
        tgt = target.reshape(-1, C)
        expectations = {
            "hamming": (pred == tgt).mean(),
            "overlap": (
                ((pred == tgt) & (pred == 1)).max(axis=1)
                | ((pred == 0) & (tgt == 0)).all(axis=1)
            ).mean(),
            "contain": ((pred - tgt) >= 0).all(axis=1).mean(),
            "belong": ((pred - tgt) <= 0).all(axis=1).mean(),
        }
        for criteria, expected in expectations.items():
            self.run_class_implementation_tests(
                metric=TopKMultilabelAccuracy(k=k, criteria=criteria),
                state_names={"num_correct", "num_total"},
                update_kwargs={
                    "input": jnp.asarray(SCORES),
                    "target": jnp.asarray(target),
                },
                compute_result=expected,
            )


class TestF1Classes(MetricClassTester):
    def test_multiclass_f1_weighted(self):
        self.run_class_implementation_tests(
            metric=MulticlassF1Score(num_classes=C, average="weighted"),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_f1(
                FLAT_TARGET, FLAT_PRED, average="weighted", zero_division=0
            ),
        )

    def test_binary_f1(self):
        self.run_class_implementation_tests(
            metric=BinaryF1Score(),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_f1(FLAT_BIN_TARGET, FLAT_BIN_PRED, zero_division=0),
        )


class TestPrecisionRecallClasses(MetricClassTester):
    def test_multiclass_precision_macro(self):
        self.run_class_implementation_tests(
            metric=MulticlassPrecision(num_classes=C, average="macro"),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_precision(
                FLAT_TARGET, FLAT_PRED, average="macro", zero_division=0
            ),
        )

    def test_binary_precision(self):
        self.run_class_implementation_tests(
            metric=BinaryPrecision(),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_precision(FLAT_BIN_TARGET, FLAT_BIN_PRED, zero_division=0),
        )

    def test_multiclass_recall_none(self):
        self.run_class_implementation_tests(
            metric=MulticlassRecall(num_classes=C, average=None),
            state_names={"num_tp", "num_labels", "num_predictions"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_recall(
                FLAT_TARGET, FLAT_PRED, average=None, zero_division=0
            ),
        )

    def test_binary_recall(self):
        self.run_class_implementation_tests(
            metric=BinaryRecall(),
            state_names={"num_tp", "num_true_labels"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_recall(FLAT_BIN_TARGET, FLAT_BIN_PRED, zero_division=0),
        )


class TestConfusionMatrixClass(MetricClassTester):
    def test_multiclass_confusion_matrix(self):
        self.run_class_implementation_tests(
            metric=MulticlassConfusionMatrix(C),
            state_names={"confusion_matrix"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_confusion_matrix(
                FLAT_TARGET, FLAT_PRED, labels=np.arange(C)
            ),
        )

    def test_binary_confusion_matrix(self):
        from torcheval_tpu.metrics import BinaryConfusionMatrix

        self.run_class_implementation_tests(
            metric=BinaryConfusionMatrix(),
            state_names={"confusion_matrix"},
            update_kwargs={
                "input": jnp.asarray((BIN_SCORES >= 0.5).astype(np.int32)),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_confusion_matrix(
                FLAT_BIN_TARGET, FLAT_BIN_PRED, labels=[0, 1]
            ),
        )

    def test_binary_confusion_matrix_threshold_and_normalize(self):
        from torcheval_tpu.metrics import BinaryConfusionMatrix

        m = BinaryConfusionMatrix(threshold=0.3, normalize="true")
        m.update(jnp.asarray(BIN_SCORES[0]), jnp.asarray(BIN_TARGET[0]))
        pred = (BIN_SCORES[0] >= 0.3).astype(np.int64)
        want = sk_confusion_matrix(
            BIN_TARGET[0], pred, labels=[0, 1], normalize="true"
        )
        np.testing.assert_allclose(np.asarray(m.compute()), want, rtol=1e-5)

    def test_multiclass_confusion_matrix_normalize_modes(self):
        for mode in ("all", "pred", "true"):
            m = MulticlassConfusionMatrix(C, normalize=mode)
            m.update(jnp.asarray(SCORES[0]), jnp.asarray(TARGET[0]))
            want = sk_confusion_matrix(
                TARGET[0],
                SCORES[0].argmax(1),
                labels=np.arange(C),
                normalize=mode,
            )
            np.testing.assert_allclose(
                np.asarray(m.compute()), want, rtol=1e-5, err_msg=mode
            )


class TestAccuracySpecMatrix(MetricClassTester):
    """Reference-style per-metric spec matrix
    (``tests/metrics/classification/test_accuracy.py:25-61``): k>1, per-class
    averaging, macro over scores."""

    def test_multiclass_accuracy_k3(self):
        k = 3
        topk = np.argsort(-SCORES.reshape(-1, C), axis=1)[:, :k]
        want = float((topk == FLAT_TARGET[:, None]).any(1).mean())
        self.run_class_implementation_tests(
            metric=MulticlassAccuracy(num_classes=C, k=k),
            state_names={"num_correct", "num_total"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=want,
        )

    def test_multiclass_accuracy_average_none(self):
        correct = np.zeros(C)
        total = np.zeros(C)
        for cls in range(C):
            mask = FLAT_TARGET == cls
            total[cls] = mask.sum()
            correct[cls] = (FLAT_PRED[mask] == cls).sum()
        want = np.where(total > 0, correct / np.maximum(total, 1), np.nan)
        self.run_class_implementation_tests(
            metric=MulticlassAccuracy(num_classes=C, average=None),
            state_names={"num_correct", "num_total"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=want,
        )

    def test_binary_accuracy_threshold(self):
        thr = 0.7
        self.run_class_implementation_tests(
            metric=BinaryAccuracy(threshold=thr),
            state_names={"num_correct", "num_total"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=accuracy_score(
                FLAT_BIN_TARGET, (BIN_SCORES.reshape(-1) >= thr).astype(int)
            ),
        )


class TestPrecisionRecallSpecMatrix(MetricClassTester):
    def test_multiclass_precision_none(self):
        self.run_class_implementation_tests(
            metric=MulticlassPrecision(num_classes=C, average=None),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=sk_precision(
                FLAT_TARGET, FLAT_PRED, average=None, zero_division=0
            ),
        )

    def test_multiclass_precision_weighted(self):
        self.run_class_implementation_tests(
            metric=MulticlassPrecision(num_classes=C, average="weighted"),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=sk_precision(
                FLAT_TARGET, FLAT_PRED, average="weighted", zero_division=0
            ),
        )

    def test_multiclass_recall_weighted(self):
        self.run_class_implementation_tests(
            metric=MulticlassRecall(num_classes=C, average="weighted"),
            state_names={"num_tp", "num_labels", "num_predictions"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=sk_recall(
                FLAT_TARGET, FLAT_PRED, average="weighted", zero_division=0
            ),
        )

    def test_multiclass_f1_micro_and_none(self):
        self.run_class_implementation_tests(
            metric=MulticlassF1Score(num_classes=C),  # micro default
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=sk_f1(
                FLAT_TARGET, FLAT_PRED, average="micro", zero_division=0
            ),
        )
        self.run_class_implementation_tests(
            metric=MulticlassF1Score(num_classes=C, average=None),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={
                "input": jnp.asarray(SCORES),
                "target": jnp.asarray(TARGET),
            },
            compute_result=sk_f1(
                FLAT_TARGET, FLAT_PRED, average=None, zero_division=0
            ),
        )


class TestMultilabelSpecMatrix(MetricClassTester):
    ML_SCORES = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 4)).astype(np.float32)
    ML_TARGET = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE, 4))
    # plant a guaranteed all-zero (pred AND target) row: overlap's
    # empty-sets-match clause must actually be exercised, not left to the
    # ~0.4%/row chance of the random draw
    ML_SCORES[0, 0] = 0.0
    ML_TARGET[0, 0] = 0

    def _expected(self, criteria):
        pred = (self.ML_SCORES.reshape(-1, 4) >= 0.5).astype(np.int64)
        tg = self.ML_TARGET.reshape(-1, 4)
        inter = (pred & tg).sum(1)
        if criteria == "exact_match":
            return float((pred == tg).all(1).mean())
        if criteria == "hamming":
            return float((pred == tg).mean())
        if criteria == "overlap":
            # empty prediction AND empty target is a match (reference
            # accuracy.py overlap semantics)
            both_empty = (pred.sum(1) == 0) & (tg.sum(1) == 0)
            return float(((inter > 0) | both_empty).mean())
        if criteria == "contain":
            return float((inter == tg.sum(1)).mean())
        if criteria == "belong":
            return float((inter == pred.sum(1)).mean())
        raise AssertionError(criteria)

    def test_all_criteria(self):
        for criteria in ("exact_match", "hamming", "overlap", "contain", "belong"):
            with self.subTest(criteria=criteria):
                self.run_class_implementation_tests(
                    metric=MultilabelAccuracy(criteria=criteria),
                    state_names={"num_correct", "num_total"},
                    update_kwargs={
                        "input": jnp.asarray(self.ML_SCORES),
                        "target": jnp.asarray(self.ML_TARGET),
                    },
                    compute_result=self._expected(criteria),
                )

    def test_invalid_criteria(self):
        with self.assertRaisesRegex(ValueError, "criteria"):
            MultilabelAccuracy(criteria="bogus")

"""Tier-2 protocol tests for the counter-family class metrics.

Mirrors ``/root/reference/tests/metrics/classification/test_accuracy.py`` etc.:
one run_class_implementation_tests spec per class, expected values computed by
sklearn / numpy on the concatenated stream.
"""

import numpy as np
import jax.numpy as jnp
from sklearn.metrics import (
    accuracy_score,
    confusion_matrix as sk_confusion_matrix,
    f1_score as sk_f1,
    precision_score as sk_precision,
    recall_score as sk_recall,
)

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryF1Score,
    BinaryPrecision,
    BinaryRecall,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    TopKMultilabelAccuracy,
)
from torcheval_tpu.utils.test_utils import (
    BATCH_SIZE,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
)

RNG = np.random.default_rng(7)
C = 5
SCORES = RNG.normal(size=(NUM_TOTAL_UPDATES, BATCH_SIZE, C)).astype(np.float32)
TARGET = RNG.integers(0, C, size=(NUM_TOTAL_UPDATES, BATCH_SIZE))
FLAT_PRED = SCORES.reshape(-1, C).argmax(1)
FLAT_TARGET = TARGET.reshape(-1)
BIN_SCORES = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
BIN_TARGET = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE))
FLAT_BIN_PRED = (BIN_SCORES.reshape(-1) >= 0.5).astype(np.int64)
FLAT_BIN_TARGET = BIN_TARGET.reshape(-1)


class TestAccuracyClasses(MetricClassTester):
    def test_multiclass_accuracy_micro(self):
        self.run_class_implementation_tests(
            metric=MulticlassAccuracy(),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=accuracy_score(FLAT_TARGET, FLAT_PRED),
        )

    def test_multiclass_accuracy_macro(self):
        self.run_class_implementation_tests(
            metric=MulticlassAccuracy(average="macro", num_classes=C),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_recall(FLAT_TARGET, FLAT_PRED, average="macro"),
        )

    def test_binary_accuracy(self):
        self.run_class_implementation_tests(
            metric=BinaryAccuracy(),
            state_names={"num_correct", "num_total"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=accuracy_score(FLAT_BIN_TARGET, FLAT_BIN_PRED),
        )

    def test_multilabel_accuracy(self):
        target = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE, 4))
        scores = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 4)).astype(np.float32)
        pred = (scores.reshape(-1, 4) >= 0.5).astype(np.int64)
        expected = (pred == target.reshape(-1, 4)).all(axis=1).mean()
        self.run_class_implementation_tests(
            metric=MultilabelAccuracy(),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(scores), "target": jnp.asarray(target)},
            compute_result=expected,
        )

    def test_topk_multilabel_accuracy(self):
        k = 3
        target = RNG.integers(0, 2, size=(NUM_TOTAL_UPDATES, BATCH_SIZE, C))
        flat = SCORES.reshape(-1, C)
        idx = np.argsort(-flat, axis=1, kind="stable")[:, :k]
        pred = np.zeros_like(target.reshape(-1, C))
        np.put_along_axis(pred, idx, 1, axis=1)
        expected = (pred == target.reshape(-1, C)).all(axis=1).mean()
        self.run_class_implementation_tests(
            metric=TopKMultilabelAccuracy(k=k),
            state_names={"num_correct", "num_total"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(target)},
            compute_result=expected,
        )


class TestF1Classes(MetricClassTester):
    def test_multiclass_f1_weighted(self):
        self.run_class_implementation_tests(
            metric=MulticlassF1Score(num_classes=C, average="weighted"),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_f1(
                FLAT_TARGET, FLAT_PRED, average="weighted", zero_division=0
            ),
        )

    def test_binary_f1(self):
        self.run_class_implementation_tests(
            metric=BinaryF1Score(),
            state_names={"num_tp", "num_label", "num_prediction"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_f1(FLAT_BIN_TARGET, FLAT_BIN_PRED, zero_division=0),
        )


class TestPrecisionRecallClasses(MetricClassTester):
    def test_multiclass_precision_macro(self):
        self.run_class_implementation_tests(
            metric=MulticlassPrecision(num_classes=C, average="macro"),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_precision(
                FLAT_TARGET, FLAT_PRED, average="macro", zero_division=0
            ),
        )

    def test_binary_precision(self):
        self.run_class_implementation_tests(
            metric=BinaryPrecision(),
            state_names={"num_tp", "num_fp", "num_label"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_precision(FLAT_BIN_TARGET, FLAT_BIN_PRED, zero_division=0),
        )

    def test_multiclass_recall_none(self):
        self.run_class_implementation_tests(
            metric=MulticlassRecall(num_classes=C, average=None),
            state_names={"num_tp", "num_labels", "num_predictions"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_recall(
                FLAT_TARGET, FLAT_PRED, average=None, zero_division=0
            ),
        )

    def test_binary_recall(self):
        self.run_class_implementation_tests(
            metric=BinaryRecall(),
            state_names={"num_tp", "num_true_labels"},
            update_kwargs={
                "input": jnp.asarray(BIN_SCORES),
                "target": jnp.asarray(BIN_TARGET),
            },
            compute_result=sk_recall(FLAT_BIN_TARGET, FLAT_BIN_PRED, zero_division=0),
        )


class TestConfusionMatrixClass(MetricClassTester):
    def test_multiclass_confusion_matrix(self):
        self.run_class_implementation_tests(
            metric=MulticlassConfusionMatrix(C),
            state_names={"confusion_matrix"},
            update_kwargs={"input": jnp.asarray(SCORES), "target": jnp.asarray(TARGET)},
            compute_result=sk_confusion_matrix(
                FLAT_TARGET, FLAT_PRED, labels=np.arange(C)
            ),
        )

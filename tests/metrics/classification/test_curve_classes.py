"""Curve/entropy class metrics through the protocol harness (tier 2)."""

import numpy as np
from sklearn.metrics import (
    average_precision_score,
    precision_recall_curve as sk_prc,
    roc_auc_score,
)

from torcheval_tpu.metrics import (
    BinaryAUPRC,
    BinaryAUROC,
    BinaryBinnedPrecisionRecallCurve,
    BinaryNormalizedEntropy,
    BinaryPrecisionRecallCurve,
    MulticlassBinnedPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
)
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    BATCH_SIZE,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
)

RNG = np.random.default_rng(30)


def _binary_data():
    x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
    t = RNG.integers(0, 2, (NUM_TOTAL_UPDATES, BATCH_SIZE))
    return x, t


class TestBinaryAUROCClass(MetricClassTester):
    def test_auroc(self):
        x, t = _binary_data()
        self.run_class_implementation_tests(
            metric=BinaryAUROC(),
            state_names={"inputs", "targets"},
            update_kwargs={"input": x, "target": t},
            compute_result=roc_auc_score(t.reshape(-1), x.reshape(-1)),
        )

    def test_empty_compute(self):
        self.assertEqual(float(BinaryAUROC().compute()), 0.5)


class TestBinaryAUPRCClass(MetricClassTester):
    def test_auprc(self):
        x, t = _binary_data()
        self.run_class_implementation_tests(
            metric=BinaryAUPRC(),
            state_names={"inputs", "targets"},
            update_kwargs={"input": x, "target": t},
            compute_result=average_precision_score(t.reshape(-1), x.reshape(-1)),
        )


class TestBinaryPRCClass(MetricClassTester):
    def test_prc(self):
        x, t = _binary_data()
        skp, skr, skt = sk_prc(t.reshape(-1), x.reshape(-1))
        self.run_class_implementation_tests(
            metric=BinaryPrecisionRecallCurve(),
            state_names={"inputs", "targets"},
            update_kwargs={"input": x, "target": t},
            compute_result=(skp, skr, skt),
        )


class TestMulticlassPRCClass(MetricClassTester):
    def test_prc(self):
        c = 4
        x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, c)).astype(np.float32)
        t = RNG.integers(0, c, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        flat_x, flat_t = x.reshape(-1, c), t.reshape(-1)
        ps, rs, ts = [], [], []
        for k in range(c):
            p, r, th = sk_prc((flat_t == k).astype(int), flat_x[:, k])
            ps.append(p)
            rs.append(r)
            ts.append(th)
        self.run_class_implementation_tests(
            metric=MulticlassPrecisionRecallCurve(num_classes=c),
            state_names={"inputs", "targets"},
            update_kwargs={"input": x, "target": t},
            compute_result=(ps, rs, ts),
        )


class TestBinnedPRCClasses(MetricClassTester):
    def test_binary_binned(self):
        x, t = _binary_data()
        from torcheval_tpu.metrics.functional import (
            binary_binned_precision_recall_curve,
        )

        p, r, th = binary_binned_precision_recall_curve(
            x.reshape(-1), t.reshape(-1), threshold=10
        )
        self.run_class_implementation_tests(
            metric=BinaryBinnedPrecisionRecallCurve(threshold=10),
            state_names={"threshold", "num_tp", "num_fp", "num_fn"},
            update_kwargs={"input": x, "target": t},
            compute_result=(p, r, th),
        )

    def test_multiclass_binned(self):
        c = 3
        x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, c)).astype(np.float32)
        t = RNG.integers(0, c, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        from torcheval_tpu.metrics.functional import (
            multiclass_binned_precision_recall_curve,
        )

        ps, rs, th = multiclass_binned_precision_recall_curve(
            x.reshape(-1, c), t.reshape(-1), num_classes=c, threshold=7
        )
        self.run_class_implementation_tests(
            metric=MulticlassBinnedPrecisionRecallCurve(c, threshold=7),
            state_names={"threshold", "num_tp", "num_fp", "num_fn"},
            update_kwargs={"input": x, "target": t},
            compute_result=(ps, rs, th),
        )


class TestBinaryNormalizedEntropyClass(MetricClassTester):
    def test_ne(self):
        x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        t = RNG.integers(0, 2, (NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        flat_x, flat_t = x.reshape(-1).astype(np.float64), t.reshape(-1).astype(np.float64)
        ce = -np.mean(flat_t * np.log(flat_x) + (1 - flat_t) * np.log1p(-flat_x))
        p = flat_t.mean()
        baseline = -p * np.log(p) - (1 - p) * np.log(1 - p)
        self.run_class_implementation_tests(
            metric=BinaryNormalizedEntropy(),
            state_names={"total_entropy", "num_examples", "num_positive"},
            update_kwargs={"input": x, "target": t},
            compute_result=np.array([ce / baseline]),
            atol=1e-4,
            rtol=1e-3,
        )

    def test_ne_weighted_logits_multitask(self):
        x = RNG.standard_normal((NUM_TOTAL_UPDATES, 2, BATCH_SIZE)).astype(np.float32)
        t = RNG.integers(0, 2, (NUM_TOTAL_UPDATES, 2, BATCH_SIZE)).astype(np.float32)
        w = RNG.random((NUM_TOTAL_UPDATES, 2, BATCH_SIZE)).astype(np.float32)
        prob = 1 / (1 + np.exp(-x.astype(np.float64)))
        ce_terms = -(t * np.log(prob) + (1 - t) * np.log1p(-prob)) * w
        # fold over (updates, samples) per task
        tot = ce_terms.transpose(1, 0, 2).reshape(2, -1).sum(1)
        wsum = w.transpose(1, 0, 2).reshape(2, -1).sum(1)
        wpos = (w * t).transpose(1, 0, 2).reshape(2, -1).sum(1)
        pr = np.clip(wpos / wsum, 1e-12, 1 - 1e-12)
        baseline = -pr * np.log(pr) - (1 - pr) * np.log(1 - pr)
        expected = (tot / wsum) / baseline
        self.run_class_implementation_tests(
            metric=BinaryNormalizedEntropy(from_logits=True, num_tasks=2),
            state_names={"total_entropy", "num_examples", "num_positive"},
            update_kwargs={"input": x, "target": t, "weight": w},
            compute_result=expected,
            atol=1e-4,
            rtol=1e-3,
        )

    def test_empty_compute(self):
        self.assertEqual(BinaryNormalizedEntropy().compute().shape, (0,))

"""Curve/entropy class metrics through the protocol harness (tier 2)."""

import unittest

import jax.numpy as jnp
import numpy as np
from sklearn.metrics import (
    average_precision_score,
    precision_recall_curve as sk_prc,
    roc_auc_score,
)

from torcheval_tpu.metrics import (
    BinaryAUPRC,
    BinaryAUROC,
    BinaryBinnedPrecisionRecallCurve,
    BinaryNormalizedEntropy,
    BinaryPrecisionRecallCurve,
    MulticlassAUPRC,
    MulticlassAUROC,
    MulticlassBinnedPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
)
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    BATCH_SIZE,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
)

RNG = np.random.default_rng(30)


def _binary_data():
    x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
    t = RNG.integers(0, 2, (NUM_TOTAL_UPDATES, BATCH_SIZE))
    return x, t


class TestBinaryAUROCClass(MetricClassTester):
    def test_auroc(self):
        x, t = _binary_data()
        self.run_class_implementation_tests(
            metric=BinaryAUROC(),
            state_names={"inputs", "targets", "summary_scores", "summary_tp", "summary_fp", "summary_nan_dropped"},
            update_kwargs={"input": x, "target": t},
            compute_result=roc_auc_score(t.reshape(-1), x.reshape(-1)),
        )

    def test_empty_compute(self):
        self.assertEqual(float(BinaryAUROC().compute()), 0.5)


class TestBinaryAUPRCClass(MetricClassTester):
    def test_auprc(self):
        x, t = _binary_data()
        self.run_class_implementation_tests(
            metric=BinaryAUPRC(),
            state_names={"inputs", "targets", "summary_scores", "summary_tp", "summary_fp", "summary_nan_dropped"},
            update_kwargs={"input": x, "target": t},
            compute_result=average_precision_score(t.reshape(-1), x.reshape(-1)),
        )


class TestBinaryPRCClass(MetricClassTester):
    def test_prc(self):
        x, t = _binary_data()
        skp, skr, skt = sk_prc(t.reshape(-1), x.reshape(-1))
        self.run_class_implementation_tests(
            metric=BinaryPrecisionRecallCurve(),
            state_names={"inputs", "targets"},
            update_kwargs={"input": x, "target": t},
            compute_result=(skp, skr, skt),
        )


class TestMulticlassPRCClass(MetricClassTester):
    def test_prc(self):
        c = 4
        x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, c)).astype(np.float32)
        t = RNG.integers(0, c, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        flat_x, flat_t = x.reshape(-1, c), t.reshape(-1)
        ps, rs, ts = [], [], []
        for k in range(c):
            p, r, th = sk_prc((flat_t == k).astype(int), flat_x[:, k])
            ps.append(p)
            rs.append(r)
            ts.append(th)
        self.run_class_implementation_tests(
            metric=MulticlassPrecisionRecallCurve(num_classes=c),
            state_names={"inputs", "targets"},
            update_kwargs={"input": x, "target": t},
            compute_result=(ps, rs, ts),
        )


class TestBinnedPRCClasses(MetricClassTester):
    def test_binary_binned(self):
        x, t = _binary_data()
        from torcheval_tpu.metrics.functional import (
            binary_binned_precision_recall_curve,
        )

        p, r, th = binary_binned_precision_recall_curve(
            x.reshape(-1), t.reshape(-1), threshold=10
        )
        self.run_class_implementation_tests(
            metric=BinaryBinnedPrecisionRecallCurve(threshold=10),
            state_names={"threshold", "num_tp", "num_fp", "num_fn"},
            update_kwargs={"input": x, "target": t},
            compute_result=(p, r, th),
        )

    def test_multiclass_binned(self):
        c = 3
        x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE, c)).astype(np.float32)
        t = RNG.integers(0, c, (NUM_TOTAL_UPDATES, BATCH_SIZE))
        from torcheval_tpu.metrics.functional import (
            multiclass_binned_precision_recall_curve,
        )

        ps, rs, th = multiclass_binned_precision_recall_curve(
            x.reshape(-1, c), t.reshape(-1), num_classes=c, threshold=7
        )
        self.run_class_implementation_tests(
            metric=MulticlassBinnedPrecisionRecallCurve(c, threshold=7),
            state_names={"threshold", "num_tp", "num_fp", "num_fn"},
            update_kwargs={"input": x, "target": t},
            compute_result=(ps, rs, th),
        )


class TestBinaryNormalizedEntropyClass(MetricClassTester):
    def test_ne(self):
        x = RNG.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        t = RNG.integers(0, 2, (NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        flat_x, flat_t = x.reshape(-1).astype(np.float64), t.reshape(-1).astype(np.float64)
        ce = -np.mean(flat_t * np.log(flat_x) + (1 - flat_t) * np.log1p(-flat_x))
        p = flat_t.mean()
        baseline = -p * np.log(p) - (1 - p) * np.log(1 - p)
        self.run_class_implementation_tests(
            metric=BinaryNormalizedEntropy(),
            state_names={"total_entropy", "num_examples", "num_positive"},
            update_kwargs={"input": x, "target": t},
            compute_result=np.array([ce / baseline]),
            atol=1e-4,
            rtol=1e-3,
        )

    def test_ne_weighted_logits_multitask(self):
        x = RNG.standard_normal((NUM_TOTAL_UPDATES, 2, BATCH_SIZE)).astype(np.float32)
        t = RNG.integers(0, 2, (NUM_TOTAL_UPDATES, 2, BATCH_SIZE)).astype(np.float32)
        w = RNG.random((NUM_TOTAL_UPDATES, 2, BATCH_SIZE)).astype(np.float32)
        prob = 1 / (1 + np.exp(-x.astype(np.float64)))
        ce_terms = -(t * np.log(prob) + (1 - t) * np.log1p(-prob)) * w
        # fold over (updates, samples) per task
        tot = ce_terms.transpose(1, 0, 2).reshape(2, -1).sum(1)
        wsum = w.transpose(1, 0, 2).reshape(2, -1).sum(1)
        wpos = (w * t).transpose(1, 0, 2).reshape(2, -1).sum(1)
        pr = np.clip(wpos / wsum, 1e-12, 1 - 1e-12)
        baseline = -pr * np.log(pr) - (1 - pr) * np.log(1 - pr)
        expected = (tot / wsum) / baseline
        self.run_class_implementation_tests(
            metric=BinaryNormalizedEntropy(from_logits=True, num_tasks=2),
            state_names={"total_entropy", "num_examples", "num_positive"},
            update_kwargs={"input": x, "target": t, "weight": w},
            compute_result=expected,
            atol=1e-4,
            rtol=1e-3,
        )

    def test_empty_compute(self):
        self.assertEqual(BinaryNormalizedEntropy().compute().shape, (0,))


class TestCurveCompaction(unittest.TestCase):
    """The bounded-memory exact path (compaction_threshold): parity with the
    raw cache and sklearn, merge across mixed configurations, pre-sync
    compaction (VERDICT r1 missing #2 — the 1B north-star mechanism)."""

    def _data(self):
        rng = np.random.default_rng(11)
        x = (rng.random(4000) * 200).astype(np.int32) / 200.0  # forced ties
        t = (rng.random(4000) < 0.35).astype(np.float32)
        return x, t

    def test_auroc_compaction_parity(self):
        x, t = self._data()
        raw, comp = BinaryAUROC(), BinaryAUROC(compaction_threshold=500)
        for i in range(0, 4000, 250):
            raw.update(x[i : i + 250], t[i : i + 250])
            comp.update(x[i : i + 250], t[i : i + 250])
        self.assertTrue(comp.summary_scores)  # compaction actually happened
        self.assertFalse(comp.inputs)
        self.assertAlmostEqual(
            float(comp.compute()), float(raw.compute()), places=6
        )
        self.assertAlmostEqual(
            float(comp.compute()), roc_auc_score(t, x), places=6
        )

    def test_auprc_compaction_parity(self):
        x, t = self._data()
        raw, comp = BinaryAUPRC(), BinaryAUPRC(compaction_threshold=700)
        for i in range(0, 4000, 400):
            raw.update(x[i : i + 400], t[i : i + 400])
            comp.update(x[i : i + 400], t[i : i + 400])
        self.assertAlmostEqual(
            float(comp.compute()), float(raw.compute()), places=6
        )
        self.assertAlmostEqual(
            float(comp.compute()), average_precision_score(t, x), places=5
        )

    def test_merge_mixed_compacted_and_raw(self):
        x, t = self._data()
        a = BinaryAUROC(compaction_threshold=300)
        a.update(x[:2000], t[:2000])
        b = BinaryAUROC()
        b.update(x[2000:], t[2000:])
        merged = a.merge_state([b])
        self.assertAlmostEqual(
            float(merged.compute()), roc_auc_score(t, x), places=6
        )

    def test_prepare_for_merge_state_compacts(self):
        x, t = self._data()
        m = BinaryAUROC(compaction_threshold=10_000)  # above cache size
        m.update(x, t)
        self.assertTrue(m.inputs)
        m._prepare_for_merge_state()
        self.assertFalse(m.inputs)  # raw cache folded into the summary
        self.assertEqual(len(m.summary_scores), 1)
        self.assertAlmostEqual(float(m.compute()), roc_auc_score(t, x), places=6)

    def test_reset_clears_summary(self):
        x, t = self._data()
        m = BinaryAUROC(compaction_threshold=100)
        m.update(x, t)
        m.reset()
        self.assertEqual(
            (m.inputs, m.summary_scores, float(m.compute())), ([], [], 0.5)
        )

    def test_invalid_threshold(self):
        with self.assertRaisesRegex(ValueError, "compaction_threshold"):
            BinaryAUROC(compaction_threshold=0)

    def test_neg_inf_scores_survive_compaction(self):
        # regression: -inf (a legal log-prob score) must not be eaten by the
        # padding sentinel during compaction
        x = np.array([0.9, -np.inf, 0.4, -np.inf, 0.1, 0.7] * 4, np.float32)
        t = np.array([1, 1, 0, 0, 0, 1] * 4, np.float32)
        raw, comp = BinaryAUROC(), BinaryAUROC(compaction_threshold=6)
        raw.update(x, t)
        for i in range(0, len(x), 6):
            comp.update(x[i : i + 6], t[i : i + 6])
        self.assertAlmostEqual(
            float(comp.compute()), float(raw.compute()), places=6
        )

    def test_merge_fed_accumulator_still_compacts(self):
        # an accumulator fed only via merge_state must keep enforcing the
        # memory bound (cache counter maintained across merge/reset/load)
        x, t = self._data()
        acc = BinaryAUROC(compaction_threshold=1000)
        for i in range(0, 4000, 500):
            w = BinaryAUROC()
            w.update(x[i : i + 500], t[i : i + 500])
            acc.merge_state([w])
        self.assertTrue(acc.summary_scores)  # compaction fired on merges
        self.assertLess(sum(a.shape[0] for a in acc.inputs), 1000)
        self.assertAlmostEqual(
            float(acc.compute()), roc_auc_score(t, x), places=6
        )
        acc.reset()
        self.assertEqual(acc._cached_samples, 0)
        # load_state_dict recounts the cache
        src = BinaryAUROC(compaction_threshold=1000)
        src.update(x[:400], t[:400])
        acc.load_state_dict(src.state_dict())
        self.assertEqual(acc._cached_samples, 400)


class TestCurveClassErrorPaths(unittest.TestCase):
    """Invalid-input asserts for the curve classes (VERDICT r1 weak #4:
    reference-style error-path coverage)."""

    def test_auroc_shape_mismatch(self):
        with self.assertRaisesRegex(ValueError, "same shape"):
            BinaryAUROC().update(np.zeros(3), np.zeros(4))

    def test_auroc_2d_input(self):
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            BinaryAUROC().update(np.zeros((2, 2)), np.zeros((2, 2)))

    def test_auprc_shape_mismatch(self):
        with self.assertRaisesRegex(ValueError, "same shape"):
            BinaryAUPRC().update(np.zeros(3), np.zeros(4))

    def test_prc_shape_mismatch(self):
        with self.assertRaisesRegex(ValueError, "same shape"):
            BinaryPrecisionRecallCurve().update(np.zeros(3), np.zeros(4))

    def test_binned_prc_bad_threshold(self):
        from torcheval_tpu.metrics import BinaryBinnedPrecisionRecallCurve

        with self.assertRaisesRegex(ValueError, "sorted"):
            BinaryBinnedPrecisionRecallCurve(threshold=np.array([0.9, 0.1]))
        with self.assertRaisesRegex(ValueError, "range"):
            BinaryBinnedPrecisionRecallCurve(threshold=np.array([0.1, 1.5]))

    def test_multiclass_prc_wrong_class_count(self):
        from torcheval_tpu.metrics import MulticlassPrecisionRecallCurve

        m = MulticlassPrecisionRecallCurve(num_classes=4)
        with self.assertRaisesRegex(ValueError, "num_classes"):
            m.update(np.zeros((8, 3)), np.zeros(8, dtype=np.int64))

    def test_ne_invalid_inputs(self):
        from torcheval_tpu.metrics import BinaryNormalizedEntropy

        m = BinaryNormalizedEntropy()
        with self.assertRaisesRegex(ValueError, "probability"):
            m.update(np.array([1.5, 0.5]), np.array([1.0, 0.0]))
        m2 = BinaryNormalizedEntropy(num_tasks=2)
        with self.assertRaisesRegex(ValueError, "num_tasks"):
            m2.update(np.zeros(4), np.zeros(4))


class TestCompactionNanFlag(unittest.TestCase):
    def test_nan_scores_raise_at_compute(self):
        # NaN samples reaching a compaction are recorded device-side and
        # raised at compute() (round 3: the per-compaction host check became
        # a deferred flag)
        m = BinaryAUROC(compaction_threshold=10)
        x = np.linspace(0, 1, 20).astype(np.float32)
        x[3] = np.nan
        m.update(jnp.asarray(x), jnp.asarray((x > 0.5).astype(np.float32)))
        with self.assertRaisesRegex(ValueError, "NaN scores reached"):
            m.compute()

    def test_nan_flag_survives_state_dict_roundtrip(self):
        m = BinaryAUROC(compaction_threshold=10)
        x = np.linspace(0, 1, 20).astype(np.float32)
        x[3] = np.nan
        m.update(jnp.asarray(x), jnp.asarray((x > 0.5).astype(np.float32)))
        fresh = BinaryAUROC(compaction_threshold=10)
        fresh.load_state_dict(m.state_dict())
        with self.assertRaisesRegex(ValueError, "NaN scores reached"):
            fresh.compute()

    def test_clean_stream_never_syncs_at_compute(self):
        m = BinaryAUROC(compaction_threshold=10)
        x = np.linspace(0, 1, 25).astype(np.float32)
        m.update(jnp.asarray(x), jnp.asarray((x > 0.5).astype(np.float32)))
        v1 = float(m.compute())
        self.assertTrue(m._nan_checked)  # second compute skips the host read
        v2 = float(m.compute())
        self.assertEqual(v1, v2)

    def test_nan_flag_raises_on_every_compute(self):
        # a swallowed first error must not yield silent NaN-dropped results
        m = BinaryAUROC(compaction_threshold=4)
        m.update(
            jnp.asarray(np.array([0.1, np.nan, 0.3, 0.4], np.float32)),
            jnp.asarray(np.array([0, 1, 0, 1], np.float32)),
        )
        for _ in range(2):
            with self.assertRaisesRegex(ValueError, "NaN scores reached"):
                m.compute()

    def test_nan_counter_does_not_recount_on_recompaction(self):
        # the dropped NaN row's counts must not persist in the stored
        # summary: repeated compactions keep the counter at exactly 1 and
        # the clean samples' totals uncorrupted (round-3 review finding)
        m = BinaryAUROC(compaction_threshold=4)
        x = np.array([0.1, np.nan, 0.3, 0.4], np.float32)
        t = np.array([0, 1, 0, 1], np.float32)
        m.update(jnp.asarray(x), jnp.asarray(t))
        for _ in range(3):  # force re-compactions over the stored summary
            m._compact()
        self.assertEqual(int(m.summary_nan_dropped), 1)
        self.assertEqual(int(sum(np.asarray(a).sum() for a in m.summary_tp)), 1)

    def test_synced_clone_with_installed_flag_raises(self):
        # a clone that never compacted locally must still raise when a
        # nonzero flag is INSTALLED into it (the toolkit sync path)
        import copy

        src = BinaryAUROC(compaction_threshold=4)
        x = np.array([0.1, np.nan, 0.3, 0.4], np.float32)
        src.update(jnp.asarray(x), jnp.asarray((x > 0.2).astype(np.float32)))
        clean = BinaryAUROC(compaction_threshold=4)
        clean.update(jnp.asarray(x[:1]), jnp.asarray(np.ones(1, np.float32)))
        self.assertTrue(clean._nan_checked)  # never compacted: clean cache
        synced = copy.deepcopy(clean)
        synced._set_states({"summary_nan_dropped": src.summary_nan_dropped})
        with self.assertRaisesRegex(ValueError, "NaN scores reached"):
            synced.compute()


class TestMulticlassCurveCompaction(unittest.TestCase):
    """Bounded-state multiclass curves (round-4 verdict weak #6): per-class
    exact summaries via the binary machinery vmapped over classes."""

    def _data(self, n=4000, c=5):
        rng = np.random.default_rng(21)
        x = ((rng.random((n, c)) * 150).astype(np.int32) / 150.0).astype(
            np.float32
        )  # forced ties per class
        t = rng.integers(0, c, n)
        return x, t

    def test_auroc_compaction_parity_vs_raw_and_sklearn(self):
        import sklearn.metrics as sk

        from torcheval_tpu.metrics import MulticlassAUROC

        x, t = self._data()
        raw = MulticlassAUROC(num_classes=5, average=None)
        comp = MulticlassAUROC(
            num_classes=5, average=None, compaction_threshold=600
        )
        for i in range(0, 4000, 400):
            raw.update(x[i : i + 400], t[i : i + 400])
            comp.update(x[i : i + 400], t[i : i + 400])
        self.assertTrue(comp.summary_scores)  # compaction actually fired
        np.testing.assert_allclose(
            np.asarray(comp.compute()), np.asarray(raw.compute()), atol=1e-6
        )
        onehot = np.eye(5)[t]
        want = sk.roc_auc_score(onehot, x, average=None)
        np.testing.assert_allclose(np.asarray(comp.compute()), want, atol=1e-6)

    def test_auprc_compaction_parity(self):
        import sklearn.metrics as sk

        from torcheval_tpu.metrics import MulticlassAUPRC

        x, t = self._data()
        comp = MulticlassAUPRC(num_classes=5, compaction_threshold=500)
        for i in range(0, 4000, 250):
            comp.update(x[i : i + 250], t[i : i + 250])
        onehot = np.eye(5)[t]
        want = sk.average_precision_score(onehot, x, average="macro")
        self.assertAlmostEqual(float(comp.compute()), float(want), places=5)

    def test_state_is_bounded(self):
        # the memory bound: after compaction, summary rows <= padded unique
        # count, NOT the sample count — feeding the same tied grid forever
        # must not grow state
        from torcheval_tpu.metrics import MulticlassAUROC

        m = MulticlassAUROC(num_classes=3, compaction_threshold=256)
        rng = np.random.default_rng(3)
        sizes = []
        for _ in range(6):
            x = ((rng.random((512, 3)) * 60).astype(np.int32) / 60.0).astype(
                np.float32
            )
            t = rng.integers(0, 3, 512)
            m.update(x, t)
            sizes.append(sum(int(a.shape[0]) for a in m.summary_scores))
        self.assertEqual(len(m.inputs), 0)
        # with ~61 distinct scores per class the padded cap stays at 64
        self.assertLessEqual(max(sizes), 128)
        self.assertEqual(sizes[-1], sizes[1])  # no growth after settling

    def test_merge_mixed_and_nan_flag(self):
        import sklearn.metrics as sk

        from torcheval_tpu.metrics import MulticlassAUROC

        x, t = self._data(2000)
        a = MulticlassAUROC(num_classes=5, compaction_threshold=300)
        a.update(x[:1000], t[:1000])
        b = MulticlassAUROC(num_classes=5)
        b.update(x[1000:], t[1000:])
        a.merge_state([b])
        onehot = np.eye(5)[t]
        self.assertAlmostEqual(
            float(a.compute()),
            float(sk.roc_auc_score(onehot, x, average="macro")),
            places=6,
        )
        # NaN-scored samples reaching a compaction raise at compute
        bad = MulticlassAUROC(num_classes=5, compaction_threshold=4)
        xb = x[:8].copy()
        xb[1, 2] = np.nan
        bad.update(xb, t[:8])
        with self.assertRaisesRegex(ValueError, "NaN scores reached"):
            bad.compute()

    def test_state_dict_roundtrip_recounts(self):
        from torcheval_tpu.metrics import MulticlassAUPRC

        x, t = self._data(600)
        src = MulticlassAUPRC(num_classes=5, compaction_threshold=250)
        src.update(x, t)
        fresh = MulticlassAUPRC(num_classes=5, compaction_threshold=250)
        fresh.load_state_dict(src.state_dict())
        np.testing.assert_allclose(
            np.asarray(fresh.compute()), np.asarray(src.compute()), atol=1e-7
        )

    def test_invalid_threshold(self):
        from torcheval_tpu.metrics import MulticlassAUROC

        with self.assertRaisesRegex(ValueError, "compaction_threshold"):
            MulticlassAUROC(num_classes=3, compaction_threshold=0)

    def test_presorted_compute_path_taken(self):
        # steady-state compacted compute must ride the sort-free vmapped
        # presorted kernels, not re-sort the known-sorted summary
        import torcheval_tpu.metrics.classification.auroc as auroc_mod
        from torcheval_tpu.metrics import MulticlassAUROC

        x, t = self._data(1200)
        m = MulticlassAUROC(num_classes=5, compaction_threshold=400)
        m.update(x, t)
        self.assertTrue(m._summary_sorted)
        calls = []
        orig = auroc_mod._mc_auroc_from_parts

        def _spy(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        auroc_mod._mc_auroc_from_parts = _spy
        try:
            v = float(m.compute())
        finally:
            auroc_mod._mc_auroc_from_parts = orig
        self.assertEqual(calls, [])  # sorting program never ran
        import sklearn.metrics as sk

        want = sk.roc_auc_score(np.eye(5)[t], x, average="macro")
        self.assertAlmostEqual(v, float(want), places=6)


class TestMulticlassAUROCClasses(MetricClassTester):
    def test_multiclass_auroc_protocol(self):
        rng = np.random.default_rng(3)
        scores = rng.random((8, 16, 5)).astype(np.float32)
        target = rng.integers(0, 5, (8, 16))
        import sklearn.metrics as sk

        flat_s = scores.reshape(-1, 5)
        flat_t = target.reshape(-1)
        onehot = np.eye(5)[flat_t]
        want = sk.roc_auc_score(onehot, flat_s, average="macro")
        self.run_class_implementation_tests(
            MulticlassAUROC(num_classes=5),
            state_names={"inputs", "targets", "summary_scores", "summary_tp", "summary_fp", "summary_nan_dropped"},
            update_kwargs={"input": scores, "target": target},
            compute_result=np.asarray(want),
        )

    def test_multiclass_auprc_protocol(self):
        rng = np.random.default_rng(4)
        scores = rng.random((8, 16, 5)).astype(np.float32)
        target = rng.integers(0, 5, (8, 16))
        import sklearn.metrics as sk

        flat_s = scores.reshape(-1, 5)
        onehot = np.eye(5)[target.reshape(-1)]
        want = sk.average_precision_score(onehot, flat_s, average="macro")
        self.run_class_implementation_tests(
            MulticlassAUPRC(num_classes=5),
            state_names={"inputs", "targets", "summary_scores", "summary_tp", "summary_fp", "summary_nan_dropped"},
            update_kwargs={"input": scores, "target": target},
            compute_result=np.asarray(want),
            atol=1e-4,
        )

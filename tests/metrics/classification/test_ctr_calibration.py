"""ClickThroughRate / WeightedCalibration (+ windowed variants).

Extensions beyond the reference snapshot; value oracles are hand
computations. The windowed variants are the shipped deque-state metrics, so
their tests double as the deque lane's real-metric coverage: window
eviction, state-dict round trips preserving ``maxlen``, bounded merges.
"""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import (
    ClickThroughRate,
    WeightedCalibration,
    WindowedClickThroughRate,
    WindowedWeightedCalibration,
)
from torcheval_tpu.metrics.functional import (
    click_through_rate,
    weighted_calibration,
)

RNG = np.random.default_rng(5)


class TestFunctional(unittest.TestCase):
    def test_ctr_unweighted(self):
        clicks = np.asarray([1, 0, 1, 1, 0], np.float32)
        got = float(click_through_rate(jnp.asarray(clicks)))
        self.assertAlmostEqual(got, 0.6, places=6)

    def test_ctr_weighted(self):
        clicks = np.asarray([1, 0, 1], np.float32)
        w = np.asarray([2.0, 1.0, 1.0], np.float32)
        got = float(click_through_rate(jnp.asarray(clicks), jnp.asarray(w)))
        self.assertAlmostEqual(got, 3.0 / 4.0, places=6)

    def test_ctr_multitask(self):
        clicks = RNG.integers(0, 2, (3, 40)).astype(np.float32)
        got = np.asarray(click_through_rate(jnp.asarray(clicks), num_tasks=3))
        np.testing.assert_allclose(got, clicks.mean(axis=1), rtol=1e-6)

    def test_ctr_empty_weight_is_zero(self):
        got = float(
            click_through_rate(jnp.asarray([1.0, 1.0]), jnp.asarray([0.0, 0.0]))
        )
        self.assertEqual(got, 0.0)

    def test_calibration_values(self):
        pred = np.asarray([0.8, 0.2, 0.5, 0.5], np.float32)
        target = np.asarray([1, 0, 1, 0], np.float32)
        got = float(
            weighted_calibration(jnp.asarray(pred), jnp.asarray(target))
        )
        self.assertAlmostEqual(got, pred.sum() / 2.0, places=6)
        w = np.asarray([1.0, 1.0, 2.0, 1.0], np.float32)
        got = float(
            weighted_calibration(
                jnp.asarray(pred), jnp.asarray(target), jnp.asarray(w)
            )
        )
        self.assertAlmostEqual(
            got, float((pred * w).sum() / (target * w).sum()), places=6
        )

    def test_calibration_no_positives_is_zero(self):
        got = float(
            weighted_calibration(
                jnp.asarray([0.5, 0.5]), jnp.asarray([0.0, 0.0])
            )
        )
        self.assertEqual(got, 0.0)

    def test_error_paths(self):
        with self.assertRaisesRegex(ValueError, "one-dimensional"):
            click_through_rate(jnp.zeros((2, 3)))
        with self.assertRaisesRegex(ValueError, "num_tasks = 2"):
            click_through_rate(jnp.zeros(3), num_tasks=2)
        with self.assertRaisesRegex(ValueError, "`weights` shape"):
            click_through_rate(jnp.zeros(3), jnp.zeros(4))
        with self.assertRaisesRegex(ValueError, "`target` shape"):
            weighted_calibration(jnp.zeros(3), jnp.zeros(4))
        with self.assertRaisesRegex(ValueError, "`weight` shape"):
            weighted_calibration(jnp.zeros(3), jnp.zeros(3), jnp.zeros(4))


class TestClassMetrics(unittest.TestCase):
    def test_ctr_streaming_and_merge(self):
        m = ClickThroughRate()
        a = RNG.integers(0, 2, 50).astype(np.float32)
        b = RNG.integers(0, 2, 30).astype(np.float32)
        m.update(jnp.asarray(a)).update(jnp.asarray(b))
        want = np.concatenate([a, b]).mean()
        self.assertAlmostEqual(float(m.compute()[0]), want, places=6)
        # merge
        x, y = ClickThroughRate(), ClickThroughRate()
        x.update(jnp.asarray(a))
        y.update(jnp.asarray(b))
        x.merge_state([y])
        self.assertAlmostEqual(float(x.compute()[0]), want, places=6)

    def test_calibration_streaming(self):
        m = WeightedCalibration()
        pred = RNG.random(60).astype(np.float32)
        target = RNG.integers(0, 2, 60).astype(np.float32)
        m.update(jnp.asarray(pred[:30]), jnp.asarray(target[:30]))
        m.update(jnp.asarray(pred[30:]), jnp.asarray(target[30:]))
        self.assertAlmostEqual(
            float(m.compute()[0]), float(pred.sum() / target.sum()), places=5
        )

    def test_constructor_errors(self):
        with self.assertRaisesRegex(ValueError, "num_tasks"):
            ClickThroughRate(num_tasks=0)
        with self.assertRaisesRegex(ValueError, "window_size"):
            WindowedClickThroughRate(window_size=0)


class TestWindowed(unittest.TestCase):
    def test_window_evicts_old_updates(self):
        m = WindowedClickThroughRate(window_size=2)
        m.update(jnp.asarray([1.0, 1.0]))  # falls out of the window
        m.update(jnp.asarray([0.0, 0.0]))
        m.update(jnp.asarray([0.0, 1.0]))
        lifetime, windowed = m.compute()
        self.assertAlmostEqual(float(lifetime[0]), 3.0 / 6.0, places=6)
        self.assertAlmostEqual(float(windowed[0]), 1.0 / 4.0, places=6)

    def test_windowed_without_lifetime(self):
        m = WindowedClickThroughRate(window_size=8, enable_lifetime=False)
        m.update(jnp.asarray([1.0, 0.0]))
        out = m.compute()  # single value, not a tuple
        self.assertAlmostEqual(float(out[0]), 0.5, places=6)

    def test_windowed_calibration(self):
        m = WindowedWeightedCalibration(window_size=1)
        m.update(jnp.asarray([0.9, 0.1]), jnp.asarray([1.0, 0.0]))
        m.update(jnp.asarray([0.4, 0.6]), jnp.asarray([1.0, 1.0]))
        lifetime, windowed = m.compute()
        self.assertAlmostEqual(float(windowed[0]), 1.0 / 2.0, places=6)
        self.assertAlmostEqual(float(lifetime[0]), 2.0 / 3.0, places=6)

    def test_state_dict_roundtrip_preserves_window(self):
        m = WindowedClickThroughRate(window_size=3)
        for i in range(5):
            m.update(jnp.asarray([float(i % 2)] * 4))
        sd = m.state_dict()
        m2 = WindowedClickThroughRate(window_size=3)
        m2.load_state_dict(sd)
        self.assertEqual(len(m2.window), 3)
        self.assertEqual(m2.window.maxlen, 3)
        for a, b in zip(m.compute(), m2.compute()):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def test_merge_bounded_by_window(self):
        a = WindowedClickThroughRate(window_size=2)
        b = WindowedClickThroughRate(window_size=2)
        a.update(jnp.asarray([1.0]))
        b.update(jnp.asarray([0.0]))
        b.update(jnp.asarray([0.0]))
        a.merge_state([b])
        # window keeps the most recent 2 entries: b's two zero updates
        _, windowed = a.compute()
        self.assertEqual(float(windowed[0]), 0.0)
        # lifetime still counts everything
        lifetime, _ = a.compute()
        self.assertAlmostEqual(float(lifetime[0]), 1.0 / 3.0, places=6)

    def test_merge_config_mismatch_rejected(self):
        # merging replicas that disagree on the window configuration would
        # silently drop lifetime counters or miscount the bound
        a = WindowedClickThroughRate(window_size=4)
        for bad in (
            WindowedClickThroughRate(window_size=2),
            WindowedClickThroughRate(window_size=4, enable_lifetime=False),
            WindowedClickThroughRate(window_size=4, num_tasks=2),
        ):
            with self.assertRaisesRegex(ValueError, "Cannot merge"):
                a.merge_state([bad])

    def test_multitask_windowed(self):
        m = WindowedClickThroughRate(num_tasks=2, window_size=4)
        data = RNG.integers(0, 2, (2, 20)).astype(np.float32)
        m.update(jnp.asarray(data))
        lifetime, windowed = m.compute()
        np.testing.assert_allclose(
            np.asarray(windowed), data.mean(axis=1), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(lifetime), data.mean(axis=1), rtol=1e-6
        )


class TestStandardProtocol(unittest.TestCase):
    """Run the 4 new classes through the standard class-metric harness:
    init/state registry, pickle + state-dict round trips, idempotent
    compute, N-way merge == single stream, merge leaves sources unmutated.
    Windowed metrics use window_size >= total updates so the bounded window
    holds every update and the merge==stream equivalence applies."""

    def _run(self, metric, state_names, update_kwargs, compute_result):
        from torcheval_tpu.utils.test_utils import MetricClassTester

        class _T(MetricClassTester):
            def runTest(self):  # pragma: no cover - invoked via _run
                pass

        t = _T()
        t.run_class_implementation_tests(
            metric=metric,
            state_names=state_names,
            update_kwargs=update_kwargs,
            compute_result=compute_result,
        )

    def test_ctr_protocol(self):
        clicks = RNG.integers(0, 2, (8, 16)).astype(np.float32)
        self._run(
            ClickThroughRate(),
            {"click_total", "weight_total"},
            {"input": jnp.asarray(clicks)},
            np.asarray([clicks.mean()], np.float32),
        )

    def test_calibration_protocol(self):
        pred = RNG.random((8, 16)).astype(np.float32)
        target = RNG.integers(0, 2, (8, 16)).astype(np.float32)
        self._run(
            WeightedCalibration(),
            {"weighted_input_sum", "weighted_label_sum"},
            {"input": jnp.asarray(pred), "target": jnp.asarray(target)},
            np.asarray([pred.sum() / target.sum()], np.float32),
        )

    def test_windowed_ctr_protocol(self):
        clicks = RNG.integers(0, 2, (8, 16)).astype(np.float32)
        want = np.asarray([clicks.mean()], np.float32)
        self._run(
            WindowedClickThroughRate(window_size=16),
            {"click_total", "weight_total", "window"},
            {"input": jnp.asarray(clicks)},
            (want, want),  # lifetime == windowed: everything fits the window
        )

    def test_windowed_calibration_protocol(self):
        pred = RNG.random((8, 16)).astype(np.float32)
        target = RNG.integers(0, 2, (8, 16)).astype(np.float32)
        want = np.asarray([pred.sum() / target.sum()], np.float32)
        self._run(
            WindowedWeightedCalibration(window_size=16),
            {"weighted_input_sum", "weighted_label_sum", "window"},
            {"input": jnp.asarray(pred), "target": jnp.asarray(target)},
            (want, want),
        )


if __name__ == "__main__":
    unittest.main()

"""Class regression metrics through the protocol harness (SURVEY §4 tier 2)."""

import jax.numpy as jnp
import numpy as np
from sklearn.metrics import mean_squared_error as sk_mse
from sklearn.metrics import r2_score as sk_r2

from torcheval_tpu.metrics import MeanSquaredError, R2Score
from torcheval_tpu.utils.test_utils.metric_class_tester import (
    BATCH_SIZE,
    NUM_TOTAL_UPDATES,
    MetricClassTester,
)


class TestMeanSquaredErrorClass(MetricClassTester):
    def test_mse_1d(self):
        rng = np.random.default_rng(10)
        input = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        target = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=MeanSquaredError(),
            state_names={"sum_squared_error", "sum_weight"},
            update_kwargs={"input": input, "target": target},
            compute_result=sk_mse(target.reshape(-1), input.reshape(-1)),
        )

    def test_mse_multioutput_raw(self):
        rng = np.random.default_rng(11)
        input = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 3)).astype(np.float32)
        target = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 3)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=MeanSquaredError(multioutput="raw_values"),
            state_names={"sum_squared_error", "sum_weight"},
            update_kwargs={"input": input, "target": target},
            compute_result=sk_mse(
                target.reshape(-1, 3), input.reshape(-1, 3), multioutput="raw_values"
            ),
        )

    def test_mse_weighted(self):
        rng = np.random.default_rng(12)
        input = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        target = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        weight = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=MeanSquaredError(),
            state_names={"sum_squared_error", "sum_weight"},
            update_kwargs={
                "input": input,
                "target": target,
                "sample_weight": weight,
            },
            compute_result=sk_mse(
                target.reshape(-1), input.reshape(-1), sample_weight=weight.reshape(-1)
            ),
        )


class TestR2ScoreClass(MetricClassTester):
    def test_r2_1d(self):
        rng = np.random.default_rng(13)
        target = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        input = (target + 0.1 * rng.standard_normal(target.shape)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=R2Score(),
            state_names={
                "sum_squared_obs",
                "sum_obs",
                "sum_squared_residual",
                "num_obs",
            },
            update_kwargs={"input": input, "target": target},
            compute_result=sk_r2(target.reshape(-1), input.reshape(-1)),
        )

    def test_r2_variance_weighted_multioutput(self):
        rng = np.random.default_rng(14)
        target = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE, 2)).astype(np.float32)
        input = (target + 0.05 * rng.standard_normal(target.shape)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=R2Score(multioutput="variance_weighted"),
            state_names={
                "sum_squared_obs",
                "sum_obs",
                "sum_squared_residual",
                "num_obs",
            },
            update_kwargs={"input": input, "target": target},
            compute_result=sk_r2(
                target.reshape(-1, 2),
                input.reshape(-1, 2),
                multioutput="variance_weighted",
            ),
            atol=1e-4,
        )

    def test_r2_adjusted(self):
        rng = np.random.default_rng(15)
        target = rng.random((NUM_TOTAL_UPDATES, BATCH_SIZE)).astype(np.float32)
        input = (target + 0.1 * rng.standard_normal(target.shape)).astype(np.float32)
        n = NUM_TOTAL_UPDATES * BATCH_SIZE
        plain = sk_r2(target.reshape(-1), input.reshape(-1))
        adjusted = 1 - (1 - plain) * (n - 1) / (n - 3 - 1)
        self.run_class_implementation_tests(
            metric=R2Score(num_regressors=3),
            state_names={
                "sum_squared_obs",
                "sum_obs",
                "sum_squared_residual",
                "num_obs",
            },
            update_kwargs={"input": input, "target": target},
            compute_result=adjusted,
        )


class TestRegressionSpecMatrix(MetricClassTester):
    def test_r2_raw_values_multioutput(self):
        rng = np.random.default_rng(60)
        x = rng.random((NUM_TOTAL_UPDATES, 16, 3)).astype(np.float32)
        y = (x + 0.1 * rng.standard_normal(x.shape)).astype(np.float32)
        flat_x, flat_y = x.reshape(-1, 3), y.reshape(-1, 3)
        want = sk_r2(flat_y, flat_x, multioutput="raw_values")
        self.run_class_implementation_tests(
            metric=R2Score(multioutput="raw_values"),
            state_names={
                "sum_squared_obs",
                "sum_obs",
                "sum_squared_residual",
                "num_obs",
            },
            update_kwargs={"input": jnp.asarray(x), "target": jnp.asarray(y)},
            compute_result=want,
        )

    def test_mse_invalid_multioutput(self):
        with self.assertRaisesRegex(ValueError, "multioutput"):
            MeanSquaredError(multioutput="bogus")

    def test_r2_invalid_params(self):
        with self.assertRaisesRegex(ValueError, "multioutput"):
            R2Score(multioutput="bogus")
        with self.assertRaisesRegex(ValueError, "num_regressors"):
            R2Score(num_regressors=-1)

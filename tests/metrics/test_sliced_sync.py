"""Sliced-state sync over the simulated 4-rank wire (ISSUE 15).

Rides the ``test_sync_quantized`` barrier-threaded wire harness: the REAL
two-round exchange — schema digest, per-rank descriptors (ragged leading
dims!), payload concatenation, per-rank decode, the post-gather sorted-union
row alignment, the per-reduction fold — everything but the transport (the
real 4-process world rides ``test_multiprocess_sync.py``'s sliced scenario).

Pinned contracts:

* ragged per-rank cohort populations (overlapping, disjoint, and EMPTY
  ranks) sync BIT-identically to a single-stream oracle for exact counter
  members and sketch members alike;
* the collective count is exactly the wire's two rounds and INDEPENDENT of
  the slice count — the slice axis rides the same SUM lanes, only wider;
* the quantized codecs (ISSUE 12/13) apply to the sliced int32 lanes as-is
  and stay lossless;
* the synced clone is a fully live sliced member: union id table installed,
  capacity adopted, further updates accepted.
"""

import unittest

import numpy as np

import torcheval_tpu.metrics.toolkit as tk
from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    SlicedMetricCollection,
)
from tests.metrics.test_sync_quantized import run_world

WORLD = 4


def _rank_batches(rank: int, pool: int = 9, n: int = 211):
    """Deterministic ragged shards: rank 2 is EMPTY; the others hold
    overlapping-but-different cohort pools."""
    if rank == 2:
        return []
    rng = np.random.default_rng(40 + rank)
    pool_ids = (np.arange(pool) + rank * (pool // 2)) * 97 - 13
    out = []
    for _ in range(2):
        ids = rng.choice(pool_ids, n)
        s = rng.random(n).astype(np.float32)
        t = (rng.random(n) < 0.5).astype(np.float32)
        out.append((ids, s, t))
    return out


def _make_col(capacity: int = 4):
    return SlicedMetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
        capacity=capacity,
    )


def _oracle(pool: int = 9):
    col = _make_col()
    for r in range(WORLD):
        for b in _rank_batches(r, pool=pool):
            col.update(*b)
    return col.compute()


class TestSlicedSync(unittest.TestCase):
    def _sync_world(self, pool=9, quantize=None):
        def fn(rank):
            col = _make_col()
            for b in _rank_batches(rank, pool=pool):
                col.update(*b)
            return tk.sync_and_compute_collection(
                dict(col.metrics), recipient_rank="all", quantize=quantize
            )

        return run_world(WORLD, fn)

    def _assert_matches_oracle(self, results, want):
        for res in results:
            for key in ("acc", "auroc"):
                got = res[key]
                # the synced union table is id-sorted; align the oracle
                order = np.argsort(want[key].slice_ids)
                np.testing.assert_array_equal(
                    got["slice_ids"], want[key].slice_ids[order]
                )
                np.testing.assert_array_equal(
                    np.asarray(got["values"]),
                    np.asarray(want[key]["values"])[order],
                )

    def test_ragged_cohorts_bit_identical_to_single_stream_oracle(self):
        results, _ = self._sync_world()
        self._assert_matches_oracle(results, _oracle())

    def test_two_rounds_independent_of_slice_count(self):
        rounds = {}
        for pool in (5, 512):
            _, sim = self._sync_world(pool=pool)
            rounds[pool] = len(sim.round_bytes) // WORLD
        # the ≤3-collective acceptance bar, obs/wire-asserted: the typed
        # exchange is exactly TWO rounds at 5 slices and at ~1500 (3 ranks
        # x 512-pool) — the slice axis widens lanes, never adds rounds
        self.assertEqual(rounds[5], 2)
        self.assertEqual(rounds[512], 2)

    def test_quantized_codecs_stay_lossless_on_sliced_lanes(self):
        results_q, sim_q = self._sync_world(quantize=True)
        self._assert_matches_oracle(results_q, _oracle())
        _, sim_raw = self._sync_world(quantize=False)
        # the sketch lanes are sparse int32 histograms: the bucket/narrow
        # codecs must actually engage (payload strictly below raw)
        self.assertLess(sim_q.round_bytes[-1], sim_raw.round_bytes[-1])

    def test_synced_clone_is_live(self):
        def fn(rank):
            c = _make_col()
            for b in _rank_batches(rank):
                c.update(*b)
            return {
                name: tk.get_synced_metric(m, recipient_rank="all")
                for name, m in c.metrics.items()
            }

        synced_all, _ = run_world(WORLD, fn)
        member = synced_all[0]["acc"]
        before = member._table.count
        self.assertGreater(before, 0)
        # keep streaming into the synced clone, new cohorts included
        member.update(
            np.asarray([0, 1], np.int32),
            np.asarray([0.9, 0.2], np.float32),
            np.asarray([1.0, 0.0], np.float32),
        )
        member.compute()

    def test_empty_rank_contributes_identity(self):
        # rank 2 never updates: its lanes are all-default with count 0 and
        # must fold as the reduce identity (asserted implicitly by the
        # oracle test; here pin the union table does NOT contain ghosts)
        results, _ = self._sync_world()
        ids = results[0]["acc"]["slice_ids"]
        want_ids = np.unique(
            np.concatenate(
                [
                    np.concatenate([b[0] for b in _rank_batches(r)])
                    for r in range(WORLD)
                    if _rank_batches(r)
                ]
            )
        )
        np.testing.assert_array_equal(ids, want_ids)


if __name__ == "__main__":
    unittest.main()

"""Sliced-state sync over the simulated 4-rank wire (ISSUE 15).

Rides the ``test_sync_quantized`` barrier-threaded wire harness: the REAL
two-round exchange — schema digest, per-rank descriptors (ragged leading
dims!), payload concatenation, per-rank decode, the post-gather sorted-union
row alignment, the per-reduction fold — everything but the transport (the
real 4-process world rides ``test_multiprocess_sync.py``'s sliced scenario).

Pinned contracts:

* ragged per-rank cohort populations (overlapping, disjoint, and EMPTY
  ranks) sync BIT-identically to a single-stream oracle for exact counter
  members and sketch members alike;
* the collective count is exactly the wire's two rounds and INDEPENDENT of
  the slice count — the slice axis rides the same SUM lanes, only wider;
* the quantized codecs (ISSUE 12/13) apply to the sliced int32 lanes as-is
  and stay lossless;
* the synced clone is a fully live sliced member: union id table installed,
  capacity adopted, further updates accepted.
"""

import unittest

import numpy as np

import torcheval_tpu.metrics.toolkit as tk
from torcheval_tpu import obs
from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    SlicedMetricCollection,
)
from tests.metrics.test_sync_quantized import run_world

WORLD = 4


def _rank_batches(rank: int, pool: int = 9, n: int = 211):
    """Deterministic ragged shards: rank 2 is EMPTY; the others hold
    overlapping-but-different cohort pools."""
    if rank == 2:
        return []
    rng = np.random.default_rng(40 + rank)
    pool_ids = (np.arange(pool) + rank * (pool // 2)) * 97 - 13
    out = []
    for _ in range(2):
        ids = rng.choice(pool_ids, n)
        s = rng.random(n).astype(np.float32)
        t = (rng.random(n) < 0.5).astype(np.float32)
        out.append((ids, s, t))
    return out


def _make_col(capacity: int = 4):
    return SlicedMetricCollection(
        {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
        capacity=capacity,
    )


def _oracle(pool: int = 9):
    col = _make_col()
    for r in range(WORLD):
        for b in _rank_batches(r, pool=pool):
            col.update(*b)
    return col.compute()


def _assert_matches_oracle(results, want):
    for res in results:
        for key in ("acc", "auroc"):
            got = res[key]
            # the synced union table is id-sorted; align the oracle
            order = np.argsort(want[key].slice_ids)
            np.testing.assert_array_equal(
                got["slice_ids"], want[key].slice_ids[order]
            )
            np.testing.assert_array_equal(
                np.asarray(got["values"]),
                np.asarray(want[key]["values"])[order],
            )


class TestSlicedSync(unittest.TestCase):
    def _sync_world(self, pool=9, quantize=None):
        def fn(rank):
            col = _make_col()
            for b in _rank_batches(rank, pool=pool):
                col.update(*b)
            return tk.sync_and_compute_collection(
                dict(col.metrics), recipient_rank="all", quantize=quantize
            )

        return run_world(WORLD, fn)

    def _assert_matches_oracle(self, results, want):
        _assert_matches_oracle(results, want)

    def test_ragged_cohorts_bit_identical_to_single_stream_oracle(self):
        results, _ = self._sync_world()
        self._assert_matches_oracle(results, _oracle())

    def test_two_rounds_independent_of_slice_count(self):
        rounds = {}
        for pool in (5, 512):
            _, sim = self._sync_world(pool=pool)
            rounds[pool] = len(sim.round_bytes) // WORLD
        # the ≤3-collective acceptance bar, obs/wire-asserted: the typed
        # exchange is exactly TWO rounds at 5 slices and at ~1500 (3 ranks
        # x 512-pool) — the slice axis widens lanes, never adds rounds
        self.assertEqual(rounds[5], 2)
        self.assertEqual(rounds[512], 2)

    def test_quantized_codecs_stay_lossless_on_sliced_lanes(self):
        results_q, sim_q = self._sync_world(quantize=True)
        self._assert_matches_oracle(results_q, _oracle())
        _, sim_raw = self._sync_world(quantize=False)
        # the sketch lanes are sparse int32 histograms: the bucket/narrow
        # codecs must actually engage (payload strictly below raw)
        self.assertLess(sim_q.round_bytes[-1], sim_raw.round_bytes[-1])

    def test_synced_clone_is_live(self):
        def fn(rank):
            c = _make_col()
            for b in _rank_batches(rank):
                c.update(*b)
            return {
                name: tk.get_synced_metric(m, recipient_rank="all")
                for name, m in c.metrics.items()
            }

        synced_all, _ = run_world(WORLD, fn)
        member = synced_all[0]["acc"]
        before = member._table.count
        self.assertGreater(before, 0)
        # keep streaming into the synced clone, new cohorts included
        member.update(
            np.asarray([0, 1], np.int32),
            np.asarray([0.9, 0.2], np.float32),
            np.asarray([1.0, 0.0], np.float32),
        )
        member.compute()

    def test_empty_rank_contributes_identity(self):
        # rank 2 never updates: its lanes are all-default with count 0 and
        # must fold as the reduce identity (asserted implicitly by the
        # oracle test; here pin the union table does NOT contain ghosts)
        results, _ = self._sync_world()
        ids = results[0]["acc"]["slice_ids"]
        want_ids = np.unique(
            np.concatenate(
                [
                    np.concatenate([b[0] for b in _rank_batches(r)])
                    for r in range(WORLD)
                    if _rank_batches(r)
                ]
            )
        )
        np.testing.assert_array_equal(ids, want_ids)


class TestSlicedSyncSharded(unittest.TestCase):
    """ISSUE 17: the same two-round wire with slice-axis-SHARDED members
    (``mesh_axis`` over the forced 8-device CPU mesh).

    Threading caveat: XLA:CPU collectives rendezvous by RunId across ALL
    local devices, so if two rank THREADS each launch a mesh-collective
    program on the shared 8-device backend concurrently, both wait for 8
    participants that never arrive and the world deadlocks. Every
    collective-bearing program (the update folds, ``compute``) therefore
    runs SEQUENTIALLY on the main thread here; only the sync itself —
    host-byte gather/align/install, which never enters a collective —
    rides the threaded wire harness. The real multi-PROCESS world (own
    devices per process) has no such constraint and is covered by
    ``test_multiprocess_sync.py``'s sharded sliced scenario.
    """

    @staticmethod
    def _make_sharded_col():
        return SlicedMetricCollection(
            {"acc": BinaryAccuracy(), "auroc": BinaryAUROC(approx=1024)},
            capacity=4,
            mesh_axis="slices",
        )

    def _sync_world_sharded(self, quantize=None):
        cols = []
        for rank in range(WORLD):
            col = self._make_sharded_col()
            for b in _rank_batches(rank):
                col.update(*b)
            for m in col.metrics.values():
                m._fold_now()  # drain folds BEFORE entering the threads
            cols.append(col)

        def fn(rank):
            return {
                name: tk.get_synced_metric(
                    m, recipient_rank="all", quantize=quantize
                )
                for name, m in cols[rank].metrics.items()
            }

        synced, sim = run_world(WORLD, fn)
        results = [
            {name: m.compute() for name, m in rank_res.items()}
            for rank_res in synced
        ]
        return synced, results, sim

    def test_sharded_sync_bit_identical_to_unsharded_oracle(self):
        _, results, sim = self._sync_world_sharded()
        _assert_matches_oracle(results, _oracle())
        # two members synced one at a time: 2 wire rounds each, no
        # sharding-induced extra collectives on the wire
        self.assertEqual(len(sim.round_bytes) // WORLD, 4)

    def test_sharded_quantized_lossless_and_codec_engages(self):
        obs.enable()
        try:
            obs.reset()
            _, results_q, sim_q = self._sync_world_sharded(quantize=True)
            counters = obs.snapshot()["counters"]
        finally:
            obs.disable()
            obs.reset()
        _assert_matches_oracle(results_q, _oracle())
        _, _, sim_raw = self._sync_world_sharded(quantize=False)
        # the sparse int32 sketch lanes still engage the bucket/narrow
        # codecs on the gathered global payload: strictly below raw, and
        # the encoded/raw counter ratio holds the >= 4x sketch-lane bar
        self.assertLess(sim_q.round_bytes[-1], sim_raw.round_bytes[-1])
        raw = sum(
            v
            for k, v in counters.items()
            if k.startswith("toolkit.sync.lane_bytes{")
        )
        enc = sum(
            v
            for k, v in counters.items()
            if k.startswith("toolkit.sync.lane_bytes_encoded{")
        )
        self.assertGreater(raw, 0)
        self.assertLessEqual(enc * 4, raw)
        self.assertTrue(
            any(
                "codec=bucket" in k or "codec=narrow" in k
                for k in counters
                if k.startswith("toolkit.sync.lane_bytes_encoded{")
            ),
            sorted(k for k in counters if "lane_bytes_encoded" in k),
        )

    def test_synced_clone_stays_sharded_and_live(self):
        from jax.sharding import PartitionSpec as P

        synced, _, _ = self._sync_world_sharded()
        member = synced[0]["auroc"]
        for name in member._sliced_state_names:
            st = getattr(member, name)
            self.assertEqual(st.sharding.spec, P("slices"))
        # still live: stream new cohorts into the synced clone
        member.update(
            np.asarray([123456, 7], np.int64),
            np.asarray([0.9, 0.2], np.float32),
            np.asarray([1.0, 0.0], np.float32),
        )
        member._fold_now()
        member.compute()


if __name__ == "__main__":
    unittest.main()

"""Toolkit + SPMD sync tests on the forced 8-device CPU mesh (SURVEY §4
tier 3: multi-node semantics simulated as multi-device single-process SPMD)."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.metrics import roc_auc_score

from torcheval_tpu.metrics import (
    BinaryAUROC,
    Cat,
    Max,
    MulticlassAccuracy,
    MulticlassF1Score,
    Sum,
    Throughput,
)
from torcheval_tpu.metrics.state import Reduction
from torcheval_tpu.metrics.toolkit import (
    _fold_states,
    clone_metric,
    clone_metrics,
    get_synced_metric,
    merge_metrics,
    reset_metrics,
    sync_and_compute,
    sync_and_compute_collection,
    to_device,
)
from torcheval_tpu.parallel import ShardedEvaluator, data_parallel_mesh, shard_batch

RNG = np.random.default_rng(40)


class TestLocalToolkit(unittest.TestCase):
    def test_clone_and_reset(self):
        m = Sum()
        m.update(jnp.asarray([1.0, 2.0]))
        c = clone_metric(m)
        self.assertEqual(float(c.compute()), 3.0)
        c.update(jnp.asarray([5.0]))
        self.assertEqual(float(m.compute()), 3.0)  # clone is independent
        (r,) = reset_metrics([c])
        self.assertEqual(float(r.compute()), 0.0)
        cs = clone_metrics([m, Max()])
        self.assertEqual(len(cs), 2)

    def test_to_device(self):
        m = Sum()
        m.update(jnp.asarray([1.0]))
        (moved,) = to_device([m], "cpu")
        self.assertEqual(float(moved.compute()), 1.0)

    def test_merge_metrics_does_not_mutate(self):
        a, b = Sum(), Sum()
        a.update(jnp.asarray([1.0]))
        b.update(jnp.asarray([2.0]))
        merged = merge_metrics([a, b])
        self.assertEqual(float(merged.compute()), 3.0)
        self.assertEqual(float(a.compute()), 1.0)
        self.assertEqual(float(b.compute()), 2.0)

    def test_world_size_one_sync_returns_input(self):
        m = Sum()
        m.update(jnp.asarray([4.0]))
        with self.assertLogs(level="WARNING"):
            synced = get_synced_metric(m, recipient_rank=0)
        self.assertIs(synced, m)
        with self.assertLogs(level="WARNING"):
            self.assertEqual(float(sync_and_compute(m)), 4.0)

    def test_invalid_recipient(self):
        with self.assertRaisesRegex(ValueError, "recipient_rank"):
            get_synced_metric(Sum(), recipient_rank="some")

    def test_collection(self):
        a, b = Sum(), Max()
        a.update(jnp.asarray([1.0]))
        b.update(jnp.asarray([7.0]))
        with self.assertLogs(level="WARNING"):
            out = sync_and_compute_collection({"s": a, "m": b})
        self.assertEqual(float(out["s"]), 1.0)
        self.assertEqual(float(out["m"]), 7.0)

    def test_processes_validation(self):
        # single-process world: the only member subgroup is [0]; it behaves
        # like world size 1 (warn + return input). The real member-semantics
        # live in the 4-process suite (test_multiprocess_sync.py).
        m = Sum()
        m.update(jnp.asarray([4.0]))
        with self.assertLogs(level="WARNING"):
            self.assertIs(get_synced_metric(m, processes=[0]), m)
        with self.assertRaisesRegex(ValueError, "out of range"):
            sync_and_compute(m, processes=[0, 7])
        with self.assertRaisesRegex(ValueError, "non-empty"):
            sync_and_compute(m, processes=[])
        with self.assertRaisesRegex(ValueError, "out of range"):
            sync_and_compute_collection({"s": m}, processes=[0, 3])
        # membership and in-group recipient rejection are exercised in the
        # real 4-process world (test_multiprocess_sync.py::test_subgroup_sync)


class TestFoldStates(unittest.TestCase):
    """The typed reduction fold is the core of cross-process sync; exercise it
    with simulated rank state-dicts for every Reduction."""

    def test_sum_max_min_cat_none(self):
        ranks = [
            {
                "s": jnp.asarray(float(i + 1)),
                "mx": jnp.asarray(float(i)),
                "mn": jnp.asarray(float(i)),
                "c": [jnp.arange(i + 1, dtype=jnp.float32)],
                "t": jnp.asarray([0.5]),
            }
            for i in range(4)
        ]
        reductions = {
            "s": Reduction.SUM,
            "mx": Reduction.MAX,
            "mn": Reduction.MIN,
            "c": Reduction.CAT,
            "t": Reduction.NONE,
        }
        folded = _fold_states(ranks, reductions)
        self.assertEqual(float(folded["s"]), 10.0)
        self.assertEqual(float(folded["mx"]), 3.0)
        self.assertEqual(float(folded["mn"]), 0.0)
        self.assertEqual(folded["c"][0].shape, (10,))
        np.testing.assert_allclose(np.asarray(folded["t"]), [0.5])

    def test_custom_raises(self):
        with self.assertRaises(NotImplementedError):
            _fold_states(
                [{"x": jnp.zeros(())}], {"x": Reduction.CUSTOM}
            )

    def test_window_fold_preserves_row_boundaries_in_rank_order(self):
        # WINDOW values arrive as stacked (k, ...) arrays off the wire (or
        # [] for an empty rank); the fold yields per-update rows in rank
        # order, never concatenating them into one slot
        ranks = [
            {"w": jnp.asarray([[1.0, 1.0], [2.0, 2.0]])},  # 2 updates
            {"w": []},  # empty rank
            {"w": jnp.asarray([[3.0, 3.0]])},  # 1 update
        ]
        folded = _fold_states(ranks, {"w": Reduction.WINDOW})
        self.assertEqual(len(folded["w"]), 3)
        np.testing.assert_allclose(np.asarray(folded["w"][0]), [1.0, 1.0])
        np.testing.assert_allclose(np.asarray(folded["w"][2]), [3.0, 3.0])

    def test_windowed_metric_single_gather_round_trip(self):
        # full path through _gather_collection_states' entry encoding on a
        # 1-process world: stacked rows encode/decode bit-identically and
        # the maxlen bound is re-imposed at install
        from collections import deque

        from torcheval_tpu.metrics import WindowedClickThroughRate
        from torcheval_tpu.metrics.toolkit import _gather_collection_states

        m = WindowedClickThroughRate(window_size=3)
        for v in (1.0, 0.0, 1.0, 1.0):  # 4 updates into a window of 3
            m.update(jnp.asarray([v]))
        gathered = _gather_collection_states({"m": m})
        rows = gathered[0]["m"]["window"]
        self.assertEqual(np.asarray(rows).shape, (3, 2, 1))
        win = deque(list(rows), maxlen=3)
        np.testing.assert_allclose(
            np.asarray(jnp.stack(list(win))), np.asarray(jnp.stack(list(m.window)))
        )

    def test_cat_descriptor_rank_guard(self):
        # a rank-6 cache cannot fit the fixed wire layout; its descriptor
        # records the oversized ndim and the post-exchange check raises
        # uniformly on every rank (a pre-collective raise would hang the
        # empty-cache ranks inside process_allgather)
        from torcheval_tpu.metrics.toolkit import (
            _check_cat_descriptors,
            _encode_entry_descriptor,
        )

        desc = np.asarray(
            _encode_entry_descriptor(np.zeros((2,) * 6)), np.int32
        )
        self.assertEqual(int(desc[1]), 6)
        all_desc = np.stack([np.zeros_like(desc), desc])
        with self.assertRaisesRegex(NotImplementedError, "rank 6"):
            _check_cat_descriptors("inputs", all_desc)
        # in-range descriptors pass
        _check_cat_descriptors(
            "inputs",
            np.asarray(_encode_entry_descriptor(np.zeros((3, 2))), np.int32)[
                None
            ],
        )

    def test_cat_descriptor_dtype_guard_is_post_exchange(self):
        # unsupported dtypes encode the -1 sentinel (no one-sided raise that
        # would hang empty-cache peers) and fail uniformly after the exchange
        from torcheval_tpu.metrics.toolkit import (
            _check_cat_descriptors,
            _encode_entry_descriptor,
        )

        # complex64: outside the wire allowlist (int16 joined it in round 3)
        desc = np.asarray(
            _encode_entry_descriptor(np.zeros((4,), dtype=np.complex64)),
            np.int32,
        )
        self.assertEqual(int(desc[2]), -1)
        with self.assertRaisesRegex(NotImplementedError, "dtype"):
            _check_cat_descriptors("inputs", desc[None])

    def test_tree_host_roundtrip_preserves_container_metadata(self):
        from collections import defaultdict, deque

        from torcheval_tpu.metrics.metric import _zero_scalar
        from torcheval_tpu.metrics.toolkit import _tree_to_device, _tree_to_host

        d = defaultdict(_zero_scalar, {"a": jnp.asarray(1.0)})
        q = deque([jnp.asarray([1.0])], maxlen=3)
        back = _tree_to_device(_tree_to_host({"d": d, "q": q}))
        self.assertIsInstance(back["d"], defaultdict)
        self.assertEqual(float(back["d"]["missing"]), 0.0)
        self.assertEqual(back["q"].maxlen, 3)

    def test_fold_matches_merge_state_for_real_metrics(self):
        """Typed fold of per-rank states == the metric's own merge_state."""
        n_ranks, batches_per_rank = 4, 2
        replicas = [MulticlassF1Score(num_classes=5, average="macro") for _ in range(n_ranks)]
        all_x, all_t = [], []
        for rep in replicas:
            for _ in range(batches_per_rank):
                x = RNG.random((32, 5)).astype(np.float32)
                t = RNG.integers(0, 5, 32)
                rep.update(x, t)
                all_x.append(x)
                all_t.append(t)
        gathered = [rep.state_dict() for rep in replicas]
        folded = _fold_states(gathered, replicas[0]._state_name_to_reduction)
        merged = merge_metrics(replicas)
        for name, value in folded.items():
            np.testing.assert_allclose(
                np.asarray(value),
                np.asarray(getattr(merged, name)),
            )

    def test_fold_throughput_max_elapsed(self):
        reps = [Throughput() for _ in range(3)]
        for i, r in enumerate(reps):
            r.update(num_processed=100 * (i + 1), elapsed_time_sec=float(i + 1))
        gathered = [r.state_dict() for r in reps]
        folded = _fold_states(gathered, reps[0]._state_name_to_reduction)
        self.assertEqual(float(folded["num_total"]), 600.0)
        self.assertEqual(float(folded["elapsed_time_sec"]), 3.0)  # max, not sum


class TestWindowWireBound(unittest.TestCase):
    """The WINDOW lane's byte-payload round is bounded by the deque maxlen
    (round-5 verdict weak #5): after the descriptor round every rank knows
    every rank's row counts, so rows that cannot survive the install-time
    ``deque(maxlen)`` fold are dropped BEFORE the payload round — at most
    ``maxlen`` window rows cross the wire in total, not ``maxlen`` per rank."""

    def test_keep_counts_survive_fold_exactly(self):
        from collections import deque

        from torcheval_tpu.metrics.toolkit import _window_keep_counts

        cases = [
            (np.full(32, 32), 32),  # 32 full ranks (realistic config)
            (np.asarray([2, 2]), 3),
            (np.asarray([0, 5, 0, 1]), 4),
            (np.asarray([1, 1, 1]), 8),  # under-full: everything survives
            (np.asarray([40, 0, 7]), 5),
        ]
        for d0, maxlen in cases:
            keep = _window_keep_counts(d0, maxlen)
            # bound: the wire never moves more than maxlen surviving rows
            self.assertLessEqual(int(keep.sum()), maxlen)
            self.assertEqual(int(keep.sum()), min(maxlen, int(d0.sum())))
            # equivalence: folding the truncated tails == folding everything
            rows = [
                [(r, i) for i in range(int(n))] for r, n in enumerate(d0)
            ]
            full = deque(
                [x for rr in rows for x in rr], maxlen=maxlen
            )
            trunc = deque(
                [
                    x
                    for rr, k in zip(rows, keep)
                    for x in rr[len(rr) - int(k):]
                ],
                maxlen=maxlen,
            )
            self.assertEqual(list(full), list(trunc))

    def test_32_rank_window_payload_is_bounded(self):
        # simulate a 32-rank SPMD world (every rank lockstep-identical, the
        # realistic configuration) by stubbing the collectives: the payload
        # round must carry ONE window's worth of rows in total, and the
        # synced result must equal a local 32-replica merge_state fold
        from unittest import mock

        from jax.experimental import multihost_utils

        import torcheval_tpu.metrics.toolkit as tk
        from torcheval_tpu.metrics import WindowedClickThroughRate

        world, window = 32, 32
        def make_replica():
            m = WindowedClickThroughRate(window_size=window)
            for i in range(40):  # 40 updates stream through a 32-row window
                m.update(jnp.full((4,), float(i % 2)))
            return m

        m = make_replica()
        row_bytes = int(np.asarray(jnp.stack(list(m.window))).nbytes)
        scalar_bytes = 8  # click_total + weight_total (f32 each)
        rounds = []

        def fake_allgather(x):
            x = np.asarray(x)
            rounds.append(x.nbytes)
            return np.stack([x] * world)

        with mock.patch.object(tk, "_world_size", return_value=world), \
                mock.patch.object(
                    tk, "_process_index", return_value=world - 1
                ), mock.patch.object(
                    multihost_utils, "process_allgather", fake_allgather
                ):
            synced = get_synced_metric(m, recipient_rank="all")
        # round 2 (payload): this last rank ships its full window — every
        # OTHER rank's is fully shadowed and ships zero rows, so max_total
        # (what every rank pads to) is ONE window + the scalars, not 32x.
        # (identical ranks => the stubbed gather's stacked copies are
        # byte-faithful: shadowed ranks contribute zero window bytes at the
        # same offsets)
        self.assertEqual(len(rounds), 2)
        self.assertEqual(rounds[1], row_bytes + scalar_bytes)
        # fold semantics unchanged: == merging 32 identical replicas locally
        merged = make_replica().merge_state(
            [make_replica() for _ in range(world - 1)]
        )
        self.assertEqual(len(synced.window), window)
        for a, b in zip(synced.window, merged.window):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(
            np.asarray(synced.compute()), np.asarray(merged.compute())
        )

    def test_empty_rank_applies_same_truncation_as_peers(self):
        # review regression: a rank whose OWN window is empty must still
        # apply the keep-count rewrite to the gathered descriptors — the
        # payload totals, padding and decode offsets derive from them, so a
        # skipped rewrite would put ranks into the payload collective with
        # mismatched shapes (and mis-decode the peer's rows)
        from collections import deque
        from unittest import mock

        from jax.experimental import multihost_utils

        import torcheval_tpu.metrics.toolkit as tk
        from torcheval_tpu.metrics.metric import Metric
        from torcheval_tpu.metrics.toolkit import (
            _encode_entry_descriptor,
            _gather_collection_states,
            _schema_digest_row,
        )

        class BoundedWindow(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self._add_state(
                    "window", deque(maxlen=3), reduction=Reduction.WINDOW
                )

            def update(self, v):
                self.window.append(jnp.asarray([float(v)]))
                return self

            def compute(self):
                return jnp.sum(jnp.stack(list(self.window)))

            def merge_state(self, metrics):
                for other in metrics:
                    self.window.extend(other.window)
                return self

        me = BoundedWindow()  # rank 0: EMPTY window this sync
        # peer rank 1 declares 5 stacked rows, truncated by the bound to its
        # newest 3 (maxlen) — craft its wire contribution by hand
        peer_rows = np.arange(5, dtype=np.float32).reshape(5, 1)
        peer_desc = np.asarray(
            [_schema_digest_row({"m": me})]
            + [_encode_entry_descriptor(peer_rows)],
            dtype=np.int32,
        )
        rounds = []

        def fake_allgather(x):
            x = np.asarray(x)
            rounds.append(x)
            if len(rounds) == 1:  # descriptor round
                return np.stack([x, peer_desc])
            # payload round: the peer ships its newest 3 rows (12 bytes);
            # both ranks must have padded to the SAME max_total for the
            # collective to be well-formed
            peer_payload = np.zeros_like(x)
            raw = peer_rows[2:].view(np.uint8).reshape(-1)
            peer_payload[: raw.size] = raw
            return np.stack([x, peer_payload])

        with mock.patch.object(tk, "_world_size", return_value=2), \
                mock.patch.object(tk, "_process_index", return_value=0), \
                mock.patch.object(
                    multihost_utils, "process_allgather", fake_allgather
                ):
            gathered = _gather_collection_states({"m": me})
        # the empty rank computed the truncated totals (3 rows = 12 bytes),
        # not the peer's declared 5 rows (20 bytes)
        self.assertEqual(rounds[1].nbytes, 12)
        np.testing.assert_array_equal(
            np.asarray(gathered[1]["m"]["window"]), peer_rows[2:]
        )
        self.assertEqual(len(gathered[0]["m"]["window"]), 0)

    def test_unbounded_deque_ships_in_full(self):
        # maxlen=None has no fold bound — nothing may be dropped
        from torcheval_tpu.metrics.toolkit import _gather_collection_states
        from torcheval_tpu.metrics.metric import Metric
        from collections import deque

        class UnboundedWindow(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self._add_state(
                    "window", deque(), reduction=Reduction.WINDOW
                )

            def update(self, v):
                self.window.append(jnp.asarray([float(v)]))
                return self

            def compute(self):
                return jnp.sum(jnp.stack(list(self.window)))

            def merge_state(self, metrics):
                for other in metrics:
                    self.window.extend(other.window)
                return self

        m = UnboundedWindow()
        for i in range(5):
            m.update(i)
        gathered = _gather_collection_states({"m": m})
        self.assertEqual(np.asarray(gathered[0]["m"]["window"]).shape, (5, 1))


class TestShardedEvaluator(unittest.TestCase):
    """Implicit SPMD sync: sharded batches + replicated state on the 8-device
    CPU mesh — the code path that rides ICI on a real pod."""

    def setUp(self):
        self.assertEqual(len(jax.devices()), 8, "conftest must force 8 devices")
        self.mesh = data_parallel_mesh()

    def test_sharded_accuracy_matches_host(self):
        ev = ShardedEvaluator(MulticlassAccuracy(num_classes=10), mesh=self.mesh)
        xs, ts = [], []
        for _ in range(4):
            x = RNG.random((64, 10)).astype(np.float32)
            t = RNG.integers(0, 10, 64)
            xs.append(x)
            ts.append(t)
            ev.update(x, t)
        result = float(ev.compute())
        X, T = np.concatenate(xs), np.concatenate(ts)
        self.assertAlmostEqual(result, float((X.argmax(1) == T).mean()), places=6)

    def test_batch_really_sharded(self):
        batch = shard_batch(self.mesh, np.zeros((64, 4), dtype=np.float32))
        self.assertEqual(len(batch.sharding.device_set), 8)
        shard_shapes = {s.data.shape for s in batch.addressable_shards}
        self.assertEqual(shard_shapes, {(8, 4)})

    def test_replicated_input_gets_resharded(self):
        # a REPLICATED array on the mesh (e.g. a jitted forward pass with
        # replicated output) must still be re-placed to P("data") — the
        # already-global fast path may only bypass the exact target sharding
        from jax.sharding import NamedSharding, PartitionSpec as P

        replicated = jax.device_put(
            jnp.zeros((64, 4), jnp.float32), NamedSharding(self.mesh, P())
        )
        out = shard_batch(self.mesh, replicated)
        shard_shapes = {s.data.shape for s in out.addressable_shards}
        self.assertEqual(shard_shapes, {(8, 4)})

    def test_presharded_input_passes_through_untouched(self):
        presharded = shard_batch(self.mesh, np.zeros((64, 4), dtype=np.float32))
        self.assertIs(shard_batch(self.mesh, presharded), presharded)

    def test_sharded_collection_and_state_correct(self):
        ev = ShardedEvaluator(
            {
                "acc": MulticlassAccuracy(num_classes=5),
                "f1": MulticlassF1Score(num_classes=5, average="macro"),
            },
            mesh=self.mesh,
        )
        x = RNG.random((80, 5)).astype(np.float32)
        t = RNG.integers(0, 5, 80)
        out = ev.update(x, t).compute()
        host_acc = MulticlassAccuracy(num_classes=5).update(x, t).compute()
        self.assertAlmostEqual(float(out["acc"]), float(host_acc), places=6)
        from torcheval_tpu.metrics import functional as F

        self.assertAlmostEqual(
            float(out["f1"]),
            float(F.multiclass_f1_score(x, t, num_classes=5, average="macro")),
            places=5,
        )

    def test_sharded_auroc_sample_cache(self):
        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        xs, ts = [], []
        for _ in range(3):
            x = RNG.random(64).astype(np.float32)
            t = RNG.integers(0, 2, 64)
            xs.append(x)
            ts.append(t)
            ev.update(x, t)
        got = float(ev.compute())
        want = roc_auc_score(np.concatenate(ts), np.concatenate(xs))
        self.assertAlmostEqual(got, want, places=5)

    def test_sharded_auroc_compute_is_one_partitioned_program(self):
        """The cache→curve compute path must be a single SPMD executable over
        the mesh (no host concat of shards it may not address — VERDICT r1
        missing #3): inspect the compiled program for 8-way partitioning."""
        from torcheval_tpu.metrics.classification.auroc import _auroc_from_parts

        ev = ShardedEvaluator(BinaryAUROC(), mesh=self.mesh)
        x = RNG.random(64).astype(np.float32)
        t = RNG.integers(0, 2, 64)
        ev.update(x, t)
        m = ev.metrics["metric"]
        # precondition: the cache entries really are 8-device global arrays
        self.assertEqual(len(m.inputs[0].sharding.device_set), 8)
        compiled = _auroc_from_parts.lower(
            m.inputs, m.targets, m.summary_scores, m.summary_tp, m.summary_fp
        ).compile()
        hlo = compiled.as_text()
        self.assertIn("num_partitions=8", hlo.splitlines()[0])

    def test_sharded_compaction_stays_on_mesh(self):
        """Compaction fed by sharded batches must produce a correct summary
        without pulling shard data to the host."""
        ev = ShardedEvaluator(
            BinaryAUROC(compaction_threshold=128), mesh=self.mesh
        )
        xs, ts = [], []
        for _ in range(4):
            x = (RNG.random(64) * 20).astype(np.int32).astype(np.float32) / 20
            t = RNG.integers(0, 2, 64)
            xs.append(x)
            ts.append(t)
            ev.update(x, t)
        m = ev.metrics["metric"]
        self.assertTrue(m.summary_scores)  # compaction fired
        got = float(ev.compute())
        want = roc_auc_score(np.concatenate(ts), np.concatenate(xs))
        self.assertAlmostEqual(got, want, places=5)

    def test_sharded_cat(self):
        ev = ShardedEvaluator(Cat(), mesh=self.mesh)
        ev.update(np.arange(16, dtype=np.float32))
        ev.update(np.arange(16, 32, dtype=np.float32))
        np.testing.assert_array_equal(
            np.asarray(ev.compute()), np.arange(32, dtype=np.float32)
        )


class TestCollectionWireSchema(unittest.TestCase):
    """The round-1 descriptor exchange carries a schema digest so ranks that
    enumerate collection entries in different orders fail loudly instead of
    silently decoding bytes into the wrong states (advisor r3, medium)."""

    def test_digest_row_deterministic_and_order_sensitive(self):
        from torcheval_tpu.metrics.aggregation import Max, Sum
        from torcheval_tpu.metrics.toolkit import _schema_digest_row

        a = {"s": Sum(), "m": Max()}
        b = {"s": Sum(), "m": Max()}
        swapped = {"m": Max(), "s": Sum()}
        self.assertEqual(_schema_digest_row(a), _schema_digest_row(b))
        self.assertNotEqual(_schema_digest_row(a), _schema_digest_row(swapped))
        self.assertEqual(_schema_digest_row(a)[0], 2)  # entry count header

    def test_digest_distinguishes_same_shapes_different_metrics(self):
        # the dangerous cases the digest exists for: states whose byte
        # payloads are indistinguishable on the wire (same shapes/dtypes)
        from torcheval_tpu.metrics.aggregation import Mean, Sum
        from torcheval_tpu.metrics.toolkit import _schema_digest_row

        # different metric keys / state names
        self.assertNotEqual(
            _schema_digest_row({"x": Sum()}), _schema_digest_row({"y": Sum()})
        )
        # different metric TYPES with coinciding (key, state, reduction)
        # schemas still mismatch — the class is part of the digest
        class SumLookalike(Sum):
            pass

        self.assertNotEqual(
            _schema_digest_row({"x": Sum()}),
            _schema_digest_row({"x": SumLookalike()}),
        )
        self.assertNotEqual(
            _schema_digest_row({"x": Sum()}), _schema_digest_row({"x": Mean()})
        )

    def test_schema_mismatch_raises_uniformly_post_exchange(self):
        from unittest import mock

        from jax.experimental import multihost_utils

        import torcheval_tpu.metrics.toolkit as tk
        from torcheval_tpu.metrics.aggregation import Max, Sum

        # simulate a 2-process world where the peer built {"m", "s"} while we
        # built {"s", "m"}: the gathered descriptor matrix carries both
        # digest header rows and the check must raise BEFORE the payload round
        metrics = {"s": Sum(), "m": Max()}
        peer_metrics = {"m": Max(), "s": Sum()}
        peer_entries = tk._collection_entries(peer_metrics)
        peer_desc = np.asarray(
            [tk._schema_digest_row(peer_metrics)]
            + [
                tk._encode_entry_descriptor(local)
                for _, _, _, local in peer_entries
            ],
            dtype=np.int32,
        )

        calls = []

        def fake_allgather(x):
            calls.append(np.asarray(x).shape)
            return np.stack([np.asarray(x), peer_desc])

        with mock.patch.object(tk, "_world_size", return_value=2), \
                mock.patch.object(
                    multihost_utils, "process_allgather", fake_allgather
                ):
            with self.assertRaisesRegex(RuntimeError, "schema mismatch"):
                tk._gather_collection_states(metrics)
        # exactly ONE collective happened (the descriptor round) — the raise
        # fires on gathered data every rank sees, before any payload exchange
        self.assertEqual(len(calls), 1)


class TestObsCollectiveAccounting(unittest.TestCase):
    """The obs registry's view of the sync wire (ISSUE 1): every collective
    round funnels through ``_allgather_stacked``, so with obs enabled the
    two-round invariant and per-lane payload bytes are observables. The
    4-real-process variant of these assertions lives in
    ``tests/metrics/test_multiprocess_sync.py``; here the single-process
    world exercises the same counters through the same code path."""

    def setUp(self):
        from torcheval_tpu import obs

        obs.enable()
        obs.reset()

    def tearDown(self):
        from torcheval_tpu import obs

        obs.disable()
        obs.reset()

    def test_collection_gather_is_two_accounted_rounds(self):
        from torcheval_tpu import obs
        from torcheval_tpu.metrics.toolkit import _gather_collection_states

        acc = MulticlassAccuracy(num_classes=3)
        acc.update(
            jnp.asarray(RNG.random((8, 3)).astype(np.float32)),
            jnp.asarray(RNG.integers(0, 3, 8)),
        )
        auroc = BinaryAUROC()
        auroc.update(
            jnp.asarray(RNG.random(16).astype(np.float32)),
            jnp.asarray((RNG.random(16) > 0.5).astype(np.float32)),
        )
        _gather_collection_states({"acc": acc, "auroc": auroc})
        snap = obs.snapshot()
        # descriptor matrix + concatenated byte payload: exactly 2 rounds
        # no matter how many states the collection has
        self.assertEqual(snap["counters"]["toolkit.sync.rounds"], 2)
        self.assertGreater(snap["counters"]["toolkit.sync.payload_bytes"], 0)
        # per-Reduction-lane bytes: both populated lanes nonzero
        self.assertGreater(
            snap["counters"]["toolkit.sync.lane_bytes{lane=SUM}"], 0
        )
        self.assertGreater(
            snap["counters"]["toolkit.sync.lane_bytes{lane=CAT}"], 0
        )
        self.assertEqual(snap["gauges"]["toolkit.sync.world_size"], 1)
        # spans are per-(lane, round) series since ISSUE 7 (the flight
        # recorder labels each exchange): descriptor + payload, typed lane
        round_spans = {
            k: v
            for k, v in snap["spans"].items()
            if k.startswith("toolkit.sync.round{")
        }
        self.assertEqual(sum(v["count"] for v in round_spans.values()), 2)
        self.assertIn(
            "toolkit.sync.round{lane=typed,round=descriptor}", round_spans
        )
        self.assertIn(
            "toolkit.sync.round{lane=typed,round=payload}", round_spans
        )
        # the per-lane latency histogram recorded both rounds
        self.assertEqual(
            snap["histograms"]["toolkit.sync.round_seconds{lane=typed}"][
                "count"
            ],
            2,
        )

    def test_world_size_one_sync_enters_no_collective(self):
        from torcheval_tpu import obs

        m = Sum()
        m.update(jnp.asarray([1.0]))
        sync_and_compute(m)  # world 1: warned no-op
        self.assertNotIn(
            "toolkit.sync.rounds", obs.snapshot()["counters"]
        )

    def test_sync_api_span_recorded(self):
        from torcheval_tpu import obs

        m = Sum()
        m.update(jnp.asarray([2.0]))
        sync_and_compute(m)
        spans = obs.snapshot()["spans"]
        self.assertIn(
            "toolkit.sync_and_compute/toolkit.get_synced_metric", spans
        )

    def test_disabled_snapshot_untouched_by_gather(self):
        from torcheval_tpu import obs
        from torcheval_tpu.metrics.toolkit import _gather_collection_states

        obs.disable()
        obs.reset()
        m = Sum()
        m.update(jnp.asarray([1.0]))
        _gather_collection_states({"m": m})
        self.assertEqual(obs.snapshot()["counters"], {})


if __name__ == "__main__":
    unittest.main()

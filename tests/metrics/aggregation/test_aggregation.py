"""Aggregation metrics: protocol tests + numpy oracles.

Mirrors ``/root/reference/tests/metrics/aggregation/``.
"""

import unittest

import jax.numpy as jnp
import numpy as np

from torcheval_tpu.metrics import Cat, Max, Mean, Min, Sum, Throughput
from torcheval_tpu.utils.test_utils import (
    NUM_TOTAL_UPDATES,
    MetricClassTester,
    assert_result_close,
)


class TestSum(MetricClassTester):
    def test_sum_class(self):
        x = np.random.default_rng(0).random((NUM_TOTAL_UPDATES, 16)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Sum(),
            state_names={"weighted_sum"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.sum(),
        )

    def test_sum_weighted(self):
        m = Sum()
        m.update(jnp.asarray([1.0, 2.0]), weight=2.0)
        m.update(jnp.asarray([3.0]), weight=jnp.asarray([4.0]))
        assert_result_close(m.compute(), 18.0)

    def test_sum_weight_shape_mismatch(self):
        with self.assertRaisesRegex(ValueError, "weight"):
            Sum().update(jnp.asarray([1.0, 2.0]), weight=jnp.asarray([1.0, 2.0, 3.0]))


class TestComputeTraceSafety(unittest.TestCase):
    """Mean/Throughput compute must be jit-embeddable (VERDICT r1 weak #5):
    no host readback inside a trace; degenerate no-update case still 0.0."""

    def test_mean_compute_under_jit(self):
        import jax

        m = Mean()
        m.update(jnp.asarray([1.0, 3.0]))
        # read through state_dict: it folds the deferred pending batches
        # first (direct attribute reads see only the folded-so-far value)
        sd = m.state_dict()

        def f(ws, w):
            mm = Mean()
            mm.weighted_sum, mm.weights = ws, w
            return mm.compute()

        assert_result_close(jax.jit(f)(sd["weighted_sum"], sd["weights"]), 2.0)
        assert_result_close(jax.jit(f)(jnp.zeros(()), jnp.zeros(())), 0.0)

    def test_throughput_compute_under_jit(self):
        import jax

        t = Throughput()
        t.update(100, 2.0)

        def f(n, e):
            tt = Throughput()
            tt.num_total, tt.elapsed_time_sec = n, e
            return tt.compute()

        assert_result_close(jax.jit(f)(t.num_total, t.elapsed_time_sec), 50.0)
        assert_result_close(jax.jit(f)(jnp.zeros(()), jnp.zeros(())), 0.0)


class TestMean(MetricClassTester):
    def test_mean_class(self):
        x = np.random.default_rng(1).random((NUM_TOTAL_UPDATES, 16)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Mean(),
            state_names={"weighted_sum", "weights"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.mean(),
        )

    def test_mean_weighted(self):
        m = Mean()
        m.update(jnp.asarray([1.0, 2.0]), weight=1.0)
        m.update(jnp.asarray([6.0]), weight=2.0)
        # (1 + 2 + 12) / (1 + 1 + 2)
        assert_result_close(m.compute(), 15.0 / 4.0)

    def test_mean_zero_mean_data_is_not_treated_as_empty(self):
        # documented fix of the reference quirk (mean.py:92-94)
        m = Mean()
        m.update(jnp.asarray([-1.0, 1.0]))
        assert_result_close(m.compute(), 0.0)

    def test_mean_no_update_returns_zero(self):
        assert_result_close(Mean().compute(), 0.0)


class TestMaxMin(MetricClassTester):
    def test_max_class(self):
        x = np.random.default_rng(2).random((NUM_TOTAL_UPDATES, 16)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Max(),
            state_names={"max"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.max(),
        )

    def test_min_class(self):
        x = np.random.default_rng(3).random((NUM_TOTAL_UPDATES, 16)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Min(),
            state_names={"min"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.min(),
        )


class TestCat(MetricClassTester):
    def test_cat_class(self):
        x = np.random.default_rng(4).random((NUM_TOTAL_UPDATES, 4, 3)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Cat(),
            state_names={"inputs"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.reshape(-1, 3),
            # merged order differs from stream order only in grouping; with
            # contiguous per-rank slices the concat equals the stream result.
        )

    def test_cat_empty(self):
        self.assertEqual(Cat().compute().shape, (0,))

    def test_cat_dim(self):
        m = Cat(dim=1)
        m.update(jnp.ones((2, 1)))
        m.update(jnp.zeros((2, 2)))
        self.assertEqual(m.compute().shape, (2, 3))


class TestThroughput(MetricClassTester):
    def test_throughput_class(self):
        self.run_class_implementation_tests(
            metric=Throughput(),
            state_names={"num_total", "elapsed_time_sec"},
            update_kwargs={
                "num_processed": [10] * NUM_TOTAL_UPDATES,
                "elapsed_time_sec": [2.0] * NUM_TOTAL_UPDATES,
            },
            compute_result=80 / 16.0,
            # per-rank: 2 updates -> 20 items / 4 s; merge: 80 items, max 4 s
            merge_and_compute_result=80 / 4.0,
        )

    def test_throughput_validation(self):
        with self.assertRaisesRegex(ValueError, "num_processed"):
            Throughput().update(-1, 1.0)
        with self.assertRaisesRegex(ValueError, "elapsed_time_sec"):
            Throughput().update(1, 0.0)

    def test_throughput_no_update(self):
        assert_result_close(Throughput().compute(), 0.0)


class TestFunctionalAggregation(unittest.TestCase):
    def test_functional_sum_and_mean(self):
        from torcheval_tpu.metrics import functional as F

        x = np.random.default_rng(5).random(100).astype(np.float32)
        w = np.random.default_rng(6).random(100).astype(np.float32)
        assert_result_close(F.sum(jnp.asarray(x)), x.sum())
        assert_result_close(F.sum(jnp.asarray(x), jnp.asarray(w)), (x * w).sum())
        assert_result_close(F.mean(jnp.asarray(x)), x.mean())
        assert_result_close(
            F.mean(jnp.asarray(x), jnp.asarray(w)), (x * w).sum() / w.sum()
        )


if __name__ == "__main__":
    unittest.main()


class TestAggregationSpecMatrix(MetricClassTester):
    """Extra shape/weight permutations per aggregation metric."""

    def test_sum_2d_weighted_spec(self):
        rng = np.random.default_rng(50)
        x = rng.random((NUM_TOTAL_UPDATES, 8, 3)).astype(np.float32)
        w = rng.random((NUM_TOTAL_UPDATES, 8, 3)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Sum(),
            state_names={"weighted_sum"},
            update_kwargs={"input": jnp.asarray(x), "weight": jnp.asarray(w)},
            compute_result=(x * w).sum(),
        )

    def test_mean_vector_weight_spec(self):
        rng = np.random.default_rng(51)
        x = rng.random((NUM_TOTAL_UPDATES, 16)).astype(np.float32)
        w = rng.random((NUM_TOTAL_UPDATES, 16)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Mean(),
            state_names={"weighted_sum", "weights"},
            update_kwargs={"input": jnp.asarray(x), "weight": jnp.asarray(w)},
            compute_result=(x * w).sum() / w.sum(),
        )

    def test_max_min_2d_spec(self):
        rng = np.random.default_rng(52)
        x = rng.standard_normal((NUM_TOTAL_UPDATES, 4, 4)).astype(np.float32)
        self.run_class_implementation_tests(
            metric=Max(),
            state_names={"max"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.max(),
        )
        self.run_class_implementation_tests(
            metric=Min(),
            state_names={"min"},
            update_kwargs={"input": jnp.asarray(x)},
            compute_result=x.min(),
        )

    def test_sum_non_numeric_weight_rejected(self):
        with self.assertRaises((ValueError, TypeError)):
            Sum().update(jnp.asarray([1.0]), weight="x")

"""MetricCollection routing and equivalence.

Two lanes exist since the unification (metrics/collection.py +
metrics/deferred.py):

* deferred array-state metrics (counters AND regression/aggregation) — O(1)
  appends, one bulk group fold per budget window / read;
* host-state metrics (AUROC caches, deque windows) — eager appends.

All lanes must agree with the standalone metrics bit-for-bit.
"""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.metrics import roc_auc_score

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    Mean,
    MeanSquaredError,
    MetricCollection,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    Sum,
)

RNG = np.random.default_rng(0)


class TestMetricCollection(unittest.TestCase):
    def test_deferred_counters_match_eager(self):
        # counter metrics defer: collection routes them to the append path
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=7),
                "f1": MulticlassF1Score(num_classes=7, average="macro"),
                "cm": MulticlassConfusionMatrix(7),
            }
        )
        eager = {
            "acc": MulticlassAccuracy(num_classes=7),
            "f1": MulticlassF1Score(num_classes=7, average="macro"),
            "cm": MulticlassConfusionMatrix(7),
        }
        self.assertEqual(set(col._deferred), {"acc", "f1", "cm"})
        for _ in range(4):
            x = RNG.random((64, 7)).astype(np.float32)
            t = RNG.integers(0, 7, 64)
            col.update(x, t)
            for m in eager.values():
                m.update(x, t)
        out = col.compute()
        for name, m in eager.items():
            np.testing.assert_allclose(
                np.asarray(out[name]), np.asarray(m.compute()), rtol=1e-6
            )

    def test_array_state_metrics_defer(self):
        # aggregation metrics ride the deferred lane since the unification:
        # appends per update, ONE group fold at read; results must match the
        # standalone metrics
        col = MetricCollection({"sum": Sum(), "mean": Mean()})
        self.assertEqual(set(col._deferred), {"sum", "mean"})
        ref_sum, ref_mean = Sum(), Mean()
        for _ in range(4):
            x = RNG.random(128).astype(np.float32)
            col.update(x)
            ref_sum.update(x)
            ref_mean.update(x)
        out = col.compute()
        self.assertAlmostEqual(float(out["sum"]), float(ref_sum.compute()), places=4)
        self.assertAlmostEqual(float(out["mean"]), float(ref_mean.compute()), places=5)

    def test_mixed_deferred_and_cache_metric(self):
        # BinaryAccuracy (deferred counters) + BinaryAUROC (cache, eager)
        # share the same (input, target) update signature
        col = MetricCollection(
            {"bacc": BinaryAccuracy(), "auroc": BinaryAUROC()}
        )
        self.assertEqual(set(col._deferred), {"bacc"})  # auroc: host cache
        xs, ts = [], []
        for _ in range(3):
            x = RNG.random(128).astype(np.float32)
            t = RNG.integers(0, 2, 128).astype(np.float32)
            xs.append(x)
            ts.append(t)
            col.update(x, t)
        out = col.compute()
        X, T = np.concatenate(xs), np.concatenate(ts)
        self.assertAlmostEqual(
            float(out["bacc"]), float(((X >= 0.5) == T).mean()), places=6
        )
        self.assertAlmostEqual(
            float(out["auroc"]), roc_auc_score(T, X), places=5
        )

    def test_single_metric_form(self):
        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        x = jnp.eye(3)
        t = jnp.arange(3)
        col.update(x, t)
        self.assertEqual(float(col.compute()), 1.0)
        col.reset()
        self.assertEqual(float(col["metric"].num_total), 0.0)

    def test_repeated_updates_then_read(self):
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        x = RNG.random((32, 4)).astype(np.float32)
        t = RNG.integers(0, 4, 32)
        for _ in range(5):
            col.update(x, t)
        # state_dicts folds pending deferred batches before snapshotting
        sd = col.state_dicts()["metric"]
        self.assertEqual(float(sd["num_total"]), 160.0)

    def test_empty_collection_rejected(self):
        with self.assertRaisesRegex(ValueError, "at least one"):
            MetricCollection({})

    def test_state_dict_still_live(self):
        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        col.update(jnp.eye(3), jnp.arange(3))
        sd = col.state_dicts()["metric"]
        self.assertEqual(float(sd["num_total"]), 3.0)

    def test_state_dict_snapshot_is_a_copy(self):
        # a state_dict taken between updates must be a real buffer copy,
        # unaffected by later folds (and, for fused metrics, donation)
        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        col.update(jnp.eye(3), jnp.arange(3))
        sd = col.state_dicts()["metric"]
        col.update(jnp.eye(3), jnp.arange(3))
        self.assertEqual(float(sd["num_total"]), 3.0)  # snapshot intact
        self.assertEqual(float(col.state_dicts()["metric"]["num_total"]), 6.0)
        # and reset re-creates usable state
        col.reset()
        col.update(jnp.eye(3), jnp.arange(3))
        self.assertEqual(float(col.state_dicts()["metric"]["num_total"]), 3.0)

    def test_state_dict_snapshot_survives_donation(self):
        # deferred lane: the next FOLD donates the live buffers the snapshot
        # was taken from (on donating backends); the snapshot must be a real
        # copy
        col = MetricCollection(Sum())
        col.update(jnp.arange(3.0))
        sd = col.state_dicts()["metric"]  # folds, then snapshots
        col.update(jnp.arange(3.0))
        self.assertEqual(float(col.compute()), 6.0)  # fold donates prior state
        self.assertEqual(float(sd["weighted_sum"]), 3.0)  # snapshot intact
        col.reset()
        col.update(jnp.arange(3.0))
        self.assertEqual(float(col.compute()), 3.0)


class TestCollectionTorchBridge(unittest.TestCase):
    def test_torch_tensors_through_collection(self):
        import torch

        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        col.update(torch.eye(3), torch.arange(3))
        self.assertEqual(float(col.compute()), 1.0)

    def test_torch_tensors_through_deferred_array_state_path(self):
        import torch

        col = MetricCollection(MeanSquaredError())
        col.update(torch.zeros(4), torch.ones(4))
        self.assertEqual(float(col.compute()), 1.0)

    def test_non_donated_fold_on_tunneled_backend(self):
        # on a tunneled backend the donation gate compiles the deferred fold
        # WITHOUT donate_argnums (utils/platform.py); results must be
        # identical and repeated updates must not touch deleted buffers
        from unittest import mock

        import torcheval_tpu.metrics.collection as collection_mod

        with mock.patch(
            "torcheval_tpu.utils.platform.donation_pipelines", return_value=False
        ):
            col = collection_mod.MetricCollection(Mean())
            rng = np.random.default_rng(7)
            xs = rng.random((3, 32)).astype(np.float32)
            for row in xs:
                col.update(jnp.asarray(row))
            self.assertAlmostEqual(
                float(col.compute()), float(xs.mean()), places=6
            )

    def test_clone_survives_later_folds(self):
        # clone_metric between updates must own its buffers (deferred lane
        # folds on deepcopy; fused lane donates on the next update)
        from torcheval_tpu.metrics.toolkit import clone_metric

        m = MulticlassAccuracy(num_classes=3)
        col = MetricCollection(m)
        col.update(jnp.eye(3), jnp.arange(3))
        snap = clone_metric(m)
        col.update(jnp.eye(3), jnp.arange(3))
        self.assertEqual(float(snap.num_total), 3.0)


if __name__ == "__main__":
    unittest.main()


class TestGroupFoldFallback(unittest.TestCase):
    def test_directly_updated_member_falls_back_per_member(self):
        # a member updated OUTSIDE the collection has misaligned pending:
        # group_fold must fall back to per-member folds, never mix streams
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=4),
                "cm": MulticlassConfusionMatrix(4),
            }
        )
        x = RNG.random((32, 4)).astype(np.float32)
        t = RNG.integers(0, 4, 32)
        col.update(x, t)
        extra_x = RNG.random((16, 4)).astype(np.float32)
        extra_t = RNG.integers(0, 4, 16)
        col["acc"].update(extra_x, extra_t)  # direct update, acc only
        col.update(x, t)
        out = col.compute()
        X = np.concatenate([x, extra_x, x])
        T = np.concatenate([t, extra_t, t])
        self.assertAlmostEqual(float(out["acc"]), (X.argmax(1) == T).mean(), places=6)
        # cm never saw the extra batch
        self.assertEqual(int(np.asarray(out["cm"]).sum()), 64)

    def test_managed_member_hard_valve_still_folds(self):
        # direct streaming into a managed member must stay memory-bounded
        # (self-fold at 2x budget)
        m = MulticlassAccuracy(num_classes=3)
        MetricCollection(m)  # marks managed
        m._DEFER_MAX_CHUNKS = 4
        x = jnp.eye(3)
        t = jnp.arange(3)
        for _ in range(20):
            m.update(x, t)
        self.assertLessEqual(len(m._pending), 8)  # valve fired
        self.assertEqual(float(m.compute()), 1.0)

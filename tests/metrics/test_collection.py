"""MetricCollection: one fused jitted dispatch must equal the eager paths."""

import unittest

import jax
import jax.numpy as jnp
import numpy as np
from sklearn.metrics import roc_auc_score

from torcheval_tpu.metrics import (
    BinaryAccuracy,
    BinaryAUROC,
    MetricCollection,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
)

RNG = np.random.default_rng(0)


class TestMetricCollection(unittest.TestCase):
    def test_fused_matches_eager(self):
        col = MetricCollection(
            {
                "acc": MulticlassAccuracy(num_classes=7),
                "f1": MulticlassF1Score(num_classes=7, average="macro"),
                "cm": MulticlassConfusionMatrix(7),
            }
        )
        eager = {
            "acc": MulticlassAccuracy(num_classes=7),
            "f1": MulticlassF1Score(num_classes=7, average="macro"),
            "cm": MulticlassConfusionMatrix(7),
        }
        self.assertEqual(set(col._fused), {"acc", "f1", "cm"})
        for _ in range(4):
            x = RNG.random((64, 7)).astype(np.float32)
            t = RNG.integers(0, 7, 64)
            col.update(x, t)
            for m in eager.values():
                m.update(x, t)
        out = col.compute()
        for name, m in eager.items():
            np.testing.assert_allclose(
                np.asarray(out[name]), np.asarray(m.compute()), rtol=1e-6
            )

    def test_mixed_fused_and_cache_metric(self):
        # BinaryAccuracy (array state, fuses) + BinaryAUROC (cache, eager)
        # share the same (input, target) update signature
        col = MetricCollection(
            {"bacc": BinaryAccuracy(), "auroc": BinaryAUROC()}
        )
        self.assertEqual(col._fused, ["bacc"])
        self.assertEqual(col._eager, ["auroc"])
        xs, ts = [], []
        for _ in range(3):
            x = RNG.random(128).astype(np.float32)
            t = RNG.integers(0, 2, 128).astype(np.float32)
            xs.append(x)
            ts.append(t)
            col.update(x, t)
        out = col.compute()
        X, T = np.concatenate(xs), np.concatenate(ts)
        self.assertAlmostEqual(
            float(out["bacc"]), float(((X >= 0.5) == T).mean()), places=6
        )
        self.assertAlmostEqual(
            float(out["auroc"]), roc_auc_score(T, X), places=5
        )

    def test_single_metric_form(self):
        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        x = jnp.eye(3)
        t = jnp.arange(3)
        col.update(x, t)
        self.assertEqual(float(col.compute()), 1.0)
        col.reset()
        self.assertEqual(float(col["metric"].num_total), 0.0)

    def test_repeated_updates_after_donation(self):
        # donated buffers must be transparently replaced between calls
        col = MetricCollection(MulticlassAccuracy(num_classes=4))
        x = RNG.random((32, 4)).astype(np.float32)
        t = RNG.integers(0, 4, 32)
        for _ in range(5):
            col.update(x, t)
        self.assertEqual(float(col["metric"].num_total), 160.0)

    def test_empty_collection_rejected(self):
        with self.assertRaisesRegex(ValueError, "at least one"):
            MetricCollection({})

    def test_state_dict_still_live(self):
        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        col.update(jnp.eye(3), jnp.arange(3))
        sd = col.state_dicts()["metric"]
        self.assertEqual(float(sd["num_total"]), 3.0)

    def test_state_dict_snapshot_survives_donation(self):
        # a state_dict taken between updates must be a real buffer copy: the
        # next fused update donates the live buffers it was taken from
        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        col.update(jnp.eye(3), jnp.arange(3))
        sd = col.state_dicts()["metric"]
        col.update(jnp.eye(3), jnp.arange(3))  # donates previous live state
        self.assertEqual(float(sd["num_total"]), 3.0)  # snapshot intact
        # and reset after donation re-creates usable state
        col.reset()
        col.update(jnp.eye(3), jnp.arange(3))
        self.assertEqual(float(col["metric"].num_total), 3.0)




class TestCollectionTorchBridge(unittest.TestCase):
    def test_torch_tensors_through_fused_path(self):
        import torch

        col = MetricCollection(MulticlassAccuracy(num_classes=3))
        col.update(torch.eye(3), torch.arange(3))
        self.assertEqual(float(col.compute()), 1.0)

    def test_non_donated_step_on_tunneled_backend(self):
        # on a tunneled backend the donation gate compiles the fused step
        # WITHOUT donate_argnums (utils/platform.py); results must be
        # identical and repeated updates must not touch deleted buffers
        from unittest import mock

        import torcheval_tpu.metrics.collection as collection_mod

        with mock.patch(
            "torcheval_tpu.utils.platform.donation_pipelines", return_value=False
        ):
            col = collection_mod.MetricCollection(
                MulticlassAccuracy(num_classes=4)
            )
            rng = np.random.default_rng(7)
            scores = rng.random((32, 4)).astype(np.float32)
            labels = rng.integers(0, 4, 32)
            for _ in range(3):
                col.update(jnp.asarray(scores), jnp.asarray(labels))
            want = float(
                np.mean(scores.argmax(1) == labels)
            )
            self.assertAlmostEqual(float(col.compute()), want, places=6)

    def test_clone_survives_donation(self):
        # clone_metric between fused updates must own its buffers
        from torcheval_tpu.metrics.toolkit import clone_metric

        m = MulticlassAccuracy(num_classes=3)
        col = MetricCollection(m)
        col.update(jnp.eye(3), jnp.arange(3))
        snap = clone_metric(m)
        col.update(jnp.eye(3), jnp.arange(3))  # donates m's previous buffers
        self.assertEqual(float(snap.num_total), 3.0)

if __name__ == "__main__":
    unittest.main()

"""Sketch state across the durability machinery (ISSUE 13 satellite).

The sketch's whole design bet is that plain int32 SUM state trees ride
every existing lifecycle layer unchanged. Pinned end to end:

* mid-stream ``resilience.snapshot`` round trip is bit-identical with
  staged (unfolded) rows pending — restore + continue == uninterrupted;
* serve evict → reattach resumes an approx tenant bit-identically to an
  uninterrupted oracle (the ISSUE 8 eviction checkpoints, no new code);
* a checkpoint whose staging was folded through the SHARDED sketch-psum
  path (8-device mesh) restores onto a plain single-device metric with
  identical counts — the replicated-leaf portability contract at unequal
  device counts (``docs/robustness.md``, "Checkpoint portability").
"""

import shutil
import tempfile
import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu import resilience
from torcheval_tpu.metrics import BinaryAUROC, Quantile

RNG = np.random.default_rng(31)


def _batches(k=6, n=700, seed=0):
    rng = np.random.default_rng(seed)
    return [
        (
            (rng.lognormal(0, 3, n)).astype(np.float32),
            (rng.random(n) < 0.4).astype(np.float32),
        )
        for _ in range(k)
    ]


class _TmpDirTest(unittest.TestCase):
    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="sketch_lc_")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)


class TestSnapshotMidStream(_TmpDirTest):
    def test_mid_stream_round_trip_and_resume_bit_identical(self):
        batches = _batches()
        oracle = BinaryAUROC(approx=4096, compaction_threshold=1024)
        for s, t in batches:
            oracle.update(s, t)
        want = float(oracle.compute())

        m = BinaryAUROC(approx=4096, compaction_threshold=1024)
        for s, t in batches[:3]:
            m.update(s, t)
        self.assertTrue(m.inputs)  # staged rows genuinely pending
        resilience.save(m, self.dir)
        restored = BinaryAUROC(approx=4096, compaction_threshold=1024)
        resilience.restore(restored, self.dir)
        for s, t in batches[3:]:
            restored.update(s, t)
        self.assertEqual(float(restored.compute()), want)
        restored._compact()
        oracle._compact()
        np.testing.assert_array_equal(
            np.asarray(restored.sketch_tp), np.asarray(oracle.sketch_tp)
        )

    def test_quantile_schema_guard_on_bucket_count_drift(self):
        m = Quantile(0.5, bucket_count=4096)
        m.update(np.float32([1.0, 2.0]))
        resilience.save(m, self.dir)
        other = Quantile(0.5, bucket_count=8192)
        with self.assertRaises(resilience.CheckpointError):
            resilience.restore(other, self.dir)


class TestServeEvictReattach(_TmpDirTest):
    def test_evict_then_reattach_resumes_bit_identically(self):
        from torcheval_tpu.serve import EvalDaemon

        batches = _batches(k=6, seed=3)
        oracle = BinaryAUROC(approx=4096)
        for s, t in batches:
            oracle.update(s, t)
        want = float(oracle.compute())
        with EvalDaemon(evict_dir=self.dir) as daemon:
            h = daemon.attach("tenant", BinaryAUROC(approx=4096))
            for s, t in batches[:3]:
                h.submit(s, t)
            path = daemon.evict("tenant", timeout=60)
            self.assertTrue(path)
            h2 = daemon.attach(
                "tenant", BinaryAUROC(approx=4096), resume="require"
            )
            for s, t in batches[3:]:
                h2.submit(s, t)
            self.assertEqual(
                float(np.asarray(h2.compute(timeout=60))), want
            )


class TestPortabilityAcrossDeviceCounts(_TmpDirTest):
    def test_sharded_fold_checkpoint_restores_on_single_device(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = jax.devices()
        if len(devs) < 8:
            self.skipTest("needs the 8-device CPU mesh (tests/conftest.py)")
        mesh = Mesh(np.array(devs[:8]), ("data",))
        sh = NamedSharding(mesh, P("data"))
        n = 4096
        s = RNG.normal(size=n).astype(np.float32)
        t = (RNG.random(n) < 0.5).astype(np.float32)

        m = BinaryAUROC(approx=4096)
        m.inputs.append(jax.device_put(jnp.asarray(s), sh))
        m.targets.append(jax.device_put(jnp.asarray(t), sh))
        m._cached_samples = n
        m._compact()  # the sharded sketch-psum fold (dist_curves)
        self.assertEqual(m.inputs, [])
        resilience.save(m, self.dir)

        single = BinaryAUROC(approx=4096, device=devs[0])
        resilience.restore(single, self.dir)
        plain = BinaryAUROC(approx=4096)
        plain.update(s, t)
        plain._compact()
        np.testing.assert_array_equal(
            np.asarray(single.sketch_tp), np.asarray(plain.sketch_tp)
        )
        np.testing.assert_array_equal(
            np.asarray(single.sketch_fp), np.asarray(plain.sketch_fp)
        )
        self.assertEqual(float(single.compute()), float(plain.compute()))


if __name__ == "__main__":
    unittest.main()

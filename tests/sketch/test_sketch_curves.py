"""Approx-mode curve metrics vs the exact kernels (ISSUE 13 acceptance).

Pins the three acceptance criteria on ADVERSARIAL score distributions
(ties, heavy tails, degenerate labels, NaN policy):

* ``approx=`` AUROC/AUPRC/PRC match the exact kernels within the
  documented, a-posteriori-computable bound (``sketch.auroc_error_bound``
  / ``auprc_error_bound``) — asserted against the bound computed from the
  ACTUAL sketch, not a tolerance guess;
* resident memory is O(buckets) regardless of stream length (asserted:
  state bytes identical after 10x more data, staging bounded by the fold
  cadence);
* ``merge_state`` of sketch states is exact bucket-add — merged ==
  single-stream bit-identical.
"""

import unittest

import numpy as np

from torcheval_tpu import sketch
from torcheval_tpu.metrics import (
    BinaryAUPRC,
    BinaryAUROC,
    BinaryPrecisionRecallCurve,
    MulticlassAUPRC,
    MulticlassAUROC,
    MulticlassPrecisionRecallCurve,
)

RNG = np.random.default_rng(1234)


def _streams():
    """Named adversarial binary streams: (scores, targets) chunk lists."""
    n = 3000

    def chunks(s, t, k=4):
        return list(
            zip(np.array_split(s.astype(np.float32), k), np.array_split(t, k))
        )

    smooth_s = RNG.normal(size=n).astype(np.float32)
    heavy_s = np.concatenate(
        [RNG.lognormal(0, 5, n // 2), -RNG.lognormal(0, 5, n - n // 2)]
    ).astype(np.float32)
    tied_s = RNG.choice(np.float32([0.1, 0.5, 0.5, 0.9]), n)
    const_s = np.full(n, np.float32(0.25))
    t = (RNG.random(n) < 0.35).astype(np.float32)
    all_pos = np.ones(n, np.float32)
    return {
        "smooth": chunks(smooth_s, t),
        "heavy_tail": chunks(heavy_s, t),
        "massive_ties": chunks(tied_s, t),
        "constant": chunks(const_s, t),
        "degenerate_labels": chunks(smooth_s, all_pos),
    }


def _fill(metric, stream):
    for s, t in stream:
        metric.update(s, t)
    return metric


class TestBinaryWithinBound(unittest.TestCase):
    def test_auroc_auprc_within_computed_bound(self):
        for name, stream in _streams().items():
            for cls in (BinaryAUROC, BinaryAUPRC):
                exact = _fill(cls(), stream)
                approx = _fill(
                    cls(approx=True, compaction_threshold=1024), stream
                )
                e, a = float(exact.compute()), float(approx.compute())
                approx._compact()  # expose the full resident sketch
                bound = (
                    sketch.auroc_error_bound
                    if cls is BinaryAUROC
                    else sketch.auprc_error_bound
                )(approx.sketch_tp, approx.sketch_fp)
                self.assertLessEqual(
                    abs(e - a), bound + 1e-6, f"{cls.__name__}/{name}"
                )

    def test_pure_tie_streams_are_error_free(self):
        # exact score ties are ties in the exact kernel too: binning adds
        # ZERO error — the adversarial case that breaks naive binning bounds
        stream = _streams()["massive_ties"]
        for cls in (BinaryAUROC, BinaryAUPRC):
            e = float(_fill(cls(), stream).compute())
            a = float(_fill(cls(approx=True), stream).compute())
            self.assertAlmostEqual(e, a, places=6, msg=cls.__name__)

    def test_empty_defaults_match_exact(self):
        self.assertEqual(float(BinaryAUROC(approx=True).compute()), 0.5)
        self.assertEqual(float(BinaryAUPRC(approx=True).compute()), 0.0)

    def test_nan_scores_raise_at_compute(self):
        m = BinaryAUROC(approx=True)
        m.update(np.float32([0.2, np.nan, 0.7]), np.float32([1, 0, 1]))
        with self.assertRaisesRegex(ValueError, "NaN"):
            m.compute()
        # the poisoned sketch keeps raising after a fold, too
        m._compact()
        with self.assertRaisesRegex(ValueError, "NaN"):
            m.compute()

    def test_compute_idempotent_and_inf_scores_ok(self):
        m = BinaryAUROC(approx=True)
        m.update(
            np.float32([np.inf, -np.inf, 0.5, 0.1]),
            np.float32([1, 0, 1, 0]),
        )
        first = float(m.compute())
        self.assertEqual(first, float(m.compute()))
        exact = BinaryAUROC()
        exact.update(
            np.float32([np.inf, -np.inf, 0.5, 0.1]),
            np.float32([1, 0, 1, 0]),
        )
        self.assertAlmostEqual(first, float(exact.compute()), places=6)


class TestBoundedMemory(unittest.TestCase):
    def _resident_bytes(self, m):
        m._compact()
        return sum(
            int(np.asarray(v).nbytes)
            for v in (m.sketch_tp, m.sketch_fp, m.sketch_nan_dropped)
        )

    def test_state_bytes_independent_of_stream_length(self):
        def run(n_batches):
            m = BinaryAUROC(approx=4096, compaction_threshold=2048)
            for i in range(n_batches):
                rng = np.random.default_rng(i)
                m.update(
                    rng.random(512).astype(np.float32),
                    (rng.random(512) < 0.5).astype(np.float32),
                )
                # the staging cache never outgrows the fold cadence
                self.assertLess(
                    sum(int(a.shape[0]) for a in m.inputs), 2048 + 512
                )
            return self._resident_bytes(m)

        self.assertEqual(run(5), run(50))
        self.assertEqual(run(5), 2 * 4096 * 4 + 4)

    def test_sync_payload_is_bounded(self):
        # _prepare_for_merge_state folds staging: the wire ships ONLY the
        # fixed-size sketch (+ empty CAT descriptors), never raw samples
        m = BinaryAUROC(approx=4096)
        m.update(
            RNG.random(10_000).astype(np.float32),
            (RNG.random(10_000) < 0.5).astype(np.float32),
        )
        m._prepare_for_merge_state()
        self.assertEqual(m.inputs, [])
        self.assertEqual(m.targets, [])


class TestExactMerge(unittest.TestCase):
    def test_merged_equals_single_stream_bit_identical(self):
        stream = _streams()["heavy_tail"]
        solo = _fill(BinaryAUROC(approx=True), stream)
        a = _fill(BinaryAUROC(approx=True), stream[:2])
        b = _fill(BinaryAUROC(approx=True), stream[2:3])
        c = _fill(BinaryAUROC(approx=True), stream[3:])
        b._compact()  # mixed folded/staged replicas must still merge exactly
        a.merge_state([b, c])
        a._compact()
        solo._compact()
        np.testing.assert_array_equal(
            np.asarray(a.sketch_tp), np.asarray(solo.sketch_tp)
        )
        np.testing.assert_array_equal(
            np.asarray(a.sketch_fp), np.asarray(solo.sketch_fp)
        )
        self.assertEqual(float(a.compute()), float(solo.compute()))

    def test_reset_restores_zero_sketch(self):
        m = _fill(BinaryAUROC(approx=True), _streams()["smooth"])
        m.reset()
        self.assertEqual(int(np.asarray(m.sketch_tp).sum()), 0)
        self.assertEqual(float(m.compute()), 0.5)


class TestMulticlass(unittest.TestCase):
    def _mc_stream(self, c=6, n=4000, k=4):
        s = RNG.random((n, c)).astype(np.float32)
        lbl = RNG.integers(0, c, n)
        return list(zip(np.array_split(s, k), np.array_split(lbl, k)))

    def test_per_class_within_bound(self):
        c = 6
        stream = self._mc_stream(c)
        for cls in (MulticlassAUROC, MulticlassAUPRC):
            exact = _fill(cls(num_classes=c, average=None), stream)
            approx = _fill(
                cls(num_classes=c, average=None, approx=True), stream
            )
            e = np.asarray(exact.compute())
            a = np.asarray(approx.compute())
            approx._compact()
            bound_fn = (
                sketch.auroc_error_bound
                if cls is MulticlassAUROC
                else sketch.auprc_error_bound
            )
            for ci in range(c):
                self.assertLessEqual(
                    abs(float(e[ci]) - float(a[ci])),
                    bound_fn(approx.sketch_tp[ci], approx.sketch_fp[ci])
                    + 1e-6,
                    f"{cls.__name__} class {ci}",
                )

    def test_macro_average_and_merge(self):
        c = 4
        stream = self._mc_stream(c)
        solo = _fill(MulticlassAUROC(num_classes=c, approx=True), stream)
        x = _fill(MulticlassAUROC(num_classes=c, approx=True), stream[:2])
        y = _fill(MulticlassAUROC(num_classes=c, approx=True), stream[2:])
        x.merge_state([y])
        self.assertEqual(float(x.compute()), float(solo.compute()))


class TestPRCApprox(unittest.TestCase):
    def test_binary_curve_shape_and_endpoint_parity(self):
        s = RNG.random(5000).astype(np.float32)
        t = (RNG.random(5000) < 0.4).astype(np.float32)
        exact = BinaryPrecisionRecallCurve()
        approx = BinaryPrecisionRecallCurve(approx=True)
        exact.update(s, t)
        approx.update(s, t)
        p1, r1, t1 = exact.compute()
        p2, r2, t2 = approx.compute()
        self.assertEqual(p2.shape[0], r2.shape[0])
        self.assertEqual(p2.shape[0], t2.shape[0] + 1)
        # thresholds ascend; the graph origin is appended (reference layout)
        self.assertTrue((np.diff(np.asarray(t2)) > 0).all())
        self.assertEqual(float(p2[-1]), 1.0)
        self.assertEqual(float(r2[-1]), 0.0)
        # the most-permissive-threshold point is exact: every sample is
        # predicted positive in both layouts
        self.assertAlmostEqual(float(p1[0]), float(p2[0]), places=6)
        self.assertAlmostEqual(float(r1[0]), float(r2[0]), places=6)

    def test_thresholds_within_relative_error_of_scores(self):
        # scores land in buckets whose representatives are the thresholds:
        # each reported threshold must be within the documented relative
        # error of SOME true score (here: scores are one repeated value)
        m = BinaryPrecisionRecallCurve(approx=True)
        m.update(np.full(64, np.float32(0.625)), np.ones(64, np.float32))
        _, _, th = m.compute()
        self.assertEqual(th.shape[0], 1)
        self.assertLessEqual(
            abs(float(th[0]) - 0.625) / 0.625, sketch.relative_error(16)
        )

    def test_multiclass_requires_num_classes_and_merges(self):
        with self.assertRaisesRegex(ValueError, "num_classes"):
            MulticlassPrecisionRecallCurve(approx=True)
        c = 3
        m = MulticlassPrecisionRecallCurve(num_classes=c, approx=True)
        s = RNG.random((2000, c)).astype(np.float32)
        lbl = RNG.integers(0, c, 2000)
        m.update(s, lbl)
        ps, rs, ts = m.compute()
        self.assertEqual(len(ps), c)
        for p, r, th in zip(ps, rs, ts):
            self.assertEqual(p.shape[0], th.shape[0] + 1)
        # NaN raises with the multiclass noun
        bad = MulticlassPrecisionRecallCurve(num_classes=c, approx=True)
        sb = s.copy()
        sb[0, 1] = np.nan
        bad.update(sb, lbl)
        with self.assertRaisesRegex(ValueError, "per-class"):
            bad.compute()


class TestInt32ExactnessEdge(unittest.TestCase):
    def test_compute_fails_closed_past_int32_total(self):
        import jax.numpy as jnp

        # a genuine 2.2B-row stream is not testable; install the state a
        # long stream would produce (per-bucket counts fine, TOTAL past
        # 2^31) and assert compute refuses instead of wrapping cumsums
        m = BinaryAUROC(approx=4096)
        big = np.zeros(4096, np.int32)
        big[:4] = 2**29
        m.sketch_tp = jnp.asarray(big)
        m.sketch_fp = jnp.asarray(big)
        with self.assertRaisesRegex(ValueError, "int32-exact"):
            m.compute()

    def test_wrapped_bucket_detected(self):
        import jax.numpy as jnp

        m = BinaryAUPRC(approx=4096)
        bad = np.zeros(4096, np.int32)
        bad[7] = -5  # a per-bucket add that wrapped
        m.sketch_tp = jnp.asarray(bad)
        with self.assertRaisesRegex(ValueError, "int32-exact"):
            m.compute()

    def test_multiclass_bound_is_per_class_not_global(self):
        import jax.numpy as jnp

        # 1000 classes x ~2.1M samples each: the GRAND total is ~2.1e9 but
        # every per-class cumsum (the actual wrap risk) is tiny — must NOT
        # trip (review finding: a cross-class sum raised ~C times early)
        from torcheval_tpu.sketch.histogram import counts_exactness_flag

        per_class = np.zeros((1000, 4096), np.int32)
        per_class[:, :2] = 2**20
        self.assertFalse(bool(counts_exactness_flag(jnp.asarray(per_class))))
        # but a single class crossing the edge DOES trip
        hot = per_class.copy()
        hot[3, :4] = 2**29
        self.assertTrue(bool(counts_exactness_flag(jnp.asarray(hot))))

    def test_normal_totals_do_not_trip(self):
        m = BinaryAUROC(approx=4096)
        m.update(
            RNG.random(4096).astype(np.float32),
            (RNG.random(4096) < 0.5).astype(np.float32),
        )
        m.compute()  # no raise


class TestKnobsAndLifecycle(unittest.TestCase):
    def test_configurable_bucket_count(self):
        m = BinaryAUROC(approx=4096)
        self.assertEqual(np.asarray(m.sketch_tp).shape, (4096,))
        with self.assertRaises(ValueError):
            BinaryAUROC(approx=3000)

    def test_env_knob_opt_in_and_opt_out(self):
        import os
        from unittest import mock

        with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_APPROX": "1"}):
            self.assertTrue(BinaryAUROC()._sketch_enabled())
            self.assertFalse(BinaryAUROC(approx=False)._sketch_enabled())
        self.assertFalse(BinaryAUROC()._sketch_enabled())

    def test_state_dict_round_trip_bit_identical(self):
        stream = _streams()["smooth"]
        m = _fill(BinaryAUROC(approx=True), stream)
        sd = m.state_dict()
        fresh = BinaryAUROC(approx=True)
        fresh.load_state_dict(sd)
        self.assertEqual(float(fresh.compute()), float(m.compute()))


if __name__ == "__main__":
    unittest.main()

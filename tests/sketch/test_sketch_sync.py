"""Sketch state on the two-round sync wire (ISSUE 13 satellite).

Drives the REAL collection-sync machinery (encode → descriptor round →
payload round → per-rank decode → typed fold) over the barrier-threaded
simulated wire from ``tests/metrics/test_sync_quantized.py``, at world
size 4:

* ``sync_and_compute``-style merge of approx curve metrics and
  ``Quantile`` equals the single-stream oracle BIT-identically — the
  sketch lanes are integer SUM states, so the fold is exact bucket-add
  on every transport;
* with the codecs on, sketch lanes encode under the ISSUE 13 ``bucket``
  codec (sparse nonzero payload) and the
  ``lane_bytes``/``lane_bytes_encoded`` pair shows the required shrink
  (>= 4x asserted — realistic sketches land far beyond);
* a rank-local NaN flag survives the wire (summed) and still raises on
  every rank after sync;
* everything here must ALSO pass with ``TORCHEVAL_TPU_SYNC_QUANTIZE=1``
  in the env — CI re-runs this file exactly so (lossless codecs).
"""

import threading
import unittest
from unittest import mock

import numpy as np

import torcheval_tpu.metrics.toolkit as tk
from torcheval_tpu import obs
from torcheval_tpu.metrics import BinaryAUROC, Quantile

WORLD = 4


class _SimWire:
    """Barrier-coordinated allgather stub (the test_sync_quantized shape):
    each rank thread contributes its own buffer and receives the genuine
    per-rank stack."""

    def __init__(self, world):
        self.world = world
        self.barrier = threading.Barrier(world)
        self.slots = [None] * world
        self.tls = threading.local()
        self.round_bytes = []
        self._lock = threading.Lock()

    def allgather(self, x, group):
        assert group is None
        rank = self.tls.rank
        self.slots[rank] = np.array(x, copy=True)
        self.barrier.wait()
        out = np.stack(self.slots)
        with self._lock:
            self.round_bytes.append(int(np.asarray(x).nbytes))
        self.barrier.wait()
        return out


def run_world(world, fn):
    sim = _SimWire(world)
    results = [None] * world
    errors = []

    def runner(rank):
        sim.tls.rank = rank
        try:
            results[rank] = fn(rank)
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append((rank, e))

    with mock.patch.object(
        tk, "_allgather_stacked_impl", sim.allgather
    ), mock.patch.object(tk, "_world_size", lambda: world), mock.patch.object(
        tk, "_process_index", lambda: sim.tls.rank
    ):
        threads = [
            threading.Thread(target=runner, args=(r,)) for r in range(world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0][1]
    return results, sim


def _rank_chunks(rank, n=2048):
    rng = np.random.default_rng(500 + rank)
    return (
        rng.lognormal(0, 3, n).astype(np.float32)
        * np.where(rng.random(n) < 0.5, -1, 1),
        (rng.random(n) < 0.4).astype(np.float32),
    )


def _auroc_replica(rank):
    m = BinaryAUROC(approx=4096, compaction_threshold=512)
    s, t = _rank_chunks(rank)
    m.update(s, t)
    return m


def _quantile_replica(rank):
    m = Quantile((0.1, 0.9), bucket_count=65536)
    s, _ = _rank_chunks(rank)
    m.update(s)
    return m


class TestSketchSync(unittest.TestCase):
    def test_synced_auroc_equals_single_stream_oracle_bit_identical(self):
        oracle = BinaryAUROC(approx=4096, compaction_threshold=512)
        for r in range(WORLD):
            s, t = _rank_chunks(r)
            oracle.update(s, t)
        want = float(oracle.compute())
        oracle._compact()

        def fn(rank):
            synced = tk.get_synced_metric(
                _auroc_replica(rank), recipient_rank="all"
            )
            return synced

        results, _ = run_world(WORLD, fn)
        for synced in results:
            synced._compact()
            np.testing.assert_array_equal(
                np.asarray(synced.sketch_tp), np.asarray(oracle.sketch_tp)
            )
            np.testing.assert_array_equal(
                np.asarray(synced.sketch_fp), np.asarray(oracle.sketch_fp)
            )
            self.assertEqual(float(synced.compute()), want)

    def test_synced_quantile_bit_identical_and_quantized_lossless(self):
        oracle = Quantile((0.1, 0.9), bucket_count=65536)
        for r in range(WORLD):
            oracle.update(_rank_chunks(r)[0])
        want = np.asarray(oracle.compute())
        for quantize in (False, True):
            results, _ = run_world(
                WORLD,
                lambda rank: tk.get_synced_metric(
                    _quantile_replica(rank),
                    recipient_rank="all",
                    quantize=quantize,
                ),
            )
            for synced in results:
                np.testing.assert_array_equal(
                    np.asarray(synced.compute()), want
                )

    def test_sketch_lanes_use_bucket_codec_with_big_ratio(self):
        obs.enable()
        try:
            obs.reset()
            _, sim_raw = run_world(
                WORLD,
                lambda rank: tk.get_synced_metric(
                    _quantile_replica(rank),
                    recipient_rank="all",
                    quantize=False,
                ),
            )
            obs.reset()  # counter ratio below reads the QUANTIZED run only
            _, sim_q = run_world(
                WORLD,
                lambda rank: tk.get_synced_metric(
                    _quantile_replica(rank),
                    recipient_rank="all",
                    quantize=True,
                ),
            )
            # payload round shrinks >= 4x (the ROADMAP 1(c) bar; a 2048-
            # sample sketch in 64Ki buckets actually lands far beyond)
            self.assertLessEqual(
                sim_q.round_bytes[-1] * 4, sim_raw.round_bytes[-1]
            )
            counters = obs.snapshot()["counters"]
            bucket_bytes = [
                v
                for k, v in counters.items()
                if k.startswith("toolkit.sync.lane_bytes_encoded")
                and "codec=bucket" in k
            ]
            self.assertTrue(bucket_bytes, sorted(counters))
            raw = sum(
                v
                for k, v in counters.items()
                if k.startswith("toolkit.sync.lane_bytes{lane=SUM")
            )
            enc = sum(
                v
                for k, v in counters.items()
                if k.startswith("toolkit.sync.lane_bytes_encoded")
            )
            self.assertLessEqual(enc * 4, raw)
        finally:
            obs.reset()
            obs.disable()

    def test_nan_flag_survives_the_wire(self):
        def fn(rank):
            m = BinaryAUROC(approx=4096)
            s, t = _rank_chunks(rank, n=64)
            if rank == 2:
                s = s.copy()
                s[0] = np.nan
            m.update(s, t)
            return tk.get_synced_metric(m, recipient_rank="all")

        results, _ = run_world(WORLD, fn)
        for synced in results:
            with self.assertRaisesRegex(ValueError, "NaN"):
                synced.compute()


if __name__ == "__main__":
    unittest.main()

"""Float-prefix bucket mapping invariants (torcheval_tpu.sketch.buckets).

The whole sketch subsystem rests on four properties pinned here: the order
key is monotone, buckets are value-range slices (every value lies inside
its bucket's edges), representatives honor the documented relative-error
bound for every finite normal magnitude (signs, tails and tiny values
included), and the mapping is a pure deterministic function (jit == eager,
vmap-safe) so cross-replica merges agree bucket-for-bucket.
"""

import unittest

import jax
import jax.numpy as jnp
import numpy as np

from torcheval_tpu import sketch
from torcheval_tpu.sketch import buckets

RNG = np.random.default_rng(42)


def _adversarial_values(n=4000):
    """Signs, heavy tails, ties, extremes — one pool for every test."""
    return np.concatenate(
        [
            (RNG.normal(size=n) * 10).astype(np.float32),
            RNG.lognormal(0, 6, n).astype(np.float32),  # heavy tail
            -RNG.lognormal(0, 6, n).astype(np.float32),
            np.repeat(np.float32([0.25, -0.25, 1e30, 1e-30]), 50),
            np.float32(
                [0.0, -0.0, np.inf, -np.inf, 3.4e38, -3.4e38, 1.18e-38]
            ),
        ]
    )


class TestKeyAndBuckets(unittest.TestCase):
    def test_key_monotone_and_zero_canonical(self):
        x = np.sort(_adversarial_values())
        k = np.asarray(sketch.ascending_key(jnp.asarray(x))).astype(np.int64)
        self.assertTrue((np.diff(k) >= 0).all())
        kz = np.asarray(
            sketch.ascending_key(jnp.asarray(np.float32([0.0, -0.0])))
        )
        self.assertEqual(kz[0], kz[1])

    def test_every_value_within_its_bucket_edges(self):
        x = _adversarial_values()
        for bits in (10, 14, 16):
            idx = np.asarray(sketch.bucket_index(jnp.asarray(x), bits))
            lo, hi = buckets.bucket_edges(bits)
            self.assertTrue((idx >= 0).all() and (idx < 1 << bits).all())
            self.assertTrue((lo[idx] <= x).all() and (x <= hi[idx]).all())

    def test_representative_relative_error_bound(self):
        x = _adversarial_values()
        # the documented bound covers finite normal magnitudes; subnormals
        # flush to the zero bucket (absolute error < 1.18e-38, documented)
        normal = np.isfinite(x) & (np.abs(x) >= np.finfo(np.float32).tiny)
        for bits in (10, 13, 16, 20):
            idx = np.asarray(
                sketch.bucket_index(jnp.asarray(x[normal]), bits)
            )
            reps = buckets.bucket_representatives(bits)[idx]
            rel = np.abs(reps - x[normal]) / np.abs(x[normal])
            self.assertLessEqual(rel.max(), sketch.relative_error(bits))

    def test_inf_buckets_and_nan_key(self):
        bits = 12
        idx = np.asarray(
            sketch.bucket_index(
                jnp.asarray(np.float32([np.inf, -np.inf])), bits
            )
        )
        reps = buckets.bucket_representatives(bits)
        self.assertEqual(reps[idx[0]], np.inf)
        self.assertEqual(reps[idx[1]], -np.inf)
        # NaN maps to the max key (callers mask it before counting)
        k = np.asarray(
            sketch.ascending_key(jnp.asarray(np.float32([np.nan])))
        )
        self.assertEqual(k[0], 0xFFFFFFFF)

    def test_jit_vmap_agree_with_eager(self):
        x = _adversarial_values()[:2000]
        bits = 14
        eager = np.asarray(sketch.bucket_index(jnp.asarray(x), bits))
        jitted = np.asarray(
            jax.jit(lambda v: sketch.bucket_index(v, bits))(jnp.asarray(x))
        )
        vmapped = np.asarray(
            jax.vmap(lambda v: sketch.bucket_index(v, bits))(
                jnp.asarray(x.reshape(50, -1))
            )
        ).reshape(-1)
        np.testing.assert_array_equal(eager, jitted)
        np.testing.assert_array_equal(eager, vmapped)

    def test_bucket_bits_validation(self):
        for bad in (9, 21, 0, -3, 2.5):
            with self.assertRaises(ValueError):
                buckets.check_bucket_bits(bad)

    def test_resolve_approx_knob(self):
        import os
        from unittest import mock

        from torcheval_tpu.sketch import resolve_approx

        self.assertIsNone(resolve_approx(None))
        self.assertIsNone(resolve_approx(False))
        self.assertEqual(resolve_approx(True, default_bits=14), 14)
        self.assertEqual(resolve_approx(4096), 12)
        with self.assertRaises(ValueError):
            resolve_approx(1000)  # not a power of two
        with self.assertRaises(ValueError):
            resolve_approx(2)  # below MIN_BUCKET_BITS
        with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_APPROX": "1"}):
            self.assertEqual(resolve_approx(None, default_bits=13), 13)
            self.assertIsNone(resolve_approx(False))  # explicit opt-out wins
        with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_APPROX": "8192"}):
            self.assertEqual(resolve_approx(None), 13)
        with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_APPROX": "bogus"}):
            with self.assertRaises(ValueError):
                resolve_approx(None)

    def test_sync_quantize_env_validation(self):
        import os
        from unittest import mock

        from torcheval_tpu.utils import quant

        for off in ("0", "", "false", "OFF"):
            with mock.patch.dict(
                os.environ, {"TORCHEVAL_TPU_SYNC_QUANTIZE": off}
            ):
                self.assertFalse(quant.sync_quantize_enabled())
                self.assertIs(quant.sync_quantize_mode(), False)
        for on, mode in (("1", "bf16"), ("true", "bf16"), ("int8", "int8")):
            with mock.patch.dict(
                os.environ, {"TORCHEVAL_TPU_SYNC_QUANTIZE": on}
            ):
                self.assertTrue(quant.sync_quantize_enabled())
                self.assertEqual(quant.sync_quantize_mode(), mode)
        # typos raise everywhere instead of silently aliasing to bf16
        with mock.patch.dict(
            os.environ, {"TORCHEVAL_TPU_SYNC_QUANTIZE": "in8t"}
        ):
            with self.assertRaises(ValueError):
                quant.sync_quantize_mode()
        with self.assertRaises(ValueError):
            quant.sync_quantize_mode("in8t")
        self.assertEqual(quant.sync_quantize_mode("INT8"), "int8")


if __name__ == "__main__":
    unittest.main()

"""``Quantile`` aggregation metric + value-sketch approx modes
(``HitRate``/``ReciprocalRank``/``Cat``) — ISSUE 13.

Quantile estimates are pinned against the true order statistic at rank
``ceil(q * n)`` (the documented ``inverted_cdf`` convention) within
``sketch.relative_error(bucket_bits)`` RELATIVE error on adversarial
distributions; merges are exact bucket adds (merged == single-stream
bit-identical); the metric rides the deferred window-step (one compiled
program in a collection) and the resilience checkpoint machinery as plain
state trees.
"""

import shutil
import tempfile
import unittest

import numpy as np

from torcheval_tpu import sketch
from torcheval_tpu.metrics import (
    Cat,
    HitRate,
    Mean,
    MetricCollection,
    Quantile,
    ReciprocalRank,
)

RNG = np.random.default_rng(77)


def _true_quantile(values, q):
    sv = np.sort(values)
    return float(sv[max(int(np.ceil(q * len(values))) - 1, 0)])


class TestQuantileAccuracy(unittest.TestCase):
    DISTS = {
        "lognormal_heavy": lambda n: RNG.lognormal(0, 4, n),
        "normal_signed": lambda n: RNG.normal(0, 100, n),
        "tied": lambda n: RNG.choice([1.0, 2.0, 2.0, 7.5], n),
        "tiny_and_huge": lambda n: np.concatenate(
            [RNG.lognormal(-60, 2, n // 2), RNG.lognormal(60, 2, n - n // 2)]
        ),
    }

    def test_within_relative_error_on_adversarial_distributions(self):
        qs = (0.0, 0.01, 0.25, 0.5, 0.9, 0.99, 1.0)
        for name, gen in self.DISTS.items():
            v = gen(20001).astype(np.float32)
            m = Quantile(q=qs)
            for chunk in np.array_split(v, 5):
                m.update(chunk)
            est = np.asarray(m.compute())
            for q, e in zip(qs, est):
                true = _true_quantile(v, q)
                self.assertLessEqual(
                    abs(float(e) - true),
                    sketch.relative_error(16) * abs(true) + 1.2e-38,
                    f"{name} q={q}",
                )

    def test_scalar_q_returns_scalar_and_validation(self):
        m = Quantile(0.5)
        m.update(np.float32([1, 2, 3]))
        self.assertEqual(np.asarray(m.compute()).shape, ())
        for bad_q in (-0.1, 1.5, float("nan"), ()):
            with self.assertRaises(ValueError):
                Quantile(bad_q)
        with self.assertRaises(ValueError):
            Quantile(0.5, bucket_count=1000)
        with self.assertRaises(ValueError):
            Quantile(0.5, nan_policy="bogus")

    def test_empty_is_nan(self):
        self.assertTrue(np.isnan(float(Quantile(0.5).compute())))

    def test_nan_policy(self):
        m = Quantile(0.5)
        m.update(np.float32([1.0, np.nan]))
        with self.assertRaisesRegex(ValueError, "NaN"):
            m.compute()
        ok = Quantile(0.5, nan_policy="ignore")
        ok.update(np.float32([np.nan, 2.0, 2.0, np.nan]))
        self.assertLessEqual(
            abs(float(ok.compute()) - 2.0) / 2.0, sketch.relative_error(16)
        )
        self.assertEqual(int(ok.nan_dropped), 2)

    def test_inf_quantiles(self):
        m = Quantile((0.0, 1.0))
        m.update(np.float32([-np.inf, 0.0, np.inf]))
        lo, hi = np.asarray(m.compute())
        self.assertEqual(lo, -np.inf)
        self.assertEqual(hi, np.inf)


class TestQuantileMergeAndLifecycle(unittest.TestCase):
    def test_merge_bit_identical_to_single_stream(self):
        v = RNG.lognormal(1, 2, 9000).astype(np.float32)
        solo, a, b = Quantile(0.5), Quantile(0.5), Quantile(0.5)
        for i, chunk in enumerate(np.array_split(v, 6)):
            (a if i % 2 else b).update(chunk)
            solo.update(chunk)
        a.merge_state([b])
        solo._fold_now()
        np.testing.assert_array_equal(
            np.asarray(a.bucket_counts), np.asarray(solo.bucket_counts)
        )
        self.assertEqual(float(a.compute()), float(solo.compute()))

    def test_rides_collection_window_step(self):
        from torcheval_tpu import obs

        obs.enable()
        try:
            obs.reset()
            col = MetricCollection({"q": Quantile(0.5), "m": Mean()})
            v = RNG.random(6000).astype(np.float32)
            for chunk in np.array_split(v, 4):
                col.update(chunk)
            out = col.compute()
            counters = obs.snapshot()["counters"]
            steps = sum(
                n
                for k, n in counters.items()
                if k.startswith("deferred.window_steps{")
            )
            # every member folded in ONE window-step program — the sketch
            # fold is plain additive state, no private lane
            self.assertEqual(steps, 1)
            self.assertLessEqual(
                abs(float(out["q"]) - _true_quantile(v, 0.5)),
                sketch.relative_error(16),
            )
        finally:
            obs.reset()
            obs.disable()

    def test_checkpoint_round_trip_mid_window(self):
        from torcheval_tpu import resilience

        d = tempfile.mkdtemp(prefix="sketch_ckpt_")
        self.addCleanup(shutil.rmtree, d, ignore_errors=True)
        m = Quantile((0.1, 0.9))
        m.update(RNG.random(1000).astype(np.float32))
        m.update(RNG.random(1000).astype(np.float32))  # pending chunks live
        want = np.asarray(m.compute())
        resilience.save(m, d)
        fresh = Quantile((0.1, 0.9))
        resilience.restore(fresh, d)
        np.testing.assert_array_equal(np.asarray(fresh.compute()), want)

    def test_int32_edge_fails_closed(self):
        import jax.numpy as jnp

        m = Quantile(0.5, bucket_count=4096)
        big = np.zeros(4096, np.int32)
        big[:8] = 2**28
        m.bucket_counts = jnp.asarray(big)
        with self.assertRaisesRegex(ValueError, "int32-exact"):
            m.compute()

    def test_sync_schema_rejects_config_drift(self):
        # bucket_count/q ride the schema digest: replicas whose sketches
        # cannot bucket-add (or whose quantiles differ) must not fold
        a, b = Quantile(0.5), Quantile(0.5, bucket_count=4096)
        self.assertNotEqual(a._sync_schema_extra, b._sync_schema_extra)


class TestValueSketchMetrics(unittest.TestCase):
    def _rank_batches(self, k=4, c=10, n=600):
        return [
            (
                RNG.random((n, c)).astype(np.float32),
                RNG.integers(0, c, n),
            )
            for _ in range(k)
        ]

    def test_hit_rate_mean_within_bound(self):
        exact, approx = HitRate(k=3), HitRate(k=3, approx=True)
        for x, t in self._rank_batches():
            exact.update(x, t)
            approx.update(x, t)
        want = float(np.mean(np.asarray(exact.compute())))
        got = float(approx.compute())
        self.assertLessEqual(abs(want - got), sketch.relative_error(16) + 1e-6)

    def test_reciprocal_rank_mean_and_merge_bit_identity(self):
        batches = self._rank_batches()
        exact = ReciprocalRank()
        solo = ReciprocalRank(approx=True)
        a, b = ReciprocalRank(approx=True), ReciprocalRank(approx=True)
        for i, (x, t) in enumerate(batches):
            exact.update(x, t)
            solo.update(x, t)
            (a if i % 2 else b).update(x, t)
        a.merge_state([b])
        self.assertEqual(float(a.compute()), float(solo.compute()))
        want = float(np.mean(np.asarray(exact.compute())))
        self.assertLessEqual(
            abs(want - float(solo.compute())),
            sketch.relative_error(16) * max(want, 1e-9) + 1e-6,
        )

    def test_cat_weighted_histogram_view(self):
        c = Cat(approx=True)
        c.update(np.float32([3.0, 1.0, 3.0]))
        c.update(np.float32([[1.0, 3.0]]))  # any shape pools elementwise
        vals, counts = c.compute()
        self.assertEqual(int(np.asarray(counts).sum()), 5)
        self.assertEqual(len(vals), 2)
        # representatives within relative error of the true values
        got = np.sort(np.asarray(vals))
        for got_v, true_v in zip(got, [1.0, 3.0]):
            self.assertLessEqual(
                abs(float(got_v) - true_v) / true_v, sketch.relative_error(16)
            )
        with self.assertRaisesRegex(ValueError, "dim=0"):
            Cat(dim=1, approx=True)

    def test_cat_env_opt_in_with_dim_stays_exact(self):
        import os
        from unittest import mock

        with mock.patch.dict(os.environ, {"TORCHEVAL_TPU_APPROX": "1"}):
            c = Cat(dim=1)  # env cannot apply: exact, no raise
            self.assertFalse(c._sketch_enabled())
            with self.assertRaises(ValueError):
                Cat(dim=1, approx=True)  # explicit ask still raises

    def test_value_sketch_memory_bounded_and_nan_raises(self):
        from torcheval_tpu.sketch.cache import SKETCH_FOLD_ROWS

        m = HitRate(approx=4096)
        for _ in range(3):
            x = RNG.random((SKETCH_FOLD_ROWS // 2 + 10, 4)).astype(
                np.float32
            )
            m.update(x, RNG.integers(0, 4, x.shape[0]))
            self.assertLess(
                sum(int(a.size) for a in m.scores),
                SKETCH_FOLD_ROWS + x.shape[0],
            )
        self.assertEqual(np.asarray(m.sketch_counts).shape, (4096,))
        bad = Cat(approx=True)
        bad.update(np.float32([np.nan]))
        with self.assertRaisesRegex(ValueError, "NaN"):
            bad.compute()


if __name__ == "__main__":
    unittest.main()

"""Bench-script hygiene guards (ISSUE 6 satellites).

Two classes of bench regressions have slipped through rounds before:

* a reference-leg tensor conversion bypassing ``_to_torch`` — numpy views
  of jax arrays are read-only, so a raw ``torch.from_numpy(np.asarray(x))``
  re-fires the non-writable UserWarning the BENCH_r05 tail still carried
  (PR 3 routed config4 through ``_to_torch`` but one call site survived
  until PR 1's sweep; this pins ZERO raw call sites for good);
* the config1 decomposition rows quietly dropping out of the ``--smoke``
  completeness set — they are the regression pins for the window-step
  targets (host < 1 ms, floor-normalized dispatches < 20), so the smoke
  job must fail when they stop being emitted.

These are source-level asserts (no bench execution): cheap enough for
tier-1, strong enough to fail the PR that reintroduces either class.
"""

import os
import re
import unittest

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class TestBenchHygiene(unittest.TestCase):
    def setUp(self):
        with open(os.path.join(_REPO, "bench.py")) as f:
            self.source = f.read()

    def test_no_raw_from_numpy_call_sites(self):
        # every reference-leg conversion must ride _to_torch (writable
        # copy); np.asarray of a jax array is a read-only view and
        # torch.from_numpy on it warns + aliases UB on write
        code_lines = [
            line
            for line in self.source.splitlines()
            if not line.lstrip().startswith("#")
        ]
        raw = [
            line
            for line in code_lines
            if re.search(r"torch\.from_numpy\(np\.asarray", line)
        ]
        self.assertEqual(
            raw,
            [],
            "bench.py regained a raw torch.from_numpy(np.asarray(...)) "
            "call site — route it through _to_torch (see BENCH_r05's "
            "non-writable UserWarning)",
        )

    def test_smoke_pins_window_step_rows(self):
        import importlib.util

        # import bench.py WITHOUT executing main(): the module only runs
        # legs under __main__, so a plain import is side-effect-free
        spec = importlib.util.spec_from_file_location(
            "bench_under_test", os.path.join(_REPO, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        expected = bench._EXPECTED_ROW_PREFIXES
        for row in (
            "config1_python_host_ms_per_run",
            "config1_floor_normalized_dispatches",
            "config1_adjacent_dispatch_floor",
            "config1_device_plus_env_ms_per_run",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the window-step "
                "perf targets lose their regression pin",
            )
        for row in (
            "config7_serve_tenants_single",
            "config7_serve_tenants_interleaved",
            "config7_serve_tenants_throughput_ratio",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the multi-tenant "
                "serving throughput contract (ROADMAP item 3) loses its "
                "regression pin",
            )
        for row in (
            "config8_cluster_local_direct",
            "config8_cluster_wire_1host",
            "config8_cluster_wire_2host_migration",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the cluster "
                "wire-overhead / migration-blackout contract (ISSUE 10) "
                "loses its regression pin",
            )
        for row in (
            "config8_cluster_wire_1host_ratio",
            "config8_ingest_overlap_ms",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the ingest "
                "pipeline's wire-vs-in-process ratio / overlap contract "
                "(ISSUE 11) loses its regression pin",
            )
        for row in (
            "config8_cluster_wire_codec_1host",
            "config8_cluster_wire_codec_1host_ratio",
            "config8_cluster_wire_codec_gain",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the compressed "
                "cluster-wire contract (ISSUE 12 — the codec ratio must "
                "stay paired with the raw-wire ratio on the same run) "
                "loses its regression pin",
            )
        for row in (
            "config6_retrieval_L1M_k10",
            "config6_retrieval_L1M_k100",
            "config6_retrieval_L1M_sharded",
            "config6_retrieval_L1M_sharded_ratio",
            "config6_retrieval_label_bytes_ratio",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the extreme-"
                "vocabulary retrieval contract (ISSUE 14 — the label-"
                "sharded engine's per-device bytes must stay ~1/shards of "
                "dense, paired with the dense k-sweep on the same run) "
                "loses its regression pin",
            )
        for row in (
            "config10_sketch_accuracy_vs_exact",
            "config10_sketch_bytes_ratio",
            "config10_sketch_1b_rows",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the bounded-"
                "memory sketch contract (ISSUE 13 — accuracy-vs-exact "
                "under the documented bound, O(buckets) state, and the "
                "1B-row stream the exact path cannot run) loses its "
                "regression pin",
            )
        for row in (
            "config12_obs_stream_overhead",
            "config12_obs_delta_bytes",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the telemetry-"
                "stream contract (ISSUE 16 — push channel ≤2% ingest "
                "overhead and delta payloads a fraction of full "
                "snapshots) loses its regression pin",
            )
        for row in (
            "config11_sliced_1m",
            "config11_sliced_ratio",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the million-"
                "cohort sliced-eval contract (ISSUE 15 — per-slice "
                "accuracy+AUROC at the power-law distribution, ratio vs "
                "the unsliced collection on identical rows) loses its "
                "regression pin",
            )
        for row in (
            "config11_sliced_1m_sharded",
            "config11_sliced_1m_sharded_ratio",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the slice-"
                "axis-sharded contract (ISSUE 17 — per-device scatter "
                "state exactly 1/shards of the unsharded gauge, paired "
                "with the unsliced ratio on the same run) loses its "
                "regression pin",
            )
        for row in (
            "config8_cluster_wire_pipelined_1host",
            "config8_cluster_wire_pipelined_ratio",
            "config8_cluster_wire_local_transport",
            "config8_cluster_wire_local_transport_ratio",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the hardware-"
                "speed wire contract (ISSUE 18 — deferred-ack pipelining "
                "vs the lock-step wire, and the same-process shared-"
                "memory transport vs the TCP socket, each paired on the "
                "same run) loses its regression pin",
            )
        for row in (
            "config9_elastic_p99_submit_1host_ms",
            "config9_elastic_p99_submit_scaled_ms",
            "config9_elastic_p99_ratio",
            "config9_elastic_hosts_after_scaleup",
            "config9_elastic_migrations",
            "config9_elastic_queue_depth_after_scaleup",
            "config9_elastic_sheds_after_scaleup",
            "config9_elastic_split_merge_exact",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the elastic-"
                "fleet contract (ISSUE 19 — autoscale + rebalance + split "
                "absorbing over-capacity load with zero sheds and an "
                "exactly-merged split tenant) loses its regression pin",
            )
        for row in (
            "config13_router_restart_blackout_ms",
            "config13_router_restart_recovered_tenants",
            "config13_router_restart_journal_records",
            "config13_router_restart_replay_exact",
        ):
            self.assertIn(
                row,
                expected,
                f"{row} left the --smoke completeness set: the durable-"
                "control-plane contract (ISSUE 20 — a journaled router "
                "restart with a measured blackout, every tenant "
                "reconciled, and replay bit-identical to the fault-free "
                "oracle) loses its regression pin",
            )

    def test_loopback_rows_carry_machine_readable_sandbox_caveat(self):
        # ISSUE 15 satellite (ROADMAP 1a/6): the 1-core loopback artifacts
        # must be marked IN the JSON rows so trajectory tooling stops
        # reading them as regressions — prose caveats were not enough
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_under_test2", os.path.join(_REPO, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        for row in (
            "config8_cluster_wire_codec_gain",
            "config8_cluster_wire_1host_ratio",
            "config8_cluster_wire_pipelined_ratio",
            "config6_retrieval_L1M_sharded_ratio",
            "config11_sliced_1m",
            "config11_sliced_ratio",
            "config11_sliced_1m_sharded_ratio",
            "config12_obs_stream_overhead",
            "config9_elastic_p99",
            "config13_router_restart_blackout_ms",
        ):
            self.assertIn(
                row,
                bench._SANDBOX_CAVEAT_ROWS,
                f"{row} lost its sandbox_caveat field: the 1-core "
                "loopback/serial-scatter artifact would read as a "
                "regression again",
            )
        import io
        import json
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            bench._emit_row("config8_cluster_wire_codec_gain", 0.7, "x")
            bench._emit_row("config1_multiclass_accuracy_c5", 1.0, "x")
        caveated, plain = (
            json.loads(line) for line in buf.getvalue().splitlines()
        )
        self.assertIn("sandbox_caveat", caveated)
        self.assertNotIn("sandbox_caveat", plain)

    def test_caveats_are_consolidated_and_name_remeasurement(self):
        # ISSUE 18 satellite: ONE registry owns every caveat (both
        # emitters consult it — no stringly caveat text at emit sites),
        # rows with run-shape name suffixes match by longest prefix, and
        # every caveat states the condition under which the number
        # should be re-measured (otherwise the caveat is an excuse, not
        # a claim)
        import importlib.util
        import io
        import json
        from contextlib import redirect_stdout

        spec = importlib.util.spec_from_file_location(
            "bench_under_test3", os.path.join(_REPO, "bench.py")
        )
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        for row, text in bench._SANDBOX_CAVEAT_ROWS.items():
            self.assertIn(
                "re-measure",
                text,
                f"{row}'s sandbox caveat does not name its "
                "re-measurement condition",
            )
        # prefix matching: the suffixed sliced row and the _emit-emitted
        # (not just _emit_row-emitted) rows both carry the field
        self.assertIsNotNone(
            bench._sandbox_caveat("config11_sliced_1m_4096slices")
        )
        buf = io.StringIO()
        with redirect_stdout(buf):
            bench._emit("config11_sliced_1m_4096slices", 100, 1.0, None)
        self.assertIn(
            "sandbox_caveat", json.loads(buf.getvalue().splitlines()[0])
        )
        # longest prefix wins: the sharded-ratio row keeps its own text
        self.assertIn(
            "state_bytes_per_device",
            bench._sandbox_caveat("config11_sliced_1m_sharded_ratio"),
        )


if __name__ == "__main__":
    unittest.main()

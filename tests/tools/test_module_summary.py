"""Tools tests: exact parameter/FLOP counts on hand-sized stacks (SURVEY §4,
reference ``tests/tools/test_module_summary.py:35-100``). FLOP expectations
use XLA conventions: multiply and add counted separately (a dot of
(m,k)x(k,n) is 2mkn; the reference's hand mapping counts mkn MACs)."""

import unittest

import flax.linen as nn
import jax
import jax.numpy as jnp

from torcheval_tpu.tools import (
    get_module_summary,
    get_summary_table,
    module_flops,
    prune_module_summary,
)


class Block(nn.Module):
    feat: int

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.feat)(x)
        return nn.relu(x)


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = Block(16)(x)
        x = Block(8)(x)
        return nn.Dense(2)(x)


class ConvNet(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Conv(8, (3, 3), padding="VALID", name="conv")(x)


class TestModuleSummary(unittest.TestCase):
    def test_param_counts(self):
        ms = get_module_summary(
            MLP(), (jnp.ones((4, 32)),), compute_flops=False
        )
        # 32*16+16 + 16*8+8 + 8*2+2 = 528 + 136 + 18
        self.assertEqual(ms.num_parameters, 682)
        self.assertEqual(ms.num_trainable_parameters, 682)
        self.assertEqual(ms.size_bytes, 682 * 4)
        self.assertFalse(ms.has_uninitialized_param)
        # compute_flops=False => no FLOP analysis, like the reference
        # when no input is given
        self.assertEqual(ms.flops_forward, -1)
        # flax modules need example inputs; the error says so
        with self.assertRaisesRegex(TypeError, "example inputs"):
            get_module_summary(MLP())

    def test_submodule_tree(self):
        ms = get_module_summary(MLP(), (jnp.ones((4, 32)),))
        names = set(ms.submodule_summaries)
        self.assertEqual(names, {"Block_0", "Block_1", "Dense_0"})
        b0 = ms.submodule_summaries["Block_0"]
        self.assertEqual(b0.module_type, "Block")
        self.assertEqual(b0.num_parameters, 528)
        inner = b0.submodule_summaries["Block_0.Dense_0"]
        self.assertEqual(inner.module_type, "Dense")
        self.assertEqual(inner.num_parameters, 528)

    def test_exact_flops_dense(self):
        ms = get_module_summary(MLP(), (jnp.ones((4, 32)),))
        d0 = ms.submodule_summaries["Block_0"].submodule_summaries[
            "Block_0.Dense_0"
        ]
        # dot 2*4*32*16 + bias 4*16
        self.assertEqual(d0.flops_forward, 2 * 4 * 32 * 16 + 64)
        # block adds the relu elementwise max
        self.assertEqual(
            ms.submodule_summaries["Block_0"].flops_forward,
            d0.flops_forward + 64,
        )
        # root >= sum of direct work; backward computed
        self.assertGreater(ms.flops_forward, 0)
        self.assertGreater(ms.flops_backward, ms.flops_forward * 0.5)

    def test_exact_flops_conv(self):
        ms = get_module_summary(ConvNet(), (jnp.ones((1, 8, 8, 3)),))
        # reference fixture: Conv2d(3,8,3) on 1x3x8x8 = 7,776 MACs
        # (tests/tools/test_module_summary.py:55); XLA counts 2x + 288 bias adds
        self.assertEqual(ms.flops_forward, 2 * 7776 + 288)

    def test_prune(self):
        ms = get_module_summary(MLP(), (jnp.ones((4, 32)),))
        prune_module_summary(ms, max_depth=2)
        for child in ms.submodule_summaries.values():
            self.assertEqual(len(child.submodule_summaries), 0)
        with self.assertRaises(ValueError):
            prune_module_summary(ms, max_depth=0)

    def test_summary_table(self):
        ms = get_module_summary(MLP(), (jnp.ones((4, 32)),))
        table = get_summary_table(ms)
        self.assertIn("Name", table)
        self.assertIn("Forward FLOPs", table)
        self.assertIn("Block_0.Dense_0", table)
        raw = get_summary_table(ms, human_readable_nums=False)
        self.assertIn("682", raw)

    def test_module_flops_accumulates_repeated_calls(self):
        class Twice(nn.Module):
            @nn.compact
            def __call__(self, x):
                inner = nn.Dense(4, name="inner")
                return inner(inner(x))

        flops = module_flops(Twice(), jnp.ones((2, 4)))
        # inner called twice: 2 * (2*2*4*4 + 8)
        self.assertEqual(flops[("inner",)].forward, 2 * (2 * 2 * 4 * 4 + 8))


if __name__ == "__main__":
    unittest.main()

"""``bench.py --smoke --trace`` flight-record acceptance (ISSUE 7): the
smoke run must leave a loadable Chrome trace and an obs snapshot whose cost
gauges attribute every compiled window-step program.

One subprocess bench run per class (the priciest fixture in tests/tools —
~1 min at smoke sizes), then schema + content assertions on the artifacts:

* the trace is valid Chrome/Perfetto ``trace_event`` JSON (required keys
  per phase, µs timestamps, non-negative durations);
* it contains the events the flight recorder exists for — window-step
  dispatches, ``jit.compile/deferred.window_step`` bars, and
  ``toolkit.sync.round`` spans (which happen only inside the config5
  4-process sync workers; their rank-tagged timelines must survive the
  merge into the parent's export under worker pids);
* the snapshot's ``obs.cost.{flops,bytes_accessed,hbm_bytes}{entry=}``
  gauges exist for every entry the cost leg captured — the window step
  included — so dispatch-equivalent floor rows sit next to device cost.
"""

import json
import os
import re
import subprocess
import sys
import unittest

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))


class TestBenchSmokeTrace(unittest.TestCase):
    trace = None
    snapshot = None

    @classmethod
    def setUpClass(cls):
        import tempfile

        cls._tmp = tempfile.TemporaryDirectory(prefix="bench_smoke_trace_")
        art = os.path.join(cls._tmp.name, "artifacts")
        env = dict(os.environ)
        env.update(
            {
                "JAX_PLATFORMS": "cpu",
                "TORCHEVAL_TPU_TEST_ARTIFACT_DIR": art,
            }
        )
        trace_path = os.path.join(cls._tmp.name, "trace.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"),
             "--smoke", "--trace", trace_path],
            env=env,
            capture_output=True,
            text=True,
            timeout=600,
            cwd=cls._tmp.name,
        )
        assert proc.returncode == 0, (
            f"bench --smoke --trace exited {proc.returncode}:\n"
            f"{proc.stderr[-3000:]}"
        )
        with open(trace_path) as f:
            cls.trace = json.load(f)
        # --smoke must drop BOTH artifacts into the artifact dir too (the
        # copies CI uploads on every run)
        with open(os.path.join(art, "bench_trace.json")) as f:
            assert json.load(f)["traceEvents"], "artifact trace empty"
        with open(os.path.join(art, "bench_obs_snapshot.json")) as f:
            cls.snapshot = json.load(f)["obs_snapshot"]

    @classmethod
    def tearDownClass(cls):
        cls._tmp.cleanup()

    def test_trace_event_schema(self):
        doc = self.trace
        self.assertIsInstance(doc["traceEvents"], list)
        self.assertGreater(len(doc["traceEvents"]), 100)
        for e in doc["traceEvents"]:
            self.assertIn(e["ph"], ("X", "i"))
            for key in ("name", "cat", "pid", "tid", "ts", "args"):
                self.assertIn(key, e)
            self.assertGreaterEqual(e["ts"], 0.0)
            if e["ph"] == "X":
                self.assertGreater(e["dur"], 0.0)
            else:
                self.assertEqual(e.get("s"), "t")

    def test_contains_window_step_compile_and_sync_events(self):
        names = {e["name"] for e in self.trace["traceEvents"]}
        for required in (
            "deferred.window_step.dispatch",
            "deferred.window.append",
            "jit.compile/deferred.window_step",
        ):
            self.assertIn(required, names)
        # sync rounds record inside the sync API's span, so their timeline
        # name is the NESTED path (toolkit.sync_and_compute/.../
        # toolkit.sync.round) — match the leaf
        self.assertTrue(
            any(n.endswith("toolkit.sync.round") for n in names), names
        )

    def test_sync_rounds_carry_worker_pids(self):
        # the config5 sync workers' events merge in under pid rank+1; the
        # parent's own events are pid 0
        sync_pids = {
            e["pid"]
            for e in self.trace["traceEvents"]
            if e["name"].endswith("toolkit.sync.round")
        }
        self.assertTrue(sync_pids)
        self.assertNotIn(0, sync_pids)

    def test_cost_gauges_cover_every_captured_entry(self):
        gauges = self.snapshot["gauges"]
        counters = self.snapshot["counters"]
        entries = {
            m.group(1)
            for k in counters
            if (m := re.match(r"obs\.cost\.captures\{entry=(.+)\}", k))
        }
        self.assertIn("deferred.window_step", entries)
        for entry in entries:
            for g in ("flops", "bytes_accessed", "hbm_bytes"):
                self.assertIn(f"obs.cost.{g}{{entry={entry}}}", gauges)

    def test_ingest_events_schema(self):
        # ISSUE 11: the config8 legs must leave pooled-staging and
        # coalesced-transfer bars in the flight record, with the labels
        # an operator needs to read them
        stages = [
            e
            for e in self.trace["traceEvents"]
            if e["name"] == "serve.ingest.stage"
        ]
        transfers = [
            e
            for e in self.trace["traceEvents"]
            if e["name"] == "serve.ingest.transfer"
        ]
        self.assertTrue(stages, "no serve.ingest.stage events")
        self.assertTrue(transfers, "no serve.ingest.transfer events")
        for e in stages:
            self.assertEqual(e["ph"], "X")
            self.assertGreater(e["args"]["bytes"], 0)
        for e in transfers:
            self.assertEqual(e["ph"], "X")
            self.assertGreater(e["args"]["bytes"], 0)
            self.assertGreaterEqual(e["args"]["batches"], 1)
            # dedup means a group of identical broadcast batches may
            # transfer FEWER unique arrays than batches — never zero
            self.assertGreaterEqual(e["args"]["arrays"], 1)

    def test_ingest_overlaps_window_execution(self):
        # the double-buffering proof, asserted rather than eyeballed: at
        # least one window's ingest (stage or transfer) ran inside a
        # previous window step's dispatch->retire span. Retirement is
        # observed by the donated-hold sweep at the NEXT dispatch, so the
        # span [dispatch_end, retire_ts] is exactly the window in which
        # the program was (still) executing from the host's view.
        events = sorted(
            self.trace["traceEvents"], key=lambda e: e["ts"]
        )
        dispatch_ends = []  # ts at which a window-step program entered
        overlapped = 0
        for e in events:
            if e["name"] == "deferred.window_step.dispatch":
                dispatch_ends.append(e["ts"] + e["dur"])
            elif e["name"] == "deferred.window_step.retire":
                dispatch_ends = [
                    t for t in dispatch_ends if t > e["ts"]
                ]
            elif e["name"] in (
                "serve.ingest.stage",
                "serve.ingest.transfer",
            ):
                # an ingest bar while >= 1 dispatched window program has
                # not yet been observed retired: overlapped ingest
                if dispatch_ends and e["ts"] >= dispatch_ends[0]:
                    overlapped += 1
        self.assertGreater(
            overlapped,
            0,
            "no ingest stage/transfer event overlapped a window step's "
            "dispatch->retire span — the pipeline is running serially",
        )

    def test_window_occupancy_histogram_recorded(self):
        histos = self.snapshot["histograms"]
        self.assertIn("deferred.window_occupancy", histos)
        self.assertGreater(histos["deferred.window_occupancy"]["count"], 0)


if __name__ == "__main__":
    unittest.main()

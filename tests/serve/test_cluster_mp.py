"""Cluster host-failure drill: REAL multi-process hosts, chaos-killed
mid-window (ISSUE 10 acceptance).

Three worlds, each: this test process runs the ``EvalRouter`` plus
concurrent producer threads (the Podracer many-producers side), and TWO
separate host processes (``mp_cluster_host.py``) each own an
``EvalDaemon`` + ``EvalServer`` sharing ONE checkpoint root. Tenants
spread over both hosts; every tenant streams 3 batches, flushes (making
them durable in the shared root), then streams 3 more — and chaos takes
host B down at the first phase-2 submit it receives:

* **host_kill** — ``os._exit`` BEFORE processing: the in-flight batch
  was never applied; it survives only in the router's replay buffer.
* **ack_drop** — process-then-die-before-ack, the exactly-once hard
  case: the batch entered B's daemon but B's un-checkpointed state dies
  with it; the client cannot know, resends, and the replay path must
  apply it exactly once on the survivor.
* **host_partition** — B keeps TCP alive but stops processing/ACKing:
  death by deadline instead of connection error, same recovery.

Acceptance asserted per world: every tenant finishes on host A with a
compute BIT-IDENTICAL to a fault-free oracle; zero duplicate batch
application on the survivor (per-tenant ``serve.ingest.batches`` +
``dupes`` counters and checkpoint watermark arithmetic); router
migration counters and the ``serve.router.migrate`` Chrome-trace span
land in test-artifacts. All sockets bind port 0 (OS-assigned).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import unittest

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_HOST = os.path.join(_HERE, "mp_cluster_host.py")

NUM_CLASSES = 5
BATCH = 32
TENANTS_PER_HOST = 3
PHASE1, PHASE2 = 3, 3
CHAOS_EXIT_CODE = 43
SPEC = {"acc": ["MulticlassAccuracy", {"num_classes": NUM_CLASSES}]}


def _make_batch(tenant: str, idx: int):
    seed = 1000 * (hash(tenant) % 97) + idx
    rng = np.random.default_rng(seed)
    return (
        rng.random((BATCH, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, BATCH),
    )


def _oracle(tenant: str) -> float:
    from torcheval_tpu.metrics import MulticlassAccuracy

    m = MulticlassAccuracy(num_classes=NUM_CLASSES)
    for i in range(PHASE1 + PHASE2):
        m.update(*_make_batch(tenant, i))
    return float(np.asarray(m.compute()))


def _wait(predicate, timeout_s=10.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _artifact_dir(scenario: str) -> str:
    configured = os.environ.get("TORCHEVAL_TPU_TEST_ARTIFACT_DIR")
    if configured:
        out = os.path.join(configured, f"cluster_drill_{scenario}")
        os.makedirs(out, exist_ok=True)
        return out
    return tempfile.mkdtemp(prefix=f"tpu_cluster_{scenario}_")


def _launch_host(outdir: str, tag: str, ckpt_root: str, chaos_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    for k in list(env):
        if k.startswith("TORCHEVAL_TPU_CHAOS"):
            del env[k]
    if chaos_env:
        env.update(chaos_env)
    return subprocess.Popen(
        [sys.executable, _HOST, outdir, tag, ckpt_root],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _pick_spread_ids(endpoints, per_host):
    """Tenant ids chosen so rendezvous placement gives every endpoint
    exactly ``per_host`` of them (the same highest-random-weight formula
    EvalRouter uses — endpoint strings carry ephemeral ports, so fixed
    names could otherwise all land on one host)."""
    import hashlib

    counts = {ep: 0 for ep in endpoints}
    ids = []
    for i in range(256):
        if min(counts.values()) >= per_host:
            break
        tid = f"t{i}"
        ep = max(
            endpoints,
            key=lambda e: hashlib.sha256(f"{tid}@{e}".encode()).digest(),
        )
        if counts[ep] >= per_host:
            continue
        counts[ep] += 1
        ids.append(tid)
    return ids


def _wait_port(outdir: str, tag: str, timeout_s: float = 90.0) -> int:
    path = os.path.join(outdir, f"{tag}.port")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return int(f.read())
        time.sleep(0.05)
    raise TimeoutError(f"host {tag} never published its port.")


class _ClusterDrillMixin:
    ACTION = "host_kill"  # or "ack_drop" / "host_partition"
    REQUEST_TIMEOUT_S = 10.0

    @classmethod
    def setUpClass(cls):
        try:
            cls._run_world()
        except BaseException:
            # never leak parked host processes into the CI runner
            for proc in (getattr(cls, "proc_a", None), getattr(cls, "proc_b", None)):
                if proc is not None and proc.poll() is None:
                    proc.kill()
            raise

    @classmethod
    def _run_world(cls):
        from torcheval_tpu import obs
        from torcheval_tpu.serve import EvalClient, EvalRouter

        cls.outdir = _artifact_dir(cls.ACTION)
        cls.ckpt_root = os.path.join(cls.outdir, "ckpt_root")
        os.makedirs(cls.ckpt_root, exist_ok=True)
        # B's chaos: fire at the FIRST phase-2 submit it receives
        # (per-tenant submit counting: phase 1 contributes PHASE1)
        chaos = {
            "TORCHEVAL_TPU_CHAOS": "1",
            "TORCHEVAL_TPU_CHAOS_ACTION": cls.ACTION,
            "TORCHEVAL_TPU_CHAOS_TENANT": "*",
            "TORCHEVAL_TPU_CHAOS_STEP": str(PHASE1 + 1),
            "TORCHEVAL_TPU_CHAOS_EXIT_CODE": str(CHAOS_EXIT_CODE),
        }
        cls.proc_a = _launch_host(cls.outdir, "hostA", cls.ckpt_root)
        cls.proc_b = _launch_host(
            cls.outdir, "hostB", cls.ckpt_root, chaos_env=chaos
        )
        port_a = _wait_port(cls.outdir, "hostA")
        port_b = _wait_port(cls.outdir, "hostB")
        cls.ep_a = f"127.0.0.1:{port_a}"
        cls.ep_b = f"127.0.0.1:{port_b}"

        obs.reset()
        obs.enable()
        cls.router = EvalRouter(
            [cls.ep_a, cls.ep_b],
            request_timeout_s=cls.REQUEST_TIMEOUT_S,
            connect_timeout_s=5.0,
            max_attempts=2,
            backoff_base_s=0.05,
            backoff_cap_s=0.2,
            # ISSUE 18: the whole drill rides the deferred-ack pipeline
            # (every submit streams on a channel socket, acks fold
            # asynchronously) — the exactness bar below is unchanged
            pipeline_depth=3,
        )
        cls.tenants = _pick_spread_ids(
            [cls.ep_a, cls.ep_b], TENANTS_PER_HOST
        )
        for t in cls.tenants:
            cls.router.attach(t, SPEC)
        cls.placement_before = cls.router.placement()
        cls.b_tenants = [
            t for t, ep in cls.placement_before.items() if ep == cls.ep_b
        ]
        cls.a_tenants = [
            t for t, ep in cls.placement_before.items() if ep == cls.ep_a
        ]

        # ISSUE 16: stream fleet telemetry for the WHOLE drill — pushes
        # must ride the existing wire without a single extra collective
        # round, and the chaos-killed host must surface as STALE in
        # fleet_status (the failure detector, not the stream, evicts)
        cls.fleet_modes = cls.router.subscribe_obs(0.25, stale_after_s=1.0)
        cls.fleet_warmed = _wait(
            lambda: all(
                not h["stale"]
                for h in cls.router.fleet_status()["hosts"].values()
            )
        )
        # quiescent push window: nothing but telemetry flows, so host
        # A's collective-round counter must not move at all
        probe = EvalClient(cls.ep_a, request_timeout_s=30.0)
        rounds_before = probe.snapshot()["snapshot"]["counters"].get(
            "toolkit.sync.rounds", 0
        )
        pushes_before = cls.router.fleet_status()["hosts"][cls.ep_a][
            "pushes"
        ]
        cls.fleet_pushed = _wait(
            lambda: cls.router.fleet_status()["hosts"][cls.ep_a]["pushes"]
            >= pushes_before + 3
        )
        cls.sync_rounds_during_pushes = (
            rounds_before,
            probe.snapshot()["snapshot"]["counters"].get(
                "toolkit.sync.rounds", 0
            ),
        )
        probe.close()

        # phase 1: 3 batches each, round-robin, then flush -> durable in
        # the SHARED root (this is what migration restores)
        for i in range(PHASE1):
            for t in cls.tenants:
                cls.router.submit(t, *_make_batch(t, i))
        for t in cls.tenants:
            cls.router.flush(t)

        # the fleet view reflects phase-1 ingest within a push interval
        # or two: an A tenant is in the per-tenant queue map and the
        # submit-latency EWMA has left zero
        def _fleet_sees_ingest():
            lr = cls.router.fleet_status()["hosts"][cls.ep_a][
                "load_report"
            ]
            return (
                lr is not None
                and any(
                    t in lr["queue"]["per_tenant"] for t in cls.a_tenants
                )
                and lr["latency"]["submit_ewma_s"] > 0.0
            )

        cls.fleet_saw_ingest = _wait(_fleet_sees_ingest, timeout_s=5.0)

        # phase 2: concurrent producer threads over disjoint tenant
        # halves; chaos takes B down at its first phase-2 submit
        errors = []

        def producer(subset):
            try:
                for i in range(PHASE1, PHASE1 + PHASE2):
                    for t in subset:
                        cls.router.submit(t, *_make_batch(t, i))
            except Exception as e:  # noqa: BLE001 - asserted below
                errors.append(e)

        threads = [
            threading.Thread(target=producer, args=(cls.tenants[::2],)),
            threading.Thread(target=producer, args=(cls.tenants[1::2],)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        cls.producer_errors = errors

        cls.results = {
            t: float(np.asarray(cls.router.compute(t)["acc"]))
            for t in cls.tenants
        }
        cls.placement_after = cls.router.placement()

        # with B dead its stream goes quiet: fleet_status must mark it
        # stale within the horizon while KEEPING it visible (a dead host
        # silently vanishing from the fleet view is how outages hide)
        cls.fleet_b_stale = _wait(
            lambda: cls.router.fleet_status()["hosts"]
            .get(cls.ep_b, {})
            .get("stale", False),
            timeout_s=5.0,
        )
        cls.fleet_status_final = cls.router.fleet_status()
        with open(os.path.join(cls.outdir, "fleet.status.json"), "w") as f:
            json.dump(cls.fleet_status_final, f, indent=2, default=str)
        with open(os.path.join(cls.outdir, "fleet.trace.json"), "w") as f:
            f.write(cls.router.fleet_chrome_trace())

        # flight record: router-side counters + migration span, and the
        # surviving host's obs snapshot, into test-artifacts
        cls.router_snapshot = obs.snapshot()
        with open(os.path.join(cls.outdir, "router.obs.json"), "w") as f:
            json.dump(cls.router_snapshot, f, indent=2)
        with open(os.path.join(cls.outdir, "router.trace.json"), "w") as f:
            f.write(obs.chrome_trace())
        client_a = EvalClient(cls.ep_a, request_timeout_s=30.0)
        cls.host_a_flight = client_a.snapshot()
        cls.host_a_health = client_a.health()
        client_a.close()
        with open(os.path.join(cls.outdir, "hostA.obs.json"), "w") as f:
            json.dump(cls.host_a_flight["snapshot"], f, indent=2)
        with open(os.path.join(cls.outdir, "hostA.trace.json"), "w") as f:
            f.write(cls.host_a_flight["trace"])

        # teardown the processes (B is usually dead already)
        for tag in ("hostA", "hostB"):
            with open(os.path.join(cls.outdir, f"{tag}.stop"), "w"):
                pass
        try:
            cls.proc_a.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            cls.proc_a.kill()
        try:
            cls.proc_b.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            cls.proc_b.kill()
        cls.router.close()
        _wait(
            lambda: not [
                t
                for t in threading.enumerate()
                if "torcheval-tpu-obs-" in t.name
            ]
        )
        cls.leaked_obs_threads = [
            t.name
            for t in threading.enumerate()
            if "torcheval-tpu-obs-" in t.name
        ]
        obs.disable()

    def test_both_hosts_held_tenants_before_the_fault(self):
        self.assertTrue(self.b_tenants, self.placement_before)
        self.assertTrue(self.a_tenants, self.placement_before)

    def test_producers_saw_no_errors(self):
        self.assertEqual(self.producer_errors, [])

    def test_every_tenant_finished_on_host_a(self):
        for t, ep in self.placement_after.items():
            self.assertEqual(ep, self.ep_a, t)

    def test_results_bit_identical_to_fault_free_oracle(self):
        for t in self.tenants:
            self.assertEqual(self.results[t], _oracle(t), t)

    def test_wire_codec_negotiated_when_forced(self):
        # ISSUE 12: CI re-runs this drill with TORCHEVAL_TPU_WIRE_CODEC=
        # delta — the router's clients then OFFER the codec at every
        # attach and the surviving host's registry must show it
        # negotiated (raw runs skip: nothing was offered). The
        # bit-identical-to-oracle and zero-duplicate assertions above run
        # unchanged either way, which is the point: the compressed wire
        # is exercised under the same chaos with the same exactness bar.
        codec = os.environ.get("TORCHEVAL_TPU_WIRE_CODEC", "raw")
        if codec == "raw":
            self.skipTest("raw-wire run (TORCHEVAL_TPU_WIRE_CODEC unset)")
        counters = self.host_a_flight["snapshot"]["counters"]
        self.assertGreaterEqual(
            counters.get(f"serve.wire.codec{{codec={codec}}}", 0),
            1,
            sorted(k for k in counters if "codec" in k),
        )

    def test_zero_duplicate_application_on_survivor(self):
        """Exactly-once arithmetic on host A: a migrated tenant's batches
        split durable-through-checkpoint (PHASE1, restored, never re-run)
        vs applied-at-A (replayed tail + post-migration submits); A-native
        tenants applied everything locally. ``serve.ingest.batches`` and
        the dedup counter prove no batch ran twice."""
        counters = self.host_a_flight["snapshot"]["counters"]
        tenants = self.host_a_health["tenants"]
        for t in self.b_tenants:
            self.assertEqual(
                tenants[t]["processed"], PHASE2, f"{t}: {tenants[t]}"
            )
            self.assertEqual(tenants[t]["dupes"], 0, t)
            self.assertEqual(
                counters.get(f"serve.ingest.batches{{tenant={t}}}"),
                float(PHASE2),
                t,
            )
            # the checkpoint restored exactly the durable phase-1 window
            self.assertEqual(tenants[t]["durable_seq"] >= PHASE1, True, t)
        for t in self.a_tenants:
            self.assertEqual(
                tenants[t]["processed"], PHASE1 + PHASE2, t
            )
            self.assertEqual(tenants[t]["dupes"], 0, t)

    def test_router_migration_counters_and_span_recorded(self):
        counters = self.router_snapshot["counters"]
        self.assertEqual(
            counters.get("serve.router.migrations{reason=host_failure}"),
            float(len(self.b_tenants)),
        )
        replay_total = sum(
            v
            for k, v in counters.items()
            if k.startswith("serve.router.replays{")
        )
        # the interrupted in-flight batches are the only un-durable
        # entries (everything earlier was flushed): at least the one that
        # detected the death; with the deferred-ack pipeline up to a full
        # window of booked-but-unacked phase-2 batches per B tenant can
        # be in flight when the host dies, and every one is delivered by
        # replay (never resubmitted; the zero-duplicate test above
        # proves the arithmetic)
        self.assertGreaterEqual(replay_total, 1.0)
        self.assertLessEqual(
            replay_total, float(PHASE2 * len(self.b_tenants))
        )
        with open(os.path.join(self.outdir, "router.trace.json")) as f:
            trace = json.load(f)
        names = [e["name"] for e in trace["traceEvents"]]
        self.assertIn("serve.router.migrate", names)

    def test_checkpoint_root_discovery_lists_every_tenant(self):
        """Operator recovery surface: with both original hosts gone, the
        shared root alone enumerates every tenant and its resume point
        (each flushed in phase 1, so each has a published checkpoint)."""
        from torcheval_tpu.resilience import discover_checkpoints

        found = discover_checkpoints(self.ckpt_root)
        for t in self.tenants:
            self.assertIn(t, found)
            self.assertTrue(os.path.isdir(found[t]), found[t])

    def test_fleet_stream_rode_the_wire_for_free(self):
        """ISSUE 16: both hosts subscribed in push mode, the fleet view
        warmed up and reflected ingest, and a pure-push window moved the
        host's ``toolkit.sync.rounds`` counter by exactly zero — the
        telemetry stream adds no collective round."""
        self.assertEqual(
            self.fleet_modes,
            {self.ep_a: "push", self.ep_b: "push"},
        )
        self.assertTrue(self.fleet_warmed, "fleet never warmed up")
        self.assertTrue(self.fleet_pushed, "push channel stalled")
        before, after = self.sync_rounds_during_pushes
        self.assertEqual(before, after)
        self.assertTrue(
            self.fleet_saw_ingest,
            f"fleet never reflected phase-1 ingest: "
            f"{self.fleet_status_final}",
        )

    def test_dead_host_marked_stale_not_dropped(self):
        """The stream marks the killed host STALE within the horizon but
        never removes it: eviction stays with the failure detector. The
        partitioned variant is exempt — its process (and publisher
        thread) survives, so its stream legitimately stays fresh."""
        if self.ACTION == "host_partition":
            self.skipTest("partitioned host keeps pushing; never stale")
        self.assertTrue(
            self.fleet_b_stale, "killed host never went stale"
        )
        host = self.fleet_status_final["hosts"][self.ep_b]
        self.assertTrue(host["stale"], host)

    def test_no_subscriber_threads_leaked(self):
        self.assertEqual(self.leaked_obs_threads, [])

    def test_artifacts_written(self):
        for name in (
            "router.obs.json",
            "router.trace.json",
            "hostA.obs.json",
            "hostA.trace.json",
            "fleet.status.json",
            "fleet.trace.json",
        ):
            self.assertTrue(
                os.path.getsize(os.path.join(self.outdir, name)) > 0, name
            )


class TestClusterHostKill(_ClusterDrillMixin, unittest.TestCase):
    """Host B hard-dies (os._exit) before processing the in-flight
    submit."""

    ACTION = "host_kill"

    def test_host_b_died_with_injected_exit_code(self):
        self.assertEqual(self.proc_b.returncode, CHAOS_EXIT_CODE)


class TestClusterAckDrop(_ClusterDrillMixin, unittest.TestCase):
    """Host B processes the in-flight submit, then dies BEFORE the ack —
    the exactly-once hard case: the batch entered B (applied to state
    that dies un-checkpointed) and the client cannot know; the replay
    must apply it exactly once on A."""

    ACTION = "ack_drop"

    def test_host_b_died_with_injected_exit_code(self):
        self.assertEqual(self.proc_b.returncode, CHAOS_EXIT_CODE)


class TestClusterPartition(_ClusterDrillMixin, unittest.TestCase):
    """Host B goes silent (reads requests, never processes or ACKs):
    failure is discovered by request deadline, not connection error."""

    ACTION = "host_partition"
    REQUEST_TIMEOUT_S = 1.5  # partition is found by deadline; keep it short

    def test_host_b_survived_but_was_abandoned(self):
        # a partitioned process does not die; it is routed around
        self.assertEqual(self.proc_b.returncode, 0)


if __name__ == "__main__":
    unittest.main()

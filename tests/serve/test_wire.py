"""Wire layer unit tests (ISSUE 10 tentpole): framing, tree marshalling,
metric specs, and the `EvalServer` op surface over real sockets.

Every socket here binds port 0 (OS-assigned) so parallel CI lanes never
collide.
"""

import os
import socket
import tempfile
import threading
import unittest

import numpy as np

from torcheval_tpu import obs
from torcheval_tpu.metrics import MulticlassAccuracy
from torcheval_tpu.serve import (
    AdmissionError,
    EvalClient,
    EvalDaemon,
    EvalServer,
    ServeError,
    WireError,
    metric_spec,
)
from torcheval_tpu.serve.wire import (
    build_metrics,
    pack_tree,
    recv_frame,
    send_frame,
    unpack_tree,
)

NUM_CLASSES = 5


def _batch(seed=0, n=16):
    rng = np.random.default_rng(seed)
    return (
        rng.random((n, NUM_CLASSES)).astype(np.float32),
        rng.integers(0, NUM_CLASSES, n),
    )


class TestFraming(unittest.TestCase):
    def _pipe(self):
        a, b = socket.socketpair()
        self.addCleanup(a.close)
        self.addCleanup(b.close)
        return a, b

    def test_frame_roundtrip_header_and_payload(self):
        a, b = self._pipe()
        send_frame(a, {"op": "x", "n": 3}, b"\x00\x01binary\xff")
        header, payload = recv_frame(b)
        self.assertEqual(header, {"op": "x", "n": 3})
        self.assertEqual(payload, b"\x00\x01binary\xff")

    def test_empty_payload_roundtrip(self):
        a, b = self._pipe()
        send_frame(a, {"op": "health"})
        self.assertEqual(recv_frame(b), ({"op": "health"}, b""))

    def test_clean_eof_returns_none(self):
        a, b = self._pipe()
        a.close()
        self.assertIsNone(recv_frame(b))

    def test_bad_magic_is_protocol_error(self):
        a, b = self._pipe()
        a.sendall(b"HTTP/1.1 200 OK\r\n\r\n")
        with self.assertRaises(WireError) as ctx:
            recv_frame(b)
        self.assertEqual(ctx.exception.reason, "protocol")
        self.assertFalse(ctx.exception.retryable)

    def test_truncated_frame_is_protocol_error(self):
        a, b = self._pipe()
        send_frame(a, {"op": "x"}, b"12345")
        # resend only a prefix: chop by closing after partial write
        a2, b2 = self._pipe()
        import struct

        a2.sendall(struct.pack(">4sIQ", b"TEW1", 2, 10) + b"{}123")
        a2.close()
        with self.assertRaises(WireError) as ctx:
            recv_frame(b2)
        self.assertEqual(ctx.exception.reason, "protocol")


class TestTreeCoding(unittest.TestCase):
    def test_roundtrip_nested_tree_exact_dtypes(self):
        tree = {
            "acc": np.float32(0.5),
            "curve": (
                np.arange(5, dtype=np.int64),
                np.linspace(0, 1, 5, dtype=np.float64),
            ),
            "meta": {"n": 3, "name": "x", "flag": True, "none": None},
            "list": [np.float16([1.5, 2.5]), 7],
        }
        spec, payload = pack_tree(tree)
        got = unpack_tree(spec, payload)
        self.assertEqual(set(got), set(tree))
        self.assertEqual(got["curve"][0].dtype, np.int64)
        self.assertEqual(got["curve"][1].dtype, np.float64)
        self.assertEqual(got["list"][0].dtype, np.float16)
        np.testing.assert_array_equal(got["curve"][0], tree["curve"][0])
        self.assertIsInstance(got["curve"], tuple)
        self.assertEqual(got["meta"], tree["meta"])

    def test_jax_arrays_marshal_as_numpy(self):
        import jax.numpy as jnp

        spec, payload = pack_tree({"v": jnp.arange(4.0)})
        got = unpack_tree(spec, payload)
        np.testing.assert_array_equal(got["v"], np.arange(4.0))

    def test_no_arrays_means_no_payload(self):
        spec, payload = pack_tree({"a": 1})
        self.assertEqual(payload, b"")
        self.assertEqual(unpack_tree(spec, payload), {"a": 1})

    def test_unmarshalable_object_is_protocol_error(self):
        with self.assertRaises(WireError):
            pack_tree({"f": lambda: None})

    def test_malformed_spec_is_protocol_error(self):
        with self.assertRaises(WireError):
            unpack_tree({"t": "nope"}, b"")


class TestMetricSpecs(unittest.TestCase):
    def test_builds_library_metrics(self):
        out = build_metrics(
            {"acc": metric_spec("MulticlassAccuracy", num_classes=7)}
        )
        self.assertIsInstance(out["acc"], MulticlassAccuracy)

    def test_unknown_class_rejects_bad_metrics(self):
        for bad in ("NotAMetric", "os", "Metric.__subclasses__"):
            with self.assertRaises(AdmissionError) as ctx:
                build_metrics({"m": [bad, {}]})
            self.assertEqual(ctx.exception.reason, "bad_metrics")

    def test_bad_kwargs_reject_bad_metrics(self):
        with self.assertRaises(AdmissionError) as ctx:
            build_metrics(
                {"m": ["MulticlassAccuracy", {"no_such_kwarg": 5}]}
            )
        self.assertEqual(ctx.exception.reason, "bad_metrics")

    def test_non_dict_spec_rejects(self):
        with self.assertRaises(AdmissionError):
            build_metrics([])


class _ServerMixin:
    def setUp(self):
        obs.reset()
        self.root = tempfile.mkdtemp(prefix="tpu_wire_test_")
        self.daemon = EvalDaemon(evict_dir=self.root).start()
        self.server = EvalServer(self.daemon)  # port 0: OS-assigned
        self.client = EvalClient(
            self.server.endpoint,
            request_timeout_s=30.0,
            max_attempts=2,
            backoff_base_s=0.01,
        )
        self.addCleanup(self.daemon.stop)
        self.addCleanup(self.server.close)
        self.addCleanup(self.client.close)

    def _attach(self, tenant="t1", **kw):
        return self.client.attach(
            tenant,
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
            **kw,
        )


class TestServerOps(_ServerMixin, unittest.TestCase):
    def test_submit_compute_matches_local_oracle(self):
        self._attach()
        scores, labels = _batch()
        for _ in range(4):
            self.client.submit("t1", scores, labels)
        got = self.client.compute("t1")
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for _ in range(4):
            oracle.update(scores, labels)
        self.assertEqual(
            float(np.asarray(got["acc"])),
            float(np.asarray(oracle.compute())),
        )

    def test_duplicate_seq_not_reapplied(self):
        """The exactly-once contract: a blind resend of an already-
        admitted seq acks as a duplicate, the batch is applied once, and
        per-tenant ingest/dupe counters prove it."""
        obs.enable()
        self.addCleanup(obs.disable)
        self._attach()
        scores, labels = _batch()
        st = self.client._tenant_state("t1")
        self.assertTrue(self.client.submit("t1", scores, labels))
        # model the ambiguous-failure retry: same seq, straight to _call
        from torcheval_tpu.serve.wire import pack_tree as _pt

        spec, blob = _pt([scores, labels])
        header, _ = self.client._call(
            "submit", {"tenant": "t1", "seq": 1, "args": spec}, blob
        )
        self.assertFalse(header["applied"])
        got = self.client.compute("t1")
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        oracle.update(scores, labels)  # applied ONCE
        self.assertEqual(
            float(np.asarray(got["acc"])),
            float(np.asarray(oracle.compute())),
        )
        snap = obs.snapshot()
        self.assertEqual(
            snap["counters"].get("serve.ingest.batches{tenant=t1}"), 1.0
        )
        self.assertEqual(
            snap["counters"].get("serve.ingest.dupes{tenant=t1}"), 1.0
        )
        self.assertEqual(st.next_seq, 2)

    def test_flush_advances_durable_watermark_and_prunes_replay(self):
        self._attach()
        scores, labels = _batch()
        st = self.client._tenant_state("t1")
        for _ in range(3):
            self.client.submit("t1", scores, labels)
        self.assertEqual(len(st.replay), 3)
        out = self.client.flush("t1")
        self.assertTrue(os.path.isdir(out["path"]))
        self.assertEqual(out["acked_seq"], 3)
        self.assertEqual(len(st.replay), 0)
        # the tenant stays active and continues bit-identically
        self.client.submit("t1", scores, labels)
        got = self.client.compute("t1")
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for _ in range(4):
            oracle.update(scores, labels)
        self.assertEqual(
            float(np.asarray(got["acc"])),
            float(np.asarray(oracle.compute())),
        )

    def test_replay_valve_flushes_when_buffer_full(self):
        client = EvalClient(
            self.server.endpoint, replay_capacity=2, backoff_base_s=0.01
        )
        self.addCleanup(client.close)
        client.attach(
            "t2",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
        )
        scores, labels = _batch()
        st = client._tenant_state("t2")
        for _ in range(5):
            client.submit("t2", scores, labels)
            self.assertLessEqual(len(st.replay), 2)
        self.assertGreaterEqual(st.durable_seq, 2)  # a flush happened

    def test_structured_errors_cross_the_wire(self):
        self._attach()
        with self.assertRaises(AdmissionError) as ctx:
            self._attach()  # duplicate tenant
        self.assertEqual(ctx.exception.reason, "duplicate_tenant")
        self.assertFalse(ctx.exception.retryable)
        with self.assertRaises(ServeError) as ctx:
            self.client.compute("ghost")
        self.assertEqual(ctx.exception.reason, "unknown_tenant")

    def test_degenerate_attach_knobs_reject_remotely_as_value_error(self):
        for bad in (0, -1.0, float("nan"), float("inf")):
            with self.assertRaises(ValueError):
                self._attach(tenant="tv", step_timeout_s=bad)

    def test_health_carries_seq_watermarks(self):
        self._attach()
        scores, labels = _batch()
        self.client.submit("t1", scores, labels)
        self.client.flush("t1")
        health = self.client.health()
        t = health["tenants"]["t1"]
        self.assertEqual(t["last_seq"], 1)
        self.assertEqual(t["durable_seq"], 1)
        self.assertFalse(health["draining"])

    def test_detach_with_checkpoint_returns_path(self):
        self._attach()
        scores, labels = _batch()
        self.client.submit("t1", scores, labels)
        path = self.client.detach("t1", checkpoint=True)
        self.assertTrue(os.path.isdir(path))

    def test_snapshot_op_returns_obs_flight_record(self):
        obs.enable()
        self.addCleanup(obs.disable)
        self._attach()
        scores, labels = _batch()
        self.client.submit("t1", scores, labels)
        snap = self.client.snapshot()
        self.assertIn("counters", snap["snapshot"])
        self.assertIn("traceEvents", snap["trace"])

    def test_sync_compute_op_crosses_the_wire(self):
        # single-process world: the collective lane degenerates to local,
        # which still exercises the whole wire path + result marshalling
        self._attach()
        scores, labels = _batch()
        self.client.submit("t1", scores, labels)
        got = self.client.sync_compute(
            "t1", sync_timeout_s=30.0, on_failure="local"
        )
        local = self.client.compute("t1")
        self.assertEqual(
            float(np.asarray(got["acc"])), float(np.asarray(local["acc"]))
        )

    def test_unknown_op_is_protocol_error(self):
        with self.assertRaises(WireError) as ctx:
            self.client._call("frobnicate", {})
        self.assertEqual(ctx.exception.reason, "protocol")


class TestDrainOverWire(_ServerMixin, unittest.TestCase):
    def test_drain_evicts_all_and_rejects_new_work(self):
        self._attach("a")
        self._attach("b")
        scores, labels = _batch()
        self.client.submit("a", scores, labels)
        drained = self.client.drain()
        self.assertEqual(set(drained), {"a", "b"})
        for path in drained.values():
            self.assertTrue(os.path.isdir(path))
        # draining daemon rejects new attaches AND new submits with a
        # structured, non-retryable reason
        with self.assertRaises(AdmissionError) as ctx:
            self._attach("c")
        self.assertEqual(ctx.exception.reason, "draining")
        self.assertFalse(ctx.exception.retryable)
        # health still answers so a router can verify the drain
        self.assertTrue(self.client.health()["draining"])

    def test_drained_tenant_resumes_elsewhere_bit_identically(self):
        self._attach("a")
        scores, labels = _batch()
        for _ in range(3):
            self.client.submit("a", scores, labels)
        self.client.drain()
        # "elsewhere": a second daemon sharing the checkpoint root
        daemon2 = EvalDaemon(evict_dir=self.root).start()
        server2 = EvalServer(daemon2)
        client2 = EvalClient(server2.endpoint)
        self.addCleanup(daemon2.stop)
        self.addCleanup(server2.close)
        self.addCleanup(client2.close)
        resp = client2.attach(
            "a",
            {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)},
            resume="require",
        )
        self.assertEqual(resp["last_seq"], 3)
        client2.submit("a", scores, labels)
        got = client2.compute("a")
        oracle = MulticlassAccuracy(num_classes=NUM_CLASSES)
        for _ in range(4):
            oracle.update(scores, labels)
        self.assertEqual(
            float(np.asarray(got["acc"])),
            float(np.asarray(oracle.compute())),
        )


class TestAttachIdempotency(_ServerMixin, unittest.TestCase):
    def test_attach_retry_with_same_nonce_reacked_as_success(self):
        """The ambiguous-attach corner: our attach landed but the ack was
        lost; the blind retry carries the same nonce and must get the
        ORIGINAL success back, not duplicate_tenant."""
        spec = {"acc": metric_spec("MulticlassAccuracy", num_classes=NUM_CLASSES)}
        header, _ = self.client._call(
            "attach", {"tenant": "amb", "spec": spec, "nonce": "n-1"}
        )
        self.assertEqual(header["last_seq"], 0)
        retry, _ = self.client._call(
            "attach", {"tenant": "amb", "spec": spec, "nonce": "n-1"}
        )
        self.assertTrue(retry["ok"])
        self.assertEqual(retry["last_seq"], 0)
        # a DIFFERENT caller's attach of the same id still rejects
        with self.assertRaises(AdmissionError) as ctx:
            self.client._call(
                "attach", {"tenant": "amb", "spec": spec, "nonce": "n-2"}
            )
        self.assertEqual(ctx.exception.reason, "duplicate_tenant")

    def test_detach_retry_is_idempotent(self):
        self._attach("once")
        self.assertIsNone(self.client.detach("once"))
        # the "retry of a detach whose ack was lost" shape: already gone
        # counts as done, not unknown_tenant
        self.assertIsNone(self.client.detach("once"))


class TestIdleEvictionRotationSafety(unittest.TestCase):
    def test_aborted_idle_eviction_never_deletes_the_durable_checkpoint(self):
        """Review finding (ISSUE 10): with evict_keep_last=1, an idle
        eviction whose commit ABORTS (a submit raced in during the save)
        discards its own checkpoint — rotation at save time would have
        already deleted the previous durable one, leaving ZERO. Rotation
        must be deferred to the commit."""
        import tempfile

        from torcheval_tpu.resilience.snapshot import list_checkpoints
        from torcheval_tpu.serve.daemon import EvalDaemon as _Daemon

        root = tempfile.mkdtemp(prefix="tpu_rotate_abort_")
        daemon = _Daemon(evict_dir=root, evict_keep_last=1).start()
        self.addCleanup(daemon.stop)
        handle = daemon.attach(
            "t", {"acc": MulticlassAccuracy(num_classes=NUM_CLASSES)}
        )
        scores, labels = _batch()
        handle.submit(scores, labels)
        durable = handle.flush(timeout=60)["path"]  # the durable ckpt
        tenant = daemon._tenants["t"]
        # drive the idle-eviction machinery directly, injecting the race:
        # a batch lands while the eviction checkpoint is being written
        orig = daemon._checkpoint_tenant

        def racing_checkpoint(t, **kw):
            path = orig(t, **kw)
            with daemon._cond:
                t.queue.append(("batch", (None, (scores, labels)), None))
            return path

        daemon._checkpoint_tenant = racing_checkpoint
        tenant.watchdog_timeout_s = 0.0
        daemon._evict_idle(tenant)
        daemon._checkpoint_tenant = orig
        # the eviction must have aborted (tenant still active)...
        self.assertIn("t", daemon._tenants)
        # ...and the durable checkpoint must still exist: the aborted
        # eviction's own checkpoint is gone, but rotation never ran
        ckpts = list_checkpoints(os.path.join(root, "t"))
        self.assertIn(durable, ckpts)


class TestServerRobustness(_ServerMixin, unittest.TestCase):
    def test_garbage_speaker_does_not_kill_server(self):
        with socket.create_connection(self.server.address) as sock:
            sock.sendall(b"GET / HTTP/1.1\r\n\r\n")
        # server drops that connection; real clients keep working
        self._attach()
        scores, labels = _batch()
        self.assertTrue(self.client.submit("t1", scores, labels))

    def test_concurrent_producers_share_one_client(self):
        self._attach("shared")
        scores, labels = _batch()
        errors = []

        def worker(i):
            try:
                for _ in range(3):
                    self.client.submit("shared", scores, labels)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.assertEqual(errors, [])
        health = self.client.health()
        self.assertEqual(health["tenants"]["shared"]["ingested"], 12)


if __name__ == "__main__":
    unittest.main()
